// Native host-side chunk assembly/disassembly for distributedarrays_tpu.
//
// The framework's host paths — DArray(init, ...) construction, from_chunks,
// checkpoint restore — stitch per-chunk buffers into one contiguous
// global array (or slice it back apart) before/after the device scatter.
// numpy does each chunk's strided copy in C already, but serially and with
// Python-loop dispatch per chunk; for many-chunk multi-GB grids this is the
// host bottleneck.  This translation unit provides the same operation as a
// thread-parallel strided copier with one call for the whole grid.
//
// Layout contract: dst is a row-major N-d buffer; each chunk i is a
// contiguous row-major buffer of extent shapes[i*ndim..] whose destination
// origin (in elements) is offsets[i*ndim..].  scatter=false copies
// chunk->dst (assemble); scatter=true copies dst->chunk (disassemble).
//
// Built with plain g++ -O3 -shared; bound from Python via ctypes
// (distributedarrays_tpu/utils/native.py).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Job {
  char* dst;                  // global buffer base
  const int64_t* dst_dims;    // global extents (elements), length ndim
  char* chunk;                // chunk buffer base
  const int64_t* shape;       // chunk extents (elements), length ndim
  const int64_t* offset;      // chunk origin in dst (elements), length ndim
  int ndim;
  int64_t itemsize;
  bool scatter;               // true: dst -> chunk
};

// Copy one chunk: iterate all but the innermost dimension, memcpy rows.
void copy_chunk(const Job& j) {
  const int nd = j.ndim;
  if (nd == 0) {
    if (j.scatter)
      std::memcpy(j.chunk, j.dst, j.itemsize);
    else
      std::memcpy(j.dst, j.chunk, j.itemsize);
    return;
  }
  // dst strides in bytes (row-major)
  std::vector<int64_t> dstride(nd);
  dstride[nd - 1] = j.itemsize;
  for (int d = nd - 2; d >= 0; --d)
    dstride[d] = dstride[d + 1] * j.dst_dims[d + 1];

  const int64_t row = j.shape[nd - 1] * j.itemsize;   // contiguous run
  int64_t nrows = 1;
  for (int d = 0; d < nd - 1; ++d) nrows *= j.shape[d];

  std::vector<int64_t> idx(nd > 1 ? nd - 1 : 1, 0);
  char* chunk_p = j.chunk;
  for (int64_t r = 0; r < nrows; ++r) {
    int64_t doff = j.offset[nd - 1] * dstride[nd - 1];
    for (int d = 0; d < nd - 1; ++d)
      doff += (j.offset[d] + idx[d]) * dstride[d];
    char* dst_p = j.dst + doff;
    if (j.scatter)
      std::memcpy(chunk_p, dst_p, row);
    else
      std::memcpy(dst_p, chunk_p, row);
    chunk_p += row;
    for (int d = nd - 2; d >= 0; --d) {   // odometer over outer dims
      if (++idx[d] < j.shape[d]) break;
      idx[d] = 0;
    }
  }
}

}  // namespace

extern "C" {

// chunks: array of n pointers; shapes/offsets: n*ndim int64 each.
// Returns 0 on success.
int chunk_copy(char* dst, const int64_t* dst_dims, int ndim,
               char** chunks, const int64_t* shapes, const int64_t* offsets,
               int64_t n_chunks, int64_t itemsize, int scatter,
               int n_threads) {
  if (ndim < 0 || n_chunks < 0 || itemsize <= 0) return 1;
  std::vector<Job> jobs;
  jobs.reserve(n_chunks);
  for (int64_t i = 0; i < n_chunks; ++i) {
    jobs.push_back(Job{dst, dst_dims, chunks[i], shapes + i * ndim,
                       offsets + i * ndim, ndim, itemsize,
                       scatter != 0});
  }
  if (n_threads <= 1 || n_chunks <= 1) {
    for (const auto& j : jobs) copy_chunk(j);
    return 0;
  }
  const int nt = static_cast<int>(
      std::min<int64_t>(n_threads, n_chunks));
  std::vector<std::thread> pool;
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    pool.emplace_back([&, t]() {
      for (int64_t i = t; i < static_cast<int64_t>(jobs.size()); i += nt)
        copy_chunk(jobs[i]);
    });
  }
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"
