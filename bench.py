#!/usr/bin/env python
"""Benchmark harness: BASELINE.json configs on the available TPU devices.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric (from BASELINE.json configs[0]): GFLOPS on a 4096x4096
DArray GEMM through the framework (`djit` + `@`) at the TPU-native DEFAULT
precision (mixed bf16-pass matmul — labeled as such in the metric name);
the true-float32 (precision=HIGHEST) number is measured separately at the
end of the run and recorded in BENCH_DETAILS.json.  ``vs_baseline`` is the
speedup over the same GEMM in numpy (float32, multi-threaded host BLAS) —
a strictly-stronger stand-in for the reference's "4 CPU workers" config
(the reference's Julia Distributed GEMM over 4 local TCP workers cannot
beat the host's full BLAS).

Methodology (round-3 revision).  This environment reaches the TPU through
a remote tunnel: per-dispatch latency is tens of ms and
``block_until_ready`` does NOT synchronize through it, so every timing
must chain L iterations of the op inside ONE compiled ``lax.scan``
(data-dependent so XLA cannot hoist or elide) and force completion with a
scalar fetch.  Round 2 derived per-iteration cost as the MARGINAL
difference t(L+1) - t(1); that subtraction can under-estimate when the
two measurements catch different tunnel states, and it produced one
physically impossible number (213.9 TFLOPS bf16 on a ~197-peak chip,
VERDICT round-2).  The BANKED numbers now come from DIRECT timing —
``t(L) / L`` with L grown until one call takes >= ~1.2 s — which is
bounded by physics: one call's wall time >= the device compute it
contains, so derived TFLOPS cannot exceed the chip's peak.  The marginal
estimate is still recorded per entry as a cross-check diagnostic, and
every TFLOPS entry carries its MFU against the chip's known bf16 peak;
any entry above peak is flagged in ``_impossible`` (and would indicate a
methodology bug, not a fast chip).
"""

import functools
import json
import os
import random
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent))

_HEADLINE_METRIC = "gemm_4096_gflops_mixed_precision_bf16pass"


def _t(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _periter(run_for_length, L0=8, target_s=1.2, max_L=4096):
    """Direct per-iteration cost: grow L until ONE compiled scan-chain call
    takes >= ``target_s`` (so dispatch latency is amortized below ~5%),
    then return ``(t(L)/L, L)``.  Each new L costs a compile, so L grows
    in as few steps as possible (estimate from the last timing).
    Physically bounded: wall time of one call >= its device compute."""
    L = L0
    while True:
        t = run_for_length(L)
        if t >= target_s or L >= max_L:
            return t / L, L
        est = max(t / L, 1e-7)                  # upper bound incl. dispatch
        L = min(max_L, max(L * 2, int(1.4 * target_s / est) + 1))


def _marginal(run_for_length, L0=10, min_delta=0.05, max_L=1000):
    """Marginal per-iteration cost t(L+1)-t(1) / L — round-2 methodology,
    kept ONLY as a cross-check diagnostic (see module docstring)."""
    t1 = run_for_length(1)
    L = L0
    while True:
        tL = run_for_length(L + 1)
        delta = tL - t1
        if delta >= min_delta or L >= max_L:
            return max(delta, 1e-9) / L
        L *= 4


# Dense bf16 peak TFLOPS per chip, for MFU and impossibility flags.
# Sources: public TPU spec sheets (v5e 197, v4 275, v5p 459, v6e 918).
_PEAKS_BF16 = [("v6 lite", 918.0), ("v6e", 918.0), ("v5 lite", 197.0),
               ("v5e", 197.0), ("v5p", 459.0), ("v5", 459.0),
               ("v4", 275.0), ("v3", 123.0), ("v2", 45.0)]

# Int8 peak TOPS: 2x bf16 on the e/lite chips (v5e 394, v6e 1836); the
# p-class and older chips run int8 at the bf16 rate (no doubling).
_PEAKS_INT8 = [("v6 lite", 1836.0), ("v6e", 1836.0), ("v5 lite", 394.0),
               ("v5e", 394.0), ("v5p", 459.0), ("v5", 459.0),
               ("v4", 275.0), ("v3", 123.0), ("v2", 45.0)]


def _chip_peak_tflops(device_kind: str, table=_PEAKS_BF16):
    dk = device_kind.lower()
    for frag, peak in table:
        if frag in dk:
            return peak
    return None


def _bank_tflops(details, name, tflops, peak, unit="tflops"):
    """Record a TFLOPS (or, with ``unit="tops"``, integer TOPS) entry with
    its MFU; flag physically impossible values instead of publishing them
    silently.  The flag is a per-entry key (not a shared list) so configs
    merged via ``details.update`` cannot clobber each other's flags."""
    details[name + "_" + unit] = tflops
    if peak:
        details[name + "_mfu"] = round(tflops / peak, 4)
        if tflops > peak:
            details[name + "_IMPOSSIBLE_above_peak"] = True


def _run_with_timeout(fn, timeout_s: float, grace_s: float = 0.0):
    """Run ``fn`` on a daemon thread with a hard timeout (a wedged remote
    tunnel hangs forever instead of erroring).  Returns ``(finished,
    value_or_exception, thread)``."""
    import threading

    box = {}

    def runner():
        try:
            box["value"] = fn()
        except Exception as e:
            box["error"] = e

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive() and grace_s:
        t.join(grace_s)
    if t.is_alive():
        return False, None, t
    if "error" in box:
        return True, box["error"], t
    return True, box.get("value"), t


# DAT_BENCH_PLATFORM=cpu runs the whole harness on host CPU — for testing
# the harness logic itself (this image's sitecustomize pre-sets
# jax_platforms, so the env var alone is not enough; the config API is).
_PLATFORM = os.environ.get("DAT_BENCH_PLATFORM")

_FORCE = (f"import jax; jax.config.update('jax_platforms', {_PLATFORM!r}); "
          if _PLATFORM else "")
_PROBE_CODE = (_FORCE +
               "import jax, jax.numpy as jnp; "
               "print('PROBE_OK', float(jnp.sum(jnp.ones((8, 8)))), "
               "[str(d) for d in jax.devices()])")


def _backoff_sleep(attempt: int, base: float = 12.0, cap: float = 60.0,
                   bound: float | None = None):
    """Jittered exponential backoff between probe attempts.  Jitter
    matters here for the same reason it does in any retry storm: the
    watch loop, the driver's full run, and a targeted rerun can all be
    probing the same wedged tunnel, and synchronized retries hammer it
    at the same instants.  Deterministic under DA_TPU_FAULT_SEED (the
    chaos harness's seed) so resilience tests replay exactly.
    ``bound`` caps the sleep (remaining-budget clamp)."""
    delay = min(base * (2 ** attempt), cap)
    try:
        seed = int(os.environ.get("DA_TPU_FAULT_SEED", ""))
    except ValueError:
        seed = None          # unset/garbage seed: genuinely random jitter
    # integer seed mixing, not tuple hashing (hash salting breaks replay)
    r = (random.Random(seed * 1_000_003 + attempt).random()
         if seed is not None else random.random())
    s = delay * (0.5 + r)
    if bound is not None:
        s = min(s, max(bound, 0.0))
    time.sleep(s)


def _probe_with_retry(budget_s: float = 900.0):
    """Probe the accelerator in FRESH SUBPROCESSES with growing timeouts
    and bounded, jitter-backoff retries: the observed wedges are
    transient (VERDICT round-3 item 1, the BENCH_r01–r05 "unreachable"
    failure mode), and a wedged attempt must not poison this process's
    runtime.  Returns {"ok": True, "attempts": n} or
    {"ok": False, "error": ...}."""
    t0 = time.monotonic()
    schedule = [90, 120, 180, 240, 300, 300, 300]
    errors = []
    for i, tmo in enumerate(schedule):
        left = budget_s - (time.monotonic() - t0)
        if left < 45:
            break
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=min(tmo, left),
                env={**os.environ, "PYTHONWARNINGS": "ignore"})
            if "PROBE_OK 64.0" in r.stdout:
                return {"ok": True, "attempts": i + 1,
                        "probe_s": time.monotonic() - t0}
            errors.append(f"attempt {i+1}: rc={r.returncode} "
                          f"{(r.stderr or r.stdout)[-200:]!r}")
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {i+1}: timed out after {tmo:.0f}s")
        # no dead sleep after the FINAL attempt, and never sleep past
        # the budget: the failure path must report promptly
        left = budget_s - (time.monotonic() - t0)
        if i < len(schedule) - 1 and left > 45:
            _backoff_sleep(i, bound=left - 45)
    return {"ok": False,
            "error": f"accelerator unreachable after {len(errors)} attempts "
                     f"over {time.monotonic() - t0:.0f}s: "
                     + " | ".join(errors[-3:])}


def _save(details):
    Path(__file__).with_name("BENCH_DETAILS.json").write_text(
        json.dumps(details, indent=2))


def _acquire_details_lock():
    """Serialize whole bench.py invocations with an flock'd sidecar file.

    BENCH_DETAILS.json is a read-modify-write: every invocation seeds its
    table from the banked file at startup and rewrites the file on each
    _save.  Two concurrent invocations (pass-2 and pass-3 runners racing,
    or a driver full run against a targeted rerun) would each seed from
    the pre-run table and the later writer would erase the earlier one's
    freshly banked labels (ADVICE round-5).  flock is kernel-released on
    process death, so a crashed holder can never wedge later runs.
    Returns the held file object (keep it referenced), or None when the
    lock could not be acquired within DAT_BENCH_LOCK_WAIT_S (default 1h —
    longer than any single legitimate invocation)."""
    import fcntl
    f = open(_LOCK_PATH, "w")
    deadline = time.monotonic() + float(
        os.environ.get("DAT_BENCH_LOCK_WAIT_S", "3600"))
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if time.monotonic() >= deadline:
                f.close()
                return None
            time.sleep(2)


def _collapse_provenances(prior_provs):
    """Collapse provenance headers whose environment matches into one
    header carrying the list of measurement times: the pass-2 runner
    makes ~21 invocations against the same chip, and 21 near-identical
    dicts in a tracked file record nothing the utc list doesn't.
    Headers from a DIFFERENT device/platform/method stay separate — that
    distinction is the point of the chain.  ``probe_attempts`` /
    ``device_init_attempts`` are evidence (how flaky was the tunnel for
    these measurements) — the max is carried through as
    ``probe_attempts_max`` instead of being dropped with the per-run
    header (ADVICE round-5)."""
    collapsed = []
    for p in prior_provs:
        sig = {k: v for k, v in p.items()
               if k not in ("utc", "utcs", "probe_attempts",
                            "device_init_attempts", "probe_attempts_max")}
        utcs = p.get("utcs", []) + ([p["utc"]] if p.get("utc") else [])
        atts = [a for a in (p.get("probe_attempts_max"),
                            p.get("probe_attempts"),
                            p.get("device_init_attempts"))
                if a is not None]
        for c in collapsed:
            if {k: v for k, v in c.items()
                    if k not in ("utcs", "probe_attempts_max")} == sig:
                c["utcs"].extend(u for u in utcs if u not in c["utcs"])
                if atts:
                    c["probe_attempts_max"] = max(
                        atts + ([c["probe_attempts_max"]]
                                if "probe_attempts_max" in c else []))
                break
        else:
            entry = {**sig, "utcs": utcs}
            if atts:
                entry["probe_attempts_max"] = max(atts)
            collapsed.append(entry)
    return collapsed


# once a timed-out config leaves an orphaned daemon thread alive, its
# ongoing dispatches keep feeding the process-wide telemetry totals —
# every later label's delta would silently include the orphan's traffic,
# so the comms-bytes column stops being bankable for the rest of this
# invocation
_COMM_TAINTED = False

# module-level so tests can point the lock at a sandbox instead of
# contending on (or briefly holding) the repo's production lock
_LOCK_PATH = Path(__file__).with_name("BENCH_DETAILS.lock")


def _comm_bytes_now():
    """Telemetry's cumulative estimated comm bytes (0 if unavailable).
    Imported lazily: bench.py must not import jax before the subprocess
    probe has cleared the tunnel."""
    try:
        from distributedarrays_tpu import telemetry
        return telemetry.comm_bytes()
    except Exception:
        return 0


# partial-row banking (ROADMAP item 5): a config that measures several
# metrics publishes the ones already complete through ``bank_partial``;
# if the config then times out (or dies), _guarded banks the published
# metrics with ``{label}_partial: true`` provenance instead of discarding
# the whole row — a 20-minute silicon window that produced a real
# iteration count keeps it even when the timing reps never finished.  A
# later full success supersedes the partials (the flag clears with the
# other stale markers), and a partial row does NOT count as banked, so
# the next window re-attempts the full config.
import threading as _threading

_PARTIAL_LOCK = _threading.Lock()
_PARTIAL: dict = {}


def bank_partial(label, **metrics):
    """Publish already-measured metrics from inside a running config."""
    with _PARTIAL_LOCK:
        _PARTIAL.setdefault(label, {}).update(metrics)


def _take_partial(label):
    with _PARTIAL_LOCK:
        return _PARTIAL.pop(label, None)


def _span_wrapped(label, fn, stats=None):
    """Run a config under a ``bench.config`` telemetry span so the
    journal's comm/span events are attributable per bench label.  The
    span opens INSIDE the worker thread that executes ``fn`` (contextvar
    spans do not cross threads) — and so does the HBM-ledger watermark
    read: the peak is reset per config and sampled into ``stats`` right
    after ``fn`` returns, before any later config can move it.  Imported
    lazily like ``_comm_bytes_now``; degrades to the bare fn if
    telemetry is unavailable."""
    def run():
        try:
            from distributedarrays_tpu import telemetry
            from distributedarrays_tpu.telemetry import memory as _mem
        except Exception:
            return fn()
        _mem.reset_peak()
        with telemetry.span("bench.config", label=label):
            res = fn()
        if stats is not None:
            stats["hbm_peak_mb"] = round(_mem.peak_bytes() / 2 ** 20, 3)
        return res
    return run


_START = time.monotonic()
# headroom under the driver's own timeout; env override for harness tests
_GLOBAL_BUDGET_S = float(os.environ.get("DAT_BENCH_BUDGET_S", "3300"))
# targeted reruns can afford longer per-config windows: round 5's first
# hardware pass showed a full flash sweep overruns the default 900s when
# every arm pays a fresh remote compile through the tunnel
_TSCALE = float(os.environ.get("DAT_BENCH_TIMEOUT_SCALE", "1"))


_ONLY = {s.strip() for s in os.environ.get("DAT_BENCH_ONLY", "").split(",")
         if s.strip()}
_SEEN_LABELS: set[str] = set()

# One result key each guarded config is guaranteed to merge on success.
# Single source of truth for "is this label banked?" — consumed here (so a
# rerun failure never masks a banked result) and by tools/bench_pass2.py
# (so the one-config-per-process runner knows what still needs hardware);
# tests/test_bench_pass2.py pins every entry against this file's key
# literals so the map cannot drift from the configs.
BANKED_SENTINELS = {
    "flash_attn_d128": "flash_attn_d128_tuned_block",
    "flash_attn_tune": "flash_attn_tuned_block",
    "flash_attn_full": "flash_attn_full_tuned_block",
    "sp_train": "sp_train_step_s",
    "sp_train_d128": "sp_train_d128_step_s",
    "transformer_train": "transformer_train_step_s",
    "decode_kvcache": "decode_kvcache_tokens_per_s",
    "int8_gemm": "int8_gemm_4096_s_per_iter",
    "pallas_gemm": "pallas_gemm_4096_bf16_s_per_iter",
    "pallas_gemm_tune": "pallas_gemm_tuned_block",
    "gemm_16k_1x1": "gemm_16k_1x1_bf16pass_gflops",
    "ring_hop": "ring_hop_fused_8k_bf16_s",
    "ring_train": "ring_train_8k_bf16_s_per_iter",
    "flash_train": "flash_train_8k_bf16_s_per_iter",
    "stencil": "stencil_8192_step_s_per_iter",
    "stencil_jnp": "stencil_8192_jnp_gcells_per_s",
    "stencil_temporal": "stencil_8192_temporal_s_per_iter",
    "reshard_even": "reshard_even_s",
    "ring_gemm": "ring_gemm_xla_s",
    "serve_load": "serve_load_p99_s",
    "serve_decode": "serve_decode_tokens_per_s",
    "train_step": "train_step_s",
    "reshard_uneven": "reshard_uneven_fill_s",
    "reshard_mutate": "reshard_mutate_s",
    "reshard_multiaxis": "reshard_multiaxis_s",
    "broadcast_chain": "broadcast_chain_8192_s_per_iter",
    "mapreduce": "mapreduce_1e8_s_per_iter",
    "sort": "sort_1e7_s",
    "gemm_f32_highest": "gemm_4096_f32_highest_gflops",
    "gemm_16k_1x1_f32_highest": "gemm_16k_1x1_f32_highest_gflops",
    "gemm_crosscheck": "gemm_4096_marginal_crosscheck_s",
    "cg_poisson": "cg_poisson_time_s",
    "matmul_impl_tune": "matmul_impl_tune_n",
    "flash_attn": "flash_attn_8k_bf16_s_per_iter",
}


# --rows probe budgets, per label: configs that publish incremental
# partials (bank_partial after each completed measurement) can afford a
# SHORT window — whatever the window completes is banked, so retrying
# with a small budget beats waiting out one long probe.  Labels not
# listed keep the 240s default.
_ROW_PROBE_BUDGET_S = {
    "reshard_even": 120,        # banks s+gbps after the first rep
    "reshard_multiaxis": 180,   # banks each arm as it lands
    "ring_gemm": 150,           # banks the XLA arm first
    "train_step": 180,          # banks step_s+tflops after one step
    "serve_decode": 180,        # banks the unloaded rate pre-window
    "cg_poisson": 240,          # banks iters/residual, then first solve
}


def _banked_in(details, label):
    """True iff the seeded master table already holds this label's result
    from an earlier silicon run (sentinel present, no error marker)."""
    sent = BANKED_SENTINELS.get(label)
    if sent is None and label.startswith("gemm_16k_"):
        # the one dynamic label family: gemm_16k_{r}x{c}[_f32_highest],
        # tagged with the run's device grid — derive the sentinel the way
        # the config closures build their keys
        sent = label + ("_gflops" if label.endswith("_f32_highest")
                        else "_bf16pass_gflops")
    return (sent is not None and sent in details
            and f"{label}_error" not in details
            # a partial row holds real numbers but not the full config:
            # the next hardware window must re-attempt it
            and not details.get(f"{label}_partial"))


def _guarded(details, label, fn, timeout_s=420.0):
    """Run one optional bench config on a daemon thread with a timeout and
    a global deadline: a wedged tunnel (observed: remote_compile dying
    mid-read, then every subsequent dispatch hanging) must cost at most
    one config's budget, and never the already-banked numbers or the
    headline.  ``fn`` returns a dict merged into ``details``.
    ``DAT_BENCH_ONLY=label1,label2`` restricts the optional configs to the
    named ones (targeted harness validation; a short hardware window can
    aim straight at the config it needs)."""
    def _remaining():
        return _GLOBAL_BUDGET_S - (time.monotonic() - _START)

    _SEEN_LABELS.add(label)
    if _ONLY and label not in _ONLY:
        # no marker write: a targeted rerun must not stamp skip-"errors"
        # over the seeded master table's banked results (review round-5)
        return
    banked = _banked_in(details, label)
    if _remaining() < 60:
        # a banked result outlives a later invocation's deadline: the
        # skip marker would read as "this config has no number" when the
        # master table holds a real one from the silicon window
        if not banked:
            details[f"{label}_error"] = "skipped (global bench deadline)"
            _save(details)
        return
    # the label is about to actually execute: clear ITS stale failure
    # markers (in memory only — no _save until an outcome exists) so
    # whatever ends up in the table is attributable to this attempt.
    # Labels this invocation never reaches keep their markers on disk.
    for stale in (f"{label}_error", f"{label}_rerun_error",
                  f"{label}_orphan_running", f"{label}_partial"):
        details.pop(stale, None)
    _take_partial(label)                 # drop any stale published metrics
    comm0 = _comm_bytes_now()
    worker_stats: dict = {}
    fn = _span_wrapped(label, fn, worker_stats)
    effective = min(timeout_s * _TSCALE, _remaining())
    finished, res, thread = _run_with_timeout(fn, effective)
    if finished and isinstance(res, Exception) and \
            "remote_compile" in str(res) and _remaining() > 75:
        # transient tunnel-service flake (observed: response body closed
        # mid-read); one retry after a settle pause
        time.sleep(15)
        effective = min(timeout_s * _TSCALE, _remaining())
        finished, res, thread = _run_with_timeout(fn, effective)
    # a rerun failure next to a banked result goes under _rerun_error:
    # the earlier measurement stays trusted, the fresh failure stays
    # visible, and pass-2's banked() check is unaffected
    err_key = f"{label}_rerun_error" if banked else f"{label}_error"
    if not finished:
        details[err_key] = f"timed out after {effective:.0f}s"
        partial = _take_partial(label)
        if partial:
            # bank what the config DID measure, flagged as partial
            details.update(partial)
            details[f"{label}_partial"] = True
        thread.join(60)
        if thread.is_alive():
            details[f"{label}_orphan_running"] = True
            global _COMM_TAINTED
            _COMM_TAINTED = True
    elif isinstance(res, Exception):
        details[err_key] = f"{type(res).__name__}: {res}"
        partial = _take_partial(label)
        if partial:
            details.update(partial)
            details[f"{label}_partial"] = True
    elif res:
        details.update(res)
        _take_partial(label)             # full row supersedes the partials
        for stale in (f"{label}_error", f"{label}_rerun_error",
                      f"{label}_orphan_running", f"{label}_partial"):
            details.pop(stale, None)
        # comms-bytes column: estimated bytes this config moved (telemetry
        # comm accounting delta over the config's whole run, retries
        # included) — 0 when telemetry is disabled.  Not banked once an
        # orphaned config's thread is loose: its concurrent traffic would
        # inflate every later label's delta.
        if not _COMM_TAINTED:
            details[f"{label}_comm_bytes_est"] = _comm_bytes_now() - comm0
            # HBM watermark column: the ledger peak over this config's
            # run (reset + read inside the worker thread) — same taint
            # rule as the comm column: an orphaned config's allocations
            # would inflate later labels' watermarks
            if "hbm_peak_mb" in worker_stats:
                details[f"{label}_hbm_peak_mb"] = worker_stats["hbm_peak_mb"]
    _save(details)


def _replay_row(gflops, cpu_gflops, prov, probe_error) -> dict:
    """The headline row printed when the probe fails but an earlier run
    banked a direct-method measurement: a labeled REPLAY, not a fresh
    number.  ``replayed: true`` + ``probe_error`` are the machine-readable
    flags (BENCH_r05 carried only the prose note) — the regression
    sentinel (`telemetry regress`) and any trajectory tooling must never
    treat a replay as a fresh measurement, and the prose note alone was
    one rewording away from being mistaken for one."""
    return {
        "metric": _HEADLINE_METRIC,
        "value": round(gflops, 2),
        "unit": "GFLOPS",
        "vs_baseline": round(gflops / cpu_gflops, 2),
        "replayed": True,
        "replayed_from_utc": prov.get("utc"),
        "probe_error": str(probe_error)[:200],
        "note": ("replayed from the banked table measured "
                 f"{prov.get('utc')} on {prov.get('device_kind')}; "
                 "live probe failed this invocation: "
                 + str(probe_error)[:200]),
    }


def _parse_args(argv=None):
    """Per-row probe-budget selection for targeted silicon windows.

    ``--rows a,b`` selects the named guarded configs (union with
    ``DAT_BENCH_ONLY``) and drops the default tunnel-probe budget from
    900s to 240s: a window aimed at the never-live rows (``ring_gemm``,
    ``reshard_even``, ``train_step``, ``serve_decode``) should spend its
    minutes measuring, not re-proving the tunnel the full-run way.
    ``--probe-budget`` / ``--budget`` override the probe and global
    deadlines outright; ``--list-rows`` prints the known labels."""
    import argparse
    global _ONLY, _GLOBAL_BUDGET_S
    ap = argparse.ArgumentParser(
        prog="bench.py",
        description="Hardware bench: headline GEMM + guarded configs.")
    ap.add_argument("--rows", default=None, metavar="LABEL[,LABEL...]",
                    help="run only these guarded configs (plus 'headline'"
                         " to include the headline GEMM); implies a 240s"
                         " probe budget")
    ap.add_argument("--probe-budget", type=float, default=None,
                    metavar="S", help="tunnel-probe budget in seconds "
                    "(default 900, or 240 with --rows)")
    ap.add_argument("--budget", type=float, default=None, metavar="S",
                    help="global bench deadline in seconds "
                         "(default DAT_BENCH_BUDGET_S or 3300)")
    ap.add_argument("--list-rows", action="store_true",
                    help="print the known row labels and exit")
    args = ap.parse_args(argv)
    if args.list_rows:
        print("\n".join(["headline"] + sorted(BANKED_SENTINELS)))
        raise SystemExit(0)
    if args.rows:
        _ONLY = _ONLY | {s.strip() for s in args.rows.split(",")
                         if s.strip()}
        # targeted reruns take the LARGEST budget any named row asks for
        # (one probe serves them all); rows that bank incrementally via
        # bank_partial get shorter windows — even a truncated window now
        # leaves real numbers behind
        budget = max((_ROW_PROBE_BUDGET_S.get(r, 240) for r in _ONLY),
                     default=240)
        os.environ.setdefault("DAT_BENCH_PROBE_BUDGET_S", str(budget))
    if args.probe_budget is not None:
        os.environ["DAT_BENCH_PROBE_BUDGET_S"] = str(args.probe_budget)
    if args.budget is not None:
        _GLOBAL_BUDGET_S = float(args.budget)
    return args


def main():
    probe = _probe_with_retry(
        float(os.environ.get("DAT_BENCH_PROBE_BUDGET_S", "900")))
    if not probe["ok"]:
        # The tunnel is unreachable for THIS invocation — but if a run
        # earlier in the same checkout banked a direct-method headline on
        # real silicon, reprint it WITH ITS PROVENANCE instead of 0.0.
        # This is a labeled replay of a real measurement, not a live one:
        # the note says exactly when it was measured and that this
        # invocation's probe failed.  (Round-5: the tunnel held for 8
        # minutes, banked the headline, and wedged again — a 0.0 here
        # would erase the only trusted hardware evidence of the round.)
        try:
            banked = json.loads(
                Path(__file__).with_name("BENCH_DETAILS.json").read_text())
        except Exception:
            banked = {}
        prov = banked.get("_provenance") or {}
        g = banked.get("gemm_4096_mixed_bf16pass_gflops")
        cpu = banked.get("cpu_numpy_gflops")
        if g and cpu and "direct" in str(prov.get("method", "")):
            print(json.dumps(_replay_row(g, cpu, prov, probe["error"])))
            return
        print(json.dumps({
            "metric": _HEADLINE_METRIC,
            "value": 0.0, "unit": "GFLOPS", "vs_baseline": 0.0,
            "error": probe["error"],
        }))
        return

    import jax
    if _PLATFORM:
        jax.config.update("jax_platforms", _PLATFORM)
    import jax.numpy as jnp
    from jax import lax
    import distributedarrays_tpu as dat
    from distributedarrays_tpu.models import stencil

    # serialize with any concurrent bench.py before touching the details
    # file: the seeded read-modify-write below would lose the other
    # invocation's banked labels (ADVICE round-5)
    _lock_t0 = time.monotonic()
    _details_lock = _acquire_details_lock()
    if _details_lock is None:
        print(json.dumps({
            "metric": _HEADLINE_METRIC,
            "value": 0.0, "unit": "GFLOPS", "vs_baseline": 0.0,
            "error": "another bench.py invocation holds BENCH_DETAILS.lock"
                     " (waited DAT_BENCH_LOCK_WAIT_S); not running —"
                     " concurrent table writes would lose banked labels",
        }))
        return
    # time spent WAITING on another invocation's lock is not this run's
    # measurement time: shift the budget origin so a late acquisition
    # doesn't immediately stamp deadline-skip markers over every
    # unbanked label it was about to measure
    global _START
    _START += time.monotonic() - _lock_t0

    # keep the previous run's banked numbers recoverable: this run's first
    # _save overwrites the file, and a wedge mid-run must not cost the
    # last full run's evidence (copy, not rename — the tracked file must
    # never transiently disappear from the working tree)
    cur = Path(__file__).with_name("BENCH_DETAILS.json")
    if cur.exists():
        import shutil
        shutil.copyfile(cur, cur.with_name("BENCH_DETAILS_prev.json"))

    # device init in THIS process can still wedge even after a subprocess
    # probe succeeded — bounded retries with the same jittered backoff as
    # the subprocess probe, attempts banked as provenance evidence
    init_attempts = 0
    for attempt in range(3):
        init_attempts = attempt + 1
        finished, devs, _ = _run_with_timeout(jax.devices, 300)
        if finished and not isinstance(devs, Exception):
            break
        if attempt < 2:           # no dead sleep after the final attempt
            _backoff_sleep(attempt, base=15.0)
    else:
        print(json.dumps({
            "metric": _HEADLINE_METRIC,
            "value": 0.0, "unit": "GFLOPS", "vs_baseline": 0.0,
            "error": f"probe subprocess succeeded but in-process device "
                     f"init wedged {init_attempts} times",
        }))
        return

    ndev = len(devs)
    peak = _chip_peak_tflops(devs[0].device_kind)
    details = {
        "_provenance": {
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "platform_override": _PLATFORM,
            "devices": [str(d) for d in devs],
            "device_kind": devs[0].device_kind,
            "bf16_peak_tflops": peak,
            "method": "direct t(L)/L over one compiled lax.scan chain, "
                      "scalar-fetch forced; marginal t(L+1)-t(1) recorded "
                      "as *_marginal_crosscheck_s diagnostics only",
            "probe_attempts": probe.get("attempts"),
            "device_init_attempts": init_attempts,
        },
    }

    # Seed from the banked table in EVERY mode so ONE master file
    # accumulates across invocations (targeted pass-2 reruns AND the
    # driver's end-of-round full run).  Running one config per process is
    # the fix for round 5's first-pass failure mode — a sweep that times
    # out leaves an orphan daemon thread still dispatching, and every
    # later config in the same process times against that load.  A full
    # run used to start the table fresh, which meant its 55-minute budget
    # would replace 35-minute sweep winners with deadline-skip markers;
    # now a config this run reaches overwrites its banked entry, and one
    # it cannot reach keeps the silicon number (with the provenance chain
    # recording which run measured what).
    try:
        prior = json.loads(cur.read_text()) if cur.exists() else {}
    except Exception:
        prior = {}
    # NOTE: stale failure markers are cleared per-label inside _guarded,
    # at the moment the label actually executes — clearing them here for
    # every DAT_BENCH_ONLY label would erase recorded failure evidence
    # for labels this invocation never reaches (killed mid-run, deadline)
    for k in ("bench_only_unmatched_labels", "bench_only_known_labels"):
        prior.pop(k, None)
    prior_prov = prior.pop("_provenance", None)
    prior_provs = prior.pop("_prior_provenances", [])
    details.update(prior)
    if prior_prov is not None:
        prior_provs = prior_provs + [prior_prov]
    # Collapse runs whose environment matches into one header carrying the
    # list of measurement times: the pass-2 runner makes ~21 invocations
    # against the same chip, and 21 near-identical dicts in a tracked file
    # record nothing the utc list doesn't.  Headers from a DIFFERENT
    # device/platform/method stay separate — that distinction is the
    # point of the chain.
    collapsed = _collapse_provenances(prior_provs)
    if collapsed:
        details["_prior_provenances"] = collapsed
    # a banked headline is only reusable if it came from the direct
    # t(L)/L method — never reprint a distrusted-format table's number
    _prior_direct = bool(prior_prov) and \
        "direct" in str(prior_prov.get("method", ""))

    # ---- config 0 (headline): 4096^2 GEMM, DEFAULT precision ------------
    N = 4096
    dat.seed(7)
    A = dat.drand((N, N), dtype=jnp.float32)
    B = dat.drand((N, N), dtype=jnp.float32)
    scale = jnp.float32(1.0 / N)

    def gemm_chain_at(precision, reps=2):
        def gemm_chain(L):
            @dat.djit
            def f(a, b):
                def body(c, _):
                    return jnp.matmul(c, b, precision=precision) * scale, None
                c, _ = lax.scan(body, a, None, length=L)
                return jnp.sum(c)
            float(f(A, B))                  # compile + warmup
            return min(_t(lambda: float(f(A, B))) for _ in range(reps))
        return gemm_chain

    chain = gemm_chain_at(jax.lax.Precision.DEFAULT)
    # in a targeted rerun the headline is usually already banked — don't
    # re-pay its ~2 min before the config the short window is aimed at
    _SEEN_LABELS.add("headline")
    _have_headline = ("gemm_4096_mixed_bf16pass_gflops" in details
                      and "gemm_4096_mixed_bf16pass_s_per_iter" in details
                      and "cpu_numpy_gflops" in details
                      and _prior_direct)
    if not _ONLY or "headline" in _ONLY or not _have_headline:
        comm0 = _comm_bytes_now()
        t_gemm, L_used = _periter(chain, L0=64)
        gflops = 2 * N**3 / t_gemm / 1e9
        details["gemm_4096_mixed_bf16pass_s_per_iter"] = t_gemm
        details["gemm_4096_mixed_bf16pass_L"] = L_used
        details["gemm_4096_mixed_bf16pass_gflops"] = gflops
        _bank_tflops(details, "gemm_4096_mixed_bf16pass", gflops / 1e3, peak)
        (A @ B).garray                     # compile the eager path
        details["gemm_4096_mixed_bf16pass_eager_latency_s"] = _t(
            lambda: (A @ B).garray)
        details["gemm_4096_mixed_bf16pass_comm_bytes_est"] = (
            _comm_bytes_now() - comm0)
        _save(details)

        # ---- CPU baseline: same GEMM in numpy (host BLAS) ----------------
        an = np.asarray(A, dtype=np.float32)
        bn = np.asarray(B, dtype=np.float32)
        t_np = min(_t(lambda: an @ bn) for _ in range(2))
        cpu_gflops = 2 * N**3 / t_np / 1e9
        details["cpu_numpy_gflops"] = cpu_gflops
        _save(details)
    else:
        gflops = details["gemm_4096_mixed_bf16pass_gflops"]
        cpu_gflops = details["cpu_numpy_gflops"]
        t_gemm = details["gemm_4096_mixed_bf16pass_s_per_iter"]

    # headline out NOW: everything after this point is banked detail, and a
    # tunnel wedge in a later config must not cost the round its one JSON
    # line (round-1 lesson; this run prints exactly this one line)
    print(json.dumps({
        "metric": _HEADLINE_METRIC,
        "value": round(gflops, 2),
        "unit": "GFLOPS",
        "vs_baseline": round(gflops / cpu_gflops, 2),
    }), flush=True)

    if not _ONLY or "headline" in _ONLY:
        # sum(A.^2) half of config 0 (after the headline: detail only).
        # In targeted mode this runs ONLY when explicitly asked: it is
        # unguarded (no per-config timeout), and a wedge here would cost
        # the config the short hardware window was aimed at.
        float(dat.dmapreduce(jnp.square, "sum", A))
        details["sum_sq_4096_eager_s"] = _t(
            lambda: float(dat.dmapreduce(jnp.square, "sum", A)))
        _save(details)

    # methodology cross-check on the SAME op: the round-2 marginal
    # estimator vs the banked direct number (agreement ratio recorded; a
    # marginal-derived TFLOPS above peak proves the estimator, not the
    # chip)
    def cfg_crosscheck():
        t_m = _marginal(chain, L0=50)
        out = {"gemm_4096_marginal_crosscheck_s": t_m,
               "gemm_4096_marginal_vs_direct_ratio": t_m / t_gemm}
        return out

    _guarded(details, "gemm_crosscheck", cfg_crosscheck, timeout_s=300)

    # ---- matmul implementation tune (VERDICT round-4 item 4): measure
    # jnp.matmul vs the owned Pallas schedule at the headline shape for
    # the dtypes users actually hit, bank the winner in the autotune
    # registry (consulted by `matmul` / `DArray @ DArray`), and persist
    # it so every later process in this tree dispatches to the winner.
    def cfg_matmul_impl_tune():
        from distributedarrays_tpu.utils import autotune
        from distributedarrays_tpu.ops import linalg as _la
        # DAT_BENCH_TUNE_N: harness-validation override — the 4096 shape
        # in interpret-mode Pallas is unboundedly slow on host CPU
        TN = int(os.environ.get("DAT_BENCH_TUNE_N", N))

        def chain_timer(op, a, b):
            # the trusted t(L)/L method, handed to the API's tuner so
            # measure/record/persist has ONE owner (linalg._tune_impls)
            dt = a.dtype
            sc = jnp.asarray(1.0 / a.shape[-1], dt)

            def chain(L):
                @jax.jit
                def f(a_, b_):
                    def body(c, _):
                        return (op(c, b_) * sc).astype(dt), None
                    c, _ = lax.scan(body, a_, None, length=L)
                    return jnp.sum(c.astype(jnp.float32))
                float(f(a, b))              # compile + warmup
                return min(_t(lambda: float(f(a, b))) for _ in range(2))

            t, _ = _periter(chain, L0=32)
            return t

        # a winner measured under the forced host-CPU validation run must
        # never persist where a TPU process would load it (the registry
        # key carries the device kind as a second fence)
        persist = _PLATFORM != "cpu" and jax.default_backend() != "cpu"
        # the shape is part of the result's identity: an override run
        # (harness validation) must never read as headline-4096 numbers
        out = {"matmul_impl_tune_n": TN}
        # each tuner persists its own winner the moment it lands (wedge
        # resilience: a later tuner dying must not cost earlier spoils)
        for dt, tag in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
            winner, results = _la.tune_matmul_impl(
                TN, TN, TN, dtype=dt, timer=chain_timer, persist=persist)
            for impl, t in results.items():
                if t != float("inf"):
                    out[f"matmul_impl_{tag}_{impl}_s_per_iter"] = t
            out[f"matmul_impl_{tag}_winner"] = winner
        if len(jax.devices()) >= 2:
            winner, results = _la.tune_matmul_impl_dist(
                TN, TN, TN, timer=chain_timer, persist=persist)
            for impl, t in results.items():
                if t != float("inf"):
                    out[f"matmul_impl_dist_{impl}_s_per_iter"] = t
            out["matmul_impl_dist_winner"] = winner
        if len(jax.devices()) >= 4:
            # the 2-D-grid arm (BASELINE config 3's block layout): GSPMD
            # vs the owned tile schedule (Cannon on square grids, SUMMA
            # panels on rectangles) on the largest power-of-two (r, c)
            # grid the devices support — power-of-two factors so the
            # shape rounding below always divides; e.g. 4 -> 2x2,
            # 8 -> 2x4 (all chips used), 16 -> 4x4 — at the 16384²
            # config's shape (scaled by the harness override, rounded
            # to an lcm(r, c) multiple)
            ndev = len(jax.devices())
            gr = 2
            while (2 * gr) * (2 * gr) <= ndev:
                gr *= 2
            gc = gr * 2 if gr * gr * 2 <= ndev else gr
            TS = int(os.environ.get("DAT_BENCH_TUNE_N", 4 * N))
            TS -= TS % max(gr, gc)
            winner, results = _la.tune_matmul_impl_summa(
                TS, TS, TS, g=(gr, gc), timer=chain_timer, persist=persist)
            for impl, t in results.items():
                if t != float("inf"):
                    out[f"matmul_impl_summa_{gr}x{gc}_{impl}_s_per_iter"] = t
            out[f"matmul_impl_summa_{gr}x{gc}_winner"] = winner
            out["matmul_impl_summa_n"] = TS
        if persist:
            out["matmul_impl_cache_path"] = autotune.default_cache_path()
        return out

    _guarded(details, "matmul_impl_tune", cfg_matmul_impl_tune,
             timeout_s=600)


    # ---- extra: Pallas flash attention at long context -------------------
    def cfg_flash():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        SQ, HQ, DQ = 8192, 8, 64
        q = jax.random.normal(jax.random.key(1), (SQ, HQ, DQ), jnp.bfloat16)

        def fa_len(L):
            def f():
                def body(x, _):
                    # 1024^2 blocks: the measured-best tiling on v5e
                    return flash_attention(x, q, q, causal=True,
                                           block_q=1024, block_k=1024), None
                x, _ = lax.scan(body, q, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t_fa, L = _periter(fa_len, L0=8)
        # causal flash: ~2*S^2*D*H flops (QK^T + PV), halved by causality
        flops = 2 * 2 * SQ * SQ * DQ * HQ / 2
        out = {"flash_attn_8k_bf16_s_per_iter": t_fa}
        _bank_tflops(out, "flash_attn_8k_bf16_causal_effective",
                     flops / t_fa / 1e12, peak)
        return out

    _guarded(details, "flash_attn", cfg_flash)

    # ---- extra: flash-attention block autotune sweep ---------------------
    # sweeps (block_q, block_k) at the bench shape, records the winner in
    # the autotune registry (consulted by flash_attention when blocks are
    # unspecified), and reports the tuned TFLOPS
    def cfg_flash_tune():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        from distributedarrays_tpu.utils import autotune
        SQ, HQ, DQ = 8192, 8, 64
        q = jax.random.normal(jax.random.key(1), (SQ, HQ, DQ), jnp.bfloat16)

        def timer(cfg):
            bq, bk = cfg[0], cfg[1]
            hf = cfg[2] if len(cfg) > 2 else 1

            # FIXED chain length — exactly ONE compile per arm.  Through
            # the tunnel each compile costs tens of seconds, and growing
            # L re-compiles; ranking arms needs ratios at ~0.5 s/call
            # (dispatch noise <5%), not dispatch-free absolutes — the
            # banked entry re-times the winner properly.
            L = 384

            def f():
                def body(x, _):
                    return flash_attention(x, q, q, causal=True,
                                           block_q=bq, block_k=bk,
                                           head_fold=hf), None
                x, _ = lax.scan(body, q, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2)) / L

        cands = [(bq, bk) for bq in (512, 1024, 2048)
                 for bk in (512, 1024, 2048)]
        # head-fold arms: batched-dot grid steps amortize grid/DMA
        # overhead at small head_dim (the QK/PV contraction width stays
        # 64, so this tunes scheduling, not the MXU ceiling)
        cands += [(1024, 1024, 2), (1024, 1024, 4), (2048, 1024, 2),
                  (512, 512, 2), (512, 512, 4)]
        key = autotune.device_key_for(SQ, HQ, DQ, jnp.bfloat16(0).dtype, True)
        best, results = autotune.sweep("flash_attention", key, cands, timer, persist=True)
        cache = autotune.save_default()   # future processes pick this up
        flops = 2 * 2 * SQ * SQ * DQ * HQ / 2
        out = {
            "flash_attn_tuned_block": list(best),
            "flash_attn_sweep": {
                "x".join(str(v) for v in cfg): flops / t / 1e12
                for cfg, t in results.items()},
            "autotune_cache_path": cache,
        }
        _bank_tflops(out, "flash_attn_tuned_causal_effective",
                     flops / results[best] / 1e12, peak)
        return out

    _guarded(details, "flash_attn_tune", cfg_flash_tune, timeout_s=900)

    # ---- extra: non-causal flash MFU (VERDICT round-3 item 5) ------------
    def cfg_flash_full():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        from distributedarrays_tpu.utils import autotune
        SQ, HQ, DQ = 8192, 8, 64
        q = jax.random.normal(jax.random.key(1), (SQ, HQ, DQ), jnp.bfloat16)

        def timer(cfg):
            bq, bk = cfg[0], cfg[1]
            hf = cfg[2] if len(cfg) > 2 else 1
            L = 192                      # fixed: one compile per arm

            def f():
                def body(x, _):
                    return flash_attention(x, q, q, causal=False,
                                           block_q=bq, block_k=bk,
                                           head_fold=hf), None
                x, _ = lax.scan(body, q, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2)) / L

        cands = [(512, 512), (1024, 1024), (2048, 1024), (1024, 2048),
                 (2048, 2048), (4096, 1024),
                 (1024, 1024, 2), (1024, 1024, 4), (2048, 1024, 2)]
        key = autotune.device_key_for(SQ, HQ, DQ, jnp.bfloat16(0).dtype, False)
        best, results = autotune.sweep("flash_attention", key, cands, timer, persist=True)
        autotune.save_default()
        flops = 2 * 2 * SQ * SQ * DQ * HQ        # full: no causal halving
        out = {"flash_attn_full_tuned_block": list(best),
               "flash_attn_full_sweep": {
                   "x".join(str(v) for v in cfg): flops / t / 1e12
                   for cfg, t in results.items()}}
        _bank_tflops(out, "flash_attn_8k_bf16_full",
                     flops / results[best] / 1e12, peak)
        return out

    _guarded(details, "flash_attn_full", cfg_flash_full, timeout_s=900)

    # ---- extra: d=128 flash MFU (VERDICT round-3 item 5) -----------------
    # at d=64 BOTH flash matmuls carry a 64-wide dim (QK^T contracts over
    # d, PV's N is d), so each MXU pass uses half the 128x128 array — a
    # ~50% MFU ceiling no tiling can lift.  d=128 fills the array; this
    # config shows the kernel's MFU where the hardware allows >60%.
    def cfg_flash_d128():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        from distributedarrays_tpu.utils import autotune
        SQ, HQ, DQ = 8192, 4, 128              # same bytes as the 8x64 run
        q = jax.random.normal(jax.random.key(7), (SQ, HQ, DQ), jnp.bfloat16)

        def timer(cfg):
            bq, bk = cfg[0], cfg[1]
            hf = cfg[2] if len(cfg) > 2 else 1
            L = 192                      # fixed: one compile per arm

            def f():
                def body(x, _):
                    return flash_attention(x, q, q, causal=False,
                                           block_q=bq, block_k=bk,
                                           head_fold=hf), None
                x, _ = lax.scan(body, q, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2)) / L

        cands = [(512, 512), (1024, 512), (512, 1024), (1024, 1024),
                 (2048, 512), (2048, 1024),
                 (1024, 512, 2), (1024, 1024, 2), (2048, 1024, 2),
                 # round-5 second wave: the first silicon sweep showed
                 # bk=1024 dominating bk=512 (113-117 vs 66-82 TFLOPS) and
                 # bq=1024 beating 2048 — probe deeper K tiles and the
                 # all-heads fold before settling at 0.596 MFU
                 (512, 2048), (1024, 2048), (2048, 2048),
                 (1024, 2048, 2), (1024, 1024, 4)]
        key = autotune.device_key_for(SQ, HQ, DQ, jnp.bfloat16(0).dtype, False)
        best, results = autotune.sweep("flash_attention", key, cands, timer, persist=True)
        autotune.save_default()
        flops = 2 * 2 * SQ * SQ * DQ * HQ
        out = {"flash_attn_d128_tuned_block": list(best),
               "flash_attn_d128_sweep": {
                   "x".join(str(v) for v in cfg): flops / t / 1e12
                   for cfg, t in results.items()}}
        _bank_tflops(out, "flash_attn_8k_bf16_d128_full",
                     flops / results[best] / 1e12, peak)
        return out

    _guarded(details, "flash_attn_d128", cfg_flash_d128, timeout_s=600)

    # ---- config 1: broadcast chain sin.(A) .+ B .* C on 8192^2 ----------
    M = 8192
    X = dat.drand((M, M)); Y = dat.drand((M, M)); Z = dat.drand((M, M))

    def chain_chain(L):
        @dat.djit
        def f(a, b, c):
            def body(acc, _):
                return jnp.sin(acc) + b * c, None
            acc, _ = lax.scan(body, a, None, length=L)
            return jnp.sum(acc)
        float(f(X, Y, Z))
        return min(_t(lambda: float(f(X, Y, Z))) for _ in range(2))

    def cfg_chain():
        t_chain, L = _periter(chain_chain, L0=32)
        return {"broadcast_chain_8192_s_per_iter": t_chain,
                "broadcast_chain_8192_gbps": 4 * M * M * 4 / t_chain / 1e9}

    _guarded(details, "broadcast_chain", cfg_chain)

    # ---- config 2: mapreduce(abs2,+) and mean/std over 1e8 --------------
    V = dat.drand((100_000_000,))

    def mr_chain(L):
        @dat.djit
        def f(v):
            def body(acc, _):
                # acc feeds back so the reduction re-reads v every iteration
                return acc * 1e-30 + jnp.sum(jnp.square(v + acc * 1e-30)), None
            acc, _ = lax.scan(body, jnp.float32(0), None, length=L)
            return acc
        float(f(V))
        return min(_t(lambda: float(f(V))) for _ in range(2))

    def cfg_mr():
        t_mr, L = _periter(mr_chain, L0=64)
        out = {"mapreduce_1e8_s_per_iter": t_mr,
               "mapreduce_1e8_gbps": 4 * 1e8 / t_mr / 1e9}
        float(dat.dmean(V)); float(dat.dstd(V))
        out["mean_std_1e8_eager_s"] = _t(
            lambda: (float(dat.dmean(V)), float(dat.dstd(V))))
        return out

    _guarded(details, "mapreduce", cfg_mr)

    # ---- config 4: stencil halo exchange on 8192^2 -----------------------
    rows = (M // ndev) * ndev
    S = dat.drand((rows, M), procs=range(ndev), dist=(ndev, 1))

    def st(iters, use_pallas=None, temporal=None):
        r = stencil.stencil5(S, iters=iters, use_pallas=use_pallas,
                             temporal=temporal)
        v = float(dat.dsum(r))                       # one compiled scan
        r.close()
        return v

    def st_len_at(use_pallas, temporal=None):
        def st_len(L):
            st(L, use_pallas, temporal)              # compile
            return min(_t(lambda: st(L, use_pallas, temporal))
                       for _ in range(2))
        return st_len

    # single-step streaming kernel (the BASELINE config semantics: one
    # halo exchange per step), the jnp formulation for comparison, and the
    # temporal-blocked kernel (k=8 steps per launch, ghost-zone scheme)
    def cfg_stencil():
        t_st, L = _periter(st_len_at(None, temporal=1), L0=16)
        return {"stencil_8192_step_s_per_iter": t_st,
                "stencil_8192_gcells_per_s": rows * M / t_st / 1e9}

    def cfg_stencil_jnp():
        t_stj, L = _periter(st_len_at(False), L0=16)
        return {"stencil_8192_jnp_gcells_per_s": rows * M / t_stj / 1e9}

    def cfg_stencil_temporal():
        t_stt, L = _periter(st_len_at(None), L0=32)  # auto temporal depth
        return {"stencil_8192_temporal_s_per_iter": t_stt,
                "stencil_8192_temporal_gcells_per_s": rows * M / t_stt / 1e9}

    _guarded(details, "stencil", cfg_stencil)
    _guarded(details, "stencil_jnp", cfg_stencil_jnp)
    _guarded(details, "stencil_temporal", cfg_stencil_temporal)

    # free the bandwidth-config buffers before the 16k arrays go up
    for arr in (X, Y, Z, V, S):
        arr.close()

    # ---- config 3: 16384^2 GEMM on an explicit block layout --------------
    # BASELINE.json configs[3]; reference semantics = the tile-grid
    # _matmatmul! (/root/reference/src/linalg.jl:189-311), here one jitted
    # matmul over block-sharded operands (XLA SUMMA over ICI).  A true 2x2
    # grid needs >=4 devices; on fewer the grid degrades and the key label
    # says which grid actually ran.  bf16-pass first (banked); the riskier
    # f32-HIGHEST pass runs in the guarded tail below.
    K16 = 16384
    g3 = (2, 2) if ndev >= 4 else (1, 1)
    tag = f"gemm_16k_{g3[0]}x{g3[1]}"
    A3 = dat.drand((K16, K16), dtype=jnp.float32,
                   procs=range(g3[0] * g3[1]), dist=g3)
    B3 = dat.drand((K16, K16), dtype=jnp.float32,
                   procs=range(g3[0] * g3[1]), dist=g3)
    s16 = jnp.float32(1.0 / K16)

    def gemm16_chain_at(precision):
        def gemm16_chain(L):
            @dat.djit
            def f(a, b):
                def body(c, _):
                    return jnp.matmul(c, b, precision=precision) * s16, None
                c, _ = lax.scan(body, a, None, length=L)
                return jnp.sum(c)
            float(f(A3, B3))
            return min(_t(lambda: float(f(A3, B3))) for _ in range(2))
        return gemm16_chain

    def cfg_gemm16():
        t16, L = _periter(gemm16_chain_at(jax.lax.Precision.DEFAULT), L0=2)
        g = 2 * K16**3 / t16 / 1e9
        out = {f"{tag}_bf16pass_s_per_iter": t16,
               f"{tag}_bf16pass_gflops": g}
        _bank_tflops(out, f"{tag}_bf16pass", g / 1e3, peak)
        return out

    _guarded(details, tag, cfg_gemm16, timeout_s=600)

    # ---- extra: fused (Pallas) vs einsum ring-attention hop --------------
    # One chip = a 1-rank ring, so this isolates the per-hop compute the
    # ring pipelines against ppermute: the fused path must be >= the
    # einsum composition (VERDICT round-2 item 7).
    def cfg_ring():
        from distributedarrays_tpu import layout as L
        from distributedarrays_tpu.models.ring_attention import (
            ring_attention_kernel, ring_flash_attention_kernel)
        from jax.sharding import PartitionSpec as RP
        SR, HR, DR = 8192, 8, 64
        mesh1 = L.mesh_for([0], (1,))
        ax = mesh1.axis_names[0]
        qr = jax.random.normal(jax.random.key(2), (SR, HR, DR), jnp.bfloat16)

        def ring_len(kernel, **kw):
            shm = jax.shard_map(
                lambda a, b, c: kernel(a, b, c, ax, causal=True, **kw),
                mesh=mesh1, in_specs=(RP(ax),) * 3, out_specs=RP(ax),
                check_vma=False)

            def run(Ln):
                @jax.jit
                def f(qq):
                    def body(c, _):
                        return shm(c, qq, qq), None
                    c, _ = lax.scan(body, qq, None, length=Ln)
                    return jnp.sum(c.astype(jnp.float32))
                float(f(qr))
                return min(_t(lambda: float(f(qr))) for _ in range(2))
            return run

        # sweep the fused hop's blocks and bank the winner under
        # "ring_flash" (consulted by ring_flash_attention_kernel when
        # blocks are unspecified — the sp-transformer's hot path)
        from distributedarrays_tpu.utils import autotune
        cands = [(512, 512), (1024, 512), (1024, 1024), (2048, 1024),
                 (1024, 1024, 2), (1024, 1024, 4), (512, 512, 2)]
        key = autotune.device_key_for(SR, HR, DR, jnp.bfloat16(0).dtype, True)

        def hop_timer(cfg):
            run = ring_len(ring_flash_attention_kernel,
                           block_q=cfg[0], block_k=cfg[1],
                           head_fold=cfg[2] if len(cfg) > 2 else 1)
            # fixed chain length: one compile per arm (remote compiles
            # dominate sweep wall time through the tunnel)
            return run(384) / 384

        best, sweep = autotune.sweep("ring_flash", key, cands, hop_timer, persist=True)
        # _tuned_hop_blocks keys on the PER-RANK local block, and a real
        # P-rank ring sees SR/P — extrapolate the swept winner to the
        # common ring sizes (the hop programs clip blocks to the local
        # extent, so an oversized tuned block degrades gracefully);
        # labeled extrapolated so nobody mistakes them for swept shapes
        extrap = []
        for rp in (2, 4, 8, 16, 32):
            if SR % rp == 0 and SR // rp >= 512:
                autotune.record("ring_flash",
                                autotune.device_key_for(
                                    SR // rp, HR, DR,
                                    jnp.bfloat16(0).dtype, True),
                                list(best))
                extrap.append(SR // rp)
        autotune.save_default()
        t_fused = sweep[best]
        t_einsum, _ = _periter(ring_len(ring_attention_kernel), L0=4)
        return {"ring_hop_fused_8k_bf16_s": t_fused,
                "ring_hop_tuned_block": list(best),
                "ring_hop_tuned_extrapolated_to_local_blocks": extrap,
                "ring_hop_sweep": {
                    "x".join(str(v) for v in cfg): t
                    for cfg, t in sweep.items()},
                "ring_hop_einsum_8k_bf16_s": t_einsum,
                "ring_hop_fused_speedup": t_einsum / t_fused}

    _guarded(details, "ring_hop", cfg_ring)

    # ---- extra: ring-attention TRAINING step (fused FA2 ring backward) ---
    # the round-3 deliverable: grads through the Pallas ring path
    def cfg_ring_train():
        from distributedarrays_tpu import layout as L
        from distributedarrays_tpu.models.ring_attention import (
            ring_flash_attention_kernel)
        from jax.sharding import PartitionSpec as RP
        SR, HR, DR = 8192, 8, 64
        mesh1 = L.mesh_for([0], (1,))
        ax = mesh1.axis_names[0]
        qr = jax.random.normal(jax.random.key(6), (SR, HR, DR), jnp.bfloat16)
        shm = jax.shard_map(
            lambda a, b, c: ring_flash_attention_kernel(
                a, b, c, ax, causal=True, block_q=1024, block_k=1024),
            mesh=mesh1, in_specs=(RP(ax),) * 3, out_specs=RP(ax),
            check_vma=False)
        g = jax.grad(lambda x: jnp.sum(shm(x, x, x).astype(jnp.float32)))

        def run(Ln):
            @jax.jit
            def f(qq):
                def body(x, _):
                    return (x + 1e-6 * g(x).astype(x.dtype)), None
                x, _ = lax.scan(body, qq, None, length=Ln)
                return jnp.sum(x.astype(jnp.float32))
            float(f(qr))
            return min(_t(lambda: float(f(qr))) for _ in range(2))

        t_rt, _ = _periter(run, L0=2)
        # fwd 2 matmuls + bwd 5 -> 3.5x fwd flops, causal half
        flops = 3.5 * (2 * 2 * SR * SR * DR * HR / 2)
        out = {"ring_train_8k_bf16_s_per_iter": t_rt}
        _bank_tflops(out, "ring_train_8k_bf16", flops / t_rt / 1e12, peak)
        return out

    _guarded(details, "ring_train", cfg_ring_train, timeout_s=600)

    # ---- extra: hand-written Pallas GEMM kernel (compiled) ---------------
    def cfg_pallas_gemm():
        from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
        ap = jax.random.normal(jax.random.key(3), (4096, 4096), jnp.bfloat16)
        bp = jax.random.normal(jax.random.key(4), (4096, 4096), jnp.bfloat16)
        spg = jnp.bfloat16(1.0 / 4096)

        def pg_len(L):
            def f():
                def body(c, _):
                    return (pallas_matmul(c, bp) * spg).astype(jnp.bfloat16), None
                c, _ = lax.scan(body, ap, None, length=L)
                return jnp.sum(c.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t_pg, L = _periter(pg_len, L0=16)
        out = {"pallas_gemm_4096_bf16_s_per_iter": t_pg,
               "pallas_gemm_4096_marginal_crosscheck_s":
                   _marginal(pg_len, L0=4, min_delta=0.05)}
        _bank_tflops(out, "pallas_gemm_4096_bf16",
                     2 * 4096**3 / t_pg / 1e12, peak)
        return out

    _guarded(details, "pallas_gemm", cfg_pallas_gemm)

    # ---- extra: Pallas GEMM block autotune sweep -------------------------
    def cfg_pallas_gemm_tune():
        from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
        from distributedarrays_tpu.utils import autotune
        NP = 4096
        ap = jax.random.normal(jax.random.key(3), (NP, NP), jnp.bfloat16)
        bp = jax.random.normal(jax.random.key(4), (NP, NP), jnp.bfloat16)
        spg = jnp.bfloat16(1.0 / NP)

        def timer(cfg):
            L = 512                      # fixed: one compile per arm
            # (~0.9ms/iter at the 152-TFLOPS class -> ~0.5 s/call; the
            # winner is re-timed with full amortization by cfg_pallas_gemm)

            def f():
                def body(c, _):
                    return (pallas_matmul(c, bp, block=cfg) * spg
                            ).astype(jnp.bfloat16), None
                c, _ = lax.scan(body, ap, None, length=L)
                return jnp.sum(c.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2)) / L

        cands = [(1024, 1024, 512), (1024, 1024, 1024), (2048, 1024, 512),
                 (1024, 2048, 512), (512, 1024, 1024), (2048, 2048, 256),
                 # wider K streams (fewer acc flushes) and full-row tiles;
                 # VMEM-overflow arms are skipped by the sweep's try/except
                 (512, 512, 2048), (1024, 512, 2048), (2048, 2048, 512),
                 (4096, 1024, 256), (1024, 4096, 256)]
        key = autotune.device_key_for(NP, NP, NP, ap.dtype, bp.dtype)
        best, results = autotune.sweep("pallas_matmul", key, cands, timer, persist=True)
        autotune.save_default()
        out = {
            "pallas_gemm_tuned_block": list(best),
            "pallas_gemm_sweep": {
                "x".join(map(str, c)): 2 * NP**3 / t / 1e12
                for c, t in results.items()},
        }
        _bank_tflops(out, "pallas_gemm_tuned",
                     2 * NP**3 / results[best] / 1e12, peak)
        return out

    _guarded(details, "pallas_gemm_tune", cfg_pallas_gemm_tune,
             timeout_s=600)

    # ---- extra: int8 quantized Pallas GEMM (beyond-bf16-peak path) -------
    # e-class MXUs run int8 at 2x the bf16 rate; the dynamic-quantization
    # GEMM (quantize -> int8 matmul -> fused dequant) can therefore beat
    # the chip's bf16 peak.  TOPS banked against the int8 peak table.
    def cfg_int8_gemm():
        from distributedarrays_tpu.ops.pallas_gemm import quantized_matmul
        peak8 = _chip_peak_tflops(devs[0].device_kind, _PEAKS_INT8)
        NP = 4096
        ap = jax.random.normal(jax.random.key(3), (NP, NP), jnp.float32)
        bp = jax.random.normal(jax.random.key(4), (NP, NP), jnp.float32)
        s8 = jnp.float32(1.0 / NP)

        def q8_len(L):
            def f():
                def body(c, _):
                    # full dynamic path each iter: quantize + int8 MXU +
                    # fused dequant (the honest end-to-end op cost)
                    return quantized_matmul(c, bp) * s8, None
                c, _ = lax.scan(body, ap, None, length=L)
                return jnp.sum(c)
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t8, L = _periter(q8_len, L0=16)
        out = {"int8_gemm_4096_s_per_iter": t8,
               "int8_gemm_peak_tops": peak8}
        _bank_tflops(out, "int8_gemm_4096", 2 * NP**3 / t8 / 1e12, peak8,
                     unit="tops")
        # vs the chip's BF16 peak — >1.0 here is the beyond-parity headline
        if peak:
            out["int8_gemm_vs_bf16_peak"] = round(
                2 * NP**3 / t8 / 1e12 / peak, 4)
        return out

    _guarded(details, "int8_gemm", cfg_int8_gemm, timeout_s=600)

    # ---- extra: flash-attention TRAINING step (fwd+bwd, FA2 custom-vjp) --
    def cfg_flash_train():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        ST, HT, DT = 8192, 8, 64
        qt = jax.random.normal(jax.random.key(5), (ST, HT, DT), jnp.bfloat16)

        def grad_len(L):
            def one(x):
                return jnp.sum(flash_attention(x, x, x, causal=True,
                                               block_q=1024, block_k=1024)
                               .astype(jnp.float32))
            g = jax.grad(one)

            def f():
                def body(x, _):
                    return (x + 1e-6 * g(x).astype(x.dtype)), None
                x, _ = lax.scan(body, qt, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t_tr, L = _periter(grad_len, L0=4)
        # fwd 2 matmuls + bwd 5 -> 3.5x the fwd matmul flops, causal half
        flops = 3.5 * (2 * 2 * ST * ST * DT * HT / 2)
        out = {"flash_train_8k_bf16_s_per_iter": t_tr}
        _bank_tflops(out, "flash_train_8k_bf16", flops / t_tr / 1e12, peak)
        return out

    _guarded(details, "flash_train", cfg_flash_train)

    # ---- extra: full transformer train step (flagship model) -------------
    def cfg_transformer_train():
        from distributedarrays_tpu.models import transformer as T
        cfg = T.Config(vocab=8192, dim=1024, heads=16, layers=8,
                       ffn_mult=4, max_seq=2048, dtype=jnp.bfloat16)
        params = T.init_params(jax.random.key(0), cfg)
        Bt, St = 4, 2048
        toks = jax.random.randint(jax.random.key(1), (Bt, St), 0, cfg.vocab)
        lr = jnp.float32(1e-4)

        def steps_len(L):
            @jax.jit
            def f(p):
                def body(p, _):
                    loss, g = jax.value_and_grad(T.loss_fn)(p, toks, cfg)
                    p = jax.tree_util.tree_map(
                        lambda w, gg: (w.astype(jnp.float32)
                                       - lr * gg.astype(jnp.float32))
                        .astype(w.dtype), p, g)
                    return p, loss
                p, losses = lax.scan(body, p, None, length=L)
                return losses[-1]
            float(f(params))
            return min(_t(lambda: float(f(params))) for _ in range(2))

        t_step, L = _periter(steps_len, L0=4)
        nparams = sum(int(np.prod(x.shape))
                      for x in jax.tree_util.tree_leaves(params))
        toks_per_step = Bt * (St - 1)
        out = {
            "transformer_train_step_s": t_step,
            "transformer_train_tokens_per_s": toks_per_step / t_step,
            "transformer_train_params": nparams,
        }
        _bank_tflops(out, "transformer_train_est",
                     6 * nparams * toks_per_step / t_step / 1e12, peak)
        return out

    _guarded(details, "transformer_train", cfg_transformer_train,
             timeout_s=600)

    # ---- extra: sp-transformer train step + KV-cache decode --------------
    # The composed flagship perf story (VERDICT round-4 item 7): the
    # explicit-SPMD sequence-parallel model (ring flash attention +
    # tp_ffn) timed as train tokens/sec with model-FLOPs MFU, plus the
    # KV-cache decode step.  On one chip the ring is 1-rank (hop-free)
    # — still the full composed program; multi-chip scaling is covered
    # by the dryrun/CPU-mesh legs until a multi-chip window exists.
    def _sp_train_entry(SH, prefix):
        from distributedarrays_tpu.models import sp_transformer as SPT
        from distributedarrays_tpu.parallel import collectives as C_
        p_ = len(jax.devices())
        mesh = C_.spmd_mesh(p_)
        SV, SE, SL = 8192, 1024, 8
        SS = int(os.environ.get("DAT_BENCH_SP_SEQ", 8192))
        cfg = SPT.SPConfig(vocab=SV, dim=SE, heads=SH, layers=SL,
                           ffn_mult=4, max_seq=SS, dtype=jnp.bfloat16)
        params = SPT.init_params(jax.random.key(0), cfg)
        Bt = 1
        toks = jax.random.randint(jax.random.key(1), (Bt, SS), 0, SV,
                                  dtype=jnp.int32)
        lr = jnp.float32(1e-4)
        # resolve the tuned hop blocks OUTSIDE the chain jit (the
        # sp_transformer contract) so a tune banked earlier in this run
        # is what gets timed
        rcfg = SPT._resolve_cfg(cfg, mesh, "p", toks.shape)
        grad_fn = SPT._grad_program(mesh, rcfg, "p")

        def steps_len(L):
            @jax.jit
            def f(prm):
                def body(prm, _):
                    loss, g = grad_fn(prm, toks)
                    prm = jax.tree_util.tree_map(
                        lambda w, gg: (w.astype(jnp.float32)
                                       - lr * gg.astype(jnp.float32))
                        .astype(w.dtype), prm, g)
                    return prm, loss
                prm, losses = lax.scan(body, prm, None, length=L)
                return losses[-1]
            float(f(params))
            return min(_t(lambda: float(f(params))) for _ in range(2))

        t_step, L = _periter(steps_len, L0=2)
        nparams = sum(int(np.prod(x.shape))
                      for x in jax.tree_util.tree_leaves(params))
        Dh = SE // SH
        # model FLOPs: 6*params per token (fwd+bwd matmuls) + causal
        # flash attention (fwd QK^T+PV pair, bwd 2.5x -> 3.5x, /2 causal)
        flops = (6 * nparams * Bt * SS
                 + 3.5 * SL * (2 * 2 * SS * SS * Dh * SH) / 2 * Bt)
        out = {
            f"{prefix}_step_s": t_step,
            f"{prefix}_seq": SS,
            f"{prefix}_heads": SH,
            f"{prefix}_head_dim": Dh,
            f"{prefix}_tokens_per_s": Bt * SS / t_step,
            f"{prefix}_params": nparams,
            f"{prefix}_hop_blocks": [rcfg.block_q, rcfg.block_k,
                                     rcfg.head_fold],
        }
        _bank_tflops(out, f"{prefix}_model", flops / t_step / 1e12, peak)
        return out

    def cfg_sp_train():
        return _sp_train_entry(16, "sp_train")

    def cfg_sp_train_d128():
        # same parameter count (QKV/O shapes are head-count-invariant),
        # head_dim 128: attention tiles span the full 128-lane MXU width
        # instead of half of it — the d=64 flash ceiling is the measured
        # bottleneck of the 16-head entry (flash d=64 0.31 vs d=128 0.60
        # MFU on this chip)
        return _sp_train_entry(8, "sp_train_d128")

    _guarded(details, "sp_train", cfg_sp_train, timeout_s=900)
    _guarded(details, "sp_train_d128", cfg_sp_train_d128, timeout_s=900)

    def cfg_decode():
        from distributedarrays_tpu.models import transformer as T
        # DAT_BENCH_DECODE_STEPS: harness-validation override (the full
        # 2k-step scan is minutes-slow on host CPU, seconds on a chip)
        total = max(int(os.environ.get("DAT_BENCH_DECODE_STEPS", 2032)), 32)
        # cache length is a SEPARATE knob: the default path must keep the
        # 2048 KV cache it has always had (a cache resize changes the
        # per-step attention cost and breaks comparability across runs)
        cache = max(int(os.environ.get("DAT_BENCH_DECODE_CACHE", 2048)),
                    total)
        cfg = T.Config(vocab=8192, dim=1024, heads=16, layers=8,
                       ffn_mult=4, max_seq=cache, dtype=jnp.bfloat16)
        params = T.init_params(jax.random.key(2), cfg)
        Bd, S0, NEW = 8, 16, total - 16
        prompt = jax.random.randint(jax.random.key(3), (Bd, S0), 0,
                                    cfg.vocab, dtype=jnp.int32)

        def run():
            outt = T.generate(params, prompt, NEW, cfg)
            return float(jnp.sum(outt[:, -1]))   # scalar fetch = sync

        run()                                    # compile
        t_dec = min(_t(run) for _ in range(2))
        steps = S0 + NEW - 1                     # scan length (prefill+gen)
        return {"decode_kvcache_total_s": t_dec,
                "decode_kvcache_tokens_per_s": Bd * steps / t_dec,
                "decode_kvcache_batch": Bd,
                "decode_kvcache_steps": steps}

    _guarded(details, "decode_kvcache", cfg_decode, timeout_s=600)

    # ---- extra: reshard planner (chunked collective redistribution) ------
    # Three legs of the layout-aware reshard planner: the even→even
    # transpose repartition (all_to_all lowering on >1 chip, noop/1-chip
    # degenerate otherwise — strategy banked alongside the time so the
    # numbers are attributable), the uneven-layout in-place fill (now
    # emitted straight into blocked physical form: zero redistribution)
    # next to a full re-pad rebind, and the incremental slice-mutate
    # (owner-block writes only; the _comm_bytes_est column shows the
    # sub-full-array traffic).
    def cfg_reshard_even():
        from distributedarrays_tpu import layout as L_
        from distributedarrays_tpu.parallel import reshard as R_
        p = len(devs)
        NR = 8192
        src = L_.sharding_for(list(range(p)), (p, 1), (NR, NR))
        dst = L_.sharding_for(list(range(p)), (1, p), (NR, NR))
        x = jax.device_put(jax.random.normal(jax.random.key(11), (NR, NR),
                                             jnp.float32), src)
        plan = R_.plan_reshard(x, dst)

        def once():
            y = R_.reshard(x, dst)
            return float(y[0, 0])          # scalar fetch = sync

        once()                             # compile
        # first timed rep banks immediately: a tunnel wedge during the
        # remaining reps still leaves a real reshard time (+ bandwidth)
        t_rs = _t(once)
        part = {"reshard_even_s": t_rs}
        if plan.moved_bytes:
            part["reshard_even_gbps"] = plan.moved_bytes / t_rs / 1e9
        bank_partial("reshard_even", **part)
        t_rs = min([t_rs] + [_t(once) for _ in range(2)])
        from distributedarrays_tpu.ops import pallas_collectives as P_
        rdma = P_.rdma_mode()
        out = {
            "reshard_even_n": NR,
            "reshard_even_nranks": p,
            "reshard_even_strategy": plan.strategy,
            "reshard_even_nchunks": plan.nchunks,
            "reshard_even_plan_moved_mb": plan.moved_bytes / 2**20,
            "reshard_even_dispatch": rdma or "xla",
            "reshard_even_s": t_rs,
        }
        if rdma and plan.strategy == "all_to_all":
            lshape = tuple(s // p if d == plan.src_dim else s
                           for d, s in enumerate(plan.shape))
            nc, csrc = P_.a2a_chunks_for(lshape, "float32", p,
                                         plan.src_dim)
            out["reshard_even_rdma_chunks"] = nc
            out["reshard_even_rdma_chunks_source"] = csrc
        if plan.moved_bytes:
            out["reshard_even_gbps"] = plan.moved_bytes / t_rs / 1e9
        # repeated same-pair planning must hit the plan cache
        st0 = R_.plan_stats()
        for _ in range(4):
            R_.plan_reshard(x, dst)
        out["reshard_plan_cache_hits_delta"] = \
            R_.plan_stats()["hits"] - st0["hits"]
        return out

    _guarded(details, "reshard_even", cfg_reshard_even)

    def cfg_reshard_uneven():
        p = len(devs)
        NU = 4096 * 2048 + 37              # indivisible -> blocked-padded
        d = dat.distribute(np.zeros(NU, np.float32),
                           procs=list(range(p)), dist=[p])
        try:
            def fill_once():
                d.fill_(3.0)
                return float(d.garray_padded[0])

            from distributedarrays_tpu import telemetry as _tm2
            fill_once()                    # compile
            rb0 = _tm2.comm_bytes("reshard")
            t_fill = min(_t(fill_once) for _ in range(3))
            fill_reshard_bytes = _tm2.comm_bytes("reshard") - rb0

            host = np.ones(NU, np.float32)

            def repad_once():
                dat.copyto_(d, host)       # logical -> blocked re-pad
                return float(d.garray_padded[0])

            repad_once()
            t_repad = min(_t(repad_once) for _ in range(2))
            return {
                "reshard_uneven_n": NU,
                "reshard_uneven_nranks": p,
                "reshard_uneven_fill_s": t_fill,
                "reshard_uneven_fill_reshard_bytes": fill_reshard_bytes,
                "reshard_uneven_repad_s": t_repad,
            }
        finally:
            d.close()

    _guarded(details, "reshard_uneven", cfg_reshard_uneven)

    def cfg_reshard_mutate():
        p = len(devs)
        NU = 4096 * 2048 + 37
        d = dat.distribute(np.zeros(NU, np.float32),
                           procs=list(range(p)), dist=[p])
        try:
            # one small interior window: the incremental path writes only
            # the owner blocks' physical regions
            lo = NU // (2 * max(p, 1))
            w = 4096
            v = np.full(w, 5.0, np.float32)

            def mutate_once():
                d[lo:lo + w] = v
                return float(d.garray_padded[0])

            from distributedarrays_tpu import telemetry as _tm2
            mutate_once()                  # compile
            rb0 = _tm2.comm_bytes("reshard")
            t_mut = min(_t(mutate_once) for _ in range(3))
            # reshard-kind bytes for the timed mutations alone: the
            # owner-block traffic (vs NU*4 per mutation pre-planner)
            rb = _tm2.comm_bytes("reshard") - rb0
            return {
                "reshard_mutate_n": NU,
                "reshard_mutate_window": w,
                "reshard_mutate_s": t_mut,
                "reshard_mutate_touched_frac": w / NU,
                "reshard_mutate_reshard_bytes_per_full": rb / 3 / (NU * 4),
            }
        finally:
            d.close()

    _guarded(details, "reshard_mutate", cfg_reshard_mutate)

    # ---- extra: reshard, multi-axis chain lowering -----------------------
    # The general per-axis collective chain (PR 19) against the
    # device_put baseline it demotes: an 8192² two-axis repartition
    # ((p,1) -> (p/2,2), a single axis-wise all-to-all moving half the
    # array) and a mesh-axis transpose (gather+a2a+slice).  Banks the
    # chain strategy and the plan's intra/cross-domain byte split so the
    # row attributes the win to the hierarchical tier.
    def cfg_reshard_multiaxis():
        from distributedarrays_tpu import layout as L_
        from distributedarrays_tpu.parallel import reshard as R_
        from jax.sharding import NamedSharding as _NS, \
            PartitionSpec as _P2
        p = len(devs)
        if p < 4 or p % 2:
            return {"reshard_multiaxis_skipped": f"needs p>=4 even, p={p}"}
        NR = 8192
        src = L_.sharding_for(list(range(p)), (p, 1), (NR, NR))
        dst = L_.sharding_for(list(range(p)), (p // 2, 2), (NR, NR))
        x = jax.device_put(jax.random.normal(jax.random.key(13), (NR, NR),
                                             jnp.float32), src)
        plan = R_.plan_reshard(x, dst)

        def once():
            y = R_.reshard(x, dst)
            return float(y[0, 0])          # scalar fetch = sync

        def baseline():
            y = jax.device_put(x, dst)     # the baseline under measurement
            return float(y[0, 0])

        once(); baseline()                 # compile/warm both arms
        # bank each arm as soon as its first rep lands: the multi-hop
        # row keeps its headline time even if the transpose arm below
        # never gets to run
        t_rs = _t(once)
        part = {"reshard_multiaxis_s": t_rs}
        if plan.moved_bytes:
            part["reshard_multiaxis_gbps"] = plan.moved_bytes / t_rs / 1e9
        bank_partial("reshard_multiaxis", **part)
        t_rs = min([t_rs] + [_t(once) for _ in range(2)])
        t_dp = _t(baseline)
        bank_partial("reshard_multiaxis",
                     reshard_multiaxis_device_put_s=t_dp)
        t_dp = min([t_dp] + [_t(baseline) for _ in range(2)])
        out = {
            "reshard_multiaxis_n": NR,
            "reshard_multiaxis_nranks": p,
            "reshard_multiaxis_strategy": plan.strategy,
            "reshard_multiaxis_steps": ",".join(s[0] for s in plan.steps),
            "reshard_multiaxis_plan_moved_mb": plan.moved_bytes / 2**20,
            "reshard_multiaxis_intra_mb": plan.intra_bytes / 2**20,
            "reshard_multiaxis_cross_mb": plan.cross_bytes / 2**20,
            "reshard_multiaxis_s": t_rs,
            "reshard_multiaxis_device_put_s": t_dp,
        }
        if plan.moved_bytes:
            out["reshard_multiaxis_gbps"] = plan.moved_bytes / t_rs / 1e9
            out["reshard_multiaxis_device_put_gbps"] = \
                plan.moved_bytes / t_dp / 1e9
        # the mesh-axis transpose on the destination's (p/2, 2) mesh
        mesh = L_.mesh_for(list(range(p)), (p // 2, 2))
        tsrc = _NS(mesh, _P2("d0", "d1"))
        tdst = _NS(mesh, _P2("d1", "d0"))
        xt = jax.device_put(jax.random.normal(jax.random.key(17),
                                              (NR, NR), jnp.float32), tsrc)
        tplan = R_.plan_reshard(xt, tdst)

        def tonce():
            y = R_.reshard(xt, tdst)
            return float(y[0, 0])

        tonce()
        t_tr = min(_t(tonce) for _ in range(3))
        out["reshard_multiaxis_transpose_strategy"] = tplan.strategy
        out["reshard_multiaxis_transpose_s"] = t_tr
        out["reshard_multiaxis_transpose_moved_mb"] = \
            tplan.moved_bytes / 2**20
        return out

    _guarded(details, "reshard_multiaxis", cfg_reshard_multiaxis,
             timeout_s=600)

    # ---- extra: ring GEMM, RDMA vs XLA-ppermute paths --------------------
    # The fused Pallas RDMA collective GEMM (pallas_collectives) against
    # the lax ring it replaces: same program shape, same operands, the
    # only delta is who schedules the wire time.  Banks both wall times,
    # the RDMA path's TFLOPS, and the dispatch that actually ran (on a
    # non-TPU platform the "rdma" arm resolves to the lax fallback and
    # the row says so — a no-delta row is evidence, not a failure).
    def cfg_ring_gemm():
        from distributedarrays_tpu.ops import pallas_collectives as _pc
        from distributedarrays_tpu.ops.collective_matmul import \
            allgather_matmul_rhs
        from distributedarrays_tpu.parallel.collectives import (run_spmd,
                                                                spmd_mesh)
        from jax.sharding import PartitionSpec as _P
        from distributedarrays_tpu import telemetry as _tmb
        p = len(devs)
        NG = 2048
        mesh = spmd_mesh(p)
        a = jnp.asarray(np.random.default_rng(21)
                        .standard_normal((NG, NG)), jnp.bfloat16)
        b = jnp.asarray(np.random.default_rng(22)
                        .standard_normal((NG, NG)), jnp.bfloat16)
        specs = (_P("p", None), _P("p", None))
        fns = {}
        for name, arm in (("xla", False), ("rdma", True)):
            fns[name] = run_spmd(
                functools.partial(lambda aa, bb, _arm: allgather_matmul_rhs(
                    aa, bb, "p", rdma=_arm), _arm=arm),
                mesh, specs, _P("p", None))

        def once(fn):
            return float(jnp.sum(fn(a, b)[0, :8]))   # scalar fetch = sync

        # the dispatch that ACTUALLY ran: rdma_mode() alone ignores the
        # kernel-level gates (VMEM budget, dtype) — the trace-time
        # dispatch counter is ground truth, sampled across the compiles
        disp0 = _tmb.counter_value("pallas_collectives.dispatch",
                                   op="ring_allgather_matmul_rhs",
                                   path="rdma")
        for fn in fns.values():
            once(fn)                                 # compile both arms
        armed = _tmb.counter_value("pallas_collectives.dispatch",
                                   op="ring_allgather_matmul_rhs",
                                   path="rdma") > disp0
        rdma = _pc.rdma_mode()
        flops = 2.0 * NG * NG * NG
        # the XLA arm banks the sentinel metric the moment its first rep
        # lands — a wedge in the RDMA arm can no longer void the row
        t_xla = _t(lambda: once(fns["xla"]))
        bank_partial("ring_gemm", ring_gemm_xla_s=t_xla,
                     ring_gemm_xla_tflops=flops / t_xla / 1e12)
        t_xla = min([t_xla]
                    + [_t(lambda: once(fns["xla"])) for _ in range(2)])
        t_rdma = min(_t(lambda: once(fns["rdma"])) for _ in range(3))
        return {
            "ring_gemm_n": NG,
            "ring_gemm_nranks": p,
            "ring_gemm_dispatch": (rdma or "xla") if armed else
                                  ("xla (gated)" if rdma else "xla"),
            "ring_gemm_xla_s": t_xla,
            "ring_gemm_rdma_s": t_rdma,
            "ring_gemm_xla_tflops": flops / t_xla / 1e12,
            "ring_gemm_rdma_tflops": flops / t_rdma / 1e12,
        }

    _guarded(details, "ring_gemm", cfg_ring_gemm)

    # ---- extra: serving layer under synthetic open-loop load -------------
    # The multi-tenant async executor end to end: a resident sharded
    # weight matrix, a batched scoring endpoint, a sequential pass for the
    # unloaded latency baseline, then an open-loop generator offering ~2x
    # the sustainable rate for a fixed window.  Banks sustained admitted
    # req/s, p50/p99 of ADMITTED requests, and the shed fraction — the
    # ROADMAP item 2 acceptance trio.
    def cfg_serve_load():
        from distributedarrays_tpu import serve as _serve
        p = len(devs)
        NSV = 1024
        w = dat.distribute(np.asarray(np.random.default_rng(5)
                                      .standard_normal((NSV, NSV)),
                                      np.float32))
        srv = None
        try:
            g = w.garray

            def ep(xs):
                y = jnp.matmul(jnp.stack([jnp.asarray(x) for x in xs]), g)
                return list(np.asarray(y[:, 0]))

            cfg = _serve.ServeConfig(max_batch=8, flush_s=0.002,
                                     max_queue=32, tenant_rate=1e9,
                                     tenant_burst=1e9)
            srv = _serve.Server(cfg)
            srv.register("score", ep)
            x = np.zeros((NSV,), np.float32)
            srv.submit("score", x).result(timeout=60)      # compile
            lats = []
            for _ in range(30):                            # unloaded pass
                t0 = time.monotonic()
                srv.submit("score", x).result(timeout=60)
                lats.append(time.monotonic() - t0)
            lats.sort()
            # same index formula as the loaded percentile below, so the
            # banked loaded-vs-unloaded comparison is one statistic
            p99_unloaded = lats[int(0.99 * (len(lats) - 1))]
            batch_s = max(srv.stats()["latency_p50_s"], 1e-4)
            sustainable = cfg.max_batch / batch_s
            interval = 1.0 / (2.0 * sustainable)
            window_s = 3.0
            # submit→resolve latency per admitted request, captured by a
            # done-callback at resolution time (collecting .result() after
            # the window would only time inter-completion gaps)
            import threading as _threading
            futs, shed, loaded = [], 0, []
            _lat_lock = _threading.Lock()

            def _mark(t0):
                def cb(_f):
                    dt = time.monotonic() - t0
                    with _lat_lock:
                        loaded.append(dt)
                return cb

            t_start = time.monotonic()
            while time.monotonic() - t_start < window_s:
                try:
                    t0 = time.monotonic()
                    f = srv.submit("score", x)
                    f.add_done_callback(_mark(t0))
                    futs.append(f)
                except _serve.Overloaded:
                    shed += 1
                time.sleep(interval)
            for f in futs:
                f.result(timeout=60)
            duration = time.monotonic() - t_start
            loaded.sort()
            offered = len(futs) + shed
            return {
                "serve_load_nranks": p,
                "serve_load_offered_rps": offered / duration,
                "serve_load_admitted_rps": len(futs) / duration,
                "serve_load_shed_frac": shed / max(offered, 1),
                "serve_load_p50_s": loaded[len(loaded) // 2] if loaded
                else 0.0,
                "serve_load_p99_s": loaded[int(0.99 * (len(loaded) - 1))]
                if loaded else 0.0,
                "serve_load_p99_unloaded_s": p99_unloaded,
            }
        finally:
            if srv is not None:
                srv.close()
            w.close()

    _guarded(details, "serve_load", cfg_serve_load, timeout_s=300)

    # ---- extra: the decode service under open-loop token load ------------
    # The paged-KV continuous-batching engine end to end: a warm pass
    # measures the single-stream token rate, then an open-loop generator
    # offers ~2x the engine's batch-sustainable sequence rate for a fixed
    # window.  Banks offered vs sustained tokens/s (and the at-SLO rate),
    # TTFT p50/p99, per-token latency p50/p99, the shed fraction, and the
    # KV ledger's HBM peak — the decode-service acceptance row.
    def cfg_serve_decode():
        import threading as _threading

        from distributedarrays_tpu import serve as _serve
        from distributedarrays_tpu.telemetry import memory as _tmem
        model = _serve.TinyLM()
        max_new = 16
        eng = _serve.DecodeEngine(
            model,
            _serve.PagedKVCache(_serve.KVCacheConfig(
                heads=model.heads, head_dim=model.head_dim,
                page_tokens=16, block_pages=4, max_pages=512)),
            _serve.DecodeConfig(max_new_tokens=max_new, poll_s=0.001,
                                max_sequences=64, token_budget=512,
                                # prompts below the floor prefill via the
                                # exact reference path: the row measures
                                # scheduler+cache throughput, not ring
                                # collectives (ring_hop/ring_train own
                                # those); CPU-harness rendezvous stalls
                                # would otherwise drown the token rate
                                min_ring_tokens=64,
                                default_deadline_s=120.0))
        rng = np.random.default_rng(7)

        def _prompt():
            return rng.integers(0, model.vocab, size=32).tolist()

        rec_lock = _threading.Lock()
        ttfts, gaps = [], []
        # KV peak is ledger-relative: earlier configs' still-live buffers
        # must not masquerade as cache bytes
        base_bytes = _tmem.live_bytes()
        kv_peak = [0]
        stop = _threading.Event()

        def _monitor():
            while not stop.is_set():
                kv_peak[0] = max(kv_peak[0],
                                 _tmem.live_bytes() - base_bytes)
                time.sleep(0.002)

        def _tracked_submit():
            t0 = time.monotonic()
            last = [t0]

            def _cb(kind, _v):
                if kind != "token":
                    return
                now = time.monotonic()
                with rec_lock:
                    (ttfts if last[0] == t0 else gaps).append(
                        now - last[0])
                    last[0] = now

            s = eng.submit(_prompt())
            s.add_listener(_cb)
            return s

        try:
            # warm single-stream pass: the unloaded token rate and the
            # SLO.  The first sequence pays every compile/first-touch
            # cost; the SECOND is the steady-state rate
            eng.submit(_prompt()).result(timeout=120)
            t0 = time.monotonic()
            eng.submit(_prompt()).result(timeout=120)
            seq_s = max(time.monotonic() - t0, 1e-4)
            tok_s_single = (max_new) / seq_s
            slo_s = 20.0 * (seq_s / max_new)   # per-token latency bound
            # the unloaded rate and the SLO it implies are complete
            # measurements the moment the warm pass returns — bank them
            # before the 3s open-loop window (the part that wedges)
            bank_partial("serve_decode",
                         serve_decode_single_stream_tokens_per_s=
                         tok_s_single,
                         serve_decode_slo_s=slo_s)
            sustainable_seqs = eng.config.max_decode_batch / seq_s
            interval = 1.0 / (2.0 * sustainable_seqs)
            window_s = 3.0
            mon = _threading.Thread(target=_monitor, daemon=True)
            mon.start()
            streams, shed = [], 0
            t_start = time.monotonic()
            while time.monotonic() - t_start < window_s:
                try:
                    streams.append(_tracked_submit())
                except _serve.Overloaded:
                    shed += 1
                time.sleep(interval)
            for s in streams:
                s.result(timeout=120)
            duration = time.monotonic() - t_start
            stop.set()
            mon.join(2.0)
            with rec_lock:
                tt = sorted(ttfts)
                gp = sorted(gaps)
            delivered = sum(len(s.tokens) for s in streams)
            within = len([g for g in gp if g <= slo_s]) + \
                len([t for t in tt if t <= slo_s])
            offered = len(streams) + shed
            st = eng.stats()["cache"]
            return {
                "serve_decode_nranks": len(devs),
                "serve_decode_single_stream_tokens_per_s": tok_s_single,
                "serve_decode_offered_tokens_per_s":
                    offered * (max_new + 1) / duration,
                "serve_decode_tokens_per_s": delivered / duration,
                "serve_decode_slo_s": slo_s,
                "serve_decode_at_slo_tokens_per_s": within / duration,
                "serve_decode_ttft_p50_s": tt[len(tt) // 2] if tt else 0.0,
                "serve_decode_ttft_p99_s":
                    tt[int(0.99 * (len(tt) - 1))] if tt else 0.0,
                "serve_decode_token_p50_s": gp[len(gp) // 2] if gp
                else 0.0,
                "serve_decode_token_p99_s":
                    gp[int(0.99 * (len(gp) - 1))] if gp else 0.0,
                "serve_decode_shed_frac": shed / max(offered, 1),
                "serve_decode_kv_hbm_peak_bytes": kv_peak[0],
                "serve_decode_evictions": st["evictions"],
            }
        finally:
            stop.set()
            eng.close()

    _guarded(details, "serve_decode", cfg_serve_decode, timeout_s=300)

    # ---- train_step: the fault-tolerant data-parallel trainer ------------
    def cfg_train_step():
        from distributedarrays_tpu import telemetry as _tmt
        from distributedarrays_tpu.ops import pallas_collectives as P_
        from distributedarrays_tpu.telemetry import perf as _perf
        from distributedarrays_tpu.train import Trainer, adam, mlp_task
        p = len(devs)
        task = mlp_task(sizes=(256, 512, 256), batch_size=32 * p)
        tr = Trainer(task, adam(lr=1e-3), seed=0)
        try:
            tr.step_once()                 # compile + first state layout
            t_step = _t(tr.step_once)
            # the step time (and its TFLOPS) banks after ONE timed step:
            # the overlap analysis below needs four more and telemetry
            # event parsing — none of which should hold the row hostage
            bank_partial("train_step", train_step_s=t_step,
                         train_step_tflops=task.step_flops(
                             task.batch_size) / t_step / 1e12)
            t_step = min([t_step] + [_t(tr.step_once) for _ in range(4)])
            # grad-sync overlap from the measured train.step timelines
            # of exactly the timed steps: the event buffer is a bounded
            # deque, so select by step label (the last 5 = the timed
            # ones) rather than by index offset into a rotating ring
            steps_ov = _perf.train_step_overlap(_tmt.events())[-5:]
            ov = (sum(o["overlap_frac"] for o in steps_ov)
                  / len(steps_ov)) if steps_ov else 0.0
            # dispatch provenance from the step spans themselves (the
            # trainer labels the path its kernels ACTUALLY took, gates
            # included), falling back to the armed mode
            dispatch = (steps_ov[-1].get("dispatch") if steps_ov
                        else None) or P_.rdma_mode() or "xla"
            return {
                "train_step_nranks": p,
                "train_step_batch": task.batch_size,
                "train_step_dispatch": dispatch,
                "train_step_overlap_frac": round(ov, 4),
                "train_step_tflops":
                    task.step_flops(task.batch_size) / t_step / 1e12,
                "train_step_s": t_step,
            }
        finally:
            tr.close()

    _guarded(details, "train_step", cfg_train_step)

    # ---- extra: distributed sort over 1e7 elements -----------------------
    def cfg_sort():
        from distributedarrays_tpu.ops.sort import dsort
        VS = dat.drand((10_000_000,))

        def sort_once():
            s = dsort(VS)
            # force completion with a scalar fetch (tunnel caveat above)
            v = float(s.garray[-1])
            s.close()
            return v

        sort_once()                       # compile
        t_sort = min(_t(sort_once) for _ in range(2))
        VS.close()
        return {"sort_1e7_s": t_sort,
                "sort_1e7_melem_per_s": 1e7 / t_sort / 1e6}

    _guarded(details, "sort", cfg_sort)

    # ---- solver: CG time-to-tolerance on the 2-D Poisson system ----------
    # the second hardware-meaningful number beyond GEMM: an HBM-bound
    # iteration (5-point stencil matvec + BLAS-1 sweeps), reported as
    # achieved GB/s against the spmv cost stamp.  Iteration count and
    # final residual publish as partials the moment the first solve
    # converges, so a timeout during the timing reps still banks them.
    def cfg_cg_poisson():
        from distributedarrays_tpu import solvers
        from distributedarrays_tpu.telemetry import perf as _perf
        NP = 1024
        op = solvers.StencilOperator((NP, NP))
        procs, pdist = op.vector_layout()
        rhs = np.random.default_rng(7).standard_normal(
            (NP, NP)).astype(np.float32)
        b = dat.distribute(rhs, procs=procs, dist=list(pdist))
        try:
            def solve_once():
                # iterations grow ~2.5*NP on this system (~2600 at 1024);
                # the cap is headroom, not the expected count
                r = solvers.cg(op, b, tol=1e-6, maxiter=6000)
                r.x.close()
                return r

            res = solve_once()           # compile + correctness probe
            bank_partial("cg_poisson",
                         cg_poisson_iters=res.iterations,
                         cg_poisson_residual=res.residual)
            if not res.converged:
                raise RuntimeError(
                    f"cg outcome {res.outcome} after {res.iterations} iters")
            t_solve = _t(solve_once)
            # the first timed solve is already a real time-to-tolerance:
            # bank it before the confirmation rep
            bank_partial("cg_poisson", cg_poisson_time_s=t_solve)
            t_solve = min(t_solve, _t(solve_once))
            # per-iteration HBM traffic: the stamped spmv volume plus ~10
            # whole-vector passes of BLAS-1 (r/p/x/Ap reads and writes)
            per_iter = (_perf.spmv_cost(5 * NP * NP, NP * NP, 4,
                                        index_itemsize=0)["bytes_hbm"]
                        + 10 * NP * NP * 4)
            return {
                "cg_poisson_iters": res.iterations,
                "cg_poisson_residual": res.residual,
                "cg_poisson_time_s": t_solve,
                "cg_poisson_gbps":
                    res.iterations * per_iter / t_solve / 1e9,
            }
        finally:
            b.close()

    _guarded(details, "cg_poisson", cfg_cg_poisson, timeout_s=600)

    # ---- last (riskiest): true-f32 GEMM (precision=HIGHEST) --------------
    # attempted after everything is banked, under a thread timeout: a
    # wedged remote compile must not cost the run its other numbers.
    def highest():
        t, L = _periter(gemm_chain_at(jax.lax.Precision.HIGHEST), L0=16)
        return {"gemm_4096_f32_highest_s_per_iter": t,
                "gemm_4096_f32_highest_gflops": 2 * N**3 / t / 1e9}

    _guarded(details, "gemm_f32_highest", highest, timeout_s=600)

    # the 16k f32-HIGHEST pass (the BASELINE config-3 metric), same guard
    def highest16():
        t, L = _periter(gemm16_chain_at(jax.lax.Precision.HIGHEST), L0=1)
        return {f"{tag}_f32_highest_s_per_iter": t,
                f"{tag}_f32_highest_gflops": 2 * K16**3 / t / 1e9}

    _guarded(details, f"{tag}_f32_highest", highest16, timeout_s=600)

    # a DAT_BENCH_ONLY entry that matched nothing is a typo that would
    # otherwise silently cost a short hardware window its target number —
    # surface it in the details AND on stderr
    unmatched = sorted(_ONLY - _SEEN_LABELS)
    if unmatched:
        details["bench_only_unmatched_labels"] = unmatched
        details["bench_only_known_labels"] = sorted(_SEEN_LABELS)
        print(f"bench: DAT_BENCH_ONLY entries matched no config: "
              f"{unmatched}; known labels: {sorted(_SEEN_LABELS)}",
              file=sys.stderr)
        _save(details)

    # cleanup may hang on a wedged tunnel: bounded (headline already out)
    _run_with_timeout(dat.d_closeall, 60)
    if any(k.endswith("_orphan_running") for k in details):
        # a wedged config left a daemon thread stuck inside the XLA
        # runtime; normal interpreter teardown can SIGABRT through it.
        # Everything is printed and persisted — exit hard and clean.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)


if __name__ == "__main__":
    _parse_args()
    main()
