#!/usr/bin/env python
"""Benchmark harness: BASELINE.json configs on the available TPU devices.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric (from BASELINE.json configs[0]): GFLOPS on a 4096x4096
DArray GEMM through the framework (`djit` + `@`) at the TPU-native DEFAULT
precision (mixed bf16-pass matmul — labeled as such in the metric name);
the true-float32 (precision=HIGHEST) number is measured separately at the
end of the run and recorded in BENCH_DETAILS.json.  ``vs_baseline`` is the
speedup over the same GEMM in numpy (float32, multi-threaded host BLAS) —
a strictly-stronger stand-in for the reference's "4 CPU workers" config
(the reference's Julia Distributed GEMM over 4 local TCP workers cannot
beat the host's full BLAS).

Methodology: this environment reaches the TPU through a remote tunnel with
~tens-of-ms per-dispatch latency, so per-call wall timing measures the
tunnel, not the chip.  Each config is therefore timed as the *marginal*
cost inside one compiled program: run L iterations and 1 iteration of the
op chained in a ``lax.scan`` (data-dependent so XLA cannot hoist or elide),
force completion with a scalar fetch, and divide the difference.  Eager
per-call latencies are recorded alongside in BENCH_DETAILS.json.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent))


def _t(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _marginal(run_for_length, L0=10, min_delta=0.05, max_L=1000):
    """Marginal per-iteration cost: time(L iters) - time(1 iter), growing L
    until the delta clears the tunnel-latency noise floor."""
    t1 = run_for_length(1)
    L = L0
    while True:
        tL = run_for_length(L + 1)
        delta = tL - t1
        if delta >= min_delta or L >= max_L:
            return max(delta, 1e-9) / L
        L *= 4


def _run_with_timeout(fn, timeout_s: float, grace_s: float = 0.0):
    """Run ``fn`` on a daemon thread with a hard timeout (a wedged remote
    tunnel hangs forever instead of erroring).  Returns ``(finished,
    value_or_exception, thread)``; on timeout the thread is abandoned
    after an optional ``grace_s`` extra join (callers can use the thread
    handle to detect an orphan still dispatching device work)."""
    import threading

    box = {}

    def runner():
        try:
            box["value"] = fn()
        except Exception as e:
            box["error"] = e

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive() and grace_s:
        t.join(grace_s)
    if t.is_alive():
        return False, None, t
    if "error" in box:
        return True, box["error"], t
    return True, box.get("value"), t


def _device_watchdog(timeout_s: float = 480.0):
    """Probe the accelerator with a tiny op under a hard timeout."""
    def probe():
        import jax.numpy as jnp
        return float(jnp.sum(jnp.ones((8, 8))))

    finished, v, _ = _run_with_timeout(probe, timeout_s)
    if not finished:
        return {"ok": False, "error": f"device probe timed out after "
                                      f"{timeout_s:.0f}s (wedged tunnel?)"}
    if isinstance(v, Exception):
        return {"ok": False,
                "error": f"device probe raised: {type(v).__name__}: {v}"}
    if v != 64.0:
        return {"ok": False, "error": f"device probe returned {v}, expected 64.0"}
    return {"ok": True}


def _save(details):
    Path(__file__).with_name("BENCH_DETAILS.json").write_text(
        json.dumps(details, indent=2))


_START = time.monotonic()
_GLOBAL_BUDGET_S = 3000.0   # leave headroom under the driver's own timeout


def _guarded(details, label, fn, timeout_s=420.0):
    """Run one optional bench config on a daemon thread with a timeout and
    a global deadline: a wedged tunnel (observed: remote_compile dying
    mid-read, then every subsequent dispatch hanging) must cost at most
    one config's budget, and never the already-banked numbers or the
    headline.  ``fn`` returns a dict merged into ``details``."""
    def _remaining():
        return _GLOBAL_BUDGET_S - (time.monotonic() - _START)

    if _remaining() < 60:
        details[f"{label}_error"] = "skipped (global bench deadline)"
        _save(details)
        return
    effective = min(timeout_s, _remaining())
    finished, res, thread = _run_with_timeout(fn, effective)
    if finished and isinstance(res, Exception) and \
            "remote_compile" in str(res) and _remaining() > 75:
        # transient tunnel-service flake (observed: response body closed
        # mid-read); one retry after a settle pause, against the budget
        # actually left now
        time.sleep(15)
        effective = min(timeout_s, _remaining())
        finished, res, thread = _run_with_timeout(fn, effective)
    if not finished:
        details[f"{label}_error"] = f"timed out after {effective:.0f}s"
        # the abandoned thread may still be dispatching device work; give
        # it a bounded drain so it cannot pollute the NEXT config's
        # timings, and flag it if it outlives the grace
        thread.join(60)
        if thread.is_alive():
            details[f"{label}_orphan_running"] = True
    elif isinstance(res, Exception):
        details[f"{label}_error"] = f"{type(res).__name__}: {res}"
    elif res:
        details.update(res)
    _save(details)


def main():
    probe = _device_watchdog()
    if not probe["ok"]:
        print(json.dumps({
            "metric": "gemm_4096_gflops_mixed_precision_bf16pass",
            "value": 0.0, "unit": "GFLOPS", "vs_baseline": 0.0,
            "error": f"accelerator unreachable ({probe['error']})",
        }))
        return

    import jax
    import jax.numpy as jnp
    from jax import lax
    import distributedarrays_tpu as dat
    from distributedarrays_tpu.models import stencil

    # keep the previous run's banked numbers recoverable: this run's first
    # _save overwrites the file, and a wedge mid-run must not cost the
    # last full run's evidence (copy, not rename — the tracked file must
    # never transiently disappear from the working tree)
    cur = Path(__file__).with_name("BENCH_DETAILS.json")
    if cur.exists():
        import shutil
        shutil.copyfile(cur, cur.with_name("BENCH_DETAILS_prev.json"))

    ndev = len(jax.devices())
    details = {"devices": [str(d) for d in jax.devices()]}

    # ---- config 0: 4096^2 f32 GEMM ---------------------------------------
    N = 4096
    dat.seed(7)
    A = dat.drand((N, N), dtype=jnp.float32)
    B = dat.drand((N, N), dtype=jnp.float32)
    scale = jnp.float32(1.0 / N)

    def gemm_chain_at(precision):
        def gemm_chain(L):
            @dat.djit
            def f(a, b):
                def body(c, _):
                    return jnp.matmul(c, b, precision=precision) * scale, None
                c, _ = lax.scan(body, a, None, length=L)
                return jnp.sum(c)
            float(f(A, B))                  # compile + warmup
            return min(_t(lambda: float(f(A, B))) for _ in range(3))
        return gemm_chain

    # headline: DEFAULT precision (the TPU-native mixed bf16-pass matmul,
    # labeled as such).  A previous session observed the remote-compile
    # service wedge while compiling a HIGHEST-precision scan, so the true-
    # f32 measurement is attempted LAST (see end of main) under a timeout,
    # after every other number is already banked.
    t_gemm = _marginal(gemm_chain_at(jax.lax.Precision.DEFAULT), L0=50)
    gflops = 2 * N**3 / t_gemm / 1e9
    details["gemm_4096_mixed_bf16pass_marginal_s"] = t_gemm
    details["gemm_4096_mixed_bf16pass_gflops"] = gflops
    (A @ B).garray                         # compile the eager path
    details["gemm_4096_mixed_bf16pass_eager_latency_s"] = _t(
        lambda: (A @ B).garray)
    _save(details)

    # sum(A.^2) half of config 0
    float(dat.dmapreduce(jnp.square, "sum", A))
    t_sum = _t(lambda: float(dat.dmapreduce(jnp.square, "sum", A)))
    details["sum_sq_4096_eager_s"] = t_sum

    # ---- CPU baseline: same GEMM in numpy (host BLAS) --------------------
    an = np.asarray(A, dtype=np.float32)
    bn = np.asarray(B, dtype=np.float32)
    t_np = min(_t(lambda: an @ bn) for _ in range(2))
    cpu_gflops = 2 * N**3 / t_np / 1e9
    details["cpu_numpy_gflops"] = cpu_gflops
    _save(details)

    # headline out NOW: everything after this point is banked detail, and a
    # tunnel wedge in a later config must not cost the round its one JSON
    # line (round-1 lesson; this run prints exactly this one line)
    print(json.dumps({
        "metric": "gemm_4096_gflops_mixed_precision_bf16pass",
        "value": round(gflops, 2),
        "unit": "GFLOPS",
        "vs_baseline": round(gflops / cpu_gflops, 2),
    }), flush=True)

    # ---- config 1: broadcast chain sin.(A) .+ B .* C on 8192^2 ----------
    M = 8192
    X = dat.drand((M, M)); Y = dat.drand((M, M)); Z = dat.drand((M, M))

    def chain_chain(L):
        @dat.djit
        def f(a, b, c):
            def body(acc, _):
                return jnp.sin(acc) + b * c, None
            acc, _ = lax.scan(body, a, None, length=L)
            return jnp.sum(acc)
        float(f(X, Y, Z))
        return min(_t(lambda: float(f(X, Y, Z))) for _ in range(3))

    def cfg_chain():
        t_chain = _marginal(chain_chain, L0=20)
        return {"broadcast_chain_8192_marginal_s": t_chain,
                "broadcast_chain_8192_gbps": 4 * M * M * 4 / t_chain / 1e9}

    _guarded(details, "broadcast_chain", cfg_chain)

    # ---- config 2: mapreduce(abs2,+) and mean/std over 1e8 --------------
    V = dat.drand((100_000_000,))

    def mr_chain(L):
        @dat.djit
        def f(v):
            def body(acc, _):
                # acc feeds back so the reduction re-reads v every iteration
                return acc * 1e-30 + jnp.sum(jnp.square(v + acc * 1e-30)), None
            acc, _ = lax.scan(body, jnp.float32(0), None, length=L)
            return acc
        float(f(V))
        return min(_t(lambda: float(f(V))) for _ in range(3))

    def cfg_mr():
        t_mr = _marginal(mr_chain, L0=40)
        out = {"mapreduce_1e8_marginal_s": t_mr,
               "mapreduce_1e8_gbps": 4 * 1e8 / t_mr / 1e9}
        float(dat.dmean(V)); float(dat.dstd(V))
        out["mean_std_1e8_eager_s"] = _t(
            lambda: (float(dat.dmean(V)), float(dat.dstd(V))))
        return out

    _guarded(details, "mapreduce", cfg_mr)

    # ---- config 4: stencil halo exchange on 8192^2 -----------------------
    rows = (M // ndev) * ndev
    S = dat.drand((rows, M), procs=range(ndev), dist=(ndev, 1))

    def st(iters, use_pallas=None, temporal=None):
        r = stencil.stencil5(S, iters=iters, use_pallas=use_pallas,
                             temporal=temporal)
        v = float(dat.dsum(r))                       # one compiled scan
        r.close()
        return v

    def st_len_at(use_pallas, temporal=None):
        def st_len(L):
            st(L, use_pallas, temporal)              # compile
            return min(_t(lambda: st(L, use_pallas, temporal))
                       for _ in range(2))
        return st_len

    # single-step streaming kernel (the BASELINE config semantics: one
    # halo exchange per step), the jnp formulation for comparison, and the
    # temporal-blocked kernel (k=8 steps per launch, ghost-zone scheme)
    def cfg_stencil():
        t_st = _marginal(st_len_at(None, temporal=1), L0=10)
        return {"stencil_8192_step_marginal_s": t_st,
                "stencil_8192_gcells_per_s": rows * M / t_st / 1e9}

    def cfg_stencil_jnp():
        t_stj = _marginal(st_len_at(False), L0=10)
        return {"stencil_8192_jnp_gcells_per_s": rows * M / t_stj / 1e9}

    def cfg_stencil_temporal():
        t_stt = _marginal(st_len_at(None), L0=16)    # auto temporal depth
        return {"stencil_8192_temporal_marginal_s": t_stt,
                "stencil_8192_temporal_gcells_per_s": rows * M / t_stt / 1e9}

    _guarded(details, "stencil", cfg_stencil)
    _guarded(details, "stencil_jnp", cfg_stencil_jnp)
    _guarded(details, "stencil_temporal", cfg_stencil_temporal)

    # free the bandwidth-config buffers before the 16k arrays go up
    for arr in (X, Y, Z, V, S):
        arr.close()

    # ---- config 3: 16384^2 GEMM on an explicit block layout --------------
    # BASELINE.json configs[3]; reference semantics = the tile-grid
    # _matmatmul! (/root/reference/src/linalg.jl:189-311), here one jitted
    # matmul over block-sharded operands (XLA SUMMA over ICI).  A true 2x2
    # grid needs >=4 devices; on fewer the grid degrades and the key label
    # says which grid actually ran.  bf16-pass first (banked); the riskier
    # f32-HIGHEST pass runs in the guarded tail below.
    K16 = 16384
    g3 = (2, 2) if ndev >= 4 else (1, 1)
    tag = f"gemm_16k_{g3[0]}x{g3[1]}"
    A3 = dat.drand((K16, K16), dtype=jnp.float32,
                   procs=range(g3[0] * g3[1]), dist=g3)
    B3 = dat.drand((K16, K16), dtype=jnp.float32,
                   procs=range(g3[0] * g3[1]), dist=g3)
    s16 = jnp.float32(1.0 / K16)

    def gemm16_chain_at(precision):
        def gemm16_chain(L):
            @dat.djit
            def f(a, b):
                def body(c, _):
                    return jnp.matmul(c, b, precision=precision) * s16, None
                c, _ = lax.scan(body, a, None, length=L)
                return jnp.sum(c)
            float(f(A3, B3))
            return min(_t(lambda: float(f(A3, B3))) for _ in range(2))
        return gemm16_chain

    def cfg_gemm16():
        t16 = _marginal(gemm16_chain_at(jax.lax.Precision.DEFAULT),
                        L0=5, min_delta=0.1)
        return {f"{tag}_bf16pass_marginal_s": t16,
                f"{tag}_bf16pass_gflops": 2 * K16**3 / t16 / 1e9}

    _guarded(details, tag, cfg_gemm16, timeout_s=600)

    # ---- extra: Pallas flash attention at long context -------------------
    def cfg_flash():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        SQ, HQ, DQ = 8192, 8, 64
        q = jax.random.normal(jax.random.key(1), (SQ, HQ, DQ), jnp.bfloat16)

        def fa_len(L):
            def f():
                def body(x, _):
                    # 1024^2 blocks: the measured-best tiling on v5e
                    # (52 TFLOPS causal vs 2.7 at 128^2)
                    return flash_attention(x, q, q, causal=True,
                                           block_q=1024, block_k=1024), None
                x, _ = lax.scan(body, q, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t_fa = _marginal(fa_len, L0=4, min_delta=0.05)
        # causal flash: ~2*S^2*D*H flops (QK^T + PV), halved by causality
        flops = 2 * 2 * SQ * SQ * DQ * HQ / 2
        return {"flash_attn_8k_bf16_marginal_s": t_fa,
                "flash_attn_8k_bf16_tflops": flops / t_fa / 1e12}

    _guarded(details, "flash_attn", cfg_flash)

    # ---- extra: flash-attention block autotune sweep ---------------------
    # sweeps (block_q, block_k) at the bench shape, records the winner in
    # the autotune registry (consulted by flash_attention when blocks are
    # unspecified), and reports the tuned TFLOPS
    def cfg_flash_tune():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        from distributedarrays_tpu.utils import autotune
        SQ, HQ, DQ = 8192, 8, 64
        q = jax.random.normal(jax.random.key(1), (SQ, HQ, DQ), jnp.bfloat16)

        def timer(cfg):
            bq, bk = cfg

            def fa_len(L):
                def f():
                    def body(x, _):
                        return flash_attention(x, q, q, causal=True,
                                               block_q=bq, block_k=bk), None
                    x, _ = lax.scan(body, q, None, length=L)
                    return jnp.sum(x.astype(jnp.float32))
                jf = jax.jit(f)
                float(jf())
                return min(_t(lambda: float(jf())) for _ in range(2))
            return _marginal(fa_len, L0=4, min_delta=0.05)

        cands = [(bq, bk) for bq in (512, 1024, 2048)
                 for bk in (512, 1024, 2048)]
        key = autotune.key_for(SQ, HQ, DQ, jnp.bfloat16(0).dtype, True)
        best, results = autotune.sweep("flash_attention", key, cands, timer)
        cache = autotune.save_default()   # future processes pick this up
        flops = 2 * 2 * SQ * SQ * DQ * HQ / 2
        return {
            "flash_attn_tuned_block": list(best),
            "flash_attn_tuned_tflops": flops / results[best] / 1e12,
            "flash_attn_sweep": {f"{bq}x{bk}": flops / t / 1e12
                                 for (bq, bk), t in results.items()},
            "autotune_cache_path": cache,
        }

    _guarded(details, "flash_attn_tune", cfg_flash_tune, timeout_s=600)

    # ---- extra: fused (Pallas) vs einsum ring-attention hop --------------
    # One chip = a 1-rank ring, so this isolates the per-hop compute the
    # ring pipelines against ppermute: the fused path must be >= the
    # einsum composition (VERDICT round-2 item 7).
    def cfg_ring():
        from distributedarrays_tpu import layout as L
        from distributedarrays_tpu.models.ring_attention import (
            ring_attention_kernel, ring_flash_attention_kernel)
        from jax.sharding import PartitionSpec as RP
        SR, HR, DR = 8192, 8, 64
        mesh1 = L.mesh_for([0], (1,))
        ax = mesh1.axis_names[0]
        qr = jax.random.normal(jax.random.key(2), (SR, HR, DR), jnp.bfloat16)

        def ring_len(kernel, **kw):
            shm = jax.shard_map(
                lambda a, b, c: kernel(a, b, c, ax, causal=True, **kw),
                mesh=mesh1, in_specs=(RP(ax),) * 3, out_specs=RP(ax),
                check_vma=False)

            def run(Ln):
                @jax.jit
                def f(qq):
                    def body(c, _):
                        return shm(c, qq, qq), None
                    c, _ = lax.scan(body, qq, None, length=Ln)
                    return jnp.sum(c.astype(jnp.float32))
                float(f(qr))
                return min(_t(lambda: float(f(qr))) for _ in range(2))
            return run

        t_fused = _marginal(ring_len(ring_flash_attention_kernel,
                                     block_q=1024, block_k=1024),
                            L0=4, min_delta=0.05)
        t_einsum = _marginal(ring_len(ring_attention_kernel),
                             L0=4, min_delta=0.05)
        return {"ring_hop_fused_8k_bf16_s": t_fused,
                "ring_hop_einsum_8k_bf16_s": t_einsum,
                "ring_hop_fused_speedup": t_einsum / t_fused}

    _guarded(details, "ring_hop", cfg_ring)

    # ---- extra: hand-written Pallas GEMM kernel (compiled) ---------------
    def cfg_pallas_gemm():
        from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
        ap = jax.random.normal(jax.random.key(3), (4096, 4096), jnp.bfloat16)
        bp = jax.random.normal(jax.random.key(4), (4096, 4096), jnp.bfloat16)
        spg = jnp.bfloat16(1.0 / 4096)

        def pg_len(L):
            def f():
                def body(c, _):
                    return (pallas_matmul(c, bp) * spg).astype(jnp.bfloat16), None
                c, _ = lax.scan(body, ap, None, length=L)
                return jnp.sum(c.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t_pg = _marginal(pg_len, L0=4, min_delta=0.05)
        return {"pallas_gemm_4096_bf16_marginal_s": t_pg,
                "pallas_gemm_4096_bf16_tflops": 2 * 4096**3 / t_pg / 1e12}

    _guarded(details, "pallas_gemm", cfg_pallas_gemm)

    # ---- extra: Pallas GEMM block autotune sweep -------------------------
    def cfg_pallas_gemm_tune():
        from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
        from distributedarrays_tpu.utils import autotune
        NP = 4096
        ap = jax.random.normal(jax.random.key(3), (NP, NP), jnp.bfloat16)
        bp = jax.random.normal(jax.random.key(4), (NP, NP), jnp.bfloat16)
        spg = jnp.bfloat16(1.0 / NP)

        def timer(cfg):
            def pg_len(L):
                def f():
                    def body(c, _):
                        return (pallas_matmul(c, bp, block=cfg) * spg
                                ).astype(jnp.bfloat16), None
                    c, _ = lax.scan(body, ap, None, length=L)
                    return jnp.sum(c.astype(jnp.float32))
                jf = jax.jit(f)
                float(jf())
                return min(_t(lambda: float(jf())) for _ in range(2))
            return _marginal(pg_len, L0=4, min_delta=0.05)

        cands = [(1024, 1024, 512), (1024, 1024, 1024), (2048, 1024, 512),
                 (1024, 2048, 512), (512, 1024, 1024), (2048, 2048, 256)]
        key = autotune.key_for(NP, NP, NP, ap.dtype, bp.dtype)
        best, results = autotune.sweep("pallas_matmul", key, cands, timer)
        autotune.save_default()
        return {
            "pallas_gemm_tuned_block": list(best),
            "pallas_gemm_tuned_tflops": 2 * NP**3 / results[best] / 1e12,
            "pallas_gemm_sweep": {
                "x".join(map(str, c)): 2 * NP**3 / t / 1e12
                for c, t in results.items()},
        }

    _guarded(details, "pallas_gemm_tune", cfg_pallas_gemm_tune,
             timeout_s=600)

    # ---- extra: flash-attention TRAINING step (fwd+bwd, FA2 custom-vjp) --
    def cfg_flash_train():
        from distributedarrays_tpu.ops.pallas_attention import flash_attention
        ST, HT, DT = 8192, 8, 64
        qt = jax.random.normal(jax.random.key(5), (ST, HT, DT), jnp.bfloat16)

        def grad_len(L):
            def one(x):
                return jnp.sum(flash_attention(x, x, x, causal=True,
                                               block_q=1024, block_k=1024)
                               .astype(jnp.float32))
            g = jax.grad(one)

            def f():
                def body(x, _):
                    return (x + 1e-6 * g(x).astype(x.dtype)), None
                x, _ = lax.scan(body, qt, None, length=L)
                return jnp.sum(x.astype(jnp.float32))
            jf = jax.jit(f)
            float(jf())
            return min(_t(lambda: float(jf())) for _ in range(2))

        t_tr = _marginal(grad_len, L0=2, min_delta=0.05)
        # fwd 2 matmuls + bwd 5 -> 3.5x the fwd matmul flops, causal half
        flops = 3.5 * (2 * 2 * ST * ST * DT * HT / 2)
        return {"flash_train_8k_bf16_marginal_s": t_tr,
                "flash_train_8k_bf16_tflops": flops / t_tr / 1e12}

    _guarded(details, "flash_train", cfg_flash_train)

    # ---- extra: full transformer train step (flagship model) -------------
    def cfg_transformer_train():
        from distributedarrays_tpu.models import transformer as T
        cfg = T.Config(vocab=8192, dim=1024, heads=16, layers=8,
                       ffn_mult=4, max_seq=2048, dtype=jnp.bfloat16)
        params = T.init_params(jax.random.key(0), cfg)
        B, S = 4, 2048
        toks = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab)
        lr = jnp.float32(1e-4)

        def steps_len(L):
            @jax.jit
            def f(p):
                def body(p, _):
                    loss, g = jax.value_and_grad(T.loss_fn)(p, toks, cfg)
                    p = jax.tree_util.tree_map(
                        lambda w, gg: (w.astype(jnp.float32)
                                       - lr * gg.astype(jnp.float32))
                        .astype(w.dtype), p, g)
                    return p, loss
                p, losses = lax.scan(body, p, None, length=L)
                return losses[-1]
            float(f(params))
            return min(_t(lambda: float(f(params))) for _ in range(2))

        t_step = _marginal(steps_len, L0=2, min_delta=0.1)
        nparams = sum(int(np.prod(x.shape))
                      for x in jax.tree_util.tree_leaves(params))
        toks_per_step = B * (S - 1)
        return {
            "transformer_train_step_s": t_step,
            "transformer_train_tokens_per_s": toks_per_step / t_step,
            "transformer_train_params": nparams,
            "transformer_train_tflops_est":
                6 * nparams * toks_per_step / t_step / 1e12,
        }

    _guarded(details, "transformer_train", cfg_transformer_train,
             timeout_s=600)

    # ---- extra: distributed sort over 1e7 elements -----------------------
    def cfg_sort():
        from distributedarrays_tpu.ops.sort import dsort
        VS = dat.drand((10_000_000,))

        def sort_once():
            s = dsort(VS)
            # force completion with a scalar fetch (tunnel caveat above)
            v = float(s.garray[-1])
            s.close()
            return v

        sort_once()                       # compile
        t_sort = min(_t(sort_once) for _ in range(2))
        VS.close()
        return {"sort_1e7_s": t_sort,
                "sort_1e7_melem_per_s": 1e7 / t_sort / 1e6}

    _guarded(details, "sort", cfg_sort)

    # ---- last (riskiest): true-f32 GEMM (precision=HIGHEST) --------------
    # attempted after everything is banked, under a thread timeout: a
    # wedged remote compile must not cost the run its other numbers.  The
    # worker writes into its own dict, merged only if it finished (so a
    # late completion cannot mutate `details` mid-serialization), and the
    # headline is printed BEFORE touching the device again.
    def highest():
        t = _marginal(gemm_chain_at(jax.lax.Precision.HIGHEST), L0=50)
        return {"gemm_4096_f32_highest_marginal_s": t,
                "gemm_4096_f32_highest_gflops": 2 * N**3 / t / 1e9}

    _guarded(details, "gemm_f32_highest", highest, timeout_s=600)

    # the 16k f32-HIGHEST pass (the BASELINE config-3 metric), same guard
    def highest16():
        t = _marginal(gemm16_chain_at(jax.lax.Precision.HIGHEST),
                      L0=3, min_delta=0.2)
        return {f"{tag}_f32_highest_marginal_s": t,
                f"{tag}_f32_highest_gflops": 2 * K16**3 / t / 1e9}

    _guarded(details, f"{tag}_f32_highest", highest16, timeout_s=600)

    # cleanup may hang on a wedged tunnel: bounded (headline already out)
    _run_with_timeout(dat.d_closeall, 60)


if __name__ == "__main__":
    main()
