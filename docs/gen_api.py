"""Generate docs/api.md from the package's public surface.

Run from the repo root:  python docs/gen_api.py
"""

import importlib
import inspect
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

MODULES = [
    "distributedarrays_tpu",
    "distributedarrays_tpu.layout",
    "distributedarrays_tpu.core",
    "distributedarrays_tpu.darray",
    "distributedarrays_tpu.ops.broadcast",
    "distributedarrays_tpu.ops.mapreduce",
    "distributedarrays_tpu.ops.linalg",
    "distributedarrays_tpu.ops.sort",
    "distributedarrays_tpu.ops.sparse",
    "distributedarrays_tpu.ops.fft",
    "distributedarrays_tpu.ops.conv",
    "distributedarrays_tpu.ops.pallas_gemm",
    "distributedarrays_tpu.ops.pallas_attention",
    "distributedarrays_tpu.ops.pallas_stencil",
    "distributedarrays_tpu.ops.pallas_collectives",
    "distributedarrays_tpu.ops.ring_schedules",
    "distributedarrays_tpu.ops.collective_matmul",
    "distributedarrays_tpu.parallel.spmd_mode",
    "distributedarrays_tpu.parallel.collectives",
    "distributedarrays_tpu.parallel.reshard",
    "distributedarrays_tpu.parallel.multihost",
    "distributedarrays_tpu.models.stencil",
    "distributedarrays_tpu.models.ring_attention",
    "distributedarrays_tpu.models.ulysses",
    "distributedarrays_tpu.models.pipeline",
    "distributedarrays_tpu.models.moe",
    "distributedarrays_tpu.models.kmeans",
    "distributedarrays_tpu.models.montecarlo",
    "distributedarrays_tpu.models.mlp",
    "distributedarrays_tpu.models.transformer",
    "distributedarrays_tpu.models.sp_transformer",
    "distributedarrays_tpu.train.trainer",
    "distributedarrays_tpu.train.optim",
    "distributedarrays_tpu.train.tasks",
    "distributedarrays_tpu.telemetry",
    "distributedarrays_tpu.telemetry.tracing",
    "distributedarrays_tpu.telemetry.memory",
    "distributedarrays_tpu.telemetry.flight",
    "distributedarrays_tpu.telemetry.export",
    "distributedarrays_tpu.telemetry.summarize",
    "distributedarrays_tpu.telemetry.perf",
    "distributedarrays_tpu.telemetry.regress",
    "distributedarrays_tpu.telemetry.cluster",
    "distributedarrays_tpu.telemetry.alerts",
    "distributedarrays_tpu.telemetry.advisor",
    "distributedarrays_tpu.telemetry.stream",
    "distributedarrays_tpu.telemetry.agg",
    "distributedarrays_tpu.analysis",
    "distributedarrays_tpu.analysis.divergence",
    "distributedarrays_tpu.analysis.protocol",
    "distributedarrays_tpu.analysis.locks",
    "distributedarrays_tpu.resilience",
    "distributedarrays_tpu.resilience.domains",
    "distributedarrays_tpu.resilience.faults",
    "distributedarrays_tpu.resilience.elastic",
    "distributedarrays_tpu.resilience.recovery",
    "distributedarrays_tpu.serve",
    "distributedarrays_tpu.serve.server",
    "distributedarrays_tpu.serve.admission",
    "distributedarrays_tpu.serve.batching",
    "distributedarrays_tpu.serve.errors",
    "distributedarrays_tpu.serve.kvcache",
    "distributedarrays_tpu.serve.decode",
    "distributedarrays_tpu.serve.aio",
    "distributedarrays_tpu.solvers",
    "distributedarrays_tpu.solvers.operators",
    "distributedarrays_tpu.solvers.krylov",
    "distributedarrays_tpu.solvers.multigrid",
    "distributedarrays_tpu.solvers.service",
    "distributedarrays_tpu.utils.checkpoint",
    "distributedarrays_tpu.utils.autotune",
    "distributedarrays_tpu.utils.profiling",
    "distributedarrays_tpu.utils.debug",
    "distributedarrays_tpu.utils.native",
]


def first_para(doc):
    if not doc:
        return ""
    return inspect.cleandoc(doc).split("\n\n")[0].replace("\n", " ")


def fmt_sig(obj, drop_self=False):
    try:
        sig = inspect.signature(obj)
    except (ValueError, TypeError):
        return "(...)"
    params = list(sig.parameters.values())
    if drop_self and params and params[0].name in ("self", "cls"):
        params = params[1:]
    sig = sig.replace(parameters=params)
    # `from __future__ import annotations` stringizes annotations; unquote
    return str(sig).replace('"', "").replace("'", "")


def describe(mod):
    out = [f"## `{mod.__name__}`\n"]
    if mod.__doc__:
        out.append(first_para(mod.__doc__) + "\n")
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in vars(mod)
                 if not n.startswith("_") and
                 getattr(getattr(mod, n), "__module__", None) == mod.__name__]
    for name in names:
        obj = getattr(mod, name, None)
        if obj is None:
            continue
        if inspect.isclass(obj):
            out.append(f"### class `{name}`\n\n{first_para(obj.__doc__)}\n")
            for mname, m in inspect.getmembers(obj):
                if mname.startswith("_") or not callable(m):
                    continue
                out.append(f"- `{name}.{mname}{fmt_sig(m, drop_self=True)}` — "
                           f"{first_para(m.__doc__)}")
        elif callable(obj):
            out.append(f"- **`{name}{fmt_sig(obj)}`** — "
                       f"{first_para(obj.__doc__)}")
    return "\n".join(out) + "\n"


def main():
    parts = ["# API reference\n\nGenerated by `python docs/gen_api.py`; "
             "one-line summaries — see docstrings for the full contracts "
             "and reference citations.\n"]
    for name in MODULES:
        parts.append(describe(importlib.import_module(name)))
    Path(__file__).with_name("api.md").write_text("\n".join(parts))
    print(f"wrote docs/api.md ({sum(len(p) for p in parts)} chars)")


if __name__ == "__main__":
    main()
