"""Overlapped tensor parallelism + int8 quantized GEMM demo.

Two round-3 performance features on one page:

1. ``ops.collective_matmul`` — the Megatron sequence-parallel FFN
   (``tp_ffn``): ring all-gather GEMM in, GEMM + ring reduce-scatter
   out, each ICI hop pipelined behind the MXU.  Run as ONE shard_map
   program over a tp axis and verified against the dense oracle.
2. ``ops.pallas_gemm.quantized_matmul`` — float in/out, int8 on the
   MXU: dynamic per-row/per-column symmetric quantization, exact int32
   accumulation, dequant fused into the tile flush.  On e-class TPUs
   the int8 MXU rate is 2x bf16, so this path can beat the chip's bf16
   peak (bench.py's ``int8_gemm`` config measures it).
"""

import _setup  # noqa: F401

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributedarrays_tpu.ops.collective_matmul import tp_ffn
from distributedarrays_tpu.ops.pallas_gemm import quantized_matmul
from distributedarrays_tpu.parallel import collectives as C

# ---- 1. sequence-parallel FFN over a 4-rank tp axis ----------------------
p = min(4, len(jax.devices()))
mesh = C.spmd_mesh(p)
S, E, F = 32 * p, 64, 64 * p
rng = np.random.default_rng(0)
x = rng.standard_normal((S, E)).astype(np.float32)
w1 = rng.standard_normal((E, F)).astype(np.float32) * 0.1
w2 = rng.standard_normal((F, E)).astype(np.float32) * 0.1

ffn = C.run_spmd(lambda a, b, c: tp_ffn(a, b, c, "p"), mesh,
                 in_specs=(P("p", None), P(None, "p"), P("p", None)),
                 out_specs=P("p", None))
y = np.asarray(ffn(x, w1, w2))
want = np.asarray(jax.nn.gelu(jnp.asarray(x @ w1))) @ w2
err = np.abs(y - want).max() / np.abs(want).max()
print(f"tp_ffn over {p} ranks: sequence shard {S // p}x{E}, "
      f"intermediate {S}x{F // p} (1/{p} of full), rel err {err:.2e}")
assert err < 1e-4

# and it trains: gradients flow through both ring loops
g1, g2 = jax.jit(jax.grad(lambda b, c: jnp.sum(ffn(x, b, c) ** 2),
                          (0, 1)))(jnp.asarray(w1), jnp.asarray(w2))
print(f"grad norms through the rings: |dW1|={float(jnp.abs(g1).max()):.3f} "
      f"|dW2|={float(jnp.abs(g2).max()):.3f}")

# ---- 2. int8 quantized GEMM ----------------------------------------------
N = 512
a = rng.standard_normal((N, N)).astype(np.float32)
b = rng.standard_normal((N, N)).astype(np.float32)
c8 = np.asarray(quantized_matmul(a, b))
rel = np.abs(c8 - a @ b).max() / np.abs(a @ b).max()
print(f"int8 GEMM {N}x{N}: rel err {rel:.2e} "
      "(quantization noise; int32 accumulation is exact)")
assert rel < 2e-2

# ---- 3. square 2-D-grid GEMM: the Cannon double panel ring ---------------
# The reference's tile-grid mul! shape (both operands block-distributed
# over one (g,g) grid).  Float panels ride two overlapped ppermute rings;
# the int8 variant ships int8 panels + per-panel scales (4x less wire).
if len(jax.devices()) >= 4:
    import distributedarrays_tpu as dat
    from distributedarrays_tpu.ops import linalg as la
    from distributedarrays_tpu.utils import autotune

    M = 64
    A2 = rng.standard_normal((M, M)).astype(np.float32)
    B2 = rng.standard_normal((M, M)).astype(np.float32)
    ga = dat.distribute(A2, procs=range(4), dist=(2, 2))
    gb = dat.distribute(B2, procs=range(4), dist=(2, 2))
    # promotion is by measurement (tune_matmul_impl_summa / bench.py);
    # force the registry here so the demo exercises the owned schedule
    autotune.record("matmul_impl_dist",
                    la._impl_key(M, M, M, "2x2", ga.dtype, gb.dtype),
                    "summa")
    gc = ga @ gb
    err2 = np.abs(np.asarray(gc) - A2 @ B2).max() / np.abs(A2 @ B2).max()
    print(f"Cannon 2x2-grid GEMM {M}x{M}: rel err {err2:.2e}")
    assert err2 < 1e-4
    qc = dat.dmatmul_int8(ga, gb)
    err8 = np.abs(np.asarray(qc) - A2 @ B2).max() / np.abs(A2 @ B2).max()
    print(f"Cannon 2x2-grid int8 GEMM {M}x{M}: rel err {err8:.2e}")
    assert err8 < 3e-2
    autotune.clear()
    dat.d_closeall()
print("OK")
