"""Spectral Poisson solve: -lap(u) = f with periodic boundaries, solved
exactly in Fourier space on a sharded grid.

The FFT-space counterpart of ``examples/cg_poisson.py`` (which iterates a
halo-exchange stencil): here the whole solve is THREE framework calls —
``dfft2`` (distributed FFT via all_to_all transpose), one elementwise
multiply by the inverse eigenvalues (built in place with
``dfromfunction``, each device materializing only its chunk), ``difft2``
back.  No iteration, no halo.
"""

import _setup  # noqa: F401

import numpy as np

import jax

import distributedarrays_tpu as dat

M = N = 64
p = min(8, len(jax.devices()))
procs, dist = range(p), (p, 1)

# a smooth zero-mean source term
rng = np.random.default_rng(0)
f_host = rng.standard_normal((M, N)).astype(np.float32)
f_host -= f_host.mean()
f = dat.distribute(f_host, procs=procs, dist=dist)

# inverse eigenvalues of the periodic 5-point Laplacian, built sharded:
# lam(k,l) = 4 - 2cos(2 pi k/M) - 2cos(2 pi l/N); zero mode pinned to 0
def _inv_eig(i, j):
    # jnp (not np) ops: keeps dfromfunction on its COMPILED path, so each
    # device builds only its own chunk of the eigenvalue table on device
    import jax.numpy as jnp
    lam = (4.0 - 2.0 * jnp.cos(2 * jnp.pi * i / M)
           - 2.0 * jnp.cos(2 * jnp.pi * j / N))
    zero = (i == 0) & (j == 0)
    return jnp.where(zero, 0.0, 1.0 / jnp.where(zero, 1.0, lam))


inv_eig = dat.dfromfunction(_inv_eig, (M, N), procs=procs, dist=dist)

u = dat.difft2(dat.dfft2(f) * inv_eig)
u_host = np.asarray(u).real

# residual of the discrete periodic Laplacian
lap = (np.roll(u_host, 1, 0) + np.roll(u_host, -1, 0)
       + np.roll(u_host, 1, 1) + np.roll(u_host, -1, 1) - 4 * u_host)
res = np.abs(-lap - f_host).max() / np.abs(f_host).max()
print(f"grid {M}x{N} over {p} ranks: residual |lap(u)+f|/|f| = {res:.2e}")
assert res < 1e-4
print("OK")
