"""Quickstart: the minimum end-to-end slice — distribute, compute, reduce,
gather (the reference's core workflow, TPU-native)."""

import _setup  # noqa: F401

import numpy as np
import jax.numpy as jnp

import distributedarrays_tpu as dat

# construct distributed arrays (generated on device, sharded over the mesh)
A = dat.drand((1024, 1024))
B = dat.drand((1024, 1024))
print("A:", A)
print("A sharding:", A.garray.sharding)

# owner-computes elementwise math; whole chains fuse under djit
C = dat.dmap(jnp.sin, A) + B * 2.0
fused = dat.djit(lambda a, b: jnp.sin(a) + b * 2.0)(A, B)
assert C == fused

# reductions: local reduce per device + all-reduce over ICI
print("sum:", float(dat.dsum(C)), " mean:", float(dat.dmean(C)))

# distributed GEMM on the MXU
G = A @ B
print("GEMM result:", G.dims, "fro-norm:", float(dat.dnorm(G)))

# layout inspection and localparts
print("chunk grid:", A.pids.shape, " cuts[0][:3]:", A.cuts[0][:3])
print("rank 0 owns:", A.localindices(0))

# scalar reads are guarded (they gather from HBM)
try:
    C[0, 0]
except RuntimeError as e:
    print("guarded:", str(e)[:60], "...")
with dat.allowscalar(True):
    print("C[0,0] =", float(C[0, 0]))

# gather to host, clean up
host = np.asarray(C)
print("gathered:", host.shape, host.dtype)
dat.d_closeall()
