"""Long-context training demo: the sequence-parallel transformer.

One `shard_map` program per train step — activations stay
sequence-sharded on every rank, attention is the differentiable fused
ring (Pallas flash hops with the online-softmax carry riding ppermute),
the FFN is the overlapped `tp_ffn`, and the next-token shift crosses
rank boundaries with one `pshift`.  Trains the same counting task as
`examples/train_transformer.py`, then repeats it in the zigzag
(load-balanced causal) layout.
"""

import _setup  # noqa: F401

import numpy as np

import jax
import jax.numpy as jnp

from distributedarrays_tpu.models import sp_transformer as SPT
from distributedarrays_tpu.models.ring_attention import zigzag_order
from distributedarrays_tpu.parallel import collectives as C

p = min(4, len(jax.devices()))
mesh = C.spmd_mesh(p)
S = 8 * p
cfg = SPT.SPConfig(vocab=32, dim=64, heads=4, layers=2, max_seq=S,
                   dtype=jnp.float32, block_q=8, block_k=8)

# counting task: next token = (t + 1) % vocab
start = jax.random.randint(jax.random.key(1), (8, 1), 0, cfg.vocab)
tokens = ((start + jnp.arange(S)[None]) % cfg.vocab).astype(jnp.int32)

step = SPT.make_train_step(mesh, cfg)
params = SPT.init_params(jax.random.key(0), cfg)
losses = []
for i in range(40):
    params, loss = step(params, tokens, jnp.float32(0.1))
    losses.append(float(loss))
print(f"sequence-parallel over {p} ranks ({S // p} positions/rank): "
      f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")
assert losses[-1] < 0.5 * losses[0]

# same task, zigzag layout: rank i holds chunk pair (i, 2p-1-i)
zcfg = SPT.SPConfig(vocab=32, dim=64, heads=4, layers=2, max_seq=S,
                    dtype=jnp.float32, block_q=4, block_k=4, zigzag=True)
zz_tokens = jnp.asarray(np.asarray(tokens)[:, np.asarray(zigzag_order(S, p))])
zstep = SPT.make_train_step(mesh, zcfg)
zparams = SPT.init_params(jax.random.key(0), zcfg)
zlosses = []
for i in range(40):
    zparams, zloss = zstep(zparams, zz_tokens, jnp.float32(0.1))
    zlosses.append(float(zloss))
print(f"zigzag (load-balanced causal): loss {zlosses[0]:.3f} -> "
      f"{zlosses[-1]:.3f}")
assert zlosses[-1] < 0.5 * zlosses[0]
print("OK")
