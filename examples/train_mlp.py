"""dp×tp-sharded MLP training with layout-exact checkpointing."""

import _setup  # noqa: F401

import shutil

import jax
import jax.numpy as jnp

from distributedarrays_tpu.models import mlp
from distributedarrays_tpu.utils import load, save

mesh = mlp.make_mesh()
print("mesh:", dict(mesh.shape))

sizes = [128, 256, 256, 64]
params = mlp.shard_params(
    mlp.init_params(jax.random.key(0), sizes, dtype=jnp.bfloat16), mesh)
x = jax.random.normal(jax.random.key(1), (256, sizes[0]), jnp.bfloat16)
y = jax.random.normal(jax.random.key(2), (256, sizes[-1]), jnp.bfloat16)
x, y = mlp.shard_batch(x, y, mesh)

for step in range(50):
    params, loss = mlp.train_step(params, x, y, lr=5e-3)
    if step % 10 == 0:
        print(f"step {step:3d} loss {float(loss):.4f}")
        shutil.rmtree("/tmp/mlp_ckpt", ignore_errors=True)
        save("/tmp/mlp_ckpt", {"step": step, "params": params})

back = load("/tmp/mlp_ckpt")
print("restored checkpoint from step", back["step"],
      "| w0 dtype:", back["params"][0]["w"].dtype)
