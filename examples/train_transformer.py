"""Train the flagship transformer (flash-attention kernels, Megatron tp
layout, dp batch) on a toy counting language, then greedy-decode.

Note: on the CPU fallback the Pallas kernels run in interpret mode, so this
example takes a couple of minutes; on a real TPU it is seconds.  The toy
model will not decode perfectly — the point is the machinery."""

import _setup  # noqa: F401

import jax
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributedarrays_tpu.models import transformer as T
from distributedarrays_tpu.models.mlp import make_mesh

cfg = T.Config(vocab=32, dim=64, heads=4, layers=2, max_seq=32)
mesh = make_mesh()
print("mesh:", dict(mesh.shape))

params = T.shard_params(T.init_params(jax.random.key(0), cfg), mesh)

# task: every sequence counts upward mod vocab
start = jax.random.randint(jax.random.key(1), (16, 1), 0, cfg.vocab)
tokens = ((start + jnp.arange(cfg.max_seq)[None]) % cfg.vocab).astype(jnp.int32)
tokens = jax.device_put(tokens, jax.NamedSharding(mesh, P("dp", None)))  # dalint: disable=DAL007 — host token batch scatter, no source layout

for step in range(60):
    params, loss = T.train_step(params, tokens, jnp.float32(0.05), cfg)
    if step % 20 == 0:
        print(f"step {step:3d}  loss {float(loss):.4f}")
print(f"final loss {float(loss):.4f}")

# decode from a prompt length the model has seen in training context —
# the KV-cache path: the whole loop is ONE jitted lax.scan (round 4),
# vs re-running the full forward per token
prompt = jnp.asarray([[(7 + i) % cfg.vocab for i in range(16)]], jnp.int32)
out = T.generate(params, prompt, 6, cfg)
print("greedy continuation (last 10):", np.asarray(out[0, -10:]).tolist())

# compare against the naive per-token re-forward oracle.  Under the
# default bf16 config the two take different rounding paths (fp32
# einsum over a bf16 cache vs the Pallas flash kernel), so a near-tie
# in logits can legitimately flip a token — report agreement instead of
# hard-asserting it (tests/test_transformer.py pins exact equality in
# fp32)
seq = np.asarray(prompt[0]).tolist()
for _ in range(6):
    logits = T.forward(params, jnp.asarray([seq], jnp.int32), cfg)
    seq.append(int(jnp.argmax(logits[0, -1])))
agree = sum(a == b for a, b in zip(seq, np.asarray(out[0]).tolist()))
print(f"KV-cache decode vs per-token re-forward oracle: "
      f"{agree}/{len(seq)} tokens agree (bf16 rounding can flip ties)")
