"""MPI-style dynamic SPMD: tagged ring send/recv, collectives, contexts
(reference spmd.jl usage, docs/src/index.md:285-457)."""

import _setup  # noqa: F401

from distributedarrays_tpu import parallel as par
from distributedarrays_tpu.parallel import (barrier, bcast, context,
                                            context_local_storage,
                                            gather_spmd, myid, recvfrom,
                                            scatter, sendto, spmd)

NP = 8


def ring_program():
    me = myid()
    nxt, prv = (me + 1) % NP, (me - 1) % NP
    # pass a token around the ring, accumulating rank ids
    token = [me] if me == 0 else None
    if me == 0:
        sendto(nxt, token, tag="ring")
        token = recvfrom(prv, tag="ring")      # full circle
    else:
        token = recvfrom(prv, tag="ring")
        token.append(me)
        sendto(nxt, token, tag="ring")
    barrier()
    # collectives
    word = bcast("hello" if me == 3 else None, root=3)
    part = scatter(list(range(2 * NP)) if me == 0 else None, root=0)
    sums = gather_spmd(sum(part), root=0)
    ctx_store = context_local_storage()
    ctx_store["visits"] = ctx_store.get("visits", 0) + 1
    return token if me == 0 else (word, part, sums)


ctx = context()
out = spmd(ring_program, context=ctx)
print("rank 0 saw the full ring:", out[0])
print("rank 5 got:", out[5])
out2 = spmd(ring_program, context=ctx)   # storage persists across runs
counts = spmd(lambda: context_local_storage()["visits"], context=ctx)
print("context-local visit counts:", counts)
