"""2-D-grid distributed GEMM: the owned tile schedules.

The reference's tile-grid ``mul!`` (linalg.jl:189-253) ships A-row and
B-column tiles to every destination rank; BASELINE config 3 is exactly
that shape (16384² on a 2×2 block layout).  The TPU-native answers are
compiled collective schedules run as ONE shard_map program each:

- ``cannon_matmul`` — square ``(g, g)`` grids: Cannon pre-skew (one
  two-axis ppermute per operand), then a double panel ring with every
  ICI hop pipelined behind the local MXU matmul.
- ``cannon_matmul_int8`` — the same ring with int8 panels + per-panel
  scales riding it (4× less ICI traffic), per-hop Pallas int8 kernel,
  f32 accumulation.
- ``summa_matmul`` — arbitrary ``(r, c)`` grids, where Cannon's skewed
  ring misaligns: masked-psum SUMMA panels over lcm(r, c) statically
  unrolled contraction steps, O(one panel) peak memory.

Dispatch from plain ``A @ B`` promotes to these only by measurement
(``tune_matmul_impl_summa`` / bench.py) — this demo calls them directly
and checks the dense oracle.  Runs on the virtual CPU mesh.
"""

import _setup  # noqa: F401

import numpy as np
from jax.sharding import PartitionSpec as P

from distributedarrays_tpu import layout as L
from distributedarrays_tpu.ops.collective_matmul import (
    cannon_matmul, cannon_matmul_int8, summa_matmul)
from distributedarrays_tpu.parallel import collectives as C

rng = np.random.default_rng(0)

# --- square 2x2 grid: Cannon double ring (BASELINE config 3's layout) ---
g = 2
mesh = L.mesh_for(range(g * g), (g, g))
M, K, N = 256, 128, 192
a = rng.standard_normal((M, K)).astype(np.float32)
b = rng.standard_normal((K, N)).astype(np.float32)

cannon = C.run_spmd(lambda al, bl: cannon_matmul(al, bl, "d0", "d1"), mesh,
                    in_specs=(P("d0", "d1"), P("d0", "d1")),
                    out_specs=P("d0", "d1"))
got = np.asarray(cannon(a, b))
print("cannon 2x2 max|err|:", np.abs(got - a @ b).max())
assert np.allclose(got, a @ b, rtol=1e-4, atol=1e-4)

# --- the same ring with int8 panels (quantization-tolerant workloads) ---
cannon8 = C.run_spmd(
    lambda al, bl: cannon_matmul_int8(al, bl, "d0", "d1"), mesh,
    in_specs=(P("d0", "d1"), P("d0", "d1")), out_specs=P("d0", "d1"))
got8 = np.asarray(cannon8(a, b))
rel = np.abs(got8 - a @ b).max() / np.abs(a @ b).max()
print("cannon int8 2x2 rel err:", f"{rel:.2e}", "(quantization-bounded)")
assert rel < 2e-2

# --- rectangular 4x2 grid: SUMMA panels (Cannon refuses r != c) ---
mesh42 = L.mesh_for(range(8), (4, 2))
M2, K2, N2 = 256, 256, 128
a2 = rng.standard_normal((M2, K2)).astype(np.float32)
b2 = rng.standard_normal((K2, N2)).astype(np.float32)
summa = C.run_spmd(lambda al, bl: summa_matmul(al, bl, "d0", "d1"), mesh42,
                   in_specs=(P("d0", "d1"), P("d0", "d1")),
                   out_specs=P("d0", "d1"))
got2 = np.asarray(summa(a2, b2))
print("summa 4x2 max|err|:", np.abs(got2 - a2 @ b2).max())
assert np.allclose(got2, a2 @ b2, rtol=1e-4, atol=1e-4)

print("grid GEMM demo OK")
