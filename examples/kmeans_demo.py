"""Distributed k-means with a convergence trace."""

import _setup  # noqa: F401

import numpy as np

import distributedarrays_tpu as dat
from distributedarrays_tpu.models import kmeans

rng = np.random.default_rng(1)
centers = rng.uniform(-10, 10, size=(5, 8)).astype(np.float32)
pts = np.concatenate([
    c + 0.4 * rng.standard_normal((2000, 8)).astype(np.float32)
    for c in centers])
rng.shuffle(pts)

d = dat.distribute(pts)
print("points:", d.dims, "chunk grid:", d.pids.shape)

C, shifts = kmeans.kmeans(d, k=5, iters=25, seed=3)
print("centroid shift per iter:", np.array2string(shifts[:8], precision=4))
recovered = sorted(np.min(np.linalg.norm(np.asarray(C) - c, axis=1))
                   for c in centers)
print("distance from each true center to nearest centroid:",
      [f"{x:.3f}" for x in recovered])

labels = kmeans.assign(d, C)
print("label counts:", np.bincount(np.asarray(labels)))
dat.d_closeall()
