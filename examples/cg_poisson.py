"""Distributed conjugate-gradient Poisson solve.

Solves the 2-D Poisson problem  -lap(u) = f  with zero Dirichlet boundary
on a row-sharded grid, composing three framework layers per iteration:

- the operator: one compiled halo-exchange Laplacian step
  (``models.stencil.stencil5`` — ppermutes over ICI inside one program);
- BLAS-1: ``ddot`` / ``dnorm`` / ``axpy_`` (reference linalg.jl:22-59);
- elementwise DArray arithmetic for the direction update.

The reference's docs close with exactly this kind of composition (the
life/stencil demo, docs/src/index.md:160-204); CG is its natural
"now do real numerics with it" extension.
"""

import _setup  # noqa: F401

import numpy as np

import distributedarrays_tpu as dat
from distributedarrays_tpu.models import stencil

N = 256                       # grid side; row-sharded over the mesh
NDEV = 8


def A(u):
    """Negative Laplacian with zero Dirichlet boundary (SPD)."""
    r = stencil.stencil5(u, iters=1)
    out = -r
    r.close()
    return out


def main():
    # manufactured solution: u* = sin(px)*sin(py) on the unit square so
    # -lap(u*) = 2*pi^2*u* up to the h^2 discretization error
    h = 1.0 / (N + 1)
    x = (np.arange(N, dtype=np.float32) + 1) * h
    U_true = np.sin(np.pi * x)[:, None] * np.sin(np.pi * x)[None, :]
    F = (2 * np.pi**2 * U_true * h * h).astype(np.float32)  # scaled rhs

    b = dat.distribute(F, procs=range(NDEV), dist=(NDEV, 1))
    u = dat.dzeros((N, N), procs=range(NDEV), dist=(NDEV, 1))

    r = b.copy()              # r = b - A(0) = b
    p = r.copy()
    rs = float(dat.ddot(r, r))
    b_norm = float(dat.dnorm(b))

    it = 0
    converged = False
    for it in range(1, 501):
        Ap = A(p)
        alpha = rs / float(dat.ddot(p, Ap))
        dat.axpy_(alpha, p, u)            # u += alpha p
        dat.axpy_(-alpha, Ap, r)          # r -= alpha Ap
        Ap.close()
        rs_new = float(dat.ddot(r, r))
        if np.sqrt(rs_new) <= 1e-6 * b_norm:
            rs = rs_new
            converged = True
            break
        beta = rs_new / rs
        rs = rs_new
        scaled = p * beta
        p_next = r + scaled
        scaled.close()
        p.close()
        p = p_next

    resid = np.sqrt(rs) / b_norm
    err = np.abs(np.asarray(u) - U_true).max()
    status = "converged in" if converged else "did NOT converge within"
    print(f"CG {status} {it} iterations; relative residual {resid:.2e}")
    print(f"max error vs manufactured solution: {err:.2e} "
          f"(discretization-limited)")
    dat.d_closeall()
    return it, resid, err


if __name__ == "__main__":
    main()
