"""Conway's Game of Life on a fully 2-D-sharded grid — the reference's
flagship distributed demo (docs/src/index.md:160-204), with the halo
exchange compiled to ppermutes over ICI."""

import _setup  # noqa: F401

import numpy as np

import distributedarrays_tpu as dat
from distributedarrays_tpu.models import stencil

rng = np.random.default_rng(0)
N = 64
board = (rng.random((N, N)) < 0.35).astype(np.int32)

# 2-D device grid: both dimensions distributed
d = dat.distribute(board, procs=range(8), dist=(4, 2))
print("board", d.dims, "on chunk grid", d.pids.shape)

for gen in [1, 10, 50]:
    out = stencil.life2d(d, iters=gen)   # gen steps compiled as one scan
    pop = int(np.asarray(out).sum())
    print(f"after {gen:3d} generations: population {pop}")
    out.close()

dat.d_closeall()
