"""Pipeline and expert parallelism in one sitting: a GPipe microbatch
pipeline trained a few steps, and a Switch-style MoE layer routing tokens
across expert ranks via all_to_all."""

import _setup  # noqa: F401

import numpy as np
import jax
import jax.numpy as jnp

from distributedarrays_tpu.models import moe as M
from distributedarrays_tpu.models import pipeline as PP

# ---- pipeline: 4 stages, 6 microbatches ---------------------------------
mesh = PP.make_pp_mesh(4)
params = PP.init_pipeline_params(jax.random.key(0), 4, 32)
mb = jax.random.normal(jax.random.key(1), (6, 8, 32))
tgt = jnp.zeros((6, 8, 32))

out = PP.pipeline_forward(params, mb, mesh)
err = float(jnp.abs(out - PP.reference_forward(params, mb)).max())
print(f"pipeline forward exact vs sequential: max err {err:.2e}")

for i in range(10):
    params, loss = PP.pipeline_train_step(params, mb, tgt, mesh, lr=0.1)
print(f"pipeline train loss after 10 steps: {float(loss):.4f}")

# ---- MoE: 4 experts, tokens routed via all_to_all -----------------------
ep_mesh = M.make_ep_mesh(4)
mp = M.init_moe_params(jax.random.key(2), 4, 16, 32)
x = jax.random.normal(jax.random.key(3), (32, 16))
y = M.moe_forward(mp, x, ep_mesh, capacity=8)
ref = M.reference_moe(mp, x, 8, 4)
print(f"moe routed output exact vs dense oracle: "
      f"max err {np.abs(np.asarray(y) - ref).max():.2e}")

tight = M.moe_forward(mp, x, ep_mesh, capacity=1)
passthrough = int(np.sum(np.all(np.asarray(tight) == np.asarray(x), axis=1)))
print(f"with capacity=1, {passthrough} overflow tokens took the residual path")

# ---- round-4: the 1F1B schedule (memory bounded by depth, not M) --------
p2 = PP.init_pipeline_params(jax.random.key(4), 4, 32, n_layers=2)
pg, lg = PP.pipeline_train_step(p2, mb, tgt, mesh, lr=0.1)
pf, lf = PP.pipeline_train_step_1f1b(p2, mb, tgt, mesh, lr=0.1)
dw = float(jnp.abs(pf["W"] - pg["W"]).max())
print(f"1F1B vs GPipe: identical loss ({float(lf):.6f} == {float(lg):.6f}),"
      f" max weight delta {dw:.2e}; activations per stage capped at "
      f"min(M, 2P-1) = {min(6, 7)} saved inputs")

# ---- round-4: top-2 routing with capacity factor + aux loss -------------
y2, aux = M.moe_forward(mp, x, ep_mesh, k=2, capacity_factor=1.5,
                        return_aux=True)
ref2 = M.reference_moe(mp, x, int(np.ceil(1.5 * 2 * 8 / 4)), 4, k=2)
print(f"top-2 routed output vs dense oracle: max err "
      f"{np.abs(np.asarray(y2) - ref2).max():.2e}; "
      f"Switch aux loss {float(aux):.3f} (1.0 = perfectly balanced)")
