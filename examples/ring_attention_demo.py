"""Sequence-parallel exact attention over the device mesh: K/V blocks ride
a ppermute ring while each rank keeps an online-softmax accumulator."""

import _setup  # noqa: F401

import numpy as np

import distributedarrays_tpu as dat
from distributedarrays_tpu.models import ring_attention as RA

S, H, D = 512, 8, 64
rng = np.random.default_rng(0)
mk = lambda: rng.standard_normal((S, H, D)).astype(np.float32)
q, k, v = mk(), mk(), mk()

dist = (8, 1, 1)   # sequence dim sharded over all ranks
dq = dat.distribute(q, procs=range(8), dist=dist)
dk = dat.distribute(k, procs=range(8), dist=dist)
dv = dat.distribute(v, procs=range(8), dist=dist)

out = RA.ring_attention(dq, dk, dv, causal=True)
print("output:", out.dims, "sharded", out.pids.shape)

want = RA.reference_attention(q, k, v, causal=True)
err = np.abs(np.asarray(out) - want).max()
print(f"max |ring - dense| = {err:.2e}  (exact up to f32 round-off)")
dat.d_closeall()
