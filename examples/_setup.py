"""Shared example bootstrap: put the repo on sys.path and pick devices.

If an accelerator platform is configured (JAX_PLATFORMS names one, e.g. a
TPU), the examples run on it.  Otherwise — or when EXAMPLES_FORCE_CPU=1 —
they fall back to a virtual 8-device CPU mesh so they run anywhere."""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

_platforms = os.environ.get("JAX_PLATFORMS", "")
_has_accel = any(p and p != "cpu" for p in _platforms.split(","))
if os.environ.get("EXAMPLES_FORCE_CPU") == "1" or not _has_accel:
    # the wedged-tunnel-safe CPU bootstrap lives in ONE place, shared
    # with tests/conftest.py — see _cpu_harness.py for why each step
    # exists
    import _cpu_harness
    _cpu_harness.force_cpu_mesh()
