"""Shared example bootstrap: put the repo on sys.path and pick devices.

If an accelerator platform is configured (JAX_PLATFORMS names one, e.g. a
TPU), the examples run on it.  Otherwise — or when EXAMPLES_FORCE_CPU=1 —
they fall back to a virtual 8-device CPU mesh so they run anywhere."""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

_platforms = os.environ.get("JAX_PLATFORMS", "")
_has_accel = any(p and p != "cpu" for p in _platforms.split(","))
if os.environ.get("EXAMPLES_FORCE_CPU") == "1" or not _has_accel:
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
