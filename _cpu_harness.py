"""The one copy of the virtual-CPU-mesh bootstrap recipe.

Shared by tests/conftest.py and examples/_setup.py — environment-critical
hang-avoidance logic must not exist as hand-synced duplicates.  Call
``force_cpu_mesh()`` BEFORE the first ``import jax`` in the process.

Why each step exists (observed round 5):
- ``JAX_PLATFORMS=cpu`` in the ENV, not just the config API: children
  (multihost forks, example subprocesses) inherit it, and the axon shim
  consults it during backend init.
- Dropping the axon plugin site from ``sys.path`` AND children's
  ``PYTHONPATH``: a WEDGED tunnel (connection alive but hung, unlike a
  refused one) blocks jax backend discovery even in CPU mode — the
  plugin dials the relay during backend init.
- ``--xla_force_host_platform_device_count``: the 8-device virtual mesh,
  the JAX analog of the reference's ``addprocs`` harness.
- ``jax.config.update`` AFTER import: this image's sitecustomize pre-sets
  ``jax_platforms="axon,cpu"`` at interpreter startup, which outranks
  the env var for the current process.
"""

import os
import sys


def force_cpu_mesh(device_count: int = 8) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count"
            f"={device_count}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
