"""Direct tests for public API entry points only exercised indirectly
elsewhere."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import layout as L
from distributedarrays_tpu.models import stencil
from distributedarrays_tpu.ops.broadcast import broadcasted
from distributedarrays_tpu.ops.mapreduce import dreduce
from distributedarrays_tpu.ops.pallas_attention import flash_block_size
from distributedarrays_tpu.parallel import collectives as C
from distributedarrays_tpu.parallel import spmd_mode as S


def test_broadcasted_alias(rng):
    A = rng.standard_normal((8, 8)).astype(np.float32)
    r = broadcasted(jnp.add, dat.distribute(A), 1.0)
    assert np.allclose(np.asarray(r), A + 1, rtol=1e-6)


def test_dreduce(rng):
    A = rng.standard_normal((16, 4)).astype(np.float32)
    d = dat.distribute(A)
    assert np.allclose(float(dreduce("sum", d)), A.sum(), rtol=1e-4)
    r = dreduce("max", d, dims=0)
    assert np.allclose(np.asarray(r), A.max(axis=0, keepdims=True))


def test_current_rank_and_nprocs():
    assert dat.current_rank() == 0          # controller
    out = S.spmd(lambda: (S.myid(), S.nprocs()), pids=[2, 5])
    assert out == [(2, 2), (5, 2)]
    assert S.nprocs() == 8                  # outside spmd: all ranks


def test_localpartindex():
    d = dat.dzeros((16, 8), procs=range(8), dist=(4, 2))
    assert d.localpartindex(0) == (0, 0)
    assert d.localpartindex(5) == (2, 1)
    assert d.localpartindex(99) is None


def test_all_ranks_next_did():
    assert L.all_ranks() == list(range(8))
    a, b = dat.next_did(), dat.next_did()
    assert a[0] == 0 and b[1] == a[1] + 1


def test_axis_size(rng):
    from jax.sharding import PartitionSpec as P
    mesh = C.spmd_mesh(4)
    f = C.run_spmd(lambda x: x * C.axis_size("p"), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    assert np.allclose(np.asarray(f(np.ones(4, np.float32))), 4.0)


def test_single_step_helpers(rng):
    A = rng.standard_normal((16, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))
    s1 = np.asarray(stencil.stencil5_step(d))
    s2 = np.asarray(stencil.stencil5(d, iters=1))
    assert np.array_equal(s1, s2)
    b = (rng.random((16, 8)) < 0.5).astype(np.int32)
    db = dat.distribute(b, procs=range(4), dist=(4, 1))
    l1 = np.asarray(stencil.life_step(db))
    l2 = np.asarray(stencil.life(db, iters=1))
    assert np.array_equal(l1, l2)


def test_flash_block_size():
    assert flash_block_size(256) == 256      # cap defaults to the tuned 512
    assert flash_block_size(2048) == 512
    assert flash_block_size(96) == 32
    assert flash_block_size(31) == 1
    assert flash_block_size(64, cap=32) == 32


def test_subdarray_materialize(rng):
    A = rng.standard_normal((12, 12)).astype(np.float32)
    d = dat.distribute(A)
    m = d[2:8, 3:9].materialize()
    assert m.shape == (6, 6)
    assert np.array_equal(np.asarray(m), A[2:8, 3:9])


# ---------------------------------------------------------------------------
# isassigned (reference Base.isassigned, darray.jl:663-674)
# ---------------------------------------------------------------------------


def test_isassigned_darray(rng):
    d = dat.distribute(rng.standard_normal((8, 6)).astype(np.float32))
    assert dat.isassigned(d, 0, 0)
    assert dat.isassigned(d, 7, 5)
    assert dat.isassigned(d, -1, -1)      # numpy-style wrap is in bounds
    assert not dat.isassigned(d, 8, 0)    # out of bounds
    assert not dat.isassigned(d, 0, 6)
    assert not dat.isassigned(d, 0)       # wrong arity
    assert not dat.isassigned(d, 0, 0, 0)


def test_isassigned_subdarray(rng):
    d = dat.distribute(rng.standard_normal((8, 6)).astype(np.float32))
    v = d[2:6, 1:4]
    assert dat.isassigned(v, 0, 0)
    assert dat.isassigned(v, 3, 2)
    assert not dat.isassigned(v, 4, 0)
    assert not dat.isassigned(v, 0, 3)


def test_isassigned_ddata():
    dd = dat.ddata(init=lambda i: f"part{i}")
    assert dat.isassigned(dd, 0)
    assert dat.isassigned(dd, len(dd) - 1)
    assert not dat.isassigned(dd, len(dd))
    assert not dat.isassigned(dd, 0, 0)


def test_isassigned_wrong_type():
    with pytest.raises(TypeError):
        dat.isassigned(np.zeros(3), 0)


# ---------------------------------------------------------------------------
# advanced-indexing result shapes (SubDArray.shape must follow numpy/jax
# broadcasting of array indices)
# ---------------------------------------------------------------------------


def test_subdarray_shape_two_array_indices(rng):
    A = rng.standard_normal((8, 6)).astype(np.float32)
    d = dat.distribute(A)
    i1 = np.array([0, 3])
    i2 = np.array([1, 4])
    v = d[i1, i2]
    assert v.shape == A[i1, i2].shape  # (2,), not (2, 2)
    np.testing.assert_allclose(np.asarray(v), A[i1, i2])


def test_subdarray_shape_array_and_int(rng):
    A = rng.standard_normal((8, 6)).astype(np.float32)
    d = dat.distribute(A)
    i1 = np.array([0, 3, 5])
    v = d[i1, 2]
    assert v.shape == A[i1, 2].shape
    np.testing.assert_allclose(np.asarray(v), A[i1, 2])


def test_subdarray_shape_separated_array_indices(rng):
    A = rng.standard_normal((8, 6, 4)).astype(np.float32)
    d = dat.distribute(A)
    i1 = np.array([0, 3])
    i2 = np.array([1, 2])
    v = d[i1, :, i2]  # separated advanced indices -> broadcast dims first
    assert v.shape == A[i1, :, i2].shape
    np.testing.assert_allclose(np.asarray(v), A[i1, :, i2])


def test_subdarray_shape_array_with_slice(rng):
    A = rng.standard_normal((8, 6)).astype(np.float32)
    d = dat.distribute(A)
    i1 = np.array([[0, 3], [2, 5]])  # 2-d array index
    v = d[i1, :]
    assert v.shape == A[i1, :].shape
    np.testing.assert_allclose(np.asarray(v), A[i1, :])


def test_subdarray_int_and_array_separated(rng):
    # int + slice + array index: materialize must follow the same numpy
    # advanced-indexing rules _result_shape promises for .shape
    A = rng.standard_normal((8, 6, 4)).astype(np.float32)
    d = dat.distribute(A)
    v = d[2, :, np.array([1, 2])]
    want = A[2, :, np.array([1, 2])]
    assert v.shape == want.shape
    np.testing.assert_allclose(np.asarray(v), want)
