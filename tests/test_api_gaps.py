"""Direct tests for public API entry points only exercised indirectly
elsewhere."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import layout as L
from distributedarrays_tpu.models import stencil
from distributedarrays_tpu.ops.broadcast import broadcasted
from distributedarrays_tpu.ops.mapreduce import dreduce
from distributedarrays_tpu.ops.pallas_attention import flash_block_size
from distributedarrays_tpu.parallel import collectives as C
from distributedarrays_tpu.parallel import spmd_mode as S


def test_broadcasted_alias(rng):
    A = rng.standard_normal((8, 8)).astype(np.float32)
    r = broadcasted(jnp.add, dat.distribute(A), 1.0)
    assert np.allclose(np.asarray(r), A + 1, rtol=1e-6)


def test_dreduce(rng):
    A = rng.standard_normal((16, 4)).astype(np.float32)
    d = dat.distribute(A)
    assert np.allclose(float(dreduce("sum", d)), A.sum(), rtol=1e-4)
    r = dreduce("max", d, dims=0)
    assert np.allclose(np.asarray(r), A.max(axis=0, keepdims=True))


def test_current_rank_and_nprocs():
    assert dat.current_rank() == 0          # controller
    out = S.spmd(lambda: (S.myid(), S.nprocs()), pids=[2, 5])
    assert out == [(2, 2), (5, 2)]
    assert S.nprocs() == 8                  # outside spmd: all ranks


def test_localpartindex():
    d = dat.dzeros((16, 8), procs=range(8), dist=(4, 2))
    assert d.localpartindex(0) == (0, 0)
    assert d.localpartindex(5) == (2, 1)
    assert d.localpartindex(99) is None


def test_all_ranks_next_did():
    assert L.all_ranks() == list(range(8))
    a, b = dat.next_did(), dat.next_did()
    assert a[0] == 0 and b[1] == a[1] + 1


def test_axis_size(rng):
    from jax.sharding import PartitionSpec as P
    mesh = C.spmd_mesh(4)
    f = C.run_spmd(lambda x: x * C.axis_size("p"), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    assert np.allclose(np.asarray(f(np.ones(4, np.float32))), 4.0)


def test_single_step_helpers(rng):
    A = rng.standard_normal((16, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))
    s1 = np.asarray(stencil.stencil5_step(d))
    s2 = np.asarray(stencil.stencil5(d, iters=1))
    assert np.array_equal(s1, s2)
    b = (rng.random((16, 8)) < 0.5).astype(np.int32)
    db = dat.distribute(b, procs=range(4), dist=(4, 1))
    l1 = np.asarray(stencil.life_step(db))
    l2 = np.asarray(stencil.life(db, iters=1))
    assert np.array_equal(l1, l2)


def test_flash_block_size():
    assert flash_block_size(256) == 128
    assert flash_block_size(96) == 32
    assert flash_block_size(31) == 1
    assert flash_block_size(64, cap=32) == 32


def test_subdarray_materialize(rng):
    A = rng.standard_normal((12, 12)).astype(np.float32)
    d = dat.distribute(A)
    m = d[2:8, 3:9].materialize()
    assert m.shape == (6, 6)
    assert np.array_equal(np.asarray(m), A[2:8, 3:9])
