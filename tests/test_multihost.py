"""Multi-process comm-backend smoke test.

The reference's harness is genuinely multi-process (addprocs,
/root/reference/test/runtests.jl:10-13).  Single-controller JAX collapses
that for everything else in this suite, but the DCN half of the comm
backend (``parallel/multihost.py``) only exists multi-process — so this
test spawns TWO real OS processes, joins them with
``jax.distributed.initialize`` over a local coordinator, and drives a
global mesh, one cross-process psum, and one cross-process DArray.
"""

import os
import socket
import subprocess
import sys

import pytest

import distributedarrays_tpu  # noqa: F401  (import check only)

_CHILD = os.path.join(os.path.dirname(__file__), "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_cluster(stage: str, timeout: int, nprocs: int = 2):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, _CHILD, str(port), str(i), stage,
                          str(nprocs)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost children hung; partial output: {outs}")
    # typed-marker protocol (see _multihost_child.py): exit 3 = the
    # runtime formed the cluster but cannot compile multiprocess
    # computations — a missing backend capability, skip naming it; exit
    # 4 = cluster formation itself failed within the bounded init
    # timeout — a diagnosable failure, never a silent hang
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode == 3 and "MULTIHOST_CAPABILITY_MISSING" in out:
            line = next(ln for ln in out.splitlines()
                        if ln.startswith("MULTIHOST_CAPABILITY_MISSING"))
            pytest.skip("multihost runtime capability missing: "
                        + line.split(": ", 1)[1])
        if p.returncode == 4 and "MULTIHOST_STARTUP_FAILED" in out:
            pytest.fail(f"multihost cluster formation failed "
                        f"(proc {i}, bounded init timeout):\n{out}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"MULTIHOST_OK proc={i}" in out, out


def test_two_process_smoke():
    """Default-loop guard (<60 s): cluster formation + the core
    cross-process DArray ops, so regressions in `_put_global`'s
    process-spanning branches surface without DAT_TEST_SLOW=1
    (VERDICT round-3 item 8)."""
    _run_cluster("smoke", timeout=120)


@pytest.mark.slow
@pytest.mark.parametrize("nprocs", [3, 4])
def test_multi_process_jax_distributed(nprocs):
    """The full cross-process op matrix (the reference runs its entire
    suite multi-process and REFUSES fewer than 3 workers,
    runtests.jl:10-15): elementwise, reductions, GEMM, uneven, scan,
    FFT, dsort, compiled run_spmd+pshift, checkpoint round-trip, ring
    attention.  At p=3 the 50-row layouts chunk unevenly and every ring
    has distinct left/right neighbors — the asymmetries a 2-process
    cluster structurally folds away (VERDICT round-4 item 4); p=4 adds
    the power-of-two grid the collective layouts favor."""
    _run_cluster("full", timeout=420, nprocs=nprocs)


def test_initialize_no_cluster_degrades_to_single_process():
    # auto-detect path with no cluster env must degrade silently — but only
    # for the "no cluster detected" family; run in a fresh process because
    # a live backend is itself a (correctly surfaced) hard error
    prog = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from distributedarrays_tpu.parallel import multihost\n"
        "multihost.initialize()\n"
        "assert multihost.process_info()['process_count'] == 1\n"
        "print('SINGLE_OK')\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SINGLE_OK" in r.stdout


def test_initialize_backend_already_live_raises():
    # the old blanket `except Exception: pass` hid this real error; the
    # narrowed filter must let it surface
    prog = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp; jnp.ones(3).sum()\n"
        "from distributedarrays_tpu.parallel import multihost\n"
        "try:\n"
        "    multihost.initialize()\n"
        "except RuntimeError:\n"
        "    print('RAISED_OK')\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RAISED_OK" in r.stdout
