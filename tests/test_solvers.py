"""Solver-suite tests: distributed matrix-free operators against dense
oracles, Krylov convergence (CG / BiCGStab / GMRES) on the 8-device
mesh, the multigrid-preconditioned iteration-count win, the streaming
solve service (updates, cancel-frees-residency), the SpMV roofline
classification the telemetry doctor relies on — and the solver chaos
leg (seeded device loss mid-CG shrinks the operands onto survivors and
still converges to the fault-free answer).

CI runs this file twice: the plain unit leg, and the `solver-chaos` leg
under pinned DA_TPU_FAULT_SEED + DA_TPU_CHECK_DIVERGENCE=1.
"""

import json

import numpy as np
import pytest
import scipy.sparse as sps

import distributedarrays_tpu as dat
from distributedarrays_tpu import telemetry as tm
from distributedarrays_tpu.resilience import elastic, faults
from distributedarrays_tpu.serve import Cancelled
from distributedarrays_tpu.solvers import (DenseOperator, Multigrid,
                                           SolverService, SparseOperator,
                                           StencilOperator, bicgstab, cg,
                                           gmres, poisson2d_dense)
from distributedarrays_tpu.telemetry import memory as tmem, perf
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)


@pytest.fixture(autouse=True)
def _clean_resilience():
    faults.clear()
    elastic.manager().reset()
    yield
    faults.clear()
    elastic.manager().reset()


def _vec(op, arr):
    """Distribute a host vector/grid on the operator's preferred layout."""
    procs, dist = op.vector_layout()
    return dat.distribute(np.asarray(arr, dtype=np.float32), procs=procs,
                          dist=list(dist))


def _banded(n, *, sym=False):
    """A well-conditioned banded test matrix (nonsymmetric by default)."""
    lower = 0.5 if not sym else -1.0
    return (3.0 * np.eye(n) - np.eye(n, k=1)
            + lower * np.eye(n, k=-1)).astype(np.float32)


# ---------------------------------------------------------------------------
# cost model + dense oracle
# ---------------------------------------------------------------------------


def test_spmv_cost_fields():
    c = perf.spmv_cost(100, 10, 4, index_itemsize=4, bytes_ici=64)
    assert c == {"flops": 200, "bytes_hbm": 100 * 8 + 2 * 10 * 4,
                 "bytes_ici": 64}
    # stencil flavour: no stored indices, no halo
    c = perf.spmv_cost(5 * 64, 64, 4, index_itemsize=0)
    assert c["bytes_hbm"] == 5 * 64 * 4 + 2 * 64 * 4
    assert c["bytes_ici"] == 0


def test_poisson2d_dense_is_spd():
    A = poisson2d_dense(4, 5)
    assert A.shape == (20, 20)
    np.testing.assert_array_equal(A, A.T)
    assert np.linalg.eigvalsh(A.astype(np.float64)).min() > 0


# ---------------------------------------------------------------------------
# operators vs oracles
# ---------------------------------------------------------------------------


def test_dense_operator_matches_host(rng):
    n = 32
    A = rng.standard_normal((n, n)).astype(np.float32)
    op = DenseOperator(A)
    assert len(op.vector_layout()[0]) > 1      # genuinely sharded
    x = rng.standard_normal(n).astype(np.float32)
    xd = _vec(op, x)
    y = op.apply(xd)
    np.testing.assert_allclose(np.asarray(dat.gather(y)), A @ x,
                               rtol=2e-5, atol=2e-5)
    y.close()
    xd.close()
    op.close()


def test_sparse_operator_matches_dense(rng):
    n = 64
    A = _banded(n)
    x = rng.standard_normal(n).astype(np.float32)
    for built in (A, sps.csr_matrix(A)):
        op = SparseOperator(built)
        assert op.nnz == int(np.count_nonzero(A))
        assert op._p > 1                       # halo path exercised
        xd = _vec(op, x)
        y = op.apply(xd)
        np.testing.assert_allclose(np.asarray(dat.gather(y)), A @ x,
                                   rtol=2e-5, atol=2e-5)
        y.close()
        xd.close()


def test_sparse_operator_from_darray(rng):
    # n matches test_sparse_operator_matches_dense so the SpMV programs
    # hit the in-process jit cache — this test's subject is the
    # DArray -> chunk-offset COO reassembly, which is host-side
    n = 64
    A = _banded(n)
    dA = dat.distribute(A)
    op = SparseOperator(dA)                    # routed through ddata_bcoo
    dA.close()
    x = rng.standard_normal(n).astype(np.float32)
    xd = _vec(op, x)
    y = op.apply(xd)
    np.testing.assert_allclose(np.asarray(dat.gather(y)), A @ x,
                               rtol=2e-5, atol=2e-5)
    y.close()
    xd.close()


def test_sparse_partition_coarsens_for_wide_bandwidth(rng):
    # one entry reaching 40 columns off-diagonal: every multi-rank block
    # size (8, 16, 32 rows) is narrower than the reach, so the partition
    # must coarsen to a single rank — and stay correct
    n = 64
    A = _banded(n)
    A[0, 40] = 2.0
    op = SparseOperator(A)
    assert op._p == 1
    x = rng.standard_normal(n).astype(np.float32)
    xd = _vec(op, x)
    y = op.apply(xd)
    np.testing.assert_allclose(np.asarray(dat.gather(y)), A @ x,
                               rtol=2e-5, atol=2e-5)
    y.close()
    xd.close()


def test_stencil_operator_matches_kron_oracle(rng):
    nx, ny = 8, 8
    op = StencilOperator((nx, ny), scale=0.5)
    dense = poisson2d_dense(nx, ny, scale=0.5)
    x = rng.standard_normal((nx, ny)).astype(np.float32)
    xd = _vec(op, x)
    y = op.apply(xd)
    np.testing.assert_allclose(np.asarray(dat.gather(y)),
                               (dense @ x.ravel()).reshape(nx, ny),
                               rtol=2e-5, atol=2e-5)
    y.close()
    xd.close()


def test_operator_align_accepts_foreign_layout(rng):
    # a vector distributed on a different rank set/layout is re-seated
    # through the planner, the caller's copy untouched
    op = StencilOperator((8, 8))
    x = rng.standard_normal((8, 8)).astype(np.float32)
    xd = dat.distribute(x, procs=[0, 1], dist=[1, 2])
    y = op.apply(xd)
    np.testing.assert_allclose(
        np.asarray(dat.gather(y)),
        (poisson2d_dense(8, 8) @ x.ravel()).reshape(8, 8),
        rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(dat.gather(xd)), x)
    y.close()
    xd.close()


# ---------------------------------------------------------------------------
# Krylov convergence on >= 2 devices
# ---------------------------------------------------------------------------


def test_cg_poisson_converges_to_dense_oracle(rng):
    nx, ny = 16, 16
    op = StencilOperator((nx, ny))
    b = rng.standard_normal((nx, ny)).astype(np.float32)
    bd = _vec(op, b)
    res = cg(op, bd, tol=1e-6)
    assert res.converged and res.outcome == "converged"
    assert len(set(int(p) for p in res.x.pids.flat)) >= 2
    assert len(res.history) == res.iterations > 1
    assert res.residual <= 1e-6 * np.linalg.norm(b)
    oracle = np.linalg.solve(poisson2d_dense(nx, ny).astype(np.float64),
                             b.ravel().astype(np.float64))
    np.testing.assert_allclose(np.asarray(res.x.garray).ravel(), oracle,
                               atol=5e-4)
    res.x.close()
    bd.close()


def test_cg_dense_and_sparse_operators(rng):
    n = 40
    A = _banded(n, sym=True)                  # SPD tridiagonal
    b = rng.standard_normal(n).astype(np.float32)
    oracle = np.linalg.solve(A.astype(np.float64), b.astype(np.float64))
    for op in (DenseOperator(A), SparseOperator(sps.csr_matrix(A))):
        bd = _vec(op, b)
        res = cg(op, bd, tol=1e-7)
        assert res.converged, res.outcome
        np.testing.assert_allclose(np.asarray(res.x.garray), oracle,
                                   atol=1e-3)
        res.x.close()
        bd.close()
        if hasattr(op, "close"):
            op.close()


def test_cg_maxiter_typed_outcome(rng):
    op = StencilOperator((16, 16))
    bd = _vec(op, rng.standard_normal((16, 16)))
    res = cg(op, bd, tol=1e-12, maxiter=3)
    assert res.outcome == "maxiter" and not res.converged
    assert res.iterations == 3 and len(res.history) == 3
    res.x.close()
    bd.close()


def test_bicgstab_and_gmres_nonsymmetric(rng):
    n = 48
    A = _banded(n)
    b = rng.standard_normal(n).astype(np.float32)
    oracle = np.linalg.solve(A.astype(np.float64), b.astype(np.float64))
    for solve in (bicgstab, gmres):
        op = SparseOperator(A)
        bd = _vec(op, b)
        res = solve(op, bd, tol=1e-7)
        assert res.converged, (solve.__name__, res.outcome, res.detail)
        assert res.solver == solve.__name__
        np.testing.assert_allclose(np.asarray(res.x.garray), oracle,
                                   atol=1e-3, err_msg=solve.__name__)
        res.x.close()
        bd.close()


def test_gmres_restart_and_warm_start(rng):
    nx, ny = 16, 16
    op = StencilOperator((nx, ny))
    bd = _vec(op, rng.standard_normal((nx, ny)))
    res = gmres(op, bd, tol=1e-6, restart=5)   # forces outer restarts
    assert res.converged and res.iterations > 5
    # warm start from the solution: the entry residual check converges
    # without growing a Krylov space (looser tol — the recomputed f32
    # residual sits a hair above the Givens estimate the solve stopped on)
    res2 = gmres(op, bd, x0=res.x, tol=1e-5)
    assert res2.converged and res2.iterations == 0
    assert len(res2.history) == 1              # the entry residual
    res2.x.close()
    res.x.close()
    bd.close()


# ---------------------------------------------------------------------------
# multigrid preconditioning
# ---------------------------------------------------------------------------


def test_mgcg_converges_in_far_fewer_iterations(rng):
    nx, ny = 32, 32
    op = StencilOperator((nx, ny))
    b = rng.standard_normal((nx, ny)).astype(np.float32)
    bd = _vec(op, b)
    plain = cg(op, bd, tol=1e-6)
    mg = cg(op, bd, tol=1e-6, M=Multigrid(op))
    assert plain.converged and mg.converged
    assert mg.iterations < plain.iterations / 2, \
        (mg.iterations, plain.iterations)
    np.testing.assert_allclose(np.asarray(mg.x.garray),
                               np.asarray(plain.x.garray), atol=1e-3)
    plain.x.close()
    mg.x.close()
    bd.close()


def test_multigrid_requires_stencil_operator():
    with pytest.raises(TypeError):
        Multigrid(DenseOperator(np.eye(8, dtype=np.float32)))


# ---------------------------------------------------------------------------
# observability: SpMV roofline + stamped solve span
# ---------------------------------------------------------------------------


def test_spmv_spans_classify_memory_bound(telemetry_capture, rng):
    # the doctor's acceptance: SpMV's arithmetic intensity (2 flops per
    # stored element) sits far under the ridge, so every stamped
    # solver.spmv occurrence must classify hbm- or ici-bound — never
    # compute-bound
    op = StencilOperator((16, 16))
    bd = _vec(op, rng.standard_normal((16, 16)))
    res = cg(op, bd, tol=1e-12, maxiter=5)
    res.x.close()
    bd.close()
    sop = SparseOperator(_banded(64))
    vd = _vec(sop, np.ones(64))
    y = sop.apply(vd)
    y.close()
    vd.close()

    spans = telemetry_capture.spans("solver.spmv")
    assert len(spans) >= 6
    assert {s["labels"]["op"] for s in spans} == {"stencil", "bcoo"}
    peaks = perf.peaks_for()
    occs = [perf.classify_occurrence(s, peaks) for s in spans]
    assert all(o is not None for o in occs)       # every span is stamped
    assert {o["bound"] for o in occs} <= {"hbm", "ici"}
    # the solve span itself carries the aggregate stamp (coverage: a
    # stamped parent covers the BLAS-1 self-time under it)
    solve = telemetry_capture.spans("solver.solve")[-1]
    assert float(solve["labels"]["bytes_hbm"]) > 0
    telemetry_capture.assert_counter("solver.iterations", 5, solver="cg")


# ---------------------------------------------------------------------------
# the solver chaos leg
# ---------------------------------------------------------------------------


def test_chaos_device_loss_mid_cg_converges_on_survivors(rng, monkeypatch):
    """Seeded plan downs device 5 on the fourth CG iteration: recovery
    probes, shrinks the registered operands onto the survivors, the
    segment re-derives the operator partition and restarts the Krylov
    space from the current x — and the final answer matches the
    fault-free solve to solver tolerance."""
    nx, ny = 16, 16
    op = StencilOperator((nx, ny))
    b = rng.standard_normal((nx, ny)).astype(np.float32)
    bd = _vec(op, b)
    free = cg(op, bd, tol=1e-6)
    assert free.converged and free.recoveries == 0
    x_free = np.asarray(free.x.garray).copy()
    free.x.close()

    plan = [{"site": "solver.iterate", "action": "device_loss", "at": 4,
             "count": 1, "device": 5}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "1234")
    faults.configure()
    retries0 = tm.counter_value("recovery.retries", verdict="device_loss")

    chaos_op = StencilOperator((nx, ny))
    res = cg(chaos_op, bd, tol=1e-6)
    assert res.converged, (res.outcome, res.detail)
    assert res.recoveries >= 1
    assert [h["action"] for h in faults.history()] == ["device_loss"]
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") > retries0
    # operands live strictly on survivors
    assert 5 not in elastic.manager().live_ranks()
    assert 5 not in {int(p) for p in res.x.pids.flat}
    np.testing.assert_allclose(np.asarray(res.x.garray).ravel(),
                               x_free.ravel(), atol=5e-4)
    res.x.close()
    bd.close()


# ---------------------------------------------------------------------------
# streaming solve service
# ---------------------------------------------------------------------------


def test_service_streams_iterations_and_result(rng):
    nx, ny = 16, 16
    rhs = rng.standard_normal((nx, ny)).astype(np.float32)
    svc = SolverService()
    try:
        stream = svc.submit({"kind": "poisson", "grid": (nx, ny), "b": rhs},
                            tol=1e-6)
        updates = list(stream)                 # (iter, residual) as they land
        summary = stream.result(timeout=120)
    finally:
        svc.close()
    assert summary["outcome"] == "converged"
    assert [it for it, _ in updates] == \
        list(range(1, summary["iterations"] + 1))
    assert len(updates) > 5
    assert updates[-1][1] < updates[0][1]      # residual actually fell
    assert summary["history"] == [r for _, r in updates]
    oracle = np.linalg.solve(poisson2d_dense(nx, ny).astype(np.float64),
                             rhs.ravel().astype(np.float64))
    np.testing.assert_allclose(summary["x"].ravel(), oracle, atol=5e-4)
    assert tmem.live_bytes() == 0              # residency freed with request


def test_service_dense_system_and_bad_method(rng):
    n = 32
    A = _banded(n, sym=True)
    b = rng.standard_normal(n).astype(np.float32)
    svc = SolverService()
    try:
        with pytest.raises(ValueError):
            svc.submit({"kind": "dense", "A": A, "b": b}, method="qr")
        stream = svc.submit({"kind": "dense", "A": A, "b": b}, tol=1e-7)
        summary = stream.result(timeout=120)
    finally:
        svc.close()
    np.testing.assert_allclose(
        summary["x"],
        np.linalg.solve(A.astype(np.float64), b.astype(np.float64)),
        atol=1e-3)


def test_service_cancel_frees_residency(rng):
    # a solve that cannot converge keeps iterating until cancel; the
    # stream resolves typed Cancelled and the dispatch's finally frees
    # the system's operand residency
    rhs = rng.standard_normal((32, 32)).astype(np.float32)
    svc = SolverService()
    try:
        stream = svc.submit({"kind": "poisson", "grid": (32, 32), "b": rhs},
                            precond="multigrid", tol=1e-30, maxiter=100_000)
        with pytest.raises(Cancelled):
            for it, _res in stream:
                if it >= 3:
                    stream.cancel()
        assert stream.cancelled() and stream.done()
        summary = stream.future.result(timeout=60)   # dispatch succeeded
        assert summary["outcome"] == "cancelled"
        assert summary["iterations"] < 100_000
    finally:
        svc.close()
    assert tmem.live_bytes() == 0
