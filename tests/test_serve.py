"""Serving-layer suite: admission, continuous batching, deadlines,
backpressure shedding, graceful drain/SIGTERM, async dispatch, real
elastic health probes — and the chaos leg (a seeded fault plan kills a
device mid-batch; every in-flight request must resolve to a correct
result or a typed error, never a silent hang, with the per-test
registry/HBM-ledger leak gate draining afterwards).
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu import serve, telemetry as tm
from distributedarrays_tpu.parallel import multihost, spmd_mode as S
from distributedarrays_tpu.resilience import elastic, faults, recovery
from distributedarrays_tpu.serve import (DeadlineExceeded, Draining,
                                         Overloaded, QuotaExceeded,
                                         RequestFailed, ServeError)
from distributedarrays_tpu.telemetry import flight
from distributedarrays_tpu.telemetry import memory as tmem

_HAS_FORK = hasattr(os, "fork")
process_only = pytest.mark.skipif(not _HAS_FORK, reason="needs POSIX fork")


@pytest.fixture(autouse=True)
def _clean_serving():
    """Process-wide singletons (fault plan, elastic manager, flight
    recorder) start and end pristine, like the resilience suite."""
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    yield
    faults.clear()
    elastic.manager().reset()
    flight._reset()


def _fast_policy(**kw):
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    return recovery.RetryPolicy(**kw)


def _cfg(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("flush_s", 0.005)
    kw.setdefault("max_queue", 32)
    kw.setdefault("tenant_rate", 10_000.0)
    kw.setdefault("tenant_burst", 10_000.0)
    return serve.ServeConfig(**kw)


# ---------------------------------------------------------------------------
# basic request/future flow + continuous batching
# ---------------------------------------------------------------------------


def test_submit_resolves_results_in_order():
    with serve.Server(_cfg()) as srv:
        srv.register("double", lambda xs: [x * 2 for x in xs])
        futs = [srv.submit("double", np.full((3,), i)) for i in range(12)]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(timeout=10),
                                          np.full((3,), 2 * i))


def test_requests_coalesce_into_batches():
    sizes = []

    def ep(xs):
        sizes.append(len(xs))
        time.sleep(0.003)          # let the queue build a real batch
        return list(xs)

    with serve.Server(_cfg(max_batch=4, flush_s=0.05)) as srv:
        srv.register("echo", ep)
        futs = [srv.submit("echo", np.zeros(2)) for _ in range(10)]
        for f in futs:
            f.result(timeout=10)
    assert sum(sizes) == 10
    assert max(sizes) > 1, f"no coalescing happened: {sizes}"
    assert max(sizes) <= 4, f"batch cap violated: {sizes}"


def test_incompatible_shapes_never_share_a_batch():
    keys = []

    def ep(xs):
        keys.append({x.shape for x in xs})
        return [x.sum() for x in xs]

    with serve.Server(_cfg(flush_s=0.02)) as srv:
        srv.register("sum", ep)
        futs = [srv.submit("sum", np.ones((2,)) if i % 2 else np.ones((3,)))
                for i in range(8)]
        for f in futs:
            f.result(timeout=10)
    for seen in keys:
        assert len(seen) == 1, f"mixed-shape batch dispatched: {keys}"


def test_payload_key_signatures():
    k = serve.payload_key
    assert k(np.zeros((2, 3))) == k(np.ones((2, 3)))
    assert k(np.zeros((2, 3))) != k(np.zeros((3, 2)))
    assert k(np.zeros(2, np.float32)) != k(np.zeros(2, np.float64))
    assert k({"a": np.zeros(2), "b": 1}) == k({"b": 2, "a": np.ones(2)})
    assert k((1, "x")) == k((2, "y"))
    assert k([1]) != k((1,))
    # mixed-type dict keys are a legal payload, not an untyped TypeError
    assert k({1: "a", "b": 2}) == k({"b": 3, 1: "c"})


def test_per_endpoint_batch_limits_honored_with_multiple_endpoints():
    sizes = {"bulk": [], "small": []}

    def make(name):
        def ep(xs):
            sizes[name].append(len(xs))
            time.sleep(0.002)
            return list(xs)
        return ep

    # bulk's max_batch EXCEEDS the config default: its own bound, not
    # the config cap, must govern its batches
    with serve.Server(_cfg(max_batch=2, flush_s=0.05)) as srv:
        srv.register("bulk", make("bulk"), max_batch=6)
        srv.register("small", make("small"), max_batch=2)
        futs = [srv.submit("bulk", np.zeros(1)) for _ in range(12)]
        futs += [srv.submit("small", np.zeros(1)) for _ in range(4)]
        for f in futs:
            f.result(timeout=10)
    assert max(sizes["bulk"]) > 2, f"bulk capped at config: {sizes}"
    assert max(sizes["bulk"]) <= 6
    assert max(sizes["small"]) <= 2


def test_unknown_endpoint_is_typed():
    with serve.Server(_cfg()) as srv:
        srv.register("known", lambda xs: xs)
        with pytest.raises(ServeError, match="unknown endpoint"):
            srv.submit("nope", 1)


def test_endpoint_result_count_contract():
    with serve.Server(_cfg(max_batch=1)) as srv:
        srv.register("bad", lambda xs: [])        # wrong arity
        fut = srv.submit("bad", np.zeros(1))
        with pytest.raises(RequestFailed, match="returned 0 results"):
            fut.result(timeout=10)


# ---------------------------------------------------------------------------
# deadline propagation: enqueue, batch formation, dispatch
# ---------------------------------------------------------------------------


def test_dead_on_arrival_rejected_at_enqueue():
    with serve.Server(_cfg()) as srv:
        srv.register("echo", lambda xs: xs)
        with pytest.raises(DeadlineExceeded) as ei:
            srv.submit("echo", 1, deadline_s=0.0)
        assert ei.value.stage == "enqueue"


def test_expired_queued_request_never_dispatched():
    block = threading.Event()
    seen = []

    def ep(xs):
        seen.extend(xs)
        block.wait(10)
        return list(xs)

    srv = serve.Server(_cfg(max_batch=1, flush_s=0.0))
    try:
        srv.register("slow", ep)
        f1 = srv.submit("slow", "first")
        for _ in range(200):            # wait until the worker is stuck
            if seen:
                break
            time.sleep(0.005)
        assert seen == ["first"]
        f2 = srv.submit("slow", "second", deadline_s=0.05)
        time.sleep(0.15)                # budget expires while queued
        block.set()
        assert f1.result(timeout=10) == "first"
        with pytest.raises(DeadlineExceeded) as ei:
            f2.result(timeout=10)
        assert ei.value.stage in ("batch", "dispatch")
        assert seen == ["first"], "expired request was dispatched"
    finally:
        block.set()
        srv.close()
    assert tm.counter_value("serve.expired", stage=ei.value.stage) >= 1


# ---------------------------------------------------------------------------
# admission control: quotas, queue bound, backpressure signals
# ---------------------------------------------------------------------------


def test_token_bucket_refills_at_rate():
    b = serve.TokenBucket(rate=100.0, burst=2.0)
    assert b.try_take() == 0.0
    assert b.try_take() == 0.0
    wait = b.try_take()
    assert 0.0 < wait <= 0.01 + 1e-3
    time.sleep(wait + 0.005)
    assert b.try_take() == 0.0


def test_latency_window_percentiles_roll():
    w = serve.LatencyWindow(maxlen=4)
    for v in (1.0, 1.0, 1.0, 1.0):
        w.record(v)
    assert w.p99() == 1.0
    for v in (0.1, 0.1, 0.1, 0.1):   # old samples roll out entirely
        w.record(v)
    assert w.p99() == pytest.approx(0.1)
    assert w.p50() == pytest.approx(0.1)


def test_tenant_quota_rejects_typed_and_isolated():
    with serve.Server(_cfg()) as srv:
        srv.register("echo", lambda xs: xs)
        srv.set_quota("greedy", rate=5.0, burst=1.0)
        assert srv.submit("echo", 1, tenant="greedy").result(timeout=10) == 1
        with pytest.raises(QuotaExceeded) as ei:
            srv.submit("echo", 2, tenant="greedy")
        assert ei.value.retry_after > 0
        assert ei.value.reason == "quota"
        assert ei.value.tenant == "greedy"
        # another tenant is untouched by greedy's empty bucket
        assert srv.submit("echo", 3, tenant="polite").result(timeout=10) == 3
    assert tm.counter_value("serve.shed", reason="quota",
                            tenant="greedy") >= 1


def test_bounded_queue_sheds_overloaded_with_retry_after():
    block = threading.Event()

    def ep(xs):
        block.wait(10)
        return list(xs)

    srv = serve.Server(_cfg(max_batch=1, flush_s=0.0, max_queue=4))
    try:
        srv.register("slow", ep)
        futs, rejections = [], []
        for i in range(12):
            try:
                futs.append(srv.submit("slow", i))
            except Overloaded as e:
                rejections.append(e)
        assert rejections, "queue bound never shed"
        for e in rejections:
            assert e.retry_after > 0
            assert e.reason == "queue"
        assert srv.stats()["queue_depth"] <= 4
        block.set()
        for f in futs:
            f.result(timeout=10)       # every admitted request resolves
    finally:
        block.set()
        srv.close()


def test_hbm_backpressure_sheds(rng):
    d = dat.distribute(rng.standard_normal((16, 16)))
    try:
        assert tmem.live_bytes() > 0
        with serve.Server(_cfg(hbm_budget_bytes=1)) as srv:
            srv.register("echo", lambda xs: xs)
            with pytest.raises(Overloaded) as ei:
                srv.submit("echo", 1)
            assert ei.value.reason == "hbm"
            assert ei.value.retry_after > 0
    finally:
        dat.close(d)


def test_rolling_p99_sheds():
    ctl = serve.AdmissionController(
        max_queue=64, tenant_rate=1e6, tenant_burst=1e6,
        p99_shed_s=0.05, max_batch=4)
    for _ in range(16):
        ctl.latency.record(0.2)        # dispatch latencies over threshold
    with pytest.raises(Overloaded) as ei:
        ctl.admit("t", queue_depth=1)
    assert ei.value.reason == "latency"
    assert ei.value.retry_after > 0


# ---------------------------------------------------------------------------
# the open-loop overload acceptance
# ---------------------------------------------------------------------------


def test_open_loop_overload_bounded_and_typed():
    """At ~2x sustainable offered load: queue depth and HBM live bytes
    stay bounded, excess requests shed typed with retry_after, and the
    p99 of ADMITTED requests stays within 2x the unloaded p99 (with a
    small absolute floor against timer noise on a loaded CI box)."""
    service_s = 0.004

    def ep(xs):
        time.sleep(service_s)
        return [x + 1 for x in xs]

    cfg = _cfg(max_batch=4, flush_s=0.002, max_queue=8)
    hbm_before = tmem.live_bytes()
    with serve.Server(cfg) as srv:
        srv.register("work", ep)
        # unloaded baseline: sequential round-trips
        unloaded = []
        for i in range(20):
            t0 = time.monotonic()
            assert srv.submit("work", i).result(timeout=10) == i + 1
            unloaded.append(time.monotonic() - t0)
        p99_unloaded = sorted(unloaded)[-1]
        # open loop at ~2x sustainable (sustainable ~ max_batch/service)
        sustainable = cfg.max_batch / service_s
        interval = 1.0 / (2.0 * sustainable)
        futs, sheds, depths = [], [], []
        latencies, lat_lock = [], threading.Lock()

        def _mark(t0):
            def cb(_f):
                dt = time.monotonic() - t0
                with lat_lock:
                    latencies.append(dt)
            return cb

        t_end = time.monotonic() + 0.8
        while time.monotonic() < t_end:
            try:
                t0 = time.monotonic()
                f = srv.submit("work", 0)
                f.add_done_callback(_mark(t0))   # submit→resolve latency
                futs.append(f)
            except Overloaded as e:
                sheds.append(e)
            depths.append(srv.stats()["queue_depth"])
            time.sleep(interval)
        for f in futs:
            assert f.result(timeout=10) == 1
        assert sheds, "2x offered load never shed"
        assert all(e.retry_after > 0 for e in sheds)
        assert max(depths) <= cfg.max_queue, "queue depth unbounded"
        assert tmem.live_bytes() == hbm_before, "HBM live bytes grew"
        # admitted requests kept their latency SLO: every future already
        # resolved or resolves promptly — the tail is bounded by the
        # queue bound, not by the offered load
        admitted_p99 = sorted(latencies)[-1] if latencies else 0.0
        floor = 0.05
        assert admitted_p99 <= 2.0 * max(p99_unloaded, floor), (
            f"admitted p99 {admitted_p99:.4f}s vs unloaded "
            f"{p99_unloaded:.4f}s")
    assert tm.counter_value("serve.shed", reason="queue",
                            tenant="default") >= len(sheds)


# ---------------------------------------------------------------------------
# graceful drain / shutdown
# ---------------------------------------------------------------------------


def test_drain_flushes_queue_then_rejects_typed():
    def ep(xs):
        time.sleep(0.01)
        return list(xs)

    srv = serve.Server(_cfg(max_batch=2, flush_s=0.0))
    srv.register("work", ep)
    futs = [srv.submit("work", i) for i in range(6)]
    assert srv.drain(timeout=10)
    with pytest.raises(Draining):
        srv.submit("work", 99)
    for i, f in enumerate(futs):       # queued work flushed, not dropped
        assert f.result(timeout=10) == i
    srv.close()
    assert tm.counter_value("serve.shed", reason="draining",
                            tenant="default") >= 1


def test_drain_wakes_sleeping_retry_backoff():
    def ep(xs):
        raise ValueError("always transient")

    # pathological backoff: without the interruptible sleep the drain
    # would sit out ~30s; with it the server finishes in well under 5
    srv = serve.Server(_cfg(max_batch=1, flush_s=0.0),
                       policy=recovery.RetryPolicy(base_delay=30.0,
                                                   max_delay=30.0))
    srv.register("fail", ep)
    fut = srv.submit("fail", 1)
    for _ in range(400):               # wait for the first failed attempt
        if tm.counter_value("recovery.attempts") >= 1 and \
                srv.stats()["inflight"] >= 1:
            break
        time.sleep(0.005)
    t0 = time.monotonic()
    assert srv.drain(timeout=10)
    assert time.monotonic() - t0 < 5.0, "drain blocked on a sleeping retry"
    with pytest.raises(RequestFailed) as ei:
        fut.result(timeout=10)
    assert isinstance(ei.value.__cause__, ValueError)
    srv.close()
    assert tm.counter_value("recovery.interrupted", verdict="transient") >= 1


def test_close_without_drain_fails_queued_typed():
    block = threading.Event()

    def ep(xs):
        block.wait(10)
        return list(xs)

    srv = serve.Server(_cfg(max_batch=1, flush_s=0.0))
    srv.register("stuck", ep)
    f1 = srv.submit("stuck", "inflight")
    time.sleep(0.05)                   # let the worker pick up f1
    f2 = srv.submit("stuck", "queued")
    srv.close(drain=True, timeout=0.2)
    with pytest.raises(Draining):
        f2.result(timeout=10)          # typed, never a hang
    block.set()
    assert f1.result(timeout=10) == "inflight"


def test_close_with_closeall_releases_arrays(rng):
    d = dat.distribute(rng.standard_normal((8, 8)))
    srv = serve.Server(_cfg())
    srv.register("echo", lambda xs: xs)
    assert srv.submit("echo", 5).result(timeout=10) == 5
    srv.close(closeall=True)
    assert dat.live_ids() == []
    assert d._closed


def test_run_with_recovery_stop_event_pre_set():
    ev = threading.Event()
    ev.set()
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("boom")

    t0 = time.monotonic()
    with pytest.raises(ValueError):
        recovery.run_with_recovery(fn, policy=_fast_policy(max_retries=5),
                                   stop_event=ev)
    assert len(calls) == 1, "stop_event set must prevent every retry"
    assert time.monotonic() - t0 < 1.0


def test_install_sigterm_drains_and_chains():
    chained = []
    srv = serve.Server(_cfg())
    srv.register("echo", lambda xs: xs)
    assert srv.submit("echo", 1).result(timeout=10) == 1
    prev = signal.getsignal(signal.SIGTERM)
    try:
        # a benign callable prior disposition: the handler must drain
        # FIRST, then chain it (SIG_DFL would instead be re-delivered,
        # which would terminate this test process — covered by reading
        # the handler's code path, not by delivering it here)
        signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
        assert serve.install_sigterm(srv, closeall=False)
        handler = signal.getsignal(signal.SIGTERM)
        handler(signal.SIGTERM, None)          # simulate delivery
        assert srv.stats()["closed"]
        assert chained == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)
    with pytest.raises(Draining):
        srv.submit("echo", 2)


# ---------------------------------------------------------------------------
# async SPMD dispatch (the refactored fan-out)
# ---------------------------------------------------------------------------


def test_spmd_async_matches_blocking_results():
    fut = S.spmd_async(lambda: S.myid() * 3)
    assert fut.result(timeout=30) == [r * 3 for r in range(dat.nranks())]


def test_spmd_async_runs_overlap():
    def step():
        time.sleep(0.1)
        return S.myid()

    t0 = time.monotonic()
    f1, f2 = S.spmd_async(step), S.spmd_async(step)
    r1, r2 = f1.result(timeout=30), f2.result(timeout=30)
    elapsed = time.monotonic() - t0
    assert r1 == r2 == list(range(dat.nranks()))
    assert elapsed < 0.19, f"async runs serialized ({elapsed:.3f}s)"


def test_spmd_async_propagates_typed_failure():
    def boom():
        if S.myid() == 1:
            raise ValueError("rank 1 exploded")
        return S.myid()

    fut = S.spmd_async(boom)
    with pytest.raises(RuntimeError, match="rank 1"):
        fut.result(timeout=30)


# ---------------------------------------------------------------------------
# process-backend graceful shutdown (SIGTERM forwarding)
# ---------------------------------------------------------------------------


def _pidfile_then_sleep(tmp: str):
    rank = S.myid()
    with open(os.path.join(tmp, f"{rank}.pid"), "w") as fh:
        fh.write(str(os.getpid()))
    time.sleep(8 if rank == 1 else 0.05)
    return rank


def _kill_when_written(path, sig, pids):
    for _ in range(200):
        if all(os.path.exists(os.path.join(path, f"{r}.pid"))
               for r in pids):
            break
        time.sleep(0.02)
    time.sleep(0.05)
    with open(os.path.join(path, "1.pid")) as fh:
        os.kill(int(fh.read()), sig)


@process_only
def test_process_worker_sigterm_drains_and_reports(tmp_path):
    # a SIGTERM straight to a worker child must surface as a clear
    # "received SIGTERM" rank failure, not a cryptic receive timeout
    killer = threading.Thread(
        target=_kill_when_written,
        args=(str(tmp_path), signal.SIGTERM, [1]), daemon=True)
    killer.start()
    with pytest.raises(RuntimeError, match="received SIGTERM"):
        S.spmd(_pidfile_then_sleep, str(tmp_path), pids=[0, 1],
               backend="process", timeout=30)


@process_only
def test_parent_sigterm_forwarded_to_workers(tmp_path):
    # SIGTERM at the CONTROLLER while a process run is in flight is
    # forwarded to every child; the run fails loudly with the workers'
    # graceful reports (previous SIGTERM disposition was SIG_DFL and is
    # restored by run_spmd_process's finally)
    prev = signal.getsignal(signal.SIGTERM)

    def killer():
        for _ in range(200):
            if all(os.path.exists(os.path.join(str(tmp_path), f"{r}.pid"))
                   for r in (0, 1)):
                break
            time.sleep(0.02)
        time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGTERM)

    def both_sleep(tmp):
        rank = S.myid()
        with open(os.path.join(tmp, f"{rank}.pid"), "w") as fh:
            fh.write(str(os.getpid()))
        time.sleep(8)
        return rank

    threading.Thread(target=killer, daemon=True).start()
    try:
        with pytest.raises(RuntimeError, match="received SIGTERM"):
            S.spmd(both_sleep, str(tmp_path), pids=[0, 1],
                   backend="process", timeout=30)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert signal.getsignal(signal.SIGTERM) == prev


# ---------------------------------------------------------------------------
# elastic health probes on REAL device signals
# ---------------------------------------------------------------------------


def test_probe_reports_all_down_when_runtime_unreachable(monkeypatch):
    m = elastic.manager()
    assert m.probe()["down"] == []        # snapshot cached while healthy
    import jax

    def _dead():
        raise RuntimeError("device runtime unreachable")

    monkeypatch.setattr(jax, "devices", _dead)
    res = m.probe()
    assert res["down"] == list(range(8))
    assert res["live"] == []
    monkeypatch.undo()
    res = m.probe()                       # revives on the next healthy epoch
    assert res["down"] == []
    assert len(res["live"]) == 8


def test_shrunken_enumeration_downs_vanished_ranks(monkeypatch):
    m = elastic.manager()
    assert m.probe()["down"] == []        # baseline snapshot: 8 ranks
    import jax
    real = list(jax.devices())
    monkeypatch.setattr(jax, "devices", lambda *a, **k: real[:6])
    res = m.probe()
    assert res["down"] == [6, 7], "vanished trailing ranks not marked down"
    assert res["live"] == list(range(6))
    res = m.probe()                       # the mark persists across epochs
    assert res["down"] == [6, 7]
    monkeypatch.undo()
    res = m.probe()                       # full enumeration back: revived
    assert res["down"] == []
    assert len(res["live"]) == 8


def test_hw_probe_env_kill_switch(monkeypatch):
    m = elastic.manager()
    m.probe()
    import jax
    monkeypatch.setenv("DA_TPU_ELASTIC_HW_PROBE", "0")
    monkeypatch.setattr(jax, "devices",
                        lambda: (_ for _ in ()).throw(RuntimeError("dead")))
    # real-signal half disabled: the probe trusts the cached snapshot and
    # the deterministic fault-harness fallback only
    assert m.probe()["down"] == []


def test_probe_merges_sim_down_as_deterministic_fallback():
    faults.configure(plan=[{"site": "spmd.rank", "match": {"rank": 0},
                            "action": "device_loss", "device": 2,
                            "revive_after": 2}], seed=7)
    with pytest.raises(faults.InjectedDeviceLoss):
        faults.check("spmd.rank", rank=0, backend="thread")
    m = elastic.manager()
    res = m.probe()                        # tick 1: still down
    assert 2 in res["down"]
    res = m.probe()                        # tick 2: revives
    assert res["down"] == []


def test_multihost_heartbeat_single_process_degrades():
    assert multihost.heartbeat() is False
    assert multihost.down_peer_processes() == set()


def test_stale_peer_process_downs_its_ranks(monkeypatch):
    m = elastic.manager()
    m.probe()
    monkeypatch.setattr(multihost, "down_peer_processes",
                        lambda stale_s=30.0: {0})
    res = m.probe()
    # on this harness every virtual device belongs to process 0
    assert res["down"] == list(range(8))


# ---------------------------------------------------------------------------
# the serving chaos leg
# ---------------------------------------------------------------------------


def test_chaos_device_loss_mid_batch_all_requests_resolve(monkeypatch, rng):
    """Seeded DA_TPU_FAULT_PLAN kills a device mid-batch: the recovery
    executor probes, shrinks the resident DArray off the dead rank, and
    retries; every in-flight request resolves to a correct result or a
    typed error (zero hangs), shed requests carry retry_after, recovery
    counters are recorded, and the leak gate (conftest) drains."""
    plan = [{"site": "serve.dispatch", "action": "device_loss", "at": 2,
             "count": 1, "device": 3, "revive_after": 3}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "1234")
    faults.configure()

    base = rng.standard_normal((8, 8))
    d = dat.distribute(base)
    retries0 = tm.counter_value("recovery.retries", verdict="device_loss")

    def ep(xs):
        resident = dat.gather(d)       # resident sharded state
        return [float(resident.sum() + np.sum(x)) for x in xs]

    expect_base = float(base.sum())
    srv = serve.Server(_cfg(max_batch=4, flush_s=0.01),
                       policy=_fast_policy())
    try:
        srv.register("score", ep)
        # wave 1 (dispatch invocation 1: clean), wave 2 (invocation 2:
        # the plan kills device 3 mid-batch; recovery shrinks + retries)
        for wave in range(2):
            futs = [srv.submit("score", np.full((2,), float(i)))
                    for i in range(4)]
            for i, f in enumerate(futs):
                assert f.result(timeout=30) == pytest.approx(
                    expect_base + 2.0 * i), f"wave {wave} wrong result"
        # the shed path still carries retry_after under chaos
        srv.set_quota("greedy", rate=1.0, burst=1.0)
        assert srv.submit("score", np.zeros(2),
                          tenant="greedy").result(timeout=30) == \
            pytest.approx(expect_base)
        with pytest.raises(Overloaded) as ei:
            srv.submit("score", np.zeros(2), tenant="greedy")
        assert ei.value.retry_after > 0
        assert srv.drain(timeout=10)
    finally:
        srv.close()
    # the fault really fired, was classified device_loss, and recovery
    # retried after shrinking the resident array off the dead rank
    hist = faults.history()
    assert [h["action"] for h in hist] == ["device_loss"]
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") > retries0
    assert 3 not in {int(p) for p in d.pids.flat}, \
        "resident state still touches the dead device"
    assert tm.counter_value("serve.completed", endpoint="score") >= 9
    dat.close(d)


def test_chaos_unretryable_failure_resolves_typed(monkeypatch):
    # a failure the verdict table refuses to retry (divergence marker in
    # the message) must fail the batch typed, never hang the futures
    plan = [{"site": "serve.dispatch", "action": "raise", "at": 1,
             "count": -1}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "7")
    faults.configure()
    srv = serve.Server(_cfg(max_batch=2, flush_s=0.0),
                       policy=_fast_policy(max_retries=1))
    try:
        srv.register("echo", lambda xs: xs)
        futs = [srv.submit("echo", i) for i in range(4)]
        for f in futs:
            with pytest.raises(RequestFailed) as ei:
                f.result(timeout=30)
            assert isinstance(ei.value.__cause__, faults.InjectedFault)
    finally:
        srv.close()
    assert tm.counter_value("serve.failed", endpoint="echo") >= 4


# ---------------------------------------------------------------------------
# telemetry surface
# ---------------------------------------------------------------------------


def test_serving_metrics_and_spans_recorded():
    with serve.Server(_cfg()) as srv:
        srv.register("echo", lambda xs: xs)
        for i in range(6):
            assert srv.submit("echo", i).result(timeout=10) == i
    assert tm.counter_value("serve.admitted", tenant="default") >= 6
    assert tm.counter_value("serve.batches", endpoint="echo") >= 1
    assert tm.gauge_value("serve.queue_depth") == 0
    assert "serve.dispatch" in tm.span_stats()


# ---------------------------------------------------------------------------
# ragged / streaming payload signatures (the decode service's traffic)
# ---------------------------------------------------------------------------


def test_payload_key_ragged_sequences_never_coalesce():
    k = serve.payload_key
    # variable-length prompts: lists of different lengths are distinct
    assert k([1, 2]) != k([1, 2, 3])
    assert k([1, 2]) == k([9, 9])
    # object-dtype (ragged) arrays key elementwise, not by (shape, dtype)
    a = np.empty(2, dtype=object)
    a[0], a[1] = [1, 2], [3, 4, 5]
    b = np.empty(2, dtype=object)
    b[0], b[1] = [7, 8, 9], [1]
    assert k(a) != k(b)               # different inner lengths
    c = np.empty(2, dtype=object)
    c[0], c[1] = [5, 6], [7, 8, 9]
    assert k(a) == k(c)               # same ragged profile coalesces
    assert k(a)[0] == "array_obj"
    # streaming payloads (generators) key by type — opaque, one class
    assert k(x for x in [1]) == k(x for x in [2, 3])


def test_ragged_prompts_batch_safely_end_to_end():
    """An endpoint that stacks its batch would crash on a mixed-length
    batch; the key must keep every dispatched batch homogeneous."""
    def ep(xs):
        stacked = np.stack([np.asarray(x) for x in xs])   # throws if ragged
        return [int(r.sum()) for r in stacked]

    with serve.Server(_cfg(flush_s=0.02, max_batch=8)) as srv:
        srv.register("sum", ep)
        prompts = [[1] * (2 + i % 3) for i in range(12)]
        futs = [srv.submit("sum", p) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=10) == sum(p)


# ---------------------------------------------------------------------------
# per-endpoint latency windows + eviction-aware HBM retry_after
# ---------------------------------------------------------------------------


def test_per_endpoint_latency_window_and_p99_gauge():
    cfg = _cfg(endpoint_latency_windows={"fast": 4})
    with serve.Server(cfg) as srv:
        srv.register("fast", lambda xs: xs)
        srv.register("slow", lambda xs: xs, latency_window=8)
        for i in range(6):
            assert srv.submit("fast", i).result(timeout=10) == i
            assert srv.submit("slow", i).result(timeout=10) == i
        adm = srv._admission
        # ServeConfig map and register() override both take effect
        assert adm.endpoint_latency("fast")._samples.maxlen == 4
        assert adm.endpoint_latency("slow")._samples.maxlen == 8
        assert adm.endpoint_latency("other")._samples.maxlen == \
            adm.window                  # unconfigured: the global size
        assert adm.endpoint_latency("fast").count() == 4   # window rolled
    # the per-endpoint p99 gauge carries the endpoint label; the
    # unlabeled gauge stays the global shed signal
    assert tm.gauge_value("serve.request_p99_s", endpoint="fast") >= 0
    assert tm.gauge_value("serve.request_p99_s", endpoint="slow") >= 0
    assert tm.gauge_value("serve.request_p99_s") is not None


def test_hbm_shed_retry_after_accounts_reclaimable(rng):
    d = dat.distribute(rng.standard_normal((16, 16)))
    try:
        live = tmem.live_bytes()
        assert live > 0

        def _ctl(**kw):
            c = serve.AdmissionController(
                max_queue=64, tenant_rate=1e6, tenant_burst=1e6,
                hbm_budget_bytes=live, hbm_shed_fraction=0.5,
                max_batch=1, **kw)
            for _ in range(8):
                c.latency.record(2.0)   # slow drain: estimate >> floor
            return c

        # without a reclaimable signal the shed ships the drain estimate
        slow = _ctl()
        with pytest.raises(Overloaded) as e1:
            slow.admit("t", queue_depth=2)
        assert e1.value.reason == "hbm"
        assert e1.value.retry_after > slow.min_retry_after
        # with the pressure fully reclaimable (idle-evictable KV pages),
        # the honest retry_after is the floor: eviction clears at the
        # next sweep, not at queue-drain pace
        fast = _ctl(reclaimable_fn=lambda: live)
        with pytest.raises(Overloaded) as e2:
            fast.admit("t", queue_depth=2)
        assert e2.value.retry_after == fast.min_retry_after
        assert "reclaimable by eviction" in str(e2.value)
        # a broken reclaimable callback degrades to the conservative path
        broken = _ctl(reclaimable_fn=lambda: 1 / 0)
        with pytest.raises(Overloaded) as e3:
            broken.admit("t", queue_depth=2)
        assert e3.value.retry_after == e1.value.retry_after
    finally:
        dat.close(d)
