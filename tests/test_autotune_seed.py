"""The tracked AUTOTUNE_SEED.json must be loaded under the live cache.

The seed ships hardware-measured winners with device-fenced keys
(VERDICT round-4 weak 3: without it, the GEMM/flash dispatch is inert on
a fresh checkout until the user's first tune).  Pin that the seed file
exists, parses, carries only device-fenced keys, and is visible through
``autotune.get`` after a registry reset — with the live cache taking
precedence on collision.
"""

import json
import os

_SEED_REFRESH_TOOL = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools", "seed_refresh.py")

from distributedarrays_tpu.utils import autotune


def _reload_fresh(monkeypatch):
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", False)


def test_seed_file_parses_and_is_device_fenced():
    with open(autotune.seed_path()) as f:
        data = json.load(f)
    assert isinstance(data, dict) and data
    for kernel, entries in data.items():
        for key in entries:
            # device_key_for appends "<platform>|<device_kind>"; the
            # shipped seed may hold HARDWARE winners only — a cpu/
            # interpret-mode winner in the tracked file would be exactly
            # the foreign-platform leakage the fence exists to stop
            assert len(key.split("|")) >= 2, (kernel, key)
            platform = key.split("|")[-2]
            assert platform in ("tpu", "gpu", "axon"), (kernel, key)


def test_seed_refresh_allowlist_matches_this_fence():
    # tools/seed_refresh.py promotes live-cache entries into the seed;
    # its hardware allowlist and this test's fence must be the same set
    # or the tool can write a seed this suite rejects
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "seed_refresh", _SEED_REFRESH_TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert set(mod._HW_PLATFORMS) == {"tpu", "gpu", "axon"}


def _load_tool():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "seed_refresh", _SEED_REFRESH_TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_seed_refresh_gemm_gate_matches_kernel_owner():
    # the tool's _GEMM_KERNELS gate (which kernels go through the
    # dispatch-validity filter) must agree with the predicate's own
    # kernel set, or a new GEMM kernel's winners would promote
    # unvalidated (or a non-GEMM kernel would be import-gated for
    # nothing)
    from distributedarrays_tpu.ops.pallas_gemm import entry_valid_for_seed
    mod = _load_tool()
    probe = "256|256|256|float32|float32|tpu|x"
    for k in mod._GEMM_KERNELS:
        assert entry_valid_for_seed(k, probe, [128, 128, 128]) is not None, k
    assert entry_valid_for_seed("flash_attention", probe, [128, 128]) is None
    assert mod._dispatch_valid("flash_attention", probe, [128, 128]) is None


def test_seed_refresh_filters_dispatch_invalid_gemm_winners(tmp_path):
    # a winner that _resolve_block would reject at dispatch (over-VMEM
    # tiling, broken alignment) must not ship into the tracked seed
    # (ADVICE round-5: pre-VMEM-fix winners were dead entries)
    mod = _load_tool()
    mod.CACHE = tmp_path / "AUTOTUNE_CACHE.json"
    mod.SEED = tmp_path / "AUTOTUNE_SEED.json"
    # an already-shipped dead entry (committed pre-predicate) must be
    # PRUNED, not just blocked at promotion — otherwise --dry-run keeps
    # reporting the seed current while dispatch rejects it forever
    mod.SEED.write_text(json.dumps({
        "pallas_matmul_int8": {
            "4096|4096|4096|int8|tpu|TPU v5 lite": [8, 128, 128]},
    }))
    mod.CACHE.write_text(json.dumps({
        "pallas_matmul": {
            # valid: fits VMEM, aligned, divides
            "4096|4096|4096|float32|float32|tpu|TPU v5 lite":
                [512, 512, 512],
            # over the scoped-VMEM budget at bf16 2048^2 blocks
            "4096|4096|4096|bfloat16|bfloat16|tpu|TPU v5 lite":
                [2048, 2048, 1024],
        },
        "pallas_matmul_int8": {
            # m block % 32 != 0 — Mosaic int8 alignment violation
            "4096|4096|4096|int8|tpu|TPU v5 lite": [8, 128, 128],
        },
    }))
    assert mod.main() == 0
    seed = json.loads(mod.SEED.read_text())
    assert seed == {"pallas_matmul": {
        "4096|4096|4096|float32|float32|tpu|TPU v5 lite": [512, 512, 512]}}


def test_seed_entries_visible_after_registry_reset(monkeypatch):
    with open(autotune.seed_path()) as f:
        data = json.load(f)
    kernel = next(iter(data))
    key = next(iter(data[kernel]))
    _reload_fresh(monkeypatch)
    got = autotune.get(kernel, key)
    assert got is not None
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", False)


def test_live_cache_overrides_seed(monkeypatch, tmp_path):
    with open(autotune.seed_path()) as f:
        data = json.load(f)
    kernel = next(iter(data))
    key = next(iter(data[kernel]))
    live = tmp_path / "live.json"
    live.write_text(json.dumps({kernel: {key: [7, 7]}}))
    monkeypatch.setenv("DAT_AUTOTUNE_CACHE", str(live))
    _reload_fresh(monkeypatch)
    assert autotune.get(kernel, key) == [7, 7]
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", False)


def test_seed_refresh_rc_contract(tmp_path):
    # the tool's exit codes are a CI contract: 0 = current/merged,
    # 1 = --dry-run found stale entries, 2 = unreadable input (must be
    # a diagnostic, not a traceback)
    import json as _json
    import subprocess
    import sys as _sys
    tool = _SEED_REFRESH_TOOL

    def run_in(workdir, *args):
        # run a COPY of the tool from a sandbox repo root so the real
        # AUTOTUNE_SEED.json is never touched
        import shutil
        tooldir = workdir / "tools"
        tooldir.mkdir(exist_ok=True)
        shutil.copyfile(tool, tooldir / "seed_refresh.py")
        return subprocess.run(
            [_sys.executable, str(tooldir / "seed_refresh.py"), *args],
            capture_output=True, text=True, cwd=workdir)

    # no cache at all -> rc 0
    r = run_in(tmp_path)
    assert r.returncode == 0 and "nothing to merge" in r.stdout

    # corrupt cache -> rc 2 with a clean diagnostic
    (tmp_path / "AUTOTUNE_CACHE.json").write_text("{truncated")
    r = run_in(tmp_path)
    assert r.returncode == 2 and "unreadable" in r.stdout
    assert "Traceback" not in r.stderr

    # stale seed + --dry-run -> rc 1 and no write
    (tmp_path / "AUTOTUNE_CACHE.json").write_text(_json.dumps(
        {"k": {"1|2|tpu|TPU v5 lite": [8, 8]}}))
    r = run_in(tmp_path, "--dry-run")
    assert r.returncode == 1 and not (tmp_path / "AUTOTUNE_SEED.json").exists()

    # corrupt SEED next to a valid cache -> the other rc-2 branch
    (tmp_path / "AUTOTUNE_SEED.json").write_text("{truncated")
    r = run_in(tmp_path)
    assert r.returncode == 2 and "unreadable" in r.stdout
    assert "Traceback" not in r.stderr
    (tmp_path / "AUTOTUNE_SEED.json").unlink()

    # real merge -> rc 0, hardware entry written, cpu entry excluded
    (tmp_path / "AUTOTUNE_CACHE.json").write_text(_json.dumps(
        {"k": {"1|2|tpu|TPU v5 lite": [8, 8],
               "1|2|cpu|cpu": [4, 4]}}))
    r = run_in(tmp_path)
    assert r.returncode == 0
    seed = _json.loads((tmp_path / "AUTOTUNE_SEED.json").read_text())
    assert seed == {"k": {"1|2|tpu|TPU v5 lite": [8, 8]}}
