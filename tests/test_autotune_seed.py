"""The tracked AUTOTUNE_SEED.json must be loaded under the live cache.

The seed ships hardware-measured winners with device-fenced keys
(VERDICT round-4 weak 3: without it, the GEMM/flash dispatch is inert on
a fresh checkout until the user's first tune).  Pin that the seed file
exists, parses, carries only device-fenced keys, and is visible through
``autotune.get`` after a registry reset — with the live cache taking
precedence on collision.
"""

import json
import os

from distributedarrays_tpu.utils import autotune


def _reload_fresh(monkeypatch):
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", False)


def test_seed_file_parses_and_is_device_fenced():
    with open(autotune.seed_path()) as f:
        data = json.load(f)
    assert isinstance(data, dict) and data
    for kernel, entries in data.items():
        for key in entries:
            # device_key_for appends "<platform>|<device_kind>"; the
            # shipped seed may hold HARDWARE winners only — a cpu/
            # interpret-mode winner in the tracked file would be exactly
            # the foreign-platform leakage the fence exists to stop
            assert len(key.split("|")) >= 2, (kernel, key)
            platform = key.split("|")[-2]
            assert platform in ("tpu", "gpu", "axon"), (kernel, key)


def test_seed_refresh_allowlist_matches_this_fence():
    # tools/seed_refresh.py promotes live-cache entries into the seed;
    # its hardware allowlist and this test's fence must be the same set
    # or the tool can write a seed this suite rejects
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "seed_refresh", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "seed_refresh.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert set(mod._HW_PLATFORMS) == {"tpu", "gpu", "axon"}


def test_seed_entries_visible_after_registry_reset(monkeypatch):
    with open(autotune.seed_path()) as f:
        data = json.load(f)
    kernel = next(iter(data))
    key = next(iter(data[kernel]))
    _reload_fresh(monkeypatch)
    got = autotune.get(kernel, key)
    assert got is not None
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", False)


def test_live_cache_overrides_seed(monkeypatch, tmp_path):
    with open(autotune.seed_path()) as f:
        data = json.load(f)
    kernel = next(iter(data))
    key = next(iter(data[kernel]))
    live = tmp_path / "live.json"
    live.write_text(json.dumps({kernel: {key: [7, 7]}}))
    monkeypatch.setenv("DAT_AUTOTUNE_CACHE", str(live))
    _reload_fresh(monkeypatch)
    assert autotune.get(kernel, key) == [7, 7]
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", False)
