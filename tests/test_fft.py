"""Distributed FFT tests: the all-to-all transpose algorithm on the
collective substrate (no reference analog — beyond-reference spectral
ops), every path against numpy oracles."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat


def test_dfft_resident_axis(rng):
    A = rng.standard_normal((32, 16)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(dat.dfft(d, axis=1))
    np.testing.assert_allclose(got, np.fft.fft(A, axis=1),
                               rtol=1e-4, atol=1e-4)
    dat.d_closeall()


def test_dfft_sharded_axis_all_to_all(rng):
    A = rng.standard_normal((32, 16)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(dat.dfft(d, axis=0))
    np.testing.assert_allclose(got, np.fft.fft(A, axis=0),
                               rtol=1e-4, atol=1e-4)
    dat.d_closeall()


def test_dfft2_roundtrip_keeps_layout(rng):
    A = rng.standard_normal((32, 16)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    f2 = dat.dfft2(d)
    np.testing.assert_allclose(np.asarray(f2), np.fft.fft2(A),
                               rtol=1e-3, atol=1e-3)
    back = dat.difft2(f2)
    np.testing.assert_allclose(np.asarray(back).real, A,
                               rtol=1e-4, atol=1e-4)
    assert back.cuts == d.cuts
    dat.d_closeall()


def test_dfft_uneven_host_path_keeps_cuts(rng):
    V = dat.distribute(rng.standard_normal(50).astype(np.float32),
                       procs=range(4))
    got = dat.dfft(V)
    np.testing.assert_allclose(
        np.asarray(got), np.fft.fft(np.asarray(V)).astype(np.complex64),
        rtol=1e-3, atol=1e-3)
    assert got.cuts == V.cuts
    np.testing.assert_allclose(np.asarray(dat.difft(got)).real,
                               np.asarray(V), rtol=1e-4, atol=1e-4)
    dat.d_closeall()


def test_dfft_2d_grid_host_path(rng):
    A = rng.standard_normal((32, 16)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    got = np.asarray(dat.dfft(d, axis=0))
    np.testing.assert_allclose(got, np.fft.fft(A, axis=0).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    dat.d_closeall()


def test_dfft_validation(rng):
    d = dat.dzeros((8, 8), procs=range(4), dist=(4, 1))
    with pytest.raises(ValueError, match="axis"):
        dat.dfft(d, axis=3)
    with pytest.raises(TypeError, match="DArray"):
        dat.dfft(np.zeros(4))
    with pytest.raises(ValueError, match="2-D"):
        dat.dfft2(dat.dzeros((8,), procs=range(4)))
    dat.d_closeall()


def test_dfft_resident_axis_non_divisible_stays_compiled(rng):
    # (32, 10) over 8 ranks: axis 1 resident -> compiled path, no warning
    # even though 10 % 8 != 0 (divisibility only matters when the
    # transform axis is the sharded one)
    import warnings
    A = rng.standard_normal((32, 10)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        got = np.asarray(dat.dfft(d, axis=1))
    np.testing.assert_allclose(got, np.fft.fft(A, axis=1),
                               rtol=1e-4, atol=1e-4)
    # sharded axis with non-divisible other dim -> loud host fallback
    with pytest.warns(RuntimeWarning, match="gathering"):
        got0 = np.asarray(dat.dfft(d, axis=0))
    np.testing.assert_allclose(got0, np.fft.fft(A, axis=0).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    dat.d_closeall()


def test_dfft_1d_compiled_four_step(rng):
    # n % p**2 == 0 -> the four-step Bailey path, no host gather
    import warnings
    x = rng.standard_normal(256).astype(np.float32)
    d = dat.distribute(x, procs=range(8))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        got = np.asarray(dat.dfft(d))
    np.testing.assert_allclose(got, np.fft.fft(x), rtol=1e-3, atol=1e-3)
    # inverse path roundtrips with its own twiddles/normalization
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        back = dat.difft(dat.dfft(d))
    np.testing.assert_allclose(np.asarray(back).real, x,
                               rtol=1e-4, atol=1e-4)
    assert back.cuts == d.cuts
    dat.d_closeall()


def test_dfft_1d_complex_input_compiled(rng):
    z = (rng.standard_normal(128) + 1j * rng.standard_normal(128)) \
        .astype(np.complex64)
    d = dat.distribute(z, procs=range(4))
    got = np.asarray(dat.dfft(d))
    np.testing.assert_allclose(got, np.fft.fft(z).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    dat.d_closeall()


def test_dfft_1d_not_p_squared_divisible_host_path(rng):
    # even layout (72 % 8 == 0) but 72 % 64 != 0 -> loud host fallback
    x = rng.standard_normal(72).astype(np.float32)
    d = dat.distribute(x, procs=range(8))
    with pytest.warns(RuntimeWarning, match="gathering"):
        got = np.asarray(dat.dfft(d))
    np.testing.assert_allclose(got, np.fft.fft(x).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    dat.d_closeall()
