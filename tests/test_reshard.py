"""Layout-aware reshard planner: plan correctness, plan caching, chunked
collective lowering, and the incremental-mutation fast paths.

The planner's contract: whatever strategy it picks, the result must be
byte-identical to the ``jax.device_put`` oracle; the chunked collective
path must account only its *moved* bytes (no full-array blowup); and
repeated reshards of one layout pair must hit the plan cache.
"""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import distributedarrays_tpu as dat
from distributedarrays_tpu import layout as L
from distributedarrays_tpu.parallel import reshard as R
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)


# ---------------------------------------------------------------------------
# block algebra (layout.cut_intersections / chunk_span)
# ---------------------------------------------------------------------------


def test_cut_intersections_covers_extent():
    a = [0, 13, 26, 38, 50]
    b = [0, 25, 50]
    overlaps = L.cut_intersections(a, b)
    # the overlaps tile [0, 50) exactly, in order
    assert overlaps[0][2] == 0 and overlaps[-1][3] == 50
    for (prev, nxt) in zip(overlaps, overlaps[1:]):
        assert prev[3] == nxt[2]
    # every overlap lies inside both claimed chunks
    for ai, bi, lo, hi in overlaps:
        assert a[ai] <= lo < hi <= a[ai + 1]
        assert b[bi] <= lo < hi <= b[bi + 1]


def test_cut_intersections_identity_and_mismatch():
    c = [0, 10, 20]
    assert L.cut_intersections(c, c) == [(0, 0, 0, 10), (1, 1, 10, 20)]
    with pytest.raises(ValueError):
        L.cut_intersections([0, 10], [0, 20])


def test_cut_intersections_empty_chunks():
    # empty chunks (equal cut entries) produce no overlap entries
    a = [0, 1, 2, 3, 3, 3, 3, 3, 3]          # trailing empties (sz < nc)
    b = [0, 3]
    overlaps = L.cut_intersections(a, b)
    assert [(o[0], o[2], o[3]) for o in overlaps] == \
        [(0, 0, 1), (1, 1, 2), (2, 2, 3)]


def test_chunk_span():
    cuts = [0, 13, 26, 38, 50]
    assert L.chunk_span(cuts, 12, 27) == (0, 2)
    assert L.chunk_span(cuts, 13, 26) == (1, 1)
    assert L.chunk_span(cuts, 0, 50) == (0, 3)
    assert L.chunk_span(cuts, 7, 7) == (0, -1)   # empty interval


# ---------------------------------------------------------------------------
# planner output ≡ device_put oracle (property sweep over layout pairs)
# ---------------------------------------------------------------------------


def _shardings_for(shape, grid):
    n = int(np.prod(grid))
    return L.sharding_for(list(range(n)), grid, shape)


_GRIDS_2D = [(8, 1), (1, 8), (4, 1), (1, 4), (2, 1), (1, 2), (1, 1),
             (4, 2), (2, 4)]


def test_planner_matches_device_put_oracle_2d(rng):
    # every src/dst grid pair on a divisible 2-D shape: planner result ==
    # the plain device_put oracle, whatever strategy was planned
    shape = (16, 24)
    A = rng.standard_normal(shape).astype(np.float32)
    seen = set()
    for gs, gd in itertools.product(_GRIDS_2D, _GRIDS_2D):
        src, dst = _shardings_for(shape, gs), _shardings_for(shape, gd)
        x = jax.device_put(A, src)
        plan = R.plan_reshard(x, dst)
        seen.add(plan.strategy)
        y = R.reshard(x, dst)
        assert y.sharding == dst or plan.strategy == "noop", (gs, gd)
        oracle = jax.device_put(A, dst)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(oracle)), \
            (gs, gd, plan.strategy)
    # the sweep must have exercised the planned collective lowerings,
    # not just fallbacks
    assert "all_to_all" in seen
    assert {"noop", "device_put"} <= seen


def test_planner_matches_oracle_random_uneven_cuts(rng):
    # random (often uneven / ragged) 1-D layout pairs via distribute +
    # samedist: uneven pairs take the fallback, even pairs the
    # collective — both must equal the host oracle
    for n, ps, pd in [(50, 4, 2), (64, 8, 4), (37, 4, 8), (48, 8, 8),
                      (29, 2, 4), (96, 8, 2)]:
        A = rng.standard_normal(n).astype(np.float32)
        d = dat.distribute(A, procs=list(range(ps)), dist=[ps])
        like = dat.dzeros((n,), procs=list(range(pd)), dist=[pd])
        r = dat.samedist(d, like)
        np.testing.assert_array_equal(np.asarray(r), A)
        assert [int(c) for c in r.cuts[0]] == [int(c) for c in like.cuts[0]]
        dat.d_closeall()


def test_planner_matches_oracle_skinny_vector_layouts(rng):
    # the solver loops re-seat skinny operands between operator
    # partitions every recovery attempt: (n, 1) column vectors and
    # single-row-block layouts (one grid row per rank, the degenerate
    # chunking a StencilOperator on p == nx ranks produces).  Every such
    # planner pair must equal the plain device_put oracle.
    row_grids = [(8, 1), (4, 1), (2, 1), (1, 1)]
    for shape in [(64, 1), (8, 1), (8, 8)]:    # (8, *): 1-row blocks on p=8
        A = rng.standard_normal(shape).astype(np.float32)
        for gs, gd in itertools.product(row_grids, row_grids):
            src, dst = _shardings_for(shape, gs), _shardings_for(shape, gd)
            x = jax.device_put(A, src)
            y = R.reshard(x, dst)
            assert y.sharding == dst or gs == gd, (shape, gs, gd)
            oracle = jax.device_put(A, dst)
            np.testing.assert_array_equal(np.asarray(y), np.asarray(oracle),
                                          err_msg=f"{shape} {gs}->{gd}")


def test_samedist_oracle_vector_and_single_row_blocks(rng):
    # the DArray-level leg of the same sweep: (n, 1) vectors moved with
    # samedist across rank counts, including single-row blocks (p == n)
    for n, ps, pd in [(8, 8, 2), (8, 2, 8), (64, 8, 8), (64, 8, 4)]:
        A = rng.standard_normal((n, 1)).astype(np.float32)
        d = dat.distribute(A, procs=list(range(ps)), dist=[ps, 1])
        like = dat.dzeros((n, 1), procs=list(range(pd)), dist=[pd, 1])
        r = dat.samedist(d, like)
        np.testing.assert_array_equal(np.asarray(r), A)
        assert [int(c) for c in r.cuts[0]] == [int(c) for c in like.cuts[0]]
        dat.d_closeall()


def test_planner_replicated_and_gather_strategies(rng):
    shape = (32, 16)
    A = rng.standard_normal(shape).astype(np.float32)
    sharded = _shardings_for(shape, (8, 1))
    rep = NamedSharding(sharded.mesh, P())
    x = jax.device_put(A, sharded)
    plan = R.plan_reshard(x, rep)
    assert plan.strategy == "all_gather"
    z = R.reshard(x, rep)
    np.testing.assert_array_equal(np.asarray(z), A)
    # replicated -> sharded is comm-free local slicing
    plan2 = R.plan_reshard(z, sharded)
    assert plan2.strategy == "local_slice" and plan2.moved_bytes == 0
    w = R.reshard(z, sharded)
    assert w.sharding == sharded
    np.testing.assert_array_equal(np.asarray(w), A)


def test_chunked_lowering_matches_oracle(rng, monkeypatch):
    # force tiny staging chunks so the pre-slice all_to_all chunking and
    # the chunked all_gather actually run, then check exactness
    monkeypatch.setenv("DA_TPU_RESHARD_CHUNK_MB", "0.0005")
    shape = (64, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _shardings_for(shape, (8, 1)), _shardings_for(shape, (1, 8))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "all_to_all" and plan.nchunks > 1
    y = R.reshard(x, dst, plan=plan)
    np.testing.assert_array_equal(np.asarray(y), A)
    rep = NamedSharding(src.mesh, P())
    plang = R.plan_reshard(x, rep)
    assert plang.strategy == "all_gather" and plang.nchunks > 1
    z = R.reshard(x, rep, plan=plang)
    np.testing.assert_array_equal(np.asarray(z), A)


# ---------------------------------------------------------------------------
# plan cache + telemetry
# ---------------------------------------------------------------------------


def test_plan_cache_hits_via_telemetry(telemetry_capture, rng):
    tm = telemetry_capture
    shape = (16, 8)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _shardings_for(shape, (8, 1)), _shardings_for(shape, (1, 8))
    x = jax.device_put(A, src)
    R.plan_reshard(x, dst)                    # may build or already cached
    req0 = tm.counter_value("reshard.plan_requests")
    build0 = tm.counter_value("reshard.plan_builds")
    for _ in range(5):
        R.plan_reshard(x, dst)
    assert tm.assert_counter("reshard.plan_requests", req0 + 5) == req0 + 5
    # repeated same-layout-pair planning hits the lru — zero new builds
    assert tm.counter_value("reshard.plan_builds") - build0 == 0


def test_reshard_comm_bytes_bounded_by_plan(telemetry_capture, rng):
    # peak-memory guard: the chunked path accounts exactly the plan's
    # moved bytes — never the full logical array
    tm = telemetry_capture
    shape = (64, 64)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _shardings_for(shape, (8, 1)), _shardings_for(shape, (1, 8))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "all_to_all"
    b0 = tm.comm_bytes("reshard")
    y = R.reshard(x, dst, plan=plan)
    y.block_until_ready()
    delta = tm.comm_bytes("reshard") - b0
    assert delta == plan.moved_bytes
    assert delta < plan.total_bytes           # no full-array blowup
    assert plan.moved_bytes == plan.total_bytes * 7 // 8
    # the strategy is attributed on the span and the plan event
    spans = tm.spans("reshard")
    assert any(s.get("labels", {}).get("strategy") == "all_to_all"
               for s in spans)


def test_plan_event_journaled(telemetry_capture, rng):
    tm = telemetry_capture
    shape = (8, 32)
    A = rng.standard_normal(shape).astype(np.float32)
    x = jax.device_put(A, _shardings_for(shape, (1, 8)))
    R.plan_reshard(x, _shardings_for(shape, (8, 1)))
    evs = tm.events("reshard")
    assert any(e.get("name") == "plan" and "strategy" in e for e in evs)


# ---------------------------------------------------------------------------
# rewired call sites
# ---------------------------------------------------------------------------


def test_rebind_routes_through_planner(telemetry_capture, rng):
    tm = telemetry_capture
    A = rng.standard_normal((16, 8)).astype(np.float32)
    src = dat.distribute(A, dist=(8, 1))
    dest = dat.dzeros((16, 8), dist=(1, 8))
    b0 = tm.comm_bytes("reshard")
    dat.copyto_(dest, src)                     # dest._rebind(src.garray)
    np.testing.assert_array_equal(np.asarray(dest), A)
    # moved-bytes accounting: (p-1)/p of the array, not all of it
    assert tm.comm_bytes("reshard") - b0 == 16 * 8 * 4 * 7 // 8
    dat.d_closeall()


def test_samedist_aligned_fast_path_no_copy(telemetry_capture, rng):
    tm = telemetry_capture
    a = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    b = dat.dzeros((16, 8), dtype=np.float32)
    b0 = tm.comm_bytes("reshard")
    c = dat.samedist(a, b)
    # no reshard bytes AND no buffer copy — c co-owns a's buffer
    assert tm.comm_bytes("reshard") - b0 == 0
    assert c.garray is a.garray
    # shared-ownership: closing either side must not invalidate the other
    c.close()
    assert not a.garray.is_deleted()
    np.testing.assert_array_equal(
        np.asarray(a), np.asarray(a))          # still readable
    a.close()


def test_samedist_share_released_on_rebind(rng):
    # a holder that REBINDS (fill_/mutation) leaves the share group, so
    # the remaining holder's close() must eagerly delete the old buffer
    # (regression: the token used to keep counting the departed holder
    # and pinned the buffer past every close)
    a = dat.distribute(np.ones((16, 8), np.float32))
    b = dat.dzeros((16, 8), dtype=np.float32)
    c = dat.samedist(a, b)
    shared_buf = c.garray
    a.fill_(0.0)                               # a rebinds, leaves group
    c.close()                                  # sole holder: eager delete
    assert shared_buf.is_deleted()
    np.testing.assert_allclose(np.asarray(a), 0.0)   # a unaffected
    a.close()


def test_samedist_shared_buffer_close_order_reversed(rng):
    a = dat.distribute(rng.standard_normal((8, 8)).astype(np.float32))
    ref = np.asarray(a).copy()
    b = dat.dzeros((8, 8), dtype=np.float32)
    c = dat.samedist(a, b)
    a.close()                                  # original goes first
    np.testing.assert_array_equal(np.asarray(c), ref)
    dat.d_closeall()


def test_broadcast_align_routes_through_planner(rng):
    # mismatched committed layouts in one elementwise op: the aligned arg
    # goes through _put_global -> parallel.reshard; result is correct
    A = rng.standard_normal((16, 8)).astype(np.float32)
    B = rng.standard_normal((16, 8)).astype(np.float32)
    da = dat.distribute(A, dist=(8, 1))
    db = dat.distribute(B, dist=(1, 8))
    r = da + db
    np.testing.assert_allclose(np.asarray(r), A + B, rtol=1e-6)
    dat.d_closeall()


# ---------------------------------------------------------------------------
# incremental mutation of padded (uneven) layouts
# ---------------------------------------------------------------------------


def test_incremental_slice_mutate_touches_owner_blocks_only(
        telemetry_capture, rng):
    tm = telemetry_capture
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A.copy(), procs=[0, 1, 2, 3], dist=[4])
    b0 = tm.comm_bytes("reshard")
    d[10:30] = 99.0
    want = A.copy()
    want[10:30] = 99.0
    np.testing.assert_array_equal(np.asarray(d), want)
    delta = tm.comm_bytes("reshard") - b0
    # only the touched window is accounted — sub-full-array traffic
    assert 0 < delta <= 20 * 4
    assert delta < 50 * 4
    # the update never depadded: no blocked_pad reshard events recorded
    evs = [e for e in tm.events("comm")
           if e.get("name") == "reshard" and e.get("op") == "blocked_pad"]
    assert not evs
    d.close()


def test_incremental_mutate_2d_multiblock(rng):
    B = rng.standard_normal((50, 30)).astype(np.float32)
    e = dat.distribute(B.copy(), dist=[4, 2])
    want = B.copy()
    e[7, 3:25] = 5.0
    want[7, 3:25] = 5.0
    e[4:40, 2] = np.arange(36, dtype=np.float32)
    want[4:40, 2] = np.arange(36)
    e[12:14, 14:16] = np.array([[1., 2.], [3., 4.]], np.float32)
    want[12:14, 14:16] = [[1, 2], [3, 4]]
    np.testing.assert_array_equal(np.asarray(e), want)
    # pad regions stay zero after incremental writes
    padded = np.asarray(jax.device_get(e.garray_padded))
    cuts_r, cuts_c = e.cuts
    bs = L.block_sizes(e.cuts)
    for bi in range(len(cuts_r) - 1):
        valid = cuts_r[bi + 1] - cuts_r[bi]
        np.testing.assert_allclose(
            padded[bi * bs[0] + valid:(bi + 1) * bs[0], :], 0.0)
    e.close()


def test_incremental_mutate_scalar_setitem_padded(rng):
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A.copy(), dist=[4])
    with dat.allowscalar(True):
        d[13] = 7.0
    want = A.copy()
    want[13] = 7.0
    np.testing.assert_array_equal(np.asarray(d), want)
    d.close()


def test_subdarray_copyto_incremental(rng):
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A.copy(), dist=[4])
    dat.copyto_(d[20:40], np.ones(20, np.float32))
    want = A.copy()
    want[20:40] = 1.0
    np.testing.assert_array_equal(np.asarray(d), want)
    d.close()


def test_advanced_indexing_still_full_path(rng):
    # array keys are not basic: must fall back to the full-array path and
    # stay correct
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A.copy(), dist=[4])
    idx = np.array([3, 17, 44])
    d[idx] = 0.5
    want = A.copy()
    want[idx] = 0.5
    np.testing.assert_array_equal(np.asarray(d), want)
    d.close()


def test_padded_fill_zero_redistribution(telemetry_capture, rng):
    tm = telemetry_capture
    d = dat.distribute(rng.standard_normal(50).astype(np.float32), dist=[4])
    b0 = tm.comm_bytes("reshard")
    d.fill_(5.0)
    assert tm.comm_bytes("reshard") - b0 == 0    # no depad/repad round trip
    np.testing.assert_allclose(np.asarray(d), 5.0)
    padded = np.asarray(jax.device_get(d.garray_padded))
    np.testing.assert_allclose(padded[51:52], 0.0)   # pad stays zero
    b1 = tm.comm_bytes("reshard")
    d.rand_()
    assert tm.comm_bytes("reshard") - b1 == 0
    v = np.asarray(d)
    assert v.shape == (50,) and len(np.unique(v)) > 10
    padded = np.asarray(jax.device_get(d.garray_padded))
    np.testing.assert_allclose(padded[51:52], 0.0)
    d.close()


def test_padded_fill_2d_matches_logical(rng):
    d = dat.distribute(rng.standard_normal((50, 30)).astype(np.float32),
                       dist=[4, 2])
    d.fill_(2.5)
    np.testing.assert_allclose(np.asarray(d), 2.5)
    assert float(dat.dsum(d)) == pytest.approx(50 * 30 * 2.5, rel=1e-5)
    d.close()


# ---------------------------------------------------------------------------
# device-side __eq__
# ---------------------------------------------------------------------------


def test_eq_darray_device_side_no_gather(telemetry_capture, rng):
    tm = telemetry_capture
    A = rng.standard_normal((16, 8)).astype(np.float32)
    a = dat.distribute(A)
    b = dat.distribute(A.copy())
    c = dat.distribute(A + 1.0)
    d2h0 = tm.comm_bytes("d2h")
    assert a == b
    assert not (a == c)
    assert a != c
    # the compare ran on device: no gather-sized d2h traffic
    assert tm.comm_bytes("d2h") - d2h0 == 0
    # numpy operand still works (host path)
    assert a == A
    sub = a[0:16, 0:8]
    assert sub == b
    dat.d_closeall()


def test_eq_shape_mismatch_and_foreign_types(rng):
    a = dat.distribute(rng.standard_normal((4, 4)).astype(np.float32))
    b = dat.distribute(rng.standard_normal((2, 8)).astype(np.float32))
    assert not (a == b)
    assert a != b
    # foreign type: __eq__ returns NotImplemented, Python resolves to False
    assert (a == "nope") is False
    dat.d_closeall()


# ---------------------------------------------------------------------------
# DAL007
# ---------------------------------------------------------------------------


def test_dal007_flags_cross_sharding_device_put():
    from distributedarrays_tpu.analysis import lint_source
    bad = (
        "import jax\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "def f(x, mesh):\n"
        "    return jax.device_put(x, NamedSharding(mesh, P('d0')))\n"
    )
    findings = [f for f in lint_source(bad, "pkg/ops/thing.py")
                if f.code == "DAL007"]
    assert len(findings) == 1


def test_dal007_silent_in_reshard_home_and_on_devices():
    from distributedarrays_tpu.analysis import lint_source
    src = (
        "import jax\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "def f(x, mesh):\n"
        "    return jax.device_put(x, NamedSharding(mesh, P('d0')))\n"
    )
    assert not [f for f in lint_source(
        src, "distributedarrays_tpu/parallel/reshard.py")
        if f.code == "DAL007"]
    dev = (
        "import jax\n"
        "def f(x):\n"
        "    device = jax.devices()[0]\n"
        "    return jax.device_put(x, device)\n"
    )
    assert not [f for f in lint_source(dev, "pkg/m.py")
                if f.code == "DAL007"]


def test_dal007_suppressible():
    from distributedarrays_tpu.analysis import lint_source
    src = (
        "import jax\n"
        "def f(x, sharding):\n"
        "    return jax.device_put(x, sharding)  "
        "# dalint: disable=DAL007 — justified\n"
    )
    fs = [f for f in lint_source(src, "pkg/m.py") if f.code == "DAL007"]
    assert len(fs) == 1 and fs[0].suppressed


# ---------------------------------------------------------------------------
# multi-axis chain lowering (PR 19: general per-axis collective sequences)
# ---------------------------------------------------------------------------


def test_chain_matches_oracle_multiaxis_pairs(rng):
    # same-device-set multi-axis repartitions lower to the collective
    # chain (NOT device_put) and stay bit-identical to the oracle
    shape = (48, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    for gs, gd in [((8, 1), (4, 2)), ((4, 2), (8, 1)), ((4, 2), (2, 4)),
                   ((2, 4), (4, 2)), ((1, 8), (4, 2)), ((2, 2), (4, 1))]:
        src, dst = _shardings_for(shape, gs), _shardings_for(shape, gd)
        x = jax.device_put(A, src)
        plan = R.plan_reshard(x, dst)
        assert plan.strategy == "chain", (gs, gd, plan.strategy,
                                          plan.reason)
        assert all(s[0] in ("a2a", "gather", "slice") for s in plan.steps)
        y = R.reshard(x, dst)
        assert y.sharding.is_equivalent_to(dst, y.ndim), (gs, gd)
        np.testing.assert_array_equal(
            np.asarray(y), np.asarray(jax.device_put(A, dst)))


def test_chain_two_axis_repartition_halves_moved_bytes(rng):
    # the acceptance shape: a (p,1) -> (p/2,2) repartition is ONE
    # axis-wise all_to_all moving exactly half the array
    shape = (64, 64)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _shardings_for(shape, (8, 1)), _shardings_for(shape, (4, 2))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "chain"
    assert [s[0] for s in plan.steps] == ["a2a"]
    assert plan.moved_bytes * 2 == plan.total_bytes
    np.testing.assert_array_equal(
        np.asarray(R.reshard(x, dst)),
        np.asarray(jax.device_put(A, dst)))


def test_chain_mesh_axis_transpose(rng):
    # P(d0,d1) -> P(d1,d0) on one (4,2) mesh: gather + a2a + slice
    shape = (48, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    mesh = L.mesh_for(list(range(8)), (4, 2))
    src = NamedSharding(mesh, P("d0", "d1"))
    dst = NamedSharding(mesh, P("d1", "d0"))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "chain", plan.reason
    assert "a2a" in [s[0] for s in plan.steps]
    y = R.reshard(x, dst)
    assert y.sharding.is_equivalent_to(dst, y.ndim)
    np.testing.assert_array_equal(
        np.asarray(y), np.asarray(jax.device_put(A, dst)))


def test_chain_matches_oracle_3d_mesh(rng):
    # a 3-D (2,2,2) mesh flattening onto a 2-D grid
    shape = (8, 8, 8)
    A = rng.standard_normal(shape).astype(np.float32)
    mesh = L.mesh_for(list(range(8)), (2, 2, 2))
    src = NamedSharding(mesh, P("d0", "d1", "d2"))
    dst = _shardings_for(shape, (2, 4, 1))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "chain", plan.reason
    np.testing.assert_array_equal(
        np.asarray(R.reshard(x, dst)),
        np.asarray(jax.device_put(A, dst)))


def test_chain_partial_replication_is_comm_free(rng):
    # P(None,d1) -> P(d0,d1): every rank already holds its block — the
    # chain is all local slices and the plan predicts zero moved bytes
    shape = (48, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    mesh = L.mesh_for(list(range(8)), (4, 2))
    src = NamedSharding(mesh, P(None, "d1"))
    dst = NamedSharding(mesh, P("d0", "d1"))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "chain", plan.reason
    assert all(s[0] == "slice" for s in plan.steps)
    assert plan.moved_bytes == 0
    np.testing.assert_array_equal(
        np.asarray(R.reshard(x, dst)),
        np.asarray(jax.device_put(A, dst)))


def test_chain_staging_bounded_under_tiny_chunk_target(
        rng, monkeypatch, telemetry_capture):
    # forced ~512 B chunk target: every chain step is chunked and the
    # OBSERVED staging watermark stays within 2x the budget
    tm = telemetry_capture
    monkeypatch.setenv("DA_TPU_RESHARD_CHUNK_MB", "0.0005")
    from distributedarrays_tpu.telemetry import memory as tmem
    shape = (64, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    mesh = L.mesh_for(list(range(8)), (4, 2))
    target = 2 * int(0.0005 * 2**20)
    for src, dst in [
            (_shardings_for(shape, (8, 1)), _shardings_for(shape, (4, 2))),
            (NamedSharding(mesh, P("d0", "d1")),
             NamedSharding(mesh, P("d1", "d0")))]:
        x = jax.device_put(A, src)
        plan = R.plan_reshard(x, dst)
        assert plan.strategy == "chain"
        assert plan.nchunks > 1
        assert plan.staging_bytes <= target, plan.steps
        y = R.reshard(x, dst)
        np.testing.assert_array_equal(
            np.asarray(y), np.asarray(jax.device_put(A, dst)))
    assert 0 < tmem.staging_peak("reshard.chain") <= target


def test_gather_put_on_replicated_subset(rng):
    # a shrink onto a strict device subset whose target is replicated
    # (the uneven-survivor elastic shape): chain-gather on the source
    # mesh, then a comm-free restriction
    shape = (48, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    src = _shardings_for(shape, (8, 1))
    dst = NamedSharding(L.mesh_for(list(range(6)), (6, 1)), P(None, None))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "gather_put", plan.reason
    y = R.reshard(x, dst)
    assert {d.id for d in y.sharding.device_set} == set(range(6))
    np.testing.assert_array_equal(
        np.asarray(y), np.asarray(jax.device_put(A, dst)))


def test_chain_plan_stamps_domain_byte_split(rng, monkeypatch):
    # with two failure domains split mid-mesh, the a2a along the major
    # axis crosses domains and the plan's intra/cross stamps say so
    from distributedarrays_tpu.resilience import domains
    monkeypatch.setenv("DA_TPU_DOMAINS", "4,4")
    domains.reset()
    try:
        shape = (64, 64)
        A = rng.standard_normal(shape).astype(np.float32)
        src = _shardings_for(shape, (8, 1))
        dst = _shardings_for(shape, (4, 2))
        x = jax.device_put(A, src)
        plan = R.plan_reshard(x, dst)
        assert plan.strategy == "chain"
        # the single a2a runs along the minor (intra-domain) digit: the
        # sub-groups {0,1},{2,3},... never span the 4|4 domain boundary
        assert plan.cross_bytes == 0
        assert plan.intra_bytes == plan.moved_bytes > 0
        # transpose on the (4,2) mesh must touch the major axis -> the
        # gather/a2a sub-groups span both domains
        mesh = L.mesh_for(list(range(8)), (4, 2))
        tsrc = NamedSharding(mesh, P("d0", "d1"))
        tdst = NamedSharding(mesh, P("d1", "d0"))
        xt = jax.device_put(A, tsrc)
        tplan = R.plan_reshard(xt, tdst)
        assert tplan.strategy == "chain"
        assert tplan.cross_bytes > 0
        assert tplan.intra_bytes + tplan.cross_bytes == tplan.moved_bytes
        np.testing.assert_array_equal(
            np.asarray(R.reshard(xt, tdst)),
            np.asarray(jax.device_put(A, tdst)))
    finally:
        domains.reset()


def test_collective_fallback_counter_reason_labels(telemetry_capture, rng):
    tm = telemetry_capture
    shape = (48, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    # device sets differ with a properly-sharded destination: counted
    # under reason=device_set
    src = _shardings_for(shape, (8, 1))
    dst = _shardings_for(shape, (4, 1))
    x = jax.device_put(A, src)
    c0 = tm.counter_value("reshard.collective_fallbacks",
                          reason="device_set")
    R.reshard(x, dst)
    assert tm.counter_value("reshard.collective_fallbacks",
                            reason="device_set") == c0 + 1
    # extended dtypes (PRNG keys) force device_put under reason=dtype
    keys = jax.random.split(jax.random.key(0), 48)
    ks = jax.device_put(keys, _shardings_for((48,), (8,)))
    kdst = NamedSharding(L.mesh_for(list(range(8)), (8,)), P(None))
    d0 = tm.counter_value("reshard.collective_fallbacks", reason="dtype")
    R.reshard(ks, kdst)
    assert tm.counter_value("reshard.collective_fallbacks",
                            reason="dtype") == d0 + 1


# --- uneven multi-axis cuts at the planner level (uneven NamedShardings
# are not constructible under this jax, so the ceil-pad lowering is
# exercised against synthetic owner maps) ---


class _FakeDev:
    def __init__(self, i):
        self.id = i


class _FakeSharding:
    """Minimal devices_indices_map carrier: one rank per block, blocks in
    row-major grid order over explicit per-dim cut vectors."""

    def __init__(self, cuts_per_dim, ranks):
        self.cuts = cuts_per_dim
        self.ranks = ranks

    def devices_indices_map(self, shape):
        grids = [len(c) - 1 for c in self.cuts]
        out = {}
        for r, coord in zip(self.ranks,
                            itertools.product(*[range(g) for g in grids])):
            out[_FakeDev(r)] = tuple(
                slice(self.cuts[d][coord[d]], self.cuts[d][coord[d] + 1])
                for d in range(len(grids)))
        return out


def _ceil_cuts(n, g):
    c = -(-n // g)
    return [min(k * c, n) for k in range(g + 1)]


def test_pad_chain_plans_for_agreeing_ceil_cuts():
    # n=14 over 8 then 4 chunks: both ceil layouts pad to 16 -> the
    # planner lowers through the padded even chain
    tgt = R._chunk_target_bytes()
    p = R._build_plan(
        (14, 8), 4,
        _FakeSharding([_ceil_cuts(14, 8), [0, 8]], list(range(8))),
        _FakeSharding([_ceil_cuts(14, 4), [0, 4, 8]], list(range(8))),
        tgt)
    assert p.strategy == "chain"
    assert p.pad_shape == (16, 8)
    assert [s[0] for s in p.steps] == ["a2a"]


def test_pad_chain_rejects_disagreeing_or_arbitrary_cuts():
    tgt = R._chunk_target_bytes()
    # ceil pads disagree (52 vs 50): fallback, counted as uneven
    p = R._build_plan(
        (50, 2), 4,
        _FakeSharding([_ceil_cuts(50, 4), [0, 2]], list(range(4))),
        _FakeSharding([_ceil_cuts(50, 2), [0, 1, 2]], list(range(4))),
        tgt)
    assert p.strategy == "device_put"
    assert R._fallback_reason(p.reason) == "uneven"
    # arbitrary (non-ceil) cuts: fallback, counted as uneven
    p = R._build_plan(
        (16,), 4,
        _FakeSharding([[0, 3, 16]], [0, 1]),
        _FakeSharding([[0, 8, 16]], [0, 1]), tgt)
    assert p.strategy == "device_put"
    assert R._fallback_reason(p.reason) == "uneven"


def test_fallback_reason_canonicalization():
    fr = R._fallback_reason
    assert fr("uneven source shards") == "uneven"
    assert fr("dst dim not divisible") == "uneven"
    assert fr("device sets differ") == "device_set"
    assert fr("source not replicated on dst devices") == "device_set"
    assert fr("extended dtype") == "dtype"
    assert fr("multi-dim chunk grid") == "multi_axis"
    assert fr("replicated blocks or rank order differs") == "multi_axis"
    assert fr("opaque layouts (ValueError)") == "shape"
