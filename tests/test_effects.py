"""Interprocedural SPMD effect inference (dalint v3) tests.

Three halves, cross-validated against each other:

- **mutant corpus**: ≥10 seeded divergent SPMD programs, every one
  flagged by DAL010/DAL012 *statically* (with call path + per-arm
  signatures in the finding) AND — for the runtime-executable subset —
  aborted by the runtime ``DivergenceChecker`` under
  ``DA_TPU_CHECK_DIVERGENCE=1``.  Static catches what runtime catches.
- **clean corpus**: rank-symmetric idioms (symmetric ``bcast``,
  rank-gated point-to-point, uniform loops, equivalent arms via
  different helpers) stay silent statically AND run divergence-free
  with the checker armed.  No false positives on the happy corpus.
- **machinery**: callgraph resolution, signature algebra/rendering, the
  ``effects``/``verify-spmd``/``rules --json`` CLI verbs, the
  content-hash lint cache, and the process-backend coverage-gap
  journaling.

The corpus programs are module-level *source strings*: the same text is
linted by ``effects.analyze_sources`` and ``exec``'d for the runtime
run, so the two checkers are proven against literally the same program.
(As strings they are also invisible to the repo's own ``verify-spmd``
sweep — no suppressions needed here.)
"""

import io
import json
import textwrap
import tokenize
from pathlib import Path

import pytest

from distributedarrays_tpu import telemetry
from distributedarrays_tpu.analysis import (CollectiveDivergenceError,
                                            checking)
from distributedarrays_tpu.analysis import effects
from distributedarrays_tpu.analysis.cache import LintCache
from distributedarrays_tpu.analysis.callgraph import CallGraph
from distributedarrays_tpu.analysis.engine import lint_source
from distributedarrays_tpu.parallel import spmd_mode as S

REPO = Path(__file__).resolve().parents[1]

PRELUDE = "from distributedarrays_tpu.parallel import spmd_mode as S\n"


@pytest.fixture
def divergence_on(monkeypatch):
    monkeypatch.setenv("DA_TPU_CHECK_DIVERGENCE", "1")
    assert checking()


def static_findings(src, code=None, path="corpus.py"):
    rep = effects.analyze_sources([(path, textwrap.dedent(src))])
    if code is None:
        return rep.findings
    return [f for f in rep.findings if f.code == code]


def run_corpus(src, entry="prog", pids=(0, 1)):
    ns = {}
    exec(compile(textwrap.dedent(src), "corpus.py", "exec"), ns)
    return S.spmd(ns[entry], pids=list(pids))


# ---------------------------------------------------------------------------
# the mutant corpus: seeded divergent programs, all DAL010/DAL012-flagged
# ---------------------------------------------------------------------------

# name -> (source, expected code, runtime-divergent?)
DIVERGENT = {
    "direct_branch": (PRELUDE + """
def prog():
    if S.myid() == 0:
        S.barrier()
    return True
""", "DAL010", True),

    "taint_via_helper_return": (PRELUDE + """
def is_leader():
    return S.myid() == 0

def prog():
    if is_leader():
        S.barrier()
    return True
""", "DAL010", True),

    "collective_via_helper": (PRELUDE + """
def sync():
    S.barrier(tag="s")

def prog():
    if S.myid() == 0:
        sync()
    return True
""", "DAL010", True),

    "op_mismatch_arms": (PRELUDE + """
def prog():
    if S.myid() == 0:
        S.barrier()
    else:
        S.bcast("x", root=1)
    return True
""", "DAL010", True),

    "early_return_skips_collective": (PRELUDE + """
def prog():
    if S.myid() == 0:
        return None
    S.barrier()
    return True
""", "DAL010", True),

    "taint_via_parameter": (PRELUDE + """
def go(rank):
    if rank == 0:
        S.barrier()

def prog():
    go(S.myid())
    return True
""", "DAL010", True),

    "taint_via_partial": (PRELUDE + """
import functools

def go(rank):
    if rank == 0:
        S.barrier()

def prog():
    h = functools.partial(go, S.myid())
    h()
    return True
""", "DAL010", True),

    "taint_via_closure_capture": (PRELUDE + """
def prog():
    me = S.myid()
    def inner():
        if me == 0:
            S.barrier()
    inner()
    return True
""", "DAL010", True),

    "tag_mismatch_same_op": (PRELUDE + """
def prog():
    if S.myid() == 0:
        S.barrier(tag="a")
    else:
        S.barrier(tag="b")
    return True
""", "DAL010", True),

    "extra_collective_one_arm": (PRELUDE + """
def prog():
    if S.myid() == 0:
        S.barrier()
        S.bcast(1, root=0)
    else:
        S.barrier()
    return True
""", "DAL010", True),

    "two_level_call_chain": (PRELUDE + """
def leaf():
    S.barrier(tag="deep")

def mid():
    leaf()

def prog():
    if S.myid() == 0:
        mid()
    return True
""", "DAL010", True),

    "method_via_receiver_type": (PRELUDE + """
class Worker:
    def sync(self):
        S.barrier()

def prog():
    w = Worker()
    if S.myid() == 0:
        w.sync()
    return True
""", "DAL010", True),

    "gather_payload_shape": (PRELUDE + """
import numpy as np

def prog():
    me = S.myid()
    x = np.zeros((me + 1, 4), np.float32)
    S.gather_spmd(x, root=0)
    return True
""", "DAL010", True),

    "quorum_verdict_branch": (PRELUDE + """
def prog(elastic):
    verdict = elastic.partition_verdict()
    if verdict == "quorum":
        S.barrier()
    return True
""", "DAL010", False),

    "loop_bound_tainted": (PRELUDE + """
def prog():
    for _ in range(S.myid()):
        S.barrier()
    return True
""", "DAL012", True),

    "while_bound_tainted": (PRELUDE + """
def prog():
    n = S.myid()
    while n > 0:
        S.barrier()
        n -= 1
    return True
""", "DAL012", True),
}


def test_corpus_is_big_enough():
    # acceptance criterion: ≥10 seeded divergent programs DAL010-flagged
    dal010 = [k for k, (_, code, _) in DIVERGENT.items()
              if code == "DAL010"]
    assert len(dal010) >= 10


@pytest.mark.parametrize("name", sorted(DIVERGENT))
def test_divergent_corpus_statically_flagged(name):
    src, code, _rt = DIVERGENT[name]
    found = static_findings(src, code)
    assert found, f"{name}: {code} must fire"
    msg = found[0].message
    # every finding prints the call path and, for DAL010 branch
    # findings, both per-arm signatures in the runtime-report shape
    assert "call path" in msg, name
    assert "prog" in msg, name
    if code == "DAL010" and "payload" not in msg:
        assert "if-arm" in msg and "else-arm" in msg, name
    assert "deadlock" in msg, name


@pytest.mark.parametrize("name", sorted(
    k for k, (_, _, rt) in DIVERGENT.items() if rt))
def test_divergent_corpus_caught_at_runtime(name, divergence_on):
    # cross-validation: the same source the static prover flags must
    # abort under the runtime checker (static catches what runtime
    # catches — and vice versa)
    src, _code, _rt = DIVERGENT[name]
    with pytest.raises(CollectiveDivergenceError):
        run_corpus(src)


def test_dal010_prints_both_signatures():
    src, _, _ = DIVERGENT["op_mismatch_arms"]
    msg = static_findings(src, "DAL010")[0].message
    assert "barrier" in msg and "bcast" in msg
    assert "(none)" not in msg.splitlines()[0]


def test_dal010_early_return_signature_includes_continuation():
    src, _, _ = DIVERGENT["early_return_skips_collective"]
    msg = static_findings(src, "DAL010")[0].message
    # the arm that returns early has NO collectives; the fallthrough
    # arm picks up the barrier after the if — rendered like the runtime
    # per-rank sequence diff
    assert "(none)" in msg and "barrier" in msg


def test_interprocedural_call_path_printed():
    src, _, _ = DIVERGENT["two_level_call_chain"]
    msg = static_findings(src, "DAL010")[0].message
    assert "prog" in msg and "barrier(tag='deep')" in msg


# ---------------------------------------------------------------------------
# the clean corpus: rank-symmetric idioms stay silent and run clean
# ---------------------------------------------------------------------------

CLEAN = {
    "symmetric_bcast": (PRELUDE + """
def prog():
    me = S.myid()
    data = "payload" if me == 0 else None
    return S.bcast(data, root=0)
""", True),

    "rank_gated_point_to_point": (PRELUDE + """
def prog():
    me = S.myid()
    if me == 0:
        S.sendto(1, "ping")
        return "sent"
    got = S.recvfrom(0)
    return got
""", True),

    "uniform_loop": (PRELUDE + """
def prog():
    for i in range(3):
        S.barrier(tag="step")
    return True
""", True),

    "equivalent_arms_via_different_helpers": (PRELUDE + """
def sync_a():
    S.barrier(tag="x")

def sync_b():
    S.barrier(tag="x")

def prog():
    if S.myid() == 0:
        sync_a()
    else:
        sync_b()
    return True
""", True),

    "raise_arm_exempt": (PRELUDE + """
def prog(ok):
    if S.myid() == 0 and not ok:
        raise ValueError("leader bailed")
    S.barrier()
    return True
""", False),

    "uniform_gather_shape": (PRELUDE + """
import numpy as np

def prog():
    x = np.zeros((4, 4), np.float32)
    S.gather_spmd(x, root=0)
    return True
""", True),
}


@pytest.mark.parametrize("name", sorted(CLEAN))
def test_clean_corpus_statically_silent(name):
    src, _rt = CLEAN[name]
    assert static_findings(src) == [], name


@pytest.mark.parametrize("name", sorted(
    k for k, (_, rt) in CLEAN.items() if rt))
def test_clean_corpus_runs_divergence_free(name, divergence_on):
    src, _rt = CLEAN[name]
    run_corpus(src)   # must not raise


# ---------------------------------------------------------------------------
# cross-validation against the pre-existing runtime fixtures
# ---------------------------------------------------------------------------


def test_runtime_divergence_fixtures_statically_caught():
    # every seeded runtime-divergence fixture in tests/test_analysis.py
    # carries a justified DAL010 suppression; the static prover must
    # flag each of those exact lines (i.e. no suppression has rotted —
    # static coverage ⊇ the runtime corpus)
    path = REPO / "tests" / "test_analysis.py"
    src = path.read_text()
    # scan COMMENT tokens, not raw lines: source-string fixtures inside
    # tests embed the same marker text but aren't top-level code the
    # prover sees
    marked = sorted({
        tok.start[0]
        for tok in tokenize.generate_tokens(io.StringIO(src).readline)
        if tok.type == tokenize.COMMENT and "disable=DAL010" in tok.string
    })
    assert len(marked) >= 6, "the seeded fixtures moved?"
    rep = effects.analyze_sources([(str(path), src)])
    flagged = {f.line for f in rep.findings if f.code == "DAL010"}
    for line in marked:
        assert line in flagged, \
            f"fixture at test_analysis.py:{line} not statically caught"
    # and all of them are suppressed: the repo sweep stays clean
    assert all(f.suppressed for f in rep.findings
               if f.code == "DAL010")


def test_package_sweep_clean():
    # the acceptance gate itself: verify-spmd over the default surface
    # has zero unsuppressed findings and completes within budget
    targets = [str(REPO / p) for p in ("distributedarrays_tpu",
                                       "examples", "tests")
               if (REPO / p).exists()]
    rep = effects.analyze_paths(targets)
    assert not rep.truncated
    active = [f for f in rep.findings if not f.suppressed]
    assert active == [], [f.format() for f in active]


# ---------------------------------------------------------------------------
# DAL011: interprocedural mesh-context / axis checking
# ---------------------------------------------------------------------------


def test_dal011_axis_unbound_across_call():
    src = """
import jax

def body():
    jax.lax.psum(1, axis_name="model")

def prog():
    mesh = jax.make_mesh((4,), ("data",))
    with mesh:
        body()
"""
    found = static_findings(src, "DAL011")
    assert found
    msg = found[0].message
    assert "'model'" in msg and "data" in msg and "call path" in msg


def test_dal011_bound_axis_silent_across_call():
    src = """
import jax

def body():
    jax.lax.psum(1, axis_name="data")

def prog():
    mesh = jax.make_mesh((4,), ("data",))
    with mesh:
        body()
"""
    assert static_findings(src, "DAL011") == []


def test_dal011_own_mesh_stays_dal004_domain():
    # a function building its own mesh is DAL004's single-function
    # domain — DAL011 only checks axes against an *inherited* context
    src = """
import jax

def prog():
    mesh = jax.make_mesh((4,), ("data",))
    with mesh:
        jax.lax.psum(1, axis_name="model")
"""
    assert static_findings(src, "DAL011") == []
    # the same mismatch IS caught by DAL004 for the mesh ctors whose
    # axis binding it resolves statically (Mesh with literal names)
    src2 = """
from jax.sharding import Mesh

def prog(devs):
    with Mesh(devs, ("data",)):
        import jax
        jax.lax.psum(1, axis_name="model")
"""
    assert "DAL004" in [f.code for f in lint_source(
        textwrap.dedent(src2), "corpus.py")]


# ---------------------------------------------------------------------------
# signatures, algebra, rendering, CLI
# ---------------------------------------------------------------------------


def test_signature_rendering_sequence_alt_star():
    src = PRELUDE + """
def prog(flag):
    S.barrier(tag="start")
    if flag:
        S.bcast(1, root=0)
    else:
        S.scatter([1, 2], root=0)
    for i in range(3):
        S.barrier(tag="step")
"""
    out = effects.render(_sig_of(src, "prog"))
    assert "barrier(tag='start')" in out
    assert "{" in out and "|" in out and "}" in out      # alternation
    assert "(barrier(tag='step'))*" in out               # loop star
    # sequencing order is preserved
    assert out.index("barrier(tag='start')") < out.index("{")


def test_signature_empty_renders_none():
    src = "def prog():\n    return 1\n"
    assert effects.render(_sig_of(src, "prog")) == "(none)"


def _sig_of(src, fn):
    graph = CallGraph([("corpus.py", textwrap.dedent(src))])
    ana = effects._Analysis(graph)
    key = next(k for k in graph.funcs if k[2] == fn and k[1] is None)
    return ana.summarize(key, effects._Ctx(), ()).sig


def test_effects_cli_verb(tmp_path, capsys):
    from distributedarrays_tpu.analysis.__main__ import main
    f = tmp_path / "mod.py"
    f.write_text(PRELUDE + "def prog():\n    S.barrier(tag='cli')\n")
    assert main(["effects", f"{f}:prog", str(f)]) == 0
    out = capsys.readouterr().out
    assert "barrier(tag='cli')" in out
    assert main(["effects", f"{f}:nonexistent", str(f)]) == 2


def test_verify_spmd_cli_bad_then_clean(tmp_path, capsys):
    from distributedarrays_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(DIVERGENT["direct_branch"][0])
    assert main(["verify-spmd", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "DAL010" in out and "call path" in out
    good = tmp_path / "good.py"
    good.write_text(CLEAN["symmetric_bcast"][0])
    assert main(["verify-spmd", str(good)]) == 0


def test_verify_spmd_json_format(tmp_path, capsys):
    from distributedarrays_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(DIVERGENT["direct_branch"][0])
    assert main(["verify-spmd", "--format=json", str(bad)]) == 1
    rows = json.loads(capsys.readouterr().out)
    assert rows and rows[0]["code"] == "DAL010"


def test_verify_spmd_unused_suppression_rot(tmp_path, capsys):
    from distributedarrays_tpu.analysis.__main__ import main
    f = tmp_path / "rot.py"
    f.write_text("x = 1  # dalint: disable=DAL010 — silences nothing\n")
    assert main(["verify-spmd", "--warn-unused-suppressions",
                 str(f)]) == 1
    assert "DAL100" in capsys.readouterr().out


def test_rules_json_cli(capsys):
    from distributedarrays_tpu.analysis.__main__ import main
    assert main(["rules", "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    codes = {r["code"] for r in rows}
    assert {"DAL001", "DAL010", "DAL011", "DAL012"} <= codes
    assert all(r["severity"] and r["title"] for r in rows)


# ---------------------------------------------------------------------------
# the content-hash lint cache
# ---------------------------------------------------------------------------


def test_cache_roundtrip_and_hit_miss(tmp_path):
    from distributedarrays_tpu.analysis.engine import Finding
    cpath = tmp_path / "cache.json"
    c = LintCache(cpath)
    src = "x = 1\n"
    assert c.lookup("a.py", src) is None          # cold: miss
    f = Finding("a.py", 1, 0, "DAL001", "error", "m", False)
    c.store("a.py", src, [f], [])
    c.save()
    c2 = LintCache(cpath)
    hit = c2.lookup("a.py", src)
    assert hit is not None and hit[0][0] == f     # warm: hit, equal
    assert c2.lookup("a.py", "x = 2\n") is None   # content change: miss
    assert c2.hits == 1 and c2.misses == 1


def test_cache_salted_by_analysis_sources(tmp_path, monkeypatch):
    from distributedarrays_tpu.analysis import cache as cache_mod
    cpath = tmp_path / "cache.json"
    c = LintCache(cpath)
    c.store("a.py", "x = 1\n", [], [])
    c.save()
    # simulate an analysis-code change: the whole cache invalidates
    monkeypatch.setattr(cache_mod, "analysis_salt", lambda: "different")
    c2 = LintCache(cpath)
    assert c2.lookup("a.py", "x = 1\n") is None


def test_cache_corrupt_file_degrades_to_off(tmp_path):
    cpath = tmp_path / "cache.json"
    cpath.write_text("{not json")
    c = LintCache(cpath)                           # must not raise
    assert c.lookup("a.py", "x = 1\n") is None


def test_lint_cli_cache_counters(tmp_path, capsys, monkeypatch):
    from distributedarrays_tpu.analysis.__main__ import main
    monkeypatch.chdir(tmp_path)
    f = tmp_path / "m.py"
    f.write_text("x = 1\n")
    assert main(["lint", str(f)]) == 0
    out1 = capsys.readouterr().out
    assert "cache: 0 hit / 1 miss" in out1
    assert main(["lint", str(f)]) == 0
    out2 = capsys.readouterr().out
    assert "cache: 1 hit / 0 miss" in out2
    assert (tmp_path / "build" / "dalint_cache.json").exists()
    assert main(["lint", "--no-cache", str(f)]) == 0
    assert "cache: off" in capsys.readouterr().out


def test_lint_cache_does_not_mask_new_findings(tmp_path, capsys,
                                               monkeypatch):
    from distributedarrays_tpu.analysis.__main__ import main
    monkeypatch.chdir(tmp_path)
    f = tmp_path / "m.py"
    f.write_text("x = 1\n")
    assert main(["lint", str(f)]) == 0
    capsys.readouterr()
    f.write_text(PRELUDE + "def p():\n"
                 "    if S.myid() == 0:\n        S.barrier()\n")
    assert main(["lint", str(f)]) == 1     # changed content re-lints
    assert "DAL0" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# process-backend divergence coverage gap is journaled (satellite fix)
# ---------------------------------------------------------------------------


def test_process_backend_unchecked_divergence_journaled(monkeypatch):
    monkeypatch.setenv("DA_TPU_CHECK_DIVERGENCE", "1")
    telemetry.reset()
    telemetry.enable()
    try:
        S.spmd(lambda: 7, pids=[0, 1], backend="process", timeout=60)
        evs = [e for e in telemetry.events("divergence")
               if e.get("name") == "unchecked_backend"]
        assert evs, "coverage gap must journal a typed event"
        assert evs[0]["backend"] == "process"
        assert telemetry.counter_value("analysis.divergence_unchecked",
                                       backend="process") >= 1
    finally:
        telemetry.reset()


def test_thread_backend_has_no_unchecked_event(divergence_on):
    telemetry.reset()
    telemetry.enable()
    try:
        S.spmd(lambda: 7, pids=[0, 1])
        assert not [e for e in telemetry.events("divergence")
                    if e.get("name") == "unchecked_backend"]
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# callgraph unit checks
# ---------------------------------------------------------------------------


def test_callgraph_resolves_import_alias_and_method():
    g = CallGraph([
        ("pkg_a.py", "def helper():\n    return 1\n"
                     "class C:\n    def m(self):\n        return 2\n"),
        ("pkg_b.py", "from pkg_a import helper as h\n"
                     "import pkg_a\n"
                     "def use():\n"
                     "    h()\n"
                     "    c = pkg_a.C()\n"
                     "    c.m()\n"),
    ])
    use = next(k for k in g.funcs if k[2] == "use")
    import ast as _ast
    tree = _ast.parse(Path("x").name and
                      "h()\nc = pkg_a.C()\nc.m()\n")
    calls = [n for n in _ast.walk(tree) if isinstance(n, _ast.Call)]
    b = g.resolve_call(calls[0], use[0], None, {})
    assert b is not None and b.ref[2] == "helper"


def test_callgraph_partial_carries_bound_args():
    g = CallGraph([
        ("mod.py", "import functools\n"
                   "def f(a, b):\n    return a + b\n"
                   "g2 = functools.partial(f, 1)\n"),
    ])
    sc = g.scans["mod"]
    b = g._module_binding(sc, "g2")
    assert b is not None and b.kind == "partial"
    assert len(b.bound_args) == 1
