"""Test harness: 8 virtual CPU devices, scalar-indexing ban, leak checks.

Mirrors the reference harness (/root/reference/test/runtests.jl):
- real multi-worker processes via addprocs (runtests.jl:10-13) → here an
  8-device CPU mesh via --xla_force_host_platform_device_count, the JAX
  moral equivalent for exercising true multi-device sharding in CI;
- global allowscalar(false) so accidental scalar fallbacks throw
  (runtests.jl:5-7);
- leak checking between suites (runtests.jl:28-37): every test must leave
  the DArray registry empty or close what it made.
"""

import os

# DAT_TEST_TPU=1 runs the suite on whatever real devices JAX sees (tests
# needing >1 device will fail on a 1-chip host — intended for real slices);
# default is the virtual 8-device CPU mesh, the reference's addprocs analog.
_ON_REAL = os.environ.get("DAT_TEST_TPU") == "1"

if not _ON_REAL:
    # the full wedged-tunnel-safe CPU bootstrap lives in ONE place,
    # shared with examples/_setup.py — see _cpu_harness.py for why each
    # step exists
    import sys as _sys
    from pathlib import Path as _Path
    _sys.path.insert(0, str(_Path(__file__).resolve().parents[1]))
    import _cpu_harness
    _cpu_harness.force_cpu_mesh()

import gc

import numpy as np
import pytest

import jax  # noqa: F401  (config already forced by _cpu_harness)

import distributedarrays_tpu as dat


@pytest.fixture(autouse=True)
def _seed_and_leakcheck(request):
    dat.seed(1234)
    yield
    # After the test body returns, its locals are collectable: any DArray the
    # test didn't explicitly keep must vanish from the registry on gc (the
    # finalizer discipline the reference asserts in test/darray.jl:1079-1086).
    # Whatever legitimately remains (fixture-held refs) is then reaped with
    # d_closeall like the reference does between testsets (test/darray.jl:314).
    # A young-generation pass reaps the typical test's droppings; the full
    # (gen-2) collect — tens of ms per call across ~950 tests — runs only
    # when something survived it, so the growth gate below keeps its exact
    # meaning at a fraction of the wall cost.
    gc.collect(1)
    leaked = dat.live_ids()
    if leaked:
        gc.collect()
        leaked = dat.live_ids()
    dat.d_closeall()
    assert dat.live_ids() == []
    # real leak check lives in test_leaks.py; here we only flag runaway growth
    assert len(leaked) < 64, f"suspicious registry growth: {len(leaked)} live"
    # HBM-ledger leak gate: with the registry drained the ledger must be
    # empty too — a nonzero residue means some lifecycle path swapped or
    # dropped a buffer without telling the ledger.  Opt out (tests that
    # leak on purpose) with @pytest.mark.intentional_leak.
    if "intentional_leak" not in request.keywords:
        from distributedarrays_tpu.telemetry import memory as _tmem
        residue = _tmem.live_bytes()
        assert residue == 0, (
            f"HBM ledger not drained after d_closeall: {residue} bytes "
            f"across {_tmem.tracked_count()} entries — "
            f"{_tmem.entries(limit=5)}")


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def pytest_configure(config):
    if not _ON_REAL:
        assert len(jax.devices()) == 8, (
            f"test harness expects 8 virtual devices, got {jax.devices()}")
    config.addinivalue_line(
        "markers", "slow: long-running test (property fuzz, training "
        "convergence, subprocess clusters); run with --runslow or "
        "DAT_TEST_SLOW=1 — CI always runs them")
    config.addinivalue_line(
        "markers", "intentional_leak: test leaves device buffers "
        "unaccounted on purpose; skips the per-test HBM-ledger drain "
        "assertion")


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="include tests marked slow (default loop skips them to stay "
             "under ~5 minutes; CI sets DAT_TEST_SLOW=1 for the full run)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow") or \
            os.environ.get("DAT_TEST_SLOW") == "1":
        return
    skip = pytest.mark.skip(reason="slow; use --runslow / DAT_TEST_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
