"""Protocol model checker + declarative ring-schedule tests.

The checker (analysis/protocol.py) must prove all six shipped RDMA
ring-kernel schedules clean over every rank-asynchronous interleaving
(semaphore drain, no in-flight slot races, write-once discipline, no
starvation, token-exact data flow) AND refute every seeded mutant with
a printed interleaving counterexample — the mutation harness is the
proof that the gate gates.  Unit halves: hand-built miniature schedules
trigger each violation kind individually, so a checker regression is
attributable to one property.
"""

import subprocess
import sys
from pathlib import Path

import pytest

from distributedarrays_tpu.analysis import protocol
from distributedarrays_tpu.ops import ring_schedules as rs

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# the shipped schedules verify
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", protocol.KERNEL_NAMES)
@pytest.mark.parametrize("p", [2, 3, 4])
def test_shipped_schedules_verify(name, p):
    for nc in ((1, 2) if name in ("ring_all_to_all",
                                  "ring_reduce_scatter") else (1,)):
        res = protocol.check_schedule(rs.build(name, p, nc))
        assert res.ok, f"{name} p={p} nc={nc}: {res.kind}: {res.detail}"
        assert res.states >= 1


def test_schedules_cover_all_six_kernels():
    # the emitter and the checker share ops/ring_schedules.SCHEDULES as
    # their single source of truth — every shipped kernel is registered
    assert set(protocol.KERNEL_NAMES) == {
        "ring_all_gather", "ring_all_to_all", "ring_reduce_scatter",
        "ring_allgather_matmul", "ring_allgather_matmul_rhs",
        "ring_matmul_reducescatter"}


def test_schedules_are_pure_data():
    # hashable, comparable, deterministic — the mutation harness diffs
    # programs and the lru caches key on (p, nc)
    a = rs.build("ring_reduce_scatter", 4, 2)
    b = rs.build("ring_reduce_scatter", 4, 2)
    assert a == b and hash(a.program) == hash(b.program)
    assert a != rs.build("ring_reduce_scatter", 4, 1)


# ---------------------------------------------------------------------------
# the mutation harness: every mutant refuted, with a counterexample
# ---------------------------------------------------------------------------


def test_verify_protocols_end_to_end():
    rep = protocol.verify_protocols(ps=(2, 3, 4), depths=(1, 2))
    assert rep["ok"]
    assert all(r.ok for r in rep["kernels"])
    assert rep["mutants"], "mutation harness produced no mutants"
    for m in rep["mutants"]:
        assert not m.ok, f"MISSED mutant {m.name}"
        assert m.kind != "state-budget"
        assert m.counterexample, "refutation must carry an interleaving"
        assert m.mutation in protocol.MUTATIONS


def test_every_credit_kernel_has_a_credit_mutant():
    # the credit-gated kernels must each be refutable by dropping one
    # credit take — the exact bug class the credits exist for
    rep = protocol.verify_protocols(ps=(2,), depths=(1,), mutant_p=4)
    got = {m.name.split("!")[0] for m in rep["mutants"]
           if m.mutation == "drop-credit-take"}
    assert got == {"ring_reduce_scatter", "ring_allgather_matmul",
                   "ring_allgather_matmul_rhs",
                   "ring_matmul_reducescatter"}


def test_mutant_counterexample_is_a_readable_interleaving():
    sched = rs.build("ring_allgather_matmul", 4, 1)
    m = protocol.mutate(sched, "drop-credit-take")
    res = protocol.check_schedule(m)
    assert not res.ok
    trace = "\n".join(res.counterexample)
    # the trace names ranks, DMA starts and landings — a reviewer can
    # replay it against docs/pallas_collectives.md's schedule diagrams
    assert "start dma" in trace and "landed" in trace
    assert res.kind in ("race", "stale-read")


def test_mutate_returns_none_when_not_applicable():
    # the all-gather has no credits to drop
    assert protocol.mutate(rs.build("ring_all_gather", 4),
                           "drop-credit-take") is None
    with pytest.raises(ValueError):
        protocol.mutate(rs.build("ring_all_gather", 4), "no-such")


def test_format_report_prints_verdicts_and_skips():
    rep = protocol.verify_protocols(ps=(2, 8), depths=(1,),
                                    mutants=False)
    text = protocol.format_report(rep)
    assert "OK " in text and "protocol verification: OK" in text
    # p=8 exceeds most kernels' tractable caps: skips are PRINTED,
    # never silent
    assert rep["skipped"] and "SKIP" in text
    assert "SKIP ring_all_to_all" not in text
    # the all-to-all reduces to one canonical interleaving -> checked
    a2a = [r for r in rep["kernels"]
           if r.name == "ring_all_to_all" and r.p == 8]
    assert a2a and a2a[0].ok


def test_raised_max_states_lifts_the_tractability_cap(monkeypatch):
    # the SKIP line advertises a deep-run command with a raised
    # --max-states; that command must actually RUN the skipped combo,
    # not skip it again.  Pin the all-to-all's cap low (it is the one
    # kernel cheap at any p) and check both sides of the default budget.
    monkeypatch.setitem(protocol.P_CAPS, "ring_all_to_all", 2)
    kw = dict(ps=(4,), depths=(1,), mutants=False)
    skipped_default = protocol.verify_protocols(**kw)
    assert any(n == "ring_all_to_all"
               for n, _, _ in skipped_default["skipped"])
    deep = protocol.verify_protocols(
        **kw, max_states=protocol.DEFAULT_MAX_STATES + 1)
    assert not any(n == "ring_all_to_all" for n, _, _ in deep["skipped"])
    ran = [r for r in deep["kernels"] if r.name == "ring_all_to_all"]
    assert ran and ran[0].ok


# ---------------------------------------------------------------------------
# unit violations on miniature hand-built schedules
# ---------------------------------------------------------------------------


def _mini(program, *, sems=(("s", 0),), final=(),
          buffers=(("b", rs.BufferSpec("scratch")),), p=2):
    return rs.Schedule("mini", p, (), buffers, sems, tuple(program),
                       tuple(final))


def test_violation_drain():
    # a local copy whose semaphore is never waited: +1 at exit
    d = rs.Dma(src=("b", (0,)), dst=("b", (1,)), sem=("s", 0), token=1)
    res = protocol.check_schedule(_mini([rs.Start(d)]))
    assert not res.ok and res.kind == "drain"
    assert "undrained" in res.detail


def test_violation_starvation():
    # a wait with no signal anywhere: deadlock, reported not hung
    d = rs.Dma(src=("b", (0,)), dst=("b", (1,)), sem=("s", 0))
    res = protocol.check_schedule(_mini([rs.WaitLocal(d)]))
    assert not res.ok and res.kind == "starvation"
    assert "deadlock" in res.detail


def test_violation_write_once():
    d1 = rs.Dma(src=("b", (0,)), dst=("o", (0,)), sem=("s", 0), token=1)
    res = protocol.check_schedule(_mini(
        [rs.Start(d1), rs.WaitLocal(d1), rs.Start(d1), rs.WaitLocal(d1)],
        buffers=(("b", rs.BufferSpec("scratch")),
                 ("o", rs.BufferSpec("output", write_once=True)))))
    assert not res.ok and res.kind == "write-once"


def test_violation_race_write_while_in_flight():
    # second copy writes b[1] while the first is still landing into it
    d1 = rs.Dma(src=("b", (0,)), dst=("b", (1,)), sem=("s", 0), token=1)
    d2 = rs.Dma(src=("b", (2,)), dst=("b", (1,)), sem=("s", 0), token=2)
    res = protocol.check_schedule(_mini(
        [rs.Start(d1), rs.Start(d2), rs.WaitLocal(d1),
         rs.WaitLocal(d2)]))
    assert not res.ok and res.kind == "race"


def test_violation_stale_read_token():
    # a compute expecting a token the slot never received
    c = rs.Compute("use", reads=((("b", (0,)), ("fresh",)),))
    res = protocol.check_schedule(_mini([c]))
    assert not res.ok and res.kind == "stale-read"
    assert "<unwritten>" in res.detail


def test_violation_final_token():
    res = protocol.check_schedule(_mini(
        [], final=(((("b", (0,))), ("never",)),)))
    assert not res.ok and res.kind == "final"


def test_state_budget_is_a_failure_not_a_pass():
    res = protocol.check_schedule(rs.build("ring_reduce_scatter", 4, 2),
                                  max_states=3)
    assert not res.ok and res.kind == "state-budget"
    # and a budgeted-out mutant does NOT count as caught
    rep = {"ok": None, "kernels": [], "mutants": [res]}
    assert "MISSED" in protocol.format_report(rep)


def test_per_link_fifo_is_modeled():
    """Same-link DMA landings are delivered in issue order (ICI
    in-order delivery) — the 2-revolving-slot all-gather is only
    correct under that premise, so the premise must be explicit: an
    out-of-order model would (and, before the FIFO constraint, did)
    refute ring_all_gather at p >= 4."""
    res = protocol.check_schedule(rs.build("ring_all_gather", 4))
    assert res.ok
    # the premise is documented where reviewers will look
    assert "in-order" in protocol.__doc__


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_verify_protocols_roundtrip():
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis",
         "verify-protocols", "--ps", "2,3", "--depths", "1", "--quiet"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "protocol verification: OK" in r.stdout
    assert "CAUGHT" in r.stdout          # mutants ran and were refuted


def test_cli_verify_protocols_fails_closed_on_budget():
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis",
         "verify-protocols", "--ps", "4", "--depths", "2",
         "--max-states", "5", "--no-mutants", "--quiet"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 1
    assert "state-budget" in r.stdout or "FAILED" in r.stdout


# ---------------------------------------------------------------------------
# mesh-axis variants (PR 19: per-axis sub-rings on 2-D/3-D meshes)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh_shape,axis", list(protocol.DEFAULT_MESHES))
def test_mesh_schedule_ring_all_gather_verifies(mesh_shape, axis):
    p = mesh_shape[axis]
    sched = rs.build("ring_all_gather", p, 2)
    res = protocol.check_mesh_schedule(sched, mesh_shape, axis)
    assert res.ok, (mesh_shape, axis, res.kind, res.detail)


def test_verify_mesh_protocols_end_to_end():
    # every shipped schedule x every (mesh, axis) variant verifies, and
    # every mesh-geometry mutant is REFUTED (not budget-skipped)
    rep = protocol.verify_mesh_protocols()
    assert rep["ok"]
    assert all(r.ok for r in rep["kernels"])
    assert len(rep["kernels"]) >= len(protocol.KERNEL_NAMES) * \
        len(protocol.DEFAULT_MESHES)
    assert rep["mutants"], "mesh mutant harness must run"
    for m in rep["mutants"]:
        assert not m.ok and m.kind != "state-budget", m.name
        assert m.mutation in protocol.MESH_MUTATIONS


@pytest.mark.parametrize("mutation", protocol.MESH_MUTATIONS)
def test_mesh_mutant_addr_leaves_the_subring(mutation):
    # the mutant address computations really do land outside the armed
    # sub-ring for some (rank, pos) — the property the isolation check
    # refutes them by
    mesh_shape, axis = (2, 4), 1
    addr = protocol.mesh_mutant_addr(mesh_shape, axis, mutation)
    escaped = False
    for ring in rs.mesh_subrings(mesh_shape, axis):
        for rank in ring:
            for pos in range(len(ring)):
                if addr(rank, pos) not in ring:
                    escaped = True
    assert escaped


def test_cli_verify_protocols_mesh_flag():
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis",
         "verify-protocols", "--ps", "2", "--depths", "1", "--mesh",
         "--quiet"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "protocol verification: OK" in r.stdout
