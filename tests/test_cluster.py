"""Cluster observatory suite: cross-host journal merge (clock-offset /
wall-anchor / first-common-event alignment, dedup, rotated siblings),
causal incident reconstruction (episode grouping, bundle attribution,
orphan witnesses), bundle schema versioning, the incident CLI, the
regress empty-baseline guard — and the slow two-process partition
incident acceptance soak (quorum side in-process with the sampler and a
burn-rate alert armed, minority side in a subprocess, the two journals
merged into ONE complete incident story with zero orphans).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from distributedarrays_tpu import telemetry as tm
from distributedarrays_tpu.resilience import (domains, elastic, faults,
                                              recovery)
from distributedarrays_tpu.telemetry import alerts, cluster, flight
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)
from distributedarrays_tpu.train import Trainer, mlp_task

REPO = Path(__file__).resolve().parents[1]

_SPLIT = [[0, 1, 2, 3, 4], [5, 6, 7]]


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Process-wide singletons pristine around every test (same guard as
    test_domains: fault plan, elastic manager, flight recorder,
    topology)."""
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    domains.reset()
    yield
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    domains.reset()


def _fast_policy(**kw):
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    return recovery.RetryPolicy(**kw)


def _ev(host, pid, seq, t, cat, name, wall=None, **fields):
    e = {"host": host, "pid": pid, "seq": seq, "t": t, "cat": cat,
         "name": name, "tid": 1}
    if wall is not None:
        e["wall"] = wall
    e.update(fields)
    return e


def _cli(argv):
    from distributedarrays_tpu.telemetry.__main__ import main
    return main(argv)


def _write_journal(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


# ---------------------------------------------------------------------------
# merge_journals: the three alignment tiers
# ---------------------------------------------------------------------------


def test_merge_clock_edge_alignment():
    # host A's monotonic origin sits at A-wall 100.0; host B's at B-wall
    # 110.0; A's wall is AHEAD of B's by 8.0s (the clock edge).  B's
    # origin on A's wall timeline is therefore 118.0, so B t=2.0 is
    # simultaneous with A t=20.0.
    a = [_ev("hostA", 1, 0, 1.0, "train", "step", wall=101.0),
         _ev("hostA", 1, 1, 5.0, "train", "step", wall=105.0),
         _ev("hostA", 1, 2, 6.0, "multihost", "clock", wall=106.0,
             offsets={"1": {"offset_s": 8.0, "host": "hostB"}})]
    b = [_ev("hostB", 2, 0, 2.0, "train", "step", wall=112.0)]
    merged = cluster.merge_journals([a, b])
    by_host = {e["host"]: e for e in merged if e["name"] == "step"
               and e["seq"] == 0}
    # rebased so the earliest event (A t=1.0) is the origin
    assert by_host["hostA"]["t"] == pytest.approx(0.0)
    assert by_host["hostB"]["t"] == pytest.approx(19.0)
    assert by_host["hostB"]["t_local"] == pytest.approx(2.0)


def test_merge_wall_anchor_fallback():
    # no clock edge and no shared configuration event: pure wall-anchor
    # placement (anchors 100.0 vs 110.0 -> B shifts +10)
    a = [_ev("hostA", 1, 0, 1.0, "train", "step", wall=101.0),
         _ev("hostA", 1, 1, 5.0, "train", "step", wall=105.0)]
    b = [_ev("hostB", 2, 0, 2.0, "train", "step", wall=112.0)]
    merged = cluster.merge_journals([a, b])
    by_host = {e["host"]: e for e in merged if e["seq"] == 0}
    assert by_host["hostA"]["t"] == pytest.approx(0.0)
    assert by_host["hostB"]["t"] == pytest.approx(11.0)


def test_merge_common_event_overrides_skewed_walls():
    # both hosts journal the SAME fault plan; host B's wall clock is 6s
    # off NTP, so the wall anchors disagree — the shared configure event
    # (assumed simultaneous) must win over the skewed anchors
    plan_fields = {"seed": 7, "sites": 1}
    a = [_ev("hostA", 1, 0, 0.5, "train", "step", wall=100.5),
         _ev("hostA", 1, 1, 3.0, "faults", "configure", wall=103.0,
             **plan_fields)]
    b = [_ev("hostB", 2, 0, 9.0, "faults", "configure", wall=119.0,
             **plan_fields),
         _ev("hostB", 2, 1, 10.0, "train", "step", wall=120.0)]
    merged = cluster.merge_journals([a, b])
    confs = [e for e in merged if e["name"] == "configure"]
    assert len(confs) == 2
    assert confs[0]["t"] == pytest.approx(confs[1]["t"])
    assert confs[0]["t"] == pytest.approx(2.5)   # 3.0 rebased by A's 0.5


def test_merge_no_wall_stamps_uses_common_event():
    a = [_ev("hostA", 1, 0, 2.0, "domains", "configure",
             domains=2, ranks=8, sizes=[5, 3]),
         _ev("hostA", 1, 1, 4.0, "train", "step")]
    b = [_ev("hostB", 2, 0, 7.0, "domains", "configure",
             domains=2, ranks=8, sizes=[5, 3]),
         _ev("hostB", 2, 1, 8.0, "train", "step")]
    merged = cluster.merge_journals([a, b])
    confs = [e for e in merged if e["name"] == "configure"]
    assert confs[0]["t"] == pytest.approx(confs[1]["t"])
    steps = {e["host"]: e["t"] for e in merged if e["name"] == "step"}
    assert steps["hostB"] == pytest.approx(steps["hostA"] - 1.0)


def test_merge_dedups_shared_events_and_sorts():
    a = [_ev("hostA", 1, 0, 1.0, "train", "step", wall=101.0),
         _ev("hostA", 1, 1, 2.0, "train", "step", wall=102.0)]
    # the same journal fed twice (a copied file): every (host, pid, seq)
    # appears exactly once
    merged = cluster.merge_journals([a, list(a)])
    assert len(merged) == 2
    assert [e["seq"] for e in merged] == [0, 1]
    assert merged[0]["t"] <= merged[1]["t"]


def test_merge_reads_rotated_sibling_oldest_first(tmp_path):
    p = tmp_path / "j.jsonl"
    _write_journal(str(p) + ".1",
                   [_ev("h", 1, 0, 1.0, "train", "step", wall=101.0)])
    _write_journal(str(p),
                   [_ev("h", 1, 1, 2.0, "train", "step", wall=102.0)])
    merged = cluster.merge_journals([str(p)])
    assert [e["seq"] for e in merged] == [0, 1]


# ---------------------------------------------------------------------------
# reconstruct_incidents
# ---------------------------------------------------------------------------


_I1 = "inc-hostA-1-1"
_I2 = "inc-hostB-2-1"


def _partition_story():
    """A merged two-host timeline of one 5/3 partition: quorum side
    recovers, minority side exits typed; the injection and the serve
    drain are UNSTAMPED (recorded outside the id windows' owners)."""
    return [
        _ev("hostA", 1, 0, 10.0, "faults", "fire", wall=1000.0,
            action="partition", site="train.step"),
        _ev("hostA", 1, 1, 10.2, "multihost", "quorum", wall=1000.2,
            verdict="quorum", side=[0, 1, 2, 3, 4], lost=[5, 6, 7],
            incident=_I1),
        _ev("hostA", 1, 2, 10.3, "incident", "begin", wall=1000.3,
            kind="partition", incident=_I1),
        _ev("hostA", 1, 3, 10.4, "recovery", "failure", wall=1000.4,
            attempt=1, verdict="partition", retrying=True, incident=_I1),
        _ev("hostA", 1, 4, 10.5, "checkpoint", "restore_peer",
            wall=1000.5, step=4, incident=_I1),
        _ev("hostA", 1, 5, 10.6, "elastic", "shrink", wall=1000.6,
            live=5, moved=3, incident=_I1),
        _ev("hostA", 1, 6, 10.9, "recovery", "recovered", wall=1000.9,
            attempts=1, incident=_I1),
        _ev("hostA", 1, 7, 11.0, "incident", "end", wall=1001.0,
            resolution="recovered", incident=_I1),
        _ev("hostB", 2, 0, 10.35, "incident", "begin", wall=1000.35,
            kind="partition", incident=_I2),
        _ev("hostB", 2, 1, 10.45, "multihost", "quorum", wall=1000.45,
            verdict="minority", side=[5, 6, 7], lost=[0, 1, 2, 3, 4],
            incident=_I2),
        _ev("hostB", 2, 2, 10.55, "recovery", "minority_exit",
            wall=1000.55, side=[5, 6, 7], lost=[0, 1, 2, 3, 4],
            incident=_I2),
        _ev("hostB", 2, 3, 10.65, "incident", "end", wall=1000.65,
            resolution="minority_exit", incident=_I2),
        _ev("hostB", 2, 4, 10.75, "serve", "partition_drain",
            wall=1000.75, side=[5, 6, 7], lost=[0, 1, 2, 3, 4],
            endpoint="echo"),
    ]


def test_reconstruct_one_episode_from_two_sides():
    report = cluster.reconstruct_incidents(_partition_story())
    assert report["events_total"] == 13
    assert len(report["incidents"]) == 1
    ep = report["incidents"][0]
    assert sorted(ep["ids"]) == [_I1, _I2]
    assert ep["kinds"] == ["partition"]
    assert ep["hosts"] == ["hostA", "hostB"]
    assert ep["resolutions"] == {_I1: "recovered", _I2: "minority_exit"}
    whats = [s["what"] for s in ep["steps"]]
    assert whats[0] == "partition injected at train.step"
    assert any("quorum verdict quorum" in w for w in whats)
    assert any("quorum verdict minority" in w for w in whats)
    assert any("restored step 4 from peer replicas (zero disk reads)"
               in w for w in whats)
    assert any(w.startswith("shrank to 5 live devices") for w in whats)
    assert any("recovered after 1 attempts" in w for w in whats)
    assert any("exiting typed" in w for w in whats)
    assert any("server drained typed" in w for w in whats)
    # steps come out time-ordered
    ts = [s["t"] for s in ep["steps"]]
    assert ts == sorted(ts)
    assert report["unattributed_recovery_events"] == 0


def test_reconstruct_separate_windows_stay_separate_episodes():
    late = [_ev("hostA", 1, 10, 500.0, "incident", "begin", wall=1490.0,
                kind="device_loss", incident="inc-hostA-1-9"),
            _ev("hostA", 1, 11, 500.5, "incident", "end", wall=1490.5,
                resolution="recovered", incident="inc-hostA-1-9")]
    report = cluster.reconstruct_incidents(_partition_story() + late)
    assert len(report["incidents"]) == 2
    kinds = {tuple(ep["kinds"]) for ep in report["incidents"]}
    assert kinds == {("partition",), ("device_loss",)}


def test_reconstruct_counts_orphan_recovery_events():
    events = _partition_story() + [
        _ev("hostA", 1, 20, 900.0, "recovery", "failure", wall=1900.0,
            attempt=1, verdict="oom", retrying=False)]
    report = cluster.reconstruct_incidents(events)
    assert report["unattributed_recovery_events"] == 1


def _bundle(path, *, incident=None, host="hostB", pid=2, wall=1000.7,
            version=flight.SCHEMA_VERSION, kind="da_tpu_postmortem"):
    b = {"kind": kind, "reason": "crash", "classification": "partition",
         "host": host, "pid": pid, "wall": wall}
    if version is not None:
        b["schema_version"] = version
    if incident is not None:
        b["incident"] = incident
    with open(path, "w") as f:
        json.dump(b, f)
    return b


def test_bundle_attribution_by_id_window_and_orphan(tmp_path):
    p_id = tmp_path / "by_id.json"
    p_win = tmp_path / "by_window.json"
    p_orphan = tmp_path / "orphan.json"
    _bundle(p_id, incident=_I2)
    _bundle(p_win)                       # unstamped: host/pid + wall fit
    _bundle(p_orphan, wall=5000.0)       # nowhere near the episode
    bundles = cluster.load_bundles([str(tmp_path)])
    report = cluster.reconstruct_incidents(_partition_story(), bundles)
    assert report["bundles_total"] == 3
    assert report["bundles_attributed"] == 2
    assert report["bundles_unattributed"] == [str(p_orphan)]
    ep = report["incidents"][0]
    got = sorted(b["path"] for b in ep["bundles"])
    assert got == sorted([str(p_id), str(p_win)])


def test_load_bundles_schema_versions(tmp_path):
    _bundle(tmp_path / "v1.json", version=None)       # pre-version era
    _bundle(tmp_path / "v2.json")
    (tmp_path / "not_a_bundle.json").write_text('{"kind": "other"}')
    (tmp_path / "garbage.json").write_text("not json at all")
    loaded = cluster.load_bundles([str(tmp_path)])
    assert len(loaded) == 2
    assert {b.get("schema_version", 1) for b in loaded} == \
        {1, flight.SCHEMA_VERSION}
    _bundle(tmp_path / "v99.json", version=99)
    with pytest.raises(ValueError, match="upgrade distributedarrays_tpu"):
        cluster.load_bundles([str(tmp_path)])


def test_incident_trace_threads_flow_arrows():
    events = _partition_story()
    trace = cluster.incident_trace(events)
    flows = [e for e in trace["traceEvents"]
             if e.get("cat") == "incident" and e.get("ph") in "stf"]
    assert len(flows) >= 2
    assert flows[0]["ph"] == "s"
    assert flows[-1]["ph"] == "f" and flows[-1]["bp"] == "e"
    assert len({e["id"] for e in flows}) == 1     # one flow per episode
    assert all(e["ph"] == "t" for e in flows[1:-1])


# ---------------------------------------------------------------------------
# incident lifecycle: the recovery executor mints / closes ids
# ---------------------------------------------------------------------------


def test_quorum_recovery_mints_and_closes_incident(telemetry_capture,
                                                   tmp_path):
    tm_ = telemetry_capture
    domains.configure(_SPLIT)
    faults.configure(seed=9, plan=[
        {"site": "train.step", "match": {"step": 3}, "action": "partition",
         "at": 1, "groups": _SPLIT, "observer": 0}])
    with Trainer(mlp_task(batch_size=56), ckpt_dir=tmp_path, save_every=2,
                 policy=_fast_policy(), peer_replicas=True) as t:
        res = t.fit(5)
    assert len(res["losses"]) == 5
    incs = list(tm_.events("incident"))
    begins = [e for e in incs if e["name"] == "begin"]
    ends = [e for e in incs if e["name"] == "end"]
    assert len(begins) == 1 and begins[0]["kind"] == "partition"
    assert len(ends) == 1 and ends[0]["resolution"] == "recovered"
    inc = begins[0]["incident"]
    assert inc.startswith("inc-")
    # the causal neighbours got stamped with the same id
    fails = [e for e in tm_.events("recovery") if e["name"] == "failure"]
    assert fails and all(e.get("incident") == inc for e in fails)
    assert tm_.current_incident() is None         # closed after recovery


def test_minority_exit_closes_incident_and_stamps_bundle(telemetry_capture,
                                                         tmp_path):
    tm_ = telemetry_capture
    domains.configure(_SPLIT)
    faults.configure(seed=9, plan=[
        {"site": "train.step", "match": {"step": 3}, "action": "partition",
         "at": 1, "groups": _SPLIT, "observer": 6}])
    with Trainer(mlp_task(batch_size=56), ckpt_dir=tmp_path, save_every=2,
                 policy=_fast_policy(), peer_replicas=True) as t:
        with pytest.raises(recovery.MinorityPartitionExit) as ei:
            t.fit(5)
    assert ei.value.incident and ei.value.incident.startswith("inc-")
    ends = [e for e in tm_.events("incident") if e["name"] == "end"]
    assert len(ends) == 1 and ends[0]["resolution"] == "minority_exit"
    # the flight bundle carries the schema version and the incident id
    bundles = cluster.load_bundles([os.path.dirname(tm_.journal_path())])
    assert len(bundles) == 1
    assert bundles[0]["schema_version"] == flight.SCHEMA_VERSION
    assert bundles[0]["incident"] == ei.value.incident


# ---------------------------------------------------------------------------
# the incident CLI
# ---------------------------------------------------------------------------


def _story_journals(tmp_path):
    story = _partition_story()
    j1 = tmp_path / "hostA.jsonl"
    j2 = tmp_path / "hostB.jsonl"
    _write_journal(j1, [e for e in story if e["host"] == "hostA"])
    _write_journal(j2, [e for e in story if e["host"] == "hostB"])
    return str(j1), str(j2)


def test_cli_incident_text_json_and_trace(tmp_path, capsys):
    j1, j2 = _story_journals(tmp_path)
    assert _cli(["incident", j1, j2]) == 0
    out = capsys.readouterr().out
    assert "incident 1: partition" in out
    assert _I1 in out and _I2 in out
    assert "partition injected at train.step" in out
    assert f"{_I2}=minority_exit" in out

    trace_path = tmp_path / "trace.json"
    assert _cli(["incident", j1, j2, "--json",
                 "--trace", str(trace_path)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert len(report["incidents"]) == 1
    assert sorted(report["incidents"][0]["ids"]) == [_I1, _I2]
    trace = json.loads(trace_path.read_text())
    assert any(e.get("cat") == "incident" for e in trace["traceEvents"])


def test_cli_incident_strict_bundles_gate(tmp_path, capsys):
    j1, j2 = _story_journals(tmp_path)
    bdir = tmp_path / "bundles"
    bdir.mkdir()
    _bundle(bdir / "attributed.json", incident=_I2)
    assert _cli(["incident", j1, j2, "--bundles", str(bdir),
                 "--strict-bundles"]) == 0
    capsys.readouterr()
    _bundle(bdir / "orphan.json", wall=5000.0)
    assert _cli(["incident", j1, j2, "--bundles", str(bdir),
                 "--strict-bundles"]) == 1
    err = capsys.readouterr().err
    assert "orphaned bundle" in err and "incomplete" in err


def test_cli_incident_refuses_newer_bundle_schema(tmp_path, capsys):
    j1, j2 = _story_journals(tmp_path)
    bdir = tmp_path / "bundles"
    bdir.mkdir()
    _bundle(bdir / "future.json", version=flight.SCHEMA_VERSION + 1)
    assert _cli(["incident", j1, j2, "--bundles", str(bdir)]) == 2
    err = capsys.readouterr().err
    assert "schema_version" in err and "upgrade" in err


def test_cli_incident_rc2_on_empty_journal(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert _cli(["incident", str(empty)]) == 2
    assert "journal is empty" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# regress: the empty / all-replay baseline guard
# ---------------------------------------------------------------------------


def test_regress_no_live_trajectory_is_typed_not_crash(tmp_path, capsys):
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps({"metric": "step_ms", "value": 1.2}))
    bank = tmp_path / "bank"
    bank.mkdir()
    # empty bank: rc 0 with the one-line typed message
    assert _cli(["regress", str(fresh), "--baseline", str(bank)]) == 0
    assert "NO_LIVE_TRAJECTORY" in capsys.readouterr().out
    # an all-replay bank is just as judgeless; --strict makes it rc 2
    (bank / "BENCH_r1.json").write_text(json.dumps(
        {"metric": "step_ms", "value": 1.0, "replayed": True}))
    assert _cli(["regress", str(fresh), "--baseline", str(bank)]) == 0
    assert "NO_LIVE_TRAJECTORY" in capsys.readouterr().out
    assert _cli(["regress", str(fresh), "--baseline", str(bank),
                 "--strict"]) == 2


# ---------------------------------------------------------------------------
# the two-process partition incident acceptance soak
# ---------------------------------------------------------------------------

_MINORITY_SCRIPT = """
import _cpu_harness; _cpu_harness.force_cpu_mesh()
import sys
from distributedarrays_tpu.resilience import domains, faults, recovery
from distributedarrays_tpu.train import Trainer, mlp_task
domains.configure([[0, 1, 2, 3, 4], [5, 6, 7]])
faults.configure(seed=42, plan=[
    {"site": "train.step", "match": {"step": 5}, "action": "partition",
     "at": 1, "groups": [[0, 1, 2, 3, 4], [5, 6, 7]], "observer": 6}])
pol = recovery.RetryPolicy(base_delay=0.005, max_delay=0.02)
t = Trainer(mlp_task(batch_size=56), ckpt_dir=sys.argv[1], save_every=2,
            policy=pol, peer_replicas=True)
try:
    t.fit(8)
    print("UNEXPECTED_COMPLETE")
except recovery.MinorityPartitionExit as e:
    print("MINORITY_OK", e.incident)
finally:
    t.close()
"""


@pytest.mark.slow
def test_partition_incident_observatory_soak(telemetry_capture, tmp_path):
    """The PR's acceptance soak: the 5/3 partition observed from BOTH
    sides — minority in a subprocess (own journal + flight dir), quorum
    in-process with the health sampler running and a fast-burn serve p99
    alert armed.  Merging the two journals must yield ONE complete
    incident story: injection, both quorum verdicts, a peer-first
    restore with zero disk reads, the shrink, the retry, the minority's
    single bundle — no orphans — and the alert fires during the episode
    and clears after."""
    tm_ = telemetry_capture
    bdir = tmp_path / "bundles"
    bdir.mkdir()
    j2 = tmp_path / "minority.jsonl"

    # ---- minority side, its own process (slow: imports jax) ----------
    r = subprocess.run(
        [sys.executable, "-c", _MINORITY_SCRIPT,
         str(tmp_path / "ckpt_minority")],
        cwd=str(REPO), capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "DA_TPU_TELEMETRY": "1",
             "DA_TPU_TELEMETRY_JOURNAL": str(j2),
             "DA_TPU_FLIGHT_DIR": str(bdir)})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "MINORITY_OK inc-" in r.stdout

    # ---- quorum side, in-process, sampler + alert armed --------------
    assert alerts.start_sampler(interval_s=0.05)
    mgr = alerts.AlertManager([alerts.AlertRule(
        "serve_p99", lambda: tm_.gauge_value("serve.request_p99_s"),
        threshold=0.5, fast_window_s=0.5, slow_window_s=1.0)])
    try:
        domains.configure(_SPLIT)
        faults.configure(seed=42, plan=[
            {"site": "train.step", "match": {"step": 5},
             "action": "partition", "at": 1, "groups": _SPLIT,
             "observer": 0}])
        d0 = tm_.counter_value("checkpoint.restore_source", source="disk")
        with Trainer(mlp_task(batch_size=56),
                     ckpt_dir=tmp_path / "ckpt_quorum", save_every=2,
                     policy=_fast_policy(), peer_replicas=True) as t:
            res = t.fit(8)
        assert len(res["losses"]) == 8
        # the SLO breach rides the incident window: fast burn fires ...
        tm_.set_gauge("serve.request_p99_s", 2.0)
        state = mgr.evaluate(now=100.0)
        assert state["serve_p99"] is True
        # ... and the recovery clears it
        tm_.set_gauge("serve.request_p99_s", 0.01)
        mgr.evaluate(now=100.4)
        state = mgr.evaluate(now=100.7)
        assert state["serve_p99"] is False
        import time
        time.sleep(0.15)                 # at least one sampler tick
    finally:
        alerts.stop_sampler()

    # ---- merge the two sides and reconstruct -------------------------
    merged = cluster.merge_journals([tm_.journal_path(), str(j2)])
    hosts_pids = {(e.get("host"), e.get("pid")) for e in merged}
    assert len(hosts_pids) == 2          # two streams, one per process
    bundles = cluster.load_bundles(
        [str(bdir), os.path.dirname(tm_.journal_path())])
    assert len(bundles) == 2             # one crash bundle per side
    # generous slack: the two runs execute sequentially, so their id
    # windows sit tens of seconds apart on the merged wall timeline
    report = cluster.reconstruct_incidents(merged, bundles, slack_s=60.0)
    assert report["bundles_total"] == 2
    assert report["bundles_attributed"] == 2
    assert report["bundles_unattributed"] == []
    assert report["unattributed_recovery_events"] == 0
    all_ids = sorted(i for ep in report["incidents"] for i in ep["ids"])
    assert len(all_ids) == 2             # one id minted per side
    whats = [s["what"] for ep in report["incidents"]
             for s in ep["steps"]]
    assert any("partition injected" in w for w in whats)
    assert any("quorum verdict quorum" in w for w in whats)
    assert any("quorum verdict minority" in w for w in whats)
    assert any("restored" in w and "peer replicas (zero disk reads)" in w
               for w in whats)
    assert any(w.startswith("shrank to") for w in whats)
    assert any("exiting typed" in w for w in whats)
    assert any("alert serve_p99 firing" in w for w in whats)
    # zero disk restores on the quorum side, and the alert CLEARED after
    assert tm_.counter_value("checkpoint.restore_source",
                             source="disk") == d0
    clear = [e for e in merged if e.get("cat") == "alert"
             and e.get("state") == "cleared"]
    assert clear, "the serve_p99 alert never cleared"
    # the sampler left health samples on the quorum journal
    assert any(e.get("cat") == "sample" and e.get("name") == "health"
               for e in merged)
