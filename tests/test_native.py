"""Native chunk-copy runtime tests (native/chunkcopy.cpp via
utils/native.py ctypes bindings).  Correctness is asserted against numpy
on uneven grids in 1/2/3-D; the framework paths must behave identically
whether or not the native tier engages."""

import numpy as np
import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu.utils import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="native toolchain unavailable")


@requires_native
def test_assemble_uneven_2d(rng):
    dst = np.zeros((50, 40), np.float32)
    cuts0, cuts1 = [0, 13, 26, 38, 50], [0, 20, 40]
    chunks, offs = [], []
    for i in range(4):
        for j in range(2):
            c = rng.standard_normal(
                (cuts0[i + 1] - cuts0[i], cuts1[j + 1] - cuts1[j])
            ).astype(np.float32)
            chunks.append(c)
            offs.append((cuts0[i], cuts1[j]))
    native.assemble(dst, chunks, offs)
    want = np.zeros_like(dst)
    for c, o in zip(chunks, offs):
        want[o[0]:o[0] + c.shape[0], o[1]:o[1] + c.shape[1]] = c
    assert np.array_equal(dst, want)


@requires_native
def test_scatter_roundtrip(rng):
    src = rng.standard_normal((32, 16)).astype(np.float32)
    shapes = [(16, 16), (16, 16)]
    offs = [(0, 0), (16, 0)]
    back = native.scatter_chunks(src, shapes, offs)
    assert np.array_equal(np.concatenate(back, axis=0), src)


@requires_native
def test_assemble_1d_3d(rng):
    d1 = np.zeros(100, np.int64)
    native.assemble(d1, [np.arange(30, dtype=np.int64),
                         np.arange(70, dtype=np.int64)], [(0,), (30,)])
    assert d1[29] == 29 and d1[30] == 0 and d1[99] == 69
    d3 = np.zeros((8, 8, 8), np.float32)
    c3 = rng.standard_normal((4, 8, 8)).astype(np.float32)
    native.assemble(d3, [c3], [(4, 0, 0)])
    assert np.array_equal(d3[4:], c3)


def test_framework_paths_unchanged(rng):
    # from_chunks and darray() must produce identical results regardless of
    # which copy tier runs
    chunks = np.empty((3,), dtype=object)
    chunks[0] = rng.standard_normal(5).astype(np.float32)
    chunks[1] = rng.standard_normal(4).astype(np.float32)
    chunks[2] = rng.standard_normal(3).astype(np.float32)
    d = dat.from_chunks(chunks)
    want = np.concatenate([chunks[0], chunks[1], chunks[2]])
    assert np.array_equal(np.asarray(d), want)


def test_worth_using_policy():
    # single-chunk / tiny workloads never engage the native tier
    assert not native.worth_using(1024, 1)
    assert not native.worth_using(1024, 100)
