"""Decode-service suite: paged KV cache residency, continuous batching,
WFQ scheduling, streaming cancellation, eviction + re-prefill
bit-identity, the asyncio bridge — and the decode chaos leg (seeded
device loss mid-decode/mid-prefill resolves every sequence
correct-or-typed with cache pages re-laid onto survivors, a minority
partition drains typed, and the acceptance soak holds the KV ledger
under budget through 2x overload with bit-identical results).
"""

import asyncio
import json
import threading
import time

import numpy as np
import pytest

from distributedarrays_tpu import serve, telemetry as tm
from distributedarrays_tpu.models.ring_attention import (
    reference_attention, ring_attention_prefill)
from distributedarrays_tpu.resilience import domains, elastic, faults, \
    recovery
from distributedarrays_tpu.serve import (Cancelled, DeadlineExceeded,
                                         Draining, Overloaded, Rejected,
                                         ServeError)
from distributedarrays_tpu.serve.decode import _decode_attention
from distributedarrays_tpu.telemetry import export, flight, perf
from distributedarrays_tpu.telemetry import memory as tmem
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)
from distributedarrays_tpu.telemetry.summarize import read_journal


@pytest.fixture(autouse=True)
def _clean_serving():
    """Process-wide singletons (fault plan, elastic manager, domain
    topology, flight recorder) start and end pristine."""
    faults.clear()
    elastic.manager().reset()
    domains.reset()
    flight._reset()
    yield
    faults.clear()
    elastic.manager().reset()
    domains.reset()
    flight._reset()


def _fast_policy(**kw):
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    return recovery.RetryPolicy(**kw)


def _model(**kw):
    kw.setdefault("vocab", 32)
    kw.setdefault("heads", 2)
    kw.setdefault("head_dim", 4)
    kw.setdefault("max_pos", 512)
    kw.setdefault("seed", 3)
    return serve.TinyLM(**kw)


def _kv(**kw):
    kw.setdefault("page_tokens", 4)
    kw.setdefault("heads", 2)
    kw.setdefault("head_dim", 4)
    kw.setdefault("block_pages", 2)
    kw.setdefault("max_pages", 64)
    return serve.PagedKVCache(serve.KVCacheConfig(**kw))


def _engine(model=None, cache_kw=None, **kw):
    model = model or _model()
    ck = dict(cache_kw or {})
    ck.setdefault("heads", model.heads)
    ck.setdefault("head_dim", model.head_dim)
    kw.setdefault("poll_s", 0.002)
    kw.setdefault("use_ring_prefill", False)
    return serve.DecodeEngine(model, _kv(**ck), serve.DecodeConfig(**kw),
                              policy=_fast_policy())


def _oracle(model, prompt, max_new, *, use_ring=False, procs=None,
            min_ring_tokens=None):
    """Cache-free reference decode: same prefill entry, same decode
    attention, K/V kept in plain numpy — what the engine must match
    bit-for-bit through paging, eviction and rebuild."""
    toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
    q, k, v = model.qkv(toks, 0)
    if use_ring:
        out = ring_attention_prefill(q, k, v, causal=True, procs=procs,
                                     min_ring_tokens=min_ring_tokens)
    else:
        out = reference_attention(q, k, v, True)
    K = np.asarray(k, np.float32)
    V = np.asarray(v, np.float32)
    gen = [int(np.argmax(model.logits(out[-1])))]
    toks.append(gen[0])
    _, k1, v1 = model.qkv([gen[0]], len(toks) - 1)
    K = np.concatenate([K, k1])
    V = np.concatenate([V, v1])
    while len(gen) < max_new:
        qr, _, _ = model.qkv([toks[-1]], len(toks) - 1)
        t = int(np.argmax(model.logits(_decode_attention(qr[0], K, V))))
        toks.append(t)
        gen.append(t)
        _, k1, v1 = model.qkv([t], len(toks) - 1)
        K = np.concatenate([K, k1])
        V = np.concatenate([V, v1])
    return gen


# ---------------------------------------------------------------------------
# paged KV cache: allocation, round-trip, LRU eviction, typed exhaustion
# ---------------------------------------------------------------------------


def test_kvcache_write_read_roundtrip():
    with _kv() as kv:
        rows = np.arange(10 * 2 * 4, dtype=np.float32).reshape(10, 2, 4)
        kv.ensure(1, 10)
        kv.write(1, 0, rows[:6], rows[:6] * 2)     # page-straddling chunks
        kv.write(1, 6, rows[6:], rows[6:] * 2)
        k, v = kv.read(1)
        np.testing.assert_array_equal(np.asarray(k), rows)
        np.testing.assert_array_equal(np.asarray(v), rows * 2)
        assert kv.ntok(1) == 10
        assert kv.stats()["pages_live"] == kv.pages_for(10) == 3
        kv.release(1)
        assert kv.stats()["pages_live"] == 0 and not kv.has(1)
    assert tmem.live_bytes() == 0


def test_kvcache_ledger_attribution_and_block_reap(telemetry_capture):
    kv = _kv(block_pages=2)
    assert tmem.live_bytes() == 0
    kv.ensure(1, 8, tenant="t0")      # 2 pages -> 1 block in the ledger
    assert tmem.live_bytes() > 0
    telemetry_capture.assert_span("serve.kv")     # allocation attributed
    sp = telemetry_capture.spans("serve.kv")[0]
    assert sp["labels"]["op"] == "alloc_block"
    telemetry_capture.assert_counter("serve.kv.blocks_created", 1)
    kv.release(1)                     # fully-free block reaps eagerly
    assert tmem.live_bytes() == 0
    telemetry_capture.assert_counter("serve.kv.blocks_reaped", 1)
    kv.close()


def test_kvcache_lru_eviction_order():
    with _kv(max_pages=4, block_pages=2) as kv:
        for sid in (1, 2, 3, 4):
            kv.ensure(sid, 1)
        kv.ensure(1, 1)               # touch 1: seq 2 is now the LRU
        evicted = kv.ensure(5, 1)
        assert evicted == [2]
        assert kv.has(1) and not kv.has(2)
        assert kv.stats()["evictions"] == 1


def test_kvcache_pinned_never_evicted_and_typed_exhaustion():
    with _kv(max_pages=2, block_pages=2) as kv:
        kv.ensure(1, 1)
        kv.ensure(2, 1)
        kv.pin(1)
        kv.pin(2)
        with pytest.raises(Overloaded) as ei:
            kv.ensure(3, 1, tenant="t")
        assert ei.value.reason == "kv" and ei.value.retry_after > 0
        kv.unpin(1)
        assert kv.ensure(3, 1) == [1]     # only the unpinned one goes
        assert kv.has(2)


def test_kvcache_rejects_oversized_before_evicting():
    with _kv(max_pages=2, block_pages=2, page_tokens=4) as kv:
        kv.ensure(1, 1)
        with pytest.raises(Rejected) as ei:
            kv.ensure(2, 1000)        # can never fit: typed, no eviction
        assert ei.value.reason == "kv"
        assert kv.has(1)              # no innocent was evicted


def test_kvcache_budget_eviction_and_idle_evictable_bytes():
    # page = 2*4*2*4*4 = 256 B, block (2 pages) = 512 B; budget 2048 at
    # fraction 0.5 -> bound 1024 -> at most two blocks live
    kv = _kv(max_pages=16, block_pages=2, hbm_budget_bytes=2048,
             hbm_evict_fraction=0.5)
    assert kv.page_nbytes == 256
    kv.ensure(1, 8)                   # 2 pages: block 1
    kv.ensure(2, 8)                   # 2 pages: block 2 (at the bound)
    assert tmem.live_bytes() == 1024
    assert kv.idle_evictable_bytes() == 1024
    kv.pin(1)
    assert kv.idle_evictable_bytes() == 512
    evicted = kv.maybe_evict()        # live >= bound: sweep idle LRU
    assert evicted == [2]
    assert tmem.live_bytes() == 512   # seq 2's block reaped
    kv.unpin(1)
    kv.close()
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# weighted-fair queuing
# ---------------------------------------------------------------------------


def test_wfq_weight_shares_and_priority_classes():
    q = serve.WeightedFairQueue()
    for i in range(3):                # interleaved arrivals, equal cost
        q.push(("a", i), tenant="a", cost=1.0, weight=1.0)
        q.push(("b", i), tenant="b", cost=1.0, weight=3.0)
    order = [q.pop()[0] for _ in range(6)]
    # SCFQ finish tags: b at 1/3, 2/3, 1; a at 1, 2, 3 — b takes 3 of
    # the first 4 grants (the 1:3 share), a drains afterwards
    assert order[:4].count("b") == 3
    assert order[4:] == ["a", "a"]
    # strict priority classes beat any weight
    q.push(("late", 0), tenant="a", cost=1.0, weight=0.001, priority=-1)
    q.push(("bulk", 0), tenant="b", cost=1.0, weight=100.0)
    assert q.pop()[0] == "late"


def test_engine_wfq_order_and_priority_preemption():
    """Deterministic service order: the loop thread is parked so the
    test turns the scheduler crank itself via ``_round()``."""
    eng = _engine(max_new_tokens=1, max_prefill_seqs=1)
    eng._stop.set()                   # loop thread exits; manual rounds
    done_order: list[str] = []
    try:
        eng.set_weight("b", 3.0)
        streams = []
        for i in range(3):
            for t in ("a", "b"):
                s = eng.submit([3 + i, 7, 2, 9, 1, 4, 8, 5], tenant=t)
                s.add_listener(lambda kind, _v, t=t: done_order.append(t)
                               if kind == "done" else None)
                streams.append(s)
        urgent = eng.submit([9, 9, 9, 9, 9, 9, 9, 9], tenant="a",
                            priority=-1)
        urgent.add_listener(lambda kind, _v: done_order.append("urgent")
                            if kind == "done" else None)
        for _ in range(40):
            if all(s.done() for s in streams) and urgent.done():
                break
            eng._round()
        assert urgent.done() and all(s.done() for s in streams)
    finally:
        eng.close(drain=False)
    # priority class first, then the 1:3 WFQ share within class 0
    assert done_order[0] == "urgent"
    assert done_order[1:5].count("b") == 3
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# decode correctness: engine output is bit-identical to the no-cache oracle
# ---------------------------------------------------------------------------


def test_engine_tokens_match_oracle_and_stream_iterates():
    model = _model()
    prompts = [[5, 3, 7, 2, 9, 1, 4], [8, 8, 1], [30, 2, 17, 11]]
    with _engine(model, max_new_tokens=6) as eng:
        streams = [eng.submit(p) for p in prompts]
        for p, s in zip(prompts, streams):
            want = _oracle(model, p, 6)
            assert s.result(timeout=30) == want
            assert list(s) == want            # iteration replays history
            assert s.tokens == want and s.error() is None
        st = eng.stats()
        assert st["sequences"] == 0 and st["cache"]["pages_live"] == 0
    assert tmem.live_bytes() == 0
    assert tm.counter_value("serve.decode.completed",
                            tenant="default") >= 3


def test_ring_prefill_long_prompt_matches_oracle():
    model = _model()
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, model.vocab, size=64).tolist()
    procs = elastic.manager().live_ranks()
    q, k, v = model.qkv(prompt, 0)
    ring = ring_attention_prefill(q, k, v, causal=True, procs=procs)
    ref = reference_attention(q, k, v, True)
    np.testing.assert_allclose(ring, ref, rtol=2e-4, atol=2e-4)
    # below the ring floor the fallback IS the reference — bit-equal
    q2, k2, v2 = model.qkv(prompt[:6], 0)
    np.testing.assert_array_equal(
        ring_attention_prefill(q2, k2, v2, causal=True, procs=procs),
        reference_attention(q2, k2, v2, True))
    with _engine(model, use_ring_prefill=True, max_new_tokens=4) as eng:
        got = eng.submit(prompt).result(timeout=30)
    assert got == _oracle(model, prompt, 4, use_ring=True, procs=procs)
    assert tmem.live_bytes() == 0


def test_eviction_reprefill_bit_identical_to_unevicted_run():
    """Two engines, same traffic: one with a 4-page pool that must
    thrash-evict, one with a roomy pool.  Token streams must be
    bit-identical — eviction + re-prefill rebuilds exactly."""
    model = _model()
    prompts = [[5, 3, 7, 2, 9, 1], [8, 8, 1, 30, 2, 17]]
    results = {}
    evictions = {}
    for label, pages in (("tight", 4), ("roomy", 64)):
        with _engine(model, cache_kw={"max_pages": pages},
                     max_new_tokens=8) as eng:
            streams = [eng.submit(p) for p in prompts]
            results[label] = [s.result(timeout=60) for s in streams]
            evictions[label] = eng.cache.stats()["evictions"]
    assert evictions["tight"] > 0 and evictions["roomy"] == 0
    assert results["tight"] == results["roomy"]
    assert results["roomy"] == [_oracle(model, p, 8) for p in prompts]
    assert tm.counter_value("serve.decode.evicted", tenant="default") > 0
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# cancellation, deadlines, admission bounds, drain
# ---------------------------------------------------------------------------


def test_cancel_frees_pages_immediately():
    with _engine(max_new_tokens=100, poll_s=0.001) as eng:
        s = eng.submit([5, 3, 7, 2])
        it = iter(s)
        next(it)
        next(it)                      # two tokens landed; mid-generation
        assert eng.cache.stats()["pages_live"] > 0
        assert s.cancel() is True
        # pages returned and blocks reaped BEFORE cancel() returned
        assert eng.cache.stats()["pages_live"] == 0
        assert tmem.live_bytes() == 0
        assert isinstance(s.error(), Cancelled)
        with pytest.raises(Cancelled):
            s.result(timeout=5)
        with pytest.raises(Cancelled):
            list(it)
        assert s.cancel() is False    # idempotent: already gone
    assert tm.counter_value("serve.decode.cancelled",
                            tenant="default") >= 1


def test_deadline_exceeded_typed_with_stage():
    with _engine() as eng:
        s = eng.submit([1, 2, 3], deadline_s=0.0)
        with pytest.raises(DeadlineExceeded) as ei:
            s.result(timeout=10)
        assert ei.value.stage == "prefill"
    assert tmem.live_bytes() == 0


def test_max_sequences_sheds_typed_and_submit_gates():
    with _engine(max_sequences=2, max_new_tokens=100,
                 poll_s=0.001) as eng:
        a = eng.submit([1, 2, 3])
        b = eng.submit([4, 5, 6])
        with pytest.raises(Overloaded) as ei:
            eng.submit([7, 8, 9])
        assert ei.value.reason == "queue" and ei.value.retry_after > 0
        with pytest.raises(Rejected) as ri:
            eng.submit(list(range(10_000)))     # can never fit the pool
        assert ri.value.reason == "kv"
        with pytest.raises(ServeError):
            eng.submit([])
        a.cancel()
        b.cancel()
    assert tm.counter_value("serve.shed", reason="queue",
                            tenant="default") >= 1


def test_drain_then_submit_is_typed_draining():
    eng = _engine(max_new_tokens=2)
    s = eng.submit([5, 3, 7])
    assert eng.drain(timeout=30) is True
    assert s.done() and s.error() is None
    with pytest.raises(Draining):
        eng.submit([1, 2])
    eng.close()
    eng.close()                       # idempotent
    assert tmem.live_bytes() == 0


def test_token_stream_listener_replay_after_done():
    with _engine(max_new_tokens=3) as eng:
        s = eng.submit([5, 3, 7, 2])
        want = s.result(timeout=30)
        got = []
        s.add_listener(lambda kind, v: got.append((kind, v)))
        assert got == [("token", t) for t in want] + [("done", None)]


# ---------------------------------------------------------------------------
# server integration + asyncio bridge
# ---------------------------------------------------------------------------


def _srv_cfg(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("flush_s", 0.005)
    kw.setdefault("max_queue", 32)
    kw.setdefault("tenant_rate", 10_000.0)
    kw.setdefault("tenant_burst", 10_000.0)
    return serve.ServeConfig(**kw)


def test_attach_server_roundtrip_and_reclaimable_wiring():
    model = _model()
    eng = _engine(model, max_new_tokens=5)
    srv = serve.Server(_srv_cfg())
    try:
        eng.attach(srv, "decode")
        # the cache's reclaimable signal feeds the admission controller
        assert srv._admission.reclaimable_fn == \
            eng.cache.idle_evictable_bytes
        stream = srv.submit("decode", [5, 3, 7, 2]).result(timeout=30)
        assert isinstance(stream, serve.TokenStream)
        assert stream.result(timeout=30) == _oracle(model, [5, 3, 7, 2], 5)
        # dict payloads carry per-sequence knobs through the server
        s2 = srv.submit("decode", {"prompt": [8, 8, 1], "tenant": "t2",
                                   "max_new_tokens": 2}).result(timeout=30)
        assert s2.result(timeout=30) == _oracle(model, [8, 8, 1], 2)
        assert s2.tenant == "t2"
    finally:
        srv.close()
        eng.close()
    assert tmem.live_bytes() == 0


def test_aio_generate_streams_and_cancels_on_exit():
    model = _model()
    eng = _engine(model, max_new_tokens=6, poll_s=0.001)
    srv = serve.Server(_srv_cfg())
    try:
        eng.attach(srv, "decode")

        async def _full():
            return [t async for t in serve.aio.generate(
                srv, [5, 3, 7, 2], tenant="aio")]

        assert asyncio.run(_full()) == _oracle(model, [5, 3, 7, 2], 6)

        async def _partial():
            handle = await serve.aio.submit(srv, "decode", [9, 1, 4])
            got = []
            async for t in serve.aio.stream_tokens(handle):
                got.append(t)
                if len(got) == 2:
                    break             # client walks away mid-stream
            return handle, got

        handle, got = asyncio.run(_partial())
        assert len(got) == 2
        deadline = time.monotonic() + 5
        while not handle.done() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert isinstance(handle.error(), Cancelled)
        assert eng.cache.stats()["pages_live"] == 0

        async def _not_a_stream():
            async for _ in serve.aio.generate(srv, 1, endpoint="echo"):
                pass

        srv.register("echo", lambda xs: xs)
        with pytest.raises(TypeError):
            asyncio.run(_not_a_stream())
    finally:
        srv.close()
        eng.close()
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# the two regimes under the roofline doctor + per-endpoint SLO histograms
# ---------------------------------------------------------------------------


def test_doctor_classifies_prefill_compute_decode_hbm(telemetry_capture):
    model = _model()
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, model.vocab, size=64).tolist()
    with _engine(model, use_ring_prefill=True, max_new_tokens=4) as eng:
        eng.submit(prompt).result(timeout=30)
    occs = perf.classify(read_journal(telemetry_capture.journal_path()),
                         perf.peaks_for("cpu"))
    pre = [o for o in occs if o["name"] == "serve.prefill"]
    dec = [o for o in occs if o["name"] == "serve.decode"]
    assert pre and dec
    assert all(o["bound"] == "compute" for o in pre), pre
    assert all(o["bound"] == "hbm" for o in dec), dec
    # both regimes land in the per-endpoint SLO histogram family
    text = export.to_prometheus(telemetry_capture.report())
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="decode.prefill"' \
        in text
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="decode.decode"' \
        in text
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# the decode chaos leg
# ---------------------------------------------------------------------------


def test_chaos_device_loss_mid_decode_correct_and_relayed(monkeypatch):
    """Seeded plan downs a device on the second decode dispatch: the
    recovery executor probes, shrinks — re-laying the registered cache
    blocks onto survivors — and retries; the token stream is
    bit-identical to the fault-free oracle."""
    plan = [{"site": "serve.decode", "action": "device_loss", "at": 2,
             "count": 1, "device": 3}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "1234")
    faults.configure()
    model = _model()
    retries0 = tm.counter_value("recovery.retries", verdict="device_loss")
    with _engine(model, max_new_tokens=10, poll_s=0.001) as eng:
        s = eng.submit([5, 3, 7, 2, 9])
        assert s.result(timeout=60) == _oracle(model, [5, 3, 7, 2, 9], 10)
        # survivors-only: a sequence admitted after the loss lays its
        # pages strictly on live ranks
        s2 = eng.submit([8, 8, 1], max_new_tokens=200)
        deadline = time.monotonic() + 10
        pids = None
        while time.monotonic() < deadline:
            with eng.cache._lock:
                blocks = list(eng.cache._blocks.values())
            if blocks:
                pids = {int(p) for b in blocks for p in b.d.pids.flat}
                break
            time.sleep(0.002)
        assert pids is not None and 3 not in pids, pids
        s2.cancel()
    assert [h["action"] for h in faults.history()] == ["device_loss"]
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") > retries0
    assert 3 not in elastic.manager().live_ranks()
    assert tmem.live_bytes() == 0


def test_chaos_device_loss_mid_prefill_correct(monkeypatch):
    plan = [{"site": "serve.prefill", "action": "device_loss", "at": 1,
             "count": 1, "device": 2}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "1234")
    faults.configure()
    model = _model()
    retries0 = tm.counter_value("recovery.retries", verdict="device_loss")
    with _engine(model, max_new_tokens=4) as eng:
        s = eng.submit([5, 3, 7, 2, 9, 1])
        assert s.result(timeout=60) == _oracle(model, [5, 3, 7, 2, 9, 1], 4)
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") > retries0
    assert 2 not in elastic.manager().live_ranks()
    assert tmem.live_bytes() == 0


def test_chaos_minority_partition_drains_typed(monkeypatch):
    """The engine observes a partition from the minority side: every
    in-flight sequence resolves typed Draining (clients failover, they
    never wait out a timeout), and new submits are refused typed."""
    split = [[0, 1, 2, 3, 4], [5, 6, 7]]
    domains.configure(split)
    plan = [{"site": "serve.decode", "action": "partition", "at": 1,
             "groups": split, "observer": 6}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "1234")
    faults.configure()
    eng = _engine(max_new_tokens=10, poll_s=0.001)
    try:
        streams = [eng.submit([5, 3, 7, 2]), eng.submit([8, 8, 1])]
        for s in streams:
            with pytest.raises(Draining) as ei:
                s.result(timeout=60)
            assert isinstance(ei.value.__cause__,
                              recovery.MinorityPartitionExit)
        assert eng.stats()["draining"] is True
        with pytest.raises(Draining):
            eng.submit([1, 2])
        assert tm.counter_value("serve.partition_drains") >= 1
    finally:
        eng.close(drain=False)
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# the acceptance soak: 2x overload, tight HBM budget, seeded device loss
# ---------------------------------------------------------------------------


def test_acceptance_soak_overload_budget_eviction_chaos(monkeypatch):
    """ISSUE acceptance: open-loop ~2x overload against a budget that
    holds ~7 of the ~24 demanded pages' blocks.  The ledger witness must
    never exceed the budget, sheds are typed with retry_after, evictions
    + re-prefills keep every admitted stream bit-identical to the
    oracle, a seeded device loss mid-decode resolves correct-or-typed,
    and the leak gate drains to zero."""
    plan = [{"site": "serve.decode", "action": "device_loss", "at": 3,
             "count": 1, "device": 5}]
    monkeypatch.setenv("DA_TPU_FAULT_PLAN", json.dumps(plan))
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "1234")
    faults.configure()
    model = _model()
    budget = 4096                     # 7 x 512 B blocks under 0.9 frac
    eng = serve.DecodeEngine(
        model,
        serve.PagedKVCache(serve.KVCacheConfig(
            page_tokens=4, heads=model.heads, head_dim=model.head_dim,
            block_pages=2, max_pages=16, hbm_budget_bytes=budget,
            retry_after_s=0.01)),
        serve.DecodeConfig(max_new_tokens=6, max_sequences=6,
                           token_budget=64, poll_s=0.001,
                           use_ring_prefill=False),
        policy=_fast_policy())
    peak = {"v": 0}
    stop = threading.Event()

    def _monitor():                   # the ledger witness
        while not stop.is_set():
            peak["v"] = max(peak["v"], tmem.live_bytes())
            time.sleep(0.001)

    mon = threading.Thread(target=_monitor, daemon=True)
    mon.start()
    rng = np.random.default_rng(5)
    admitted: list[tuple[list, serve.TokenStream]] = []
    sheds = 0
    try:
        for i in range(16):           # ~2x the 6-sequence capacity
            prompt = rng.integers(0, model.vocab, size=6).tolist()
            try:
                admitted.append((prompt, eng.submit(prompt)))
            except Overloaded as e:
                assert e.retry_after > 0 and e.reason in ("kv", "queue")
                sheds += 1
            time.sleep(0.003)
        assert sheds >= 1, "overload never shed: not a soak"
        assert len(admitted) >= 6
        for prompt, s in admitted:    # correct-or-typed: here, correct
            assert s.result(timeout=60) == _oracle(model, prompt, 6), \
                f"prompt {prompt} diverged after eviction/chaos"
        assert eng.cache.stats()["evictions"] > 0, \
            "budget never forced an eviction: not a soak"
    finally:
        stop.set()
        mon.join(2.0)
        eng.close()
    assert peak["v"] > 0 and peak["v"] <= budget, peak
    assert [h["action"] for h in faults.history()] == ["device_loss"]
    assert 5 not in elastic.manager().live_ranks()
    assert tmem.live_bytes() == 0     # the leak gate's explicit witness
