"""Child program for the multi-process jax.distributed cluster tests.

Run as: python tests/_multihost_child.py <coordinator_port> <process_id> \
            [smoke|full] [num_processes]

Each process owns 4 virtual CPU devices; ``num_processes`` of them
(default 2) form one ``4*num_processes``-device global mesh — the moral
equivalent of the reference's multi-process addprocs harness
(/root/reference/test/runtests.jl:10-15, which REFUSES to run with fewer
than 3 workers: ``@assert nworkers() >= 3``).  p=2 is degenerate for ring
topologies (left neighbor == right neighbor) and for all_to_all ordering,
so the slow leg drives this matrix at 3 AND 4 processes (VERDICT round-4
item 4); the default loop keeps a <60 s 2-process smoke.

``smoke`` runs cluster formation + the core DArray
construction/psum/sum/gather; ``full`` adds the complete cross-process op
matrix: elementwise, reductions, GEMM, uneven layouts, scan, FFT, dsort,
a compiled run_spmd+pshift program, a checkpoint save/restore round-trip,
and ring attention.
"""

import os
import sys

port, proc_id = sys.argv[1], int(sys.argv[2])
stage = sys.argv[3] if len(sys.argv) > 3 else "full"
nprocs = int(sys.argv[4]) if len(sys.argv) > 4 else 2

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from distributedarrays_tpu.parallel import multihost  # noqa: E402
from distributedarrays_tpu.parallel.collectives import shard_map_compat  # noqa: E402

try:
    # bounded cluster formation: a coordinator that never comes up must
    # exit with a diagnosable marker, not hang the tier-1 budget — the
    # parent turns exit code 4 into a bounded diagnostic failure
    multihost.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nprocs, process_id=proc_id,
        initialization_timeout_s=int(
            os.environ.get("DA_TPU_MH_INIT_TIMEOUT_S", "60")))
except Exception as e:  # noqa: BLE001 — marker protocol for the parent
    print(f"MULTIHOST_STARTUP_FAILED: {type(e).__name__}: "
          f"{str(e).splitlines()[0] if str(e) else ''}", flush=True)
    sys.exit(4)

info = multihost.process_info()
assert info["process_count"] == nprocs, info
assert info["local_devices"] == 4, info
N = 4 * nprocs
assert info["global_devices"] == N, info

mesh = multihost.global_mesh((N,), ("x",))

# --- one psum across all processes (compiled collective over "DCN") -------
# this first compiled cross-process collective is also the CAPABILITY
# probe: some backends form the cluster fine but cannot COMPILE
# multiprocess computations (CPU: "Multiprocess computations aren't
# implemented on the CPU backend").  That is a missing runtime
# capability, not a bug in this framework — exit with the typed marker
# (code 3) so the parent skips, naming the capability, instead of failing
sh = NamedSharding(mesh, P("x"))
host = np.arange(float(N), dtype=np.float32)
garr = jax.make_array_from_callback((N,), sh, lambda idx: host[idx])
try:
    total = jax.jit(shard_map_compat(lambda x: jax.lax.psum(jnp.sum(x), "x"),
                              mesh=mesh, in_specs=P("x"), out_specs=P()))(garr)
except Exception as e:  # noqa: BLE001 — marker protocol for the parent
    msg = str(e).splitlines()[0] if str(e) else ""
    if "implemented" in str(e):
        print(f"MULTIHOST_CAPABILITY_MISSING: {type(e).__name__}: {msg}",
              flush=True)
        sys.exit(3)
    raise
assert float(total.addressable_data(0)) == N * (N - 1) / 2, total

# --- one DArray constructed across processes ------------------------------
import distributedarrays_tpu as dat

A = np.arange(2.0 * N, dtype=np.float32)
d = dat.distribute(A)  # default layout spans all global devices
assert not d.garray.is_fully_addressable, "DArray should span processes"
assert len(d.garray.addressable_shards) == 4  # this process's local shards
s = dat.dsum(d)
assert float(s.addressable_data(0)) == (2 * N) * (2 * N - 1) / 2, s

# localpart of a rank owned by this process comes off a local shard
local_pids = [pid for pid, _ in multihost.host_local_slice(d)]
assert len(local_pids) == 4, local_pids
lp = d.localpart(local_pids[0])
assert int(np.asarray(lp).size) == 2

# --- gather a non-fully-addressable DArray back to every host -------------
got = multihost.gather_global(d)
assert np.array_equal(got, A), got
d.close()

if stage == "smoke":
    dat.d_closeall()
    multihost.sync_hosts("done")
    print(f"MULTIHOST_OK proc={proc_id}")
    sys.exit(0)

# --- core ops END-TO-END across controllers (VERDICT round-3 item 4) ------
# every process executes the same program on the same data; results are
# checked against numpy oracles gathered through the DCN all-gather.

# elementwise (djit broadcast fusion) over the global mesh
X = np.linspace(-1.0, 1.0, 64, dtype=np.float32).reshape(16, 4)
dx = dat.distribute(X)                      # default layout spans processes
assert not dx.garray.is_fully_addressable
ew = dat.djit(lambda a: jnp.sin(a) * 2 + 1)(dx)
np.testing.assert_allclose(multihost.gather_global(ew), np.sin(X) * 2 + 1,
                           rtol=1e-6, atol=1e-6)

# reduction: dims-reduction + whole-array mapreduce
col = dat.dsum(dx, dims=0)
np.testing.assert_allclose(multihost.gather_global(col),
                           X.sum(axis=0, keepdims=True), rtol=1e-5)
tot = float(dat.dmapreduce(jnp.square, "sum", dx).addressable_data(0))
np.testing.assert_allclose(tot, (X ** 2).sum(), rtol=1e-5)

# GEMM over a 2x(N/2) process-spanning grid (XLA SUMMA over the DCN mesh)
Am = np.arange(32.0 * 4 * N, dtype=np.float32).reshape(32, 4 * N) / 100
Bm = np.arange(4.0 * N * 8, dtype=np.float32).reshape(4 * N, 8) / 100
da = dat.distribute(Am, procs=range(N), dist=(2, N // 2))
db = dat.distribute(Bm, procs=range(N), dist=(N // 2, 2))
dc = da @ db
np.testing.assert_allclose(multihost.gather_global(dc), Am @ Bm,
                           rtol=1e-4, atol=1e-4)

# uneven (blocked-padded) ctor across processes: the _place_chunked
# non-addressable branch.  50 rows over N/2 row-ranks is uneven for
# every N here (leading-remainder cuts, reference chunk_sizes)
U = np.arange(50.0 * 8, dtype=np.float32).reshape(50, 8)
du = dat.distribute(U, procs=range(N), dist=(N // 2, 2))
q, r = divmod(50, N // 2)
assert [int(c) for c in np.diff(du.cuts[0])] == [q + 1] * r + \
    [q] * (N // 2 - r)
np.testing.assert_allclose(multihost.gather_global(du), U)
u2 = du + du
np.testing.assert_allclose(multihost.gather_global(u2), U * 2)

for a in (dx, ew, col, da, db, dc, du, u2):
    a.close()

# --- round-3 ops across controllers: prefix scan + FFT all_to_all ---------
S1 = np.arange(64.0, dtype=np.float32).reshape(16, 4) / 7
ds = dat.distribute(S1)                     # layout spans both processes
cs = dat.dcumsum(ds, axis=0)                # shard_map scan over the DCN mesh
np.testing.assert_allclose(multihost.gather_global(cs),
                           np.cumsum(S1, axis=0), rtol=1e-5, atol=1e-5)
# round-4: UNEVEN scan (padded compiled path) across processes
su = np.arange(50.0, dtype=np.float32) / 9
dsu = dat.distribute(su)                    # uneven cuts over N devices
csu = dat.dcumsum(dsu)
np.testing.assert_allclose(multihost.gather_global(csu),
                           np.cumsum(su), rtol=1e-5, atol=1e-5)
# columns = N so the all_to_all repartition dim divides the shard count
# (keeps the COMPILED matrix path exercised at every process count)
F1 = np.sin(np.arange(4.0 * N * N, dtype=np.float32)).reshape(4 * N, N)
dfm = dat.distribute(F1, procs=range(N), dist=(N, 1))
ff = dat.dfft(dfm, axis=0)                  # all_to_all across processes
np.testing.assert_allclose(multihost.gather_global(ff),
                           np.fft.fft(F1, axis=0), rtol=1e-3, atol=1e-3)
for a in (ds, cs, dsu, csu, dfm, ff):
    a.close()

# --- round-4 legs (VERDICT round-3 item 8) --------------------------------

# dsort: the PSRS shard_map program over the process-spanning mesh
rngs = np.random.default_rng(7)
sv = rngs.standard_normal(8 * N).astype(np.float32)
dsv = dat.distribute(sv)                    # spans all processes
assert not dsv.garray.is_fully_addressable
srt = dat.dsort(dsv)
np.testing.assert_allclose(multihost.gather_global(srt), np.sort(sv),
                           rtol=1e-6, atol=1e-6)

# compiled SPMD collective program: run_spmd + pshift ring hop over DCN.
# At p>=3 the +1 shift is direction-sensitive (left != right neighbor —
# the asymmetry a 2-process ring cannot catch, runtests.jl:14-15)
from distributedarrays_tpu.parallel import collectives as C  # noqa: E402
from jax.sharding import PartitionSpec as P2  # noqa: E402

ring = C.run_spmd(lambda x: C.pshift(x, "x", 1), mesh,
                  in_specs=P2("x"), out_specs=P2("x"))
rin = np.arange(float(N), dtype=np.float32)
rarr = jax.make_array_from_callback(
    (N,), NamedSharding(mesh, P2("x")), lambda idx: rin[idx])
rout = multihost.gather_global(ring(rarr))
np.testing.assert_array_equal(rout, np.roll(rin, 1))  # i receives i-1's

# checkpoint save/restore round-trip of a process-spanning DArray: every
# process writes its own copy (SPMD discipline), restores, and compares
from distributedarrays_tpu.utils import checkpoint as ckpt  # noqa: E402
import tempfile  # noqa: E402

ck = rngs.standard_normal((16, 4)).astype(np.float32)
dck = dat.distribute(ck)
assert not dck.garray.is_fully_addressable
with tempfile.TemporaryDirectory() as td:
    ckpath = os.path.join(td, f"ck_proc{proc_id}")
    ckpt.save(ckpath, {"w": dck, "step": 3})
    back = ckpt.load(ckpath)
    assert back["step"] == 3
    np.testing.assert_allclose(multihost.gather_global(back["w"]), ck,
                               rtol=1e-6)
    assert back["w"].cuts == dck.cuts

# ring attention across processes: the seq dim sharded over the global
# mesh, softmax statistics riding the DCN+ICI ring (at p>=3 every rank's
# K/V block transits ranks it is NOT adjacent to — hop-order bugs that a
# 2-rank ring folds away surface here)
from distributedarrays_tpu.models.ring_attention import (  # noqa: E402
    ring_attention)

S, H, Dh = 4 * N, 2, 8
qkv = [dat.distribute(rngs.standard_normal((S, H, Dh)).astype(np.float32),
                      procs=range(N), dist=(N, 1, 1))
       for _ in range(3)]
assert not qkv[0].garray.is_fully_addressable
att = ring_attention(*qkv)
qn, kn, vn = (multihost.gather_global(a) for a in qkv)
logits = np.einsum("qhd,khd->hqk", qn / np.sqrt(Dh), kn)
w = np.exp(logits - logits.max(-1, keepdims=True))
w /= w.sum(-1, keepdims=True)
oracle = np.einsum("hqk,khd->qhd", w, vn)
np.testing.assert_allclose(multihost.gather_global(att), oracle,
                           rtol=2e-3, atol=2e-3)

dat.d_closeall()
multihost.sync_hosts("done")
print(f"MULTIHOST_OK proc={proc_id}")
