"""Child program for the 2-process jax.distributed smoke test.

Run as: python tests/_multihost_child.py <coordinator_port> <process_id>

Each process owns 4 virtual CPU devices; together they form one 8-device
global mesh — the moral equivalent of the reference's multi-process
addprocs harness (/root/reference/test/runtests.jl:10-13), but with two
real OS processes joined through ``jax.distributed`` (the DCN path).
"""

import os
import sys

port, proc_id = sys.argv[1], int(sys.argv[2])

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from distributedarrays_tpu.parallel import multihost  # noqa: E402

multihost.initialize(coordinator_address=f"localhost:{port}",
                     num_processes=2, process_id=proc_id)

info = multihost.process_info()
assert info["process_count"] == 2, info
assert info["local_devices"] == 4, info
assert info["global_devices"] == 8, info

mesh = multihost.global_mesh((8,), ("x",))

# --- one psum across both processes (compiled collective over "DCN") ------
sh = NamedSharding(mesh, P("x"))
host = np.arange(8.0, dtype=np.float32)
garr = jax.make_array_from_callback((8,), sh, lambda idx: host[idx])
total = jax.jit(jax.shard_map(lambda x: jax.lax.psum(jnp.sum(x), "x"),
                              mesh=mesh, in_specs=P("x"), out_specs=P()))(garr)
assert float(total.addressable_data(0)) == 28.0, total

# --- one DArray constructed across processes ------------------------------
import distributedarrays_tpu as dat  # noqa: E402

A = np.arange(16.0, dtype=np.float32)
d = dat.distribute(A)  # default layout spans all 8 global devices
assert not d.garray.is_fully_addressable, "DArray should span both processes"
assert len(d.garray.addressable_shards) == 4  # this process's local shards
s = dat.dsum(d)
assert float(s.addressable_data(0)) == 120.0, s

# localpart of a rank owned by this process comes off a local shard
local_pids = [pid for pid, _ in multihost.host_local_slice(d)]
assert len(local_pids) == 4, local_pids
lp = d.localpart(local_pids[0])
assert int(np.asarray(lp).size) == 2

d.close()
multihost.sync_hosts("done")
print(f"MULTIHOST_OK proc={proc_id}")
