"""Doctor-driven self-tuning suite: the advisor decision table against
hand-built journals (one per finding kind — unoverlapped rdma comm,
rdma-vs-xla side-by-side deltas, low-roofline ``pallas.matmul``),
provenance round-trip through the cache file, the guarded apply path
(micro-probe rollback on an injected 2x-slower tune, measure-or-revert
on a probe that dies after the write), the ``autotune_regressed`` alert
firing exactly once per rollback and clearing as the sample ages out,
the ``advise`` / ``regress --explain`` CLI surfaces, and the summarize
tuning-provenance table."""

import io
import json
import subprocess
import sys
from pathlib import Path

import pytest

from distributedarrays_tpu.telemetry import advisor, alerts, perf, regress
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401
from distributedarrays_tpu.telemetry.summarize import (format_summary,
                                                       summarize)
from distributedarrays_tpu.utils import autotune

REPO = Path(__file__).resolve().parents[1]

# synthetic platform: every peak 100 units/s makes the roofline math
# hand-computable (bytes_ici=100 over 1s == exactly the ICI peak)
PEAKS = {"flops": 100.0, "hbm": 100.0, "ici": 100.0, "platform": "t"}

A2A_KEY = "a2a|8|64|float32|8|t|t"
DISPATCH_KEY = "reshard|allconcat|64|64|float32|8|t|t"
GEMM_KEY = "512|512|512|float32|float32|t|t"


@pytest.fixture
def clean_autotune(monkeypatch):
    """Empty registry that never lazily reloads the seed/env cache."""
    autotune.clear()
    monkeypatch.setattr(autotune, "_LOADED_ENV", True)
    yield autotune
    autotune.clear()


def _sp(sid, name, start, dur, labels=None, parent=None):
    return {"cat": "span", "name": name, "span_id": sid,
            "parent_id": parent, "start": float(start),
            "dur": float(dur), "tid": 1, "labels": dict(labels or {})}


def _rdma_reshard_span(sid=1, *, dur=1.0, chunks=4, start=0.0):
    """A reshard span whose ICI stamp fills its whole duration with
    zero compute to hide behind -> unoverlapped_comm, severity == dur."""
    return _sp(sid, "reshard", start, dur, labels={
        "bytes_ici": 100.0 * dur, "dispatch": "rdma",
        "autotune_key": A2A_KEY, "dispatch_key": DISPATCH_KEY,
        "rdma_chunks": chunks, "shape": [64, 64], "dtype": "float32",
        "src_dim": 0, "dst_dim": 1, "nparts": 8})


def _xla_reshard_span(sid=2, *, dur=0.4, start=10.0):
    return _sp(sid, "reshard", start, dur, labels={
        "bytes_ici": 10.0, "dispatch": "xla",
        "dispatch_key": DISPATCH_KEY, "shape": [64, 64],
        "dtype": "float32", "src_dim": 0, "dst_dim": 1, "nparts": 8})


def _gemm_span(sid=3, *, dur=1.0, flops=30.0, start=20.0):
    """flops=30 over 1s against a 100-peak -> 30% roofline -> finding."""
    return _sp(sid, "pallas.matmul", start, dur, labels={
        "flops": flops, "autotune_key": GEMM_KEY,
        "shape": [512, 512, 512], "dtype": ["float32", "float32"]})


# ---------------------------------------------------------------------------
# finding action hints (satellite: machine-readable hint field)
# ---------------------------------------------------------------------------


def test_findings_carry_action_hints():
    evs = [_rdma_reshard_span(1), _xla_reshard_span(2), _gemm_span(3)]
    analysis = perf.analyze(evs, peaks=PEAKS)
    hints = {f["action"]["kernel"]: f["action"]
             for f in analysis["findings"] if f.get("action")}
    rc = hints["rdma_chunks"]
    assert rc["key"] == A2A_KEY
    assert rc["direction"] == "increase" and rc["current"] == 4
    assert rc["dispatch_key"] == DISPATCH_KEY
    assert hints["rdma_dispatch"]["current"] == "xla"   # the xla span
    lr = hints["pallas_matmul"]
    assert lr["key"] == GEMM_KEY
    assert lr["direction"] == "resweep"
    assert lr["shape"] == [512, 512, 512]


def test_action_hint_xla_span_suggests_dispatch_compare():
    # an unoverlapped xla span has no chunk knob; the hint degrades to a
    # dispatch comparison keyed on the span's shape class
    hint = perf._action_hint("unoverlapped_comm", "reshard",
                             {"dispatch": "xla",
                              "dispatch_key": DISPATCH_KEY})
    assert hint == {"kernel": "rdma_dispatch", "key": DISPATCH_KEY,
                    "param": "dispatch", "direction": "compare",
                    "current": "xla"}
    # no registry key on the span -> no hint, never a guess
    assert perf._action_hint("unoverlapped_comm", "reshard", {}) is None
    assert perf._action_hint("low_roofline", "other.op",
                             {"autotune_key": GEMM_KEY}) is None


# ---------------------------------------------------------------------------
# the decision table
# ---------------------------------------------------------------------------


def test_advise_unoverlapped_rdma_doubles_chunks(clean_autotune):
    analysis = perf.analyze([_rdma_reshard_span(chunks=4)], peaks=PEAKS)
    actions = {a.kind: a for a in advisor.advise(analysis)}
    a = actions["rdma_chunks"]
    assert a.kernel == "rdma_chunks" and a.key == A2A_KEY
    assert a.proposed == [8]                       # 4 -> 8
    assert a.finding == "unoverlapped_comm"
    assert a.evidence["chunks"] == 4
    assert a.evidence["overlap_frac"] == 0.0
    assert a.probe["op"] == "reshard" and a.probe["shape"] == [64, 64]


def test_advise_chunk_depth_edge_cases(clean_autotune):
    # chunks=1 doubles to 2; at the cap there is nothing to propose
    one = perf.analyze([_rdma_reshard_span(chunks=1)], peaks=PEAKS)
    acts = [a for a in advisor.advise(one) if a.kind == "rdma_chunks"]
    assert acts and acts[0].proposed == [2]
    capped = perf.analyze([_rdma_reshard_span(chunks=advisor.MAX_CHUNKS)],
                          peaks=PEAKS)
    assert not [a for a in advisor.advise(capped)
                if a.kind == "rdma_chunks"]
    # 48 doubles past the cap -> clamps to 64, still a real change
    near = perf.analyze([_rdma_reshard_span(chunks=48)], peaks=PEAKS)
    acts = [a for a in advisor.advise(near) if a.kind == "rdma_chunks"]
    assert acts and acts[0].proposed == [advisor.MAX_CHUNKS]


def test_dispatch_deltas_need_both_sides(clean_autotune):
    only_rdma = perf.analyze([_rdma_reshard_span()], peaks=PEAKS)
    assert advisor.dispatch_deltas(only_rdma) == []
    both = perf.analyze([_rdma_reshard_span(dur=1.0),
                         _xla_reshard_span(dur=0.4)], peaks=PEAKS)
    deltas = advisor.dispatch_deltas(both)
    assert len(deltas) == 1
    d = deltas[0]
    assert d["key"] == DISPATCH_KEY and d["faster"] == "xla"
    assert d["rdma_s"] == pytest.approx(1.0)
    assert d["xla_s"] == pytest.approx(0.4)
    assert d["delta_frac"] == pytest.approx(0.6)


def test_advise_pins_faster_dispatch(clean_autotune):
    analysis = perf.analyze([_rdma_reshard_span(dur=1.0),
                             _xla_reshard_span(dur=0.4)], peaks=PEAKS)
    acts = [a for a in advisor.advise(analysis) if a.kind == "dispatch"]
    assert len(acts) == 1
    a = acts[0]
    assert a.kernel == "rdma_dispatch" and a.key == DISPATCH_KEY
    assert a.proposed == "xla" and a.current is None
    assert a.evidence["delta_frac"] == pytest.approx(0.6)
    # already pinned to the winner -> nothing to do
    autotune.record("rdma_dispatch", DISPATCH_KEY, "xla")
    again = advisor.advise(analysis)
    assert not [x for x in again if x.kind == "dispatch"]


def test_advise_dispatch_jitter_gate(clean_autotune):
    # 5% apart is scheduler noise, not a preference
    analysis = perf.analyze([_rdma_reshard_span(dur=1.0),
                             _xla_reshard_span(dur=0.95)], peaks=PEAKS)
    assert not [a for a in advisor.advise(analysis)
                if a.kind == "dispatch"]


def test_advise_low_roofline_resweep(clean_autotune):
    autotune.record("pallas_matmul", GEMM_KEY, [8, 8, 8])
    analysis = perf.analyze([_gemm_span()], peaks=PEAKS)
    acts = [a for a in advisor.advise(analysis) if a.kind == "resweep"]
    assert len(acts) == 1
    a = acts[0]
    assert a.kernel == "pallas_matmul" and a.key == GEMM_KEY
    assert a.current == [8, 8, 8] and a.proposed is None
    assert a.candidates and len(a.candidates) <= 24
    for bm, bn, bk in a.candidates:
        assert 512 % bm == 0 and 512 % bn == 0 and 512 % bk == 0
    assert a.evidence["roofline_frac"] == pytest.approx(0.3)


def test_advise_dedups_per_registry_address(clean_autotune):
    # three findings for the same shape class -> one action per address
    evs = [_rdma_reshard_span(1, start=0.0),
           _rdma_reshard_span(2, start=5.0),
           _gemm_span(3), _gemm_span(4, start=30.0)]
    actions = advisor.advise(perf.analyze(evs, peaks=PEAKS))
    addrs = [(a.kernel, a.key) for a in actions]
    assert len(addrs) == len(set(addrs))
    assert set(a.kind for a in actions) == {"rdma_chunks", "resweep"}


# ---------------------------------------------------------------------------
# provenance round-trip + undo
# ---------------------------------------------------------------------------


def test_provenance_roundtrip_and_undo(clean_autotune, tmp_path):
    autotune.record("rdma_chunks", A2A_KEY, [1])           # plain seed
    assert autotune.provenance_for("rdma_chunks", A2A_KEY) is None
    stamp = {"source": "advisor", "finding": "unoverlapped_comm",
             "evidence": {"before_s": [0.01]}, "previous": [1]}
    autotune.record("rdma_chunks", A2A_KEY, [2], provenance=stamp)
    assert autotune.get("rdma_chunks", A2A_KEY) == [2]
    assert autotune.provenance_for(
        "rdma_chunks", A2A_KEY)["source"] == "advisor"
    # the stamp survives the cache file round-trip in a sidecar key
    path = tmp_path / "cache.json"
    autotune.save(str(path))
    doc = json.loads(path.read_text())
    assert doc["rdma_chunks"][A2A_KEY] == [2]
    assert doc["__provenance__"]["rdma_chunks"][A2A_KEY][
        "finding"] == "unoverlapped_comm"
    autotune.clear()
    autotune.load(str(path))
    assert autotune.get("rdma_chunks", A2A_KEY) == [2]
    assert autotune.get("__provenance__", A2A_KEY) is None  # not an entry
    assert autotune.provenance_for(
        "rdma_chunks", A2A_KEY)["source"] == "advisor"
    # undo restores the exact pre-write state (value AND no provenance);
    # reloading dropped the undo journal, so re-stamp first
    autotune.record("rdma_chunks", A2A_KEY, [4], provenance=stamp)
    assert autotune.undo("rdma_chunks", A2A_KEY) is True
    assert autotune.get("rdma_chunks", A2A_KEY) == [2]
    assert autotune.undo("rdma_chunks", A2A_KEY) is False  # journal drained


def test_undo_restores_deletion(clean_autotune):
    assert autotune.get("rdma_dispatch", DISPATCH_KEY) is None
    autotune.record("rdma_dispatch", DISPATCH_KEY, "xla",
                    provenance={"source": "advisor"})
    assert autotune.undo("rdma_dispatch", DISPATCH_KEY) is True
    assert autotune.get("rdma_dispatch", DISPATCH_KEY) is None
    assert DISPATCH_KEY not in autotune._REGISTRY.get("rdma_dispatch", {})


def test_undo_journal_is_bounded(clean_autotune):
    for i in range(autotune._UNDO_LIMIT + 10):
        autotune.record("k", f"key{i}", [i], provenance={"i": i})
    assert len(autotune.undo_log()) == autotune._UNDO_LIMIT


# ---------------------------------------------------------------------------
# guarded apply
# ---------------------------------------------------------------------------


def _chunk_action(current=None, proposed=None):
    return advisor.TuningAction(
        kind="rdma_chunks", kernel="rdma_chunks", key=A2A_KEY,
        current=current, proposed=proposed or [2],
        finding="unoverlapped_comm", evidence={"severity_s": 1.0},
        probe={"op": "reshard", "shape": [64, 64]})


def _registry_probe(slow_on, fast=0.01, slow=0.02):
    """Deterministic probe: reads the registry the way a real workload
    would — the configs in ``slow_on`` measure ``slow`` seconds."""
    def probe(action, config=None):
        cur = autotune.get(action.kernel, action.key)
        return slow if cur in slow_on else fast
    return probe


def test_apply_keeps_an_improving_tune(clean_autotune, telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [1])
    action = _chunk_action(current=[1], proposed=[2])
    results = advisor.apply([action], probe=_registry_probe([[1]]),
                            repeats=3, evaluate_alerts=False)
    assert [r["status"] for r in results] == ["applied"]
    assert autotune.get("rdma_chunks", A2A_KEY) == [2]
    prov = autotune.provenance_for("rdma_chunks", A2A_KEY)
    assert prov["source"] == "advisor"
    assert prov["finding"] == "unoverlapped_comm"
    assert prov["previous"] == [1]
    assert prov["evidence"]["before_s"] == [0.02, 0.02, 0.02]
    assert telemetry_capture.counter_value(
        "autotune.advisor_applies", kind="rdma_chunks") == 1


def test_apply_rolls_back_a_regressing_tune(clean_autotune,
                                            telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [1])
    action = _chunk_action(current=[1], proposed=[2])
    # the proposal measures 2x slower -> must not survive
    results = advisor.apply([action],
                            probe=_registry_probe([[2]], slow=0.02),
                            repeats=3, evaluate_alerts=False)
    r = results[0]
    assert r["status"] == "rolled_back"
    assert "micro-probe regressed" in r["reason"]
    assert autotune.get("rdma_chunks", A2A_KEY) == [1]        # restored
    assert autotune.provenance_for("rdma_chunks", A2A_KEY) is None
    assert autotune.undo_log() == []                  # entry consumed
    assert telemetry_capture.counter_value(
        "autotune.advisor_rollbacks", kind="rdma_chunks") == 1
    assert telemetry_capture.counter_value(
        "autotune.undo", kernel="rdma_chunks") == 1


def test_apply_measure_or_revert_contract(clean_autotune,
                                          telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [1])

    calls = {"n": 0}

    def probe(action, config=None):
        calls["n"] += 1
        if calls["n"] > 4:            # warmup+3 before OK; after dies
            raise RuntimeError("tunnel dropped")
        return 0.01

    results = advisor.apply([_chunk_action(current=[1], proposed=[2])],
                            probe=probe, repeats=3,
                            evaluate_alerts=False)
    assert results[0]["status"] == "rolled_back"
    assert "after-probe failed" in results[0]["reason"]
    assert autotune.get("rdma_chunks", A2A_KEY) == [1]
    # a probe that cannot even measure the baseline skips, writes nothing
    def dead(action, config=None):
        raise RuntimeError("no devices")
    results = advisor.apply([_chunk_action(current=[1], proposed=[4])],
                            probe=dead, repeats=3, evaluate_alerts=False)
    assert results[0]["status"] == "skipped"
    assert autotune.get("rdma_chunks", A2A_KEY) == [1]


def test_apply_resweep_records_sweep_winner(clean_autotune,
                                            telemetry_capture):
    autotune.record("pallas_matmul", GEMM_KEY, [8, 8, 8])
    action = advisor.TuningAction(
        kind="resweep", kernel="pallas_matmul", key=GEMM_KEY,
        current=[8, 8, 8], proposed=None, finding="low_roofline",
        evidence={"severity_s": 0.7},
        probe={"op": "pallas.matmul", "shape": [512, 512, 512],
               "dtype": ["float32", "float32"]},
        candidates=[(8, 8, 8), (128, 128, 128), (512, 512, 512)])

    def probe(act, config=None):
        # candidate timing: 128-blocks win; the bare probes (config None)
        # read the registry, so after the write the probe speeds up
        if config is not None:
            return {(8, 8, 8): 0.03, (128, 128, 128): 0.01,
                    (512, 512, 512): 0.02}[tuple(config)]
        cur = autotune.get(act.kernel, act.key)
        return 0.01 if cur == [128, 128, 128] else 0.03

    results = advisor.apply([action], probe=probe, repeats=3,
                            evaluate_alerts=False)
    r = results[0]
    assert r["status"] == "applied"
    assert r["proposed"] == [128, 128, 128]
    assert r["sweep_candidates"] == 3
    assert autotune.get("pallas_matmul", GEMM_KEY) == [128, 128, 128]
    assert autotune.provenance_for(
        "pallas_matmul", GEMM_KEY)["finding"] == "low_roofline"


def test_apply_skips_noop_proposal(clean_autotune, telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [2])
    results = advisor.apply([_chunk_action(current=[2], proposed=[2])],
                            probe=lambda a, c=None: 0.01,
                            evaluate_alerts=False)
    assert results[0]["status"] == "skipped"
    assert results[0]["reason"] == "already at proposal"
    assert autotune.provenance_for("rdma_chunks", A2A_KEY) is None


# ---------------------------------------------------------------------------
# the autotune_regressed alert
# ---------------------------------------------------------------------------


def test_autotune_regressed_fires_once_and_clears(clean_autotune,
                                                  telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [1])
    mgr = alerts.AlertManager()
    t0 = 1000.0
    # healthy tick before the rollback: signal exists, no breach
    alerts.ensure_autotune_rule(mgr)
    assert alerts.ensure_autotune_rule(mgr) is mgr.rules()[0]  # idempotent
    mgr.evaluate(t0 - 30.0)
    assert mgr.firing() == []
    advisor.apply([_chunk_action(current=[1], proposed=[2])],
                  probe=_registry_probe([[2]]), repeats=3,
                  manager=mgr, now=t0)
    assert mgr.firing() == ["autotune_regressed"]
    transitions = [e for e in telemetry_capture.events()
                   if e.get("cat") == "alert"
                   and e.get("name") == "autotune_regressed"]
    assert [e["state"] for e in transitions] == ["firing"]
    # the rollback sample ages out of the 60s fast window -> clears
    mgr.evaluate(t0 + 120.0)
    assert mgr.firing() == []
    transitions = [e for e in telemetry_capture.events()
                   if e.get("cat") == "alert"
                   and e.get("name") == "autotune_regressed"]
    assert [e["state"] for e in transitions] == ["firing", "cleared"]
    # exactly one firing transition for exactly one rollback
    assert telemetry_capture.counter_value(
        "alerts.transitions", alert="autotune_regressed",
        state="firing") == 1


def test_applied_tune_never_pages(clean_autotune, telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [1])
    mgr = alerts.AlertManager()
    advisor.apply([_chunk_action(current=[1], proposed=[2])],
                  probe=_registry_probe([[1]]), repeats=3,
                  manager=mgr, now=500.0)
    assert mgr.firing() == []


# ---------------------------------------------------------------------------
# journal + summarize tuning-provenance table
# ---------------------------------------------------------------------------


def test_summarize_renders_tuning_table(clean_autotune,
                                        telemetry_capture):
    autotune.record("rdma_chunks", A2A_KEY, [1])
    advisor.apply([_chunk_action(current=[1], proposed=[2])],
                  probe=_registry_probe([[2]]), repeats=3,
                  evaluate_alerts=False)
    from distributedarrays_tpu.telemetry.summarize import read_journal
    events = read_journal(telemetry_capture.journal_path())
    s = summarize(events)
    assert len(s["tuning"]) == 2          # the advise verdict + the undo
    adv = [t for t in s["tuning"] if t["name"] == "advise"][0]
    assert adv["kernel"] == "rdma_chunks" and adv["key"] == A2A_KEY
    assert adv["status"] == "rolled_back"
    assert adv["old"] == [1] and adv["new"] == [2]
    out = io.StringIO()
    format_summary(s, out)
    text = out.getvalue()
    assert "tuning provenance (advisor writes):" in text
    assert "ROLLED_BACK" in text and A2A_KEY in text


def test_format_results_renders_outcomes(clean_autotune):
    action = _chunk_action(current=[1], proposed=[2])
    results = [dict(action.to_dict(), status="applied",
                    before_s=[0.02], after_s=[0.01])]
    out = io.StringIO()
    advisor.format_results([action], results, out)
    text = out.getvalue()
    assert "APPLIED" in text and A2A_KEY in text
    assert "severity_s=1" in text
    assert "before median 0.02s" in text
    out = io.StringIO()
    advisor.format_results([], None, out)
    assert "no tuning actions" in out.getvalue()


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def _run_cli(*argv, env=None):
    import os
    e = dict(os.environ, JAX_PLATFORMS="cpu")
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.telemetry", *argv],
        capture_output=True, text=True, cwd=str(REPO), env=e)


@pytest.mark.slow
def test_advise_cli_json(tmp_path):
    journal = tmp_path / "run.jsonl"
    with open(journal, "w") as f:
        for ev in (_rdma_reshard_span(1), _xla_reshard_span(2)):
            f.write(json.dumps(ev) + "\n")
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({"rdma_chunks": {A2A_KEY: [1]}}))
    p = _run_cli("advise", str(journal), "--json", "--platform", "cpu",
                 env={"DAT_AUTOTUNE_CACHE": str(cache),
                      "DA_TPU_PEAKS": json.dumps(
                          {"cpu": {k: v for k, v in PEAKS.items()
                                   if k != "platform"}})})
    assert p.returncode == 0, p.stderr
    doc = json.loads(p.stdout)
    kinds = {a["kind"] for a in doc["actions"]}
    assert "rdma_chunks" in kinds and "dispatch" in kinds
    assert doc["results"] is None                    # no --apply
    chunk = [a for a in doc["actions"]
             if a["kind"] == "rdma_chunks"][0]
    # the doubling starts from the chunk depth the span actually ran
    # with (4, off its labels), not the cache entry
    assert chunk["key"] == A2A_KEY and chunk["proposed"] == [8]
    assert chunk["current"] == [1]                   # the cache entry


@pytest.mark.slow
def test_regress_explain_cli(tmp_path):
    base = tmp_path / "BENCH_r1.json"
    fresh = tmp_path / "fresh.json"
    base.write_text(json.dumps(
        {"parsed": {"metric": "gemm_s", "value": 1.0}}))
    fresh.write_text(json.dumps(
        {"metric": "gemm_s", "value": 2.0}))
    p = _run_cli("regress", str(fresh), "--baseline", str(tmp_path),
                 "--explain")
    assert p.returncode == 1                        # regression found
    assert "REGRESSION" in p.stdout
    assert "baseline: median 1" in p.stdout
    assert "lower is better" in p.stdout
    assert "conservative 50% of |median|" in p.stdout


def test_regress_explain_library():
    results = regress.compare({"x_s": 2.0}, {"x_s": [1.0, 1.0, 1.0]})
    assert results[0]["direction"] == "lower_is_better"
    out = io.StringIO()
    regress.format_results(results, out, explain=True)
    assert "max(mad_k*1.4826*MAD, rel_floor*|median|)" in out.getvalue()
