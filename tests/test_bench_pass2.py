"""Drift-guard for the pass-2 bench runner (tools/bench_pass2.py).

The runner decides whether a config is banked by looking for ONE sentinel
result key per label in BENCH_DETAILS.json.  Those sentinels are copies of
key literals inside bench.py's config closures; if a bench.py key is
renamed, the runner would silently re-run (or worse, never re-run) that
config.  Pin the correspondence textually: every sentinel must appear in
bench.py — either verbatim or, for the two grid-tagged gemm_16k keys,
via its f-string template.
"""

import importlib.util
import re
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def bp2():
    spec = importlib.util.spec_from_file_location(
        "bench_pass2", REPO / "tools" / "bench_pass2.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench_src():
    return (REPO / "bench.py").read_text()


def test_every_batch_label_has_a_sentinel(bp2):
    missing = [lbl for lbl, _, _ in bp2.BATCHES if lbl not in bp2.SENTINELS]
    assert not missing, missing


def test_every_guarded_config_has_a_sentinel(bp2, bench_src):
    # bench.py's own _banked_in guard (rerun failures must not mask
    # banked silicon results) only protects labels present in the map;
    # the one dynamic label (gemm_16k_{r}x{c} tag) is covered by its
    # single-chip 1x1 instantiation, which is what the driver runs
    labels = set(re.findall(r'_guarded\(details,\s*"([^"]+)"', bench_src))
    missing = sorted(labels - set(bp2.SENTINELS))
    assert not missing, missing


def test_every_batch_label_is_a_bench_config(bp2, bench_src):
    # labels are the second argument of _guarded(details, "label", ...);
    # the gemm_16k pair is f-string-tagged with the device-count grid
    labels = set(re.findall(r'_guarded\(details,\s*"([^"]+)"', bench_src))
    for lbl, _, _ in bp2.BATCHES:
        if lbl.startswith("gemm_16k_"):
            assert 'tag = f"gemm_16k_{g3[0]}x{g3[1]}"' in bench_src
            continue
        assert lbl in labels, (lbl, sorted(labels))


def test_every_sentinel_key_exists_in_bench(bp2, bench_src):
    # BANKED_SENTINELS itself lives in bench.py, so every sentinel string
    # trivially appears once in the source — strip the map before the
    # literal checks or the test is vacuous (each key self-matches its
    # own map entry and a renamed config key would never be caught)
    src = re.sub(r"BANKED_SENTINELS = \{.*?\n\}", "", bench_src,
                 flags=re.S)
    assert "BANKED_SENTINELS = {" not in src, "sentinel map not stripped"
    for lbl, key in bp2.SENTINELS.items():
        if lbl.startswith("gemm_16k_"):
            # key is built as f"{tag}..." — check the suffix template
            suffix = key.removeprefix("gemm_16k_1x1")
            assert f'"{{tag}}{suffix}"' in src or \
                f'f"{{tag}}{suffix}"' in src, key
            continue
        # _bank_tflops-generated keys end in _tflops/_mfu/_tops; the
        # sentinel must be the literal passed as the entry name + unit
        m = re.fullmatch(r"(.+)_(tflops|tops|mfu)", key)
        if m and f'"{key}"' not in src:
            assert f'"{m.group(1)}"' in src, key
            continue
        if f'"{key}"' in src:
            continue
        # prefix-templated families (sp_train / sp_train_d128 share one
        # parametrized config body): the key is built as
        # f"{prefix}_suffix", and the label must be the prefix string
        # actually passed at a call site — i.e. appear as the final
        # string argument of some call (`..., "sp_train_d128")`), which
        # neither the _guarded label position nor a map entry matches
        suffix = key.removeprefix(lbl)
        assert f'f"{{prefix}}{suffix}"' in src, key
        assert re.search(r'\w+\([^()]*"%s"\)' % re.escape(lbl), src), \
            f"{lbl} never passed as a prefix argument"


@pytest.fixture()
def bp3(tmp_path, monkeypatch):
    # bench_pass3 reuses bench_pass2's module-level paths; point the
    # liveness probes at a sandbox so the repo's real markers/logs (which
    # may exist from an actual round) cannot leak into the assertions
    spec = importlib.util.spec_from_file_location(
        "bench_pass3", REPO / "tools" / "bench_pass3.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod.p2, "DONE", tmp_path / "pass2.done")
    monkeypatch.setattr(mod.p2, "LOG", tmp_path / "pass2.log")
    return mod


def test_pass2_active_missing_log_waits_out_grace(bp3):
    import time
    # no DONE marker, no log yet: pass-2 may simply not have launched —
    # within the grace window this must read as ACTIVE (the round-5 race:
    # treating the absent log as "finished" had pass-3 stealing the queue
    # while pass-2 spun up)
    now = time.time()
    assert bp3.pass2_active(armed_at=now) is True
    assert bp3.pass2_active(armed_at=None) is True      # no grace started
    # past the grace with still no log: pass-2 genuinely never ran
    assert bp3.pass2_active(
        armed_at=now - bp3.NO_LOG_GRACE_S - 1) is False


def test_pass2_active_done_marker_wins(bp3):
    import time
    bp3.p2.DONE.write_text("done")
    assert bp3.pass2_active(armed_at=time.time()) is False


def test_pass2_active_log_heartbeat(bp3):
    import os
    import time
    armed = time.time()
    bp3.p2.LOG.write_text("heartbeat")
    assert bp3.pass2_active(armed_at=armed) is True      # fresh log
    stale = time.time() - bp3.STALE_LOG_S - 10
    os.utime(bp3.p2.LOG, (stale, stale))
    assert bp3.pass2_active(armed_at=armed) is False     # dead/wedged


def test_pass2_active_ignores_previous_round_markers(bp3):
    import os
    import time
    # gitignored markers survive between rounds: a day-old DONE file or
    # log must read as ABSENT (grace logic), not as "this round's pass-2
    # already finished" — or the arming race recurs on every round after
    # the first
    old = time.time() - bp3.MARKER_FRESH_S - 60
    bp3.p2.DONE.write_text("previous round")
    os.utime(bp3.p2.DONE, (old, old))
    bp3.p2.LOG.write_text("previous round heartbeat")
    os.utime(bp3.p2.LOG, (old, old))
    now = time.time()
    assert bp3.pass2_active(armed_at=now) is True          # within grace
    assert bp3.pass2_active(
        armed_at=now - bp3.NO_LOG_GRACE_S - 1) is False    # grace expired
    # a FRESH done marker still wins immediately
    bp3.p2.DONE.write_text("this round")
    assert bp3.pass2_active(armed_at=now) is False
