"""Live telemetry plane: exporter, aggregator, continuous flame profile.

Covers the streaming contracts the post-hoc suite cannot: bounded-ring
drop accounting, rotation-safe journal tailing, exporter→aggregator
frame flow (Prometheus scrape, healthz, chunked trace), the
exporter-outlives-aggregator path (drops counted, never blocks,
reconnects), live flame sampling vs post-hoc attribution, the `top`
dashboard, and — marked slow — the two-host soak with a seeded SLO burn
alert and the live-matches-post-hoc ordering check.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from distributedarrays_tpu import telemetry
from distributedarrays_tpu.telemetry import agg as tagg
from distributedarrays_tpu.telemetry import core as tcore
from distributedarrays_tpu.telemetry import stream as tstream
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)

REPO = Path(__file__).resolve().parents[1]


def _get(url, path, timeout=10):
    with urllib.request.urlopen(url.rstrip("/") + path,
                                timeout=timeout) as r:
        return r.status, r.read()


# ---------------------------------------------------------------------------
# ring
# ---------------------------------------------------------------------------


def test_ring_drop_accounting():
    r = tstream._Ring(4)
    for i in range(4):
        r.push({"i": i})
    assert len(r) == 4 and r.dropped == 0
    r.push({"i": 4})                      # laps: oldest dropped, counted
    r.push({"i": 5})
    assert r.dropped == 2
    assert r.peek()["i"] == 2             # oldest surviving frame
    got = []
    while r.peek() is not None:
        got.append(r.peek()["i"])
        r.pop()
    assert got == [2, 3, 4, 5]
    assert len(r) == 0
    r.pop()                               # pop on empty is a no-op
    assert r.peek() is None


# ---------------------------------------------------------------------------
# journal tailer across rotation
# ---------------------------------------------------------------------------


def test_journal_tailer_rotation_under_load(telemetry_capture, monkeypatch):
    # a tiny cap (sampled at file open) forces several rotations while
    # the tailer is live
    monkeypatch.setenv("DA_TPU_TELEMETRY_JOURNAL_MAX_MB", "0.002")
    jpath = str(telemetry_capture.journal_path())
    tcore.configure(jpath)                # reopen → resample the cap
    tailer = tstream.JournalTailer(jpath)
    seen = []
    for i in range(120):
        telemetry.event("soak", "tick", i=i)
        if i % 7 == 0:
            seen.extend(tailer.poll())
    # drain whatever the writer still holds
    for _ in range(4):
        seen.extend(tailer.poll())
    assert tcore._journal_rotations >= 2, \
        "cap too large: test never exercised rotation"
    assert tailer.rotations >= 2
    ticks = [e for e in seen if e.get("cat") == "soak"]
    # no gap, no double-ship: every tick exactly once, in order
    assert [e["i"] for e in ticks] == list(range(120))
    seqs = [e["seq"] for e in seen]
    assert seqs == sorted(set(seqs)), "seq dedup/order violated"
    assert tailer.dropped == 0
    # the rotation markers themselves flow through (continuity witness)
    assert any(e.get("name") == "rotated" for e in seen)
    tailer.close()


def test_journal_tailer_late_start_seeds_seq(telemetry_capture):
    jpath = str(telemetry_capture.journal_path())
    for i in range(5):
        telemetry.event("soak", "early", i=i)
    tailer = tstream.JournalTailer(jpath, from_start=False)
    assert tailer.poll() == []            # positioned at EOF
    # the intentionally-skipped prefix seeded last_seq, so it is neither
    # re-shipped nor miscounted as a gap...
    assert tailer.last_seq >= 4 and tailer.dropped == 0
    telemetry.event("soak", "late")
    evs = tailer.poll()
    assert [e["name"] for e in evs] == ["late"]
    assert tailer.dropped == 0
    tailer.close()


# ---------------------------------------------------------------------------
# flame: live sampler + post-hoc attribution
# ---------------------------------------------------------------------------


def test_flame_profiler_samples_open_stacks(telemetry_capture):
    prof = tstream.FlameProfiler(hz=50)
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            for _ in range(5):
                prof.sample_once()
    counts = prof.counts()
    assert counts.get("outer;inner", 0) >= 5
    assert prof.samples >= 5
    delta = prof.take_delta()
    assert delta.get("outer;inner", 0) >= 5
    assert prof.take_delta() == {}        # delta drained
    # idle samples (no open spans) are counted, not attributed
    prof.sample_once()
    assert prof.idle >= 1
    assert any(ln.startswith("outer;inner ")
               for ln in prof.collapsed().splitlines())


def test_collapsed_from_events_attribution(telemetry_capture):
    with telemetry.span("step"):
        with telemetry.span("fwd"):
            time.sleep(0.04)
        with telemetry.span("bwd"):
            time.sleep(0.02)
    events = telemetry.events()
    counts, stats = tstream.collapsed_from_events(events)
    assert stats["spans"] == 3
    # self time: the leaves carry their sleeps, the root only overhead
    assert counts["step;fwd"] >= 30
    assert counts["step;bwd"] >= 10
    assert counts.get("step", 0) <= 15
    # ≥90% of wall time attributed when the workload runs under spans —
    # the live-plane acceptance number
    assert stats["attributed_frac"] >= 0.9, stats
    lines = tstream.collapsed_lines(counts)
    assert any(ln.startswith("step;fwd ") for ln in lines.splitlines())


# ---------------------------------------------------------------------------
# exporter → aggregator, end to end
# ---------------------------------------------------------------------------


def test_exporter_to_aggregator_end_to_end(telemetry_capture):
    with tagg.AggServer(port=0) as srv:
        exp = tstream.StreamExporter(srv.url, interval_s=0.05,
                                     heartbeat_every=1)
        telemetry.count("x.y", 3)
        telemetry.set_gauge("elastic.live_devices", 8)
        telemetry.event("soak", "one")
        with telemetry.span("work"):
            pass
        exp.add_note("serve.request_p99_s", 0.012, {})
        exp.tick()
        telemetry.count("x.y", 2)
        exp.tick()

        agg = srv.agg
        assert agg.frames_ingested >= 2
        (hs,) = agg._states()
        assert hs.counters.get("x.y") == 5.0     # absolute, self-healing
        assert agg.gauge("elastic.live_devices") == 8.0
        assert agg.gauge("serve.request_p99_s") == 0.012
        names = [e.get("name") for e in agg.merged_events()]
        assert "one" in names and "work" in names

        code, body = _get(srv.url, "/metrics")
        text = body.decode()
        assert code == 200
        assert "da_tpu_stream_dropped_frames" in text
        assert "da_tpu_x_y_total" in text
        # every sample line parses as `name{labels} value`
        for ln in text.splitlines():
            if not ln or ln.startswith("#"):
                continue
            name, _, val = ln.rpartition(" ")
            assert name and float(val) is not None

        code, body = _get(srv.url, "/healthz")
        hz = json.loads(body)
        assert code == 200 and hz["status"] == "ok"
        assert hz["hosts"] == 1

        # chunked Perfetto download round-trips
        code, body = _get(srv.url, "/trace")
        trace = json.loads(body)
        assert code == 200 and trace["traceEvents"]

        code, body = _get(srv.url, "/snapshot")
        snap = json.loads(body)
        key = next(iter(snap["hosts"]))
        assert snap["hosts"][key]["serve_p99_s"] == 0.012

        code, _ = _get(srv.url, "/flame")
        assert code == 200
        exp.stop()


def test_exporter_outlives_aggregator(telemetry_capture):
    srv = tagg.AggServer(port=0)
    srv.start()
    url, port = srv.url, srv.port
    exp = tstream.StreamExporter(url, interval_s=0.05, ring_frames=4,
                                 reconnect_s=0.05, heartbeat_every=1)
    telemetry.count("x.y")
    exp.tick()
    assert exp.frames_sent == 1
    srv.close()

    # dead aggregator: ticks never block, never raise; the tiny ring
    # laps and the overwritten frames are counted
    t0 = time.monotonic()
    for i in range(8):
        telemetry.count("x.y")
        time.sleep(0.06)                  # clear the reconnect cold-down
        exp.tick()
    assert time.monotonic() - t0 < 10.0
    assert exp.send_errors >= 1
    assert exp.frames_dropped >= 1, exp.stats_dict()
    stats = exp.stats_dict()
    assert stats["frames_dropped"] == exp.frames_dropped
    assert stats["lag_frames"] >= 1

    # the drop counters reach flight bundles (satellite: crash evidence
    # must show whether streamed telemetry was degraded)
    # exporter is constructed directly (not armed via stream.start), so
    # arm it for the bundle capture
    tstream._EXPORTER = exp
    try:
        bundle = telemetry.flight.snapshot_bundle("test")
        assert bundle["stream"]["armed"] is True
        assert bundle["stream"]["frames_dropped"] >= 1
    finally:
        tstream._EXPORTER = None

    # revive the aggregator on the SAME port: frames flow again
    srv2 = tagg.AggServer(port=port)
    srv2.start()
    try:
        sent0 = exp.frames_sent
        deadline = time.monotonic() + 10
        while exp.frames_sent == sent0 and time.monotonic() < deadline:
            telemetry.count("x.y")
            time.sleep(0.06)
            exp.tick()
        assert exp.frames_sent > sent0, exp.stats_dict()
        assert srv2.agg.frames_ingested >= 1
    finally:
        exp.stop()
        srv2.close()


def test_frame_seq_gap_counted_as_lost(telemetry_capture):
    agg = tagg.Aggregator()
    base = {"v": 1, "host": "h", "pid": 1, "wall": time.time(), "t": 0.0}
    agg.ingest(dict(base, frame_seq=0, counters={"x.y": 1.0}))
    agg.ingest(dict(base, frame_seq=3, counters={"x.y": 4.0}))
    (hs,) = agg._states()
    assert hs.lost_frames == 2            # transport gap, counted
    assert hs.counters["x.y"] == 4.0      # absolute values self-heal


def test_live_alert_fires_and_clears_with_hysteresis(telemetry_capture):
    agg = tagg.Aggregator(p99_slo_s=0.1, fast_window_s=0.2,
                          slow_window_s=0.4)
    base = {"v": 1, "host": "h", "pid": 1, "t": 0.0}

    def feed(p99, n=8, dt=0.03):
        for _ in range(n):
            agg.ingest({**base, "frame_seq": agg.frames_ingested,
                        "wall": time.time(),
                        "gauges": {"serve.request_p99_s": p99}})
            agg.evaluate()
            time.sleep(dt)

    feed(0.5)                             # sustained breach
    assert "serve_p99" in agg.manager.firing()
    feed(0.01, n=6)                       # recovery — but hysteresis
    assert "serve_p99" not in agg.manager.firing()
    snap = agg.snapshot()
    assert snap["alerts"] == []


def test_stream_drops_rule_fires_on_exporter_loss(telemetry_capture):
    agg = tagg.Aggregator(fast_window_s=0.15, slow_window_s=0.3)
    base = {"v": 1, "host": "h", "pid": 1, "t": 0.0}
    for i in range(8):
        agg.ingest({**base, "frame_seq": i, "wall": time.time(),
                    "stream": {"frames_dropped": i * 3}})
        agg.evaluate()
        time.sleep(0.03)
    assert "stream_drops" in agg.manager.firing()


# ---------------------------------------------------------------------------
# module-level arming discipline
# ---------------------------------------------------------------------------


def test_note_and_poke_are_noops_unarmed(telemetry_capture):
    assert tstream.armed() is False
    tstream.note("serve.request_p99_s", 0.5)
    tstream.poke()
    tstream.note_health({"p": 1})
    assert tstream.stats() == {"armed": False}
    tstream.stop()                        # idempotent when unarmed


def test_start_arms_and_notes_flow(telemetry_capture):
    with tagg.AggServer(port=0) as srv:
        exp = tstream.start(srv.url, interval_s=0.05)
        try:
            assert exp is not None and tstream.armed()
            assert tstream.start(srv.url) is exp  # second start: same one
            tstream.note("train.step_s", 0.25)
            exp.tick()
            assert srv.agg.gauge("train.step_s") == 0.25
            st = tstream.stats()
            assert st["armed"] is True and st["frames_sent"] >= 1
        finally:
            tstream.stop()
        assert not tstream.armed()


# ---------------------------------------------------------------------------
# CLI: top/flame against a live aggregator
# ---------------------------------------------------------------------------


def test_cli_top_once_and_flame_url(telemetry_capture, capsys):
    from distributedarrays_tpu.telemetry.__main__ import main as cli
    with tagg.AggServer(port=0) as srv:
        exp = tstream.StreamExporter(srv.url, interval_s=0.05,
                                     heartbeat_every=1)
        telemetry.set_gauge("train.step_s", 0.123)
        telemetry.set_gauge("serve.request_p99_s", 0.02)
        exp.tick()
        exp.stop()
        assert cli(["top", "--url", srv.url, "--once"]) == 0
        out = capsys.readouterr().out
        assert "HOST" in out and "0.123" in out
        assert "alerts firing: none" in out
        assert cli(["top", "--url", srv.url, "--json"]) == 0
        snap = json.loads(capsys.readouterr().out)
        assert snap["frames_ingested"] >= 1
        assert cli(["flame", "--url", srv.url]) == 0
    # unreachable aggregator: one-line diagnostic, exit 2
    assert cli(["top", "--url", "127.0.0.1:9", "--once"]) == 2


def test_cli_flame_journal_min_frac(telemetry_capture, capsys, tmp_path):
    from distributedarrays_tpu.telemetry.__main__ import main as cli
    with telemetry.span("step"):
        with telemetry.span("fwd"):
            time.sleep(0.03)
    jpath = telemetry.journal_path()
    assert cli(["flame", jpath, "--min-frac", "0.9"]) == 0
    cap = capsys.readouterr()
    assert any(ln.startswith("step;fwd ") for ln in cap.out.splitlines())
    assert "attributed" in cap.err
    # the CI gate: demand more attribution than exists → exit 2
    assert cli(["flame", jpath, "--min-frac", "1.01"]) == 2


# ---------------------------------------------------------------------------
# regress guards the widened banking trajectory
# ---------------------------------------------------------------------------


def test_regress_directions_cover_partial_banked_metrics():
    # every metric the widened bench partial-banking can leave behind
    # must be judged in the right direction by `telemetry regress` —
    # a partial row is only useful if the guard reads it correctly
    from distributedarrays_tpu.telemetry import regress as tregress
    lower = ["reshard_even_s", "reshard_multiaxis_s",
             "reshard_multiaxis_device_put_s", "ring_gemm_xla_s",
             "train_step_s", "serve_decode_slo_s", "cg_poisson_time_s",
             "cg_poisson_iters", "cg_poisson_residual"]
    higher = ["reshard_even_gbps", "reshard_multiaxis_gbps",
              "ring_gemm_xla_tflops", "train_step_tflops",
              "serve_decode_single_stream_tokens_per_s",
              "serve_decode_tokens_per_s"]
    for m in lower:
        assert tregress.direction(m) == -1, m
    for m in higher:
        assert tregress.direction(m) == 1, m


def test_bench_partial_rows_not_treated_as_banked():
    import bench
    for label in ("reshard_even", "reshard_multiaxis", "ring_gemm",
                  "train_step", "serve_decode", "cg_poisson"):
        sent = bench.BANKED_SENTINELS[label]
        details = {sent: 1.0, f"{label}_partial": True}
        assert not bench._banked_in(details, label), label
        details.pop(f"{label}_partial")
        assert bench._banked_in(details, label), label
        assert label in bench._ROW_PROBE_BUDGET_S


# ---------------------------------------------------------------------------
# two-host soak (slow): live plane matches post-hoc, alert round-trip
# ---------------------------------------------------------------------------

_SOAK_HOST = """
import os, sys, time
sys.path.insert(0, os.environ["DAT_REPO"])
import _cpu_harness; _cpu_harness.force_cpu_mesh()
from distributedarrays_tpu import telemetry
from distributedarrays_tpu.telemetry import stream

telemetry.configure(os.environ["DAT_SOAK_JOURNAL"])
exp = stream.start(os.environ["DAT_SOAK_AGG"], interval_s=0.1,
                   flame_hz=50)
assert exp is not None
bad = os.environ.get("DAT_SOAK_BAD_P99") == "1"
for i in range(25):
    with telemetry.span("soak.step", step=i):
        with telemetry.span("soak.work"):
            time.sleep(0.03)
    telemetry.count("soak.ticks")
    p99 = 0.9 if (bad and 5 <= i < 18) else 0.01
    telemetry.set_gauge("serve.request_p99_s", p99)
    stream.note("serve.request_p99_s", p99)
stream.stop()
print("SOAK_DONE " + telemetry.journal_path())
"""


@pytest.mark.slow
def test_two_host_soak_live_matches_posthoc(telemetry_capture, tmp_path):
    srv = tagg.AggServer(port=0, p99_slo_s=0.1, fast_window_s=0.4,
                         slow_window_s=0.8, eval_interval_s=0.1)
    srv.start()
    fired = {"fired": False}
    try:
        procs = []
        journals = []
        for idx, host in enumerate(["hostA", "hostB"]):
            j = str(tmp_path / f"{host}.jsonl")
            journals.append(j)
            env = {**os.environ, "JAX_PLATFORMS": "cpu",
                   "DAT_REPO": str(REPO),
                   "DA_TPU_TELEMETRY": "1",
                   "DA_TPU_TELEMETRY_HOST": host,
                   "DAT_SOAK_JOURNAL": j,
                   "DAT_SOAK_AGG": srv.url,
                   "DAT_SOAK_BAD_P99": "1" if idx == 0 else "0"}
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _SOAK_HOST], cwd=str(REPO),
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        deadline = time.monotonic() + 120
        while any(p.poll() is None for p in procs) and \
                time.monotonic() < deadline:
            if "serve_p99" in srv.agg.manager.firing():
                fired["fired"] = True
            time.sleep(0.05)
        for p in procs:
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, err[-2000:]
            assert "SOAK_DONE" in out

        # mid-run breach fired the live alert, recovery cleared it
        for _ in range(40):                # drain the burn windows
            srv.agg.evaluate()
            time.sleep(0.05)
        assert fired["fired"], "seeded p99 breach never fired live"
        assert "serve_p99" not in srv.agg.manager.firing()

        # both hosts streamed, nothing dropped on the loopback path
        snap = srv.agg.snapshot()
        hostnames = {h["host"] for h in snap["hosts"].values()}
        assert hostnames == {"hostA", "hostB"}
        for h in snap["hosts"].values():
            assert h["dropped_frames"] == 0 and h["lost_frames"] == 0

        # live timeline == post-hoc merge_journals on identity + order
        live = srv.agg.merged_events()
        posthoc = telemetry.merge_journals(journals)

        def keys(evs):
            return [(e["host"], e["pid"], e["seq"]) for e in evs
                    if e.get("cat") == "span"
                    and e.get("name", "").startswith("soak.")]
        lk, pk = keys(live), keys(posthoc)
        assert set(lk) == set(pk), "live plane missed/duplicated events"
        assert lk == pk, "live ordering diverged from post-hoc merge"

        # continuous flame profile covered the soak's stacks
        flame = srv.agg.flame_counts()
        assert flame.get("soak.step;soak.work", 0) > 0, flame
        # ...and the post-hoc attribution meets the ≥90% gate per host
        for j in journals:
            from distributedarrays_tpu.telemetry.summarize import \
                read_journal
            counts, stats = tstream.collapsed_from_events(read_journal(j))
            assert stats["attributed_frac"] >= 0.9, (j, stats)

        code, body = _get(srv.url, "/metrics")
        text = body.decode()
        assert "da_tpu_stream_dropped_frames" in text
        assert "da_tpu_soak_ticks_total" in text
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.close()


@pytest.mark.slow
def test_streaming_overhead_under_three_percent(telemetry_capture):
    # min-of-repeats isolates the exporter's hot-path cost (a pull-based
    # design: recording calls never do streaming work) from scheduler
    # noise; <3% is the ISSUE acceptance bound
    def workload():
        t0 = time.perf_counter()
        for i in range(80000):
            telemetry.count("ovh.ticks")
            telemetry.set_gauge("ovh.gauge", float(i))
            if i % 500 == 0:
                telemetry.event("ovh", "tick", i=i)
        return time.perf_counter() - t0

    def drain(exp):
        # arming mid-run streams the pre-arm event backlog; let that
        # one-time catch-up finish before charging the steady-state path
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                (exp._last_seq < tcore._events_total - 1
                 or len(exp.ring) > 0):
            time.sleep(0.05)

    # the aggregator lives in its OWN process (as deployed): co-hosting
    # it would charge frame parsing + ingest to the workload's GIL and
    # measure the wrong thing
    srv = subprocess.Popen(
        [sys.executable, "-m", "distributedarrays_tpu.telemetry",
         "agg", "--port", "0", "--duration", "120", "--no-advertise"],
        cwd=str(REPO), stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "DA_TPU_TELEMETRY": "1"})
    url = None
    for line in srv.stderr:
        if "listening on" in line:
            url = line.rsplit(" ", 1)[-1].strip()
            break
    assert url, "aggregator CLI never reported its URL"
    workload()                            # warm
    rounds = []
    try:
        # interleave the two arms (off/on per pair) so both sample the
        # same machine states: this host's throughput is bimodal with a
        # ~2x swing (frequency scaling, noisy neighbors), far above the
        # 3% being measured.  Noise can only INFLATE an overhead
        # estimate, so the best round out of five bounds the true cost.
        for _ in range(5):
            offs, ons = [], []
            for _ in range(5):
                offs.append(workload())
                exp = tstream.start(url, interval_s=0.1)
                assert exp is not None
                try:
                    drain(exp)
                    ons.append(workload())
                finally:
                    tstream.stop()
            rounds.append((min(ons), min(offs)))
            if rounds[-1][0] <= rounds[-1][1] * 1.03:
                break
    finally:
        srv.kill()
        srv.wait(timeout=30)
    assert any(on <= off * 1.03 for on, off in rounds), rounds
