"""Performance-observatory suite: roofline classification against peak
tables, overlap-fraction and critical-path math on synthetic
hand-computed span timelines (fully-overlapped, fully-serial,
partial-overlap, multi-rank skew), the doctor CLI round-trip on the
scripted telemetry workload (tools/perf_workload.py — shared with the CI
observability leg), request-scoped trace ids from serve submit to
resolve, the Perfetto counter/flow/rank-track export additions, and the
noise-aware bench regression sentinel (``telemetry regress``)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu import telemetry as tm
from distributedarrays_tpu.parallel import spmd_mode as S
from distributedarrays_tpu.telemetry import perf, regress
from distributedarrays_tpu.telemetry.export import to_perfetto
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401
from distributedarrays_tpu.telemetry.summarize import read_journal

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# peak tables
# ---------------------------------------------------------------------------


def test_peak_table_defaults_and_aliases():
    assert perf.peaks_for("v5e")["flops"] == pytest.approx(197e12)
    assert perf.peaks_for("TPU v5 lite")["platform"] == "tpu-v5e"
    assert perf.peaks_for("v5p")["hbm"] == pytest.approx(2765e9)
    assert perf.peaks_for(None)["platform"] == "cpu"
    assert perf.peaks_for("some-unknown-chip")["platform"] == "cpu"


def test_peak_table_env_override_inline(monkeypatch):
    monkeypatch.setenv("DA_TPU_PEAKS", '{"cpu": {"flops": 123.0}}')
    p = perf.peaks_for("cpu")
    assert p["flops"] == 123.0
    assert p["hbm"] == perf.DEFAULT_PEAKS["cpu"]["hbm"]  # merged, not replaced
    # flat form applies to the selected platform
    monkeypatch.setenv("DA_TPU_PEAKS", '{"ici": 7.0}')
    assert perf.peaks_for("v5e")["ici"] == 7.0


def test_peak_table_env_override_path(monkeypatch, tmp_path):
    f = tmp_path / "peaks.json"
    f.write_text(json.dumps({"tpu-v5p": {"flops": 5.0}}))
    monkeypatch.setenv("DA_TPU_PEAKS", str(f))
    assert perf.peaks_for("v5p")["flops"] == 5.0
    # garbage env degrades to defaults, never raises
    monkeypatch.setenv("DA_TPU_PEAKS", "not json and not a path")
    assert perf.peaks_for("v5e")["flops"] == pytest.approx(197e12)


def test_cost_helpers():
    g = perf.gemm_cost(4, 5, 6, 2, out_itemsize=4)
    assert g["flops"] == 2 * 4 * 5 * 6
    assert g["bytes_hbm"] == (4 * 6 + 6 * 5) * 2 + 4 * 5 * 4
    a = perf.attention_cost(8, 2, 4, 4, p=4, causal=True)
    assert a["flops"] == 4 * 8 * 8 * 2 * 4 // 2
    assert a["bytes_ici"] == 3 * 2 * 8 * 2 * 4 * 4
    assert perf.reshard_cost(100, 30) == {
        "flops": 0, "bytes_hbm": 200, "bytes_ici": 30}


# ---------------------------------------------------------------------------
# synthetic span timelines
# ---------------------------------------------------------------------------


def _sp(sid, name, start, dur, parent=None, labels=None, tid=1):
    return {"cat": "span", "name": name, "span_id": sid,
            "parent_id": parent, "start": float(start),
            "dur": float(dur), "tid": tid,
            "labels": dict(labels or {})}


def test_classify_bound_classes():
    peaks = {"flops": 100.0, "hbm": 100.0, "ici": 100.0, "platform": "t"}
    evs = [
        _sp(1, "compute", 0, 1.0, labels={"flops": 90, "bytes_hbm": 10}),
        _sp(2, "hbm", 0, 1.0, labels={"flops": 10, "bytes_hbm": 80}),
        _sp(3, "ici", 0, 1.0, labels={"bytes_ici": 50}),
        _sp(4, "unstamped", 0, 1.0),
    ]
    out = {o["name"]: o for o in perf.classify(evs, peaks)}
    assert set(out) == {"compute", "hbm", "ici"}
    assert out["compute"]["bound"] == "compute"
    assert out["compute"]["roofline_frac"] == pytest.approx(0.9)
    assert out["hbm"]["bound"] == "hbm"
    assert out["ici"]["bound"] == "ici"
    assert out["ici"]["roofline_frac"] == pytest.approx(0.5)


def test_coverage_hand_computed():
    evs = [
        _sp(1, "root_unstamped", 0, 10.0),
        _sp(2, "stamped_child", 0, 9.0, parent=1,
            labels={"bytes_hbm": 1}),
        _sp(3, "stamped_root", 20, 5.0, labels={"flops": 1}),
    ]
    cov = perf.coverage(evs)
    assert cov["wall_s"] == pytest.approx(15.0)
    assert cov["attributed_s"] == pytest.approx(14.0)
    assert cov["fraction"] == pytest.approx(14 / 15, abs=1e-3)


def test_interval_overlap_cases():
    # fully overlapped
    full = perf.interval_overlap([(0, 4)], [(0, 6)])
    assert full["overlap_frac"] == pytest.approx(1.0)
    # fully serial
    serial = perf.interval_overlap([(0, 4)], [(4, 8)])
    assert serial["overlap_frac"] == pytest.approx(0.0)
    assert serial["unoverlapped_s"] == pytest.approx(4.0)
    # partial: comm [0,4], compute [2,8] -> 2 of 4 hidden
    part = perf.interval_overlap([(0, 4)], [(2, 8)])
    assert part["overlap_frac"] == pytest.approx(0.5)
    # multi-rank skew: comm on two ranks [0,2]+[1,3] (union [0,3]),
    # compute [2,5]+[3,6] (union [2,6]) -> hidden [2,3] = 1 of 3
    skew = perf.interval_overlap([(0, 2), (1, 3)], [(2, 5), (3, 6)])
    assert skew["comm_s"] == pytest.approx(3.0)
    assert skew["overlapped_s"] == pytest.approx(1.0)
    assert skew["overlap_frac"] == pytest.approx(1 / 3, abs=1e-3)


def test_timeline_overlap_groups_by_parent():
    evs = [
        _sp(1, "step", 0, 10.0),
        _sp(2, "send", 0, 4.0, parent=1, labels={"bytes_ici": 10}),
        _sp(3, "dot", 2, 6.0, parent=1, labels={"flops": 10}, tid=2),
    ]
    out = perf.timeline_overlap(evs)
    assert len(out) == 1
    assert out[0]["step"] == "step"
    assert out[0]["overlap_frac"] == pytest.approx(0.5)
    # explicit kind label overrides the stamp heuristic
    evs[2]["labels"] = {"kind": "compute"}
    assert perf.timeline_overlap(evs)[0]["overlap_frac"] == \
        pytest.approx(0.5)


def test_train_step_overlap_pinned_timeline():
    # hand-computed per-training-step grad-sync overlap: two train.step
    # parents, each with a compute (train.grad) and a comm (train.sync)
    # child.  Step 0: sync [4,8] vs grad [0,6] -> 2 of 4 hidden = 0.5.
    # Step 1: fully serial -> 0.0.  A non-train parent with the same
    # shape is ignored.
    evs = [
        _sp(1, "train.step", 0, 10.0,
            labels={"step": 0, "ranks": 8, "dispatch": "xla"}),
        _sp(2, "train.grad", 0, 6.0, parent=1,
            labels={"kind": "compute", "step": 0}),
        _sp(3, "train.sync", 4, 4.0, parent=1,
            labels={"kind": "comm", "step": 0}, tid=2),
        _sp(4, "train.step", 20, 10.0,
            labels={"step": 1, "ranks": 8, "dispatch": "xla"}),
        _sp(5, "train.grad", 20, 5.0, parent=4,
            labels={"kind": "compute", "step": 1}),
        _sp(6, "train.sync", 25, 3.0, parent=4,
            labels={"kind": "comm", "step": 1}),
        _sp(7, "other.step", 40, 10.0),
        _sp(8, "sync", 40, 4.0, parent=7, labels={"kind": "comm"}),
    ]
    out = perf.train_step_overlap(evs)
    assert [o["step"] for o in out] == [0, 1]
    assert out[0]["overlap_frac"] == pytest.approx(0.5)
    assert out[0]["comm_s"] == pytest.approx(4.0)
    assert out[0]["ranks"] == 8 and out[0]["dispatch"] == "xla"
    assert out[1]["overlap_frac"] == pytest.approx(0.0)
    assert out[1]["unoverlapped_s"] == pytest.approx(3.0)
    # analyze() surfaces the same numbers under "train_steps" and the
    # doctor rendering prints the per-step section
    a = perf.analyze(evs, peaks={"flops": 1.0, "hbm": 1.0, "ici": 1.0,
                                 "platform": "t"})
    assert [o["step"] for o in a["train_steps"]] == [0, 1]
    import io
    buf = io.StringIO()
    perf.format_analysis(a, buf)
    text = buf.getvalue()
    assert "grad-sync overlap per training step" in text
    assert "step 0" in text and "step 1" in text


def test_overlap_stats_model_tier():
    peaks = {"flops": 100.0, "hbm": 1e12, "ici": 100.0, "platform": "t"}
    labels = {"flops": 100, "bytes_ici": 100, "ranks": 5}
    # t_comm = t_work = 1.0.  Fully serial: dur = 2.0
    serial = perf.overlap_stats(_sp(1, "ring", 0, 2.0, labels=labels),
                                peaks)
    assert serial["overlap_frac"] == pytest.approx(0.0)
    assert serial["unoverlapped_s"] == pytest.approx(1.0)
    assert serial["steps"] == 4
    assert serial["per_step"]["unoverlapped_s"] == pytest.approx(0.25)
    # fully overlapped: dur = max(t_comm, t_work) = 1.0
    full = perf.overlap_stats(_sp(2, "ring", 0, 1.0, labels=labels),
                              peaks)
    assert full["overlap_frac"] == pytest.approx(1.0)
    assert full["unoverlapped_s"] == pytest.approx(0.0)
    # halfway: dur = 1.5
    half = perf.overlap_stats(_sp(3, "ring", 0, 1.5, labels=labels),
                              peaks)
    assert half["overlap_frac"] == pytest.approx(0.5)
    # no comm -> no entry
    assert perf.overlap_stats(
        _sp(4, "x", 0, 1.0, labels={"flops": 5}), peaks) is None


def test_critical_path_hand_computed():
    evs = [
        _sp(1, "root", 0, 10.0),
        _sp(2, "A", 0, 4.0, parent=1),
        _sp(3, "B", 5, 4.0, parent=1),
        _sp(4, "C", 6, 2.0, parent=3),
    ]
    path = perf.critical_path(evs)
    # timeline order: A 4s, root gap 1s, B 1s, C 2s, B 1s, root tail 1s
    assert [(s["name"], pytest.approx(s["self_s"])) for s in path] == [
        ("A", 4.0), ("root", 1.0), ("B", 1.0), ("C", 2.0), ("B", 1.0),
        ("root", 1.0)]
    assert sum(s["self_s"] for s in path) == pytest.approx(10.0)


def test_analyze_findings_ranked():
    peaks = {"flops": 100.0, "hbm": 1e12, "ici": 100.0, "platform": "t"}
    evs = [
        _sp(1, "ring", 0, 2.0,
            labels={"flops": 100, "bytes_ici": 100, "ranks": 3}),
        _sp(2, "fast", 0, 0.001, labels={"flops": 0.09}),
    ]
    a = perf.analyze(evs, peaks)
    assert a["findings"], "expected at least one finding"
    kinds = {f["kind"] for f in a["findings"]}
    assert "unoverlapped_comm" in kinds
    sev = [f["severity_s"] for f in a["findings"]]
    assert sev == sorted(sev, reverse=True)


# ---------------------------------------------------------------------------
# the doctor CLI round-trip on the scripted workload
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def workload_journal(tmp_path_factory):
    jpath = tmp_path_factory.mktemp("perf") / "journal.jsonl"
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "perf_workload.py"),
         str(jpath)],
        cwd=str(REPO), capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "DA_TPU_TELEMETRY": "1"})
    assert r.returncode == 0, r.stderr[-3000:]
    assert "perf-workload-ok" in r.stdout
    return jpath


def _doctor(jpath, *args):
    return subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.telemetry",
         "doctor", str(jpath), *args],
        cwd=str(REPO), capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_doctor_cli_acceptance(workload_journal):
    r = _doctor(workload_journal, "--json", "--min-findings", "1")
    assert r.returncode == 0, r.stderr[-2000:]
    a = json.loads(r.stdout)
    # >= 90% of span wall time is cost-classified
    assert a["coverage"]["fraction"] >= 0.9, a["coverage"]
    # a per-step overlap fraction for the RDMA-armed (interpret) reshard
    # AND its XLA twin
    resh = {o["dispatch"]: o for o in a["overlap"]
            if o["name"] == "reshard" and o.get("dispatch")}
    assert {"rdma", "xla"} <= set(resh), list(a["overlap"])
    for o in resh.values():
        assert "overlap_frac" in o and "per_step" in o and o["steps"] >= 1
    assert len(a["findings"]) >= 1
    # human rendering mentions the essentials
    r2 = _doctor(workload_journal)
    assert r2.returncode == 0
    assert "coverage:" in r2.stdout and "roofline" in r2.stdout
    assert "reshard" in r2.stdout


def test_doctor_min_findings_gate(workload_journal):
    r = _doctor(workload_journal, "--min-findings", "10000")
    assert r.returncode == 2
    assert "finding" in r.stderr


def test_workload_trace_ids_submit_to_resolve(workload_journal):
    journal = read_journal(str(workload_journal))
    spans = [e for e in journal if e.get("cat") == "span"]
    submits = [s for s in spans if s["name"] == "serve.submit"]
    assert submits, "no serve.submit spans in the journal"
    for sub in submits:
        tids = sub.get("trace_id") or []
        assert len(tids) == 1, sub
        tid = tids[0]
        carrying = {s["name"] for s in spans
                    if tid in (s.get("trace_id") or [])}
        # every stage of the journey carries the id: submit, the batch
        # dispatch, the resolve, and the SPMD rank steps under it
        assert {"serve.submit", "serve.dispatch", "serve.resolve",
                "spmd.run", "spmd.step"} <= carrying, (tid, carrying)


def test_workload_perfetto_counters_flows_ranktracks(workload_journal):
    journal = read_journal(str(workload_journal))
    t = to_perfetto(journal)["traceEvents"]
    counters = {e["name"] for e in t if e["ph"] == "C"}
    assert "serve.queue_depth" in counters
    assert any(c.startswith("serve.tokens") for c in counters), counters
    # flows: at least one request chains >= 2 spans with s .. f phases
    flows = [e for e in t if e.get("cat") == "trace"]
    assert {"s", "f"} <= {e["ph"] for e in flows}
    # rank-labeled spans land on synthetic per-rank tracks with names
    names = {e["args"]["name"] for e in t if e["ph"] == "M"}
    assert {"rank 0", "rank 1"} <= names, names
    rank_tids = {e["tid"] for e in t
                 if e["ph"] == "X"
                 and str((e.get("args") or {}).get("rank")) in ("0", "1")}
    assert len(rank_tids) >= 2


# ---------------------------------------------------------------------------
# serve trace ids + SLO histograms (in-process)
# ---------------------------------------------------------------------------


def test_serve_trace_id_on_every_span_and_slo(telemetry_capture):
    from distributedarrays_tpu.serve import Server, ServeConfig
    srv = Server(ServeConfig(max_batch=2, flush_s=0.002))

    def ep(payloads):
        return [sum(S.spmd(lambda: S.myid(), pids=[0, 1]))
                + float(np.sum(p)) for p in payloads]

    srv.register("echo", ep)
    fut = srv.submit("echo", np.ones((2, 2), dtype=np.float32))
    assert fut.result(timeout=30) == pytest.approx(5.0)
    srv.close()
    spans = telemetry_capture.spans()
    sub = [s for s in spans if s["name"] == "serve.submit"][0]
    tid = sub["trace_id"][0]
    assert tid.startswith("req-")
    for name in ("serve.submit", "serve.dispatch", "serve.resolve",
                 "spmd.run"):
        got = [s for s in spans if s["name"] == name
               and tid in (s.get("trace_id") or [])]
        assert got, (name, tid)
    steps = [s for s in spans if s["name"] == "spmd.step"
             and tid in (s.get("trace_id") or [])]
    assert {s["labels"]["rank"] for s in steps} == {0, 1}
    # caller-supplied trace ids propagate verbatim
    fut = srv = None
    # SLO histogram in the report and the Prometheus export
    rep = telemetry_capture.report()
    slo = [k for k in rep["histograms"] if k.startswith("serve.slo")]
    assert slo and "buckets" in rep["histograms"][slo[0]]
    prom = telemetry_capture.to_prometheus()
    lines = [ln for ln in prom.splitlines()
             if ln.startswith("da_tpu_serve_slo_request_s_bucket")]
    assert lines, prom[:2000]
    assert any('le="+Inf"' in ln for ln in lines)
    # cumulative: +Inf equals _count
    inf = next(ln for ln in lines if 'le="+Inf"' in ln)
    count_ln = next(ln for ln in prom.splitlines()
                    if ln.startswith("da_tpu_serve_slo_request_s_count"))
    assert inf.rsplit(" ", 1)[1] == count_ln.rsplit(" ", 1)[1]
    dat.d_closeall()


def test_serve_caller_supplied_trace_id(telemetry_capture):
    from distributedarrays_tpu.serve import Server, ServeConfig
    srv = Server(ServeConfig(max_batch=1, flush_s=0.0))
    srv.register("e", lambda ps: [0 for _ in ps])
    fut = srv.submit("e", 1, trace_id="my-trace-42")
    fut.result(timeout=30)
    srv.close()
    d = [s for s in telemetry_capture.spans("serve.dispatch")
         if "my-trace-42" in (s.get("trace_id") or [])]
    assert d


def test_spmd_process_backend_rank_spans(telemetry_capture):
    if not hasattr(os, "fork"):
        pytest.skip("needs POSIX fork")
    S.spmd(lambda: 7, pids=[0, 1], backend="process")
    steps = [s for s in telemetry_capture.spans("spmd.step")
             if (s.get("labels") or {}).get("backend") == "process"]
    assert {s["labels"]["rank"] for s in steps} == {0, 1}
    for s in steps:
        assert s["dur"] is not None and s["dur"] >= 0


def test_elastic_gauge_counter_track(telemetry_capture):
    from distributedarrays_tpu.resilience import elastic
    m = elastic.manager()
    m.reset()
    m.probe()
    journal = read_journal(telemetry_capture.journal_path())
    gauges = [e for e in journal if e.get("cat") == "gauge"
              and e.get("name") == "elastic.live_devices"]
    assert gauges, [e.get("name") for e in journal]
    t = to_perfetto(journal)["traceEvents"]
    assert any(e["ph"] == "C" and e["name"] == "elastic.live_devices"
               for e in t)
    m.reset()


# ---------------------------------------------------------------------------
# the regression sentinel
# ---------------------------------------------------------------------------


def test_regress_direction_inference():
    assert regress.direction("gemm_4096_mixed_bf16pass_s_per_iter") == -1
    assert regress.direction("serve_load_p99_s") == -1
    assert regress.direction("gemm_4096_mixed_bf16pass_gflops") == 1
    assert regress.direction("sp_train_tokens_per_s") == 1
    # the banked headline metric carries its unit MID-name — the token
    # fallback must judge it, or the sentinel never guards the one row
    # the trajectory actually banks
    assert regress.direction("gemm_4096_gflops_mixed_precision_bf16pass") == 1
    # ... but an anchored suffix still wins over a mid-name token
    assert regress.direction("gemm_gflops_probe_s") == -1
    assert regress.direction("flash_attn_d128_tuned_block") == 0
    assert regress.direction("reshard_even_comm_bytes_est") == 0
    assert regress.direction("something_unknowable") == 0
    # solver rows: iteration counts and final residuals are
    # lower-is-better (a regressed preconditioner shows up as MORE
    # iterations at the same tolerance, not slower ones)
    assert regress.direction("cg_poisson_iters") == -1
    assert regress.direction("mgcg_iterations") == -1
    assert regress.direction("cg_poisson_residual") == -1
    assert regress.direction("cg_poisson_gbps") == 1


def test_regress_replay_detection():
    assert regress.is_replay({"replayed": True})
    assert regress.is_replay(
        {"note": "replayed from the banked table measured ..."})
    assert not regress.is_replay({"note": "fresh", "value": 1.0})


def test_regress_compare_noise_aware():
    baseline = {"x_gflops": [100.0, 103.0, 98.0, 101.0]}
    ok = regress.compare({"x_gflops": 97.0}, baseline)
    assert ok[0]["status"] == "ok"
    bad = regress.compare({"x_gflops": 50.0}, baseline)
    assert bad[0]["status"] == "regression"
    up = regress.compare({"x_gflops": 200.0}, baseline)
    assert up[0]["status"] == "improved"
    # lower-better metric: a 2x slowdown flags
    lb = {"y_s": [1.0, 1.02, 0.99]}
    assert regress.compare({"y_s": 2.0}, lb)[0]["status"] == "regression"
    assert regress.compare({"y_s": 1.05}, lb)[0]["status"] == "ok"
    # with < min_points the threshold is the conservative 50%
    two = regress.compare({"y_s": 2.1}, {"y_s": [1.0, 1.01]})
    assert two[0]["status"] == "regression"
    assert regress.compare({"y_s": 1.4},
                           {"y_s": [1.0, 1.01]})[0]["status"] == "ok"


def _fixture_trajectory(d: Path, values, metric="gemm_4096_gflops"):
    for i, v in enumerate(values, start=1):
        (d / f"BENCH_r{i:02d}.json").write_text(json.dumps(
            {"n": i, "parsed": {"metric": metric, "value": v,
                                "unit": "GFLOPS"}}))


def test_regress_baseline_excludes_replays_and_errors(tmp_path):
    _fixture_trajectory(tmp_path, [100.0, 102.0, 99.0])
    # a replayed round and an errored round must not enter the series
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"n": 4, "parsed": {"metric": "gemm_4096_gflops", "value": 55.0,
                            "replayed": True, "note": "replayed from the "
                            "banked table measured x"}}))
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"n": 5, "parsed": {"metric": "gemm_4096_gflops", "value": 0.0,
                            "error": "accelerator unreachable"}}))
    series = regress.load_baseline([str(tmp_path)])
    assert series["gemm_4096_gflops"] == [100.0, 102.0, 99.0]


def _regress_cli(fresh, baseline_dir, *args):
    return subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.telemetry",
         "regress", str(fresh), "--baseline", str(baseline_dir), *args],
        cwd=str(REPO), capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_regress_cli_green_and_2x_slowdown(tmp_path):
    # a lower-is-better trajectory with ~2% noise
    _fixture_trajectory(tmp_path, [1.00, 1.02, 0.99, 1.01],
                        metric="gemm_4096_mixed_bf16pass_s_per_iter")
    ok = tmp_path / "fresh_ok.json"
    ok.write_text(json.dumps(
        {"metric": "gemm_4096_mixed_bf16pass_s_per_iter", "value": 1.03}))
    r = _regress_cli(ok, tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    # the injected 2x slowdown flags and exits 1
    bad = tmp_path / "fresh_bad.json"
    bad.write_text(json.dumps(
        {"metric": "gemm_4096_mixed_bf16pass_s_per_iter", "value": 2.0}))
    r = _regress_cli(bad, tmp_path)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout


def test_regress_cli_replay_and_strict(tmp_path):
    _fixture_trajectory(tmp_path, [100.0, 101.0, 99.0])
    replay = tmp_path / "fresh_replay.json"
    replay.write_text(json.dumps(
        {"metric": "gemm_4096_gflops", "value": 60.0, "replayed": True}))
    r = _regress_cli(replay, tmp_path)
    assert r.returncode == 0 and "SKIPPED" in r.stdout
    r = _regress_cli(replay, tmp_path, "--strict")
    assert r.returncode == 2
    # a details-table fresh input with no matching baseline judges
    # nothing: rc 0 by default, 2 under --strict
    lonely = tmp_path / "fresh_lonely.json"
    lonely.write_text(json.dumps({"unrelated_metric_gflops": 5.0}))
    assert _regress_cli(lonely, tmp_path).returncode == 0
    assert _regress_cli(lonely, tmp_path, "--strict").returncode == 2


def test_bench_replay_row_is_machine_flagged():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_for_test", str(REPO / "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    row = bench._replay_row(
        152021.34, 114.2,
        {"utc": "2026-07-31T06:50:08Z", "device_kind": "TPU v5 lite"},
        "accelerator unreachable after 5 attempts")
    assert row["replayed"] is True
    assert row["probe_error"].startswith("accelerator unreachable")
    assert row["replayed_from_utc"] == "2026-07-31T06:50:08Z"
    assert regress.is_replay(row)
    # and load_rows refuses to treat it as a fresh measurement
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump({"parsed": row}, f)
    try:
        assert regress.load_rows(f.name) == {}
    finally:
        os.unlink(f.name)


def test_annotate_and_trace_ctx_disabled_are_silent(tmp_path):
    code = (
        "import distributedarrays_tpu.telemetry as tm\n"
        "tm.annotate(flops=1)\n"
        "with tm.trace_ctx('x') as ids:\n"
        "    assert ids is None\n"
        "    with tm.span('s', flops=1) as sp:\n"
        "        assert sp is None\n"
        "assert tm.current_trace_ids() == ()\n"
        "assert tm.report()['spans']['finished'] == 0\n"
        "print('SILENT-OK')\n")
    r = subprocess.run(
        [sys.executable, "-c", code], cwd=str(REPO), capture_output=True,
        text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "DA_TPU_TELEMETRY": "0"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "SILENT-OK" in r.stdout
