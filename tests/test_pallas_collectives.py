"""Interpret-mode oracle suite for the Pallas RDMA ring collectives.

Every RDMA kernel must be bit-identical to its ``lax`` counterpart (the
collectives are pure data movement; the GEMM/reduction kernels are
exercised on integer-valued operands so reassociation cannot round).
Dispatch is exercised through every gate: forced interpret mode, the
``DA_TPU_RDMA=0`` kill switch, missing ``pltpu``, explicit-request
fallback accounting, chunk-depth resolution precedence, and the reshard
planner's RDMA arm (planner ≡ ``device_put`` oracle re-run, staging
bound under a forced tiny chunk target).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import distributedarrays_tpu as dat
from distributedarrays_tpu import layout as L
from distributedarrays_tpu import telemetry as tm
from distributedarrays_tpu.ops import pallas_collectives as PC
from distributedarrays_tpu.ops.collective_matmul import (
    allgather_matmul, allgather_matmul_rhs, matmul_reducescatter)
from distributedarrays_tpu.parallel import reshard as R
from distributedarrays_tpu.parallel.collectives import run_spmd, spmd_mesh


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _ints(rng, shape, dtype=np.float32, lo=-8, hi=8):
    return rng.integers(lo, hi, shape).astype(dtype)


# ---------------------------------------------------------------------------
# kernel <-> lax bit-identity oracles
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("dim,dtype", [(0, np.float32), (1, np.float32),
                                       (0, np.int32)])
def test_ring_all_gather_oracle(p, dim, dtype, rng):
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 4, 2 * 128), dtype)
    spec = P("p", None)
    out = P(None, None)
    y1 = run_spmd(lambda a: PC.ring_all_gather(a, "p", dim=dim,
                                               interpret=True),
                  mesh, (spec,), out)(x)
    y2 = run_spmd(lambda a: lax.all_gather(a, "p", axis=dim, tiled=True),
                  mesh, (spec,), out)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_ring_all_gather_bf16_3d(rng):
    p = 8
    mesh = spmd_mesh(p)
    x = jnp.asarray(_ints(rng, (p * 2, 4, 128)), jnp.bfloat16)
    spec = P("p", None, None)
    out = P(None, None, None)
    y1 = run_spmd(lambda a: PC.ring_all_gather(a, "p", dim=1,
                                               interpret=True),
                  mesh, (spec,), out)(x)
    y2 = run_spmd(lambda a: lax.all_gather(a, "p", axis=1, tiled=True),
                  mesh, (spec,), out)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("chunks", [None, 4])
def test_ring_all_to_all_oracle(p, chunks, rng):
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 4, p * 12))
    spec = P("p", None)
    y1 = run_spmd(lambda a: PC.ring_all_to_all(
        a, "p", split_dim=1, concat_dim=0, chunks=chunks, interpret=True),
        mesh, (spec,), spec)(x)
    y2 = run_spmd(lambda a: lax.all_to_all(
        a, "p", split_axis=1, concat_axis=0, tiled=True),
        mesh, (spec,), spec)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("chunks", [None, 4])
def test_ring_reduce_scatter_oracle(p, chunks, rng):
    mesh = spmd_mesh(p)
    # integer-valued so the ring's summation order is exact
    x = _ints(rng, (p * p * 4, 64))
    spec = P("p", None)
    y1 = run_spmd(lambda a: PC.ring_reduce_scatter(
        a, "p", dim=0, chunks=chunks, interpret=True),
        mesh, (spec,), spec)(x)
    y2 = run_spmd(lambda a: lax.psum_scatter(
        a, "p", scatter_dimension=0, tiled=True),
        mesh, (spec,), spec)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


@pytest.mark.parametrize("p", [2, 4, 8])
def test_fused_allgather_matmul_oracle(p, rng):
    mesh = spmd_mesh(p)
    m_loc, k, n = 8, 4 * p, 16
    x = _ints(rng, (p * m_loc, k), lo=-4, hi=4)
    w = _ints(rng, (k, n), lo=-4, hi=4)
    specs = (P("p", None), P(None, None))
    out = P(None, None)
    y1 = run_spmd(lambda a, b: allgather_matmul(a, b, "p", rdma=True,
                                                interpret=True),
                  mesh, specs, out)(x, w)
    y2 = run_spmd(lambda a, b: allgather_matmul(a, b, "p"),
                  mesh, specs, out)(x, w)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    np.testing.assert_array_equal(np.asarray(y1), x @ w)


@pytest.mark.parametrize("p", [2, 4, 8])
def test_fused_allgather_matmul_rhs_oracle(p, rng):
    mesh = spmd_mesh(p)
    a = _ints(rng, (p * 8, p * 8), lo=-4, hi=4)
    b = _ints(rng, (p * 8, 16), lo=-4, hi=4)
    specs = (P("p", None), P("p", None))
    out = P("p", None)
    y1 = run_spmd(lambda aa, bb: allgather_matmul_rhs(
        aa, bb, "p", rdma=True, interpret=True), mesh, specs, out)(a, b)
    y2 = run_spmd(lambda aa, bb: allgather_matmul_rhs(aa, bb, "p"),
                  mesh, specs, out)(a, b)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    np.testing.assert_array_equal(np.asarray(y1), a @ b)


@pytest.mark.parametrize("p", [2, 4, 8])
def test_fused_matmul_reducescatter_oracle(p, rng):
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 8, 8 * p), lo=-4, hi=4)
    w = _ints(rng, (8 * p, 16), lo=-4, hi=4)
    specs = (P(None, "p"), P("p", None))
    out = P("p", None)
    y1 = run_spmd(lambda a, b: matmul_reducescatter(
        a, b, "p", rdma=True, interpret=True), mesh, specs, out)(x, w)
    y2 = run_spmd(lambda a, b: matmul_reducescatter(a, b, "p"),
                  mesh, specs, out)(x, w)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    np.testing.assert_array_equal(np.asarray(y1), x @ w)


@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_rdma_ring_attention_oracle(p, causal, rng):
    from distributedarrays_tpu.models.ring_attention import (
        reference_attention, ring_attention_kernel,
        ring_attention_rdma_kernel)
    mesh = spmd_mesh(p)
    b, h, dh = 16, 2, 32
    q, k, v = (rng.standard_normal((p * b, h, dh)).astype(np.float32)
               for _ in range(3))
    spec = P("p", None, None)
    y1 = run_spmd(lambda a, bb, c: ring_attention_rdma_kernel(
        a, bb, c, "p", causal=causal, interpret=True),
        mesh, (spec,) * 3, spec)(q, k, v)
    y2 = run_spmd(lambda a, bb, c: ring_attention_kernel(
        a, bb, c, "p", causal=causal), mesh, (spec,) * 3, spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(y1),
                               reference_attention(q, k, v, causal=causal),
                               atol=1e-4)


def test_ring_attention_darray_entry_rdma(monkeypatch, rng):
    # the DArray entry dispatches through rdma_mode(): armed-in-interpret
    # it must produce the same result as the XLA path
    from distributedarrays_tpu.models.ring_attention import ring_attention
    p, b, h, dh = 8, 8, 2, 16
    q, k, v = (rng.standard_normal((p * b, h, dh)).astype(np.float32)
               for _ in range(3))
    ds = dict(procs=list(range(p)), dist=[p, 1, 1])
    dq, dk, dv = (dat.distribute(a, **ds) for a in (q, k, v))
    out_xla = np.asarray(ring_attention(dq, dk, dv, causal=True))
    monkeypatch.setenv("DA_TPU_RDMA", "interpret")
    out_rdma = np.asarray(ring_attention(dq, dk, dv, causal=True))
    np.testing.assert_allclose(out_rdma, out_xla, atol=1e-5)
    dat.d_closeall()


# ---------------------------------------------------------------------------
# dispatch gates
# ---------------------------------------------------------------------------


def test_kill_switch_forces_xla(monkeypatch):
    monkeypatch.setenv("DA_TPU_RDMA", "0")
    assert PC.rdma_mode() is None
    assert PC.rdma_mode(interpret=True) is None   # kill switch dominates
    monkeypatch.setenv("DA_TPU_RDMA", "interpret")
    assert PC.rdma_mode() == "interpret"
    monkeypatch.delenv("DA_TPU_RDMA")
    # auto mode on CPU: quiet fallback
    assert PC.rdma_mode() is None


def test_missing_pltpu_falls_back(monkeypatch):
    monkeypatch.setattr(PC, "pltpu", None)
    assert PC.rdma_mode(interpret=True) is None
    assert PC.rdma_mode() is None


def test_explicit_request_counts_fallback_hits(monkeypatch, rng):
    from distributedarrays_tpu.utils import debug as dbg
    monkeypatch.setenv("DA_TPU_RDMA", "1")
    key = "pallas_collectives:platform not tpu"
    dbg._warned.discard(key)
    before = tm.counter_value("fallback.hits", key=key)
    with pytest.warns(RuntimeWarning, match="DA_TPU_RDMA requested"):
        assert PC.rdma_mode() is None
    assert tm.counter_value("fallback.hits", key=key) == before + 1
    # warned once, counted every time
    assert PC.rdma_mode() is None
    assert tm.counter_value("fallback.hits", key=key) == before + 2


def test_xla_fallback_is_bit_identical(monkeypatch, rng):
    # with RDMA killed the wrappers ARE the lax collectives
    monkeypatch.setenv("DA_TPU_RDMA", "0")
    p = 4
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 4, 128))
    spec = P("p", None)
    out = P(None, None)
    before = tm.counter_value("pallas_collectives.dispatch",
                              op="ring_all_gather", path="xla")
    y1 = run_spmd(lambda a: PC.ring_all_gather(a, "p", interpret=True),
                  mesh, (spec,), out)(x)
    y2 = run_spmd(lambda a: lax.all_gather(a, "p", axis=0, tiled=True),
                  mesh, (spec,), out)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    assert tm.counter_value("pallas_collectives.dispatch",
                            op="ring_all_gather", path="xla") > before


def test_rdma_dispatch_counter_labels(rng):
    p = 4
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 4, 128))
    before = tm.counter_value("pallas_collectives.dispatch",
                              op="ring_all_gather", path="rdma")
    run_spmd(lambda a: PC.ring_all_gather(a, "p", interpret=True),
             mesh, (P("p", None),), P(None, None))(x)
    assert tm.counter_value("pallas_collectives.dispatch",
                            op="ring_all_gather", path="rdma") > before


def test_split_equals_concat_keeps_lax(rng):
    # split_dim == concat_dim is outside the direct-scatter scheme
    p = 4
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 8, 16))
    spec = P("p", None)
    y1 = run_spmd(lambda a: PC.ring_all_to_all(
        a, "p", split_dim=0, concat_dim=0, interpret=True),
        mesh, (spec,), spec)(x)
    y2 = run_spmd(lambda a: lax.all_to_all(
        a, "p", split_axis=0, concat_axis=0, tiled=True),
        mesh, (spec,), spec)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


# ---------------------------------------------------------------------------
# chunk-depth knob
# ---------------------------------------------------------------------------


def test_resolve_chunks_precedence(monkeypatch):
    from distributedarrays_tpu.utils import autotune
    # derived: from DA_TPU_RESHARD_CHUNK_MB
    monkeypatch.delenv(PC.CHUNKS_ENV, raising=False)
    monkeypatch.setenv("DA_TPU_RESHARD_CHUNK_MB", "1")
    n, src = PC.resolve_chunks(3 * 2**20, "t1", 1, 2)
    assert (n, src) == (3, "derived")
    # autotune entry beats derived
    key = autotune.device_key_for("t1", 1, 2)
    autotune.record("rdma_chunks", key, (7,))
    try:
        n, src = PC.resolve_chunks(3 * 2**20, "t1", 1, 2)
        assert (n, src) == (7, "autotune")
        # malformed entry degrades to derived
        autotune.record("rdma_chunks", key, "garbage")
        n, src = PC.resolve_chunks(3 * 2**20, "t1", 1, 2)
        assert (n, src) == (3, "derived")
        # env beats everything
        monkeypatch.setenv(PC.CHUNKS_ENV, "5")
        n, src = PC.resolve_chunks(3 * 2**20, "t1", 1, 2)
        assert (n, src) == (5, "env")
    finally:
        autotune.record("rdma_chunks", key, None)


def test_chunk_fit_divisors():
    assert PC._chunk_fit(12, 5) == 4
    assert PC._chunk_fit(12, 100) == 12
    assert PC._chunk_fit(7, 3) == 1
    assert PC._chunk_fit(8, 0) == 1


# ---------------------------------------------------------------------------
# reshard planner with RDMA armed
# ---------------------------------------------------------------------------


_GRIDS_2D = [(8, 1), (1, 8), (4, 1), (1, 4), (2, 1), (1, 2), (1, 1),
             (4, 2), (2, 4)]


def _shardings_for(shape, grid):
    n = int(np.prod(grid))
    return L.sharding_for(list(range(n)), grid, shape)


def test_reshard_oracle_sweep_rdma_armed(monkeypatch, rng):
    # the PR 4 planner ≡ device_put oracle sweep, re-run with the RDMA
    # kernels armed in interpret mode: every grid pair must still be
    # byte-identical, and the collective strategies must have dispatched
    # on the rdma path
    import itertools
    monkeypatch.setenv("DA_TPU_RDMA", "interpret")
    shape = (16, 24)
    A = rng.standard_normal(shape).astype(np.float32)
    seen = set()
    before = tm.counter_value("pallas_collectives.dispatch",
                              op="ring_all_to_all", path="rdma")
    for gs, gd in itertools.product(_GRIDS_2D, _GRIDS_2D):
        src, dst = _shardings_for(shape, gs), _shardings_for(shape, gd)
        x = jax.device_put(A, src)
        plan = R.plan_reshard(x, dst)
        seen.add(plan.strategy)
        y = R.reshard(x, dst)
        oracle = jax.device_put(A, dst)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(oracle))
    # (sharded -> replicated pairs are exercised by the staging-bound
    # test: this sweep's (1,1) grid is a single device, not replication)
    assert "all_to_all" in seen
    assert tm.counter_value("pallas_collectives.dispatch",
                            op="ring_all_to_all", path="rdma") > before


def test_reshard_rdma_staging_bound(monkeypatch, rng):
    # acceptance: under a forced tiny chunk target with RDMA armed, the
    # recorded staging high-water stays within 2x the budget
    from distributedarrays_tpu.telemetry import memory as tmem
    monkeypatch.setenv("DA_TPU_RDMA", "interpret")
    monkeypatch.setenv("DA_TPU_RESHARD_CHUNK_MB", "0.0005")
    target = int(0.0005 * 2**20)
    shape = (64, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _shardings_for(shape, (8, 1)), _shardings_for(shape, (1, 8))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "all_to_all" and plan.nchunks > 1
    y = R.reshard(x, dst, plan=plan)
    np.testing.assert_array_equal(np.asarray(y), A)
    assert tmem.staging_peak("reshard.all_to_all") <= 2 * target
    rep = NamedSharding(src.mesh, P())
    plang = R.plan_reshard(x, rep)
    assert plang.strategy == "all_gather"
    z = R.reshard(x, rep, plan=plang)
    np.testing.assert_array_equal(np.asarray(z), A)
    assert tmem.staging_peak("reshard.all_gather") <= 2 * target


def test_reshard_span_labels_dispatch(monkeypatch, rng):
    from distributedarrays_tpu.telemetry import tracing
    monkeypatch.setenv("DA_TPU_RDMA", "interpret")
    shape = (16, 24)
    A = rng.standard_normal(shape).astype(np.float32)
    x = jax.device_put(A, _shardings_for(shape, (8, 1)))
    R.reshard(x, _shardings_for(shape, (1, 8)))
    labeled = [s for s in tracing.spans("reshard")
               if s.get("labels", {}).get("dispatch") == "rdma"]
    assert labeled, "no reshard span labeled dispatch=rdma"
    assert "rdma_chunks" in labeled[-1]["labels"]


def test_reshard_rdma_vs_xla_bit_identical(monkeypatch, rng):
    # flipping the env re-jits (the program is keyed on the mode) and
    # both lowerings produce identical bytes
    shape = (32, 40)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _shardings_for(shape, (8, 1)), _shardings_for(shape, (1, 8))
    x = jax.device_put(A, src)
    monkeypatch.setenv("DA_TPU_RDMA", "0")
    y_xla = np.asarray(R.reshard(x, dst))
    monkeypatch.setenv("DA_TPU_RDMA", "interpret")
    y_rdma = np.asarray(R.reshard(x, dst))
    np.testing.assert_array_equal(y_xla, y_rdma)


# ---------------------------------------------------------------------------
# no discarded final hop (the satellite fix): the last ring iteration
# must not pay a ppermute whose result is thrown away
# ---------------------------------------------------------------------------


class _PermuteCounter:
    def __init__(self, monkeypatch):
        self.n = 0
        real = lax.ppermute

        def counted(*a, **k):
            self.n += 1
            return real(*a, **k)

        monkeypatch.setattr(jax.lax, "ppermute", counted)


def test_ring_attention_no_final_rotation(monkeypatch):
    # the dense ring kernel's final accumulate is unrolled outside the
    # loop WITHOUT a rotation: exactly 2 trace-time ppermutes (k and v,
    # inside the loop body), none in the epilogue
    from distributedarrays_tpu.models import ring_attention as RA
    mesh = spmd_mesh(4)
    spec = P("p", None, None)
    cnt = _PermuteCounter(monkeypatch)
    fn = run_spmd(lambda q, k, v: RA.ring_attention_kernel(q, k, v, "p"),
                  mesh, (spec,) * 3, spec)
    s = jax.ShapeDtypeStruct((16, 2, 8), jnp.float32)
    fn.lower(s, s, s)
    assert cnt.n == 2, f"expected 2 traced ppermutes, got {cnt.n}"


def test_pipeline_skips_final_tick_send(monkeypatch):
    # GPipe: one in-loop send, none in the unrolled final tick; 1F1B:
    # two in-loop sends (activation down + cotangent up), none final
    from distributedarrays_tpu.models import pipeline as PL
    mesh = spmd_mesh(4)
    PL._pipeline_jit.cache_clear()
    cnt = _PermuteCounter(monkeypatch)
    fn = PL._pipeline_jit(mesh)
    fn.lower(jax.ShapeDtypeStruct((4, 2, 8), jnp.float32),
             jax.ShapeDtypeStruct((4, 1, 8, 8), jnp.float32),
             jax.ShapeDtypeStruct((4, 1, 8), jnp.float32))
    assert cnt.n == 1, f"GPipe: expected 1 traced ppermute, got {cnt.n}"


def test_pipeline_forward_unchanged_by_hop_skip(rng):
    # semantic pin for the skip: pipeline output still equals the
    # sequential stage composition
    from distributedarrays_tpu.models import pipeline as PL
    mesh = spmd_mesh(4)
    M, B, H = 5, 3, 8
    W = rng.standard_normal((4, 1, H, H)).astype(np.float32) * 0.3
    b = rng.standard_normal((4, 1, H)).astype(np.float32) * 0.1
    mb = rng.standard_normal((M, B, H)).astype(np.float32)
    out = np.asarray(PL.pipeline_forward({"W": W, "b": b}, mb, mesh))
    want = mb
    for s in range(4):
        want = np.asarray(PL._stage_fn(jnp.asarray(want.reshape(M * B, H)),
                                       jnp.asarray(W[s]),
                                       jnp.asarray(b[s]))).reshape(M, B, H)
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# VMEM gates + comm accounting
# ---------------------------------------------------------------------------


def test_gemm_ring_eligibility_gate():
    # a tile set over the scoped-VMEM budget must be rejected for the
    # compiled path (CPU: judge the predicate directly)
    assert PC.gemm_ring_eligible("ag", (128, 512), (512, 256), 4, 4)
    assert not PC.gemm_ring_eligible("ag", (4096, 4096), (4096, 4096), 4, 4)
    assert PC.gemm_ring_eligible("rs", (256, 128), (128, 256), 4, 4)


def test_comm_bytes_recorded_on_dispatch(rng):
    p = 4
    mesh = spmd_mesh(p)
    x = _ints(rng, (p * 4, 128))
    before = tm.comm_bytes("ring_all_gather")
    run_spmd(lambda a: PC.ring_all_gather(a, "p", interpret=True),
             mesh, (P("p", None),), P(None, None))(x)
    after = tm.comm_bytes("ring_all_gather")
    assert after > before


def test_disabled_telemetry_subprocess():
    # the dispatch path must collapse to plain work under
    # DA_TPU_TELEMETRY=0 (no counter writes, identical numerics)
    code = (
        "import numpy as np\n"
        "import jax, jax.numpy as jnp\n"
        "from jax import lax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from distributedarrays_tpu.parallel.collectives import "
        "run_spmd, spmd_mesh\n"
        "from distributedarrays_tpu.ops import pallas_collectives as PC\n"
        "import distributedarrays_tpu.telemetry as tm\n"
        "assert not tm.enabled()\n"
        "p = 4\n"
        "mesh = spmd_mesh(p)\n"
        "x = np.arange(p * 4 * 128, dtype=np.float32)"
        ".reshape(p * 4, 128)\n"
        "y1 = run_spmd(lambda a: PC.ring_all_gather(a, 'p', "
        "interpret=True), mesh, (P('p', None),), P(None, None))(x)\n"
        "y2 = run_spmd(lambda a: lax.all_gather(a, 'p', axis=0, "
        "tiled=True), mesh, (P('p', None),), P(None, None))(x)\n"
        "assert np.array_equal(np.asarray(y1), np.asarray(y2))\n"
        "print('OK')\n"
    )
    env = dict(os.environ, DA_TPU_TELEMETRY="0", JAX_PLATFORMS="cpu")
    env.pop("DA_TPU_RDMA", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.dirname(__file__)))
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


# ---------------------------------------------------------------------------
# mesh-coordinate addressing (PR 19: per-axis sub-rings on 2-D meshes)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("grid,axis_i", [((4, 2), 0), ((4, 2), 1),
                                         ((2, 2, 2), 1)])
def test_ring_all_gather_mesh_axes_oracle(grid, axis_i, rng):
    # armed along one axis of a multi-axis mesh, the kernel must equal
    # the per-axis lax.all_gather (on CPU the interpret demotion routes
    # through the lax fallback — the dispatch seam under test)
    mesh = L.mesh_for(list(range(int(np.prod(grid)))), grid)
    names = mesh.axis_names
    ax = names[axis_i]
    ndim = len(grid)
    x = _ints(rng, tuple(8 * g for g in grid))
    spec = P(*names)
    out = P(*[None if i == axis_i else names[i] for i in range(ndim)])
    y1 = run_spmd(lambda a: PC.ring_all_gather(
        a, ax, dim=axis_i, interpret=True, mesh_axes=names),
        mesh, (spec,), out)(x)
    y2 = run_spmd(lambda a: lax.all_gather(a, ax, axis=axis_i, tiled=True),
                  mesh, (spec,), out)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_ring_all_to_all_mesh_axes_oracle(rng):
    grid = (4, 2)
    mesh = L.mesh_for(list(range(8)), grid)
    names = mesh.axis_names
    x = _ints(rng, (32, 16))
    spec = P("d0", "d1")
    y1 = run_spmd(lambda a: PC.ring_all_to_all(
        a, "d0", split_dim=1, concat_dim=0, interpret=True,
        mesh_axes=names), mesh, (spec,), P(None, ("d1", "d0")))(x)
    y2 = run_spmd(lambda a: lax.all_to_all(
        a, "d0", split_axis=1, concat_axis=0, tiled=True),
        mesh, (spec,), P(None, ("d1", "d0")))(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_arm_mesh_validates_and_demotes():
    # unknown armed axis fails loudly
    with pytest.raises(ValueError, match="not in mesh axes"):
        PC._arm_mesh("compiled", "bogus", ("d0", "d1"))
    # 1-D (or omitted) meshes keep logical addressing
    assert PC._arm_mesh("compiled", "d0", None) == ("compiled", None)
    assert PC._arm_mesh("compiled", "d0", ("d0",)) == ("compiled", None)
    # multi-axis + interpret demotes to the lax fallback (interpret-mode
    # DMA only discharges on 1-D meshes); compiled keeps MESH addressing
    assert PC._arm_mesh("interpret", "d1", ("d0", "d1")) == (None, None)
    assert PC._arm_mesh("compiled", "d1", ("d0", "d1")) == \
        ("compiled", ("d0", "d1"))


def test_fused_matmul_helpers_accept_mesh_axes(rng):
    # the collective_matmul helpers forward mesh_axes to the fused
    # kernels; on a multi-axis CPU mesh the interpret demotion keeps the
    # lax ring and results stay exact
    grid = (4, 2)
    mesh = L.mesh_for(list(range(8)), grid)
    names = mesh.axis_names
    a = _ints(rng, (32, 16))
    b = _ints(rng, (32, 16))
    specs = (P("d0", None), P("d0", None))
    out = P("d0", None)
    y1 = run_spmd(lambda aa, bb: allgather_matmul_rhs(
        aa, bb, "d0", rdma=True, interpret=True, mesh_axes=names),
        mesh, specs, out)(a, b)
    y2 = run_spmd(lambda aa, bb: allgather_matmul_rhs(aa, bb, "d0"),
                  mesh, specs, out)(a, b)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)
