"""Pin bench.py's banked-result protection semantics (round 5).

These rules are what make BENCH_DETAILS.json trustworthy as a master
table accumulated across invocations: a later run's failure or deadline
skip must never mask a result measured in a real silicon window, and a
success must clear every stale failure marker.  The bench harness is the
round's evidence pipeline, so its semantics get the same pinning as the
library.
"""

import importlib.util
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_for_guard_tests",
                                                  REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # NEVER let a test write the repo's master table
    monkeypatch.setattr(mod, "_save", lambda d: None)
    monkeypatch.setattr(mod, "_ONLY", set())
    return mod


def test_expired_budget_keeps_banked_entry(bench):
    bench._GLOBAL_BUDGET_S = 0.0
    d = {"sort_1e7_s": 1.23}
    bench._guarded(d, "sort", lambda: {"sort_1e7_s": 9.9})
    assert d == {"sort_1e7_s": 1.23}


def test_expired_budget_marks_unbanked_label(bench):
    bench._GLOBAL_BUDGET_S = 0.0
    d = {}
    bench._guarded(d, "mapreduce", lambda: {})
    assert d.get("mapreduce_error") == "skipped (global bench deadline)"


def test_failure_next_to_banked_result_goes_to_rerun_error(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_1e7_s": 1.23}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert d["sort_1e7_s"] == 1.23
    assert "boom" in d["sort_rerun_error"]
    assert "sort_error" not in d


def test_failure_with_no_banked_result_is_plain_error(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert "boom" in d["sort_error"]


def test_stale_markers_cleared_at_execution_even_on_refailure(bench):
    # markers are cleared when the label EXECUTES (not at seed time, so
    # unreached labels keep their failure evidence); a re-failure then
    # records the fresh error, never the stale one
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_1e7_s": 1.0, "sort_rerun_error": "old"}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("fresh")))
    assert "fresh" in d["sort_rerun_error"]
    d2 = {"sort_error": "old"}
    bench._guarded(d2, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("fresh")))
    assert "fresh" in d2["sort_error"]


def test_success_pops_every_stale_marker(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_error": "old", "sort_rerun_error": "old",
         "sort_orphan_running": True}
    bench._guarded(d, "sort", lambda: {"sort_1e7_s": 4.5})
    assert d == {"sort_1e7_s": 4.5}


def test_banked_in_handles_dynamic_gemm16k_labels(bench):
    # the one dynamic label family is grid-tagged; its sentinel is
    # derived, not listed (multi-chip runs tag e.g. gemm_16k_2x2)
    d = {"gemm_16k_2x2_bf16pass_gflops": 1.0,
         "gemm_16k_2x2_f32_highest_gflops": 1.0}
    assert bench._banked_in(d, "gemm_16k_2x2")
    assert bench._banked_in(d, "gemm_16k_2x2_f32_highest")
    assert not bench._banked_in(d, "gemm_16k_4x1")
    d["gemm_16k_2x2_error"] = "boom"
    assert not bench._banked_in(d, "gemm_16k_2x2")


def test_error_label_is_not_banked(bench):
    d = {"sort_1e7_s": 1.0, "sort_error": "boom"}
    assert not bench._banked_in(d, "sort")
    # a rerun failure does NOT unbank (the earlier result stays trusted)
    d2 = {"sort_1e7_s": 1.0, "sort_rerun_error": "boom"}
    assert bench._banked_in(d2, "sort")
