"""Pin bench.py's banked-result protection semantics (round 5).

These rules are what make BENCH_DETAILS.json trustworthy as a master
table accumulated across invocations: a later run's failure or deadline
skip must never mask a result measured in a real silicon window, and a
success must clear every stale failure marker.  The bench harness is the
round's evidence pipeline, so its semantics get the same pinning as the
library.
"""

import importlib.util
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_for_guard_tests",
                                                  REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # NEVER let a test write the repo's master table
    monkeypatch.setattr(mod, "_save", lambda d: None)
    monkeypatch.setattr(mod, "_ONLY", set())
    return mod


def test_expired_budget_keeps_banked_entry(bench):
    bench._GLOBAL_BUDGET_S = 0.0
    d = {"sort_1e7_s": 1.23}
    bench._guarded(d, "sort", lambda: {"sort_1e7_s": 9.9})
    assert d == {"sort_1e7_s": 1.23}


def test_expired_budget_marks_unbanked_label(bench):
    bench._GLOBAL_BUDGET_S = 0.0
    d = {}
    bench._guarded(d, "mapreduce", lambda: {})
    assert d.get("mapreduce_error") == "skipped (global bench deadline)"


def test_failure_next_to_banked_result_goes_to_rerun_error(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_1e7_s": 1.23}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert d["sort_1e7_s"] == 1.23
    assert "boom" in d["sort_rerun_error"]
    assert "sort_error" not in d


def test_failure_with_no_banked_result_is_plain_error(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert "boom" in d["sort_error"]


def test_stale_markers_cleared_at_execution_even_on_refailure(bench):
    # markers are cleared when the label EXECUTES (not at seed time, so
    # unreached labels keep their failure evidence); a re-failure then
    # records the fresh error, never the stale one
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_1e7_s": 1.0, "sort_rerun_error": "old"}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("fresh")))
    assert "fresh" in d["sort_rerun_error"]
    d2 = {"sort_error": "old"}
    bench._guarded(d2, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("fresh")))
    assert "fresh" in d2["sort_error"]


def test_success_pops_every_stale_marker(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_error": "old", "sort_rerun_error": "old",
         "sort_orphan_running": True}
    bench._guarded(d, "sort", lambda: {"sort_1e7_s": 4.5})
    assert d["sort_1e7_s"] == 4.5
    assert not any(k.endswith(("_error", "_orphan_running")) for k in d), d


def test_success_banks_comm_bytes_column(bench):
    # every successful config banks its telemetry comms-bytes delta
    bench._GLOBAL_BUDGET_S = 1e9
    d = {}

    def cfg():
        from distributedarrays_tpu import telemetry
        if telemetry.enabled():
            telemetry.record_comm("reshard", 4096, op="benchtest",
                                  journal=False)
        return {"sort_1e7_s": 4.5}

    bench._guarded(d, "sort", cfg)
    assert d["sort_1e7_s"] == 4.5
    from distributedarrays_tpu import telemetry
    want = 4096 if telemetry.enabled() else 0
    assert d["sort_comm_bytes_est"] == want


def test_failure_banks_no_comm_bytes_column(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {}
    bench._guarded(d, "sort",
                   lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert "sort_comm_bytes_est" not in d


def test_provenance_collapse_carries_probe_attempts(bench):
    # same-environment headers merge; probe_attempts survives as the max
    provs = [
        {"device_kind": "v5e", "method": "direct", "utc": "t1",
         "probe_attempts": 2},
        {"device_kind": "v5e", "method": "direct", "utc": "t2",
         "probe_attempts": 5},
        {"device_kind": "v5e", "method": "direct",
         "utcs": ["t0"], "probe_attempts_max": 7},   # already collapsed
        {"device_kind": "v4", "method": "direct", "utc": "t3",
         "probe_attempts": 1},
        {"device_kind": "v4", "method": "direct", "utc": "t4"},  # no attempts
    ]
    out = bench._collapse_provenances(provs)
    assert len(out) == 2
    v5e = next(c for c in out if c["device_kind"] == "v5e")
    assert v5e["utcs"] == ["t1", "t2", "t0"]
    assert v5e["probe_attempts_max"] == 7
    v4 = next(c for c in out if c["device_kind"] == "v4")
    assert v4["utcs"] == ["t3", "t4"]
    assert v4["probe_attempts_max"] == 1


def test_details_lock_serializes_invocations(bench, monkeypatch, tmp_path):
    # second acquirer must wait; with a zero wait budget it gives up with
    # None instead of proceeding into the read-modify-write race.  flock
    # is per open-file-description, so two opens conflict even in-process.
    # Sandboxed lock path: the test must never contend on (or briefly
    # hold) the repo's production BENCH_DETAILS.lock.
    monkeypatch.setattr(bench, "_LOCK_PATH", tmp_path / "details.lock")
    monkeypatch.setenv("DAT_BENCH_LOCK_WAIT_S", "5")
    lock1 = bench._acquire_details_lock()
    assert lock1 is not None
    monkeypatch.setenv("DAT_BENCH_LOCK_WAIT_S", "0")
    assert bench._acquire_details_lock() is None
    lock1.close()   # releases the flock
    monkeypatch.setenv("DAT_BENCH_LOCK_WAIT_S", "5")
    lock2 = bench._acquire_details_lock()
    assert lock2 is not None
    lock2.close()


def test_banked_in_handles_dynamic_gemm16k_labels(bench):
    # the one dynamic label family is grid-tagged; its sentinel is
    # derived, not listed (multi-chip runs tag e.g. gemm_16k_2x2)
    d = {"gemm_16k_2x2_bf16pass_gflops": 1.0,
         "gemm_16k_2x2_f32_highest_gflops": 1.0}
    assert bench._banked_in(d, "gemm_16k_2x2")
    assert bench._banked_in(d, "gemm_16k_2x2_f32_highest")
    assert not bench._banked_in(d, "gemm_16k_4x1")
    d["gemm_16k_2x2_error"] = "boom"
    assert not bench._banked_in(d, "gemm_16k_2x2")


def test_error_label_is_not_banked(bench):
    d = {"sort_1e7_s": 1.0, "sort_error": "boom"}
    assert not bench._banked_in(d, "sort")
    # a rerun failure does NOT unbank (the earlier result stays trusted)
    d2 = {"sort_1e7_s": 1.0, "sort_rerun_error": "boom"}
    assert bench._banked_in(d2, "sort")


# ---------------------------------------------------------------------------
# partial-row banking
# ---------------------------------------------------------------------------


def test_timeout_banks_published_partials_flagged(bench):
    # a config that published metrics mid-run, then timed out: the
    # completed metrics land in the row flagged {label}_partial, and the
    # flag keeps the label un-banked so the next window re-attempts it
    bench._GLOBAL_BUDGET_S = 1e9
    d = {}

    def cfg():
        bench.bank_partial("sort", sort_1e7_s=1.5, sort_iters=42)
        import time
        time.sleep(1)
        return {"sort_1e7_s": 9.9}

    bench._guarded(d, "sort", cfg, timeout_s=0.3)
    assert "timed out" in d["sort_error"]
    assert d["sort_1e7_s"] == 1.5 and d["sort_iters"] == 42
    assert d["sort_partial"] is True
    assert not bench._banked_in(d, "sort")


def test_exception_banks_published_partials_flagged(bench):
    bench._GLOBAL_BUDGET_S = 1e9
    d = {}

    def cfg():
        bench.bank_partial("sort", sort_iters=17)
        raise ValueError("died after the iteration count")

    bench._guarded(d, "sort", cfg)
    assert "died after" in d["sort_error"]
    assert d["sort_iters"] == 17 and d["sort_partial"] is True


def test_full_success_supersedes_partial_row(bench):
    # a later complete run clears the partial flag with the other stale
    # markers and the label counts as banked again
    bench._GLOBAL_BUDGET_S = 1e9
    d = {"sort_1e7_s": 1.5, "sort_partial": True, "sort_error": "old"}
    assert not bench._banked_in(d, "sort")
    bench._guarded(d, "sort", lambda: {"sort_1e7_s": 4.5})
    assert d["sort_1e7_s"] == 4.5
    assert "sort_partial" not in d and "sort_error" not in d
    assert bench._banked_in(d, "sort")


def test_stale_partials_dropped_at_execution(bench):
    # publications left over from an earlier attempt never leak into a
    # fresh run's row (success path shown; _guarded drops them on entry)
    bench._GLOBAL_BUDGET_S = 1e9
    bench.bank_partial("sort", sort_iters=99)
    d = {}
    bench._guarded(d, "sort", lambda: {"sort_1e7_s": 2.0})
    assert d["sort_1e7_s"] == 2.0
    assert "sort_iters" not in d and "sort_partial" not in d
