"""Elementwise / broadcast engine tests (reference src/broadcast.jl semantics;
oracle = numpy, matching the reference's Array-vs-DArray comparisons,
e.g. test/darray.jl:778-791 scalar-math loop)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray


@pytest.fixture
def abc(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    B = rng.standard_normal((40, 24)).astype(np.float32)
    C = rng.standard_normal((40, 24)).astype(np.float32)
    return A, B, C


def test_binary_operators(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)
    for op in ["__add__", "__sub__", "__mul__", "__truediv__"]:
        got = getattr(da, op)(db)
        want = getattr(A, op)(B)
        assert isinstance(got, DArray)
        assert np.allclose(np.asarray(got), want, rtol=1e-6)


def test_scalar_operands(abc):
    A, _, _ = abc
    d = dat.distribute(A)
    assert np.allclose(np.asarray(d + 1.5), A + 1.5, rtol=1e-6)
    assert np.allclose(np.asarray(2.0 * d), 2.0 * A, rtol=1e-6)
    assert np.allclose(np.asarray(1.0 / (d + 10.0)), 1.0 / (A + 10.0), rtol=1e-5)
    assert np.allclose(np.asarray(d ** 2), A ** 2, rtol=1e-6)


def test_unary_and_comparisons(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)
    assert np.allclose(np.asarray(-da), -A)
    assert np.allclose(np.asarray(abs(da)), np.abs(A))
    lt = da < db
    assert lt.dtype == jnp.bool_
    assert np.array_equal(np.asarray(lt), A < B)


def test_broadcast_chain(abc):
    # the BASELINE config-1 chain: sin.(A) .+ B .* C  (broadcast.jl:65-98)
    A, B, C = abc
    da, db, dc = map(dat.distribute, (A, B, C))
    got = dat.dmap(jnp.sin, da) + db * dc
    want = np.sin(A) + B * C
    assert np.allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_result_inherits_layout(abc):
    A, B, _ = abc
    da = dat.distribute(A, procs=range(8), dist=(4, 2))
    db = dat.distribute(B, procs=range(8), dist=(4, 2))
    r = da + db
    assert r.pids.shape == (4, 2)
    assert r.cuts == da.cuts


def test_mixed_plain_array_arg(abc):
    # plain arrays get distributed (reference bcdistribute, broadcast.jl:124-137)
    A, B, _ = abc
    da = dat.distribute(A)
    r = da + B
    assert isinstance(r, DArray)
    assert np.allclose(np.asarray(r), A + B, rtol=1e-6)


def test_row_broadcasting(abc):
    A, _, _ = abc
    da = dat.distribute(A)
    row = np.arange(24, dtype=np.float32)
    r = da + row
    assert np.allclose(np.asarray(r), A + row, rtol=1e-6)


def test_mismatched_layouts_reshard(abc):
    A, B, _ = abc
    da = dat.distribute(A, procs=range(8), dist=(8, 1))
    db = dat.distribute(B, procs=range(4), dist=(2, 2))
    r = da + db
    assert np.allclose(np.asarray(r), A + B, rtol=1e-6)
    assert r.pids.shape == (8, 1)


def test_divisibility_misfit_reshards_without_replication_warning(rng):
    # NamedSharding accepts uneven shards, so an arg whose dims don't
    # divide the target mesh axes must go through the real reshard —
    # replicating it was a memory/bandwidth regression (ADVICE round-4);
    # only rank misfits replicate (with a warning)
    import warnings
    U = rng.standard_normal((50, 8)).astype(np.float32)
    V = rng.standard_normal((50, 8)).astype(np.float32)
    du = dat.distribute(U, procs=range(8), dist=(4, 2))   # uneven rows
    dv = dat.distribute(V, procs=range(4), dist=(2, 2))   # other mesh
    from distributedarrays_tpu.utils import debug as dbg
    with dbg._warned_lock:
        dbg._warned.clear()               # a prior test must not mask it
    with warnings.catch_warnings():
        warnings.simplefilter("error")                    # any warn fails
        r = du + dv
    assert np.allclose(np.asarray(r), U + V, rtol=1e-6)
    dat.d_closeall()


def test_dmap_into(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)
    dest = dat.dzeros((40, 24))
    out = dat.dmap_into(jnp.add, dest, da, db)
    assert out is dest
    assert np.allclose(np.asarray(dest), A + B, rtol=1e-6)


def test_dmap_into_shape_mismatch(abc):
    A, _, _ = abc
    dest = dat.dzeros((3, 3))
    with pytest.raises(ValueError):
        dat.dmap_into(jnp.sin, dest, dat.distribute(A))


def test_djit_fuses_whole_chain(abc):
    A, B, C = abc
    da, db, dc = map(dat.distribute, (A, B, C))

    @dat.djit
    def chain(a, b, c):
        return jnp.sin(a) + b * c

    r = chain(da, db, dc)
    assert isinstance(r, DArray)
    assert r.cuts == da.cuts
    assert np.allclose(np.asarray(r), np.sin(A) + B * C, rtol=1e-5, atol=1e-6)


def test_djit_multiple_outputs(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)

    @dat.djit
    def two(a, b):
        return a + b, (a * b).sum()

    s, t = two(da, db)
    assert isinstance(s, DArray)
    assert np.allclose(np.asarray(s), A + B, rtol=1e-6)
    assert np.allclose(float(t), (A * B).sum(), rtol=1e-4)


def test_many_scalar_functions(abc):
    # reference test/darray.jl:778-791 runs ~70 scalar functions through
    # broadcast; the jnp-available equivalents, domain-partitioned
    A, _, _ = abc
    pos = dat.distribute(np.abs(A) + 0.5)            # (0.5, inf)
    anyv = dat.distribute(A)                          # (-inf, inf)
    unit = dat.distribute(np.tanh(A) * 0.99)          # (-1, 1)
    cases = {
        pos: [(jnp.log, np.log), (jnp.sqrt, np.sqrt), (jnp.log1p, np.log1p),
              (jnp.log2, np.log2), (jnp.log10, np.log10),
              (jnp.reciprocal, np.reciprocal)],
        anyv: [(jnp.sin, np.sin), (jnp.cos, np.cos), (jnp.tan, np.tan),
               (jnp.exp, np.exp), (jnp.tanh, np.tanh), (jnp.sinh, np.sinh),
               (jnp.cosh, np.cosh), (jnp.floor, np.floor),
               (jnp.ceil, np.ceil), (jnp.trunc, np.trunc),
               (jnp.rint, np.rint), (jnp.sign, np.sign),
               (jnp.arctan, np.arctan), (jnp.arcsinh, np.arcsinh),
               (jnp.expm1, np.expm1), (jnp.cbrt, np.cbrt),
               (jnp.exp2, np.exp2), (jnp.square, np.square),
               (jnp.deg2rad, np.deg2rad), (jnp.rad2deg, np.rad2deg),
               (jnp.abs, np.abs)],
        unit: [(jnp.arcsin, np.arcsin), (jnp.arccos, np.arccos),
               (jnp.arctanh, np.arctanh)],
    }
    for d, fns in cases.items():
        host = np.asarray(d)
        for jf, nf in fns:
            got = dat.dmap(jf, d)
            want = nf(host)
            assert np.allclose(np.asarray(got), want, rtol=1e-4,
                               atol=1e-5), jf


def test_reduction_methods(abc):
    # numpy-style methods delegate to the distributed reductions
    A, _, _ = abc
    d = dat.distribute(A)
    assert np.allclose(float(d.sum()), A.sum(), rtol=1e-4)
    assert np.allclose(float(d.mean()), A.mean(), rtol=1e-5)
    assert np.allclose(float(d.std()), A.std(ddof=1), rtol=1e-4)
    # var defaults corrected like std (regression: std^2 == var)
    assert np.allclose(float(d.var()), A.var(ddof=1), rtol=1e-4)
    assert np.allclose(float(d.std()) ** 2, float(d.var()), rtol=1e-4)
    assert np.allclose(float(d.min()), A.min())
    assert np.allclose(float(d.max()), A.max())
    r = d.sum(dims=0)
    assert r.dims == (1, 24)
    assert bool((d * 0 + 1).all())
