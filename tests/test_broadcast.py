"""Elementwise / broadcast engine tests (reference src/broadcast.jl semantics;
oracle = numpy, matching the reference's Array-vs-DArray comparisons,
e.g. test/darray.jl:778-791 scalar-math loop)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray


@pytest.fixture
def abc(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    B = rng.standard_normal((40, 24)).astype(np.float32)
    C = rng.standard_normal((40, 24)).astype(np.float32)
    return A, B, C


def test_binary_operators(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)
    for op in ["__add__", "__sub__", "__mul__", "__truediv__"]:
        got = getattr(da, op)(db)
        want = getattr(A, op)(B)
        assert isinstance(got, DArray)
        assert np.allclose(np.asarray(got), want, rtol=1e-6)


def test_scalar_operands(abc):
    A, _, _ = abc
    d = dat.distribute(A)
    assert np.allclose(np.asarray(d + 1.5), A + 1.5, rtol=1e-6)
    assert np.allclose(np.asarray(2.0 * d), 2.0 * A, rtol=1e-6)
    assert np.allclose(np.asarray(1.0 / (d + 10.0)), 1.0 / (A + 10.0), rtol=1e-5)
    assert np.allclose(np.asarray(d ** 2), A ** 2, rtol=1e-6)


def test_unary_and_comparisons(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)
    assert np.allclose(np.asarray(-da), -A)
    assert np.allclose(np.asarray(abs(da)), np.abs(A))
    lt = da < db
    assert lt.dtype == jnp.bool_
    assert np.array_equal(np.asarray(lt), A < B)


def test_broadcast_chain(abc):
    # the BASELINE config-1 chain: sin.(A) .+ B .* C  (broadcast.jl:65-98)
    A, B, C = abc
    da, db, dc = map(dat.distribute, (A, B, C))
    got = dat.dmap(jnp.sin, da) + db * dc
    want = np.sin(A) + B * C
    assert np.allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_result_inherits_layout(abc):
    A, B, _ = abc
    da = dat.distribute(A, procs=range(8), dist=(4, 2))
    db = dat.distribute(B, procs=range(8), dist=(4, 2))
    r = da + db
    assert r.pids.shape == (4, 2)
    assert r.cuts == da.cuts


def test_mixed_plain_array_arg(abc):
    # plain arrays get distributed (reference bcdistribute, broadcast.jl:124-137)
    A, B, _ = abc
    da = dat.distribute(A)
    r = da + B
    assert isinstance(r, DArray)
    assert np.allclose(np.asarray(r), A + B, rtol=1e-6)


def test_row_broadcasting(abc):
    A, _, _ = abc
    da = dat.distribute(A)
    row = np.arange(24, dtype=np.float32)
    r = da + row
    assert np.allclose(np.asarray(r), A + row, rtol=1e-6)


def test_mismatched_layouts_reshard(abc):
    A, B, _ = abc
    da = dat.distribute(A, procs=range(8), dist=(8, 1))
    db = dat.distribute(B, procs=range(4), dist=(2, 2))
    r = da + db
    assert np.allclose(np.asarray(r), A + B, rtol=1e-6)
    assert r.pids.shape == (8, 1)


def test_dmap_into(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)
    dest = dat.dzeros((40, 24))
    out = dat.dmap_into(jnp.add, dest, da, db)
    assert out is dest
    assert np.allclose(np.asarray(dest), A + B, rtol=1e-6)


def test_dmap_into_shape_mismatch(abc):
    A, _, _ = abc
    dest = dat.dzeros((3, 3))
    with pytest.raises(ValueError):
        dat.dmap_into(jnp.sin, dest, dat.distribute(A))


def test_djit_fuses_whole_chain(abc):
    A, B, C = abc
    da, db, dc = map(dat.distribute, (A, B, C))

    @dat.djit
    def chain(a, b, c):
        return jnp.sin(a) + b * c

    r = chain(da, db, dc)
    assert isinstance(r, DArray)
    assert r.cuts == da.cuts
    assert np.allclose(np.asarray(r), np.sin(A) + B * C, rtol=1e-5, atol=1e-6)


def test_djit_multiple_outputs(abc):
    A, B, _ = abc
    da, db = dat.distribute(A), dat.distribute(B)

    @dat.djit
    def two(a, b):
        return a + b, (a * b).sum()

    s, t = two(da, db)
    assert isinstance(s, DArray)
    assert np.allclose(np.asarray(s), A + B, rtol=1e-6)
    assert np.allclose(float(t), (A * B).sum(), rtol=1e-4)


def test_many_scalar_functions(abc):
    # reference test/darray.jl:778-791 runs ~70 scalar functions through
    # broadcast; representative sample here
    A, _, _ = abc
    d = dat.distribute(np.abs(A) + 0.5)
    for jf, nf in [(jnp.sin, np.sin), (jnp.cos, np.cos), (jnp.exp, np.exp),
                   (jnp.log, np.log), (jnp.sqrt, np.sqrt),
                   (jnp.tanh, np.tanh), (jnp.floor, np.floor),
                   (jnp.ceil, np.ceil), (jnp.sign, np.sign),
                   (jnp.arctan, np.arctan), (jnp.log1p, np.log1p),
                   (jnp.expm1, np.expm1), (jnp.cbrt, np.cbrt)]:
        got = dat.dmap(jf, d)
        want = nf(np.asarray(d))
        assert np.allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6), jf
