"""Distributed sort tests (reference test/darray.jl:1015-1025: sort vs
Base.sort for all sample strategies)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.ops.sort import dsort


def test_psrs_matches_numpy(rng):
    x = rng.standard_normal(4096).astype(np.float32)
    d = dat.distribute(x)
    s = dsort(d, alg="psrs")
    assert np.array_equal(np.asarray(s), np.sort(x))
    # total length preserved, chunks tile it (layout may be uneven)
    assert s.dims == (4096,)


def test_psrs_result_distribution_changes(rng):
    # skewed data → uneven result chunks, like the reference's rebuilt
    # distribution (sort.jl:164-169)
    x = np.concatenate([np.zeros(3000, np.float32),
                        rng.standard_normal(1096).astype(np.float32)])
    rng.shuffle(x)
    d = dat.distribute(x)
    s = dsort(d, alg="psrs")
    assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_rev(rng):
    x = rng.standard_normal(1024).astype(np.float32)
    s = dsort(dat.distribute(x), rev=True)
    assert np.array_equal(np.asarray(s), np.sort(x)[::-1])


def test_sort_by_key(rng):
    x = rng.standard_normal(512).astype(np.float32)
    s = dsort(dat.distribute(x), by=jnp.abs)
    want = x[np.argsort(np.abs(x), kind="stable")]
    assert np.array_equal(np.asarray(s), want)


def test_sort_int_dtype(rng):
    x = rng.integers(-1000, 1000, size=2048).astype(np.int32)
    s = dsort(dat.distribute(x), alg="psrs")
    assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_uneven_length_stays_distributed(rng, monkeypatch):
    # length not divisible by ranks → STILL the distributed PSRS path,
    # via the blocked-padded buffer (round-3 de-cliffing, VERDICT item 6)
    _forbid_global_sort(monkeypatch)
    x = rng.standard_normal(1001).astype(np.float32)
    s = dsort(dat.distribute(x))
    assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_sample_kwarg_parity(rng):
    # reference accepts sample=true|false|(min,max)|Array (sort.jl:110-135)
    x = rng.standard_normal(512).astype(np.float32)
    d = dat.distribute(x)
    for sample in [True, False, (-3.0, 3.0)]:
        s = dsort(d, sample=sample)
        assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_tiny_sizes(rng):
    # reference sweeps sort over 10^0..10^6 elements (test/darray.jl:1015)
    for n in (1, 2, 7, 10, 100):
        x = rng.standard_normal(n).astype(np.float32)
        s = dsort(dat.distribute(x))
        assert np.array_equal(np.asarray(s), np.sort(x)), n


def test_sort_2d_raises(rng):
    with pytest.raises(ValueError):
        dsort(dat.dzeros((4, 4)))


def test_psrs_ineligible_raises(rng):
    # single-rank layouts have no ring to sort over
    x = rng.standard_normal(64).astype(np.float32)
    with pytest.raises(ValueError):
        dsort(dat.distribute(x, procs=[0], dist=[1]), alg="psrs")


# ---------------------------------------------------------------------------
# round-2 parity edges (VERDICT item 6): NaN inside PSRS, by= in the
# distributed path, empty-chunk dropping (sort.jl:164-169)
# ---------------------------------------------------------------------------


def _forbid_global_sort(monkeypatch):
    """Make any silent fallback to the global sort fail the test."""
    import distributedarrays_tpu.ops.sort as sort_mod

    def boom(*a, **k):
        raise AssertionError("fell back to global sort; PSRS expected")
    monkeypatch.setattr(sort_mod, "_global_sort_jit", boom)


def test_psrs_handles_nan(rng, monkeypatch):
    _forbid_global_sort(monkeypatch)
    x = rng.standard_normal(64).astype(np.float32)
    x[[3, 17, 40]] = np.nan
    d = dat.distribute(x)
    s = dsort(d, alg="psrs")  # must NOT fall back / raise
    got = np.asarray(s)
    want = np.sort(x)  # numpy: NaNs last
    np.testing.assert_array_equal(got, want)
    dat.d_closeall()


def test_psrs_nan_rev(rng):
    x = rng.standard_normal(32).astype(np.float32)
    x[5] = np.nan
    s = dsort(dat.distribute(x), alg="psrs", rev=True)
    np.testing.assert_array_equal(np.asarray(s), np.sort(x)[::-1])
    dat.d_closeall()


def test_psrs_by_traceable(rng, monkeypatch):
    _forbid_global_sort(monkeypatch)
    x = rng.standard_normal(64).astype(np.float32)
    d = dat.distribute(x)
    s = dsort(d, alg="psrs", by=jnp.abs)  # distributed path, no fallback
    want = x[np.argsort(np.abs(x), kind="stable")]
    np.testing.assert_array_equal(np.asarray(s), want)
    dat.d_closeall()


def test_psrs_by_traceable_int_keys(rng):
    x = rng.integers(-100, 100, 64).astype(np.int32)
    d = dat.distribute(x)
    s = dsort(d, alg="psrs", by=lambda v: v % 7)
    want = x[np.argsort(x % 7, kind="stable")]
    np.testing.assert_array_equal(np.asarray(s), want)
    dat.d_closeall()


def test_sort_by_untraceable_host_fallback():
    x = np.array([3.0, -1.0, 2.0, -4.0, 0.5, -0.5, 9.0, -9.0],
                 dtype=np.float32)
    d = dat.distribute(x)
    # branches on the concrete value -> cannot trace
    s = dsort(d, by=lambda v: abs(float(v)))
    want = np.asarray(sorted(x.tolist(), key=abs), dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(s), want)
    dat.d_closeall()


def test_psrs_drops_empty_chunks():
    # heavily skewed data: every element lands in the first bucket, so
    # trailing ranks end up empty and must be dropped like the reference
    x = np.zeros(64, dtype=np.float32)
    x[0] = 1.0
    d = dat.distribute(x, procs=range(8), dist=[8])
    s = dsort(d, alg="psrs")
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    sizes = list(np.diff(s.cuts[0]))
    assert all(n > 0 for n in sizes), sizes  # no empty result chunks
    assert len(sizes) <= 8
    dat.d_closeall()


def test_psrs_uniform_keeps_all_ranks(rng):
    x = rng.standard_normal(80).astype(np.float32)
    s = dsort(dat.distribute(x, procs=range(8), dist=[8]), alg="psrs")
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    assert all(n > 0 for n in np.diff(s.cuts[0]))
    dat.d_closeall()


def test_psrs_int_max_values_survive():
    # regression: the pad sentinel key equals int max; genuine int-max data
    # must not be displaced by zero-filled pad slots
    M = np.iinfo(np.int32).max
    x = np.array([0, 1, 2, 3, M, M, M, M], dtype=np.int32)
    rng = np.random.default_rng(0)
    x = x[rng.permutation(8)]
    d = dat.distribute(x, procs=range(2), dist=[2])
    s = dsort(d, alg="psrs")
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


def test_psrs_uint_max_values_survive():
    M = np.iinfo(np.uint32).max
    x = np.array([5, M, 1, M, 2, M, 0, M], dtype=np.uint32)
    s = dsort(dat.distribute(x, procs=range(4), dist=[4]), alg="psrs")
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


def test_psrs_rev_stable_ties():
    # reverse sort keeps original order among equal keys, like
    # sorted(reverse=True) and Julia's stable rev sort
    x = np.array([1, -1, 2, -2, 3, -3, 4, -4], dtype=np.float32)
    d = dat.distribute(x, procs=range(2), dist=[2])
    s = dsort(d, alg="psrs", by=jnp.abs, rev=True)
    want = np.asarray(sorted(x.tolist(), key=abs, reverse=True),
                      dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(s), want)
    dat.d_closeall()


def test_psrs_rev_int():
    x = np.array([7, -3, 11, 0, -3, 7, 2, -9], dtype=np.int32)
    s = dsort(dat.distribute(x, procs=range(4), dist=[4]), alg="psrs",
              rev=True)
    np.testing.assert_array_equal(np.asarray(s), np.sort(x)[::-1])
    dat.d_closeall()


# ---------------------------------------------------------------------------
# round-3 parity (VERDICT item 6): full sample-strategy dispatch
# (sort.jl:110-135) + PSRS on non-divisible lengths, no hidden cliffs
# ---------------------------------------------------------------------------


def test_psrs_prime_length(rng, monkeypatch):
    # a prime-length vector must sort DISTRIBUTED (padded PSRS), never via
    # a hidden global sort on one program
    _forbid_global_sort(monkeypatch)
    x = rng.standard_normal(1009).astype(np.float32)   # prime
    s = dsort(dat.distribute(x), alg="psrs")
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


def test_psrs_prime_length_nan_rev_by(rng, monkeypatch):
    _forbid_global_sort(monkeypatch)
    x = rng.standard_normal(101).astype(np.float32)
    s = dsort(dat.distribute(x), alg="psrs", by=jnp.abs, rev=True)
    want = np.asarray(sorted(x.tolist(), key=abs, reverse=True), np.float32)
    np.testing.assert_array_equal(np.asarray(s), want)
    dat.d_closeall()


def test_psrs_bool_dtype(monkeypatch):
    _forbid_global_sort(monkeypatch)
    x = np.array([True, False] * 16)
    s = dsort(dat.distribute(x), alg="psrs")
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


def test_sample_false_uniform_pivots(rng, monkeypatch):
    # sample=False: pivots assume uniform between global min/max
    # (sort.jl:117-123); correctness identical, path stays distributed
    _forbid_global_sort(monkeypatch)
    x = rng.uniform(-5, 5, 512).astype(np.float32)
    s = dsort(dat.distribute(x), sample=False)
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    # uniform data + uniform-assumption pivots → all ranks keep work
    assert len(np.diff(s.cuts[0])) == 8
    dat.d_closeall()


def test_sample_tuple_pivots(rng):
    x = rng.uniform(0, 1, 256).astype(np.float32)
    s = dsort(dat.distribute(x), sample=(0.0, 1.0))
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    # pivots at i/8: resulting chunk sizes reflect the data's true CDF
    sizes = np.diff(s.cuts[0])
    assert sizes.sum() == 256 and all(sizes > 0)
    dat.d_closeall()


def test_sample_tuple_skewed_distribution_shows(rng):
    # all data in [0, 0.1] with pivots uniform over (0, 1): everything
    # lands in the first bucket — the sample strategy demonstrably drove
    # the partitioning (and empty chunks drop, sort.jl:164-169)
    x = rng.uniform(0, 0.1, 256).astype(np.float32)
    s = dsort(dat.distribute(x), sample=(0.0, 1.0))
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    assert len(np.diff(s.cuts[0])) == 1          # one rank holds it all
    dat.d_closeall()


def test_sample_tuple_int_keys(rng):
    x = rng.integers(-100, 100, 128).astype(np.int32)
    s = dsort(dat.distribute(x), sample=(-100, 100))
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


def test_sample_array_strategy(rng):
    # a pre-drawn sample drives the pivots (sort.jl:145-151)
    x = rng.standard_normal(512).astype(np.float32)
    samp = rng.standard_normal(64).astype(np.float32)
    s = dsort(dat.distribute(x), sample=samp)
    np.testing.assert_array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


def test_sample_array_with_by(rng):
    x = rng.standard_normal(256).astype(np.float32)
    samp = np.abs(rng.standard_normal(32)).astype(np.float32)
    s = dsort(dat.distribute(x), sample=samp, by=jnp.abs)
    want = x[np.argsort(np.abs(x), kind="stable")]
    np.testing.assert_array_equal(np.asarray(s), want)
    dat.d_closeall()


def test_sample_invalid_values_raise(rng):
    d = dat.distribute(rng.standard_normal(64).astype(np.float32))
    with pytest.raises(ValueError, match="sample"):
        dsort(d, sample="bogus")
    with pytest.raises(ValueError, match="min <= max"):
        dsort(d, sample=(3.0, -3.0))
    with pytest.raises(ValueError, match="finite"):
        dsort(d, sample=(-np.inf, np.inf))
    with pytest.raises(ValueError, match="elements"):
        dsort(d, sample=np.array([1.0, 2.0]))   # < 8 ranks worth
    with pytest.raises(ValueError, match="\\(min, max\\)"):
        dsort(d, sample=(1.0, 2.0, 3.0))
    dat.d_closeall()


def test_sample_strategy_single_rank_validates_and_proceeds(rng):
    # single rank: pivots only affect balance, the sorted result is
    # identical, and the reference accepts these calls — valid strategies
    # proceed (ADVICE round-3), INVALID values still raise
    x = rng.standard_normal(64).astype(np.float32)
    d1 = dat.distribute(x, procs=[0], dist=[1])
    for sample in [(0.0, 1.0), False, np.sort(x)[::8]]:
        got = dsort(dat.distribute(x, procs=[0], dist=[1]), sample=sample)
        np.testing.assert_array_equal(np.asarray(got), np.sort(x))
    with pytest.raises(ValueError, match="min <= max"):
        dsort(d1, sample=(3.0, -3.0))
    with pytest.raises(ValueError, match="sample"):
        dsort(d1, sample="bogus")
    # an untraceable Python `by` still cannot honor (or validate) an
    # explicit strategy — loud error, never a silent ignore
    d = dat.distribute(x)
    with pytest.raises(ValueError, match="jax-traced"):
        dsort(d, sample=(0.0, 1.0), by=lambda v: hash(v))
    dat.d_closeall()


def test_sample_false_rev(rng):
    x = rng.standard_normal(128).astype(np.float32)
    s = dsort(dat.distribute(x), sample=False, rev=True)
    np.testing.assert_array_equal(np.asarray(s), np.sort(x)[::-1])
    dat.d_closeall()


def test_unknown_alg_raises(rng):
    d = dat.distribute(rng.standard_normal(64).astype(np.float32))
    with pytest.raises(ValueError, match="unknown alg"):
        dsort(d, alg="PSRS")
    dat.d_closeall()
