"""Distributed sort tests (reference test/darray.jl:1015-1025: sort vs
Base.sort for all sample strategies)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.ops.sort import dsort


def test_psrs_matches_numpy(rng):
    x = rng.standard_normal(4096).astype(np.float32)
    d = dat.distribute(x)
    s = dsort(d, alg="psrs")
    assert np.array_equal(np.asarray(s), np.sort(x))
    # total length preserved, chunks tile it (layout may be uneven)
    assert s.dims == (4096,)


def test_psrs_result_distribution_changes(rng):
    # skewed data → uneven result chunks, like the reference's rebuilt
    # distribution (sort.jl:164-169)
    x = np.concatenate([np.zeros(3000, np.float32),
                        rng.standard_normal(1096).astype(np.float32)])
    rng.shuffle(x)
    d = dat.distribute(x)
    s = dsort(d, alg="psrs")
    assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_rev(rng):
    x = rng.standard_normal(1024).astype(np.float32)
    s = dsort(dat.distribute(x), rev=True)
    assert np.array_equal(np.asarray(s), np.sort(x)[::-1])


def test_sort_by_key(rng):
    x = rng.standard_normal(512).astype(np.float32)
    s = dsort(dat.distribute(x), by=jnp.abs)
    want = x[np.argsort(np.abs(x), kind="stable")]
    assert np.array_equal(np.asarray(s), want)


def test_sort_int_dtype(rng):
    x = rng.integers(-1000, 1000, size=2048).astype(np.int32)
    s = dsort(dat.distribute(x), alg="psrs")
    assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_uneven_length_fallback(rng):
    # length not divisible by ranks → global path, still correct
    x = rng.standard_normal(1001).astype(np.float32)
    s = dsort(dat.distribute(x))
    assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_sample_kwarg_parity(rng):
    # reference accepts sample=true|false|(min,max)|Array (sort.jl:110-135)
    x = rng.standard_normal(512).astype(np.float32)
    d = dat.distribute(x)
    for sample in [True, False, (-3.0, 3.0)]:
        s = dsort(d, sample=sample)
        assert np.array_equal(np.asarray(s), np.sort(x))


def test_sort_tiny_sizes(rng):
    # reference sweeps sort over 10^0..10^6 elements (test/darray.jl:1015)
    for n in (1, 2, 7, 10, 100):
        x = rng.standard_normal(n).astype(np.float32)
        s = dsort(dat.distribute(x))
        assert np.array_equal(np.asarray(s), np.sort(x)), n


def test_sort_2d_raises(rng):
    with pytest.raises(ValueError):
        dsort(dat.dzeros((4, 4)))


def test_psrs_ineligible_raises(rng):
    x = rng.standard_normal(1001).astype(np.float32)
    with pytest.raises(ValueError):
        dsort(dat.distribute(x), alg="psrs")
