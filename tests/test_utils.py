"""Aux-subsystem tests: checkpoint/resume, profiling, multihost helpers,
Pallas GEMM kernel (interpret mode on the CPU mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
from distributedarrays_tpu.parallel import multihost
from distributedarrays_tpu.utils import checkpoint, profiling


def test_checkpoint_roundtrip_darray(tmp_path, rng):
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    state = {"step": 7, "d": d, "lr": 1e-3, "name": "run1",
             "w": jnp.ones((4,)), "hist": [1, 2, (3, 4)]}
    checkpoint.save(tmp_path / "ckpt", state)
    d.close()
    back = checkpoint.load(tmp_path / "ckpt")
    assert back["step"] == 7 and back["name"] == "run1"
    assert isinstance(back["d"], dat.DArray)
    assert back["d"].pids.shape == (4, 2)
    assert back["d"].cuts[0] == [0, 13, 26, 38, 50]
    assert np.array_equal(np.asarray(back["d"]), A)
    assert isinstance(back["w"], jax.Array)
    assert back["hist"] == [1, 2, (3, 4)]


def test_checkpoint_ddata(tmp_path):
    dd = dat.ddata(data=list(range(8)))
    checkpoint.save(tmp_path / "c2", {"dd": dd})
    back = checkpoint.load(tmp_path / "c2")
    assert dat.gather(back["dd"]) == list(range(8))


def test_checkpoint_preserves_nondefault_cuts(tmp_path):
    # regression: a from_chunks layout with non-default cuts must restore
    # with exactly those cuts, not the recomputed default
    chunks = np.empty((2,), dtype=object)
    chunks[0] = np.ones((3,), np.float32)
    chunks[1] = np.full((29,), 2.0, np.float32)
    d = dat.from_chunks(chunks)
    assert d.cuts[0] == [0, 3, 32]
    checkpoint.save(tmp_path / "c4", d)
    back = checkpoint.load(tmp_path / "c4")
    assert back.cuts[0] == [0, 3, 32]
    assert np.array_equal(np.asarray(back), np.asarray(d))


def test_checkpoint_preserves_keys_and_scalar_types(tmp_path):
    state = {"table": {3: "x", (1, 2): "y"}, "step": np.int64(7),
             "flag": np.bool_(True)}
    checkpoint.save(tmp_path / "c5", state)
    back = checkpoint.load(tmp_path / "c5")
    assert back["table"][3] == "x" and back["table"][(1, 2)] == "y"
    assert back["step"] == 7 and back["step"].dtype == np.int64
    assert back["flag"].dtype == np.bool_


def test_checkpoint_orbax_store(tmp_path, rng):
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    state = {"step": 3, "d": d, "w": jnp.arange(6, dtype=jnp.bfloat16)}
    checkpoint.save(tmp_path / "cob", state, store="orbax")
    back = checkpoint.load(tmp_path / "cob")
    assert back["step"] == 3
    assert back["d"].cuts[0] == [0, 13, 26, 38, 50]
    assert np.array_equal(np.asarray(back["d"]), A)
    assert back["w"].dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="store"):
        checkpoint.save(tmp_path / "cx", {"a": 1}, store="nope")


def test_checkpoint_bfloat16_roundtrip(tmp_path):
    # regression: ml_dtypes arrays (bfloat16) don't survive npz natively
    w = jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4) / 7
    checkpoint.save(tmp_path / "cbf", {"w": w, "host": np.asarray(w)})
    back = checkpoint.load(tmp_path / "cbf")
    assert back["w"].dtype == jnp.bfloat16
    assert jnp.array_equal(back["w"], w)
    assert back["host"].dtype == np.asarray(w).dtype


def test_checkpoint_rejects_unknown_leaf(tmp_path):
    with pytest.raises(TypeError):
        checkpoint.save(tmp_path / "c3", {"f": open})


def test_op_timer():
    t = profiling.OpTimer()
    with t("phase"):
        _ = float(dat.dsum(dat.dones((64, 64))))
    with t("phase"):
        pass
    rep = t.report()
    assert rep["phase"]["calls"] == 2
    assert rep["phase"]["total_s"] > 0


def test_trace_annotation_smoke(tmp_path):
    with profiling.annotate("span"):
        _ = float(dat.dsum(dat.dones((8, 8))))


def test_multihost_single_process():
    info = multihost.process_info()
    assert info["process_count"] == 1
    assert info["global_devices"] == 8
    mesh = multihost.global_mesh((4, 2), ("dp", "tp"))
    assert mesh.shape == {"dp": 4, "tp": 2}
    with pytest.raises(ValueError):
        multihost.global_mesh((3, 2), ("a", "b"))
    multihost.sync_hosts()   # no-op single process
    # a live backend is a real user error and must surface (round 1
    # swallowed it); the degrade-gracefully paths are covered by
    # test_multihost.py in fresh subprocesses
    with pytest.raises(RuntimeError):
        multihost.initialize()


def test_host_local_slice(rng):
    A = rng.standard_normal((32, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))
    parts = multihost.host_local_slice(d)
    assert [p for p, _ in parts] == [0, 1, 2, 3]
    assert np.array_equal(np.asarray(parts[2][1]), A[16:24])


def test_validate_invariants(rng):
    from distributedarrays_tpu.utils import debug
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    debug.validate(d)                      # healthy array passes
    assert debug.check_all() >= 1
    # corrupt an invariant → precise assertion
    d.cuts[0][1] = 99
    with pytest.raises(AssertionError, match="cuts"):
        debug.validate(d)
    d.cuts[0][1] = 13                      # restore for clean teardown
    d.close()
    with pytest.raises(AssertionError, match="closed"):
        debug.validate(d)


def test_pallas_matmul_interpret(rng):
    a = rng.standard_normal((256, 128)).astype(np.float32)
    b = rng.standard_normal((128, 256)).astype(np.float32)
    c = np.asarray(pallas_matmul(a, b, block=(128, 128, 128)))
    assert np.allclose(c, a @ b, rtol=1e-4, atol=1e-4)


def test_pallas_matmul_fused_epilogue(rng):
    a = rng.standard_normal((128, 128)).astype(np.float32)
    b = rng.standard_normal((128, 128)).astype(np.float32)
    c = np.asarray(pallas_matmul(a, b, block=(128, 128, 128),
                                 epilogue=jax.nn.gelu))
    want = np.asarray(jax.nn.gelu(jnp.asarray(a @ b)))
    assert np.allclose(c, want, rtol=1e-4, atol=1e-4)


def test_pallas_matmul_validation(rng):
    a = rng.standard_normal((100, 64)).astype(np.float32)
    b = rng.standard_normal((64, 64)).astype(np.float32)
    with pytest.raises(ValueError, match="divide"):
        pallas_matmul(a, b, block=(64, 64, 64))
    with pytest.raises(ValueError, match="mismatch"):
        pallas_matmul(b, a)


def test_quantized_matmul_interpret(rng):
    from distributedarrays_tpu.ops.pallas_gemm import quantized_matmul
    a = rng.standard_normal((256, 384)).astype(np.float32)
    b = rng.standard_normal((384, 128)).astype(np.float32)
    got = np.asarray(quantized_matmul(a, b, interpret=True))
    want = a @ b
    assert np.abs(got - want).max() / np.abs(want).max() < 2e-2


def test_pallas_matmul_int8_exact_accumulation(rng):
    # the int8 path's only error is the two quantization roundings: the
    # int32 accumulate + fused dequant must reproduce the integer oracle
    # bit-for-bit (scaled), including an all-zero row (scale 0, not NaN)
    from distributedarrays_tpu.ops.pallas_gemm import (pallas_matmul_int8,
                                                       quantize_rows)
    a = rng.standard_normal((128, 256)).astype(np.float32)
    a[0] = 0.0
    b = rng.standard_normal((256, 128)).astype(np.float32)
    qa, sa = quantize_rows(a, 1)
    qb, sb = quantize_rows(b, 0)
    got = np.asarray(pallas_matmul_int8(qa, qb, sa, sb, interpret=True))
    want = (np.asarray(qa, np.int32) @ np.asarray(qb, np.int32)
            ).astype(np.float32) * np.asarray(sa)[:, None] \
        * np.asarray(sb)[None, :]
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert np.all(got[0] == 0) and np.all(np.isfinite(got))


def test_pallas_matmul_int8_validation(rng):
    from distributedarrays_tpu.ops.pallas_gemm import (pallas_matmul_int8,
                                                       quantize_rows)
    a = rng.standard_normal((128, 128)).astype(np.float32)
    qa, sa = quantize_rows(a, 1)
    with pytest.raises(ValueError, match="int8"):
        pallas_matmul_int8(a, qa, sa, sa, interpret=True)
    with pytest.raises(ValueError, match="divide"):
        pallas_matmul_int8(qa, qa, sa, sa, block=(100, 64, 64),
                           interpret=True)


# ---------------------------------------------------------------------------
# CheckpointManager: stepped async saves + rotation (design.md round-3
# item 1; the reference has no checkpoint subsystem at all, SURVEY.md §5)
# ---------------------------------------------------------------------------


def test_ckpt_manager_save_restore_rotation(tmp_path, rng):
    from distributedarrays_tpu.utils.checkpoint import CheckpointManager
    A = rng.standard_normal((24, 8)).astype(np.float32)
    with CheckpointManager(tmp_path / "run", max_to_keep=2) as mgr:
        for step in (1, 5, 9):
            d = dat.distribute(A * step, procs=range(4), dist=(4, 1))
            mgr.save(step, {"w": d, "step": step})
            d.close()
        mgr.wait()
        assert mgr.steps() == [5, 9]            # step 1 rotated out
        got = mgr.restore()                      # latest
        assert got["step"] == 9
        np.testing.assert_allclose(np.asarray(got["w"]), A * 9, rtol=1e-6)
        got5 = mgr.restore(5)
        assert got5["step"] == 5
        got5["w"].close(); got["w"].close()
    dat.d_closeall()


def test_ckpt_manager_async_decouples_mutation(tmp_path):
    # the host snapshot happens inside save(): mutating the source numpy
    # array right after save must not corrupt the checkpoint
    from distributedarrays_tpu.utils.checkpoint import CheckpointManager
    x = np.arange(16, dtype=np.float32)
    with CheckpointManager(tmp_path / "run") as mgr:
        mgr.save(0, {"x": x})
        x[:] = -1.0
    back = CheckpointManager(tmp_path / "run").restore(0)
    np.testing.assert_array_equal(back["x"], np.arange(16, dtype=np.float32))


def test_ckpt_manager_sync_mode_and_validation(tmp_path):
    from distributedarrays_tpu.utils.checkpoint import CheckpointManager
    mgr = CheckpointManager(tmp_path / "run", async_save=False,
                            max_to_keep=None)
    mgr.save(3, {"v": 7})
    assert mgr.steps() == [3]
    with pytest.raises(ValueError, match="already exists"):
        mgr.save(3, {"v": 8})
    with pytest.raises(ValueError, match="store"):
        mgr.save(4, {"v": 8}, store="tape")
    with pytest.raises(FileNotFoundError):
        mgr.restore(99)
    with pytest.raises(ValueError, match="max_to_keep"):
        CheckpointManager(tmp_path / "bad", max_to_keep=0)
    mgr.close()
    assert CheckpointManager(tmp_path / "run").restore()["v"] == 7


def test_ckpt_manager_duplicate_step_pending_async(tmp_path):
    # a duplicate step racing an in-flight async save must get the
    # designed ValueError, not a background os.replace failure
    from distributedarrays_tpu.utils.checkpoint import CheckpointManager
    with CheckpointManager(tmp_path / "run") as mgr:
        mgr.save(5, {"x": np.zeros(4096)})
        with pytest.raises(ValueError, match="already exists"):
            mgr.save(5, {"x": np.ones(4096)})
    assert CheckpointManager(tmp_path / "run").restore(5)["x"].sum() == 0


def test_ckpt_manager_background_failure_recoverable(tmp_path, monkeypatch):
    # a failed background save surfaces once and the step can be retried —
    # the failed future must leave the pending set, not wedge the manager
    from distributedarrays_tpu.utils import checkpoint as ck
    real = ck._write_store
    boom = {"n": 0}

    def flaky(*a, **k):
        if boom["n"] == 0:
            boom["n"] += 1
            raise OSError("disk full (simulated)")
        return real(*a, **k)

    monkeypatch.setattr(ck, "_write_store", flaky)
    mgr = ck.CheckpointManager(tmp_path / "run")
    mgr.save(1, {"v": 1})
    with pytest.raises(OSError, match="disk full"):
        mgr.wait()
    mgr.save(1, {"v": 2})        # retry after failure must be allowed
    mgr.wait()
    assert mgr.restore(1)["v"] == 2
    mgr.close()


def test_ckpt_manager_orbax_tier(tmp_path, rng):
    from distributedarrays_tpu.utils.checkpoint import CheckpointManager
    A = rng.standard_normal((8, 8)).astype(np.float32)
    with CheckpointManager(tmp_path / "run") as mgr:
        mgr.save(2, {"a": A}, store="orbax")
    back = CheckpointManager(tmp_path / "run").restore(2)
    np.testing.assert_allclose(back["a"], A, rtol=1e-6)


def test_ckpt_manager_ignores_partial_tmp_dirs(tmp_path):
    # a crash mid-save leaves only the hidden temp dir; steps() and
    # restore() must not see it
    from distributedarrays_tpu.utils.checkpoint import CheckpointManager
    mgr = CheckpointManager(tmp_path / "run", async_save=False)
    mgr.save(1, {"v": 1})
    (tmp_path / "run" / ".tmp_step_00000007").mkdir()
    (tmp_path / "run" / "step_00000009").mkdir()   # no meta -> incomplete
    assert mgr.steps() == [1]
    assert mgr.restore()["v"] == 1


# ---------------------------------------------------------------------------
# autotune registry
# ---------------------------------------------------------------------------


def _isolate_autotune(monkeypatch, tmp_path):
    # keep the test blind to any real tuning cache in the repo root, and
    # guarantee the process-global registry is wiped even when the test
    # body fails mid-way (in-body clear() would be skipped)
    from distributedarrays_tpu.utils import autotune
    monkeypatch.setenv("DAT_AUTOTUNE_CACHE", str(tmp_path / "none.json"))
    monkeypatch.setattr(autotune, "_LOADED_ENV", True)
    autotune.clear()
    monkeypatch.setattr(autotune, "_REGISTRY", {})
    return autotune


def test_autotune_registry_roundtrip(tmp_path, monkeypatch):
    autotune = _isolate_autotune(monkeypatch, tmp_path)
    key = autotune.key_for(8192, 8, 64, "bfloat16", True)
    assert autotune.get("flash_attention", key) is None
    autotune.record("flash_attention", key, [1024, 2048])
    assert autotune.get("flash_attention", key) == [1024, 2048]
    p = str(tmp_path / "cache.json")
    autotune.save(p)
    autotune.clear()
    assert autotune.get("flash_attention", key) is None
    autotune.load(p)
    assert autotune.get("flash_attention", key) == [1024, 2048]
    autotune.clear()


def test_autotune_sweep_picks_best_and_skips_invalid(tmp_path, monkeypatch):
    autotune = _isolate_autotune(monkeypatch, tmp_path)
    times = {(256, 256): 0.5, (512, 512): 0.2}

    def timer(cfg):
        if cfg == (1024, 1024):
            raise ValueError("invalid tiling")
        return times[cfg]

    best, results = autotune.sweep(
        "k", "key", [(256, 256), (512, 512), (1024, 1024)], timer)
    assert best == (512, 512)
    assert (1024, 1024) not in results
    assert autotune.get("k", "key") == (512, 512)
    autotune.clear()
    with pytest.raises(RuntimeError, match="boom"):
        autotune.sweep("k", "key2", [(1, 1)],
                       lambda c: (_ for _ in ()).throw(RuntimeError("boom")))


def test_flash_attention_consults_autotune(rng, tmp_path, monkeypatch):
    # tuned block sizes must flow into the kernel when blocks are left
    # unspecified — verified by recording a tune and checking the result
    # still matches the dense oracle (the tuned path must be correct, not
    # just selected)
    from distributedarrays_tpu.ops.pallas_attention import flash_attention
    autotune = _isolate_autotune(monkeypatch, tmp_path)
    import jax.numpy as jnp
    S, H, D = 256, 2, 32
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
    base = np.asarray(flash_attention(q, q, q, block_q=128, block_k=128))
    key = autotune.device_key_for(S, H, D, q.dtype, False)
    autotune.record("flash_attention", key, (64, 64))
    # spy: the kernel must ask the registry with exactly this key
    calls = []
    real_get = autotune.get

    def spy(kernel, k, default=None):
        calls.append((kernel, k))
        return real_get(kernel, k, default)

    monkeypatch.setattr(
        "distributedarrays_tpu.utils.autotune.get", spy)
    tuned = np.asarray(flash_attention(q, q, q))
    assert ("flash_attention", key) in calls, calls
    assert np.allclose(base, tuned, rtol=1e-4, atol=1e-4)
    # malformed entries must degrade to the default, not crash dispatch
    for bad in ([1024], [0, 0], "junk", None):
        autotune.record("flash_attention", key, bad)
        out = np.asarray(flash_attention(q, q, q))
        assert np.allclose(base, out, rtol=1e-4, atol=1e-4)


def test_pallas_matmul_malformed_tuned_entry_degrades(rng, tmp_path,
                                                      monkeypatch):
    from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
    autotune = _isolate_autotune(monkeypatch, tmp_path)
    import jax.numpy as jnp
    a = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
    want = np.asarray(a) @ np.asarray(a)
    key = autotune.device_key_for(256, 256, 256, a.dtype, a.dtype)
    for bad in ([256, 256], [0, 0, 0], [7, 13, 99], "junk"):
        autotune.record("pallas_matmul", key, bad)
        got = np.asarray(pallas_matmul(a, a))
        assert np.allclose(got, want, rtol=1e-4, atol=1e-3)
    # and a VALID tuned entry is honored (same numerics)
    autotune.record("pallas_matmul", key, [128, 128, 128])
    got = np.asarray(pallas_matmul(a, a))
    assert np.allclose(got, want, rtol=1e-4, atol=1e-3)


def test_pallas_matmul_int8_malformed_tuned_entry_degrades(
        rng, tmp_path, monkeypatch):
    # the int8 path shares _resolve_block: a cache entry that divides the
    # shape but violates Mosaic int8 alignment (m%32/n%128/k%128) must
    # degrade to the heuristic, not reach the kernel build
    from distributedarrays_tpu.ops import pallas_gemm as pg
    autotune = _isolate_autotune(monkeypatch, tmp_path)
    a = rng.standard_normal((256, 256)).astype(np.float32)
    qa, sa = pg.quantize_rows(a, 1)
    qb, sb = pg.quantize_rows(a, 0)
    want = np.asarray(pg.pallas_matmul_int8(qa, qb, sa, sb, interpret=True))
    key = autotune.device_key_for(256, 256, 256, "int8")
    # force the non-interpret resolution path to prove alignment filtering
    # (the kernel itself still runs in interpret mode on CPU)
    for bad in ([8, 128, 128], [32, 64, 128], [32, 128, 64], "junk"):
        autotune.record("pallas_matmul_int8", key, bad)
        bm, bn, bk = pg._resolve_block(
            256, 256, 256, None, False, kernel="pallas_matmul_int8",
            dtype_key=("int8",), caps=(1024, 1024, 1024), m_align=32)
        assert bm % 32 == 0 and bn % 128 == 0 and bk % 128 == 0, bad
    # a valid tuned entry is honored end to end
    autotune.record("pallas_matmul_int8", key, [128, 128, 128])
    got = np.asarray(pg.pallas_matmul_int8(qa, qb, sa, sb, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-6)
