"""Distributed linear algebra tests (reference src/linalg.jl semantics;
oracle = numpy, mirroring the reference's GEMM checks test/darray.jl:921-924)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray
from distributedarrays_tpu.ops import linalg as la


@pytest.fixture
def mats(rng):
    A = rng.standard_normal((48, 32)).astype(np.float32)
    B = rng.standard_normal((32, 40)).astype(np.float32)
    return A, B


def test_ddot_dnorm(rng):
    x = rng.standard_normal(1000).astype(np.float32)
    y = rng.standard_normal(1000).astype(np.float32)
    dx, dy = dat.distribute(x), dat.distribute(y)
    assert np.allclose(float(la.ddot(dx, dy)), np.dot(x, y), rtol=1e-4)
    assert np.allclose(float(la.dnorm(dx)), np.linalg.norm(x), rtol=1e-5)
    assert np.allclose(float(la.dnorm(dx, 1)), np.abs(x).sum(), rtol=1e-5)
    assert np.allclose(float(la.dnorm(dx, np.inf)), np.abs(x).max(), rtol=1e-6)
    with pytest.raises(ValueError):
        la.ddot(dx, dat.dzeros((7,)))


def test_axpy(rng):
    x = rng.standard_normal(100).astype(np.float32)
    y = rng.standard_normal(100).astype(np.float32)
    dx, dy = dat.distribute(x), dat.distribute(y.copy())
    out = la.axpy_(2.5, dx, dy)
    assert out is dy
    assert np.allclose(np.asarray(dy), 2.5 * x + y, rtol=1e-5)
    with pytest.raises(ValueError):
        la.axpy_(1.0, dat.dzeros((7,)), dy)


def test_scalar_scaling(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    d = dat.distribute(A.copy())
    la.rmul_(d, 3.0)
    assert np.allclose(np.asarray(d), A * 3, rtol=1e-6)
    la.lmul_(0.5, d)
    assert np.allclose(np.asarray(d), A * 1.5, rtol=1e-6)


def test_diagonal_scaling(rng):
    A = rng.standard_normal((12, 8)).astype(np.float32)
    dl = rng.standard_normal(12).astype(np.float32)
    dr = rng.standard_normal(8).astype(np.float32)
    d = dat.distribute(A.copy())
    la.lmul_diag(dl, d)
    assert np.allclose(np.asarray(d), dl[:, None] * A, rtol=1e-5)
    d2 = dat.distribute(A.copy())
    la.rmul_diag(d2, dr)
    assert np.allclose(np.asarray(d2), A * dr[None, :], rtol=1e-5)
    with pytest.raises(ValueError):
        la.lmul_diag(dr, d)  # wrong length


def test_transpose_adjoint(mats):
    A, _ = mats
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    t = d.T
    assert isinstance(t, DArray)
    assert t.dims == (32, 48)
    assert t.pids.shape == (2, 4)
    assert np.allclose(np.asarray(t), A.T)
    z = (dat.distribute(A.astype(np.complex64) + 1j)).garray
    dz = dat.distribute(np.asarray(z))
    adj = la.dadjoint(dz)
    assert np.allclose(np.asarray(adj), np.conj(np.asarray(z)).T)


def test_matmul_dd(mats):
    A, B = mats
    da = dat.distribute(A, procs=range(8), dist=(4, 2))
    db = dat.distribute(B, procs=range(8), dist=(2, 4))
    C = da @ db
    assert isinstance(C, DArray)
    assert C.dims == (48, 40)
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)
    # result rows follow A's row grid (reference linalg.jl:261-311)
    assert C.pids.shape[0] == 4


def test_matmul_mixed_plain(mats):
    A, B = mats
    da = dat.distribute(A)
    C = da @ B                      # plain numpy rhs
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)
    C2 = A @ dat.distribute(B)      # plain numpy lhs
    assert np.allclose(np.asarray(C2), A @ B, rtol=1e-4, atol=1e-4)


def test_matvec(mats, rng):
    A, _ = mats
    x = rng.standard_normal(32).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    y = da @ dat.distribute(x)
    assert y.dims == (48,)
    assert np.allclose(np.asarray(y), A @ x, rtol=1e-4, atol=1e-4)


def test_mul_into_cuts_contract(mats):
    A, B = mats
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    db = dat.distribute(B)
    C_good = dat.dzeros((48, 40), procs=range(4), dist=(4, 1))
    la.mul_into(C_good, da, db)
    assert np.allclose(np.asarray(C_good), A @ B, rtol=1e-4, atol=1e-4)
    # row-cuts mismatch must throw (reference linalg.jl:201)
    C_bad = dat.dzeros((48, 40), procs=range(3), dist=(3, 1))
    with pytest.raises(ValueError, match="row cuts"):
        la.mul_into(C_bad, da, db)


def test_mul_into_alpha_beta(mats, rng):
    A, B = mats
    C0 = rng.standard_normal((48, 40)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    db = dat.distribute(B)
    C = dat.distribute(C0.copy(), procs=range(4), dist=(4, 1))
    assert C.cuts[0] == da.cuts[0]
    la.mul_into(C, da, db, alpha=2.0, beta=0.5)
    assert np.allclose(np.asarray(C), 2.0 * (A @ B) + 0.5 * C0,
                       rtol=1e-4, atol=1e-4)


def test_matmul_dim_mismatch(mats):
    A, B = mats
    with pytest.raises(ValueError):
        dat.distribute(A) @ dat.distribute(A)


def test_matmul_uneven_rows(rng):
    # 50 rows over 4 chunks: uneven layout must still produce correct GEMM
    A = rng.standard_normal((50, 20)).astype(np.float32)
    B = rng.standard_normal((20, 30)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    C = da @ dat.distribute(B)
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)
