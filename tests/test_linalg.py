"""Distributed linear algebra tests (reference src/linalg.jl semantics;
oracle = numpy, mirroring the reference's GEMM checks test/darray.jl:921-924)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray
from distributedarrays_tpu.ops import linalg as la


@pytest.fixture
def mats(rng):
    A = rng.standard_normal((48, 32)).astype(np.float32)
    B = rng.standard_normal((32, 40)).astype(np.float32)
    return A, B


def test_ddot_dnorm(rng):
    x = rng.standard_normal(1000).astype(np.float32)
    y = rng.standard_normal(1000).astype(np.float32)
    dx, dy = dat.distribute(x), dat.distribute(y)
    assert np.allclose(float(la.ddot(dx, dy)), np.dot(x, y), rtol=1e-4)
    assert np.allclose(float(la.dnorm(dx)), np.linalg.norm(x), rtol=1e-5)
    assert np.allclose(float(la.dnorm(dx, 1)), np.abs(x).sum(), rtol=1e-5)
    assert np.allclose(float(la.dnorm(dx, np.inf)), np.abs(x).max(), rtol=1e-6)
    with pytest.raises(ValueError):
        la.ddot(dx, dat.dzeros((7,)))


def test_axpy(rng):
    x = rng.standard_normal(100).astype(np.float32)
    y = rng.standard_normal(100).astype(np.float32)
    dx, dy = dat.distribute(x), dat.distribute(y.copy())
    out = la.axpy_(2.5, dx, dy)
    assert out is dy
    assert np.allclose(np.asarray(dy), 2.5 * x + y, rtol=1e-5)
    with pytest.raises(ValueError):
        la.axpy_(1.0, dat.dzeros((7,)), dy)


def test_scalar_scaling(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    d = dat.distribute(A.copy())
    la.rmul_(d, 3.0)
    assert np.allclose(np.asarray(d), A * 3, rtol=1e-6)
    la.lmul_(0.5, d)
    assert np.allclose(np.asarray(d), A * 1.5, rtol=1e-6)


def test_diagonal_scaling(rng):
    A = rng.standard_normal((12, 8)).astype(np.float32)
    dl = rng.standard_normal(12).astype(np.float32)
    dr = rng.standard_normal(8).astype(np.float32)
    d = dat.distribute(A.copy())
    la.lmul_diag(dl, d)
    assert np.allclose(np.asarray(d), dl[:, None] * A, rtol=1e-5)
    d2 = dat.distribute(A.copy())
    la.rmul_diag(d2, dr)
    assert np.allclose(np.asarray(d2), A * dr[None, :], rtol=1e-5)
    with pytest.raises(ValueError):
        la.lmul_diag(dr, d)  # wrong length


def test_transpose_adjoint(mats):
    A, _ = mats
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    t = d.T
    assert isinstance(t, DArray)
    assert t.dims == (32, 48)
    assert t.pids.shape == (2, 4)
    assert np.allclose(np.asarray(t), A.T)
    z = (dat.distribute(A.astype(np.complex64) + 1j)).garray
    dz = dat.distribute(np.asarray(z))
    adj = la.dadjoint(dz)
    assert np.allclose(np.asarray(adj), np.conj(np.asarray(z)).T)


def test_matmul_dd(mats):
    A, B = mats
    da = dat.distribute(A, procs=range(8), dist=(4, 2))
    db = dat.distribute(B, procs=range(8), dist=(2, 4))
    C = da @ db
    assert isinstance(C, DArray)
    assert C.dims == (48, 40)
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)
    # result rows follow A's row grid (reference linalg.jl:261-311)
    assert C.pids.shape[0] == 4


def test_matmul_mixed_plain(mats):
    A, B = mats
    da = dat.distribute(A)
    C = da @ B                      # plain numpy rhs
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)
    C2 = A @ dat.distribute(B)      # plain numpy lhs
    assert np.allclose(np.asarray(C2), A @ B, rtol=1e-4, atol=1e-4)


def test_matvec(mats, rng):
    A, _ = mats
    x = rng.standard_normal(32).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    y = da @ dat.distribute(x)
    assert y.dims == (48,)
    assert np.allclose(np.asarray(y), A @ x, rtol=1e-4, atol=1e-4)


def test_mul_into_cuts_contract(mats):
    A, B = mats
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    db = dat.distribute(B)
    C_good = dat.dzeros((48, 40), procs=range(4), dist=(4, 1))
    la.mul_into(C_good, da, db)
    assert np.allclose(np.asarray(C_good), A @ B, rtol=1e-4, atol=1e-4)
    # row-cuts mismatch must throw (reference linalg.jl:201)
    C_bad = dat.dzeros((48, 40), procs=range(3), dist=(3, 1))
    with pytest.raises(ValueError, match="row cuts"):
        la.mul_into(C_bad, da, db)


def test_mul_into_alpha_beta(mats, rng):
    A, B = mats
    C0 = rng.standard_normal((48, 40)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    db = dat.distribute(B)
    C = dat.distribute(C0.copy(), procs=range(4), dist=(4, 1))
    assert C.cuts[0] == da.cuts[0]
    la.mul_into(C, da, db, alpha=2.0, beta=0.5)
    assert np.allclose(np.asarray(C), 2.0 * (A @ B) + 0.5 * C0,
                       rtol=1e-4, atol=1e-4)


def test_matmul_dim_mismatch(mats):
    A, B = mats
    with pytest.raises(ValueError):
        dat.distribute(A) @ dat.distribute(A)


def test_matmul_uneven_rows(rng):
    # 50 rows over 4 chunks: uneven layout must still produce correct GEMM
    A = rng.standard_normal((50, 20)).astype(np.float32)
    B = rng.standard_normal((20, 30)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    C = da @ dat.distribute(B)
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# matmul implementation dispatch (VERDICT round-3 item 4): the owned GEMM
# schedules behind the autotune registry, jnp.matmul as the default
# ---------------------------------------------------------------------------


def test_matmul_default_impl_is_jnp(mats, monkeypatch):
    A, B = mats
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    calls = []
    monkeypatch.setattr(la, "_try_pallas_gemm",
                        lambda *a: calls.append(1) or None)
    da = dat.distribute(A, procs=[0], dist=(1, 1))
    C = da @ dat.distribute(B, procs=[0], dist=(1, 1))
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4)
    assert not calls, "pallas path must not run without a banked win"
    dat.d_closeall()


def test_matmul_registry_promotes_pallas(mats, monkeypatch):
    A, B = mats
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    da = dat.distribute(A, procs=[0], dist=(1, 1))
    db = dat.distribute(B, procs=[0], dist=(1, 1))
    key = la._impl_key(48, 40, 32, da.garray.dtype, db.garray.dtype)
    autotune.record("matmul_impl", key, "pallas")
    called = []
    orig = la._try_pallas_gemm
    monkeypatch.setattr(la, "_try_pallas_gemm",
                        lambda *a: called.append(1) or orig(*a))
    C = da @ db
    assert called, "banked pallas win must route through the pallas path"
    assert np.allclose(np.asarray(C), A @ B, rtol=1e-3, atol=1e-3)
    # multi-device operands stay on the GSPMD path even with the entry
    da4 = dat.distribute(A, procs=range(4), dist=(4, 1))
    key4 = la._impl_key(48, 40, 32, da4.garray.dtype, db.garray.dtype)
    autotune.record("matmul_impl", key4, "pallas")
    C4 = da4 @ dat.distribute(B)
    assert np.allclose(np.asarray(C4), A @ B, rtol=1e-4, atol=1e-4)
    autotune.clear()
    dat.d_closeall()


def test_matmul_ring_allgather_dispatch(rng, monkeypatch):
    # the 1-D TP shape: A row-chunked (p,1) x B contraction-chunked (p,1)
    # -> C row-chunked (p,1), run as ONE overlapped-ring shard_map program
    # when the registry promotes it
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    A = rng.standard_normal((16, 32)).astype(np.float32)
    B = rng.standard_normal((32, 12)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    db = dat.distribute(B, procs=range(4), dist=(4, 1))
    called = []
    orig = la._ring_ag_gemm
    monkeypatch.setattr(la, "_ring_ag_gemm",
                        lambda *a: called.append(1) or orig(*a))
    # default (no banked entry): GSPMD path
    C0 = da @ db
    assert not called
    assert np.allclose(np.asarray(C0), A @ B, rtol=1e-4, atol=1e-4)
    # promoted: ring path, both out-of-place and mul_into
    autotune.record("matmul_impl_dist",
                    la._impl_key(16, 12, 32, 4, da.dtype, db.dtype),
                    "ring_ag")
    C1 = da @ db
    assert called, "banked ring win must route through the ring schedule"
    assert np.allclose(np.asarray(C1), A @ B, rtol=1e-4, atol=1e-4)
    assert list(C1.pids.shape) == [4, 1] and C1.cuts[0] == da.cuts[0]
    called.clear()
    C2 = dat.dzeros((16, 12), procs=range(4), dist=(4, 1))
    la.mul_into(C2, da, db)
    assert called
    assert np.allclose(np.asarray(C2), A @ B, rtol=1e-4, atol=1e-4)
    # alpha/beta mode stays off the ring
    called.clear()
    C3 = dat.dzeros((16, 12), procs=range(4), dist=(4, 1))
    la.mul_into(C3, da, db, alpha=2.0)
    assert not called
    assert np.allclose(np.asarray(C3), 2 * (A @ B), rtol=1e-4, atol=1e-4)
    autotune.clear()
    dat.d_closeall()


def test_matmul_summa_dispatch(rng, monkeypatch):
    # the square 2-D-grid shape (BASELINE config 3): A and B block-
    # distributed on the SAME (g,g) grid -> C on that grid, run as ONE
    # Cannon double-ring shard_map program when the registry promotes it
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    A = rng.standard_normal((16, 24)).astype(np.float32)
    B = rng.standard_normal((24, 8)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(2, 2))
    db = dat.distribute(B, procs=range(4), dist=(2, 2))
    called = []
    orig = la._summa_gemm
    monkeypatch.setattr(la, "_summa_gemm",
                        lambda *a: called.append(1) or orig(*a))
    # default (no banked entry): GSPMD path
    C0 = da @ db
    assert not called
    assert np.allclose(np.asarray(C0), A @ B, rtol=1e-4, atol=1e-4)
    # promoted: Cannon path, both out-of-place and mul_into
    autotune.record("matmul_impl_dist",
                    la._impl_key(16, 8, 24, "2x2", da.dtype, db.dtype),
                    "summa")
    C1 = da @ db
    assert called, "banked summa win must route through the Cannon ring"
    assert np.allclose(np.asarray(C1), A @ B, rtol=1e-4, atol=1e-4)
    assert list(C1.pids.shape) == [2, 2] and C1.cuts[0] == da.cuts[0]
    called.clear()
    C2 = dat.dzeros((16, 8), procs=range(4), dist=(2, 2))
    la.mul_into(C2, da, db)
    assert called
    assert np.allclose(np.asarray(C2), A @ B, rtol=1e-4, atol=1e-4)
    # alpha/beta mode stays off the ring
    called.clear()
    C3 = dat.dzeros((16, 8), procs=range(4), dist=(2, 2))
    la.mul_into(C3, da, db, alpha=2.0)
    assert not called
    assert np.allclose(np.asarray(C3), 2 * (A @ B), rtol=1e-4, atol=1e-4)
    # MISMATCHED grids ((2,4) A vs (4,2) B) are NOT eligible even with a
    # banked entry — the tile schedules need both operands on ONE grid
    da2 = dat.distribute(A, procs=range(8), dist=(2, 4))
    db2 = dat.distribute(B, procs=range(8), dist=(4, 2))
    autotune.record("matmul_impl_dist",
                    la._impl_key(16, 8, 24, "2x4", da2.dtype, db2.dtype),
                    "summa")
    called.clear()
    C4 = da2 @ db2
    assert not called
    assert np.allclose(np.asarray(C4), A @ B, rtol=1e-4, atol=1e-4)
    autotune.clear()
    dat.d_closeall()


def test_matmul_summa_rectangular_dispatch(rng, monkeypatch):
    # a SAME-grid rectangular (2,4) layout routes to the masked-psum
    # SUMMA panel schedule when promoted (square grids take Cannon)
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    A = rng.standard_normal((16, 24)).astype(np.float32)
    B = rng.standard_normal((24, 8)).astype(np.float32)
    da = dat.distribute(A, procs=range(8), dist=(2, 4))
    db = dat.distribute(B, procs=range(8), dist=(2, 4))
    called = []
    orig = la._summa_gemm
    monkeypatch.setattr(la, "_summa_gemm",
                        lambda *a: called.append(1) or orig(*a))
    C0 = da @ db                       # default: GSPMD
    assert not called
    assert np.allclose(np.asarray(C0), A @ B, rtol=1e-4, atol=1e-4)
    autotune.record("matmul_impl_dist",
                    la._impl_key(16, 8, 24, "2x4", da.dtype, db.dtype),
                    "summa")
    C1 = da @ db
    assert called, "banked rect-grid win must route through summa_matmul"
    assert np.allclose(np.asarray(C1), A @ B, rtol=1e-4, atol=1e-4)
    assert list(C1.pids.shape) == [2, 4]
    autotune.clear()
    dat.d_closeall()


def test_tune_matmul_impl_summa_banks_winner():
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    times = {"jnp": 1.0, "summa": 0.5}
    seen = []

    def timer(op, a, b):
        assert a.shape == (16, 24) and b.shape == (24, 8)
        name = "jnp" if not seen else "summa"
        seen.append(name)
        return times[name]

    winner, results = la.tune_matmul_impl_summa(
        16, 8, 24, g=2, timer=timer, persist=False)
    assert winner == "summa" and results == times
    f32 = jnp.float32(0).dtype
    assert autotune.get("matmul_impl_dist",
                        la._impl_key(16, 8, 24, "2x2", f32, f32)) == "summa"
    with pytest.raises(ValueError, match="divisible"):
        la.tune_matmul_impl_summa(15, 8, 24, g=2, timer=timer)
    # rectangular grid: same flow, rxc-tagged key
    winner, results = la.tune_matmul_impl_summa(
        16, 8, 24, g=(2, 4), timer=lambda op, a, b: 1.0, persist=False)
    assert set(results) == {"jnp", "summa"}
    assert autotune.get("matmul_impl_dist",
                        la._impl_key(16, 8, 24, "2x4", f32, f32)) is not None
    autotune.clear()


def test_tune_matmul_impl_banks_winner():
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    # injectable timer: declare pallas the winner deterministically
    times = {"jnp": 2.0, "pallas": 1.0}
    seq = iter(["jnp", "pallas"])

    def timer(op, a, b):
        assert a.shape == (256, 256) and b.shape == (256, 256)
        return times[next(seq)]

    winner, results = la.tune_matmul_impl(256, 256, 256, jnp.float32,
                                          timer=timer, persist=False)
    assert winner == "pallas" and results == times
    f32 = jnp.float32(0).dtype
    key = la._impl_key(256, 256, 256, f32, f32)
    assert autotune.get("matmul_impl", key) == "pallas"
    # the key is platform-fenced: a winner banked here must be invisible
    # under any other device kind
    assert autotune.get("matmul_impl",
                        autotune.key_for(256, 256, 256, f32, f32)) is None
    autotune.clear()


def test_tune_matmul_impl_dist_banks_winner():
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    times = {"jnp": 1.0, "ring_ag": 0.5}
    seen = []

    def timer(op, a, b):
        # real sharded operands reach the timer
        assert a.shape == (64, 32) and b.shape == (32, 16)
        name = "jnp" if not seen else "ring_ag"
        seen.append(name)
        return times[name]

    winner, results = la.tune_matmul_impl_dist(
        64, 16, 32, p=4, timer=timer, persist=False)
    assert winner == "ring_ag" and results == times
    f32 = jnp.float32(0).dtype
    assert autotune.get("matmul_impl_dist",
                        la._impl_key(64, 16, 32, 4, f32, f32)) == "ring_ag"
    with pytest.raises(ValueError, match="devices"):
        la.tune_matmul_impl_dist(64, 16, 32, p=1, timer=timer)
    with pytest.raises(ValueError, match="divisible"):
        la.tune_matmul_impl_dist(63, 16, 32, p=4, timer=timer)
    autotune.clear()


def test_dmatmul_int8_single_device(rng):
    A = rng.standard_normal((128, 64)).astype(np.float32)
    B = rng.standard_normal((64, 96)).astype(np.float32)
    da = dat.distribute(A, procs=[0], dist=(1, 1))
    C = dat.dmatmul_int8(da, B)
    ref = A @ B
    assert np.abs(np.asarray(C) - ref).max() / np.abs(ref).max() < 3e-2


def test_dmatmul_int8_row_sharded(rng):
    A = rng.standard_normal((128, 64)).astype(np.float32)
    B = rng.standard_normal((64, 96)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    C = dat.dmatmul_int8(da, dat.distribute(B))
    assert list(C.pids.shape) == [4, 1]
    ref = A @ B
    assert np.abs(np.asarray(C) - ref).max() / np.abs(ref).max() < 3e-2
    dat.d_closeall()


def test_dmatmul_int8_square_grid(rng):
    # both operands on one (2,2) grid: int8 panels + per-panel scales
    # ride the Cannon double ring (cannon_matmul_int8)
    A = rng.standard_normal((64, 64)).astype(np.float32)
    B = rng.standard_normal((64, 32)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(2, 2))
    db = dat.distribute(B, procs=range(4), dist=(2, 2))
    C = dat.dmatmul_int8(da, db)
    assert list(C.pids.shape) == [2, 2]
    ref = A @ B
    assert np.abs(np.asarray(C) - ref).max() / np.abs(ref).max() < 3e-2
    dat.d_closeall()


def test_dmatmul_int8_validation(rng):
    A = rng.standard_normal((50, 64)).astype(np.float32)  # uneven rows
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    with pytest.raises(ValueError, match="even"):
        dat.dmatmul_int8(da, np.zeros((64, 8), np.float32))
    db = dat.distribute(rng.standard_normal((64, 32)).astype(np.float32),
                        procs=range(8), dist=(2, 4))
    da2 = dat.distribute(rng.standard_normal((16, 64)).astype(np.float32),
                         procs=range(8), dist=(2, 4))
    with pytest.raises(ValueError, match="grid"):
        dat.dmatmul_int8(da2, db)
    with pytest.raises(ValueError, match="mismatch"):
        dat.dmatmul_int8(dat.distribute(A, procs=[0], dist=(1, 1)),
                         np.zeros((8, 8), np.float32))
    dat.d_closeall()


def test_dmatmul_int8_host_array_lhs(rng):
    # plain ndarray A lands on a supported layout automatically
    A = rng.standard_normal((128, 64)).astype(np.float32)   # 128 % 8 == 0
    B = rng.standard_normal((64, 96)).astype(np.float32)
    C = dat.dmatmul_int8(A, B)
    ref = A @ B
    assert np.abs(np.asarray(C) - ref).max() / np.abs(ref).max() < 3e-2
    A2 = rng.standard_normal((51, 64)).astype(np.float32)   # indivisible
    C2 = dat.dmatmul_int8(A2, B)
    ref2 = A2 @ B
    assert np.abs(np.asarray(C2) - ref2).max() / np.abs(ref2).max() < 3e-2
    dat.d_closeall()
