"""Model-layer tests: halo-exchange stencil programs (the reference's Life
demo, docs/src/index.md:160-204) and the flagship sharded-MLP train step,
plus the driver entry points."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.models import mlp, stencil


def _lap(A):
    p = np.zeros((1, A.shape[1]), A.dtype)
    x = np.concatenate([p, A, p], axis=0)
    left = np.concatenate([np.zeros((A.shape[0], 1), A.dtype), A[:, :-1]], axis=1)
    right = np.concatenate([A[:, 1:], np.zeros((A.shape[0], 1), A.dtype)], axis=1)
    return x[:-2] + x[2:] + left + right - 4 * A


def test_stencil5_matches_oracle(rng):
    A = rng.standard_normal((64, 32)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.stencil5(d))
    assert np.allclose(got, _lap(A), rtol=1e-5, atol=1e-5)


def test_stencil5_multi_iter(rng):
    A = rng.standard_normal((64, 32)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.stencil5(d, iters=3))
    want = _lap(_lap(_lap(A)))
    assert np.allclose(got, want, rtol=1e-4, atol=1e-4)


def test_stencil_layout_requirements():
    d = dat.dzeros((50, 8), procs=range(4), dist=(4, 1))  # 50 % 4 != 0
    with pytest.raises(ValueError, match="row-sharded"):
        stencil.stencil5(d)
    d2 = dat.dzeros((16, 16), procs=range(4), dist=(2, 2))
    with pytest.raises(ValueError, match="row-sharded"):
        stencil.stencil5(d2)


def _life_oracle(A, iters=1):
    for _ in range(iters):
        xp = np.pad(A, 1)
        neigh = sum(np.roll(np.roll(xp, i, 0), j, 1)[1:-1, 1:-1]
                    for i in (-1, 0, 1) for j in (-1, 0, 1)
                    if not (i == 0 and j == 0))
        A = (((A == 0) & (neigh == 3)) |
             ((A == 1) & ((neigh == 2) | (neigh == 3)))).astype(A.dtype)
    return A


def test_life_matches_oracle(rng):
    A = (rng.random((32, 24)) < 0.4).astype(np.int32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.life(d, iters=4))
    assert np.array_equal(got, _life_oracle(A, 4))


def test_life_glider_translates():
    # a glider moves one cell diagonally every 4 generations — an exact
    # long-horizon integration check across chunk boundaries
    A = np.zeros((40, 40), np.int32)
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.int32)
    A[1:4, 1:4] = glider
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.life(d, iters=4))
    want = np.zeros_like(A)
    want[2:5, 2:5] = glider
    assert np.array_equal(got, want)


def test_mlp_train_step_loss_decreases():
    mesh = mlp.make_mesh(8)
    sizes = [32, 64, 16]
    params = mlp.shard_params(mlp.init_params(jax.random.key(0), sizes), mesh)
    x = jax.random.normal(jax.random.key(1), (32, 32), jnp.bfloat16)
    y = jax.random.normal(jax.random.key(2), (32, 16), jnp.bfloat16)
    x, y = mlp.shard_batch(x, y, mesh)
    losses = []
    for _ in range(20):
        params, loss = mlp.train_step(params, x, y, lr=1e-2)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]


def test_graft_entry_points():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (64, 128)
    g.dryrun_multichip(8)
