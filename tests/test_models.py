"""Model-layer tests: halo-exchange stencil programs (the reference's Life
demo, docs/src/index.md:160-204) and the flagship sharded-MLP train step,
plus the driver entry points."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.models import mlp, stencil


def _lap(A):
    p = np.zeros((1, A.shape[1]), A.dtype)
    x = np.concatenate([p, A, p], axis=0)
    left = np.concatenate([np.zeros((A.shape[0], 1), A.dtype), A[:, :-1]], axis=1)
    right = np.concatenate([A[:, 1:], np.zeros((A.shape[0], 1), A.dtype)], axis=1)
    return x[:-2] + x[2:] + left + right - 4 * A


def test_stencil5_matches_oracle(rng):
    A = rng.standard_normal((64, 32)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.stencil5(d))
    assert np.allclose(got, _lap(A), rtol=1e-5, atol=1e-5)


def test_stencil5_multi_iter(rng):
    A = rng.standard_normal((64, 32)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.stencil5(d, iters=3))
    want = _lap(_lap(_lap(A)))
    assert np.allclose(got, want, rtol=1e-4, atol=1e-4)


def test_stencil5_pallas_matches_oracle(rng):
    # the Pallas streaming kernel (interpret mode off-TPU), including
    # multi-block row streaming and halo rows crossing ranks
    A = rng.standard_normal((64, 32)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.stencil5(d, use_pallas=True))
    assert np.allclose(got, _lap(A), rtol=1e-5, atol=1e-5)
    d2 = dat.distribute(A, procs=range(8), dist=(8, 1))
    got3 = np.asarray(stencil.stencil5(d2, iters=3, use_pallas=True))
    assert np.allclose(got3, _lap(_lap(_lap(A))), rtol=1e-4, atol=1e-4)


def test_stencil5_pallas_multiblock(rng):
    # force >1 row-block per rank so the top/bot boundary-row arrays and
    # identity index maps are really exercised
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_block
    A = rng.standard_normal((64, 32)).astype(np.float32)
    lo = np.zeros((1, 32), np.float32)
    hi = np.zeros((1, 32), np.float32)
    got = np.asarray(stencil5_block(jnp.asarray(A), jnp.asarray(lo),
                                    jnp.asarray(hi), block_rows=16))
    assert np.allclose(got, _lap(A), rtol=1e-5, atol=1e-5)
    # non-zero halos enter the first/last rows
    lo2 = np.full((1, 32), 2.0, np.float32)
    hi2 = np.full((1, 32), -3.0, np.float32)
    got2 = np.asarray(stencil5_block(jnp.asarray(A), jnp.asarray(lo2),
                                     jnp.asarray(hi2), block_rows=16))
    want2 = _lap(A)
    want2[0] += 2.0
    want2[-1] += -3.0
    assert np.allclose(got2, want2, rtol=1e-5, atol=1e-5)


def test_stencil5_pallas_odd_rows(rng):
    # rows with no >=8 divisor: small blocks take the whole-array escape
    # (block dims == array dims), large ones must raise with guidance
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_block
    A = rng.standard_normal((31, 32)).astype(np.float32)
    z = np.zeros((1, 32), np.float32)
    got = np.asarray(stencil5_block(jnp.asarray(A), jnp.asarray(z),
                                    jnp.asarray(z)))
    assert np.allclose(got, _lap(A), rtol=1e-5, atol=1e-5)
    big = jnp.zeros((5001, 1024), jnp.float32)
    zb = jnp.zeros((1, 1024), jnp.float32)
    with pytest.raises(ValueError, match="use_pallas=False"):
        stencil5_block(big, zb, zb)


def _apply3x3_np(A, w):
    w = np.asarray(w, np.float32)
    xp = np.pad(A, 1)
    out = np.zeros_like(A)
    for a in range(3):
        for b in range(3):
            out += w[a, b] * xp[a:a + A.shape[0], b:b + A.shape[1]]
    return out


def test_stencil3x3_matches_oracle(rng):
    # arbitrary weights (incl. diagonal taps) through the jnp path, the
    # streaming kernel, and temporal blocking, vs a numpy oracle
    from distributedarrays_tpu.models.stencil import stencil3x3
    w = rng.standard_normal((3, 3)).astype(np.float32)
    A = rng.standard_normal((64, 32)).astype(np.float32)
    want = A
    for _ in range(4):
        want = _apply3x3_np(want, w)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got_jnp = np.asarray(stencil3x3(d, w, iters=4, use_pallas=False))
    assert np.allclose(got_jnp, want, rtol=1e-3, atol=1e-3)
    d2 = dat.distribute(A, procs=range(8), dist=(8, 1))
    got_k = np.asarray(stencil3x3(d2, w, iters=4, use_pallas=True,
                                  temporal=1))
    assert np.allclose(got_k, want, rtol=1e-3, atol=1e-3)
    got_t = np.asarray(stencil3x3(d2, w, iters=4, use_pallas=True,
                                  temporal=4))
    assert np.allclose(got_t, want, rtol=1e-3, atol=1e-3)


def test_stencil3x3_weight_validation():
    from distributedarrays_tpu.models.stencil import stencil3x3
    d = dat.dzeros((16, 16), procs=range(8), dist=(8, 1))
    with pytest.raises(ValueError, match="3x3"):
        stencil3x3(d, np.ones((2, 2)))


def test_stencil5_is_laplacian_3x3(rng):
    # stencil5 must be exactly the Laplacian instance of stencil3x3
    from distributedarrays_tpu.models.stencil import stencil3x3
    from distributedarrays_tpu.ops.pallas_stencil import LAPLACIAN_3X3
    A = rng.standard_normal((32, 32)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    a = np.asarray(stencil.stencil5(d, iters=2, use_pallas=False))
    b = np.asarray(stencil3x3(d, LAPLACIAN_3X3, iters=2, use_pallas=False))
    assert np.array_equal(a, b)


def test_stencil5_temporal_matches_oracle(rng):
    # temporal blocking (k steps per launch, depth-k ghost zones) must be
    # bit-exact vs iterating the jnp step: k dividing iters, a remainder
    # launch, k > iters clamped, and the auto depth
    A = rng.standard_normal((64, 32)).astype(np.float32)
    want = A
    for _ in range(5):
        want = _lap(want)
    for kt in (2, 3, 5, None):
        d = dat.distribute(A, procs=range(8), dist=(8, 1))
        got = np.asarray(stencil.stencil5(d, iters=5, use_pallas=True,
                                          temporal=kt))
        assert np.allclose(got, want, rtol=1e-4, atol=1e-4), kt


def test_stencil5_temporal_single_rank_dirichlet(rng):
    # one rank owns both global edges: the in-kernel per-step re-zero of
    # the Dirichlet ghost zones is what keeps this exact
    A = rng.standard_normal((32, 32)).astype(np.float32)
    want = A
    for _ in range(7):
        want = _lap(want)
    d = dat.distribute(A, procs=[0], dist=(1, 1))
    got = np.asarray(stencil.stencil5(d, iters=7, use_pallas=True,
                                      temporal=4))
    # 7 chained f32 steps amplify values ~8^7x; summation-order rounding
    # accumulates, so the bound is relative
    assert np.allclose(got, want, rtol=1e-3, atol=1e-3)


def test_stencil5_temporal_ghost_deeper_than_block(rng):
    # k >= bm + 2: the Dirichlet ghost zone spills past the first/last
    # row-block, so the in-kernel re-zero must use global row coordinates
    # (block-local gating corrupts rows near the global edge)
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_multistep
    A = rng.standard_normal((32, 128)).astype(np.float32)
    k = 12
    want = A
    for _ in range(k):
        want = _lap(want)
    z = jnp.zeros((k, 128), jnp.float32)
    got = np.asarray(stencil5_multistep(jnp.asarray(A), z, z, k,
                                        True, True, block_rows=8))
    # k chained f32 steps blow values up ~8^k; bound error by the array
    # scale (near-cancelled entries are relatively inaccurate by nature)
    assert np.abs(got - want).max() <= 1e-5 * np.abs(want).max()


def test_stencil5_multistep_vmem_refusal():
    # the 8-row block floor must not overshoot the VMEM budget once ghost
    # rows are added: _plan refuses and supports() reports it
    from distributedarrays_tpu.ops.pallas_stencil import supports
    assert supports(8192, 8192, np.float32)            # single-step fine
    assert not supports(1024, 65536, np.float32, k=8)  # 6 MiB buffers
    assert supports(1024, 65536, np.float32, k=0)      # streaming still ok


def test_stencil5_multistep_validation(rng):
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_multistep
    A = jnp.zeros((16, 32), jnp.float32)
    z = jnp.zeros((2, 32), jnp.float32)
    with pytest.raises(ValueError, match="halo slabs"):
        stencil5_multistep(A, z[:1], z, 2, True, True)
    with pytest.raises(ValueError, match="k must be"):
        stencil5_multistep(A, z, z, 0, True, True)
    d = dat.dzeros((64, 32), procs=range(8), dist=(8, 1))
    with pytest.raises(ValueError, match="temporal"):
        stencil.stencil5(d, iters=64, use_pallas=True, temporal=32)


def test_pallas_matmul_auto_block_fits():
    # the auto default must keep accepting shapes the old 256^3 default
    # took (e.g. 1536: divisible by 256, not by 1024/512-tile clipping)
    from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
    a = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((1536, 1536)).astype(np.float32))
    got = np.asarray(pallas_matmul(a, a))
    want = np.asarray(a) @ np.asarray(a)
    assert np.allclose(got, want, rtol=1e-4, atol=1e-3)


def test_stencil_layout_requirements():
    d = dat.dzeros((50, 8), procs=range(4), dist=(4, 1))  # 50 % 4 != 0
    with pytest.raises(ValueError, match="row-sharded"):
        stencil.stencil5(d)
    d2 = dat.dzeros((16, 16), procs=range(4), dist=(2, 2))
    with pytest.raises(ValueError, match="row-sharded"):
        stencil.stencil5(d2)


def _life_oracle(A, iters=1):
    for _ in range(iters):
        xp = np.pad(A, 1)
        neigh = sum(np.roll(np.roll(xp, i, 0), j, 1)[1:-1, 1:-1]
                    for i in (-1, 0, 1) for j in (-1, 0, 1)
                    if not (i == 0 and j == 0))
        A = (((A == 0) & (neigh == 3)) |
             ((A == 1) & ((neigh == 2) | (neigh == 3)))).astype(A.dtype)
    return A


def test_life_matches_oracle(rng):
    A = (rng.random((32, 24)) < 0.4).astype(np.int32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.life(d, iters=4))
    assert np.array_equal(got, _life_oracle(A, 4))


def test_life_glider_translates():
    # a glider moves one cell diagonally every 4 generations — an exact
    # long-horizon integration check across chunk boundaries
    A = np.zeros((40, 40), np.int32)
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.int32)
    A[1:4, 1:4] = glider
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(stencil.life(d, iters=4))
    want = np.zeros_like(A)
    want[2:5, 2:5] = glider
    assert np.array_equal(got, want)


def test_life2d_matches_oracle(rng):
    # fully 2-D-sharded grid (4x2 mesh): corner exchange must be exact
    A = (rng.random((32, 24)) < 0.4).astype(np.int32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    got = np.asarray(stencil.life2d(d, iters=5))
    assert np.array_equal(got, _life_oracle(A, 5))


def test_life2d_glider_crosses_corner():
    # glider path crosses both a row and a column chunk boundary
    A = np.zeros((32, 32), np.int32)
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.int32)
    A[11:14, 11:14] = glider     # starts near the (16,16) corner
    d = dat.distribute(A, procs=range(4), dist=(2, 2))
    got = np.asarray(stencil.life2d(d, iters=20))
    want = np.zeros_like(A)
    want[16:19, 16:19] = glider  # 5 diagonal moves
    assert np.array_equal(got, want)


def test_mlp_train_step_loss_decreases():
    mesh = mlp.make_mesh(8)
    sizes = [32, 64, 16]
    params = mlp.shard_params(mlp.init_params(jax.random.key(0), sizes), mesh)
    x = jax.random.normal(jax.random.key(1), (32, 32), jnp.bfloat16)
    y = jax.random.normal(jax.random.key(2), (32, 16), jnp.bfloat16)
    x, y = mlp.shard_batch(x, y, mesh)
    losses = []
    for _ in range(20):
        params, loss = mlp.train_step(params, x, y, lr=1e-2)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]


def test_kmeans_recovers_clusters(rng):
    from distributedarrays_tpu.models import kmeans
    centers = np.array([[-5, -5], [5, 5], [5, -5]], np.float32)
    pts = np.concatenate([
        c + 0.3 * rng.standard_normal((64, 2)).astype(np.float32)
        for c in centers])
    rng.shuffle(pts)
    d = dat.distribute(pts)
    C, shifts = kmeans.kmeans(d, 3, iters=15)
    C = np.asarray(C)
    # each true center has a recovered centroid within 0.5
    for c in centers:
        assert np.min(np.linalg.norm(C - c, axis=1)) < 0.5
    assert shifts[-1] < 1e-3          # converged
    labels = np.asarray(kmeans.assign(d, C))
    assert labels.shape == (192,)
    assert len(np.unique(labels)) == 3


def test_kmeans_validation():
    from distributedarrays_tpu.models import kmeans
    with pytest.raises(ValueError):
        kmeans.kmeans(dat.dzeros((8,)), 2)
    with pytest.raises(ValueError):
        kmeans.kmeans(dat.dzeros((4, 2)), 10)


def test_montecarlo_pi():
    from distributedarrays_tpu.models import montecarlo
    est = montecarlo.pi_estimate(200_000, seed=0)
    assert abs(est - np.pi) < 0.02


def _abs_fn(x):
    return jnp.abs(x)


def test_montecarlo_expectation():
    from distributedarrays_tpu.models import montecarlo
    est, se = montecarlo.expectation(_abs_fn, 200_000)
    # E|N(0,1)| = sqrt(2/pi)
    assert abs(est - np.sqrt(2 / np.pi)) < 5 * se + 1e-3


def test_similar_and_deepcopy(rng):
    import copy as pycopy
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    s = d.similar()
    assert s.cuts == d.cuts and s.dtype == d.dtype
    assert float(dat.dsum(s)) == 0.0
    s2 = d.similar(dtype=jnp.int32, dims=(8, 8))
    assert s2.dims == (8, 8) and s2.dtype == jnp.int32
    dc = pycopy.deepcopy(d)
    d.fill_(0.0)
    assert np.array_equal(np.asarray(dc), A)


@pytest.mark.slow
def test_graft_entry_points():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 128, 256)    # (batch, seq, vocab) logits
    g.dryrun_multichip(8)
