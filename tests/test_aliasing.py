"""Buffer-ownership regression tests: two DArrays must never share one
jax buffer, because ``close()`` calls ``jax.Array.delete()`` which would
invalidate the other handle.  The reference always copies on these paths
(copyto! darray.jl:679-687, distribute darray.jl:544-555, deepcopy
darray.jl:689-697); JAX's no-op conversions (``device_put`` with the
current sharding, ``astype`` with the current dtype) return the *same
object*, so every construction path must force a fresh buffer when the
source is still owned by someone else.
"""

import numpy as np
import pytest

import distributedarrays_tpu as dat


def _usable(d):
    """The array's buffers are alive and readable."""
    return (not d.garray.is_deleted()) and np.isfinite(np.asarray(d)).all()


def test_copyto_same_dtype_does_not_alias(rng):
    src = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    dest = dat.dzeros((16, 8), dtype=np.float32)
    dat.copyto_(dest, src)
    ref = np.asarray(src).copy()
    dest.close()
    # src must survive dest's close
    np.testing.assert_array_equal(np.asarray(src), ref)
    src.close()


def test_copyto_then_close_src(rng):
    src = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    dest = dat.dzeros((16, 8), dtype=np.float32)
    dat.copyto_(dest, src)
    ref = np.asarray(src).copy()
    src.close()
    np.testing.assert_array_equal(np.asarray(dest), ref)
    dest.close()


def test_distribute_of_darray_does_not_alias(rng):
    d = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    d2 = dat.distribute(d)  # same default layout -> device_put would no-op
    ref = np.asarray(d).copy()
    d2.close()
    np.testing.assert_array_equal(np.asarray(d), ref)
    d.close()


def test_distribute_of_jax_array_does_not_alias(rng):
    d = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    g = d.garray
    d2 = dat.distribute(g)
    d2.close()
    # the raw jax.Array the user passed must stay alive
    assert not g.is_deleted()
    d.close()


def test_astype_same_dtype_does_not_alias(rng):
    d = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    d2 = d.astype(np.float32)
    d2.close()
    assert _usable(d)
    d.close()


def test_samedist_already_matching_does_not_alias(rng):
    a = dat.distribute(rng.standard_normal((16, 8)).astype(np.float32))
    b = dat.dzeros((16, 8), dtype=np.float32)
    c = dat.samedist(a, b)  # a already has b's layout
    c.close()
    assert _usable(a)
    dat.d_closeall()
