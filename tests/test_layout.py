"""Layout math tests (reference semantics: /root/reference/src/darray.jl:249-318,
regression values from test/darray.jl:61-67 shifted to 0-based)."""

import numpy as np
import pytest

from distributedarrays_tpu import layout as L


def test_prime_factors():
    assert L.prime_factors(1) == []
    assert L.prime_factors(8) == [2, 2, 2]
    assert L.prime_factors(12) == [2, 2, 3]
    assert L.prime_factors(13) == [13]


def test_defaultdist_1d_even():
    assert L.defaultdist_1d(100, 4) == [0, 25, 50, 75, 100]


def test_defaultdist_1d_uneven_leading_remainder():
    # reference: defaultdist(50, 4) == [1,14,27,39,51]  (test/darray.jl:66)
    assert L.defaultdist_1d(50, 4) == [0, 13, 26, 38, 50]


def test_defaultdist_1d_more_chunks_than_elements():
    # reference darray.jl:290-295: leading singleton chunks, trailing empty
    assert L.defaultdist_1d(3, 5) == [0, 1, 2, 3, 3, 3]


def test_defaultdist_nd_factor_assignment():
    # 8 ranks over a square matrix: largest factors to largest dims
    chunks = L.defaultdist((100, 100), list(range(8)))
    assert int(np.prod(chunks)) == 8
    # 1-D vector: all chunks on the single dim
    assert L.defaultdist((1000,), list(range(8))) == [8]
    # skinny matrix: chunking should favor the long dim
    chunks = L.defaultdist((10000, 4), list(range(8)))
    assert chunks[0] >= chunks[1]


def test_defaultdist_drops_unplaceable_factors():
    # dims too small to absorb all factors → fewer ranks used, never
    # over-chunked past the array extent
    chunks = L.defaultdist((2,), list(range(8)))
    assert chunks[0] <= 2


def test_chunk_idxs_grid():
    idxs, cuts = L.chunk_idxs((50, 8), (4, 2))
    assert cuts[0] == [0, 13, 26, 38, 50]
    assert cuts[1] == [0, 4, 8]
    assert idxs.shape == (4, 2)
    assert idxs[0, 0] == (range(0, 13), range(0, 4))
    assert idxs[3, 1] == (range(38, 50), range(4, 8))
    # chunks tile the array exactly
    total = sum(len(t[0]) * len(t[1]) for t in idxs.flat)
    assert total == 50 * 8


def test_locate():
    _, cuts = L.chunk_idxs((50, 8), (4, 2))
    assert L.locate(cuts, 0, 0) == (0, 0)
    assert L.locate(cuts, 12, 3) == (0, 0)
    assert L.locate(cuts, 13, 4) == (1, 1)
    assert L.locate(cuts, 49, 7) == (3, 1)
    with pytest.raises(IndexError):
        L.locate(cuts, 50, 0)


def test_locate_skips_empty_chunks():
    cuts = [L.defaultdist_1d(3, 5)]
    assert L.locate(cuts, 2) == (2,)


def test_mesh_cache_and_sharding():
    m1 = L.mesh_for(range(8), (4, 2))
    m2 = L.mesh_for(range(8), (4, 2))
    assert m1 is m2
    sh = L.sharding_for(range(8), (4, 2))
    assert sh.mesh.shape == {"d0": 4, "d1": 2}
    # single-chunk dims are unsharded in the spec
    sh2 = L.sharding_for(range(4), (4, 1))
    assert sh2.spec == ("d0", None) or tuple(sh2.spec) == ("d0", None)


def test_mesh_for_too_few_ranks():
    with pytest.raises(ValueError):
        L.mesh_for(range(4), (4, 2))


def test_mesh_for_rank_ids_beyond_devices():
    # rank ids past the visible device count must raise the same
    # ValueError family as the count check, not a raw numpy IndexError
    with pytest.raises(ValueError, match="out of range"):
        L.mesh_for(range(64), (8, 8))
    with pytest.raises(ValueError, match="out of range"):
        L.mesh_for([0, -3], (2,))
