"""Fault-tolerant trainer suite: deterministic training, checkpoint
resume, straggler detection, per-step deadlines, and the chaos soak
acceptance — a seeded device kill mid-epoch PLUS one corrupted
checkpoint shard, after which the run must complete on survivors with a
post-resume loss trajectory bit-identical to a fault-free run restarted
from the same verified step.
"""

import os
import shutil

import numpy as np
import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu import telemetry as tm
from distributedarrays_tpu.resilience import elastic, faults, recovery
from distributedarrays_tpu.telemetry import flight
from distributedarrays_tpu.telemetry import memory as tmem
from distributedarrays_tpu.train import (DeadRankError, StragglerDetector,
                                         Trainer, adam, mlp_task, sgd,
                                         transformer_task)
from distributedarrays_tpu.utils.checkpoint import CheckpointManager


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Fault injection disarmed, elastic manager pristine, flight
    recorder reset around every test (process-wide singletons)."""
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    yield
    faults.clear()
    elastic.manager().reset()
    flight._reset()


def _fast_policy(**kw):
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    return recovery.RetryPolicy(**kw)


def _trainer(tmp_path=None, task=None, save_every=2, **kw):
    kw.setdefault("policy", _fast_policy())
    kw.setdefault("seed", 0)
    return Trainer(task or mlp_task(batch_size=56),
                   ckpt_dir=None if tmp_path is None else tmp_path,
                   save_every=save_every, **kw)


# ---------------------------------------------------------------------------
# plain training: determinism, optimizers, tasks
# ---------------------------------------------------------------------------


def test_fit_decreases_loss_and_drains():
    with _trainer() as t:
        res = t.fit(6)
    assert len(res["losses"]) == 6
    assert res["losses"][-1] < res["losses"][0]
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 0


def test_fit_is_deterministic_across_runs():
    with _trainer() as a:
        ra = a.fit(5)
    with _trainer() as b:
        rb = b.fit(5)
    assert ra["losses"] == rb["losses"]       # bitwise float equality


def test_sgd_and_momentum_and_adam_all_train():
    for opt in (sgd(lr=5e-2), sgd(lr=5e-2, momentum=0.9), adam(lr=1e-2)):
        with _trainer(optimizer=opt) as t:
            res = t.fit(5)
        assert res["losses"][-1] < res["losses"][0], opt


def test_transformer_task_trains():
    task = transformer_task(vocab=32, dim=16, heads=2, layers=1, seq=8,
                            batch_size=16)
    with _trainer(task=task, optimizer=adam(lr=3e-3)) as t:
        res = t.fit(4)
    assert res["losses"][-1] < res["losses"][0]


def test_uneven_batch_and_params_pad_cleanly():
    # batch 30 over 4 ranks pads to 32 with weight-0 rows, and the
    # 66-element flat parameter vector pads to 68 — neither padding may
    # change the math vs the unpadded single-rank run of the same task
    task = mlp_task(sizes=(5, 7, 3), batch_size=30)
    with _trainer(task=task, ranks=[0, 1, 2, 3]) as t4, \
            _trainer(task=task, ranks=[0]) as t1:
        l4 = t4.fit(3)["losses"]
        l1 = t1.fit(3)["losses"]
    np.testing.assert_allclose(l4, l1, rtol=1e-5)


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    with _trainer(tmp_path / "a", save_every=2) as t:
        full = t.fit(6)["losses"]
    # run 4 steps, reopen, run to 6: the tail must match bitwise
    with _trainer(tmp_path / "b", save_every=2) as t1:
        t1.fit(4)
    with _trainer(tmp_path / "b", save_every=2) as t2:
        res = t2.fit(6)
    assert res["start"] == 4
    assert res["losses"] == full[4:]


def test_resume_with_different_optimizer_is_safe(tmp_path):
    # sgd checkpoint, adam resume: the moments are MISSING — a clear
    # error naming the optimizer mismatch, restored DArrays closed
    with _trainer(tmp_path / "s", optimizer=sgd(lr=1e-2)) as t:
        t.fit(2)
    t2 = _trainer(tmp_path / "s", optimizer=adam(lr=1e-2))
    with pytest.raises(ValueError, match="different optimizer"):
        t2.fit(4)
    t2.close()
    assert dat.live_ids() == []
    # adam checkpoint, sgd resume: surplus moments are discarded
    # (closed, not leaked) and the params-only resume proceeds
    with _trainer(tmp_path / "a", optimizer=adam(lr=1e-2)) as t3:
        t3.fit(2)
    with _trainer(tmp_path / "a", optimizer=sgd(lr=1e-2)) as t4:
        res = t4.fit(4)
    assert res["start"] == 2 and len(res["losses"]) == 2
    assert dat.live_ids() == []


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------


def test_straggler_detector_budget_math():
    det = StragglerDetector(factor=2.0, min_budget_s=0.1, warmup=3)
    assert det.budget() is None               # warmup: no budget yet
    assert det.observe(5.0) is False          # un-judged during warmup
    for _ in range(3):
        det.observe(0.01)
    b = det.budget()
    assert b == pytest.approx(2.0 * 5.0)      # p99 == the max of window
    assert det.observe(b + 1.0) is True
    assert det.observe(0.01) is False


def test_straggler_probe_confirms_dead_rank_and_recovers(tmp_path):
    # a hang spec with an explicit device: the step completes slowly AND
    # the device joins the simulated-down set — the straggler budget
    # trips, the probe confirms the death, and recovery restores +
    # shrinks + recomputes deterministically
    s0 = tm.counter_value("train.stragglers")
    r0 = tm.counter_value("recovery.retries", verdict="device_loss")
    faults.configure(plan=[
        {"site": "train.step", "match": {"step": 6}, "action": "hang",
         "hang_s": 0.6, "at": 1, "count": 1, "device": 2}], seed=7)
    det = StragglerDetector(factor=3.0, min_budget_s=0.3, warmup=3)
    with _trainer(tmp_path, straggler=det) as t:
        res = t.fit(8)
    assert tm.counter_value("train.stragglers") == s0 + 1
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") == r0 + 1
    assert 2 not in elastic.manager().live_ranks()
    assert len(res["losses"]) == 8
    assert dat.live_ids() == []


def test_closed_trainer_refuses_fit_and_step_once():
    t = _trainer()
    t.fit(1)
    t.close()
    with pytest.raises(RuntimeError, match="closed"):
        t.fit(2)
    with pytest.raises(RuntimeError, match="closed"):
        t.step_once()
    assert dat.live_ids() == []               # close() freed everything


def test_pinned_ranks_all_dead_raises_not_migrates():
    # the pin is a hard boundary: if every pinned rank is down, the
    # trainer must fail, not silently migrate onto excluded devices
    with _trainer(ranks=[2, 3]) as t:
        elastic.manager().mark_down(2)
        elastic.manager().mark_down(3)
        with pytest.raises(RuntimeError, match="no pinned rank"):
            t.fit(1)


def test_dead_rank_error_classifies_device_loss():
    e = DeadRankError([3], budget_s=0.5, dur_s=2.0)
    assert recovery.classify(e) == "device_loss"
    assert "device lost" in str(e) and "[3]" in str(e)


# ---------------------------------------------------------------------------
# per-step wall-clock deadline (RetryPolicy.max_elapsed_s)
# ---------------------------------------------------------------------------


def test_max_elapsed_s_stops_retrying():
    calls = []

    def boom():
        calls.append(1)
        import time
        time.sleep(0.05)
        raise ValueError("flaky")

    g0 = tm.counter_value("recovery.deadline_exceeded",
                          verdict="transient")
    with pytest.raises(ValueError):
        recovery.run_with_recovery(
            boom, policy=recovery.RetryPolicy(
                max_retries=100, base_delay=0.001, max_delay=0.002,
                max_elapsed_s=0.15))
    # the retry count alone allowed 100 retries; the wall-clock budget
    # cut it off after a handful
    assert 1 < len(calls) < 20
    assert tm.counter_value("recovery.deadline_exceeded",
                            verdict="transient") == g0 + 1


def test_backoff_never_sleeps_past_remaining_budget():
    import time
    attempts = []

    def boom():
        attempts.append(time.monotonic())
        raise ValueError("flaky")

    t0 = time.monotonic()
    with pytest.raises(ValueError):
        recovery.run_with_recovery(
            boom, policy=recovery.RetryPolicy(
                max_retries=50, base_delay=10.0, max_delay=10.0,
                jitter=0.0, max_elapsed_s=0.2))
    # base_delay=10s would sleep 10s on the first retry; the budget
    # clamps it, so the whole loop ends within ~the budget
    assert time.monotonic() - t0 < 2.0
    assert len(attempts) >= 2                 # it DID retry (clamped sleep)


def test_delay_clamps_to_remaining():
    pol = recovery.RetryPolicy(base_delay=10.0, max_delay=10.0,
                               jitter=0.0)
    assert pol.delay(0, remaining_s=0.25) == pytest.approx(0.25)
    assert pol.delay(0, remaining_s=-1.0) == 0.0
    assert pol.delay(0, remaining_s=None) == pytest.approx(10.0)


def test_trainer_step_deadline_bounds_recovery(tmp_path):
    # an always-raising grad.sync makes the step unrecoverable; the
    # per-step deadline must cut the retry loop off
    faults.configure(plan=[
        {"site": "grad.sync", "action": "raise", "at": 1, "count": -1}],
        seed=3)
    with _trainer(tmp_path, step_deadline_s=0.5,
                  policy=_fast_policy(max_retries=10_000)) as t:
        import time
        t0 = time.monotonic()
        with pytest.raises(faults.InjectedFault):
            t.fit(2)
        assert time.monotonic() - t0 < 30.0   # not 10k retries


# ---------------------------------------------------------------------------
# checkpoint integrity: corrupt action, CRC verification, quarantine
# ---------------------------------------------------------------------------


def test_corrupt_restore_quarantines_and_falls_back(tmp_path):
    A = np.arange(64, dtype=np.float32).reshape(8, 8)
    mgr = CheckpointManager(tmp_path, async_save=False)
    d = dat.distribute(A.copy())
    mgr.save(0, {"x": d, "tag": "old"})
    mgr.save(1, {"x": d, "tag": "new"})
    d.close()
    q0 = tm.counter_value("checkpoint.quarantines")
    f0 = tm.counter_value("checkpoint.restore_fallbacks")
    faults.configure(plan=[
        {"site": "checkpoint.read", "action": "corrupt", "at": 1,
         "count": 1}], seed=11)
    out = mgr.restore()
    assert out["tag"] == "old"                # fell back past step 1
    np.testing.assert_array_equal(np.asarray(out["x"]), A)
    out["x"].close()
    assert tm.counter_value("checkpoint.quarantines") == q0 + 1
    assert tm.counter_value("checkpoint.restore_fallbacks") == f0 + 1
    assert mgr.steps() == [0]                 # step 1 no longer restorable
    assert (tmp_path / ".quarantine_step_00000001").exists()
    mgr.close()


def test_corrupt_byte_flips_are_seeded_deterministic(tmp_path):
    def corrupted_bytes(seed):
        faults.configure(plan=[
            {"site": "checkpoint.read", "action": "corrupt", "at": 1,
             "count": 1, "flips": 4}], seed=seed)
        spec = faults.decide("checkpoint.read", store="npz", path="x")
        arrays = {"a0": np.zeros(64, np.uint8), "a1": np.zeros(8, np.uint8)}
        out = faults.corrupt_arrays(spec, arrays)
        assert any((out[k] != arrays[k]).any() for k in arrays)
        return {k: out[k].tobytes() for k in out}

    assert corrupted_bytes(5) == corrupted_bytes(5)
    assert corrupted_bytes(5) != corrupted_bytes(6)


def test_explicit_step_restore_stays_strict_on_corruption(tmp_path):
    from distributedarrays_tpu.utils.checkpoint import \
        CheckpointIntegrityError
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(3, {"v": np.arange(6)})
    faults.configure(plan=[
        {"site": "checkpoint.read", "action": "corrupt", "at": 1,
         "count": 1}], seed=2)
    with pytest.raises(CheckpointIntegrityError):
        mgr.restore(3)
    mgr.close()


def test_on_disk_corruption_detected_without_fault_harness(tmp_path):
    # real disk rot: flip one byte INSIDE the npz payload (past the zip
    # local header + npy header, well before the central directory) —
    # no fault plan armed, the CRC alone must catch it
    from distributedarrays_tpu.utils.checkpoint import \
        CheckpointIntegrityError, load, save
    save(tmp_path / "c", {"v": np.arange(100, dtype=np.int64)})
    npz = tmp_path / "c" / "arrays.npz"
    raw = bytearray(npz.read_bytes())
    raw[400] ^= 0xFF
    npz.write_bytes(bytes(raw))
    # the zip container's own member CRC may fire first (BadZipFile);
    # either way the restore MUST fail — and a CheckpointManager treats
    # both identically (restore_fallback).  Our CRC layer is the one
    # that still fires for the seeded read-corruption path and for
    # stores without container checksums.
    import zipfile
    with pytest.raises((CheckpointIntegrityError, zipfile.BadZipFile,
                        OSError)):
        load(tmp_path / "c")


def test_pre_integrity_checkpoints_still_load(tmp_path):
    # a checkpoint whose metadata has no integrity section (older
    # writer) restores unverified rather than failing
    import json
    from distributedarrays_tpu.utils.checkpoint import load, save
    save(tmp_path / "c", {"v": np.arange(4)})
    meta = json.loads((tmp_path / "c" / "dartpu_meta.json").read_text())
    del meta["integrity"]
    (tmp_path / "c" / "dartpu_meta.json").write_text(json.dumps(meta))
    out = load(tmp_path / "c")
    np.testing.assert_array_equal(out["v"], np.arange(4))


def test_all_corrupt_store_surfaces_through_recovery(tmp_path):
    # every published step corrupt: restore() quarantines them all and
    # raises — and recovery must SURFACE that (the cause-chained
    # FileNotFoundError), never silently degrade to a live-state retry
    # just because quarantine emptied steps()
    mgr = CheckpointManager(tmp_path, async_save=False, max_to_keep=None)
    mgr.save(1, {"v": np.arange(8)})
    mgr.save(2, {"v": np.arange(8)})
    faults.configure(plan=[
        {"site": "checkpoint.read", "action": "corrupt", "at": 1,
         "count": -1}], seed=4)

    def boom():
        raise ValueError("flaky")

    with pytest.raises(FileNotFoundError, match="no restorable"):
        recovery.run_with_recovery(
            boom, policy=_fast_policy(), checkpoints=mgr,
            restore_fn=lambda tree: None)
    assert mgr.steps() == []                  # all quarantined
    mgr.close()


def test_discard_from_rewinds_timeline(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False, max_to_keep=None)
    for s in (2, 4, 6):
        mgr.save(s, {"s": s})
    assert mgr.discard_from(4) == [4, 6]
    assert mgr.steps() == [2]
    mgr.close()


# ---------------------------------------------------------------------------
# fault sites
# ---------------------------------------------------------------------------


def test_train_step_fault_site_device_loss_recovers(tmp_path):
    r0 = tm.counter_value("recovery.retries", verdict="device_loss")
    faults.configure(plan=[
        {"site": "train.step", "match": {"step": 3}, "action":
         "device_loss", "at": 1, "count": 1, "device": 1}], seed=5)
    with _trainer(tmp_path) as t:
        res = t.fit(5)
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") == r0 + 1
    assert 1 not in elastic.manager().live_ranks()
    assert len(res["losses"]) == 5


def test_grad_sync_fault_site_fires_between_programs(tmp_path):
    hist0 = len(faults.history())
    faults.configure(plan=[
        {"site": "grad.sync", "match": {"step": 1}, "action": "raise",
         "at": 1, "count": 1}], seed=5)
    with _trainer(tmp_path) as t:
        t.fit(3)
    fired = faults.history()[hist0:]
    assert any(f["site"] == "grad.sync" for f in fired)


def test_corrupt_action_is_noop_at_unconsuming_sites():
    faults.configure(plan=[
        {"site": "reshard.chunk", "action": "corrupt", "at": 1,
         "count": 1}], seed=1)
    faults.check("reshard.chunk", strategy="x")   # must not raise


# ---------------------------------------------------------------------------
# the chaos soak acceptance
# ---------------------------------------------------------------------------


def _soak(tmp_path, plan, seed, **kw):
    faults.clear()
    elastic.manager().reset()
    if plan is not None:
        faults.configure(plan=plan, seed=seed)
    t = _trainer(tmp_path, save_every=2, **kw)
    try:
        return t.fit(8), elastic.manager().live_ranks()
    finally:
        t.close()


@pytest.mark.slow
def test_chaos_soak_device_kill_plus_corrupt_shard(tmp_path):
    """The acceptance soak: a seeded plan kills device 3 mid-epoch at
    step 5 AND corrupts the latest checkpoint shard on the recovery
    read.  The run must complete on the 7 survivors, the corrupt step
    must quarantine + fall back (restore_fallback journaled), and the
    post-resume loss trajectory must be bit-identical to a fault-free
    run restarted from the same verified step on the same survivors."""
    plan = [
        {"site": "train.step", "match": {"step": 5},
         "action": "device_loss", "at": 1, "count": 1, "device": 3},
        {"site": "checkpoint.read", "action": "corrupt", "at": 1,
         "count": 1},
    ]
    b0 = flight.crash_bundle_count()
    r0 = tm.counter_value("recovery.retries", verdict="device_loss")
    k0 = tm.counter_value("elastic.shrinks")
    q0 = tm.counter_value("checkpoint.quarantines")
    f0 = tm.counter_value("checkpoint.restore_fallbacks")

    res, survivors = _soak(tmp_path / "chaos", plan, seed=42)

    # completed on survivors: the dead device is out of the live set
    assert survivors == [0, 1, 2, 4, 5, 6, 7]
    assert len(res["losses"]) == 8
    # exactly the expected flight bundles: ONE, for the one device loss
    assert flight.crash_bundle_count() - b0 == 1
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") == r0 + 1
    assert tm.counter_value("elastic.shrinks") == k0 + 1
    # the corrupt shard quarantined and fell back without operator input
    assert tm.counter_value("checkpoint.quarantines") == q0 + 1
    assert tm.counter_value("checkpoint.restore_fallbacks") == f0 + 1
    assert (tmp_path / "chaos" / ".quarantine_step_00000004").exists()

    # comparison: a fault-free run restarted from the same verified step
    # (2 — step 4 was the corrupted one) on the same survivor set
    faults.clear()
    src, dst = tmp_path / "chaos", tmp_path / "clean"
    shutil.copytree(src, dst,
                    ignore=shutil.ignore_patterns(".quarantine*"))
    for p in sorted(os.listdir(dst)):
        if p.startswith("step_") and int(p[5:]) > 2:
            shutil.rmtree(dst / p)
    with _trainer(dst, save_every=1000, ranks=survivors) as t2:
        res2 = t2.fit(8)
    assert res2["start"] == 2
    # bit-identical loss trajectory from the resume point
    assert res2["losses"] == res["losses"][2:]

    # leak gate: registry and HBM ledger drain (conftest re-asserts)
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 0


@pytest.mark.slow
def test_chaos_soak_replay_is_deterministic(tmp_path):
    plan = [
        {"site": "train.step", "match": {"step": 5},
         "action": "device_loss", "at": 1, "count": 1, "device": 3},
        {"site": "checkpoint.read", "action": "corrupt", "at": 1,
         "count": 1},
    ]
    def _normalized_history():
        # the checkpoint.read site labels carry the (tmp) path — equal
        # up to the run directory, so strip it before comparing
        out = []
        for f in faults.history():
            f = dict(f, labels={k: v for k, v in f["labels"].items()
                                if k != "path"})
            out.append(f)
        return out

    res1, _ = _soak(tmp_path / "a", plan, seed=42)
    h1 = _normalized_history()
    res2, _ = _soak(tmp_path / "b", plan, seed=42)
    h2 = _normalized_history()
    assert res1["losses"] == res2["losses"]
    assert h1 == h2


# ---------------------------------------------------------------------------
# telemetry integration
# ---------------------------------------------------------------------------


def test_train_step_spans_are_stamped_and_doctor_sees_them():
    from distributedarrays_tpu.telemetry import perf
    ev0 = len(tm.events())
    with _trainer() as t:
        t.fit(3)
    events = tm.events()[ev0:]
    steps = [e for e in events
             if e.get("cat") == "span" and e.get("name") == "train.step"]
    assert len(steps) == 3
    for e in steps:
        labels = e.get("labels") or {}
        assert float(labels.get("bytes_ici", 0)) > 0    # stamped
        assert float(labels.get("flops", 0)) > 0
        assert labels.get("dispatch") in ("rdma", "xla")
    per_step = perf.train_step_overlap(events)
    assert [o["step"] for o in per_step] == [0, 1, 2]
    for o in per_step:
        assert o["comm_s"] > 0                          # sync measured
        assert 0.0 <= o["overlap_frac"] <= 1.0
