"""Ring attention tests: exactness vs a dense O(seq²) oracle, causal and
full, across all 8 sequence shards (the long-context deliverable of
SURVEY.md §5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.parallel.collectives import shard_map_compat
from distributedarrays_tpu.models import ring_attention as RA


@pytest.fixture
def qkv(rng):
    S, H, D = 64, 4, 16
    mk = lambda: rng.standard_normal((S, H, D)).astype(np.float32)
    q, k, v = mk(), mk(), mk()
    dist = (8, 1, 1)
    return (q, k, v,
            dat.distribute(q, procs=range(8), dist=dist),
            dat.distribute(k, procs=range(8), dist=dist),
            dat.distribute(v, procs=range(8), dist=dist))


def test_full_attention_exact(qkv):
    q, k, v, dq, dk, dv = qkv
    got = np.asarray(RA.ring_attention(dq, dk, dv))
    want = RA.reference_attention(q, k, v)
    assert got.shape == want.shape
    assert np.abs(got - want).max() < 1e-5


def test_causal_attention_exact(qkv):
    q, k, v, dq, dk, dv = qkv
    got = np.asarray(RA.ring_attention(dq, dk, dv, causal=True))
    want = RA.reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5
    # the first row attends only to itself
    sm = v[0] / 1.0
    assert np.allclose(got[0], sm, rtol=1e-5)


def test_result_stays_sequence_sharded(qkv):
    *_, dq, dk, dv = qkv
    out = RA.ring_attention(dq, dk, dv)
    assert out.pids.shape == (8, 1, 1)
    assert out.cuts[0] == dq.cuts[0]


@pytest.fixture
def qkv8(rng):
    # 8 heads so both ring and ulysses (heads % ranks == 0) apply
    S, H, D = 64, 8, 16
    mk = lambda: rng.standard_normal((S, H, D)).astype(np.float32)
    q, k, v = mk(), mk(), mk()
    dist = (8, 1, 1)
    return (q, k, v,
            dat.distribute(q, procs=range(8), dist=dist),
            dat.distribute(k, procs=range(8), dist=dist),
            dat.distribute(v, procs=range(8), dist=dist))


def test_ulysses_matches_dense(qkv8):
    from distributedarrays_tpu.models.ulysses import ulysses_attention
    q, k, v, dq, dk, dv = qkv8
    for causal in (False, True):
        for use_flash in (True, False):   # pallas per-rank kernel + fallback
            got = np.asarray(ulysses_attention(dq, dk, dv, causal=causal,
                                               use_flash=use_flash))
            want = RA.reference_attention(q, k, v, causal=causal)
            assert np.abs(got - want).max() < 1e-5, (causal, use_flash)


def test_ulysses_agrees_with_ring(qkv8):
    from distributedarrays_tpu.models.ulysses import ulysses_attention
    _, _, _, dq, dk, dv = qkv8
    a = np.asarray(RA.ring_attention(dq, dk, dv, causal=True))
    b = np.asarray(ulysses_attention(dq, dk, dv, causal=True))
    assert np.abs(a - b).max() < 1e-5


def test_ulysses_head_divisibility():
    from distributedarrays_tpu.models.ulysses import ulysses_attention
    bad = dat.dzeros((64, 6, 16), procs=range(8), dist=(8, 1, 1))
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(bad, bad, bad)


def test_ring_attention_differentiable(qkv):
    # sequence-parallel TRAINING works: grads through the ppermute ring
    # match the dense formulation
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.pallas_attention import _dense_attention_shd
    from distributedarrays_tpu.parallel.collectives import run_spmd

    q, k, v, *_ = qkv
    q, k, v = (jnp.asarray(x) for x in (q, k, v))
    mesh = L.mesh_for(range(8), (8, 1, 1))
    f = run_spmd(
        lambda a, b, c: RA.ring_attention_kernel(a, b, c, mesh.axis_names[0],
                                                 causal=True),
        mesh, in_specs=(P("d0", None, None),) * 3,
        out_specs=P("d0", None, None))
    g = jax.grad(lambda a, b, c: jnp.sum(f(a, b, c) ** 2), (0, 1, 2))(q, k, v)
    scale = float(1.0 / np.sqrt(q.shape[-1]))
    gd = jax.grad(lambda a, b, c: jnp.sum(
        _dense_attention_shd(a, b, c, True, scale) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(g, gd):
        assert float(jnp.abs(a - b).max()) < 1e-4


def test_shape_validation(qkv):
    _, _, _, dq, dk, _ = qkv
    with pytest.raises(ValueError, match="dims must match"):
        RA.ring_attention(dq, dk, dat.dzeros((64, 4, 8)))
    with pytest.raises(ValueError, match="must be"):
        RA.ring_attention(dat.dzeros((8, 8)), dat.dzeros((8, 8)),
                          dat.dzeros((8, 8)))


# ---------------------------------------------------------------------------
# fused (Pallas per-hop) ring attention — forward parity with the einsum
# ring and the dense oracle (VERDICT round-2 item 7)
# ---------------------------------------------------------------------------


def test_ring_flash_matches_dense(rng):
    from distributedarrays_tpu.models.ring_attention import (
        ring_flash_attention, reference_attention)
    S, H, D = 64, 2, 16
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    k = rng.standard_normal((S, H, D)).astype(np.float32)
    v = rng.standard_normal((S, H, D)).astype(np.float32)
    dq = dat.distribute(q, procs=range(8), dist=(8, 1, 1))
    dk = dat.distribute(k, procs=range(8), dist=(8, 1, 1))
    dv = dat.distribute(v, procs=range(8), dist=(8, 1, 1))
    out = ring_flash_attention(dq, dk, dv)
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=2e-5)
    dat.d_closeall()


def test_ring_flash_causal_matches_einsum_ring(rng):
    from distributedarrays_tpu.models.ring_attention import (
        ring_flash_attention, ring_attention, reference_attention)
    S, H, D = 64, 2, 16
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    k = rng.standard_normal((S, H, D)).astype(np.float32)
    v = rng.standard_normal((S, H, D)).astype(np.float32)
    dq = dat.distribute(q, procs=range(8), dist=(8, 1, 1))
    dk = dat.distribute(k, procs=range(8), dist=(8, 1, 1))
    dv = dat.distribute(v, procs=range(8), dist=(8, 1, 1))
    fused = np.asarray(ring_flash_attention(dq, dk, dv, causal=True))
    plain = np.asarray(ring_attention(dq, dk, dv, causal=True))
    dense = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(fused, dense, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(fused, plain, rtol=2e-4, atol=2e-5)
    dat.d_closeall()


def test_zigzag_order_roundtrip():
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_order, zigzag_shard, zigzag_unshard)
    order = zigzag_order(16, 4)
    # rank 0 holds chunks 0 and 7, rank 1 chunks 1 and 6, ...
    assert list(order[:4]) == [0, 1, 14, 15]
    assert list(order[4:8]) == [2, 3, 12, 13]
    x = np.arange(32.0).reshape(32, 1, 1)
    rt = np.asarray(zigzag_unshard(zigzag_shard(x, 8), 8))
    assert np.array_equal(rt, x)
    with pytest.raises(ValueError, match="divide"):
        zigzag_order(30, 4)


def test_zigzag_ring_causal_matches_dense(rng):
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_ring_attention, zigzag_shard, zigzag_unshard,
        reference_attention)
    S, H, D = 64, 2, 16
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    k = rng.standard_normal((S, H, D)).astype(np.float32)
    v = rng.standard_normal((S, H, D)).astype(np.float32)
    n = 8
    dq = dat.distribute(np.asarray(zigzag_shard(q, n)),
                        procs=range(n), dist=(n, 1, 1))
    dk = dat.distribute(np.asarray(zigzag_shard(k, n)),
                        procs=range(n), dist=(n, 1, 1))
    dv = dat.distribute(np.asarray(zigzag_shard(v, n)),
                        procs=range(n), dist=(n, 1, 1))
    zz = zigzag_ring_attention(dq, dk, dv)
    got = np.asarray(zigzag_unshard(np.asarray(zz), n))
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    dat.d_closeall()


@pytest.mark.slow
def test_zigzag_ring_differentiable(rng):
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_ring_attention_kernel, zigzag_shard, reference_attention)
    from jax.sharding import PartitionSpec as RP
    S, H, D, n = 32, 2, 8, 4
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    zq = jnp.asarray(zigzag_shard(q, n))
    mesh = L.mesh_for(list(range(n)), (n, 1, 1))
    ax = mesh.axis_names[0]
    shm = shard_map_compat(
        lambda a, b, c: zigzag_ring_attention_kernel(a, b, c, ax),
        mesh=mesh, in_specs=(RP(ax),) * 3, out_specs=RP(ax),
        check=False)

    def loss(x):
        return jnp.sum(shm(x, x, x).astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss))(zq)
    assert g.shape == zq.shape
    assert bool(jnp.all(jnp.isfinite(g)))
    # oracle gradient from the dense formulation on natural order
    def dense_loss(x):
        xs = zigzag_shard(x, n)
        return jnp.sum(shm(xs, xs, xs).astype(jnp.float32) ** 2)
    # same loss computed densely
    def dense_ref(x):
        qf = x / np.sqrt(D)
        s = jnp.einsum("qhd,khd->hqk", qf, x)
        qi = jnp.arange(S)[:, None]
        ki = jnp.arange(S)[None, :]
        s = jnp.where((ki <= qi)[None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hqk,khd->hqd", p, x)
        return jnp.sum(jnp.transpose(o, (1, 0, 2)) ** 2)
    gn = jax.grad(dense_loss)(jnp.asarray(q))
    gd = jax.grad(dense_ref)(jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(gn), np.asarray(gd),
                               rtol=5e-3, atol=5e-4)


def test_zigzag_ring_flash_matches_dense(rng):
    # fused (Pallas per-quadrant) zigzag forward vs the dense oracle —
    # interpret mode on the CPU mesh (ADVICE round-2 item 1)
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_ring_flash_attention, zigzag_shard, zigzag_unshard,
        reference_attention)
    S, H, D = 64, 2, 16
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    k = rng.standard_normal((S, H, D)).astype(np.float32)
    v = rng.standard_normal((S, H, D)).astype(np.float32)
    n = 8
    dq = dat.distribute(np.asarray(zigzag_shard(q, n)),
                        procs=range(n), dist=(n, 1, 1))
    dk = dat.distribute(np.asarray(zigzag_shard(k, n)),
                        procs=range(n), dist=(n, 1, 1))
    dv = dat.distribute(np.asarray(zigzag_shard(v, n)),
                        procs=range(n), dist=(n, 1, 1))
    zz = zigzag_ring_flash_attention(dq, dk, dv)
    got = np.asarray(zigzag_unshard(np.asarray(zz), n))
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    dat.d_closeall()


# ---------------------------------------------------------------------------
# differentiable fused ring attention (VERDICT round-3 item 3): gradients
# of the Pallas ring path vs the dense formulation, causal and full
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_differentiable(rng, causal):
    from jax.sharding import PartitionSpec as RP
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.pallas_attention import (
        _dense_attention_shd)

    S, H, D, n = 64, 2, 16, 8
    q = jnp.asarray(rng.standard_normal((S, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((S, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((S, H, D)).astype(np.float32))
    mesh = L.mesh_for(range(n), (n, 1, 1))
    ax = mesh.axis_names[0]
    shm = shard_map_compat(
        lambda a, b, c: RA.ring_flash_attention_kernel(a, b, c, ax,
                                                       causal=causal),
        mesh=mesh, in_specs=(RP(ax),) * 3, out_specs=RP(ax),
        check=False)
    g = jax.jit(jax.grad(lambda a, b, c: jnp.sum(shm(a, b, c) ** 2),
                         (0, 1, 2)))(q, k, v)
    scale = float(1.0 / np.sqrt(D))
    gd = jax.grad(lambda a, b, c: jnp.sum(
        _dense_attention_shd(a, b, c, causal, scale) ** 2), (0, 1, 2))(q, k, v)
    for got, want in zip(g, gd):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)


def test_zigzag_ring_flash_differentiable(rng):
    from jax.sharding import PartitionSpec as RP
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_ring_flash_attention_kernel, zigzag_shard)
    from distributedarrays_tpu.ops.pallas_attention import (
        _dense_attention_shd)

    S, H, D, n = 64, 2, 8, 4
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    k = rng.standard_normal((S, H, D)).astype(np.float32)
    v = rng.standard_normal((S, H, D)).astype(np.float32)
    mesh = L.mesh_for(list(range(n)), (n, 1, 1))
    ax = mesh.axis_names[0]
    shm = shard_map_compat(
        lambda a, b, c: zigzag_ring_flash_attention_kernel(a, b, c, ax),
        mesh=mesh, in_specs=(RP(ax),) * 3, out_specs=RP(ax),
        check=False)

    # loss over the fused zigzag path, differentiating through the
    # zigzag reorder so gradients land in NATURAL order for the oracle
    def loss(a, b, c):
        az, bz, cz = (zigzag_shard(x, n) for x in (a, b, c))
        return jnp.sum(shm(az, bz, cz).astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss, (0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    scale = float(1.0 / np.sqrt(D))
    gd = jax.grad(lambda a, b, c: jnp.sum(
        _dense_attention_shd(a, b, c, True, scale) ** 2), (0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for got, want in zip(g, gd):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)


def test_zigzag_validation(rng):
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_ring_attention)
    d = dat.dzeros((36, 2, 8), procs=range(4), dist=(4, 1, 1))
    with pytest.raises(ValueError, match="2\\*nranks"):
        zigzag_ring_attention(d, d, d)
    dat.d_closeall()


def test_ring_flash_blocks_from_registry(rng):
    # unspecified blocks consult the "ring_flash" registry entry;
    # malformed entries degrade to the 512 default — numerics identical
    import jax
    from jax.sharding import PartitionSpec as P
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.utils import autotune
    from distributedarrays_tpu.models.ring_attention import (
        ring_flash_attention_kernel, reference_attention)
    B, H, D = 128, 2, 16
    mesh = L.mesh_for([0], (1,))
    ax = mesh.axis_names[0]
    q = jnp.asarray(rng.standard_normal((B, H, D)).astype(np.float32))

    def run():
        shm = shard_map_compat(
            lambda a, b, c: ring_flash_attention_kernel(a, b, c, ax,
                                                        causal=True),
            mesh=mesh, in_specs=(P(ax),) * 3, out_specs=P(ax),
            check=False)
        return np.asarray(shm(q, q, q))

    want = reference_attention(np.asarray(q), np.asarray(q), np.asarray(q),
                               causal=True)
    autotune.clear()
    autotune.record("ring_flash",
                    autotune.device_key_for(B, H, D, q.dtype, True), (32, 64))
    np.testing.assert_allclose(run(), want, rtol=2e-3, atol=2e-3)
    autotune.record("ring_flash",
                    autotune.device_key_for(B, H, D, q.dtype, True), "bogus")
    np.testing.assert_allclose(run(), want, rtol=2e-3, atol=2e-3)
    autotune.clear()


def test_ring_flash_head_fold_matches(rng):
    # a 3-tuple registry entry (bq, bk, hfold) drives the fused ring's
    # batched-dot hop; numerics identical to the per-head layout, grads
    # flow through the custom_vjp unchanged
    import jax
    from jax.sharding import PartitionSpec as P
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.utils import autotune
    from distributedarrays_tpu.models.ring_attention import (
        ring_flash_attention_kernel)
    B, H, D = 128, 2, 8
    mesh = L.mesh_for([0], (1,))
    ax = mesh.axis_names[0]
    q = jnp.asarray(rng.standard_normal((B, H, D)).astype(np.float32))

    def run():
        shm = shard_map_compat(
            lambda a, b, c: ring_flash_attention_kernel(a, b, c, ax,
                                                        causal=True),
            mesh=mesh, in_specs=(P(ax),) * 3, out_specs=P(ax),
            check=False)
        return shm(q, q, q)

    autotune.clear()
    key = autotune.device_key_for(B, H, D, q.dtype, True)
    autotune.record("ring_flash", key, (32, 64))
    base = np.asarray(run())
    autotune.record("ring_flash", key, (32, 64, 2))
    folded = np.asarray(run())
    np.testing.assert_allclose(folded, base, rtol=2e-4, atol=2e-5)

    def loss(fold):
        autotune.record("ring_flash", key, (32, 64, fold))
        return jax.grad(lambda a: jnp.sum(run_with(a) ** 2))(q)

    def run_with(a):
        shm = shard_map_compat(
            lambda x, b, c: ring_flash_attention_kernel(x, b, c, ax,
                                                        causal=True),
            mesh=mesh, in_specs=(P(ax),) * 3, out_specs=P(ax),
            check=False)
        return shm(a, q, q)

    g1, g2 = loss(1), loss(2)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1),
                               rtol=1e-4, atol=1e-5)
    autotune.clear()


def test_zigzag_flash_head_fold_matches(rng):
    # round-4: the zigzag quadrant schedule threads the tuned fold
    # through its half-block hops — numerics and grads identical
    import jax
    from jax.sharding import PartitionSpec as P
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.utils import autotune
    from distributedarrays_tpu.models.ring_attention import (
        zigzag_ring_flash_attention_kernel)
    B, H, D = 64, 2, 8
    mesh = L.mesh_for([0], (1,))
    ax = mesh.axis_names[0]
    q = jnp.asarray(rng.standard_normal((B, H, D)).astype(np.float32))

    def run(a):
        shm = shard_map_compat(
            lambda x, b, c: zigzag_ring_flash_attention_kernel(
                x, b, c, ax), mesh=mesh, in_specs=(P(ax),) * 3,
            out_specs=P(ax), check=False)
        return shm(a, q, q)

    autotune.clear()
    key = autotune.device_key_for(B, H, D, q.dtype, True)
    autotune.record("ring_flash", key, (16, 16))
    base = np.asarray(run(q))
    gbase = jax.grad(lambda a: jnp.sum(run(a) ** 2))(q)
    autotune.record("ring_flash", key, (16, 16, 2))
    folded = np.asarray(run(q))
    gfold = jax.grad(lambda a: jnp.sum(run(a) ** 2))(q)
    np.testing.assert_allclose(folded, base, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gfold), np.asarray(gbase),
                               rtol=1e-4, atol=1e-5)
    autotune.clear()
