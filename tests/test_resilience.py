"""Chaos suite: deterministic fault injection, elastic shrink/grow,
retrying executor, and the kill-a-host-mid-spmd acceptance test.

The reference's whole runtime rides Julia Distributed workers that can
die mid-job; this suite rehearses that failure class against the
resilience stack (resilience/{faults,elastic,recovery}.py): a seeded
fault plan must replay exactly, a killed rank/device must recover to a
bit-identical result via checkpoint restore + re-layout onto survivors,
divergence must never be retried, and the per-test leak gate (conftest)
must still drain the registry and HBM ledger to zero afterwards.
"""

import os

import numpy as np
import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu import parallel, telemetry as tm
from distributedarrays_tpu.analysis.divergence import \
    CollectiveDivergenceError
from distributedarrays_tpu.parallel import spmd_mode as S
from distributedarrays_tpu.resilience import elastic, faults, recovery
from distributedarrays_tpu.telemetry import flight
from distributedarrays_tpu.telemetry import memory as tmem
from distributedarrays_tpu.telemetry.fixtures import \
    telemetry_capture  # noqa: F401
from distributedarrays_tpu.utils.checkpoint import CheckpointManager

_HAS_FORK = hasattr(os, "fork")
process_only = pytest.mark.skipif(not _HAS_FORK, reason="needs POSIX fork")


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Every test starts and ends with fault injection disarmed, the
    elastic manager pristine, and the flight recorder's per-process
    crash-bundle cap/dedup reset (all process-wide singletons) — so
    each test's exactly-one-bundle assertion counts only its own
    failures, not the suite's accumulated ones."""
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    yield
    faults.clear()
    elastic.manager().reset()
    flight._reset()


def _fast_policy(**kw):
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    return recovery.RetryPolicy(**kw)


# ---------------------------------------------------------------------------
# faults.py: the deterministic harness itself
# ---------------------------------------------------------------------------


def test_fault_plan_determinism_seeded():
    # identical plan + seed => identical fired-decision history,
    # including the probabilistic spec (per-(spec, invocation) draws)
    plan = [
        {"site": "spmd.rank", "match": {"rank": 1}, "action": "raise",
         "at": 2, "count": 2},
        {"site": "spmd.collective", "action": "raise", "at": 1,
         "count": -1, "p": 0.5},
    ]

    def drive():
        hist = []
        for i in range(6):
            for rank in range(4):
                for site, labels in (
                        ("spmd.rank", {"rank": rank, "backend": "thread"}),
                        ("spmd.collective", {"op": "barrier",
                                             "rank": rank})):
                    spec = faults.decide(site, **labels)
                    if spec is not None:
                        hist.append((site, spec.index))
        return hist, faults.history()

    faults.configure(plan=plan, seed=77)
    h1, full1 = drive()
    faults.configure(plan=plan, seed=77)
    h2, full2 = drive()
    assert h1 == h2 and full1 == full2
    assert any(s == "spmd.rank" for s, _ in h1)       # the 'at' window fired
    # a different seed flips at least one probabilistic decision
    faults.configure(plan=plan, seed=78)
    h3, _ = drive()
    assert [x for x in h3 if x[0] == "spmd.collective"] != \
        [x for x in h1 if x[0] == "spmd.collective"]


def test_fault_plan_json_env_roundtrip(monkeypatch):
    monkeypatch.setenv(
        "DA_TPU_FAULT_PLAN",
        '[{"site": "reshard.chunk", "action": "raise", "at": 1}]')
    monkeypatch.setenv("DA_TPU_FAULT_SEED", "9")
    faults.configure()                    # re-read from the environment
    assert faults.active()
    with pytest.raises(faults.InjectedFault):
        faults.check("reshard.chunk", strategy="all_to_all")


def test_fault_plan_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown fault-spec keys"):
        faults.configure(plan=[{"site": "x", "frobnicate": 1}])
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.configure(plan=[{"site": "x", "action": "explode"}])


def test_device_loss_marks_simulated_down_and_revives():
    faults.configure(plan=[{"site": "spmd.rank", "match": {"rank": 3},
                            "action": "device_loss", "at": 1,
                            "device": 3, "revive_after": 2}], seed=1)
    with pytest.raises(faults.InjectedDeviceLoss):
        faults.check("spmd.rank", rank=3, backend="thread")
    assert faults.simulated_down() == {3}
    assert faults.probe_tick() == {3}     # 1st probe: countdown 2 -> 1
    assert faults.probe_tick() == set()   # 2nd probe: revived
    assert faults.simulated_down() == set()


def test_mark_up_revives_plan_downed_device_without_countdown():
    # revive_after omitted => down until an explicit mark_up; the
    # operator's mark_up must work for plan-downed devices too
    faults.configure(plan=[{"site": "spmd.rank", "match": {"rank": 4},
                            "action": "device_loss", "at": 1,
                            "device": 4}], seed=1)
    with pytest.raises(faults.InjectedDeviceLoss):
        faults.check("spmd.rank", rank=4, backend="thread")
    m = elastic.manager()
    m.probe()
    assert 4 not in m.live_ranks()
    assert faults.probe_tick() == {4}     # no countdown: stays down
    m.mark_up(4)
    m.probe()
    assert 4 in m.live_ranks()
    assert faults.simulated_down() == set()


def test_jitter_deterministic_under_plan():
    faults.configure(plan=[{"site": "x"}], seed=5)
    a = [faults.jitter() for _ in range(4)]
    faults.configure(plan=[{"site": "x"}], seed=5)
    b = [faults.jitter() for _ in range(4)]
    assert a == b
    assert all(0.0 <= v < 1.0 for v in a)


# ---------------------------------------------------------------------------
# rank death: recovery on both spmd backends
# ---------------------------------------------------------------------------


def _rank_death_roundtrip(backend):
    faults.configure(plan=[{"site": "spmd.rank", "match": {"rank": 1},
                            "action": "raise", "at": 1, "count": 1}],
                     seed=1234)
    attempts = []

    def run():
        attempts.append(1)
        return parallel.spmd(lambda: S.myid() * 10, pids=[0, 1, 2, 3],
                             backend=backend)

    out = recovery.run_with_recovery(run, policy=_fast_policy())
    assert out == [0, 10, 20, 30]
    assert len(attempts) == 2             # one failure, one clean retry


def test_rank_death_recovery_thread_backend():
    retries0 = tm.counter_value("recovery.retries", verdict="transient")
    _rank_death_roundtrip("thread")
    assert tm.counter_value("recovery.retries",
                            verdict="transient") == retries0 + 1


@process_only
def test_rank_death_recovery_process_backend():
    # decisions are parent-side, so the plan's count=1 is consumed on the
    # first (failing) run even though the raise happened inside a fork
    _rank_death_roundtrip("process")


@process_only
def test_rank_death_without_report_process_backend():
    # action "exit": the forked rank dies without reporting (os._exit);
    # the parent's "died without reporting" error is transient-retryable
    faults.configure(plan=[{"site": "spmd.rank", "match": {"rank": 2},
                            "action": "exit", "at": 1, "count": 1}],
                     seed=1)
    out = recovery.run_with_recovery(
        lambda: parallel.spmd(lambda: S.myid(), pids=[0, 1, 2],
                              backend="process"),
        policy=_fast_policy())
    assert out == [0, 1, 2]


def test_collective_fault_site_fires():
    faults.configure(plan=[{"site": "spmd.collective",
                            "match": {"op": "barrier", "rank": 2},
                            "action": "raise", "at": 1, "count": 1}],
                     seed=1)

    def prog():
        S.barrier()
        return True

    with pytest.raises(RuntimeError) as ei:
        parallel.spmd(prog, pids=[0, 1, 2, 3])
    assert isinstance(ei.value.__cause__, faults.InjectedFault)
    assert all(parallel.spmd(prog, pids=[0, 1, 2, 3]))   # count consumed


def test_spmd_timeout_env_and_tag_in_message(monkeypatch):
    # satellite: DA_TPU_SPMD_TIMEOUT configures the receive timeout in
    # the error message together with the blocked tag
    monkeypatch.setenv("DA_TPU_SPMD_TIMEOUT", "0.3")

    def stuck():
        if S.myid() == 0:
            S.recvfrom(1, tag="never-sent")

    with pytest.raises(RuntimeError) as ei:
        parallel.spmd(stuck, pids=[0, 1], timeout=30)
    msg = str(ei.value.__cause__)
    assert "DA_TPU_SPMD_TIMEOUT=0.3" in msg
    assert "tag='never-sent'" in msg
    assert "0.3s" in msg
    # source attribution stays honest: an explicit timeout= argument is
    # credited to the caller, not the env var it overrode; an invalid
    # env value is named as invalid, not as the configured source
    assert S._timeout_source(5.0) == "explicit timeout argument"
    monkeypatch.setenv("DA_TPU_SPMD_TIMEOUT", "5m")
    assert "invalid" in S._timeout_source(60.0)
    monkeypatch.delenv("DA_TPU_SPMD_TIMEOUT")
    assert "default" in S._timeout_source(60.0)


def test_hang_action_trips_receive_timeout(monkeypatch):
    monkeypatch.setenv("DA_TPU_SPMD_TIMEOUT", "0.2")
    faults.configure(plan=[{"site": "spmd.collective",
                            "match": {"op": "barrier", "rank": 1},
                            "action": "hang", "hang_s": 1.0,
                            "at": 1, "count": 1}], seed=1)

    def prog():
        S.barrier()

    with pytest.raises(RuntimeError) as ei:
        parallel.spmd(prog, pids=[0, 1], timeout=30)
    assert isinstance(ei.value.__cause__, TimeoutError)


# ---------------------------------------------------------------------------
# elastic: shrink -> re-layout -> grow
# ---------------------------------------------------------------------------


def test_shrink_relayout_grow_roundtrip(rng):
    A = rng.standard_normal((64, 8)).astype(np.float32)
    B = rng.standard_normal((40,)).astype(np.float32)   # uneven on 7
    d1 = dat.distribute(A)
    d2 = dat.distribute(B)
    m = elastic.manager()
    assert m.live_ranks() == list(range(8))

    m.mark_down(5)
    res = m.shrink()
    assert res["failed"] == []
    assert res["moved"] >= 1
    for d in (d1, d2):
        assert 5 not in {int(p) for p in d.pids.flat}
    # the HBM ledger drained the downed device as the re-layout went
    assert tmem.live_bytes_by_device().get(5, 0) == 0
    # registry unchanged: same ids, same live set
    assert {d1.id, d2.id} <= set(dat.registry().keys())
    assert np.array_equal(np.asarray(d1), A)
    assert np.array_equal(np.asarray(d2), B)

    m.mark_up(5)
    m.grow()
    assert 5 in {int(p) for p in d1.pids.flat}
    assert np.array_equal(np.asarray(d1), A)
    assert np.array_equal(np.asarray(d2), B)
    d1.close()
    d2.close()
    # leak gate: registry and ledger drained clean (conftest re-asserts)
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 0


def test_grow_leaves_untouched_custom_layouts_alone(rng):
    # an array on a deliberate 2-rank subset that the failure never
    # displaced must NOT be spread over all 8 ranks by grow()
    A = rng.standard_normal((32, 8)).astype(np.float32)
    custom = dat.distribute(A, procs=[0, 1], dist=(2, 1))
    full = dat.distribute(A)
    m = elastic.manager()
    m.mark_down(7)                        # touches `full`, not `custom`
    m.shrink()
    m.mark_up(7)
    res = m.grow()
    assert res["failed"] == []
    assert sorted({int(p) for p in custom.pids.flat}) == [0, 1]
    assert 7 in {int(p) for p in full.pids.flat}
    assert np.array_equal(np.asarray(custom), A)
    custom.close()
    full.close()


def test_shrink_relayout_routes_through_general_lowering(
        rng, telemetry_capture):
    # the recovery re-layout is a PLANNED reshard, not a bare
    # device_put: shrinking 8 -> 6 over a 40-row array leaves the
    # survivor dim non-divisible (40 % 6 != 0), so the planner's
    # gather_put strategy carries the move — witnessed by the
    # recovery-time reshard span's strategy/dispatch labels
    cap = telemetry_capture
    A = rng.standard_normal((40, 8)).astype(np.float32)
    d = dat.distribute(A)
    m = elastic.manager()
    m.mark_down(6)
    m.mark_down(7)
    res = m.shrink()
    assert res["failed"] == []
    spans = cap.spans("reshard")
    assert spans, "shrink re-layout emitted no reshard span"
    labels = [s.get("labels", {}) for s in spans]
    assert "gather_put" in {lb.get("strategy") for lb in labels}, labels
    # every recovery-time reshard span carries the dispatch label —
    # proof the move went through the instrumented general lowering
    assert all(lb.get("dispatch") in ("rdma", "xla") for lb in labels)
    assert np.array_equal(np.asarray(d), A)
    d.close()


def test_shrink_requires_survivors():
    m = elastic.manager()
    for r in range(8):
        m.mark_down(r)
    with pytest.raises(RuntimeError, match="no live devices"):
        m.shrink()


def test_relayout_noop_when_layout_already_matches(rng):
    d = dat.distribute(rng.standard_normal((32, 8)).astype(np.float32))
    assert elastic.relayout(d, list(range(8))) is False
    d.close()


def test_reshard_chunk_fault_aborts_collective(rng):
    from distributedarrays_tpu.parallel import reshard as R
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributedarrays_tpu import layout as L

    mesh = L.mesh_for(range(8), (8,))
    x = jax.device_put(np.arange(64 * 8, dtype=np.float32).reshape(64, 8),
                       NamedSharding(mesh, P("d0", None)))
    dst = NamedSharding(mesh, P(None, "d0"))
    faults.configure(plan=[{"site": "reshard.chunk", "action": "raise",
                            "at": 1, "count": 1}], seed=1)
    with pytest.raises(faults.InjectedFault):
        R.reshard(x, dst)
    # count consumed: the retry goes through
    y = R.reshard(x, dst)
    assert np.array_equal(np.asarray(y), np.asarray(x))


# ---------------------------------------------------------------------------
# checkpoint: interrupted publication and restore fallback
# ---------------------------------------------------------------------------


def test_restore_skips_partial_step_dirs(tmp_path, rng):
    A = rng.standard_normal((16, 8)).astype(np.float32)
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        mgr.save(1, {"v": 1, "x": dat.distribute(A)})
        mgr.save(2, {"v": 2, "x": dat.distribute(A * 2)})
        # a partially-published step: directory exists, no publish marker
        (tmp_path / "step_00000003").mkdir()
        (tmp_path / "step_00000003" / "arrays.npz").write_bytes(b"junk")
        # and one WITH a marker but corrupt payload (crash mid-copy)
        bad = tmp_path / "step_00000004"
        bad.mkdir()
        (bad / "dartpu_meta.json").write_text(
            '{"__dartpu_store__": "npz", "tree": {"__dartpu__": "ndarray",'
            ' "key": "a0", "jax": false}}')
        # no arrays.npz: load() must fail and fall back
        assert mgr.steps() == [1, 2, 4]
        fb0 = tm.counter_value("checkpoint.restore_fallbacks")
        state = mgr.restore()
        assert state["v"] == 2
        assert np.array_equal(np.asarray(state["x"]), A * 2)
        assert tm.counter_value("checkpoint.restore_fallbacks") == fb0 + 1
        # explicit step stays strict
        with pytest.raises(FileNotFoundError):
            mgr.restore(step=7)
    dat.d_closeall()


def test_interrupted_checkpoint_write_leaves_previous_restorable(
        tmp_path, rng):
    A = rng.standard_normal((16, 8)).astype(np.float32)
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        mgr.save(1, {"step": 1, "x": dat.distribute(A)})
        faults.configure(plan=[{"site": "checkpoint.write",
                                "action": "raise", "at": 1, "count": 1}],
                         seed=1)
        with pytest.raises(faults.InjectedFault):
            mgr.save(2, {"step": 2, "x": dat.distribute(A * 3)})
        # the interrupted step never published; restore sees step 1
        assert mgr.steps() == [1]
        state = mgr.restore()
        assert state["step"] == 1
        # and the step is retryable after the fault window closes
        mgr.save(2, {"step": 2, "x": dat.distribute(A * 3)})
        assert mgr.restore()["step"] == 2
    dat.d_closeall()


# ---------------------------------------------------------------------------
# recovery: verdicts and the retry discipline
# ---------------------------------------------------------------------------


def test_divergence_is_never_retried():
    calls = []

    def diverges():
        calls.append(1)
        raise CollectiveDivergenceError("rank sequences differ")

    g0 = tm.counter_value("recovery.giveups", verdict="divergence")
    with pytest.raises(CollectiveDivergenceError):
        recovery.run_with_recovery(diverges, policy=_fast_policy())
    assert len(calls) == 1                # exactly one attempt, no retry
    assert tm.counter_value("recovery.giveups",
                            verdict="divergence") == g0 + 1


def test_timeout_retried_once_with_fresh_mesh():
    calls = []
    fm0 = tm.counter_value("recovery.fresh_mesh")

    def times_out():
        calls.append(1)
        raise TimeoutError("spmd task did not finish")

    with pytest.raises(TimeoutError):
        recovery.run_with_recovery(times_out, policy=_fast_policy())
    assert len(calls) == 2                # original + exactly one retry
    assert tm.counter_value("recovery.fresh_mesh") == fm0 + 1


def test_transient_retries_bounded():
    calls = []

    def always_fails():
        calls.append(1)
        raise ValueError("flaky")

    with pytest.raises(ValueError):
        recovery.run_with_recovery(
            always_fails, policy=_fast_policy(max_retries=2))
    assert len(calls) == 3                # 1 + max_retries


def test_classify_walks_cause_chain():
    try:
        try:
            raise faults.InjectedDeviceLoss(
                faults.FaultSpec(site="spmd.rank", action="device_loss"),
                {"rank": 1})
        except faults.InjectedDeviceLoss as inner:
            raise RuntimeError("spmd task on rank 1 failed") from inner
    except RuntimeError as wrapped:
        assert recovery.classify(wrapped) == "device_loss"
    assert recovery.classify(TimeoutError("x")) == "timeout"
    assert recovery.classify(ValueError("x")) == "transient"
    assert recovery.classify(
        CollectiveDivergenceError("boom")) == "divergence"
    # process-backend style: the verdict survives stringification
    assert recovery.classify(RuntimeError(
        "child traceback:\nInjectedDeviceLoss: injected fault at "
        "spmd.rank")) == "device_loss"


def test_bundle_is_stamped_with_classification():
    if not tm.enabled():
        pytest.skip("telemetry disabled")
    err = TimeoutError("collective stuck")
    tm.flight.record_crash(err, where="test")
    b = flight.last_bundle()
    assert b is not None and b["classification"] == "timeout"


def test_retry_without_completed_checkpoint_does_not_mask_failure(
        tmp_path):
    # a transient failure BEFORE the first save() completes: the retry
    # loop must skip the restore (nothing to restore) and still retry,
    # not abort with the checkpoint's FileNotFoundError
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise ValueError("first-step blip")
            return "ok"

        out = recovery.run_with_recovery(
            flaky, policy=_fast_policy(), checkpoints=mgr,
            restore_fn=lambda tree: None)
        assert out == "ok"
        assert len(calls) == 2


def test_grow_retries_until_device_actually_revives(rng):
    # a grow epoch while the device is STILL down must keep the shrink
    # mark, so the eventual revival epoch re-grows the array
    A = rng.standard_normal((64, 8)).astype(np.float32)
    d = dat.distribute(A)
    m = elastic.manager()
    m.mark_down(6)
    m.shrink()
    assert 6 not in {int(p) for p in d.pids.flat}
    m.grow()                              # premature: 6 still down
    assert 6 not in {int(p) for p in d.pids.flat}
    m.mark_up(6)
    m.grow()                              # real revival epoch
    assert 6 in {int(p) for p in d.pids.flat}
    assert np.array_equal(np.asarray(d), A)
    d.close()


def test_restore_fn_reseats_state(tmp_path):
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        mgr.save(0, {"value": 41})
        seen = []
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise ValueError("transient blip")
            return "ok"

        out = recovery.run_with_recovery(
            flaky, policy=_fast_policy(), checkpoints=mgr,
            restore_fn=lambda tree: seen.append(tree["value"]))
        assert out == "ok"
        assert seen == [41]               # restored exactly once


# ---------------------------------------------------------------------------
# the acceptance chaos test: kill + revive a simulated host mid-spmd
# ---------------------------------------------------------------------------


def _chaos_workload(tmp_path, plan, seed):
    """One full run: distribute, checkpoint step 0, spmd-mutate every
    localpart (*2 + 1, elementwise so the result is layout-independent),
    recover through the retrying executor, revive + grow, gather."""
    faults.clear()
    elastic.manager().reset()
    if plan is not None:
        faults.configure(plan=plan, seed=seed)
    A = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    d = dat.distribute(A.copy())
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(0, {"x": d})
    state = {"d": d}

    def reseat(tree):
        state["d"].close()                # drop the partially-mutated run
        state["d"] = tree["x"]

    def attempt():
        dd = state["d"]
        ranks = sorted({int(p) for p in dd.pids.flat})

        def f():
            lp = np.asarray(dd.localpart())
            if lp.size:
                dd.set_localpart(lp * 2 + 1)

        parallel.spmd(f, pids=ranks)
        return np.asarray(dd)

    out = recovery.run_with_recovery(
        attempt, policy=_fast_policy(), checkpoints=mgr, restore_fn=reseat)
    # revival epoch: the simulated device comes back, arrays grow back
    probe = elastic.manager().probe()
    elastic.manager().grow()
    mgr.close()
    state["d"].close()
    return out, probe


def test_chaos_kill_and_revive_host_mid_spmd(tmp_path):
    plan = [{"site": "spmd.rank", "match": {"rank": 2},
             "action": "device_loss", "at": 1, "count": 1,
             "device": 2, "revive_after": 2}]
    b0 = flight.crash_bundle_count()
    r0 = tm.counter_value("recovery.retries", verdict="device_loss")
    s0 = tm.counter_value("recovery.restores")
    k0 = tm.counter_value("elastic.shrinks")

    faulty, probe = _chaos_workload(tmp_path / "chaos", plan, seed=1234)

    # exactly ONE flight bundle for the one recovered failure
    assert flight.crash_bundle_count() - b0 == 1
    # the recovery counters recorded the shrink-and-retry path
    assert tm.counter_value("recovery.retries",
                            verdict="device_loss") == r0 + 1
    assert tm.counter_value("recovery.restores") == s0 + 1
    assert tm.counter_value("elastic.shrinks") == k0 + 1
    # the simulated host revived at the post-run probe epoch
    assert probe["down"] == []

    clean, _ = _chaos_workload(tmp_path / "clean", None, seed=0)
    # bit-identical convergence: elementwise workload, so layout churn
    # (8 -> 7 survivors -> 8 revived) must not change a single bit
    assert faulty.dtype == clean.dtype
    assert np.array_equal(faulty, clean)
    # leak gate: everything drained (conftest re-asserts after teardown)
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 0


def test_chaos_replay_is_deterministic(tmp_path):
    plan = [{"site": "spmd.rank", "match": {"rank": 1},
             "action": "device_loss", "at": 1, "count": 1,
             "device": 1, "revive_after": 2}]
    out1, _ = _chaos_workload(tmp_path / "a", plan, seed=42)
    h1 = faults.history()
    out2, _ = _chaos_workload(tmp_path / "b", plan, seed=42)
    h2 = faults.history()
    assert np.array_equal(out1, out2)
    assert h1 == h2
