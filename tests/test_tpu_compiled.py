"""Compiled-on-hardware kernel checks (run with ``DAT_TEST_TPU=1``).

The default suite runs every Pallas kernel in interpreter mode on the
virtual CPU mesh; this file is the hardware leg (VERDICT round-2 item 3):
with ``DAT_TEST_TPU=1`` and a real TPU visible, each kernel compiles
through Mosaic and must match its dense oracle.  Single-chip by design —
it exercises kernel lowering (block shapes, VMEM budgets, SMEM scalars),
not cross-chip collectives (the CPU-mesh suite covers those).

Skipped silently off-hardware so `pytest tests/` stays green everywhere.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

if os.environ.get("DAT_TEST_TPU") != "1":  # pragma: no cover
    pytest.skip("hardware leg: set DAT_TEST_TPU=1 on a TPU host",
                allow_module_level=True)

from distributedarrays_tpu.ops.pallas_gemm import _on_tpu

if not _on_tpu():  # pragma: no cover
    pytest.skip("no TPU visible", allow_module_level=True)


def test_flash_attention_compiled_fwd_bwd():
    from distributedarrays_tpu.ops.pallas_attention import flash_attention
    from distributedarrays_tpu.models.ring_attention import (
        reference_attention)
    S, H, D = 1024, 4, 64
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (S, H, D), jnp.float32)
    k = jax.random.normal(kk, (S, H, D), jnp.float32)
    v = jax.random.normal(kv, (S, H, D), jnp.float32)
    for causal in (False, True):
        got = np.asarray(flash_attention(q, k, v, causal=causal))
        want = reference_attention(q, k, v, causal=causal)
        # MXU default precision (bf16 passes) tolerance
        assert np.abs(got - want).max() < 2e-2

    def loss(q):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def dense_loss(q):
        s = jnp.einsum("qhd,khd->hqk", q / jnp.sqrt(D), k)
        qi = jnp.arange(S)[:, None]
        ki = jnp.arange(S)[None, :]
        s = jnp.where((ki <= qi)[None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("hqk,khd->qhd", p, v) ** 2)

    g = jax.grad(loss)(q)
    gd = jax.grad(dense_loss)(q)
    denom = float(jnp.abs(gd).max())
    assert float(jnp.abs(g - gd).max()) / denom < 5e-2


def test_flash_attention_hop_compiled():
    from distributedarrays_tpu.ops.pallas_attention import (
        flash_attention_hop, flash_carry_init)
    from distributedarrays_tpu.models.ring_attention import (
        reference_attention)
    S, H, D = 512, 4, 64
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, (S, H, D), jnp.float32)
    k = jax.random.normal(kk, (S, H, D), jnp.float32)
    v = jax.random.normal(kv, (S, H, D), jnp.float32)
    qh, kh, vh = (jnp.transpose(x, (1, 0, 2)) for x in (q, k, v))
    half = S // 2
    # rank-0 q block receives the FUTURE k block first (fully skipped),
    # then its own — the carry must pass through the masked hop unchanged
    m, l, a = flash_carry_init(H, half, D)
    m, l, a = flash_attention_hop(qh[:, :half], kh[:, half:], vh[:, half:],
                                  m, l, a, 0, half, causal=True)
    m, l, a = flash_attention_hop(qh[:, :half], kh[:, :half], vh[:, :half],
                                  m, l, a, 0, 0, causal=True)
    got = np.asarray(jnp.transpose(a / l[:, :, :1], (1, 0, 2)))
    want = reference_attention(q, k, v, causal=True)[:half]
    assert np.abs(got - want).max() < 2e-2


def test_pallas_matmul_compiled():
    from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
    for dt, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 2e-2)):
        a = jax.random.normal(jax.random.key(2), (2048, 2048), dt)
        b = jax.random.normal(jax.random.key(3), (2048, 2048), dt)
        got = np.asarray(pallas_matmul(a, b)).astype(np.float32)
        want = np.asarray(jnp.matmul(a, b)).astype(np.float32)
        rel = np.abs(got - want).max() / np.abs(want).max()
        assert rel < tol, (dt, rel)


def test_pallas_stencil_compiled():
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_block
    rng = np.random.default_rng(0)
    A = rng.standard_normal((2048, 1024)).astype(np.float32)
    lo = rng.standard_normal((1, 1024)).astype(np.float32)
    hi = rng.standard_normal((1, 1024)).astype(np.float32)
    got = np.asarray(stencil5_block(jnp.asarray(A), jnp.asarray(lo),
                                    jnp.asarray(hi)))
    x = np.concatenate([lo, A, hi], axis=0)
    left = np.concatenate([np.zeros((A.shape[0], 1), A.dtype), A[:, :-1]], 1)
    right = np.concatenate([A[:, 1:], np.zeros((A.shape[0], 1), A.dtype)], 1)
    want = x[:-2] + x[2:] + left + right - 4 * A
    assert np.abs(got - want).max() < 1e-4


def test_pallas_stencil_temporal_compiled():
    # temporal-blocked kernel through Mosaic: k steps, Dirichlet edges
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_multistep
    rng = np.random.default_rng(1)
    A = rng.standard_normal((2048, 1024)).astype(np.float32)
    k = 8
    want = A
    for _ in range(k):
        p = np.zeros((1, A.shape[1]), A.dtype)
        x = np.concatenate([p, want, p], axis=0)
        left = np.concatenate([np.zeros((want.shape[0], 1), A.dtype),
                               want[:, :-1]], 1)
        right = np.concatenate([want[:, 1:],
                                np.zeros((want.shape[0], 1), A.dtype)], 1)
        want = x[:-2] + x[2:] + left + right - 4 * want
    z = jnp.zeros((k, A.shape[1]), jnp.float32)
    got = np.asarray(stencil5_multistep(jnp.asarray(A), z, z, k, True, True))
    assert np.abs(got - want).max() < 1e-2   # k chained f32 steps
