"""Compiled-on-hardware kernel checks (run with ``DAT_TEST_TPU=1``).

The default suite runs every Pallas kernel in interpreter mode on the
virtual CPU mesh; this file is the hardware leg (VERDICT round-2 item 3):
with ``DAT_TEST_TPU=1`` and a real TPU visible, each kernel compiles
through Mosaic and must match its dense oracle.  Single-chip by design —
it exercises kernel lowering (block shapes, VMEM budgets, SMEM scalars),
not cross-chip collectives (the CPU-mesh suite covers those).

Skipped silently off-hardware so `pytest tests/` stays green everywhere.
"""

import os

import numpy as np
import pytest

import jax
from distributedarrays_tpu.parallel.collectives import shard_map_compat
import jax.numpy as jnp

if os.environ.get("DAT_TEST_TPU") != "1":  # pragma: no cover
    pytest.skip("hardware leg: set DAT_TEST_TPU=1 on a TPU host",
                allow_module_level=True)

from distributedarrays_tpu.ops.pallas_gemm import _on_tpu

if not _on_tpu():  # pragma: no cover
    pytest.skip("no TPU visible", allow_module_level=True)


def test_flash_attention_compiled_fwd_bwd():
    from distributedarrays_tpu.ops.pallas_attention import flash_attention
    from distributedarrays_tpu.models.ring_attention import (
        reference_attention)
    S, H, D = 1024, 4, 64
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (S, H, D), jnp.float32)
    k = jax.random.normal(kk, (S, H, D), jnp.float32)
    v = jax.random.normal(kv, (S, H, D), jnp.float32)
    for causal in (False, True):
        got = np.asarray(flash_attention(q, k, v, causal=causal))
        want = reference_attention(q, k, v, causal=causal)
        # MXU default precision (bf16 passes) tolerance
        assert np.abs(got - want).max() < 2e-2

    def loss(q):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def dense_loss(q):
        s = jnp.einsum("qhd,khd->hqk", q / jnp.sqrt(D), k)
        qi = jnp.arange(S)[:, None]
        ki = jnp.arange(S)[None, :]
        s = jnp.where((ki <= qi)[None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("hqk,khd->qhd", p, v) ** 2)

    g = jax.grad(loss)(q)
    gd = jax.grad(dense_loss)(q)
    denom = float(jnp.abs(gd).max())
    assert float(jnp.abs(g - gd).max()) / denom < 5e-2


def test_flash_attention_hop_compiled():
    from distributedarrays_tpu.ops.pallas_attention import (
        flash_attention_hop, flash_carry_init)
    from distributedarrays_tpu.models.ring_attention import (
        reference_attention)
    S, H, D = 512, 4, 64
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, (S, H, D), jnp.float32)
    k = jax.random.normal(kk, (S, H, D), jnp.float32)
    v = jax.random.normal(kv, (S, H, D), jnp.float32)
    qh, kh, vh = (jnp.transpose(x, (1, 0, 2)) for x in (q, k, v))
    half = S // 2
    # rank-0 q block receives the FUTURE k block first (fully skipped),
    # then its own — the carry must pass through the masked hop unchanged
    m, l, a = flash_carry_init(H, half, D)
    m, l, a = flash_attention_hop(qh[:, :half], kh[:, half:], vh[:, half:],
                                  m, l, a, 0, half, causal=True)
    m, l, a = flash_attention_hop(qh[:, :half], kh[:, :half], vh[:, :half],
                                  m, l, a, 0, 0, causal=True)
    got = np.asarray(jnp.transpose(a / l[:, :, :1], (1, 0, 2)))
    want = reference_attention(q, k, v, causal=True)[:half]
    assert np.abs(got - want).max() < 2e-2


def test_flash_attention_hop_bwd_compiled():
    # the FA2 hop-backward kernels through Mosaic (SMEM offsets, f32
    # contribution outputs): two-hop composition of contributions must
    # match the dense gradient (VERDICT round-3 item 3 hardware leg)
    from distributedarrays_tpu.ops.pallas_attention import (
        _LANE, flash_attention_hop, flash_attention_hop_bwd,
        flash_carry_finalize, flash_carry_init)
    S, H, D = 512, 4, 64
    kq, kk, kv = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(kq, (S, H, D), jnp.float32)
    k = jax.random.normal(kk, (S, H, D), jnp.float32)
    v = jax.random.normal(kv, (S, H, D), jnp.float32)
    qh, kh, vh = (jnp.transpose(x, (1, 0, 2)) for x in (q, k, v))
    half = S // 2
    q0, k0, v0 = qh[:, :half], kh[:, :half], vh[:, :half]
    k1, v1 = kh[:, half:], vh[:, half:]
    sc = float(1.0 / np.sqrt(D))

    # forward over both hops for rank-0's q block, collecting out + lse
    m, l, a = flash_carry_init(H, half, D)
    m, l, a = flash_attention_hop(q0, k0, v0, m, l, a, 0, 0, causal=True)
    m, l, a = flash_attention_hop(q0, k1, v1, m, l, a, 0, half, causal=True)
    oh, lse = flash_carry_finalize(m, l, a, q.dtype)

    g = jnp.ones_like(oh)                                 # dL/dout = 1
    dd = jnp.einsum("hbd,hbd->hb", g.astype(jnp.float32),
                    oh.astype(jnp.float32))
    ddb = jnp.broadcast_to(dd[:, :, None], (H, half, _LANE))
    lseb = jnp.broadcast_to(lse[:, :, None], (H, half, _LANE))
    dq = jnp.zeros((H, half, D), jnp.float32)
    dqc, dk0, dv0 = flash_attention_hop_bwd(q0, k0, v0, g, lseb, ddb,
                                            0, 0, causal=True)
    dq = dq + dqc
    dqc, dk1, dv1 = flash_attention_hop_bwd(q0, k1, v1, g, lseb, ddb,
                                            0, half, causal=True)
    dq = dq + dqc

    def dense_loss(qq, kk_, vv):
        s = jnp.einsum("hqd,hkd->hqk", qq.astype(jnp.float32) * sc,
                       kk_.astype(jnp.float32))
        qi = jnp.arange(half)[:, None]
        ki = jnp.arange(S)[None, :]
        s = jnp.where((ki <= qi)[None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("hqk,hkd->hqd", p, vv.astype(jnp.float32)))

    gd = jax.grad(dense_loss, (0, 1, 2))(q0, kh, vh)
    denom = max(float(jnp.abs(x).max()) for x in gd)
    assert float(jnp.abs(dq - gd[0]).max()) / denom < 5e-2
    dk = jnp.concatenate([dk0, dk1], axis=1)
    dv = jnp.concatenate([dv0, dv1], axis=1)
    assert float(jnp.abs(dk - gd[1]).max()) / denom < 5e-2
    assert float(jnp.abs(dv - gd[2]).max()) / denom < 5e-2


def test_ring_flash_differentiable_compiled():
    # the full custom_vjp ring path on a 1-rank ring: forward + backward
    # compile through Mosaic and match dense gradients
    from jax.sharding import PartitionSpec as P
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.models.ring_attention import (
        ring_flash_attention_kernel)
    from distributedarrays_tpu.ops.pallas_attention import (
        _dense_attention_shd)
    S, H, D = 1024, 4, 64
    q = jax.random.normal(jax.random.key(5), (S, H, D), jnp.float32)
    mesh = L.mesh_for([0], (1, 1, 1))
    ax = mesh.axis_names[0]
    shm = shard_map_compat(
        lambda a, b, c: ring_flash_attention_kernel(a, b, c, ax,
                                                    causal=True),
        mesh=mesh, in_specs=(P(ax),) * 3, out_specs=P(ax), check=False)
    g = jax.jit(jax.grad(lambda x: jnp.sum(shm(x, x, x) ** 2)))(q)
    sc = float(1.0 / np.sqrt(D))
    gd = jax.grad(lambda x: jnp.sum(
        _dense_attention_shd(x, x, x, True, sc) ** 2))(q)
    denom = float(jnp.abs(gd).max())
    assert float(jnp.abs(g - gd).max()) / denom < 5e-2


def test_pallas_matmul_compiled():
    from distributedarrays_tpu.ops.pallas_gemm import pallas_matmul
    for dt, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 2e-2)):
        a = jax.random.normal(jax.random.key(2), (2048, 2048), dt)
        b = jax.random.normal(jax.random.key(3), (2048, 2048), dt)
        got = np.asarray(pallas_matmul(a, b)).astype(np.float32)
        want = np.asarray(jnp.matmul(a, b)).astype(np.float32)
        rel = np.abs(got - want).max() / np.abs(want).max()
        assert rel < tol, (dt, rel)


def test_pallas_matmul_int8_compiled():
    # int8 x int8 -> int32 through the real MXU (Mosaic int8 tiling): the
    # dequantized result must track the f32 oracle within quantization error
    from distributedarrays_tpu.ops.pallas_gemm import quantized_matmul
    a = jax.random.normal(jax.random.key(8), (2048, 2048), jnp.float32)
    b = jax.random.normal(jax.random.key(9), (2048, 2048), jnp.float32)
    got = np.asarray(quantized_matmul(a, b))
    want = np.asarray(jnp.matmul(a, b))
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 2e-2, rel


def test_pallas_stencil_compiled():
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_block
    rng = np.random.default_rng(0)
    A = rng.standard_normal((2048, 1024)).astype(np.float32)
    lo = rng.standard_normal((1, 1024)).astype(np.float32)
    hi = rng.standard_normal((1, 1024)).astype(np.float32)
    got = np.asarray(stencil5_block(jnp.asarray(A), jnp.asarray(lo),
                                    jnp.asarray(hi)))
    x = np.concatenate([lo, A, hi], axis=0)
    left = np.concatenate([np.zeros((A.shape[0], 1), A.dtype), A[:, :-1]], 1)
    right = np.concatenate([A[:, 1:], np.zeros((A.shape[0], 1), A.dtype)], 1)
    want = x[:-2] + x[2:] + left + right - 4 * A
    assert np.abs(got - want).max() < 1e-4


def test_pallas_stencil_temporal_compiled():
    # temporal-blocked kernel through Mosaic: k steps, Dirichlet edges
    from distributedarrays_tpu.ops.pallas_stencil import stencil5_multistep
    rng = np.random.default_rng(1)
    A = rng.standard_normal((2048, 1024)).astype(np.float32)
    k = 8
    want = A
    for _ in range(k):
        p = np.zeros((1, A.shape[1]), A.dtype)
        x = np.concatenate([p, want, p], axis=0)
        left = np.concatenate([np.zeros((want.shape[0], 1), A.dtype),
                               want[:, :-1]], 1)
        right = np.concatenate([want[:, 1:],
                                np.zeros((want.shape[0], 1), A.dtype)], 1)
        want = x[:-2] + x[2:] + left + right - 4 * want
    z = jnp.zeros((k, A.shape[1]), jnp.float32)
    got = np.asarray(stencil5_multistep(jnp.asarray(A), z, z, k, True, True))
    assert np.abs(got - want).max() < 1e-2   # k chained f32 steps


def test_flash_attention_head_fold_compiled():
    # round-4: the batched-dot grid variant must lower through Mosaic and
    # match the per-head layout on real hardware
    from distributedarrays_tpu.ops.pallas_attention import flash_attention
    S, H, D = 1024, 8, 64
    q = jax.random.normal(jax.random.key(21), (S, H, D), jnp.bfloat16)
    base = np.asarray(flash_attention(q, q, q, causal=True, block_q=256,
                                      block_k=256)).astype(np.float32)
    for hf in (2, 4):
        got = np.asarray(flash_attention(q, q, q, causal=True, block_q=256,
                                         block_k=256, head_fold=hf)
                         ).astype(np.float32)
        rel = np.abs(got - base).max() / max(np.abs(base).max(), 1e-6)
        assert rel < 2e-2, (hf, rel)


def test_four_step_fft_program_lowers_single_chip():
    # the dispatcher never picks the four-step program at p=1, so drive
    # _fft1d_shm_jit directly on a 1-device mesh: the ACTUAL program
    # (reshape + cross-rank FFT + twiddle + transpose shuffle, with its
    # degenerate all_to_alls) must lower on hardware and match numpy
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from distributedarrays_tpu.ops.fft import _fft1d_shm_jit
    n = 4096
    mesh = Mesh(np.array(jax.devices()[:1]), ("d0",))
    x = jnp.asarray(np.random.default_rng(5).standard_normal(n)
                    .astype(np.float32))
    x = jax.device_put(x, NamedSharding(mesh, P("d0")))
    got = np.asarray(_fft1d_shm_jit(mesh, P("d0"), "d0", n, 1, False)(x))
    np.testing.assert_allclose(got, np.fft.fft(np.asarray(x))
                               .astype(np.complex64), rtol=2e-3, atol=2e-3)


def test_uneven_scan_program_lowers_single_chip():
    # an uneven DArray needs >= 2 ranks, so drive the padded-scan program
    # directly on a 1-device mesh with a valid extent SHORTER than the
    # block: the dynamic-index total + masked combine must lower on
    # hardware and match numpy on the valid prefix
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from distributedarrays_tpu.ops.mapreduce import _scan_uneven_shm_jit
    mesh = Mesh(np.array(jax.devices()[:1]), ("d0",))
    sh = NamedSharding(mesh, P("d0"))
    x = np.zeros(256, np.float32)
    x[:200] = np.random.default_rng(6).standard_normal(200)
    xd = jax.device_put(jnp.asarray(x), sh)
    got = np.asarray(_scan_uneven_shm_jit(sh, "sum", 0, "d0")(
        xd, jnp.asarray([200], jnp.int32)))
    np.testing.assert_allclose(got[:200], np.cumsum(x[:200]),
                               rtol=1e-3, atol=1e-3)


def test_matmul_dispatch_pallas_promoted_compiled():
    # banked pallas win must route DArray @ DArray through the Pallas
    # kernel ON HARDWARE and match GSPMD numerics
    import distributedarrays_tpu as dat
    from distributedarrays_tpu.ops import linalg as la
    from distributedarrays_tpu.utils import autotune
    autotune.clear()
    try:
        A = np.asarray(jax.random.normal(jax.random.key(30), (1024, 1024),
                                         jnp.float32))
        da = dat.distribute(A, procs=[0], dist=(1, 1))
        db = dat.distribute(A, procs=[0], dist=(1, 1))
        autotune.record("matmul_impl",
                        la._impl_key(1024, 1024, 1024, da.dtype, db.dtype),
                        "pallas")
        got = np.asarray(da @ db)
        want = A @ A
        rel = np.abs(got - want).max() / np.abs(want).max()
        assert rel < 1e-3, rel
    finally:
        autotune.clear()
        dat.d_closeall()


def test_dmatmul_int8_compiled():
    # the DArray-level dynamic int8 GEMM (per-shard Pallas under
    # shard_map on a 1-device mesh) must lower on real hardware
    import distributedarrays_tpu as dat
    try:
        A = np.asarray(jax.random.normal(jax.random.key(40), (1024, 512),
                                         jnp.float32))
        B = np.asarray(jax.random.normal(jax.random.key(41), (512, 768),
                                         jnp.float32))
        got = np.asarray(dat.dmatmul_int8(dat.distribute(A, procs=[0],
                                                         dist=(1, 1)), B))
        want = A @ B
        assert np.abs(got - want).max() / np.abs(want).max() < 3e-2
    finally:
        dat.d_closeall()


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="RDMA ring collectives need >= 2 chips")
def test_rdma_ring_collectives_compiled():
    # COMPILED-mode oracle for the PR 8 RDMA rings on a real multi-chip
    # slice: the interpret-mode suite proves the schedule, this proves
    # the Mosaic lowering (semaphore allocation, LOGICAL device ids,
    # credit DMAs) on silicon.  Same bit-identity contract.
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from distributedarrays_tpu.ops import pallas_collectives as PC
    from distributedarrays_tpu.ops.collective_matmul import \
        allgather_matmul_rhs
    from distributedarrays_tpu.parallel.collectives import (run_spmd,
                                                            spmd_mesh)
    p = len(jax.devices())
    mesh = spmd_mesh(p)
    rng = np.random.default_rng(0)
    x = rng.integers(-8, 8, (p * 8, p * 128)).astype(np.float32)
    spec = P("p", None)
    y1 = run_spmd(lambda a: PC.ring_all_gather(a, "p", interpret=False),
                  mesh, (spec,), P(None, None))(x)
    y2 = run_spmd(lambda a: lax.all_gather(a, "p", axis=0, tiled=True),
                  mesh, (spec,), P(None, None))(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    y1 = run_spmd(lambda a: PC.ring_all_to_all(
        a, "p", split_dim=1, concat_dim=0, interpret=False),
        mesh, (spec,), spec)(x)
    y2 = run_spmd(lambda a: lax.all_to_all(
        a, "p", split_axis=1, concat_axis=0, tiled=True),
        mesh, (spec,), spec)(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    a = rng.integers(-4, 4, (p * 128, p * 128)).astype(np.float32)
    b = rng.integers(-4, 4, (p * 128, 256)).astype(np.float32)
    y1 = run_spmd(lambda aa, bb: allgather_matmul_rhs(
        aa, bb, "p", rdma=True, interpret=False),
        mesh, (spec, spec), spec)(a, b)
    y2 = run_spmd(lambda aa, bb: allgather_matmul_rhs(aa, bb, "p"),
                  mesh, (spec, spec), spec)(a, b)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
