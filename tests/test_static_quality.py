"""Static-quality gates, mirroring the reference's Aqua.jl /
ExplicitImports.jl discipline (test/aqua.jl:4-6, test/explicit_imports.jl:
5-64): export hygiene, import-time side effects, API stability.

The star-import / export-hygiene checks run through the ``analysis`` rule
engine (DAL005) — the ad-hoc AST walks this file used to carry moved into
``distributedarrays_tpu.analysis.rules``; this file asserts the package is
clean under them, plus the dalint self-lint gate over the whole lint
surface (package, examples/, bench.py)."""

import importlib
import pkgutil
import subprocess
import sys
from pathlib import Path

import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu.analysis import RULES, lint_paths

PKG_ROOT = Path(dat.__file__).resolve().parent
REPO_ROOT = PKG_ROOT.parent


def _all_modules():
    errors = []
    mods = list(pkgutil.walk_packages([str(PKG_ROOT)],
                                      prefix="distributedarrays_tpu.",
                                      onerror=errors.append))
    assert not errors, f"subpackage import failures: {errors}"
    # sanity floor: every known subpackage must have been walked
    names = [m.name for m in mods]
    for sub in ("ops", "parallel", "models", "utils"):
        assert any(n.startswith(f"distributedarrays_tpu.{sub}.")
                   for n in names), f"subpackage {sub} not walked"
    return names


def test_every_export_exists():
    # reference Aqua checks undefined exports.  Static half: the DAL005
    # rule engine proves every literal __all__ entry is bound in its
    # module; dynamic half: every export must also resolve at runtime
    # (catches bindings behind dead conditionals the AST pass accepts)
    hygiene = [f for f in lint_paths([PKG_ROOT], select=["DAL005"])
               if not f.suppressed and "__all__" in f.message]
    assert hygiene == [], [f.format() for f in hygiene]
    for name in _all_modules():
        mod = importlib.import_module(name)
        for sym in getattr(mod, "__all__", []):
            assert hasattr(mod, sym), f"{name}.__all__ lists missing {sym!r}"


def test_package_namespace_complete():
    # everything the README/docs surface references must exist at top level
    for sym in ["DArray", "SubDArray", "DData", "distribute", "dzeros",
                "dones", "dfill", "drand", "drandn", "drandint", "dsample",
                "darray", "darray_like", "from_chunks", "ddata", "gather",
                "localpart", "localindices", "locate", "makelocal",
                "allowscalar", "close", "d_closeall", "procs", "dmap",
                "dmap_into", "djit", "dsum", "dmean", "dstd", "dsort",
                "dnnz", "ddot", "dnorm", "matmul", "mul_into", "axpy_",
                "samedist", "mapslices", "ppeval", "copyto_", "dcat",
                "dfetch", "parallel"]:
        assert hasattr(dat, sym), f"top-level export {sym!r} missing"


def test_no_star_imports():
    # ExplicitImports.jl analog, via the DAL005 rule: no `from x import *`
    # anywhere in the package
    stars = [f for f in lint_paths([PKG_ROOT], select=["DAL005"])
             if not f.suppressed and "star import" in f.message]
    assert stars == [], [f.format() for f in stars]


def test_dalint_self_clean():
    # the package gates itself: zero unsuppressed findings across the
    # whole lint surface (suppressions carry their justification inline).
    # lint_paths runs EVERY registered rule, so this also arms the PR 9
    # DAL008/DAL009 lock analyses — a new blocking-under-lock site or
    # lock-order cycle fails here before CI
    targets = [PKG_ROOT, REPO_ROOT / "examples", REPO_ROOT / "bench.py"]
    active = [f for f in lint_paths(targets) if not f.suppressed]
    assert active == [], "\n".join(f.format() for f in active)
    assert {"DAL008", "DAL009"} <= set(RULES), "lock rules must be armed"


def test_dalint_no_rotted_suppressions():
    # every `# dalint: disable=` comment must still silence something:
    # the unused-suppression satellite (DAL100) as a standing gate, so
    # justified suppressions cannot rot when the code around them heals
    from distributedarrays_tpu.analysis.engine import (lint_file,
                                                       unused_suppressions)
    from distributedarrays_tpu.analysis.engine import iter_python_files
    targets = [PKG_ROOT, REPO_ROOT / "examples", REPO_ROOT / "bench.py"]
    stale = []
    for f in iter_python_files(targets):
        per_file = lint_file(f)
        src = Path(f).read_text()
        stale.extend(x for x in unused_suppressions(src, str(f), per_file)
                     if not x.suppressed)
    assert stale == [], "\n".join(f.format() for f in stale)


def test_import_has_no_backend_side_effect():
    # importing the package must not initialize a JAX backend (users must
    # be able to configure jax.config afterwards); regression for the
    # import-time RNG key finding
    code = (
        "import jax\n"
        "import distributedarrays_tpu\n"
        "try:\n"
        "    import jax._src.xla_bridge as xb\n"
        "    backends = getattr(xb, '_backends', None)\n"
        "except ImportError:\n"
        "    backends = None\n"
        "if backends is None:\n"
        "    print('clean (probe unavailable on this jax version)')\n"
        "else:\n"
        "    assert not backends, f'backends initialized: {backends}'\n"
        "    print('clean')\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120,
                       cwd=str(PKG_ROOT.parent))
    assert r.returncode == 0 and "clean" in r.stdout, r.stderr[-500:]
