"""SPMD-mode tests, mirroring the reference suite /root/reference/test/spmd.jl:
collectives smoke test under spmd() (:1-72), ring programs, concurrent runs
on implicit contexts (:108-118), explicit contexts with persistent
context-local storage (:123-197)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.parallel import spmd_mode as S


NP = 8


def test_spmd_runs_all_ranks():
    out = S.spmd(lambda: S.myid())
    assert out == list(range(NP))


def test_spmd_subset_pids():
    out = S.spmd(lambda: S.myid() * 10, pids=[1, 3, 5])
    assert out == [10, 30, 50]


def test_sendto_recvfrom_ring():
    # the reference's ring program (test/spmd.jl:90-101): each rank sends to
    # its next neighbor, receives from the previous
    def ring():
        me = S.myid()
        nxt = (me + 1) % NP
        prv = (me - 1) % NP
        S.sendto(nxt, ("hello", me))
        kind, frm = S.recvfrom(prv)
        assert kind == "hello" and frm == prv
        return frm
    out = S.spmd(ring)
    assert out == [(i - 1) % NP for i in range(NP)]


def test_tagged_out_of_order_delivery():
    # tag matching with out-of-order buffering (reference spmd.jl:126-143)
    def prog():
        me = S.myid()
        if me == 0:
            S.sendto(1, "second", tag="b")
            S.sendto(1, "first", tag="a")
        elif me == 1:
            # receive in the opposite order of sending
            a = S.recvfrom(0, tag="a")
            b = S.recvfrom(0, tag="b")
            return (a, b)
        return None
    out = S.spmd(prog, pids=[0, 1])
    assert out[1] == ("first", "second")


def test_recvfrom_any():
    def prog():
        me = S.myid()
        if me == 0:
            frm, data = S.recvfrom_any()
            return (frm, data)
        S.sendto(0, S.myid() * 2)
        return None
    out = S.spmd(prog, pids=[0, 3])
    assert out[0] == (3, 6)


def test_barrier_and_double_barrier():
    log = []
    def prog():
        me = S.myid()
        S.barrier()
        log.append(("a", me))
        S.barrier()   # immediately again: generation counters must separate
        log.append(("b", me))
        S.barrier()
        return True
    assert all(S.spmd(prog))
    # all "a" entries precede all "b" entries
    phases = [p for p, _ in log]
    assert phases.index("b") >= NP


def test_bcast_scatter_gather():
    def prog():
        me = S.myid()
        v = S.bcast("payload" if me == 2 else None, root=2)
        assert v == "payload"
        part = S.scatter(list(range(16)) if me == 0 else None, root=0)
        assert part == [me * 2, me * 2 + 1]
        got = S.gather_spmd(me * me, root=1)
        if me == 1:
            assert got == [i * i for i in range(NP)]
        return v
    out = S.spmd(prog)
    assert out == ["payload"] * NP


def test_scatter_indivisible_throws():
    def prog():
        S.scatter(list(range(9)) if S.myid() == 0 else None, root=0)
    with pytest.raises(RuntimeError):
        S.spmd(prog, pids=[0, 1])


def test_localpart_resolves_per_rank(rng):
    A = rng.standard_normal((64, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    def prog():
        lp = d.localpart()          # no pid: the task's rank
        return float(np.asarray(lp).sum())
    out = S.spmd(prog)
    want = [A[8 * i:8 * (i + 1)].sum() for i in range(8)]
    assert np.allclose(out, want, rtol=1e-4)


def test_explicit_context_storage_persists():
    # reference test/spmd.jl:123-197: context-local storage survives across
    # two spmd runs on the same context
    ctx = S.context()
    def first():
        S.context_local_storage()["x"] = S.myid() + 100
    def second():
        return S.context_local_storage()["x"]
    S.spmd(first, context=ctx)
    out = S.spmd(second, context=ctx)
    assert out == [i + 100 for i in range(NP)]
    S.close_context(ctx)


def test_implicit_context_is_cleared():
    def prog():
        S.context_local_storage()["y"] = 1
        return True
    assert all(S.spmd(prog))
    # a fresh implicit run must not see the previous run's storage
    def check():
        return "y" in S.context_local_storage()
    assert not any(S.spmd(check))


def test_concurrent_spmd_runs_isolated():
    # reference runs its ring program 8x concurrently on implicit contexts
    # (test/spmd.jl:108-118); here: interleaved runs must not cross traffic
    import threading
    results = {}
    def launch(k):
        def prog():
            me = S.myid()
            S.sendto((me + 1) % 4, (k, me))
            kk, frm = S.recvfrom((me - 1) % 4)
            assert kk == k
            return True
        results[k] = all(S.spmd(prog, pids=[0, 1, 2, 3]))
    ts = [threading.Thread(target=launch, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(results.values())


def test_spmd_error_propagates_and_aborts_peers():
    def prog():
        me = S.myid()
        if me == 1:
            raise ValueError("boom")
        # rank 0 would wait forever for a message from 1; must abort
        S.recvfrom(1, timeout=30)
    with pytest.raises(RuntimeError, match="rank"):
        S.spmd(prog, pids=[0, 1])


def test_explicit_context_survives_failed_run():
    # a failed run must not poison the context (stale messages / diverged
    # barrier generations)
    ctx = S.context([0, 1, 2])
    def bad():
        S.sendto((S.myid() + 1) % 3, "stale")
        if S.myid() == 1:
            raise ValueError("boom")
        S.barrier(timeout=10)
    with pytest.raises(RuntimeError):
        S.spmd(bad, context=ctx)
    def good():
        S.barrier()
        return S.myid()
    assert S.spmd(good, context=ctx) == [0, 1, 2]
    S.close_context(ctx)


def test_collective_root_validation():
    def prog():
        S.bcast("x", root=7)
    with pytest.raises(RuntimeError) as ei:
        S.spmd(prog, pids=[0, 1])
    assert "root 7" in str(ei.value.__cause__)


def test_concurrent_set_localpart_all_land(rng):
    # every rank rewrites its own chunk concurrently; all 8 disjoint
    # updates must land (read-modify-write rebind is serialized)
    A = rng.standard_normal((64, 4)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    def prog():
        me = S.myid()
        d.set_localpart(np.full((8, 4), float(me), np.float32))
        return True
    assert all(S.spmd(prog))
    got = np.asarray(d)
    for r in range(8):
        assert np.all(got[8 * r:8 * (r + 1)] == r), f"rank {r} update lost"
    d.close()


def test_outside_spmd_raises():
    with pytest.raises(RuntimeError, match="spmd"):
        S.sendto(0, "x")
    with pytest.raises(RuntimeError, match="spmd"):
        S.barrier()


# ---------------------------------------------------------------------------
# process backend (parallel/spmd_process.py): the reference's addprocs
# worker model (runtests.jl:10-13) — real forked rank processes
# ---------------------------------------------------------------------------

_HAS_FORK = hasattr(__import__("os"), "fork")
process_only = pytest.mark.skipif(not _HAS_FORK, reason="needs POSIX fork")


@process_only
def test_process_backend_ring():
    def ring():
        me = S.myid()
        S.sendto((me + 1) % 4, ("hello", me))
        kind, frm = S.recvfrom((me - 1) % 4)
        assert kind == "hello"
        S.barrier()
        return frm
    out = S.spmd(ring, pids=range(4), backend="process")
    assert out == [(i - 1) % 4 for i in range(4)]


@process_only
def test_process_backend_gil_free_parallelism():
    # ranks run in separate processes: os.getpid differs from the parent
    # and (usually) between ranks
    import os
    parent = os.getpid()
    pids = S.spmd(lambda: os.getpid(), pids=range(4), backend="process")
    assert all(p != parent for p in pids)
    assert len(set(pids)) == 4


@process_only
def test_process_backend_collectives():
    def prog():
        me = S.myid()
        v = S.bcast("seed" if me == 1 else None, root=1)
        part = S.scatter(list(range(8)) if me == 0 else None, root=0)
        got = S.gather_spmd(sum(part), root=0)
        S.barrier()
        return (v, part, got)
    out = S.spmd(prog, pids=range(4), backend="process")
    assert all(v == "seed" for v, _, _ in out)
    assert [p for _, p, _ in out] == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert out[0][2] == [1, 5, 9, 13]
    assert all(g is None for _, _, g in out[1:])


@process_only
def test_process_backend_context_storage_persists():
    ctx = S.context(pids=range(4))
    try:
        def first():
            S.context_local_storage()["mine"] = S.myid() * 11
            return True

        def second():
            return S.context_local_storage().get("mine")

        assert all(S.spmd(first, context=ctx, backend="process"))
        got = S.spmd(second, context=ctx, backend="process")
        assert got == [0, 11, 22, 33]
        # and the thread backend sees the merged storage too
        got_thread = S.spmd(second, context=ctx)
        assert got_thread == [0, 11, 22, 33]
    finally:
        S.close_context(ctx)


@process_only
def test_process_backend_failure_propagates():
    def prog():
        me = S.myid()
        if me == 2:
            raise ValueError("rank 2 exploded")
        # other ranks block on a receive that will never arrive; the
        # failure event must abort them instead of a 60s timeout
        S.recvfrom(2, timeout=30)

    with pytest.raises(RuntimeError, match="failed"):
        S.spmd(prog, pids=range(4), backend="process")


@process_only
def test_process_backend_tagged_out_of_order():
    def prog():
        me = S.myid()
        if me == 0:
            S.sendto(1, "second", tag="b")
            S.sendto(1, "first", tag="a")
            return None
        a = S.recvfrom(0, tag="a")
        b = S.recvfrom(0, tag="b")
        return (a, b)
    out = S.spmd(prog, pids=range(2), backend="process")
    assert out[1] == ("first", "second")


@process_only
def test_process_backend_bad_backend_name():
    with pytest.raises(ValueError, match="backend"):
        S.spmd(lambda: 0, pids=range(2), backend="gondola")


@process_only
def test_process_backend_large_unconsumed_message():
    # a ~1 MB message sent but never received must not wedge the sender's
    # queue feeder (pipe buffers are ~64 KB) and stays receivable next run
    ctx = S.context(pids=range(2))
    try:
        payload = np.arange(250_000, dtype=np.float32)

        def send_big():
            if S.myid() == 0:
                S.sendto(1, payload, tag="big")
            return True

        def recv_big():
            if S.myid() == 1:
                return float(S.recvfrom(0, tag="big", timeout=10).sum())
            return None

        assert all(S.spmd(send_big, context=ctx, backend="process",
                          timeout=60))
        out = S.spmd(recv_big, context=ctx, backend="process", timeout=60)
        assert out[1] == float(payload.sum())
    finally:
        S.close_context(ctx)


@process_only
def test_process_backend_storage_survives_peer_failure():
    # successful ranks keep their context storage writes when a peer
    # fails (the thread backend mutates storage live; process mirrors it)
    ctx = S.context(pids=range(3))
    try:
        def prog():
            me = S.myid()
            S.context_local_storage()["v"] = me * 7
            if me == 2:
                raise ValueError("rank 2 exploded")
            return True

        with pytest.raises(RuntimeError, match="failed"):
            S.spmd(prog, context=ctx, backend="process")
        got = S.spmd(lambda: S.context_local_storage().get("v"),
                     context=ctx, backend="process")
        assert got[0] == 0 and got[1] == 7   # rank 2's write died with it
    finally:
        S.close_context(ctx)


@process_only
def test_process_backend_message_survives_across_runs():
    # thread-backend parity: a message sent but not received in one run
    # stays in the context's inbox for the next run
    ctx = S.context(pids=range(2))
    try:
        def send_only():
            if S.myid() == 0:
                S.sendto(1, "late delivery", tag="x")
            return True

        def recv_only():
            if S.myid() == 1:
                return S.recvfrom(0, tag="x", timeout=10)
            return None

        assert all(S.spmd(send_only, context=ctx, backend="process"))
        out = S.spmd(recv_only, context=ctx, backend="process")
        assert out[1] == "late delivery"
    finally:
        S.close_context(ctx)
