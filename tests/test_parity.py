"""Breadth parity tests mirroring reference test/darray.jl sections that the
focused suites don't cover: N-D arrays, dtype promotion, equality variants,
fancy-indexed views, localpart mutation sugar, distribute-like layouts."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray


def test_3d_construction_and_ops(rng):
    A = rng.standard_normal((16, 8, 4)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2, 1))
    assert d.pids.shape == (4, 2, 1)
    assert np.allclose(np.asarray(d + d), 2 * A, rtol=1e-6)
    assert np.allclose(float(dat.dsum(d)), A.sum(), rtol=1e-4)
    r = dat.dsum(d, dims=(1, 2))
    assert r.dims == (16, 1, 1)
    assert np.allclose(np.asarray(r), A.sum(axis=(1, 2), keepdims=True),
                       rtol=1e-4)
    lp = d.localpart(5)
    li = d.localindices(5)
    assert np.array_equal(np.asarray(lp),
                          A[np.ix_(list(li[0]), list(li[1]), list(li[2]))])


def test_dtype_promotion(rng):
    i = dat.distribute(np.arange(16, dtype=np.int32))
    f = dat.distribute(np.linspace(0, 1, 16).astype(np.float32))
    r = i + f
    assert r.dtype == jnp.float32
    assert np.allclose(np.asarray(r),
                       np.arange(16) + np.linspace(0, 1, 16).astype(np.float32),
                       rtol=1e-6)
    # int // int stays int
    q = i // 3
    assert jnp.issubdtype(q.dtype, jnp.integer)


def test_complex_dtype(rng):
    z = (rng.standard_normal(64) + 1j * rng.standard_normal(64)).astype(np.complex64)
    dz = dat.distribute(z)
    assert np.allclose(complex(np.asarray(dat.ddot(dz, dz)).item()),
                       np.vdot(z, z), rtol=1e-4)
    assert np.allclose(float(dat.dnorm(dz)), np.linalg.norm(z), rtol=1e-4)
    c = dat.dmap(jnp.conj, dz)
    assert np.allclose(np.asarray(c), np.conj(z), rtol=1e-6)


def test_equality_variants(rng):
    A = rng.standard_normal((20, 10)).astype(np.float32)
    d1 = dat.distribute(A, procs=range(8), dist=(8, 1))
    d2 = dat.distribute(A, procs=range(4), dist=(2, 2))
    assert d1 == d2              # same data, different layouts
    assert d1 == A
    assert not (d1 == A * 2)
    assert d1 != A * 2
    assert not (d1 == np.zeros((3, 3), np.float32))   # shape mismatch
    # hash is id-based (reference darray.jl:72): equal content, distinct ids
    assert hash(d1) != hash(d2)


def test_fancy_indexed_view(rng):
    A = rng.standard_normal((30, 20)).astype(np.float32)
    d = dat.distribute(A)
    rows = np.array([2, 5, 7, 11])
    v = d[rows, 3:9]
    assert v.shape == (4, 6)
    assert np.array_equal(np.asarray(v), A[rows, 3:9])


def test_bool_mask_reduction(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    d = dat.distribute(A)
    mask = d > 0
    assert mask.dtype == jnp.bool_
    frac = float(dat.dmean(mask.astype(jnp.float32)))
    assert abs(frac - (A > 0).mean()) < 1e-6


def test_lp_sugar(rng):
    A = rng.standard_normal((32, 4)).astype(np.float32)
    d = dat.distribute(A.copy(), procs=range(4), dist=(4, 1))
    # .lp getter resolves rank 0 on the controller
    assert np.array_equal(np.asarray(d.lp), A[:8])
    d.lp = np.zeros((8, 4), np.float32)
    A[:8] = 0
    assert np.array_equal(np.asarray(d), A)


def test_distribute_like(rng):
    A = rng.standard_normal((40, 8)).astype(np.float32)
    template = dat.dzeros((40, 8), procs=range(8), dist=(4, 2))
    d = dat.distribute(A, like=template)
    assert d.cuts == template.cuts
    assert np.array_equal(d.pids, template.pids)


def test_astype_roundtrip(rng):
    A = rng.standard_normal((16,)).astype(np.float32)
    d = dat.distribute(A)
    i = d.astype(jnp.int32)
    assert i.dtype == jnp.int32
    assert np.array_equal(np.asarray(i), A.astype(np.int32))
    assert i.cuts == d.cuts


def test_zero_size_dim_ops():
    d = dat.distribute(np.zeros((0, 4), np.float32))
    assert float(dat.dsum(d)) == 0.0
    r = d + d
    assert r.dims == (0, 4)
    # in-place path on a zero-size dest (regression: _rebind resharding)
    dat.dmap_into(jnp.negative, d, d)
    assert d.dims == (0, 4)


def test_jax_array_protocol(rng):
    # DArrays drop directly into jnp ops / jitted functions
    import jax
    A = rng.standard_normal((16, 8)).astype(np.float32)
    B = rng.standard_normal((8, 12)).astype(np.float32)
    da, db = dat.distribute(A), dat.distribute(B)
    r = jnp.sin(da)
    assert isinstance(r, jnp.ndarray)
    assert np.allclose(np.asarray(r), np.sin(A), rtol=1e-5)
    m = jnp.matmul(da, db)
    assert np.allclose(np.asarray(m), A @ B, rtol=1e-4, atol=1e-5)
    jitted = jax.jit(lambda x: (x * 2).sum())
    assert np.allclose(float(jitted(da)), 2 * A.sum(), rtol=1e-4)


def test_reflected_operators_stay_darray(rng):
    # regression: jax.Array on the LEFT must defer to DArray.__radd__ etc.
    # (__jax_array__ would hijack this — deliberately not defined)
    A = rng.standard_normal((8, 4)).astype(np.float32)
    d = dat.distribute(A)
    j = jnp.asarray(A)
    r = j + d
    assert isinstance(r, dat.DArray)
    assert np.allclose(np.asarray(r), 2 * A, rtol=1e-6)
    m = jnp.asarray(A) @ dat.distribute(rng.standard_normal((4, 3)).astype(np.float32))
    assert isinstance(m, dat.DArray)


def test_unflatten_sharding_mismatch_degrades():
    # tree_map that moves the leaf to one device diverges placement from
    # the recorded layout: unflatten must degrade to a plain array, not a
    # DArray whose metadata lies about distribution
    import jax
    d = dat.dzeros((16, 8), procs=range(8), dist=(8, 1))
    out = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), d)
    assert not isinstance(out, dat.DArray)
    # identity tree_map keeps placement → full DArray reconstruction
    same = jax.tree_util.tree_map(lambda x: x, d)
    assert isinstance(same, dat.DArray)
    assert same.cuts == d.cuts


def test_bool_semantics():
    with pytest.raises(ValueError, match="ambiguous"):
        bool(dat.dzeros((4,)))
    assert bool(dat.dfill(1.0, (1,))) is True
    assert bool(dat.dzeros((1,))) is False


@pytest.mark.slow
def test_matmul_property(rng):
    # random GEMM shapes across random layouts vs numpy
    for _ in range(6):
        m, k, n = (int(rng.integers(1, 40)) for _ in range(3))
        A = rng.standard_normal((m, k)).astype(np.float32)
        B = rng.standard_normal((k, n)).astype(np.float32)
        g0 = int(rng.integers(1, 5))
        g1 = max(1, 8 // g0)
        da = dat.distribute(A, procs=range(8), dist=(min(g0, m), 1))
        db = dat.distribute(B, procs=range(8), dist=(1, min(g1, n)))
        C = da @ db
        assert np.allclose(np.asarray(C), A @ B, rtol=1e-4, atol=1e-4), \
            (m, k, n, g0, g1)


def test_deepcopy_memo_aliasing(rng):
    import copy as pycopy
    d = dat.distribute(rng.standard_normal((8, 8)).astype(np.float32))
    pair = pycopy.deepcopy([d, d])
    assert pair[0] is pair[1]          # shared reference stays shared


def test_scalar_0d_result_types(rng):
    A = rng.standard_normal((8, 8)).astype(np.float32)
    d = dat.distribute(A)
    s = dat.dsum(d)
    # whole-array reductions return device scalars, not DArrays
    assert not isinstance(s, DArray)
    assert np.ndim(s) == 0


def test_makelocal_cross_chunk(rng):
    # region spanning several remote chunks (the reference's remote copyto!
    # path, darray.jl:351-368)
    A = rng.standard_normal((64, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    m = dat.makelocal(d, slice(4, 60), slice(0, 8))
    assert np.array_equal(np.asarray(m), A[4:60])


def test_ppeval_with_vector_arg(rng):
    # reference ppeval ships non-distributed args whole (mapreduce.jl:300-313)
    A = rng.standard_normal((8, 8, 4)).astype(np.float32)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    da = dat.distribute(A)
    r = dat.ppeval(jnp.matmul, da, dat.distribute(x))
    want = np.stack([A[:, :, k] @ x[:, k] for k in range(4)], axis=-1)
    assert np.allclose(np.asarray(r), want, rtol=1e-4, atol=1e-5)
