"""Pipeline-parallel and expert-parallel tests (SURVEY.md §2 parallelism
inventory: PP/EP built on the ring-shift / all-to-all substrate)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributedarrays_tpu.models import moe as M
from distributedarrays_tpu.models import pipeline as PP


def test_pipeline_matches_sequential():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 4, 32)
    mb = jax.random.normal(jax.random.key(1), (6, 8, 32))
    got = PP.pipeline_forward(params, mb, mesh)
    want = PP.reference_forward(params, mb)
    assert float(jnp.abs(got - want).max()) < 1e-5


def test_pipeline_eight_stages_single_microbatch():
    mesh = PP.make_pp_mesh(8)
    params = PP.init_pipeline_params(jax.random.key(2), 8, 16)
    mb = jax.random.normal(jax.random.key(3), (1, 4, 16))
    got = PP.pipeline_forward(params, mb, mesh)
    want = PP.reference_forward(params, mb)
    assert float(jnp.abs(got - want).max()) < 1e-5


def test_pipeline_gradients_match_sequential():
    # backward through the schedule (ppermute transposition) must agree
    # with the sequential model
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 4, 16)
    mb = jax.random.normal(jax.random.key(1), (4, 8, 16))
    tgt = jax.random.normal(jax.random.key(2), (4, 8, 16))

    def loss(params):
        return jnp.mean((PP.pipeline_forward(params, mb, mesh) - tgt) ** 2)

    def loss_ref(params):
        return jnp.mean((PP.reference_forward(params, mb) - tgt) ** 2)

    g = jax.grad(loss)(params)
    gr = jax.grad(loss_ref)(params)
    for k in g:
        assert float(jnp.abs(g[k] - gr[k]).max()) < 1e-6, k


def test_pipeline_train_step_decreases_loss():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(3), 4, 16)
    mb = jax.random.normal(jax.random.key(4), (4, 8, 16))
    tgt = jnp.zeros((4, 8, 16))     # reachable target
    losses = []
    for _ in range(25):
        params, loss = PP.pipeline_train_step(params, mb, tgt, mesh, lr=0.1)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]


def test_pipeline_validation():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 2, 8)
    with pytest.raises(ValueError, match="stages"):
        PP.pipeline_forward(params, jnp.zeros((2, 2, 8)), mesh)
    with pytest.raises(ValueError, match="microbatches"):
        PP.pipeline_forward(
            PP.init_pipeline_params(jax.random.key(0), 4, 8),
            jnp.zeros((2, 8)), mesh)


def test_moe_no_drop_matches_oracle():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    x = jax.random.normal(jax.random.key(1), (32, 16))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=8))
    want = M.reference_moe(params, x, 8, 4)
    assert np.abs(got - want).max() < 1e-5


def test_moe_capacity_overflow_passthrough():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    x = jax.random.normal(jax.random.key(1), (32, 16))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=1))
    want = M.reference_moe(params, x, 1, 4)
    assert np.abs(got - want).max() < 1e-5
    # with capacity 1 some tokens MUST pass through unchanged
    assert np.any(np.all(got == np.asarray(x), axis=1))


def test_moe_validation():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 2, 16, 32)
    with pytest.raises(ValueError, match="experts"):
        M.moe_forward(params, jnp.zeros((8, 16)), mesh)
    params4 = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    with pytest.raises(ValueError, match="divisible"):
        M.moe_forward(params4, jnp.zeros((9, 16)), mesh)
    with pytest.raises(ValueError, match="capacity"):
        M.moe_forward(params4, jnp.zeros((8, 16)), mesh, capacity=0)


# ---------------------------------------------------------------------------
# round-4: multi-layer stages + the 1F1B schedule (VERDICT round-3 item 9)
# ---------------------------------------------------------------------------


def test_pipeline_multilayer_stages_match_sequential():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(5), 4, 16, n_layers=3)
    assert params["W"].shape == (4, 3, 16, 16)
    mb = jax.random.normal(jax.random.key(6), (5, 2, 16))
    got = PP.pipeline_forward(params, mb, mesh)
    want = PP.reference_forward(params, mb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_1f1b_matches_gpipe_gradients():
    # the 1F1B hand-scheduled backward must produce EXACTLY the GPipe /
    # sequential gradients (same loss, same updated params)
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 4, 16, n_layers=2)
    mb = jax.random.normal(jax.random.key(1), (6, 3, 16))
    tgt = jax.random.normal(jax.random.key(2), (6, 3, 16))
    p_gpipe, loss_g = PP.pipeline_train_step(params, mb, tgt, mesh, lr=0.05)
    p_1f1b, loss_f = PP.pipeline_train_step_1f1b(params, mb, tgt, mesh,
                                                 lr=0.05)
    np.testing.assert_allclose(float(loss_f), float(loss_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_1f1b["W"]),
                               np.asarray(p_gpipe["W"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(p_1f1b["b"]),
                               np.asarray(p_gpipe["b"]),
                               rtol=1e-4, atol=1e-5)


def test_1f1b_more_microbatches_than_ring():
    # M > 2P-1 exercises ring-slot reuse (the 1F1B memory bound)
    mesh = PP.make_pp_mesh(2)
    params = PP.init_pipeline_params(jax.random.key(3), 2, 8)
    mb = jax.random.normal(jax.random.key(4), (9, 2, 8))   # M=9 > 2*2-1=3
    tgt = jax.random.normal(jax.random.key(5), (9, 2, 8))
    p_g, loss_g = PP.pipeline_train_step(params, mb, tgt, mesh, lr=0.1)
    p_f, loss_f = PP.pipeline_train_step_1f1b(params, mb, tgt, mesh, lr=0.1)
    np.testing.assert_allclose(float(loss_f), float(loss_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_f["W"]), np.asarray(p_g["W"]),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_1f1b_training_decreases_loss():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(7), 4, 16, n_layers=2)
    mb = jax.random.normal(jax.random.key(8), (4, 4, 16))
    tgt = jnp.tanh(mb)
    losses = []
    for _ in range(30):
        params, loss = PP.pipeline_train_step_1f1b(params, mb, tgt, mesh,
                                                   lr=0.5)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert all(b <= a + 1e-6 for a, b in zip(losses, losses[1:])), losses


# ---------------------------------------------------------------------------
# round-4: top-k MoE with capacity factor + aux loss (VERDICT round-3 item 9)
# ---------------------------------------------------------------------------


def test_moe_top2_matches_oracle():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    x = jax.random.normal(jax.random.key(1), (32, 16))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=8, k=2))
    want = M.reference_moe(params, np.asarray(x), 8, 4, k=2)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_moe_top2_capacity_overflow():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(2), 4, 16, 32)
    x = jax.random.normal(jax.random.key(3), (32, 16))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=1, k=2))
    want = M.reference_moe(params, np.asarray(x), 1, 4, k=2)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_moe_aux_loss_matches_dense():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(4), 4, 16, 32)
    x = jax.random.normal(jax.random.key(5), (32, 16))
    _, aux = M.moe_forward(params, x, mesh, capacity=8, k=2,
                           return_aux=True)
    # dense Switch eq. 4, averaged over ranks like the kernel's psum
    E, n_local = 4, 8
    auxes = []
    for r in range(E):
        xs = np.asarray(x)[r * n_local:(r + 1) * n_local]
        logits = xs @ np.asarray(params["Wg"])
        pz = np.exp(logits - logits.max(-1, keepdims=True))
        pz = pz / pz.sum(-1, keepdims=True)
        f = np.bincount(pz.argmax(-1), minlength=E) / n_local
        auxes.append(E * float((f * pz.mean(0)).sum()))
    np.testing.assert_allclose(float(aux), np.mean(auxes),
                               rtol=1e-4, atol=1e-5)
    # uniform router -> aux ~ 1 (the balanced minimum)
    params_u = dict(params, Wg=jnp.zeros_like(params["Wg"]))
    _, aux_u = M.moe_forward(params_u, x, mesh, return_aux=True)
    np.testing.assert_allclose(float(aux_u), 1.0, rtol=1e-5)


def test_moe_capacity_factor_default():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(6), 4, 16, 32)
    x = jax.random.normal(jax.random.key(7), (32, 16))
    # n_local=8, E=4: cf=2.0,k=1 -> C=4; generous cf -> no drops, out
    # matches the no-drop oracle
    got = np.asarray(M.moe_forward(params, x, mesh, k=1,
                                   capacity_factor=8.0))
    want = M.reference_moe(params, np.asarray(x), 8, 4, k=1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    with pytest.raises(ValueError, match="k must be"):
        M.moe_forward(params, x, mesh, k=5)
