"""Pipeline-parallel and expert-parallel tests (SURVEY.md §2 parallelism
inventory: PP/EP built on the ring-shift / all-to-all substrate)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributedarrays_tpu.models import moe as M
from distributedarrays_tpu.models import pipeline as PP


def test_pipeline_matches_sequential():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 4, 32)
    mb = jax.random.normal(jax.random.key(1), (6, 8, 32))
    got = PP.pipeline_forward(params, mb, mesh)
    want = PP.reference_forward(params, mb)
    assert float(jnp.abs(got - want).max()) < 1e-5


def test_pipeline_eight_stages_single_microbatch():
    mesh = PP.make_pp_mesh(8)
    params = PP.init_pipeline_params(jax.random.key(2), 8, 16)
    mb = jax.random.normal(jax.random.key(3), (1, 4, 16))
    got = PP.pipeline_forward(params, mb, mesh)
    want = PP.reference_forward(params, mb)
    assert float(jnp.abs(got - want).max()) < 1e-5


def test_pipeline_gradients_match_sequential():
    # backward through the schedule (ppermute transposition) must agree
    # with the sequential model
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 4, 16)
    mb = jax.random.normal(jax.random.key(1), (4, 8, 16))
    tgt = jax.random.normal(jax.random.key(2), (4, 8, 16))

    def loss(params):
        return jnp.mean((PP.pipeline_forward(params, mb, mesh) - tgt) ** 2)

    def loss_ref(params):
        return jnp.mean((PP.reference_forward(params, mb) - tgt) ** 2)

    g = jax.grad(loss)(params)
    gr = jax.grad(loss_ref)(params)
    for k in g:
        assert float(jnp.abs(g[k] - gr[k]).max()) < 1e-6, k


def test_pipeline_train_step_decreases_loss():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(3), 4, 16)
    mb = jax.random.normal(jax.random.key(4), (4, 8, 16))
    tgt = jnp.zeros((4, 8, 16))     # reachable target
    losses = []
    for _ in range(25):
        params, loss = PP.pipeline_train_step(params, mb, tgt, mesh, lr=0.1)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]


def test_pipeline_validation():
    mesh = PP.make_pp_mesh(4)
    params = PP.init_pipeline_params(jax.random.key(0), 2, 8)
    with pytest.raises(ValueError, match="stages"):
        PP.pipeline_forward(params, jnp.zeros((2, 2, 8)), mesh)
    with pytest.raises(ValueError, match="microbatches"):
        PP.pipeline_forward(
            PP.init_pipeline_params(jax.random.key(0), 4, 8),
            jnp.zeros((2, 8)), mesh)


def test_moe_no_drop_matches_oracle():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    x = jax.random.normal(jax.random.key(1), (32, 16))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=8))
    want = M.reference_moe(params, x, 8, 4)
    assert np.abs(got - want).max() < 1e-5


def test_moe_capacity_overflow_passthrough():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    x = jax.random.normal(jax.random.key(1), (32, 16))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=1))
    want = M.reference_moe(params, x, 1, 4)
    assert np.abs(got - want).max() < 1e-5
    # with capacity 1 some tokens MUST pass through unchanged
    assert np.any(np.all(got == np.asarray(x), axis=1))


def test_moe_validation():
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(0), 2, 16, 32)
    with pytest.raises(ValueError, match="experts"):
        M.moe_forward(params, jnp.zeros((8, 16)), mesh)
    params4 = M.init_moe_params(jax.random.key(0), 4, 16, 32)
    with pytest.raises(ValueError, match="divisible"):
        M.moe_forward(params4, jnp.zeros((9, 16)), mesh)
    with pytest.raises(ValueError, match="capacity"):
        M.moe_forward(params4, jnp.zeros((8, 16)), mesh, capacity=0)
