"""Property-based tests (hypothesis): random layouts and shapes against the
numpy oracle — the breadth analog of the reference's exhaustive
Array-vs-DArray comparisons (test/darray.jl throughout)."""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property fuzz needs hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import layout as L


dims_2d = st.tuples(st.integers(1, 64), st.integers(1, 48))
nranks = st.integers(1, 8)



pytestmark = pytest.mark.slow  # fuzz/subprocess-heavy: full run in CI (--runslow)

@settings(max_examples=40, deadline=None)
@given(sz=st.integers(1, 500), nc=st.integers(1, 12))
def test_cuts_tile_exactly(sz, nc):
    cuts = L.defaultdist_1d(sz, nc)
    assert len(cuts) == nc + 1
    assert cuts[0] == 0 and cuts[-1] == sz
    sizes = np.diff(cuts)
    assert (sizes >= 0).all()
    # remainder spreads over LEADING chunks: sizes are non-increasing and
    # differ by at most one (darray.jl:279-296)
    assert sizes.max() - sizes.min() <= 1
    assert all(a >= b for a, b in zip(sizes, sizes[1:]))


@settings(max_examples=30, deadline=None)
@given(dims=dims_2d, n=nranks, data=st.data())
def test_distribute_roundtrip_any_layout(dims, n, data):
    # any chunk grid whose cell count fits the ranks
    g0 = data.draw(st.integers(1, n))
    g1 = data.draw(st.integers(1, max(1, n // g0)))
    A = np.arange(np.prod(dims), dtype=np.float32).reshape(dims)
    d = dat.distribute(A, procs=range(n), dist=(g0, g1))
    assert np.array_equal(np.asarray(d), A)
    # localparts tile the array exactly
    seen = np.full(dims, -1.0, np.float32)
    for pid in sorted(set(int(p) for p in d.pids.flat)):
        li = d.localindices(pid)
        lp = np.asarray(d.localpart(pid))
        seen[np.ix_(list(li[0]), list(li[1]))] = lp
    assert np.array_equal(seen, A)
    d.close()


@settings(max_examples=30, deadline=None)
@given(dims=dims_2d, data=st.data())
def test_elementwise_and_reduce_match_numpy(dims, data):
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    A = rng.standard_normal(dims).astype(np.float32)
    B = rng.standard_normal(dims).astype(np.float32)
    da, db = dat.distribute(A), dat.distribute(B)
    r = da * 2.0 - db
    assert np.allclose(np.asarray(r), A * 2.0 - B, rtol=1e-5, atol=1e-5)
    assert np.allclose(float(dat.dsum(r)), (A * 2.0 - B).sum(),
                       rtol=1e-3, atol=1e-3)
    ax = data.draw(st.sampled_from([0, 1]))
    m = dat.dmaximum(da, dims=ax)
    assert np.allclose(np.asarray(m), A.max(axis=ax, keepdims=True))
    dat.d_closeall()


@settings(max_examples=25, deadline=None)
@given(n=st.integers(8, 512), data=st.data())
def test_sort_matches_numpy(n, data):
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    kind = data.draw(st.sampled_from(["normal", "dupes", "sorted", "rev"]))
    if kind == "normal":
        x = rng.standard_normal(n).astype(np.float32)
    elif kind == "dupes":
        x = rng.integers(0, 5, n).astype(np.float32)
    elif kind == "sorted":
        x = np.sort(rng.standard_normal(n)).astype(np.float32)
    else:
        x = np.sort(rng.standard_normal(n))[::-1].astype(np.float32).copy()
    s = dat.dsort(dat.distribute(x))
    assert np.array_equal(np.asarray(s), np.sort(x))
    dat.d_closeall()


@settings(max_examples=20, deadline=None)
@given(data=st.data())
def test_spmd_random_message_schedules(data):
    # random point-to-point schedules with tags: every message must arrive
    # at its addressee with its payload, regardless of send/recv ordering
    from distributedarrays_tpu.parallel import spmd_mode as S
    n = data.draw(st.integers(2, 6))
    n_msgs = data.draw(st.integers(1, 12))
    msgs = []                      # (src, dst, tag, payload)
    for i in range(n_msgs):
        src = data.draw(st.integers(0, n - 1))
        dst = data.draw(st.integers(0, n - 1))
        tag = data.draw(st.integers(0, 2))
        msgs.append((src, dst, tag, f"m{i}"))
    by_recv = {}
    for src, dst, tag, pay in msgs:
        by_recv.setdefault(dst, []).append((src, tag, pay))

    def prog():
        me = S.myid()
        # send all my outgoing messages first (async), then receive mine —
        # matching on (src, tag); duplicates of a (src, tag) pair arrive
        # in send order
        for src, dst, tag, pay in msgs:
            if src == me:
                S.sendto(dst, pay, tag=tag)
        got = []
        for src, tag, _ in by_recv.get(me, []):
            got.append((src, tag, S.recvfrom(src, tag=tag, timeout=30)))
        return got

    out = S.spmd(prog, pids=list(range(n)))
    for rank, got in zip(range(n), out):
        want = by_recv.get(rank, [])
        # payload multiset per (src, tag) must match exactly
        from collections import Counter
        w = Counter((s, t, p) for s, t, p in want)
        g = Counter(got)
        assert g == w, (rank, got, want)


@settings(max_examples=25, deadline=None)
@given(dims=dims_2d, data=st.data())
def test_view_slices_match_numpy(dims, data):
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    A = rng.standard_normal(dims).astype(np.float32)
    d = dat.distribute(A)
    i0 = data.draw(st.integers(0, dims[0] - 1))
    i1 = data.draw(st.integers(i0, dims[0]))
    j0 = data.draw(st.integers(0, dims[1] - 1))
    j1 = data.draw(st.integers(j0, dims[1]))
    v = d[i0:i1, j0:j1]
    assert np.array_equal(np.asarray(v), A[i0:i1, j0:j1])
    dat.d_closeall()


@settings(max_examples=25, deadline=None)
@given(dims=dims_2d, n=nranks, data=st.data())
def test_scans_match_numpy_any_layout(dims, n, data):
    # round-3 prefix scans over arbitrary layouts (even -> shard_map
    # path, uneven -> host path) against numpy accumulate oracles
    g0 = data.draw(st.integers(1, n))
    g1 = data.draw(st.integers(1, max(1, n // g0)))
    ax = data.draw(st.integers(0, 1))
    kind = data.draw(st.sampled_from(["sum", "max", "min"]))
    A = np.arange(np.prod(dims), dtype=np.float32).reshape(dims) / 7 - 3
    d = dat.distribute(A, procs=range(n), dist=(g0, g1))
    fn = {"sum": dat.dcumsum, "max": dat.dcummax, "min": dat.dcummin}[kind]
    want = {"sum": np.cumsum, "max": np.maximum.accumulate,
            "min": np.minimum.accumulate}[kind](A, axis=ax)
    got = fn(d, axis=ax)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)
    assert got.cuts == d.cuts
    dat.d_closeall()


@settings(max_examples=15, deadline=None)
@given(n=nranks, data=st.data())
def test_dfft_matches_numpy_any_rowshard(n, data):
    rows = data.draw(st.integers(1, 8)) * n       # divisible and not
    cols = data.draw(st.integers(2, 24))
    ax = data.draw(st.integers(0, 1))
    A = (np.sin(np.arange(rows * cols, dtype=np.float32))
         .reshape(rows, cols))
    d = dat.distribute(A, procs=range(n), dist=(n, 1))
    got = np.asarray(dat.dfft(d, axis=ax))
    np.testing.assert_allclose(got, np.fft.fft(A, axis=ax).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    dat.d_closeall()


# ---------------------------------------------------------------------------
# round-4 paths: uneven compiled scans, four-step 1-D FFT, top-k MoE
# ---------------------------------------------------------------------------


@settings(max_examples=25, deadline=None)
@given(n=st.integers(1, 300), p=st.integers(1, 8),
       kind=st.sampled_from(["sum", "max", "min"]), data=st.data())
def test_scan_any_layout_matches_numpy(n, p, kind, data):
    # every (length, ranks) pair — even, uneven, n < p with empty chunks —
    # must scan identically to numpy, compiled, with the layout kept
    x = np.asarray(data.draw(st.lists(
        st.floats(-8, 8, width=32), min_size=n, max_size=n)), np.float32)
    d = dat.distribute(x, procs=range(p))
    got = getattr(dat, f"dcum{kind}")(d)
    oracle = {"sum": np.cumsum, "max": np.maximum.accumulate,
              "min": np.minimum.accumulate}[kind]
    np.testing.assert_allclose(np.asarray(got), oracle(x),
                               rtol=1e-4, atol=1e-4)
    assert got.cuts == d.cuts
    dat.d_closeall()


@settings(max_examples=15, deadline=None)
@given(m=st.integers(1, 6), p=st.sampled_from([1, 2, 4, 8]))
def test_dfft_1d_four_step_matches_numpy(m, p):
    # lengths m * p^2: always the compiled four-step path; oracle numpy
    n = m * p * p
    rng = np.random.default_rng(n * 31 + p)
    x = rng.standard_normal(n).astype(np.float32)
    d = dat.distribute(x, procs=range(p))
    got = np.asarray(dat.dfft(d))
    np.testing.assert_allclose(got, np.fft.fft(x).astype(np.complex64),
                               rtol=2e-3, atol=2e-3)
    back = np.asarray(dat.difft(dat.dfft(d))).real
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)
    dat.d_closeall()


@settings(max_examples=10, deadline=None)
@given(k=st.integers(1, 4), cap=st.integers(1, 8), seed=st.integers(0, 99))
def test_moe_topk_matches_oracle_any_k_capacity(k, cap, seed):
    import jax
    from distributedarrays_tpu.models import moe as M
    mesh = M.make_ep_mesh(4)
    params = M.init_moe_params(jax.random.key(seed), 4, 8, 16)
    x = jax.random.normal(jax.random.key(seed + 1), (16, 8))
    got = np.asarray(M.moe_forward(params, x, mesh, capacity=cap, k=k))
    want = M.reference_moe(params, np.asarray(x), cap, 4, k=k)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@settings(max_examples=12, deadline=None)
@given(grid=st.sampled_from([(2, 2), (2, 4), (4, 2)]),
       mm=st.integers(1, 4), nn=st.integers(1, 4), kk=st.integers(1, 3),
       seed=st.integers(0, 2 ** 16))
def test_tile_grid_gemm_matches_numpy(grid, mm, nn, kk, seed):
    # the owned 2-D tile schedules (Cannon ring on square grids, SUMMA
    # panels on rectangles) over random compatible shapes must match the
    # numpy oracle — promotion forced through the registry like dispatch
    from distributedarrays_tpu.ops import linalg as la
    from distributedarrays_tpu.utils import autotune
    r, c = grid
    lcm = int(np.lcm(r, c))
    m, n, k = mm * r, nn * c, kk * lcm
    rng2 = np.random.default_rng(seed)
    A = rng2.standard_normal((m, k)).astype(np.float32)
    B = rng2.standard_normal((k, n)).astype(np.float32)
    da = dat.distribute(A, procs=range(r * c), dist=(r, c))
    db = dat.distribute(B, procs=range(r * c), dist=(r, c))
    autotune.record("matmul_impl_dist",
                    la._impl_key(m, n, k, f"{r}x{c}", da.dtype, db.dtype),
                    "summa")
    try:
        got = np.asarray(da @ db)
    finally:
        autotune.clear()
        da.close()
        db.close()
    np.testing.assert_allclose(got, A @ B, rtol=1e-4, atol=1e-4)
