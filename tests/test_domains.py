"""Failure-domain resilience suite: topology + buddy placement, the
quorum rule, partition/slow_link chaos actions, peer-replicated
checkpoints, whole-domain elastic operations, the partition verdict in
recovery, minority-side serve drain — and the partition acceptance soak
(a seeded 5/3 split mid-training: quorum side shrinks to its domains and
restores every shard from peer replicas with ZERO disk reads, bit-equal
post-resume losses; minority side exits typed with exactly one bundle).
"""

import numpy as np
import pytest

import distributedarrays_tpu as dat
from distributedarrays_tpu import serve, telemetry as tm
from distributedarrays_tpu.parallel import multihost
from distributedarrays_tpu.serve import Draining
from distributedarrays_tpu.resilience import (domains, elastic, faults,
                                              recovery)
from distributedarrays_tpu.telemetry import flight
from distributedarrays_tpu.telemetry import memory as tmem
from distributedarrays_tpu.train import Trainer, mlp_task
from distributedarrays_tpu.utils.checkpoint import (
    CheckpointIntegrityError, CheckpointManager, PeerReplicaStore,
    PeerReplicaUnavailable)


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Process-wide singletons (fault plan, elastic manager, flight
    recorder, domain topology) pristine around every test."""
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    domains.reset()
    yield
    faults.clear()
    elastic.manager().reset()
    flight._reset()
    domains.reset()


def _fast_policy(**kw):
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    return recovery.RetryPolicy(**kw)


_SPLIT = [[0, 1, 2, 3, 4], [5, 6, 7]]


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------


def test_topology_from_sizes_and_json():
    t = domains.configure("5,3")
    assert t.domains() == {0: [0, 1, 2, 3, 4], 1: [5, 6, 7]}
    t = domains.configure("[[0,2],[1,3]]")
    assert t.domains() == {0: [0, 2], 1: [1, 3]}
    assert t.domain_of(3) == 1


def test_topology_rejects_duplicates_and_empty():
    with pytest.raises(ValueError, match="more than one"):
        domains.DomainTopology([[0, 1], [1, 2]])
    with pytest.raises(ValueError, match="non-empty"):
        domains.DomainTopology([[], []])


def test_topology_default_is_one_domain_per_process():
    # single-controller CPU mesh: every device reports process 0, so the
    # default collapses to exactly one domain covering all ranks
    t = domains.topology()
    assert len(t.domains()) == 1
    assert t.ranks() == list(range(len(t.ranks())))


def test_domain_of_unknown_rank_raises():
    domains.configure(_SPLIT)
    with pytest.raises(KeyError, match="not in the domain topology"):
        domains.domain_of(99)


def test_live_domains_omits_empty():
    t = domains.configure(_SPLIT)
    assert t.live_domains([0, 1, 7]) == {0: [0, 1], 1: [7]}
    assert t.live_domains([0, 1]) == {0: [0, 1]}


# ---------------------------------------------------------------------------
# buddy placement invariant
# ---------------------------------------------------------------------------


def test_buddy_map_is_cross_domain_with_two_live_domains():
    topo = domains.configure(_SPLIT)
    bmap = domains.buddy_map(live_ranks=range(8))
    assert set(bmap) == set(range(8))
    for r, b in bmap.items():
        assert topo.domain_of(r) != topo.domain_of(b), (r, b)
    assert domains.is_cross_domain(bmap)


def test_buddy_map_rebuddies_after_uneven_shrink():
    # domain 1 shrinks to a single survivor: every domain-0 rank must
    # re-buddy onto it (cross-domain preserved), and it buddies back
    topo = domains.configure(_SPLIT)
    live = [0, 1, 2, 3, 4, 7]
    bmap = domains.buddy_map(live_ranks=live)
    assert set(bmap) == set(live)
    for r in (0, 1, 2, 3, 4):
        assert bmap[r] == 7
    assert bmap[7] in (0, 1, 2, 3, 4)
    assert domains.is_cross_domain(bmap, topo)


def test_buddy_map_degrades_in_domain_when_one_domain_left():
    domains.configure(_SPLIT)
    bmap = domains.buddy_map(live_ranks=[0, 1, 2])   # domain 1 fully gone
    # in-domain ring: the only placement that still exists — flagged by
    # is_cross_domain so callers can see the degraded state
    assert bmap == {0: 1, 1: 2, 2: 0}
    assert not domains.is_cross_domain(bmap)
    assert domains.buddy_map(live_ranks=[3]) == {3: 3}   # lone rank


def test_buddy_map_is_deterministic_per_live_set():
    domains.configure(_SPLIT)
    for live in ([0, 1, 2, 5, 6], [0, 4, 7], list(range(8))):
        assert domains.buddy_map(live_ranks=live) == \
            domains.buddy_map(live_ranks=list(reversed(live)))


# ---------------------------------------------------------------------------
# the quorum rule
# ---------------------------------------------------------------------------


def test_majority_side_strict_majority_wins():
    q = domains.majority_side(_SPLIT, observer=0)
    assert q == {"verdict": "quorum", "side": [0, 1, 2, 3, 4],
                 "lost": [5, 6, 7]}
    q = domains.majority_side(_SPLIT, observer=6)
    assert q["verdict"] == "minority" and q["side"] == [5, 6, 7]


def test_majority_side_tie_breaks_toward_coordinator():
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert domains.majority_side(groups, 1)["verdict"] == "quorum"
    assert domains.majority_side(groups, 5)["verdict"] == "minority"
    # an explicit coordinator moves the tiebreak with it
    assert domains.majority_side(groups, 5,
                                 coordinator=4)["verdict"] == "quorum"


def test_majority_side_survives_coordinator_loss():
    # the coordinator (rank 0) lands on the SMALL side: the strict
    # majority must still win — the coordinator-loss fallback
    groups = [[0, 1], [2, 3, 4, 5, 6, 7]]
    assert domains.majority_side(groups, 3)["verdict"] == "quorum"
    assert domains.majority_side(groups, 0)["verdict"] == "minority"


def test_majority_side_expected_total_counts_silent_ranks():
    # 3 of 8 expected ranks answering is NOT a majority even if they are
    # the largest connected component observed
    q = domains.majority_side([[0, 1, 2]], 0, expected_total=8)
    assert q["verdict"] == "minority"


# ---------------------------------------------------------------------------
# partition / slow_link fault actions
# ---------------------------------------------------------------------------


def test_partition_spec_requires_groups():
    with pytest.raises(ValueError, match="needs 'groups'"):
        faults.FaultSpec.from_dict({"site": "train.step",
                                    "action": "partition"}, 0)


def test_partition_action_downs_far_side_and_heals():
    faults.configure(seed=3, plan=[
        {"site": "spmd.collective", "action": "partition", "at": 1,
         "groups": _SPLIT, "observer": 0}])
    with pytest.raises(faults.InjectedPartition) as ei:
        faults.check("spmd.collective")
    assert ei.value.lost == [5, 6, 7]
    st = faults.partition_state()
    assert st["side"] == [0, 1, 2, 3, 4] and st["lost"] == [5, 6, 7]
    assert elastic.manager().probe()["down"] == [5, 6, 7]
    faults.heal_partition()
    assert faults.partition_state() is None
    assert elastic.manager().probe()["down"] == []


def test_partition_revive_after_clears_state():
    faults.configure(seed=3, plan=[
        {"site": "train.step", "action": "partition", "at": 1,
         "groups": _SPLIT, "observer": 0, "revive_after": 2}])
    with pytest.raises(faults.InjectedPartition):
        faults.check("train.step")
    m = elastic.manager()
    assert m.probe()["down"] == [5, 6, 7]    # tick 1
    assert m.probe()["down"] == []           # tick 2: revived
    assert faults.partition_state() is None


def test_slow_link_delay_is_seeded_and_bounded():
    faults.configure(seed=11, plan=[
        {"site": "reshard.chunk", "action": "slow_link", "at": 1,
         "count": 3, "hang_s": 0.01}])
    h0 = len(faults.history())
    for _ in range(3):
        faults.check("reshard.chunk")        # sleeps, never raises
    fired = faults.history()[h0:]
    assert [f["action"] for f in fired] == ["slow_link"] * 3
    # replay: same seed, same plan -> identical injection history
    faults.configure(seed=11, plan=[
        {"site": "reshard.chunk", "action": "slow_link", "at": 1,
         "count": 3, "hang_s": 0.01}])
    for _ in range(3):
        faults.check("reshard.chunk")
    again = faults.history()[-3:]
    assert [(f["site"], f["invocation"]) for f in again] == \
        [(f["site"], f["invocation"]) for f in fired]
    spec = faults.FaultSpec.from_dict(
        {"site": "x", "action": "slow_link", "hang_s": 0.5}, 0)
    d = faults.slow_link_delay(spec)
    assert 0.25 <= d < 0.5                   # [0.5, 1.0) * hang_s


def test_cross_domain_reshard_survives_seeded_slow_link(rng):
    # the hierarchical-tier chaos gate: a seeded slow_link firing at the
    # reshard chaos site stalls (never kills) a CROSS-domain collective
    # chain — the mesh-axis transpose must still lower through
    # collectives (no silent device_put demotion) and land bit-identical
    # to the oracle, with the firing on the chaos record
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.parallel import reshard as R

    domains.configure("4,4")
    faults.configure(seed=1234, plan=[
        {"site": "reshard.chunk", "action": "slow_link", "at": 1,
         "count": -1, "hang_s": 0.01}])
    A = rng.standard_normal((48, 48)).astype(np.float32)
    mesh = L.mesh_for(list(range(8)), (4, 2))
    src = NamedSharding(mesh, P("d0", "d1"))
    dst = NamedSharding(mesh, P("d1", "d0"))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    # the transpose touches the major mesh axis, so its gather/a2a
    # sub-groups span the 4|4 domain boundary: a genuine DCN-path move
    assert plan.strategy == "chain" and plan.cross_bytes > 0
    h0 = len(faults.history())
    y = R.reshard(x, dst)
    fired = [f for f in faults.history()[h0:]
             if f["action"] == "slow_link"]
    assert fired and fired[0]["site"] == "reshard.chunk"
    assert y.sharding.is_equivalent_to(dst, y.ndim)
    np.testing.assert_array_equal(
        np.asarray(y), np.asarray(jax.device_put(A, dst)))


# ---------------------------------------------------------------------------
# quorum_assess + elastic integration
# ---------------------------------------------------------------------------


def test_quorum_assess_healthy_without_evidence():
    domains.configure(_SPLIT)
    out = multihost.quorum_assess()
    assert out["verdict"] == "healthy" and out["lost"] == []


def test_quorum_assess_reads_injected_partition():
    domains.configure(_SPLIT)
    faults.configure(seed=1, plan=[
        {"site": "train.step", "action": "partition", "at": 1,
         "groups": _SPLIT, "observer": 6}])
    with pytest.raises(faults.InjectedPartition):
        faults.check("train.step")
    out = multihost.quorum_assess()
    assert out["verdict"] == "minority"
    assert out["side"] == [5, 6, 7]


def test_probe_caches_partition_verdict():
    domains.configure(_SPLIT)
    m = elastic.manager()
    assert m.partition_verdict()["verdict"] == "healthy"
    faults.configure(seed=1, plan=[
        {"site": "train.step", "action": "partition", "at": 1,
         "groups": _SPLIT, "observer": 0}])
    with pytest.raises(faults.InjectedPartition):
        faults.check("train.step")
    out = m.probe()
    assert out["partition"]["verdict"] == "quorum"
    assert m.partition_verdict()["verdict"] == "quorum"
    m.reset()
    assert m.partition_verdict()["verdict"] == "healthy"


def test_whole_domain_shrink_and_grow():
    domains.configure(_SPLIT)
    d = dat.distribute(np.arange(64.0).reshape(8, 8))
    m = elastic.manager()
    out = m.shrink(domain=1)
    assert out["live"] == [0, 1, 2, 3, 4]
    # placement invariant: re-layout keeps every chunk out of the dying
    # domain
    assert {int(p) for p in d.pids.flat} <= {0, 1, 2, 3, 4}
    np.testing.assert_array_equal(np.asarray(d),
                                  np.arange(64.0).reshape(8, 8))
    out = m.grow(domain=1)
    assert out["live"] == list(range(8))
    assert {5, 6, 7} & {int(p) for p in d.pids.flat}
    np.testing.assert_array_equal(np.asarray(d),
                                  np.arange(64.0).reshape(8, 8))
    d.close()


# ---------------------------------------------------------------------------
# peer-replicated checkpoints
# ---------------------------------------------------------------------------


def test_peer_replica_round_trip_all_live(tmp_path):
    domains.configure(_SPLIT)
    d = dat.distribute(np.arange(32.0).reshape(4, 8))
    reps = PeerReplicaStore()
    mgr = CheckpointManager(tmp_path, async_save=False, replicas=reps)
    mgr.save(1, {"w": d, "n": 7})
    assert reps.steps() == [1]
    out = mgr.restore()
    assert out["n"] == 7
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(32.0).reshape(4, 8))
    out["w"].close()
    d.close()
    mgr.close()


def test_peer_replica_serves_after_domain_loss_zero_disk_reads(tmp_path):
    domains.configure(_SPLIT)
    d = dat.distribute(np.arange(64.0).reshape(8, 8))
    reps = PeerReplicaStore()
    mgr = CheckpointManager(tmp_path, async_save=False, replicas=reps)
    mgr.save(2, {"w": d})
    m = elastic.manager()
    for r in (5, 6, 7):
        m.mark_down(r)
    dr0 = tm.counter_value("checkpoint.disk_reads")
    p0 = tm.counter_value("checkpoint.restore_source", source="peer")
    out = mgr.restore()
    assert tm.counter_value("checkpoint.disk_reads") == dr0    # ZERO reads
    assert tm.counter_value("checkpoint.restore_source",
                            source="peer") == p0 + 1
    assert tm.counter_value("checkpoint.peer_fetches") >= 1
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(64.0).reshape(8, 8))
    out["w"].close()
    d.close()
    mgr.close()


def test_peer_replica_unavailable_falls_back_to_disk(tmp_path):
    # owner AND holder of some chunk down (both domains hit): the
    # replica tier reports unavailable and restore falls back to disk
    domains.configure(_SPLIT)
    d = dat.distribute(np.arange(64.0).reshape(8, 8))
    reps = PeerReplicaStore()
    mgr = CheckpointManager(tmp_path, async_save=False, replicas=reps)
    mgr.save(1, {"w": d})
    with pytest.raises(PeerReplicaUnavailable):
        reps.fetch(1, live_ranks=[1, 2])     # rank 0 and its holder gone
    dr0 = tm.counter_value("checkpoint.disk_reads")
    m = elastic.manager()
    for r in (0, 5, 6, 7):
        m.mark_down(r)
    out = mgr.restore()                      # disk fallback
    assert tm.counter_value("checkpoint.disk_reads") == dr0 + 1
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(64.0).reshape(8, 8))
    out["w"].close()
    d.close()
    mgr.close()


def test_peer_replica_crc_mismatch_raises_and_evicts(tmp_path):
    domains.configure(_SPLIT)
    reps = PeerReplicaStore()
    mgr = CheckpointManager(tmp_path, async_save=False, replicas=reps)
    mgr.save(1, {"w": np.arange(8.0)})
    # flip a byte inside the stored replica chunk
    rec = reps._steps[1]
    k = next(iter(rec["chunks"]))
    data = bytearray(rec["chunks"][k]["data"])
    data[0] ^= 0xFF
    rec["chunks"][k]["data"] = bytes(data)
    with pytest.raises(CheckpointIntegrityError):
        reps.fetch(1, live_ranks=range(8))
    out = mgr.restore()                      # falls back to disk, evicts
    assert reps.steps() == []
    np.testing.assert_array_equal(out["w"], np.arange(8.0))
    mgr.close()


def test_replicas_rotate_and_rewind_with_disk(tmp_path):
    reps = PeerReplicaStore()
    mgr = CheckpointManager(tmp_path, async_save=False, max_to_keep=2,
                            replicas=reps)
    for s in (1, 2, 3, 4):
        mgr.save(s, {"s": s})
    assert mgr.steps() == [3, 4]
    assert reps.steps() == [3, 4]            # memory tier rotates too
    assert 4 in mgr.discard_from(4)
    assert reps.steps() == [3]               # and rewinds with the disk
    mgr.close()


def test_quarantine_gc_reaps_oldest_first(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False, max_to_keep=None,
                            keep_quarantined=2)
    for s in (1, 2, 3, 4):
        (tmp_path / f".quarantine_step_{s:08d}").mkdir()
    k0 = tm.counter_value("checkpoint.quarantine_reaps")
    mgr.save(9, {"x": 1})
    left = sorted(p.name for p in tmp_path.iterdir()
                  if p.name.startswith(".quarantine"))
    assert left == [".quarantine_step_00000003",
                    ".quarantine_step_00000004"]
    assert tm.counter_value("checkpoint.quarantine_reaps") == k0 + 2
    mgr.close()


def test_keep_quarantined_validation(tmp_path):
    with pytest.raises(ValueError, match="keep_quarantined"):
        CheckpointManager(tmp_path, keep_quarantined=-1)


# ---------------------------------------------------------------------------
# recovery: the partition verdict
# ---------------------------------------------------------------------------


def test_classify_partition_by_type_and_text():
    spec = faults.FaultSpec.from_dict(
        {"site": "x", "action": "partition", "groups": _SPLIT}, 0)
    assert recovery.classify(faults.InjectedPartition(spec, {})) == \
        "partition"
    assert recovery.classify(
        RuntimeError("network partition detected")) == "partition"


def test_quorum_side_restores_and_retries(tmp_path):
    domains.configure(_SPLIT)
    faults.configure(seed=9, plan=[
        {"site": "train.step", "match": {"step": 3}, "action": "partition",
         "at": 1, "groups": _SPLIT, "observer": 0}])
    r0 = tm.counter_value("recovery.retries", verdict="partition")
    k0 = tm.counter_value("elastic.shrinks")
    with Trainer(mlp_task(batch_size=56), ckpt_dir=tmp_path, save_every=2,
                 policy=_fast_policy(), peer_replicas=True) as t:
        res = t.fit(5)
    assert len(res["losses"]) == 5
    assert tm.counter_value("recovery.retries",
                            verdict="partition") == r0 + 1
    assert tm.counter_value("elastic.shrinks") == k0 + 1
    assert elastic.manager().live_ranks() == [0, 1, 2, 3, 4]


def test_minority_side_exits_typed_with_one_bundle(tmp_path):
    domains.configure(_SPLIT)
    faults.configure(seed=9, plan=[
        {"site": "train.step", "match": {"step": 3}, "action": "partition",
         "at": 1, "groups": _SPLIT, "observer": 6}])
    b0 = flight.crash_bundle_count()
    r0 = tm.counter_value("recovery.retries", verdict="partition")
    x0 = tm.counter_value("recovery.minority_exits")
    with Trainer(mlp_task(batch_size=56), ckpt_dir=tmp_path, save_every=2,
                 policy=_fast_policy(), peer_replicas=True) as t:
        with pytest.raises(recovery.MinorityPartitionExit) as ei:
            t.fit(5)
    assert ei.value.side == [5, 6, 7]
    assert ei.value.lost == [0, 1, 2, 3, 4]
    # exactly ONE classified flight bundle, and the step never retried
    assert flight.crash_bundle_count() - b0 == 1
    assert tm.counter_value("recovery.retries", verdict="partition") == r0
    assert tm.counter_value("recovery.minority_exits") == x0 + 1


def test_minority_exit_passes_through_nested_recovery():
    exc = recovery.MinorityPartitionExit("gone", side=[5], lost=[0])
    b0 = flight.crash_bundle_count()
    with pytest.raises(recovery.MinorityPartitionExit):
        recovery.run_with_recovery(
            lambda: (_ for _ in ()).throw(exc), policy=_fast_policy())
    assert flight.crash_bundle_count() == b0     # no second bundle


# ---------------------------------------------------------------------------
# serve: minority-side typed drain
# ---------------------------------------------------------------------------


def test_minority_server_drains_typed():
    domains.configure(_SPLIT)
    faults.configure(seed=1, plan=[
        {"site": "train.step", "action": "partition", "at": 1,
         "groups": _SPLIT, "observer": 6}])
    with pytest.raises(faults.InjectedPartition):
        faults.check("train.step")
    m = elastic.manager()
    m.probe()                                # caches the minority verdict
    assert m.partition_verdict()["verdict"] == "minority"
    s0 = tm.counter_value("serve.partition_drains")
    srv = serve.Server(serve.ServeConfig(workers=1),
                       policy=_fast_policy())
    srv.register("echo", lambda ps: list(ps))
    try:
        with pytest.raises(Draining):
            srv.submit("echo", 1.0)
        assert tm.counter_value("serve.partition_drains") == s0 + 1
        # drained, not wedged: a second submit stays typed
        with pytest.raises(Draining):
            srv.submit("echo", 2.0)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# the partition acceptance soak
# ---------------------------------------------------------------------------

_PARTITION_PLAN = [
    {"site": "train.step", "match": {"step": 5}, "action": "partition",
     "at": 1, "groups": _SPLIT, "observer": 0},
]


def _soak(tmp_path, plan, seed, steps=8, **kw):
    faults.clear()
    elastic.manager().reset()
    domains.configure(_SPLIT)
    if plan is not None:
        faults.configure(plan=plan, seed=seed)
    kw.setdefault("policy", _fast_policy())
    t = Trainer(mlp_task(batch_size=56), ckpt_dir=tmp_path, save_every=2,
                **kw)
    try:
        return t.fit(steps), elastic.manager().live_ranks()
    finally:
        t.close()


@pytest.mark.slow
def test_partition_soak_quorum_side_peer_restore_zero_disk_reads(tmp_path):
    """The acceptance soak: a seeded partition splits the 8-rank mesh
    5/3 at step 5.  The quorum side must shrink to its surviving
    domains, restore every shard from PEER replicas with zero disk
    reads (restore-source counter witness), and finish with a
    post-resume loss trajectory bit-identical to a fault-free run
    restarted from the same step on the same survivors."""
    b0 = flight.crash_bundle_count()
    r0 = tm.counter_value("recovery.retries", verdict="partition")
    dr_before_total = tm.counter_value("checkpoint.disk_reads")
    p0 = tm.counter_value("checkpoint.restore_source", source="peer")
    d0 = tm.counter_value("checkpoint.restore_source", source="disk")

    res, survivors = _soak(tmp_path / "chaos", _PARTITION_PLAN, seed=42,
                           peer_replicas=True)

    # quorum side completed on its own domains
    assert survivors == [0, 1, 2, 3, 4]
    assert len(res["losses"]) == 8
    assert flight.crash_bundle_count() - b0 == 1
    assert tm.counter_value("recovery.retries",
                            verdict="partition") == r0 + 1
    # the restore was served ENTIRELY by the peer-replica tier
    assert tm.counter_value("checkpoint.restore_source",
                            source="peer") == p0 + 1
    assert tm.counter_value("checkpoint.restore_source",
                            source="disk") == d0
    assert tm.counter_value("checkpoint.disk_reads") == dr_before_total

    # comparison: a fault-free run restarted from the same step (4) on
    # the same survivors, from the same on-disk history
    faults.clear()
    import os
    import shutil
    src, dst = tmp_path / "chaos", tmp_path / "clean"
    shutil.copytree(src, dst,
                    ignore=shutil.ignore_patterns(".quarantine*"))
    for p in sorted(os.listdir(dst)):
        if p.startswith("step_") and int(p[5:]) > 4:
            shutil.rmtree(dst / p)
    domains.configure(_SPLIT)
    with Trainer(mlp_task(batch_size=56), ckpt_dir=dst, save_every=1000,
                 policy=_fast_policy(), ranks=survivors) as t2:
        res2 = t2.fit(8)
    assert res2["start"] == 4
    assert res2["losses"] == res["losses"][4:]   # bitwise equality

    # leak gate: registry and HBM ledger drain (conftest re-asserts)
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 0


@pytest.mark.slow
def test_partition_soak_minority_exits_clean_with_one_bundle(tmp_path):
    plan = [dict(_PARTITION_PLAN[0], observer=6)]
    b0 = flight.crash_bundle_count()
    faults.clear()
    elastic.manager().reset()
    domains.configure(_SPLIT)
    faults.configure(plan=plan, seed=42)
    with Trainer(mlp_task(batch_size=56), ckpt_dir=tmp_path / "m",
                 save_every=2, policy=_fast_policy(),
                 peer_replicas=True) as t:
        with pytest.raises(recovery.MinorityPartitionExit):
            t.fit(8)
    assert flight.crash_bundle_count() - b0 == 1
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 0


@pytest.mark.slow
def test_partition_soak_replay_is_deterministic(tmp_path):
    def _normalized_history():
        out = []
        for f in faults.history():
            f = dict(f, labels={k: v for k, v in f["labels"].items()
                                if k != "path"})
            out.append(f)
        return out

    res1, _ = _soak(tmp_path / "a", _PARTITION_PLAN, seed=42,
                    peer_replicas=True)
    h1 = _normalized_history()
    res2, _ = _soak(tmp_path / "b", _PARTITION_PLAN, seed=42,
                    peer_replicas=True)
    h2 = _normalized_history()
    assert res1["losses"] == res2["losses"]
    assert h1 == h2
