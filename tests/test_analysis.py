"""dalint rule engine + SPMD collective-divergence checker tests.

Static half: every rule (DAL001-DAL007) must fire on its bad example and
stay silent on the good one — the same bad/good pairs docs/analysis.md
documents.  Runtime half: under DA_TPU_CHECK_DIVERGENCE=1 a rank-divergent
SPMD program must abort with a per-rank collective-sequence diff (fast —
no waiting out the receive timeout) while conforming programs pass
unchanged on the 8-rank CPU mesh.
"""

import subprocess
import sys
import time
from pathlib import Path

import pytest

from distributedarrays_tpu import telemetry
from distributedarrays_tpu.analysis import (CollectiveDivergenceError,
                                            DivergenceChecker, Finding,
                                            RULES, checking, lint_paths,
                                            lint_source)
from distributedarrays_tpu.parallel import spmd_mode as S

REPO = Path(__file__).resolve().parents[1]


def codes(findings, *, suppressed=False):
    return [f.code for f in findings if f.suppressed == suppressed]


# ---------------------------------------------------------------------------
# rule catalog sanity
# ---------------------------------------------------------------------------


def test_rule_catalog_complete():
    assert set(RULES) == {f"DAL{i:03d}" for i in range(1, 13)}
    for code, rule in RULES.items():
        assert rule.severity in ("error", "warning"), code
        assert rule.title, code


# ---------------------------------------------------------------------------
# DAL001 — collective in rank-dependent branch
# ---------------------------------------------------------------------------


def test_dal001_fires_on_rank_gated_collective():
    src = (
        "from distributedarrays_tpu.parallel import myid, barrier\n"
        "def f():\n"
        "    me = myid()\n"
        "    if me == 0:\n"
        "        barrier()\n")
    # the syntactic rule and the interprocedural prover both flag the
    # shape — DAL001 at the call, DAL010 at the diverging branch
    assert set(codes(lint_source(src))) == {"DAL001", "DAL010"}


def test_dal001_traced_axis_index_variant():
    src = (
        "from jax import lax\n"
        "def f(x):\n"
        "    r = lax.axis_index('p')\n"
        "    if r == 0:\n"
        "        return lax.psum(x, 'p')\n"
        "    return x\n")
    assert "DAL001" in codes(lint_source(src))


def test_dal001_silent_on_symmetric_collectives():
    # the correct idiom: rank-dependent *arguments*, symmetric *calls*
    src = (
        "from distributedarrays_tpu.parallel import myid, bcast, barrier\n"
        "def f():\n"
        "    me = myid()\n"
        "    v = bcast('x' if me == 0 else None, root=0)\n"
        "    barrier()\n"
        "    return v\n")
    assert codes(lint_source(src)) == []


def test_dal001_silent_on_rank_gated_p2p():
    # sendto/recvfrom are point-to-point: rank-dependent branching is the
    # whole point of the dynamic SPMD mode
    src = (
        "from distributedarrays_tpu.parallel import myid, sendto, recvfrom\n"
        "def f():\n"
        "    if myid() == 0:\n"
        "        sendto(1, 'x')\n"
        "    else:\n"
        "        recvfrom(0)\n")
    assert codes(lint_source(src)) == []


# ---------------------------------------------------------------------------
# DAL002 — host sync in traced region
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("body,expect", [
    ("    return np.asarray(x).sum()\n", True),      # host materialize
    ("    return x.item()\n", True),                 # scalar sync
    ("    return float(x)\n", True),                 # concretization
    ("    return jnp.sum(x)\n", False),              # clean traced code
    ("    return int(3)\n", False),                  # literal: fine
])
def test_dal002_jit_decorated(body, expect):
    src = ("import jax\nimport numpy as np\nimport jax.numpy as jnp\n"
           "@jax.jit\ndef f(x):\n" + body)
    got = "DAL002" in codes(lint_source(src))
    assert got is expect, body


def test_dal002_function_passed_to_jit_and_djit():
    src = ("import jax\n"
           "def step(x):\n"
           "    return x.item()\n"
           "g = jax.jit(step)\n")
    assert "DAL002" in codes(lint_source(src))
    src2 = ("from distributedarrays_tpu import djit, gather\n"
            "@djit\n"
            "def f(d):\n"
            "    return gather(d)\n")
    assert "DAL002" in codes(lint_source(src2))


def test_dal002_catches_method_chain_concretization():
    # the docs' canonical bad example: float() on a DERIVED traced value
    src = ("from distributedarrays_tpu import djit\n"
           "@djit\n"
           "def step(x):\n"
           "    return float(x.sum())\n")
    assert "DAL002" in codes(lint_source(src))


def test_dal002_untraced_function_free():
    src = ("import numpy as np\n"
           "def host_side(x):\n"
           "    return float(np.asarray(x).sum())\n")
    assert codes(lint_source(src)) == []


def test_dal002_lax_gather_not_confused():
    # jax.lax.gather is a device op, not the host gather()
    src = ("import jax\nfrom jax import lax\n"
           "@jax.jit\ndef f(x, idx, dnums, ss):\n"
           "    return lax.gather(x, idx, dnums, ss)\n")
    assert codes(lint_source(src)) == []


# ---------------------------------------------------------------------------
# DAL003 — unguarded telemetry with computed args
# ---------------------------------------------------------------------------


def test_dal003_unguarded_vs_guarded():
    bad = ("from distributedarrays_tpu import telemetry as _tm\n"
           "def f(n):\n"
           "    _tm.event('a', 'b', key=f'x{n}')\n")
    assert codes(lint_source(bad)) == ["DAL003"]
    good = ("from distributedarrays_tpu import telemetry as _tm\n"
            "def f(n):\n"
            "    if _tm.enabled():\n"
            "        _tm.event('a', 'b', key=f'x{n}')\n")
    assert codes(lint_source(good)) == []


def test_dal003_guard_recognized_in_nested_statements():
    src = ("from distributedarrays_tpu import telemetry as _tm\n"
           "def f(n):\n"
           "    for i in range(n):\n"
           "        if _tm.enabled():\n"
           "            _tm.record_comm('k', len(str(i)))\n")
    assert codes(lint_source(src)) == []


def test_dal003_constant_args_need_no_guard():
    src = ("from distributedarrays_tpu import telemetry as _tm\n"
           "def f():\n"
           "    _tm.event('a', 'b', key='static')\n")
    assert codes(lint_source(src)) == []


# ---------------------------------------------------------------------------
# DAL004 — unbound axis names
# ---------------------------------------------------------------------------


def test_dal004_typo_axis_caught():
    src = ("from jax.sharding import Mesh\nfrom jax import lax\n"
           "import numpy as np, jax\n"
           "def f(x):\n"
           "    mesh = Mesh(np.array(jax.devices()).reshape(8), ('p',))\n"
           "    return lax.psum(x, 'q')\n")
    found = [f for f in lint_source(src) if f.code == "DAL004"]
    assert len(found) == 1 and "'q'" in found[0].message


def test_dal004_bound_axis_and_caller_bound_axis_pass():
    src = ("from jax.sharding import Mesh\nfrom jax import lax\n"
           "import numpy as np, jax\n"
           "def f(x):\n"
           "    mesh = Mesh(np.array(jax.devices()).reshape(8), ('p',))\n"
           "    return lax.psum(x, 'p')\n"
           "def g(x, axis):\n"
           "    return lax.psum(x, axis)\n"          # axis from caller
           "def h(x):\n"
           "    return lax.psum(x, 'anything')\n")   # no mesh in scope
    assert codes(lint_source(src)) == []


def test_dal004_ignores_axisless_eager_collectives():
    # barrier/bcast/... take no axis: payload/tag strings are not axes
    src = ("from distributedarrays_tpu.parallel import (spmd_mesh, bcast,\n"
           "                                            barrier)\n"
           "def f():\n"
           "    mesh = spmd_mesh(8)\n"
           "    barrier('sync')\n"
           "    return bcast('go', root=0)\n")
    assert codes(lint_source(src)) == []


def test_dal004_spmd_mesh_default_axis():
    src = ("from distributedarrays_tpu.parallel import spmd_mesh\n"
           "from jax import lax\n"
           "def f(x):\n"
           "    mesh = spmd_mesh(8)\n"
           "    return lax.psum(x, 'p')\n")
    assert codes(lint_source(src)) == []


# ---------------------------------------------------------------------------
# DAL005 — import/export hygiene
# ---------------------------------------------------------------------------


def test_dal005_star_import_and_phantom_export():
    src = ("from os.path import *\n"
           "__all__ = ['real', 'phantom']\n"
           "def real():\n"
           "    pass\n")
    msgs = [f.message for f in lint_source(src) if f.code == "DAL005"]
    assert len(msgs) == 2
    assert any("star import" in m for m in msgs)
    assert any("phantom" in m for m in msgs)


def test_dal005_clean_module_passes():
    src = ("import os\n"
           "__all__ = ['x', 'f', 'C']\n"
           "x = 1\n"
           "def f():\n"
           "    pass\n"
           "class C:\n"
           "    pass\n")
    assert codes(lint_source(src)) == []


# ---------------------------------------------------------------------------
# DAL006 — DArray-in-loop leak pattern
# ---------------------------------------------------------------------------


def test_dal006_loop_alloc_without_close():
    src = ("import distributedarrays_tpu as dat\n"
           "def f():\n"
           "    for i in range(10):\n"
           "        d = dat.dzeros((8, 8))\n")
    assert codes(lint_source(src)) == ["DAL006"]


def test_dal006_close_discipline_passes():
    src = ("import distributedarrays_tpu as dat\n"
           "def f():\n"
           "    for i in range(10):\n"
           "        d = dat.dzeros((8, 8))\n"
           "        d.close()\n"
           "def g():\n"
           "    d = dat.dzeros((8, 8))\n"   # not in a loop
           "    return d\n")
    assert codes(lint_source(src)) == []


# ---------------------------------------------------------------------------
# DAL007 — direct cross-sharding device_put outside the reshard planner
# ---------------------------------------------------------------------------


def test_dal007_flags_sharding_device_put():
    src = ("import jax\n"
           "from jax.sharding import NamedSharding, PartitionSpec as P\n"
           "def place(x, mesh):\n"
           "    return jax.device_put(x, NamedSharding(mesh, P('d0')))\n")
    assert codes(lint_source(src, "pkg/ops/thing.py")) == ["DAL007"]


def test_dal007_flags_sharding_named_variable():
    src = ("import jax\n"
           "def place(x, out_sharding):\n"
           "    return jax.device_put(x, out_sharding)\n")
    assert codes(lint_source(src, "pkg/m.py")) == ["DAL007"]


def test_dal007_silent_in_reshard_home():
    src = ("import jax\n"
           "def place(x, sharding):\n"
           "    return jax.device_put(x, sharding)\n")
    assert codes(lint_source(
        src, "distributedarrays_tpu/parallel/reshard.py")) == []


def test_dal007_silent_in_pallas_collectives_home():
    # the RDMA ring kernels are the planner's own inner exchange: their
    # call sites are planned moves, not planner bypasses
    src = ("import jax\n"
           "def stage(x, sharding):\n"
           "    return jax.device_put(x, sharding)\n")
    assert codes(lint_source(
        src, "distributedarrays_tpu/ops/pallas_collectives.py")) == []


def test_dal007_silent_on_bare_device_targets():
    src = ("import jax\n"
           "def pin(x):\n"
           "    device = jax.devices()[0]\n"
           "    y = jax.device_put(x, device)\n"
           "    return jax.device_put(y)\n")       # no target at all
    assert codes(lint_source(src, "pkg/m.py")) == []


def test_dal007_suppressible_with_justification():
    src = ("import jax\n"
           "def place(x, sharding):\n"
           "    return jax.device_put(x, sharding)  "
           "# dalint: disable=DAL007 — host scatter, no source layout\n")
    fs = lint_source(src, "pkg/m.py")
    assert codes(fs) == [] and codes(fs, suppressed=True) == ["DAL007"]


# ---------------------------------------------------------------------------
# suppressions + CLI
# ---------------------------------------------------------------------------


def test_inline_suppression_with_justification():
    src = ("from distributedarrays_tpu.parallel import myid, barrier\n"
           "def f():\n"
           "    if myid() == 0:  # dalint: disable=DAL010 — test fixture\n"
           "        barrier()  # dalint: disable=DAL001 — test fixture\n")
    fs = lint_source(src)
    assert codes(fs) == [] and \
        sorted(codes(fs, suppressed=True)) == ["DAL001", "DAL010"]


def test_file_level_suppression():
    src = ("# dalint: disable-file=DAL006\n"
           "import distributedarrays_tpu as dat\n"
           "def f():\n"
           "    for i in range(10):\n"
           "        d = dat.dzeros((8, 8))\n")
    fs = lint_source(src)
    assert codes(fs) == [] and codes(fs, suppressed=True) == ["DAL006"]


def test_syntax_error_reported_not_raised():
    fs = lint_source("def broken(:\n", "bad.py")
    assert [f.code for f in fs] == ["DAL000"]


@pytest.mark.slow
def test_cli_round_trip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("from os.path import *\n")
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis", "lint",
         str(bad)], capture_output=True, text=True, cwd=str(REPO),
        timeout=180)
    assert r.returncode == 1 and "DAL005" in r.stdout
    bad.write_text("from os.path import *  # dalint: disable=DAL005 — demo\n")
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis", "lint",
         str(bad)], capture_output=True, text=True, cwd=str(REPO),
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    # no resolvable targets must not read as a clean gate
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis", "lint"],
        capture_output=True, text=True, cwd=str(tmp_path),
        env={**__import__("os").environ,
             "PYTHONPATH": str(REPO)}, timeout=180)
    assert r.returncode == 2 and "no lint targets" in r.stderr


# ---------------------------------------------------------------------------
# divergence checker (DA_TPU_CHECK_DIVERGENCE=1)
# ---------------------------------------------------------------------------


@pytest.fixture
def divergence_on(monkeypatch):
    monkeypatch.setenv("DA_TPU_CHECK_DIVERGENCE", "1")
    assert checking()


def test_checking_env_gate(monkeypatch):
    monkeypatch.delenv("DA_TPU_CHECK_DIVERGENCE", raising=False)
    assert not checking()
    monkeypatch.setenv("DA_TPU_CHECK_DIVERGENCE", "0")
    assert not checking()
    monkeypatch.setenv("DA_TPU_CHECK_DIVERGENCE", "1")
    assert checking()


def test_conforming_program_passes_checked(divergence_on):
    # the full eager collective set, 8 ranks, checker armed
    def prog():
        me = S.myid()
        S.barrier()
        v = S.bcast("payload" if me == 2 else None, root=2)
        part = S.scatter(list(range(16)) if me == 0 else None, root=0)
        got = S.gather_spmd(me * me, root=1)
        S.barrier(tag="end")
        return (v, part, got)
    out = S.spmd(prog)
    assert all(v == "payload" for v, _, _ in out)


def test_rank_divergent_collective_raises_with_sequences(divergence_on):
    # the acceptance-criteria program: a collective under `if rank == 0:`
    def bad():
        if S.myid() == 0:  # dalint: disable=DAL010 — seeded divergence: the runtime checker's acceptance fixture; statically cross-validated in test_effects.py
            S.barrier()
        return True
    t0 = time.monotonic()
    with pytest.raises(CollectiveDivergenceError) as ei:
        S.spmd(bad, pids=[0, 1])
    msg = str(ei.value)
    # fail fast (mismatch detection, not the 60s receive timeout)
    assert time.monotonic() - t0 < 30
    # both ranks' sequences are in the message
    assert "rank 0" in msg and "rank 1" in msg
    assert "barrier" in msg and "(none)" in msg


def test_op_mismatch_at_same_slot(divergence_on):
    def bad():
        if S.myid() == 0:  # dalint: disable=DAL010 — seeded divergence: op mismatch at the same slot; statically cross-validated in test_effects.py
            S.barrier()
        else:
            S.bcast("x", root=1)
        return True
    with pytest.raises(CollectiveDivergenceError) as ei:
        S.spmd(bad, pids=[0, 1])
    msg = str(ei.value)
    assert "barrier" in msg and "bcast" in msg


def test_explicit_context_usable_after_divergence(divergence_on):
    ctx = S.context([0, 1])
    def bad():
        if S.myid() == 0:  # dalint: disable=DAL010 — seeded divergence: context-reset-after-abort fixture; statically cross-validated in test_effects.py
            S.barrier()
    with pytest.raises(CollectiveDivergenceError):
        S.spmd(bad, context=ctx)
    # the context must be reset, not poisoned, by the aborted run
    assert S.spmd(lambda: S.myid(), context=ctx) == [0, 1]
    S.close_context(ctx)


def test_genuine_error_wins_over_divergence(divergence_on):
    # a user exception is the root cause even when sequences also diverge
    def bad():
        if S.myid() == 0:
            S.barrier(timeout=30)
        else:
            raise ValueError("boom")
    with pytest.raises(RuntimeError, match="rank") as ei:
        S.spmd(bad, pids=[0, 1])
    assert not isinstance(ei.value, CollectiveDivergenceError)
    assert isinstance(ei.value.__cause__, ValueError)


def test_checker_off_means_timeout_not_divergence(monkeypatch):
    monkeypatch.delenv("DA_TPU_CHECK_DIVERGENCE", raising=False)
    def bad():
        if S.myid() == 0:  # dalint: disable=DAL010 — seeded divergence: proves the checker-off path times out instead; statically cross-validated in test_effects.py
            S.barrier(timeout=2)
        return True
    with pytest.raises(RuntimeError) as ei:
        S.spmd(bad, pids=[0, 1])
    assert not isinstance(ei.value, CollectiveDivergenceError)


def test_mismatch_journaled_as_telemetry_event(divergence_on):
    telemetry.reset()
    telemetry.enable()
    try:
        def bad():
            if S.myid() == 0:  # dalint: disable=DAL010 — seeded divergence: journaling fixture; statically cross-validated in test_effects.py
                S.barrier()
            return True
        with pytest.raises(CollectiveDivergenceError):
            S.spmd(bad, pids=[0, 1])
        evs = [e for e in telemetry.events()
               if e.get("cat") == "divergence"]
        assert evs, "mismatch must journal a divergence event"
    finally:
        telemetry.reset()


def test_checker_unit_payload_signature_in_gather(divergence_on):
    import numpy as np
    # gather payload shape signatures must agree across ranks
    def bad():
        me = S.myid()
        x = np.zeros((me + 1, 4), np.float32)   # different shape per rank
        S.gather_spmd(x, root=0)  # dalint: disable=DAL010 — seeded divergence: per-rank gather payload shapes; statically cross-validated in test_effects.py
        return True
    with pytest.raises(CollectiveDivergenceError) as ei:
        S.spmd(bad, pids=[0, 1])
    assert "ndarray" in str(ei.value)


def test_divergence_checker_unit():
    ck = DivergenceChecker([0, 1])
    ck.record(0, "barrier", "tag=None")
    ck.record(1, "barrier", "tag=None")
    ck.finish(0)
    ck.finish(1)
    ck.verify()
    ck2 = DivergenceChecker([0, 1])
    ck2.record(0, "barrier", "tag=None")
    with pytest.raises(CollectiveDivergenceError):
        ck2.record(1, "bcast", "root=0")
    assert ck2.error is not None


# ---------------------------------------------------------------------------
# engine API shape
# ---------------------------------------------------------------------------


def test_finding_format_and_lint_paths(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("from os import *\n")
    fs = lint_paths([tmp_path])
    assert len(fs) == 1 and isinstance(fs[0], Finding)
    line = fs[0].format()
    assert "DAL005" in line and str(f) in line


# ---------------------------------------------------------------------------
# DAL008 — blocking call while holding a lock (analysis/locks.py)
# ---------------------------------------------------------------------------

_LOCKED_SLEEP = (
    "import threading, time\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "    def f(self):\n"
    "        with self._lock:\n"
    "            time.sleep(1)\n")


def test_dal008_fires_on_sleep_under_lock():
    assert "DAL008" in codes(lint_source(_LOCKED_SLEEP))


def test_dal008_silent_outside_lock():
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            x = 1\n"
        "        time.sleep(1)\n")
    assert "DAL008" not in codes(lint_source(src))


def test_dal008_queue_put_under_lock():
    src = (
        "import threading, queue\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._queue = queue.Queue(4)\n"
        "    def f(self, req):\n"
        "        with self._lock:\n"
        "            self._queue.put(req)\n")
    assert "DAL008" in codes(lint_source(src))


def test_dal008_dict_get_is_not_blocking():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._m = {}\n"
        "    def f(self, k):\n"
        "        with self._lock:\n"
        "            return self._m.get(k)\n")
    assert "DAL008" not in codes(lint_source(src))


def test_dal008_condition_wait_releases_its_own_lock():
    # cv.wait() under only its own condition: NOT blocking-under-lock
    ok = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "    def f(self):\n"
        "        with self._cond:\n"
        "            self._cond.wait(0.1)\n")
    assert "DAL008" not in codes(lint_source(ok))
    # ... but waiting while ANOTHER lock is also held IS a finding
    bad = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cond = threading.Condition()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            with self._cond:\n"
        "                self._cond.wait(0.1)\n")
    assert "DAL008" in codes(lint_source(bad))


def test_dal008_interprocedural_through_self_call():
    # the blocker is two calls deep; the finding anchors at the locked
    # call site and names the witness chain
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def _backoff(self):\n"
        "        time.sleep(0.5)\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            self._backoff()\n")
    fs = [f for f in lint_source(src) if f.code == "DAL008"]
    assert len(fs) == 1 and fs[0].line == 9
    assert "_backoff" in fs[0].message


def test_dal008_string_join_not_flagged():
    src = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f(parts):\n"
        "    with _lock:\n"
        "        return ' | '.join(parts)\n")
    assert "DAL008" not in codes(lint_source(src))


def test_dal008_thread_join_flagged():
    src = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f(worker):\n"
        "    with _lock:\n"
        "        worker.join(2.0)\n")
    assert "DAL008" in codes(lint_source(src))


def test_dal008_suppression():
    src = _LOCKED_SLEEP.replace(
        "time.sleep(1)",
        "time.sleep(1)  # dalint: disable=DAL008 — demo justification")
    fs = lint_source(src)
    assert "DAL008" not in codes(fs)
    assert "DAL008" in codes(fs, suppressed=True)


# ---------------------------------------------------------------------------
# DAL009 — lock-order cycles / non-reentrant re-acquisition
# ---------------------------------------------------------------------------


def test_dal009_abba_cycle():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")
    fs = [f for f in lint_source(src) if f.code == "DAL009"]
    assert fs, "ABBA cycle must be reported"
    assert any("cycle" in f.message and "C._a" in f.message
               and "C._b" in f.message for f in fs)


def test_dal009_consistent_order_is_clean():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n")
    assert "DAL009" not in codes(lint_source(src))


def test_dal009_nonreentrant_self_deadlock():
    # the PR 7 SIGTERM-handler shape: close() re-enters submit()'s lock
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def close(self):\n"
        "        with self._lock:\n"
        "            self._drain()\n"
        "    def _drain(self):\n"
        "        with self._lock:\n"
        "            pass\n")
    # interprocedural: close holds _lock and calls _drain which
    # re-acquires it -> cycle through the call edge is a self-edge;
    # the direct shape is also caught
    direct = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            with self._lock:\n"
        "                pass\n")
    assert "DAL009" in codes(lint_source(direct))
    # an RLock makes the same shape legal (the PR 7 fix)
    assert "DAL009" not in codes(lint_source(
        direct.replace("threading.Lock()", "threading.RLock()")))
    # the interprocedural variant (one call deep) must ALSO fire …
    fs = [f for f in lint_source(src) if f.code == "DAL009"]
    assert fs and "re-acquires" in fs[0].message, fs
    # … and point at the call site inside close(), not at _drain()
    assert fs[0].line == 7, fs[0]
    # and the RLock variant of it is legal
    assert "DAL009" not in codes(lint_source(
        src.replace("threading.Lock()", "threading.RLock()")))


def test_locks_cross_file_cycle():
    # a cycle that only closes across modules: invisible to per-file
    # lint, caught by the `locks` cross-file analysis
    from distributedarrays_tpu.analysis import locks
    a = (
        "import threading\n"
        "import b\n"
        "LOCK_A = threading.Lock()\n"
        "def fa():\n"
        "    with LOCK_A:\n"
        "        b.fb_inner()\n")
    b = (
        "import threading\n"
        "import a\n"
        "LOCK_B = threading.Lock()\n"
        "def fb():\n"
        "    with LOCK_B:\n"
        "        a.fa_inner()\n"
        "def fb_inner():\n"
        "    with LOCK_B:\n"
        "        pass\n")
    a += "def fa_inner():\n    with LOCK_A:\n        pass\n"
    rep = locks.analyze_sources([("pkg/a.py", a), ("pkg/b.py", b)])
    dal9 = [f for f in rep.findings if f.code == "DAL009"]
    assert dal9, "cross-file ABBA cycle must be reported"
    # per-file lint of either file alone sees no cycle
    assert "DAL009" not in codes(lint_source(a, "a.py"))
    assert "DAL009" not in codes(lint_source(b, "b.py"))


def test_locks_graph_format():
    from distributedarrays_tpu.analysis import locks
    src = (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def f():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n")
    rep = locks.analyze_sources([("m.py", src)])
    text = locks.format_graph(rep)
    assert "m.A" in text and "m.B" in text and "→" in text


# ---------------------------------------------------------------------------
# engine edge cases (PR 9 satellites)
# ---------------------------------------------------------------------------


def test_file_level_and_per_line_suppressions_combine():
    src = (
        "# dalint: disable-file=DAL005\n"
        "from os import *\n"
        "from sys import *  # dalint: disable=DAL001 — wrong code\n")
    fs = lint_source(src)
    # the file-level DAL005 silences BOTH star imports (the per-line
    # DAL001 comment is irrelevant to DAL005 findings)
    assert codes(fs) == []
    assert codes(fs, suppressed=True).count("DAL005") == 2


def test_crlf_source_lints_and_suppresses():
    src = ("from os import *\r\n"
           "from sys import *  # dalint: disable=DAL005 — crlf demo\r\n")
    fs = lint_source(src, "crlf.py")
    assert codes(fs) == ["DAL005"]            # line 1 unsuppressed
    assert codes(fs, suppressed=True) == ["DAL005"]   # line 2 silenced


def test_syntax_error_file_is_a_finding_not_a_crash(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("from os import *\n")
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n")
    fs = lint_paths([tmp_path])
    by_code = {f.code for f in fs}
    assert "DAL000" in by_code and "DAL005" in by_code
    dal0 = [f for f in fs if f.code == "DAL000"]
    assert dal0[0].severity == "error" and "syntax error" in dal0[0].message


def test_unused_suppression_detection():
    from distributedarrays_tpu.analysis import unused_suppressions
    src = ("from os import *  # dalint: disable=DAL005 — used\n"
           "x = 1  # dalint: disable=DAL006 — silences nothing\n"
           "y = 2  # dalint: disable=DALNOPE — typo'd code\n")
    fs = lint_source(src, "u.py")
    extra = unused_suppressions(src, "u.py", fs)
    msgs = [f.message for f in extra]
    assert all(f.code == "DAL100" for f in extra)
    assert len(extra) == 2
    assert any("DAL006" in m for m in msgs)
    assert any("DALNOPE" in m and "unknown rule code" in m for m in msgs)


def test_unused_disable_file_keeper_and_anchor():
    # the docs' keeper pattern: a deliberate unused disable-file kept
    # with disable=DAL100 on the SAME line must come back suppressed
    from distributedarrays_tpu.analysis import unused_suppressions
    src = ("# dalint: disable-file=DAL003"
           "  # dalint: disable=DAL100 — keeper\n"
           "y = 1\n")
    fs = unused_suppressions(src, "k.py", lint_source(src, "k.py"))
    assert fs and fs[0].code == "DAL100" and fs[0].suppressed
    # and without the keeper, the report anchors at the comment's own
    # line (not line 1) so the keeper syntax has a line to land on
    src2 = "x = 1\n# dalint: disable-file=DAL003\n"
    fs2 = unused_suppressions(src2, "k.py", lint_source(src2, "k.py"))
    assert fs2 and fs2[0].line == 2 and not fs2[0].suppressed


def test_unused_suppression_respects_select_subset():
    from distributedarrays_tpu.analysis import unused_suppressions
    src = "x = 1  # dalint: disable=DAL006 — rule not run\n"
    fs = lint_source(src, "u.py", select=["DAL005"])
    # DAL006 never ran under --select DAL005: nothing can be concluded
    assert unused_suppressions(src, "u.py", fs, ["DAL005"]) == []


def test_docstring_suppression_examples_are_inert():
    # a docstring QUOTING the syntax must neither suppress findings on
    # its line nor count as an (unused) suppression
    from distributedarrays_tpu.analysis import (parse_suppressions,
                                                unused_suppressions)
    src = ('"""Example:\n'
           '    x = f()  # dalint: disable=DAL006 — demo\n'
           '"""\n'
           "y = 1\n")
    per_line, whole = parse_suppressions(src.splitlines())
    assert per_line == {} and whole == set()
    assert unused_suppressions(src, "d.py", lint_source(src, "d.py")) == []


@pytest.mark.slow
def test_cli_formats_and_unused_warnings(tmp_path):
    import json as _json
    bad = tmp_path / "bad.py"
    bad.write_text("from os import *\n"
                   "x = 1  # dalint: disable=DAL006 — rotted\n")
    base = [sys.executable, "-m", "distributedarrays_tpu.analysis",
            "lint", str(bad)]
    r = subprocess.run(base + ["--format", "json"], capture_output=True,
                       text=True, cwd=str(REPO), timeout=180)
    data = _json.loads(r.stdout)
    assert r.returncode == 1
    assert data[0]["code"] == "DAL005" and data[0]["line"] == 1
    r = subprocess.run(base + ["--format", "github"],
                       capture_output=True, text=True, cwd=str(REPO),
                       timeout=180)
    # DAL005 is severity "error" -> ::error workflow command
    assert "::error " in r.stdout and "title=DAL005" in r.stdout
    r = subprocess.run(base + ["--warn-unused-suppressions"],
                       capture_output=True, text=True, cwd=str(REPO),
                       timeout=180)
    assert r.returncode == 1 and "DAL100" in r.stdout


@pytest.mark.slow
def test_cli_changed_fast_mode(tmp_path):
    import os
    env = {**os.environ, "PYTHONPATH": str(REPO)}
    repo = tmp_path / "r"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", *args], cwd=str(repo), check=True,
                       capture_output=True, timeout=60)

    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    clean = repo / "clean.py"
    clean.write_text("from os import *\n")     # would fail a full lint
    git("add", "-A")
    git("commit", "-qm", "base")
    changed = repo / "changed.py"
    changed.write_text("from sys import *\n")
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis", "lint",
         "--changed", str(repo)],
        capture_output=True, text=True, cwd=str(repo), env=env,
        timeout=180)
    # only the new file is linted: one finding, the committed bad file
    # never scanned
    assert r.returncode == 1, r.stdout + r.stderr
    assert "changed.py" in r.stdout and "clean.py" not in r.stdout
    # a deleted tracked file appears in the diff but must be filtered
    # out, not linted into a DAL000 'unreadable file' error
    clean.unlink()
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis", "lint",
         "--changed", str(repo)],
        capture_output=True, text=True, cwd=str(repo), env=env,
        timeout=180)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "DAL000" not in r.stdout and "clean.py" not in r.stdout
    # an unresolvable merge base (typo'd --base, default branch outside
    # the fallback chain) must exit 2 — NOT lint only the uncommitted
    # files and report the committed bad ones as clean
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis", "lint",
         "--changed", "--base", "no-such-ref", str(repo)],
        capture_output=True, text=True, cwd=str(repo), env=env,
        timeout=180)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "no merge base" in r.stderr


@pytest.mark.slow
def test_cli_locks_verb(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import threading, time\n"
        "_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        time.sleep(1)\n")
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis",
         "locks", str(bad)], capture_output=True, text=True,
        cwd=str(REPO), timeout=180)
    assert r.returncode == 1 and "DAL008" in r.stdout
    bad.write_text(bad.read_text().replace(
        "time.sleep(1)",
        "time.sleep(1)  # dalint: disable=DAL008 — demo"))
    r = subprocess.run(
        [sys.executable, "-m", "distributedarrays_tpu.analysis",
         "locks", str(bad)], capture_output=True, text=True,
        cwd=str(REPO), timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
