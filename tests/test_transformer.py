"""Flagship transformer tests: flash-kernel attention with custom-VJP
gradients, Megatron tp layout, dp×tp training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributedarrays_tpu.models import transformer as T
from distributedarrays_tpu.models.mlp import make_mesh
from distributedarrays_tpu.ops.pallas_attention import (_dense_attention_shd,
                                                        flash_attention)


def test_flash_custom_vjp_exact(rng):
    # gradients through the kernel == gradients of the dense formulation
    S, H, D = 64, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
               for _ in range(3))

    def via_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=32, block_k=32) ** 2)

    def via_dense(q, k, v):
        return jnp.sum(_dense_attention_shd(q, k, v, True,
                                            float(1 / np.sqrt(D))) ** 2)

    gf = jax.grad(via_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(via_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        assert float(jnp.abs(a - b).max()) < 1e-5


@pytest.fixture(scope="module")
def trained():
    cfg = T.Config(vocab=32, dim=64, heads=4, layers=2, max_seq=32)
    mesh = make_mesh(8)
    params = T.shard_params(T.init_params(jax.random.key(0), cfg), mesh)
    start = jax.random.randint(jax.random.key(1), (16, 1), 0, 32)
    tokens = ((start + jnp.arange(32)[None]) % 32).astype(jnp.int32)
    tokens = jax.device_put(
        tokens, jax.NamedSharding(mesh, P("dp", None)))
    losses = []
    for _ in range(60):
        params, loss = T.train_step(params, tokens, jnp.float32(0.05), cfg)
        losses.append(float(loss))
    return cfg, mesh, params, tokens, losses


@pytest.mark.slow
def test_transformer_learns_counting(trained):
    cfg, mesh, params, tokens, losses = trained
    assert losses[-1] < 0.3 * losses[0], losses[::10]


@pytest.mark.slow
def test_transformer_predictions(trained):
    # after training, argmax next-token should mostly be (t+1) % vocab
    cfg, mesh, params, tokens, _ = trained
    logits = T.forward(params, tokens[:, :-1], cfg)
    pred = np.asarray(jnp.argmax(logits, axis=-1))
    want = np.asarray(tokens[:, 1:])
    acc = (pred == want).mean()
    assert acc > 0.8, acc


@pytest.mark.slow
def test_transformer_sharding_layout(trained):
    cfg, mesh, params, _, _ = trained
    b = params["blocks"][0]

    def axes(x):  # normalized (XLA may drop trailing Nones)
        s = tuple(x.sharding.spec)
        return s + (None,) * (x.ndim - len(s))

    assert axes(b["qkv"]) == (None, "tp")      # column-parallel
    assert axes(b["proj"]) == ("tp", None)     # row-parallel
    assert axes(b["w1"]) == (None, "tp")
    assert axes(b["w2"]) == ("tp", None)


def test_config_validation():
    with pytest.raises(ValueError, match="divisible"):
        T.Config(dim=65, heads=4)
    # value-hashable: equal configs share one jit compilation key
    assert T.Config() == T.Config()
    assert hash(T.Config()) == hash(T.Config())
