"""Flagship transformer tests: flash-kernel attention with custom-VJP
gradients, Megatron tp layout, dp×tp training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributedarrays_tpu.models import transformer as T
from distributedarrays_tpu.parallel.collectives import shard_map_compat
from distributedarrays_tpu.models.mlp import make_mesh
from distributedarrays_tpu.ops.pallas_attention import (_dense_attention_shd,
                                                        flash_attention)


def _axes(x):
    """Normalized sharding spec (XLA may drop trailing Nones)."""
    s = tuple(x.sharding.spec)
    return s + (None,) * (x.ndim - len(s))


def test_flash_custom_vjp_exact(rng):
    # gradients through the kernel == gradients of the dense formulation
    S, H, D = 64, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
               for _ in range(3))

    def via_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=32, block_k=32) ** 2)

    def via_dense(q, k, v):
        return jnp.sum(_dense_attention_shd(q, k, v, True,
                                            float(1 / np.sqrt(D))) ** 2)

    gf = jax.grad(via_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(via_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        assert float(jnp.abs(a - b).max()) < 1e-5


@pytest.fixture(scope="module")
def trained():
    cfg = T.Config(vocab=32, dim=64, heads=4, layers=2, max_seq=32)
    mesh = make_mesh(8)
    params = T.shard_params(T.init_params(jax.random.key(0), cfg), mesh)
    start = jax.random.randint(jax.random.key(1), (16, 1), 0, 32)
    tokens = ((start + jnp.arange(32)[None]) % 32).astype(jnp.int32)
    tokens = jax.device_put(
        tokens, jax.NamedSharding(mesh, P("dp", None)))
    losses = []
    for _ in range(60):
        params, loss = T.train_step(params, tokens, jnp.float32(0.05), cfg)
        losses.append(float(loss))
    return cfg, mesh, params, tokens, losses


@pytest.mark.slow
def test_transformer_learns_counting(trained):
    cfg, mesh, params, tokens, losses = trained
    assert losses[-1] < 0.3 * losses[0], losses[::10]


@pytest.mark.slow
def test_transformer_predictions(trained):
    # after training, argmax next-token should mostly be (t+1) % vocab
    cfg, mesh, params, tokens, _ = trained
    logits = T.forward(params, tokens[:, :-1], cfg)
    pred = np.asarray(jnp.argmax(logits, axis=-1))
    want = np.asarray(tokens[:, 1:])
    acc = (pred == want).mean()
    assert acc > 0.8, acc


@pytest.mark.slow
def test_transformer_sharding_layout(trained):
    cfg, mesh, params, _, _ = trained
    b = params["blocks"][0]

    assert _axes(b["qkv"]) == (None, "tp")      # column-parallel
    assert _axes(b["proj"]) == ("tp", None)     # row-parallel
    assert _axes(b["w1"]) == (None, "tp")
    assert _axes(b["w2"]) == ("tp", None)


def test_config_validation():
    with pytest.raises(ValueError, match="divisible"):
        T.Config(dim=65, heads=4)
    # value-hashable: equal configs share one jit compilation key
    assert T.Config() == T.Config()
    assert hash(T.Config()) == hash(T.Config())


# ---------------------------------------------------------------------------
# round-3: sequence-parallel transformer (models/sp_transformer.py) — ring
# flash attention + tp_ffn composed into one shard_map training program
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sp_setup():
    from distributedarrays_tpu.models import sp_transformer as SPT
    from distributedarrays_tpu.parallel import collectives as C
    p = 4
    mesh = C.spmd_mesh(p)
    cfg = SPT.SPConfig(vocab=64, dim=32, heads=4, layers=2, max_seq=32,
                       dtype=jnp.float32, block_q=8, block_k=8,
                       interpret=True)
    params = SPT.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab,
                                dtype=jnp.int32)
    return SPT, C, p, mesh, cfg, params, tokens


def _sp_dense_forward(cfg, params, tokens):
    """Dense single-device oracle for the sp forward."""
    B, S = tokens.shape
    E, H = cfg.dim, cfg.heads
    D = E // H
    x = params["embed"][tokens] + params["pos"][:S][None]
    for blk in params["blocks"]:
        h = T._rmsnorm(x, blk["ln1"])
        q, k, v = jnp.split(h @ blk["qkv"], 3, axis=-1)

        def heads_(t):
            return jnp.transpose(t.reshape(B, S, H, D), (0, 2, 1, 3))

        s = jnp.einsum("bhqd,bhkd->bhqk", heads_(q), heads_(k)) / np.sqrt(D)
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None],
                      s, -jnp.inf)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), heads_(v))
        x = x + jnp.transpose(o, (0, 2, 1, 3)).reshape(B, S, E) @ blk["proj"]
        h2 = T._rmsnorm(x, blk["ln2"])
        x = x + jax.nn.gelu(h2 @ blk["w1"]) @ blk["w2"]
    return (T._rmsnorm(x, params["ln_f"]) @ params["head"]).astype(
        jnp.float32)


def test_sp_transformer_forward_matches_dense(sp_setup):
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    fwd = jax.jit(shard_map_compat(
        lambda pr, t: SPT.forward_local(pr, t, cfg, "p"),
        mesh=mesh, in_specs=(SPT.param_specs(cfg, "p"), P(None, "p")),
        out_specs=P(None, "p"), check=False))
    got = np.asarray(fwd(params, tokens))
    want = np.asarray(_sp_dense_forward(cfg, params, tokens))
    assert np.abs(got - want).max() / np.abs(want).max() < 1e-4


def test_sp_transformer_loss_matches_dense_ce(sp_setup):
    # the cross-rank target shift + end mask must equal the dense
    # next-token CE (which simply drops the final position)
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    # dense CE first: the train step DONATES params (buffers are gone after)
    logp = jax.nn.log_softmax(_sp_dense_forward(cfg, params, tokens), -1)
    ll = jnp.take_along_axis(logp[:, :-1], tokens[:, 1:, None], axis=-1)
    want = float(-jnp.mean(ll))
    step = SPT.make_train_step(mesh, cfg)
    params = jax.tree_util.tree_map(jnp.copy, params)  # keep fixture alive
    _, loss = step(params, tokens, jnp.float32(0.0))
    assert abs(float(loss) - want) / want < 1e-4


@pytest.mark.slow
def test_sp_transformer_trains(sp_setup):
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    step = SPT.make_train_step(mesh, cfg)
    params = SPT.init_params(jax.random.key(2), cfg)
    losses = []
    for _ in range(8):
        params, l = step(params, tokens, jnp.float32(0.5))
        losses.append(float(l))
    assert losses[-1] < 0.7 * losses[0], losses
    assert all(np.isfinite(l) for l in losses)


def test_sp_transformer_picks_up_later_banked_tune(sp_setup, monkeypatch):
    # the train-step factories must resolve None hop knobs OUTSIDE their
    # cached jits (ADVICE round-4): a tune banked AFTER the first step
    # call must change the dispatched program, not be pinned at first
    # trace.  Resolution is spied at _resolve_cfg's registry consumer.
    from distributedarrays_tpu.utils import autotune
    from distributedarrays_tpu.models import ring_attention as RA
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    autotune.clear()
    tcfg = SPT.SPConfig(vocab=64, dim=32, heads=4, layers=2, max_seq=32,
                        dtype=jnp.float32, interpret=True)  # knobs None
    step = SPT.make_train_step(mesh, tcfg)
    prm = SPT.init_params(jax.random.key(3), tcfg)
    seen = []
    real = RA.tuned_hop_blocks_for

    def spy(shape, dtype, causal, bq, bk):
        out = real(shape, dtype, causal, bq, bk)
        seen.append(out)
        return out

    monkeypatch.setattr(RA, "tuned_hop_blocks_for", spy)
    prm, l0 = step(prm, tokens, jnp.float32(0.1))
    assert seen and seen[-1][:2] == (512, 512)   # default, nothing banked
    # bank a tune for the per-rank hop shape this model sees:
    # (s_loc, b*heads, head_dim) under causal=True
    B, S = tokens.shape
    key = autotune.device_key_for(S // p, B * tcfg.heads,
                                  tcfg.dim // tcfg.heads,
                                  jnp.dtype(tcfg.dtype), True)
    autotune.record("ring_flash", key, (4, 4))
    seen.clear()
    prm, l1 = step(prm, tokens, jnp.float32(0.1))
    assert seen and seen[-1][:2] == (4, 4), \
        "a tune banked after step 1 must reach the next step's dispatch"
    assert np.isfinite(float(l1))
    autotune.clear()


def test_sp_transformer_max_seq_guard(sp_setup):
    # position reads past the table would CLAMP silently; must raise
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    small = SPT.SPConfig(vocab=64, dim=32, heads=4, layers=2, max_seq=16,
                         dtype=jnp.float32, block_q=8, block_k=8,
                         interpret=True)
    sp = SPT.init_params(jax.random.key(0), small)
    with pytest.raises(ValueError, match="max_seq"):
        shard_map_compat(
            lambda pr, t: SPT.forward_local(pr, t, small, "p"),
            mesh=mesh, in_specs=(SPT.param_specs(small, "p"), P(None, "p")),
            out_specs=P(None, "p"), check=False)(sp, tokens)


def test_sp_transformer_zigzag_matches_dense(sp_setup):
    # load-balanced layout: tokens permuted by zigzag_order; logits
    # unpermute back to natural order and must match the dense oracle,
    # and the zigzag-aware CE shift must equal the dense next-token CE
    from distributedarrays_tpu.models.ring_attention import zigzag_order
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    zcfg = SPT.SPConfig(vocab=64, dim=32, heads=4, layers=2, max_seq=32,
                        dtype=jnp.float32, block_q=8, block_k=8,
                        interpret=True, zigzag=True)
    perm = np.asarray(zigzag_order(32, p))
    zz_tokens = jnp.asarray(np.asarray(tokens)[:, perm])
    fwd = jax.jit(shard_map_compat(
        lambda pr, t: SPT.forward_local(pr, t, zcfg, "p"),
        mesh=mesh, in_specs=(SPT.param_specs(zcfg, "p"), P(None, "p")),
        out_specs=P(None, "p"), check=False))
    got = np.asarray(fwd(params, zz_tokens))[:, np.argsort(perm)]
    want = np.asarray(_sp_dense_forward(zcfg, params, tokens))
    assert np.abs(got - want).max() / np.abs(want).max() < 1e-4

    logp = jax.nn.log_softmax(jnp.asarray(want), -1)
    ll = jnp.take_along_axis(logp[:, :-1], tokens[:, 1:, None], axis=-1)
    want_loss = float(-jnp.mean(ll))
    step = SPT.make_train_step(mesh, zcfg)
    pc = jax.tree_util.tree_map(jnp.copy, params)
    _, loss = step(pc, zz_tokens, jnp.float32(0.0))
    assert abs(float(loss) - want_loss) / want_loss < 1e-4


@pytest.mark.slow
def test_sp_transformer_zigzag_trains(sp_setup):
    from distributedarrays_tpu.models.ring_attention import zigzag_order
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    zcfg = SPT.SPConfig(vocab=64, dim=32, heads=4, layers=2, max_seq=32,
                        dtype=jnp.float32, block_q=4, block_k=4,
                        interpret=True, zigzag=True)
    perm = np.asarray(zigzag_order(32, p))
    zz_tokens = jnp.asarray(np.asarray(tokens)[:, perm])
    step = SPT.make_train_step(mesh, zcfg)
    prm = SPT.init_params(jax.random.key(3), zcfg)
    losses = []
    for _ in range(8):
        prm, l = step(prm, zz_tokens, jnp.float32(0.5))
        losses.append(float(l))
    assert losses[-1] < 0.7 * losses[0], losses
    assert all(np.isfinite(v) for v in losses)


@pytest.mark.slow
def test_sp_transformer_checkpoint_roundtrip(sp_setup, tmp_path):
    # training state (incl. the tp-sharded FFN weights produced by the
    # donated train step) must survive save/load and continue identically
    from distributedarrays_tpu.utils import load, save
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    step = SPT.make_train_step(mesh, cfg)
    prm = SPT.init_params(jax.random.key(4), cfg)
    for _ in range(2):
        prm, _ = step(prm, tokens, jnp.float32(0.1))
    save(tmp_path / "sp_ckpt", {"params": prm})
    back = load(tmp_path / "sp_ckpt")["params"]
    prm_l, loss_cont = step(jax.tree_util.tree_map(jnp.copy, prm),
                            tokens, jnp.float32(0.1))
    _, loss_restored = step(back, tokens, jnp.float32(0.1))
    assert float(loss_cont) == pytest.approx(float(loss_restored),
                                             rel=1e-6)


def test_sp_transformer_update_matches_dense_sgd(sp_setup):
    # one train step == dense value_and_grad SGD step, and every
    # REPLICATED param's device copies stay bit-identical after the
    # update (regression: check=False means the train step must
    # psum replicated-param grads itself; without it the copies diverge
    # and shard 0 hides it)
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    lr = 0.1

    def dense_loss(pp):
        logp = jax.nn.log_softmax(_sp_dense_forward(cfg, pp, tokens), -1)
        ll = jnp.take_along_axis(logp[:, :-1], tokens[:, 1:, None], axis=-1)
        return -jnp.mean(ll)

    g = jax.grad(dense_loss)(params)
    want = jax.tree_util.tree_map(lambda a, b: a - lr * b, params, g)

    step = SPT.make_train_step(mesh, cfg)
    got, _ = step(jax.tree_util.tree_map(jnp.copy, params), tokens,
                  jnp.float32(lr))
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(want)[0]):
        scale = max(float(jnp.abs(b).max()), 1e-6)
        err = float(jnp.abs(a - b).max()) / scale
        assert err < 1e-4, (jax.tree_util.keystr(k), err)
    # replicated leaves: all device copies agree bit-exactly
    for k, a in jax.tree_util.tree_flatten_with_path(got)[0]:
        spec = tuple(a.sharding.spec) if hasattr(a.sharding, "spec") else ()
        if all(s is None for s in spec):
            vals = [np.asarray(s.data) for s in a.addressable_shards]
            for v in vals[1:]:
                np.testing.assert_array_equal(vals[0], v,
                                              err_msg=jax.tree_util.keystr(k))


@pytest.mark.slow
def test_sp_transformer_optax_adamw(sp_setup):
    # real-optimizer training path: grads from the shard_map program,
    # Adam moments laid out by GSPMD to match each param (sharded FFN
    # moments stay sharded)
    optax = pytest.importorskip("optax")
    SPT, C, p, mesh, cfg, params, tokens = sp_setup
    tx = optax.adamw(3e-3)
    step, init = SPT.make_optax_train_step(mesh, cfg, tx)
    prm = SPT.init_params(jax.random.key(5), cfg)
    state = init(prm)
    losses = []
    for _ in range(10):
        prm, state, l = step(prm, state, tokens)
        losses.append(float(l))
    assert losses[-1] < 0.8 * losses[0], losses
    assert all(np.isfinite(v) for v in losses)
    # Adam mu for the column-sharded w1 must be sharded like w1 (and f32)
    mu_w1 = state[0].mu["blocks"][0]["w1"]
    assert _axes(mu_w1) == _axes(prm["blocks"][0]["w1"])
    assert mu_w1.dtype == jnp.float32


@pytest.mark.slow
def test_transformer_optax_adamw_sharded_moments():
    # GSPMD flagship with a real optimizer at the DEFAULT bf16 dtype:
    # the fp32 master-precision path must keep Adam-scale updates from
    # rounding away in bf16, moments must inherit the Megatron tp
    # sharding of their params, and training must converge
    optax = pytest.importorskip("optax")
    cfg = T.Config(vocab=32, dim=64, heads=4, layers=2, max_seq=32)
    assert cfg.dtype == jnp.bfloat16
    mesh = make_mesh(8)
    params = T.shard_params(T.init_params(jax.random.key(0), cfg), mesh)
    start = jax.random.randint(jax.random.key(1), (8, 1), 0, 32)
    tokens = ((start + jnp.arange(32)[None]) % 32).astype(jnp.int32)
    tokens = jax.device_put(tokens, jax.NamedSharding(mesh, P("dp", None)))
    tx = optax.adamw(3e-3)
    step, init = T.make_optax_train_step(cfg, tx)
    state = init(params)
    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < 0.8 * losses[0], losses
    assert params["blocks"][0]["w1"].dtype == jnp.bfloat16
    mu_w1 = state[0].mu["blocks"][0]["w1"]
    assert mu_w1.dtype == jnp.float32
    assert _axes(mu_w1) == _axes(params["blocks"][0]["w1"]) == (None, "tp")


# ---------------------------------------------------------------------------
# round-4: KV-cache autoregressive generation
# ---------------------------------------------------------------------------


def test_generate_greedy_matches_forward():
    # greedy decode must be self-consistent with the full forward: for
    # every generated position, forward(seq)'s argmax at t equals seq[t+1]
    cfg = T.Config(vocab=64, dim=32, heads=4, layers=2, max_seq=48,
                   dtype=jnp.float32)
    params = T.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab,
                                dtype=jnp.int32)
    seq = T.generate(params, prompt, 12, cfg)
    assert seq.shape == (2, 20)
    np.testing.assert_array_equal(np.asarray(seq[:, :8]),
                                  np.asarray(prompt))
    logits = T.forward(params, seq, cfg)
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    for t in range(7, 19):        # generated region
        np.testing.assert_array_equal(np.asarray(seq[:, t + 1]),
                                      greedy[:, t], err_msg=str(t))


def test_generate_sampling_and_validation():
    cfg = T.Config(vocab=32, dim=16, heads=2, layers=1, max_seq=16,
                   dtype=jnp.float32)
    params = T.init_params(jax.random.key(2), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    s1 = T.generate(params, prompt, 8, cfg, temperature=1.0,
                    key=jax.random.key(3))
    s2 = T.generate(params, prompt, 8, cfg, temperature=1.0,
                    key=jax.random.key(4))
    assert s1.shape == s2.shape == (1, 12)
    assert (np.asarray(s1) != np.asarray(s2)).any()   # different keys
    with pytest.raises(ValueError, match="max_seq"):
        T.generate(params, prompt, 100, cfg)
    with pytest.raises(ValueError, match="PRNG"):
        T.generate(params, prompt, 4, cfg, temperature=0.5)
