"""Physical storage of uneven layouts (VERDICT round-1 item 2).

The reference stores uneven chunks distributed (darray.jl:279-296,
test/darray.jl:61-67).  Round 1 replicated any non-divisible dimension
across its mesh axis; now uneven DArrays are stored blocked-padded — one
(max-chunk-sized) block per device — so at-rest HBM is ~1/grid per device.
These tests pin that via ``addressable_shards`` sizes plus the semantics
around the pad (localpart, set_localpart, scalar reads, reductions).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import layout as L


def test_uneven_1d_storage_is_distributed(rng):
    # defaultdist(50, 4): logical chunks 13,13,12,12 -> blocks of 13
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A, procs=[0, 1, 2, 3], dist=[4])
    shard_sizes = {s.data.shape for s in d.garray_padded.addressable_shards}
    assert shard_sizes == {(13,)}, shard_sizes
    # four distinct devices each hold one block — not a 50-replica each
    devs = {s.device for s in d.garray_padded.addressable_shards}
    assert len(devs) == 4
    np.testing.assert_allclose(np.asarray(d), A)
    d.close()


def test_uneven_2d_storage(rng):
    A = rng.standard_normal((50, 30)).astype(np.float32)
    d = dat.distribute(A, dist=[4, 2])
    sizes = {s.data.shape for s in d.garray_padded.addressable_shards}
    assert sizes == {(13, 15)}, sizes
    np.testing.assert_allclose(np.asarray(d), A)
    d.close()


def test_uneven_localpart_hits_addressable_shard(rng):
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A, procs=[0, 1, 2, 3], dist=[4])
    cuts = d.cuts[0]
    assert cuts == [0, 13, 26, 38, 50]  # reference leading-remainder cuts
    for k in range(4):
        lp = d.localpart(k)
        assert lp.shape == (cuts[k + 1] - cuts[k],)
        np.testing.assert_allclose(np.asarray(lp), A[cuts[k]:cuts[k + 1]])
        # fast path: the chunk must come off ONE device, not a gather
        assert len(lp.devices()) == 1
    d.close()


def test_uneven_set_localpart_and_pad_stays_zero(rng):
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A, procs=[0, 1, 2, 3], dist=[4])
    new2 = np.full(12, 7.0, dtype=np.float32)
    d.set_localpart(new2, pid=2)
    B = np.asarray(d)
    np.testing.assert_allclose(B[26:38], new2)
    np.testing.assert_allclose(B[:26], A[:26])
    np.testing.assert_allclose(B[38:], A[38:])
    # the pad region must still be zero so sums over the padded buffer of
    # future ops can't be polluted
    padded = np.asarray(jax.device_get(d.garray_padded))
    assert padded.shape == (52,)
    np.testing.assert_allclose(padded[26 + 12:39], 0.0)  # block 2's pad row
    d.close()


def test_uneven_scalar_read(rng):
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A, dist=[4])
    with dat.allowscalar(True):
        for i in (0, 12, 13, 37, 38, 49):
            assert float(d[i]) == A[i]
    d.close()


def test_uneven_reductions_ignore_pad(rng):
    A = (rng.standard_normal((50, 6)) + 3.0).astype(np.float32)  # strictly >0
    d = dat.distribute(A, dist=[4, 2])
    assert np.allclose(float(dat.dsum(d)), A.sum(), rtol=1e-4)
    assert np.allclose(float(dat.dminimum(d)), A.min())  # pad zeros invisible
    r = dat.dsum(d, dims=0)
    np.testing.assert_allclose(np.asarray(r), A.sum(0, keepdims=True),
                               rtol=1e-4)
    d.close()


def test_uneven_elementwise_roundtrip(rng):
    A = rng.standard_normal(50).astype(np.float32)
    d = dat.distribute(A, dist=[4])
    r = dat.dmap(jnp.cos, d) + d * 2.0
    np.testing.assert_allclose(np.asarray(r), np.cos(A) + A * 2.0, rtol=1e-5)
    # the result is again physically blocked (storage stays ~1/grid)
    assert {s.data.shape for s in r.garray_padded.addressable_shards} == {(13,)}
    dat.d_closeall()


def test_uneven_fill_and_rand(rng):
    d = dat.distribute(rng.standard_normal(50).astype(np.float32), dist=[4])
    d.fill_(5.0)
    np.testing.assert_allclose(np.asarray(d), 5.0)
    padded = np.asarray(jax.device_get(d.garray_padded))
    # block 3 = padded[39:52], valid extent 12 (chunk [38,50)) -> pad [51:52]
    np.testing.assert_allclose(padded[51:52], 0.0)
    d.rand_()
    v = np.asarray(d)
    assert v.shape == (50,) and len(np.unique(v)) > 10
    d.close()


def test_even_layout_has_no_padding(rng):
    d = dat.distribute(rng.standard_normal((48, 8)).astype(np.float32))
    assert d.garray_padded is d.garray  # no separate padded buffer
    d.close()


def test_empty_chunks_more_ranks_than_elems():
    # sz < nc: leading singleton chunks, trailing empty (defaultdist_1d)
    A = np.arange(3, dtype=np.float32)
    d = dat.distribute(A, procs=list(range(8)), dist=[8])
    np.testing.assert_allclose(np.asarray(d), A)
    assert d.localpart(7).shape == (0,)
    assert d.localpart(1).shape == (1,)
    assert float(dat.dsum(d)) == 3.0
    d.close()


def test_from_chunks_irregular_sizes_distributed(rng):
    # from_chunks builds arbitrary cut vectors (e.g. sort results)
    parts = [rng.standard_normal(n).astype(np.float32) for n in (5, 9, 2, 4)]
    d = dat.from_chunks(parts, procs=[0, 1, 2, 3])
    np.testing.assert_allclose(np.asarray(d), np.concatenate(parts))
    sizes = {s.data.shape for s in d.garray_padded.addressable_shards}
    assert sizes == {(9,)}  # block size = max chunk
    for k, p in enumerate(parts):
        np.testing.assert_allclose(np.asarray(d.localpart(k)), p)
    d.close()
