"""Smoke-run the fast examples as subprocesses — they are the user-facing
surface and have caught bugs the unit suite missed (see docs/design.md)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

EXAMPLES = Path(__file__).resolve().parents[1] / "examples"

# examples cheap enough for the CI smoke loop (the sp training demo stays:
# it is the only end-to-end run of both sp layouts as a user would launch
# them); the big training demos are exercised by their own suites
FAST = ["quickstart.py", "life.py", "spmd_ring.py", "kmeans_demo.py",
        "cg_poisson.py", "tp_overlap_demo.py", "sp_train_demo.py",
        "spectral_poisson.py", "grid_gemm_demo.py"]



pytestmark = pytest.mark.slow  # fuzz/subprocess-heavy: full run in CI (--runslow)

@pytest.mark.parametrize("script", FAST)
def test_example_runs(script):
    env = dict(os.environ, EXAMPLES_FORCE_CPU="1")
    r = subprocess.run([sys.executable, str(EXAMPLES / script)],
                       capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    assert r.stdout.strip(), f"{script} produced no output"
    if script == "cg_poisson.py":
        # a convergence regression in the stencil/BLAS-1 stack must fail
        # loudly, not just print a different message
        assert "CG converged in" in r.stdout, r.stdout
