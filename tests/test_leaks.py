"""Lifecycle / leak-checking discipline.

The reference treats leak checking as a first-class invariant: REFS and
REGISTRY must end empty on every process (test/runtests.jl:28-37,
test/darray.jl:1079-1086).  Here the equivalents are: the registry must
self-clean when DArrays become unreachable (finalizers), close() must
actually drop device buffers, and ops must not leave stray registry entries
beyond the arrays they return."""

import gc

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat


def _n_live_buffers():
    return len([a for a in jax.live_arrays() if not a.is_deleted()])


def test_registry_self_cleans_on_gc():
    base = set(dat.live_ids())
    def scope():
        ds = [dat.dzeros((8, 8)) for _ in range(4)]
        assert len(dat.live_ids()) >= len(base) + 4
        return None
    scope()
    gc.collect()
    assert set(dat.live_ids()) == base


def test_close_frees_device_buffers():
    before = _n_live_buffers()
    d = dat.drand((64, 64))
    mid = _n_live_buffers()
    assert mid > before
    d.close()
    assert _n_live_buffers() < mid


def test_ops_do_not_leak_registry_entries(rng):
    A = rng.standard_normal((32, 16)).astype(np.float32)
    d = dat.distribute(A)
    base = len(dat.live_ids())
    r = dat.dmap(jnp.sin, d) + d          # two temporaries, one kept result
    _ = float(dat.dsum(r))                # scalar result: no registry entry
    gc.collect()
    # only d and r (plus nothing else) may remain
    assert len(dat.live_ids()) <= base + 1


def test_double_close_and_closed_errors():
    d = dat.dzeros((4, 4))
    d.close()
    d.close()  # idempotent
    for op in (lambda: d.copy(), lambda: d.reshape(16), lambda: d.garray,
               lambda: d.astype(jnp.float64), lambda: d.localpart()):
        with pytest.raises(RuntimeError, match="closed"):
            op()
    # whole-array equality on a closed array also raises cleanly
    with pytest.raises(RuntimeError, match="closed"):
        d == np.zeros((4, 4), np.float32)


def test_d_closeall_scales():
    ds = [dat.dzeros((4,)) for _ in range(20)]
    assert len(dat.live_ids()) >= 20
    dat.d_closeall()
    assert dat.live_ids() == []
    assert all(d._closed for d in ds)
