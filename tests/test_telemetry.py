"""Telemetry subsystem tests: counter/journal correctness under threads,
byte-accounting sanity for known transfers, disabled-mode zero-overhead,
CLI summary round-trip, fallback-site counting, hierarchical span
tracing (nesting, cross-thread isolation, comm attribution, Perfetto and
Prometheus export, journal size cap), and the end-to-end
scripted-workload acceptance check (distribute → matmul → copyto_
reshard → gather → checkpoint.save, ≥95% of comm bytes span-attributed)."""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import telemetry
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)
from distributedarrays_tpu.telemetry.summarize import (read_journal,
                                                       summarize)

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_counters_gauges_histograms(telemetry_capture):
    tm = telemetry_capture
    tm.count("x")
    tm.count("x", 2)
    tm.count("x", kernel="k1")
    assert tm.counter_value("x") == 3
    assert tm.counter_value("x", kernel="k1") == 1
    assert tm.counter_value("never") == 0
    tm.set_gauge("g", 7.5)
    assert tm.gauge_value("g") == 7.5
    for v in (1.0, 3.0, 2.0):
        tm.observe("h", v)
    h = tm.report()["histograms"]["h"]
    assert h["count"] == 3 and h["min"] == 1.0 and h["max"] == 3.0
    assert abs(h["mean"] - 2.0) < 1e-12


def test_thread_safety_counters_and_journal(telemetry_capture):
    tm = telemetry_capture
    NT, NC, NE = 8, 500, 25

    def worker(i):
        for _ in range(NC):
            tm.count("threads.c")
        for j in range(NE):
            tm.event("threadtest", "e", worker=i, j=j)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(NT)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tm.counter_value("threads.c") == NT * NC
    evs = tm.events("threadtest")
    assert len(evs) == NT * NE
    seqs = [e["seq"] for e in evs]
    assert len(set(seqs)) == len(seqs), "duplicate journal seq under threads"
    ts = [e["t"] for e in sorted(evs, key=lambda e: e["seq"])]
    assert all(b >= a for a, b in zip(ts, ts[1:])), \
        "journal timestamps not monotone"


def test_journal_file_is_append_only_jsonl(telemetry_capture):
    tm = telemetry_capture
    path = tm.journal_path()
    tm.event("cat1", "a", k=1)
    tm.event("cat1", "b", k=2)
    lines = Path(path).read_text().splitlines()
    assert len(lines) == 2
    recs = [json.loads(l) for l in lines]
    assert [r["name"] for r in recs] == ["a", "b"]
    assert recs[1]["t"] >= recs[0]["t"]
    assert recs[1]["seq"] > recs[0]["seq"]


def test_once_key_dedups_journal_not_counters(telemetry_capture):
    tm = telemetry_capture
    for _ in range(5):
        tm.record_comm("spmdtest", 10, op="x", once_key="only-once")
    assert len(tm.events("comm")) == 1
    assert tm.comm_bytes("spmdtest") == 50  # counters saw all 5


# ---------------------------------------------------------------------------
# disabled mode
# ---------------------------------------------------------------------------


def test_disabled_mode_zero_events_near_zero_overhead(telemetry_capture,
                                                      tmp_path):
    tm = telemetry_capture
    tm.reset()
    tm.configure(str(tmp_path / "never.jsonl"))
    tm.disable()
    t0 = time.perf_counter()
    for _ in range(50_000):
        tm.count("hot", n=1, kernel="x")
        tm.record_comm("reshard", 123, journal=True)
        tm.event("cat", "n", k=1)
        with tm.span("hot.span", kernel="x"):
            pass
    elapsed = time.perf_counter() - t0
    r = tm.report()
    assert r["enabled"] is False
    assert r["counters"] == {} and r["comm"]["total_bytes"] == 0
    assert r["events"]["recorded"] == 0
    assert r["spans"]["finished"] == 0 and r["spans"]["by_name"] == {}
    assert not (tmp_path / "never.jsonl").exists(), \
        "disabled telemetry must never create a journal file"
    # 200k no-op calls; generous bound — this is a smoke check that the
    # disabled path is a flag test, not a lock acquisition
    assert elapsed < 2.5, f"disabled-mode overhead too high: {elapsed:.3f}s"
    tm.enable()
    tm.count("hot")
    assert tm.counter_value("hot") == 1


# ---------------------------------------------------------------------------
# byte accounting
# ---------------------------------------------------------------------------


def test_reshard_byte_accounting_known_copyto(telemetry_capture):
    tm = telemetry_capture
    # row layout → column layout via copyto_: ONE reshard whose recorded
    # bytes are the planner's MOVED bytes — the (p-1)/p fraction that must
    # cross a device boundary in an even p-way repartition — not the whole
    # 16*8*4-byte array (the pre-planner accounting)
    src = dat.distribute(np.zeros((16, 8), np.float32), dist=(8, 1))
    dest = dat.dzeros((16, 8), dist=(1, 8))
    ops0 = tm.report()["comm"]["by_kind"].get("reshard", {}).get("ops", 0)
    b0 = tm.comm_bytes("reshard")
    dat.copyto_(dest, src)
    total = 16 * 8 * 4
    assert tm.comm_bytes("reshard") - b0 == total * 7 // 8
    by_kind = tm.report()["comm"]["by_kind"]
    assert by_kind["reshard"]["ops"] - ops0 == 1
    assert tm.counter_value("op.copyto_") == 1


def test_h2d_and_d2h_byte_accounting(telemetry_capture):
    tm = telemetry_capture
    a = np.ones((32, 4), np.float32)
    d = dat.distribute(a)
    assert tm.comm_bytes("h2d") == a.nbytes
    _ = np.asarray(d)
    assert tm.comm_bytes("d2h") == a.nbytes


def test_nbytes_of():
    assert telemetry.nbytes_of(np.zeros((4, 4), np.float32)) == 64
    assert telemetry.nbytes_of(jnp.zeros((2, 2), jnp.int32)) == 16
    assert telemetry.nbytes_of(b"abcd") == 4
    assert telemetry.nbytes_of(object()) == 0


# ---------------------------------------------------------------------------
# fallback sites (former warn_once-only degradations)
# ---------------------------------------------------------------------------


def test_warn_once_site_counts_exactly_once_per_trigger(telemetry_capture,
                                                        recwarn):
    from distributedarrays_tpu.utils.debug import warn_once
    tm = telemetry_capture
    warn_once("telemetrytest-site", "degraded")
    # assert_counter returns the observed value, so exactness is kept
    assert tm.assert_counter("fallback.hits",
                             key="telemetrytest-site") == 1
    assert len(tm.events("fallback")) == 1
    # a second hit of the same site: counted (hits are per-occurrence),
    # journaled and warned only once
    warn_once("telemetrytest-site", "degraded")
    assert tm.assert_counter("fallback.hits", 2,
                             key="telemetrytest-site") == 2
    assert len(tm.events("fallback")) == 1


def test_real_fallback_site_increments_counter(telemetry_capture):
    # dreduce host fallback: an untraceable binary op takes the documented
    # host-fold path and must surface as a counted fallback event
    tm = telemetry_capture
    d = dat.distribute(np.arange(8, dtype=np.float32))

    def opaque(a, b):          # concretizes → cannot trace
        return a + b if float(np.asarray(a).reshape(-1)[0]) >= -1e30 else b

    with pytest.warns(RuntimeWarning):
        dat.dreduce(opaque, d)
    hits = {k: v for k, v in tm.report()["counters"].items()
            if k.startswith("fallback.hits{key=dreduce-host-")}
    assert list(hits.values()) == [1], hits
    assert len(tm.events("fallback")) == 1


# ---------------------------------------------------------------------------
# CLI / summarize round-trip
# ---------------------------------------------------------------------------


def test_cli_summary_roundtrips_journal(telemetry_capture, capsys):
    tm = telemetry_capture
    tm.record_comm("reshard", 1024, op="rebind")
    tm.record_comm("h2d", 256, op="device_put")
    tm.event("jit", "build", fn="f")
    path = tm.journal_path()
    s = summarize(read_journal(path))
    assert s["events"] == 3
    assert s["comm"]["total_bytes"] == 1280
    assert s["comm"]["by_kind"]["reshard"]["ops"] == 1
    assert s["by_category"] == {"comm": 2, "jit": 1}
    from distributedarrays_tpu.telemetry.__main__ import main
    assert main([path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out == s
    assert main([path]) == 0
    text = capsys.readouterr().out
    assert "reshard" in text and "1.2 KiB" in text


def test_read_journal_tolerates_torn_line(tmp_path):
    p = tmp_path / "j.jsonl"
    p.write_text('{"cat": "comm", "name": "h2d", "bytes": 4, "t": 0.1}\n'
                 '{"cat": "comm", "na')          # torn mid-write
    evs = read_journal(str(p))
    s = summarize(evs)
    assert s["comm"]["total_bytes"] == 4
    assert s["by_category"]["_journal"] == 1     # malformed-line marker


def test_report_dump_roundtrip(telemetry_capture, tmp_path):
    tm = telemetry_capture
    tm.count("a")
    out = tm.dump(str(tmp_path / "report.json"))
    loaded = json.loads(Path(out).read_text())
    assert loaded["counters"]["a"] == 1
    assert loaded["enabled"] is True


# ---------------------------------------------------------------------------
# instrumented subsystems
# ---------------------------------------------------------------------------


def test_autotune_hit_miss_events(telemetry_capture):
    from distributedarrays_tpu.utils import autotune
    tm = telemetry_capture
    key = "telemetry|test|key"
    assert autotune.get("telemetry_test_kernel", key) is None
    assert tm.counter_value("autotune.miss",
                            kernel="telemetry_test_kernel") == 1
    assert len(tm.events("autotune")) == 1
    autotune.record("telemetry_test_kernel", key, [1, 2, 3])
    assert autotune.get("telemetry_test_kernel", key) == [1, 2, 3]
    assert tm.counter_value("autotune.hit",
                            kernel="telemetry_test_kernel") == 1
    # repeated misses: counted every time, journaled once
    autotune.get("telemetry_test_kernel", "other|key")
    autotune.get("telemetry_test_kernel", "other|key")
    assert tm.counter_value("autotune.miss",
                            kernel="telemetry_test_kernel") == 3
    assert len(tm.events("autotune")) == 2


def test_checkpoint_phase_events(telemetry_capture, tmp_path):
    from distributedarrays_tpu.utils import checkpoint
    tm = telemetry_capture
    d = dat.distribute(np.arange(16, dtype=np.float32))
    checkpoint.save(tmp_path / "ckpt", {"d": d})
    restored = checkpoint.load(tmp_path / "ckpt")
    assert np.allclose(np.asarray(restored["d"]), np.asarray(d))
    names = [e.get("name") for e in tm.events("checkpoint")]
    assert names == ["save_start", "save_end", "restore_start",
                     "restore_end"]
    end = tm.events("checkpoint")[1]
    assert end["bytes"] == 64 and end["arrays"] == 1
    assert tm.assert_counter("checkpoint.saves") == 1
    assert tm.assert_counter("checkpoint.restores") == 1


def test_collectives_rec_is_counted_and_flagged_traced(telemetry_capture):
    # unit-level: the shared trace-time recorder the collective wrappers
    # call — runs regardless of whether this jax build has jax.shard_map
    from distributedarrays_tpu.parallel import collectives as C
    tm = telemetry_capture
    C._rec("all_gather", np.zeros((4, 2), np.float32), "p", op="pgather")
    evs = tm.events("comm")
    assert len(evs) == 1 and evs[0]["traced"] is True
    assert evs[0]["axis"] == "p" and evs[0]["bytes"] == 32
    assert tm.comm_bytes("all_gather") == 32


def test_collectives_record_traced_comm(telemetry_capture):
    import jax
    from distributedarrays_tpu.parallel import collectives as C
    from jax.sharding import PartitionSpec as P
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable in this jax build "
                    "(run_spmd is broken at seed on this environment)")
    tm = telemetry_capture
    mesh = C.spmd_mesh(4)
    fn = C.run_spmd(lambda x: C.pshift(x, "p"), mesh,
                    in_specs=P("p"), out_specs=P("p"))
    x = jnp.arange(8, dtype=jnp.float32)
    np.asarray(fn(x))          # trace + run
    evs = [e for e in tm.events("comm") if e.get("name") == "ppermute"]
    assert len(evs) == 1 and evs[0]["traced"] is True
    assert evs[0]["axis"] == "p" and evs[0]["bytes"] == 2 * 4  # per-rank block
    # counted once per trace (>= one 8-byte record; lowering may re-enter)
    b = tm.comm_bytes("ppermute")
    assert b >= 8 and b % 8 == 0


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------


def test_span_nesting_ids_and_selftime(telemetry_capture):
    tm = telemetry_capture
    with tm.span("outer", phase="p") as outer:
        assert tm.current_span() is outer
        assert tm.current_span_id() == outer.span_id
        with tm.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            time.sleep(0.02)
    assert tm.current_span() is None
    stats = tm.span_stats()
    assert stats["outer"]["count"] == 1 and stats["inner"]["count"] == 1
    # child time is subtracted from the parent's self time
    assert stats["inner"]["total_s"] >= 0.02
    assert stats["outer"]["total_s"] >= stats["inner"]["total_s"]
    assert stats["outer"]["self_s"] < stats["inner"]["total_s"]
    # journal mirror: one "span" event per finished span, child first
    evs = tm.events("span")
    assert [e["name"] for e in evs] == ["inner", "outer"]
    assert evs[0]["parent_id"] == evs[1]["span_id"]
    assert evs[1]["parent_id"] is None
    # report section: rankings present, labels preserved on the buffer
    sec = tm.report()["spans"]
    assert sec["finished"] == 2
    assert sec["top_by_total_s"][0][0] == "outer"
    assert sec["top_by_self_s"][0][0] == "inner"
    assert tm.spans("outer")[0]["labels"] == {"phase": "p"}


def test_traced_decorator(telemetry_capture):
    tm = telemetry_capture

    @tm.traced
    def plain():
        return 1

    @tm.traced(name="renamed", kind="k")
    def named():
        return 2

    assert plain() == 1 and named() == 2
    # bare form names the span after the function's qualname
    names = {s["name"] for s in tm.spans()}
    assert any(n.endswith("plain") for n in names), names
    assert len(tm.spans("renamed")) == 1
    assert tm.spans("renamed")[0]["labels"] == {"kind": "k"}


def test_span_comm_and_event_attribution(telemetry_capture):
    tm = telemetry_capture
    with tm.span("phase") as sp:
        tm.record_comm("reshard", 100, op="x")
        tm.event("misc", "note")
        with tm.span("sub"):
            tm.record_comm("h2d", 50)
    evs = {e["name"]: e for e in tm.events("comm")}
    assert evs["reshard"]["span_id"] == sp.span_id
    assert evs["h2d"]["span_id"] != sp.span_id   # innermost span wins
    assert [e for e in tm.events("misc")][0]["span_id"] == sp.span_id
    stats = tm.span_stats()
    assert stats["phase"]["bytes"] == 100        # own bytes only
    assert stats["phase"]["child_bytes"] == 50   # child rollup
    assert stats["sub"]["bytes"] == 50


def test_journal_span_ids_resolve_and_child_bytes_roll_up(telemetry_capture):
    # comm inside an aggregate-only (_journal=False) span must journal
    # with the nearest JOURNALED ancestor's span_id (no dangling refs),
    # and the ancestor's span event must carry the rolled-up child bytes
    tm = telemetry_capture
    with tm.span("outer"):
        with tm.span("agg", _journal=False):
            tm.record_comm("h2d", 64)
    journal = read_journal(tm.journal_path())
    span_evs = [e for e in journal if e.get("cat") == "span"]
    assert [e["name"] for e in span_evs] == ["outer"], span_evs
    comm_evs = [e for e in journal if e.get("cat") == "comm"]
    assert comm_evs[0]["span_id"] == span_evs[0]["span_id"]
    assert span_evs[0]["bytes"] == 0 and span_evs[0]["child_bytes"] == 64
    # in-process stats keep the innermost attribution
    assert tm.span_stats()["agg"]["bytes"] == 64
    assert tm.span_stats()["outer"]["child_bytes"] == 64
    # offline summarize credits the journaled span with the rollup
    s = summarize(journal)
    assert s["spans"]["outer"]["bytes"] == 64


def test_span_no_cross_thread_parent_leakage(telemetry_capture):
    tm = telemetry_capture
    seen = {}

    def worker(i):
        with tm.span(f"w{i}") as sp:
            seen[i] = sp.parent_id
            with tm.span(f"w{i}.child") as c:
                seen[(i, "child")] = c.parent_id == sp.span_id

    with tm.span("main-open"):
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    # worker roots must NOT inherit the main thread's open span...
    assert all(seen[i] is None for i in range(4)), seen
    # ...but nesting within each worker thread still works
    assert all(seen[(i, "child")] for i in range(4))


def test_fixture_assert_span_helper(telemetry_capture):
    tm = telemetry_capture
    with tm.span("covered"):
        pass
    got = tm.assert_span("covered")
    assert got[0]["name"] == "covered"
    with pytest.raises(AssertionError, match="covered"):
        tm.assert_span("missing-span")
    with pytest.raises(AssertionError):
        tm.assert_span("covered", min_count=2)


def test_ops_open_spans(telemetry_capture):
    tm = telemetry_capture
    d = dat.distribute(np.arange(16, dtype=np.float32))
    tm.assert_span("distribute")
    dat.dreduce("sum", d)
    tm.assert_span("mapreduce")
    tm.assert_span("mapreduce.reduce")
    dat.gather(d)
    tm.assert_span("gather")
    # every distribute's comm lands inside a span
    for e in tm.events("comm"):
        assert e.get("span_id") is not None, e


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_perfetto_export_roundtrip(telemetry_capture, tmp_path, capsys):
    tm = telemetry_capture
    with tm.span("outer"):
        tm.record_comm("reshard", 256, op="x")
        with tm.span("inner"):
            pass
    path = tm.journal_path()
    # library round-trip
    trace = tm.to_perfetto(read_journal(path))
    assert trace["traceEvents"], "empty trace"
    for e in trace["traceEvents"]:
        for key in ("ph", "ts", "dur", "pid", "tid"):
            assert key in e, (key, e)
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"outer", "inner", "comm/reshard"} <= names
    spans_x = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {s["name"] for s in spans_x} == {"outer", "inner"}
    inner, = (s for s in spans_x if s["name"] == "inner")
    outer, = (s for s in spans_x if s["name"] == "outer")
    assert outer["ts"] <= inner["ts"]
    assert outer["args"]["bytes"] == 256
    # CLI round-trip: trace subcommand, file output then json.load
    from distributedarrays_tpu.telemetry.__main__ import main
    out_file = tmp_path / "trace.json"
    assert main(["trace", path, "-o", str(out_file)]) == 0
    loaded = json.loads(out_file.read_text())
    assert loaded == json.loads(json.dumps(trace))  # identical conversion
    # stdout variant
    assert main(["trace", path]) == 0
    assert json.loads(capsys.readouterr().out)["traceEvents"]


_PROM_LINE = __import__("re").compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? -?[0-9.eE+-]+)$")


def _check_prom(text):
    """Minimal Prometheus text-exposition line checker."""
    assert text.endswith("\n")
    for line in text.splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"


def test_prometheus_export_format_and_values(telemetry_capture, tmp_path,
                                             capsys):
    tm = telemetry_capture
    tm.count("op.matmul", 3)
    tm.count("fallback.hits", key="some-site")
    tm.set_gauge("pool.size", 7)
    tm.observe("optimer.step", 0.5)
    tm.observe("optimer.step", 1.5)
    tm.record_comm("reshard", 1024, op="x")
    with tm.span("phase"):
        tm.record_comm("h2d", 10)
    text = tm.to_prometheus(tm.report())
    _check_prom(text)
    assert "da_tpu_op_matmul_total 3" in text
    assert 'da_tpu_fallback_hits_total{key="some-site"} 1' in text
    assert "da_tpu_pool_size 7" in text
    assert "da_tpu_optimer_step_count 2" in text
    assert "da_tpu_optimer_step_sum 2" in text
    assert 'da_tpu_comm_bytes_total{kind="reshard"} 1024' in text
    assert 'da_tpu_span_bytes_total{span="phase"} 10' in text
    # CLI: prom subcommand over a dump()ed report, and over the journal
    from distributedarrays_tpu.telemetry.__main__ import main
    rep_path = tm.dump(str(tmp_path / "report.json"))
    assert main(["prom", rep_path]) == 0
    out = capsys.readouterr().out
    _check_prom(out)
    assert "da_tpu_op_matmul_total 3" in out
    assert main(["prom", tm.journal_path()]) == 0
    out = capsys.readouterr().out
    _check_prom(out)
    assert 'da_tpu_comm_bytes_total{kind="reshard"} 1024' in out


def test_prometheus_label_value_with_commas(telemetry_capture):
    # fallback keys embed tuple reprs ("dfft-host-(2, 2)-..."): the
    # registry key's unescaped commas must not shred the label value
    tm = telemetry_capture
    tm.count("fallback.hits", key="dfft-host-(2, 2)-2-(0, 1)")
    tm.count("multi", a="x,y", kernel="k")
    text = tm.to_prometheus(tm.report())
    _check_prom(text)
    assert ('da_tpu_fallback_hits_total'
            '{key="dfft-host-(2, 2)-2-(0, 1)"} 1') in text
    assert 'da_tpu_multi_total{a="x,y",kernel="k"} 1' in text


# ---------------------------------------------------------------------------
# journal size cap
# ---------------------------------------------------------------------------


def test_journal_size_cap_rotates_not_stops(telemetry_capture,
                                            tmp_path, monkeypatch):
    tm = telemetry_capture
    monkeypatch.setenv("DA_TPU_TELEMETRY_JOURNAL_MAX_MB", "0.001")  # ~1 KiB
    path = tmp_path / "rotating.jsonl"
    tm.configure(str(path))
    for i in range(200):
        tm.event("filler", "e", i=i, payload="x" * 64)
    # the cap ROTATES: the full file moved to <path>.1 and mirroring
    # continued into a fresh file whose first line is one rotated marker
    sibling = tmp_path / "rotating.jsonl.1"
    assert sibling.exists(), "cap did not rotate to <path>.1"
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert recs[0]["cat"] == "journal" and recs[0]["name"] == "rotated"
    assert recs[0]["rotated_to"] == str(sibling)
    assert not any(r.get("name") == "capped" for r in recs)
    # mirroring continues after rotation — the tiny cap may rotate again
    # during these writes, so look for the new events across BOTH
    # generations rather than asserting the live file grew
    for i in range(5):
        tm.event("filler", "post", i=i)
    on_disk = path.read_text() + sibling.read_text()
    post = [json.loads(l) for l in on_disk.splitlines()
            if '"post"' in l and json.loads(l).get("name") == "post"]
    assert {r["i"] for r in post} == set(range(5)), \
        "mirroring stopped after rotation"
    # in-memory recording sees everything
    assert len(tm.events("filler")) == 205
    rep = tm.report()["events"]
    assert rep["journal_capped"] is False
    assert rep["journal_rotations"] >= 1
    # the CLI reader auto-picks the rotated sibling: both generations
    # appear in one summarize pass
    from distributedarrays_tpu.telemetry.__main__ import _read_events
    merged = _read_events(str(path))
    live = [r for r in merged if r.get("cat") == "filler"]
    assert len(live) > len([r for r in recs if r.get("cat") == "filler"])
    # reconfiguring clears the rotation counter
    tm.configure(str(tmp_path / "fresh.jsonl"))
    tm.event("filler", "fresh")
    assert (tmp_path / "fresh.jsonl").exists()
    assert tm.report()["events"]["journal_rotations"] == 0


# ---------------------------------------------------------------------------
# summarize: traced/eager split, fallback keys, span rollups
# ---------------------------------------------------------------------------


def test_summarize_traced_eager_split_and_fallbacks(telemetry_capture,
                                                    capsys):
    tm = telemetry_capture
    tm.record_comm("all_gather", 100, axis="p", traced=True)
    tm.record_comm("all_gather", 60, axis="p", traced=True)
    tm.record_comm("reshard", 1000, op="rebind")
    tm.event("fallback", "site-a", message="m")
    tm.event("fallback", "site-b", message="m")
    tm.event("fallback", "site-b", message="m")
    with tm.span("work"):
        pass
    s = summarize(read_journal(tm.journal_path()))
    ag = s["comm"]["by_kind"]["all_gather"]
    assert ag["traced_ops"] == 2 and ag["traced_bytes"] == 160
    assert ag["eager_ops"] == 0 and ag["eager_bytes"] == 0
    rs = s["comm"]["by_kind"]["reshard"]
    assert rs["eager_bytes"] == 1000 and rs["traced_bytes"] == 0
    assert s["comm"]["traced_bytes"] == 160
    assert s["comm"]["eager_bytes"] == 1000
    # fallback keys, most-hit first
    assert list(s["fallbacks"].items()) == [("site-b", 2), ("site-a", 1)]
    assert s["spans"]["work"]["count"] == 1
    from distributedarrays_tpu.telemetry.summarize import format_summary
    import io as _io
    buf = _io.StringIO()
    format_summary(s, buf)
    text = buf.getvalue()
    assert "traced" in text and "eager" in text
    assert "top fallback keys:" in text and "site-b" in text
    assert "spans (journaled):" in text and "work" in text


# ---------------------------------------------------------------------------
# acceptance: the scripted workload
# ---------------------------------------------------------------------------

_WORKLOAD = """
import _cpu_harness; _cpu_harness.force_cpu_mesh()
import numpy as np
import tempfile
import distributedarrays_tpu as dat
from distributedarrays_tpu import telemetry
from distributedarrays_tpu.utils import checkpoint
A = dat.distribute(np.arange(64, dtype=np.float32).reshape(8, 8))
B = dat.distribute(np.ones((8, 8), dtype=np.float32))
C = A @ B
dest = dat.dzeros((8, 8), dist=(1, 8))
dat.copyto_(dest, C)
# an eligible single-axis repartition: compiles the planner's chunked
# collective program (journals a jit build + a reshard plan event)
E = dat.distribute(np.arange(64, dtype=np.float32).reshape(8, 8), dist=(8, 1))
F = dat.dzeros((8, 8), dist=(1, 8))
dat.copyto_(F, E)
g = dat.gather(dest)
with tempfile.TemporaryDirectory() as td:
    checkpoint.save(td + "/ckpt", {"d": dest})
import json
r = telemetry.report()
print("REPORT " + json.dumps(r))
pm = telemetry.postmortem()
print("PM " + json.dumps(pm is not None))
# live plane: exporter + aggregator collapse to the same boolean check
from distributedarrays_tpu.telemetry import agg, stream
exp = stream.start("127.0.0.1:1")
stream.note("workload.gauge", 1.0)
stream.poke()
print("STREAM_START " + json.dumps(exp is not None))
print("STREAM_ARMED " + json.dumps(stream.armed()))
print("STREAM_STATS " + json.dumps(stream.stats()))
import urllib.error, urllib.request
srv = agg.AggServer(port=0).start()
try:
    try:
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as resp:
            print("AGG_METRICS " + str(resp.status))
    except urllib.error.HTTPError as e:
        print("AGG_METRICS " + str(e.code))
finally:
    srv.close()
stream.stop()
"""


def _run_workload(env):
    return subprocess.run(
        [sys.executable, "-c", _WORKLOAD], cwd=str(REPO),
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu", **env})


def test_scripted_workload_acceptance(tmp_path):
    jpath = tmp_path / "journal.jsonl"
    r = _run_workload({"DA_TPU_TELEMETRY": "1",
                       "DA_TPU_TELEMETRY_JOURNAL": str(jpath)})
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(r.stdout.split("REPORT ", 1)[1].splitlines()[0])
    # nonzero reshard count and nonzero estimated comm bytes
    assert rep["comm"]["by_kind"]["reshard"]["ops"] >= 1
    assert rep["comm"]["total_bytes"] > 0
    # at least one journal event per instrumented category the workload
    # exercises: communication, jit builds, mesh builds, autotune lookups
    cats = rep["events"]["by_category"]
    for cat in ("comm", "jit", "mesh", "autotune", "reshard", "hbm"):
        assert cats.get(cat, 0) >= 1, (cat, cats)
    # HBM ledger: the workload's live arrays are tracked, watermark moved
    assert rep["memory"]["live_bytes"] > 0
    assert rep["memory"]["peak_bytes"] >= rep["memory"]["live_bytes"]
    assert rep["memory"]["tracked_arrays"] >= 4
    # on-demand postmortem wrote a bundle (journal dir is configured)
    assert "PM true" in r.stdout
    # the live plane arms when telemetry is on: exporter starts (even
    # with an unreachable aggregator — it drops, never stalls) and the
    # aggregator serves its scrape endpoint
    assert "STREAM_START true" in r.stdout
    assert "STREAM_ARMED true" in r.stdout
    assert "AGG_METRICS 200" in r.stdout
    # the journal file round-trips through the summarizer
    s = summarize(read_journal(str(jpath)))
    assert s["comm"]["by_kind"]["reshard"]["ops"] >= 1
    assert s["comm"]["total_bytes"] > 0
    # span attribution: >= 95% of recorded comm bytes carry a span_id
    journal = read_journal(str(jpath))
    comm_evs = [e for e in journal if e.get("cat") == "comm"]
    total = sum(int(e.get("bytes", 0) or 0) for e in comm_evs)
    attributed = sum(int(e.get("bytes", 0) or 0) for e in comm_evs
                     if e.get("span_id") is not None)
    assert total > 0
    assert attributed / total >= 0.95, \
        f"only {attributed}/{total} comm bytes span-attributed"
    # every comm span_id must resolve to a span event in the SAME journal
    journaled_span_ids = {e.get("span_id") for e in journal
                          if e.get("cat") == "span"}
    dangling = [e for e in comm_evs
                if e.get("span_id") is not None
                and e["span_id"] not in journaled_span_ids]
    assert not dangling, dangling[:3]
    # the workload's phases appear as spans in the report and the journal
    span_names = set(rep["spans"]["by_name"])
    assert {"matmul", "reshard", "checkpoint.save", "distribute",
            "gather"} <= span_names, span_names
    # Perfetto export of the run is valid trace-event JSON with the
    # required keys on every entry and the phase spans present
    from distributedarrays_tpu.telemetry.export import to_perfetto
    trace = json.loads(json.dumps(to_perfetto(journal)))
    assert trace["traceEvents"]
    for e in trace["traceEvents"]:
        for key in ("ph", "ts", "dur", "pid", "tid"):
            assert key in e, (key, e)
    pf_names = {e["name"] for e in trace["traceEvents"]}
    assert {"matmul", "reshard", "checkpoint.save"} <= pf_names, pf_names


def test_scripted_workload_disabled_is_silent(tmp_path):
    jpath = tmp_path / "journal.jsonl"
    r = _run_workload({"DA_TPU_TELEMETRY": "0",
                       "DA_TPU_TELEMETRY_JOURNAL": str(jpath)})
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(r.stdout.split("REPORT ", 1)[1].splitlines()[0])
    assert rep["enabled"] is False
    assert rep["counters"] == {}
    assert rep["comm"]["total_bytes"] == 0 and rep["comm"]["total_ops"] == 0
    assert rep["events"]["recorded"] == 0
    # spans collapse to the same single boolean check: none recorded
    assert rep["spans"]["finished"] == 0 and rep["spans"]["by_name"] == {}
    # the HBM ledger's hooks collapse to the same single boolean check:
    # nothing tracked, no watermark, no staging
    assert rep["memory"]["live_bytes"] == 0
    assert rep["memory"]["peak_bytes"] == 0
    assert rep["memory"]["tracked_arrays"] == 0
    assert rep["memory"]["staging"]["peak_bytes"] == 0
    # and the flight recorder refuses to bundle
    assert "PM false" in r.stdout
    # the live plane collapses to the same single boolean check: the
    # exporter refuses to arm, note/poke are no-ops, stats is the
    # disarmed sentinel, and the aggregator's endpoints refuse cleanly
    assert "STREAM_START false" in r.stdout
    assert "STREAM_ARMED false" in r.stdout
    assert 'STREAM_STATS {"armed": false}' in r.stdout
    assert "AGG_METRICS 503" in r.stdout
    assert not jpath.exists(), \
        "DA_TPU_TELEMETRY=0 must not create a journal file"
