"""Extension parity tests (reference ext/SparseArraysExt.jl,
ext/StatisticsExt.jl)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat


def test_dnnz_dense(rng):
    A = rng.standard_normal((32, 32)).astype(np.float32)
    A[A < 0.5] = 0
    d = dat.distribute(A)
    assert dat.dnnz(d) == int(np.count_nonzero(A))


def test_dnnz_bcoo(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    A[np.abs(A) < 1.0] = 0
    d = dat.distribute(A, procs=range(4), dist=(4, 1))
    dd = dat.ddata_bcoo(d)
    assert dat.dnnz(dd) == int(np.count_nonzero(A))


def test_mean_std_parity(rng):
    # reference StatisticsExt: mean(d; dims) = sum/prod(size) (:6)
    A = rng.standard_normal((64, 32)).astype(np.float32)
    d = dat.distribute(A)
    assert np.allclose(float(dat.dmean(d)), A.mean(), rtol=1e-5)
    m = dat.dmean(d, dims=0)
    assert np.allclose(np.asarray(m), A.mean(axis=0, keepdims=True), rtol=1e-4)
    assert np.allclose(float(dat.dstd(d)), A.std(ddof=1), rtol=1e-4)
