"""DArray construction / layout / indexing tests.

Oracle discipline follows the reference: compute on a plain numpy array and
on the distributed array and compare (e.g. /root/reference/test/darray.jl:
398-401 and throughout)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray, SubDArray


def test_dzeros_dones_dfill():
    d = dat.dzeros((16, 8))
    assert d.dims == (16, 8)
    assert np.asarray(d).sum() == 0
    o = dat.dones((16, 8), dtype=jnp.int32)
    assert np.asarray(o).sum() == 16 * 8
    f = dat.dfill(2.5, (4, 4))
    assert np.allclose(np.asarray(f), 2.5)


def test_drand_drandn_deterministic():
    dat.seed(42)
    a = np.asarray(dat.drand((8, 8)))
    dat.seed(42)
    b = np.asarray(dat.drand((8, 8)))
    assert np.array_equal(a, b)
    assert a.min() >= 0 and a.max() < 1
    n = np.asarray(dat.drandn((64, 64)))
    assert abs(n.mean()) < 0.2


def test_drandint_dsample():
    # reference drand(r::UnitRange, dims) / drand(arr, dims)
    # (test/darray.jl:641-654)
    dat.seed(5)
    d = dat.drandint(3, 9, (64, 8))
    a = np.asarray(d)
    assert a.min() >= 3 and a.max() < 9
    assert jnp.issubdtype(d.dtype, jnp.integer)
    vals = np.array([2.5, -1.0, 7.25], np.float32)
    s = dat.dsample(vals, (256,))
    sa = np.asarray(s)
    assert set(np.unique(sa)).issubset(set(vals.tolist()))
    assert len(np.unique(sa)) == 3


def test_collections_api(rng):
    # reference "collections API": length / lastindex (test/darray.jl:423-436)
    A = rng.standard_normal((20, 4)).astype(np.float32)
    d = dat.distribute(A)
    assert len(d) == 20
    assert d.size == 80
    with dat.allowscalar(True):
        assert float(d[-1, -1]) == A[-1, -1]       # lastindex analog


def test_shift_operators():
    i = dat.distribute(np.arange(1, 17, dtype=np.int32))
    l = i << 2
    r = i >> 1
    assert np.array_equal(np.asarray(l), np.arange(1, 17) << 2)
    assert np.array_equal(np.asarray(r), np.arange(1, 17) >> 1)


def test_distribute_roundtrip(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    d = dat.distribute(A)
    assert isinstance(d, DArray)
    assert d.dims == (40, 24)
    assert np.array_equal(np.asarray(d), A)
    assert d == A  # whole-array equality like the reference Base.==


def test_distribute_explicit_layout(rng):
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    assert d.pids.shape == (4, 2)
    assert d.cuts[0] == [0, 13, 26, 38, 50]  # uneven leading chunks
    assert np.array_equal(np.asarray(d), A)


def test_darray_init_ctor():
    # reference generic ctor: init receives the chunk's global index ranges
    # (darray.jl:76-118)
    d = dat.darray(lambda idx: np.full((len(idx[0]), len(idx[1])),
                                       idx[0].start, dtype=np.float32),
                   (50, 8), procs=range(8), dist=(4, 2))
    a = np.asarray(d)
    assert a[0, 0] == 0 and a[13, 0] == 13 and a[38, 7] == 38


def test_darray_heterogeneous_chunks_throw():
    # reference darray.jl:89-94: heterogeneous localpart types must throw
    def init(idx):
        dt = np.float32 if idx[0].start == 0 else np.float64
        return np.zeros((len(idx[0]),), dtype=dt)
    with pytest.raises(TypeError):
        dat.darray(init, (16,), procs=range(4), dist=(4,))


def test_darray_bad_chunk_shape_throws():
    with pytest.raises(ValueError):
        dat.darray(lambda idx: np.zeros((3,)), (16,), procs=range(4), dist=(4,))


def test_localpart_localindices(rng):
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    seen = np.zeros_like(A)
    for pid in range(8):
        li = d.localindices(pid)
        lp = np.asarray(d.localpart(pid))
        assert lp.shape == tuple(len(r) for r in li)
        assert np.array_equal(lp, A[np.ix_(list(li[0]), list(li[1]))])
        seen[np.ix_(list(li[0]), list(li[1]))] = lp
    assert np.array_equal(seen, A)
    # non-participant gets an empty localpart (reference darray.jl:330-339)
    d4 = dat.distribute(A, procs=range(4), dist=(4, 1))
    assert d4.localpart(7).size == 0
    assert d4.localindices(7) == (range(0, 0), range(0, 0))


def test_localpart_fast_path_is_shard(rng):
    A = rng.standard_normal((64, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    lp = d.localpart(3)
    assert np.array_equal(np.asarray(lp), A[24:32])


def test_locate():
    d = dat.dzeros((50, 8), procs=range(8), dist=(4, 2))
    assert d.locate(0, 0) == (0, 0)
    assert d.locate(13, 4) == (1, 1)
    assert d.locate(49, 7) == (3, 1)


def test_scalar_indexing_guard():
    d = dat.dzeros((8, 8))
    with pytest.raises(RuntimeError):
        d[3, 4]
    with dat.allowscalar(True):
        assert float(d[3, 4]) == 0.0
    with pytest.raises(RuntimeError):
        d[3, 4] = 1.0
    with dat.allowscalar(True):
        d[3, 4] = 1.0
        assert float(d[3, 4]) == 1.0


def test_view_indexing(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    d = dat.distribute(A)
    v = d[10:30, 4:20]
    assert isinstance(v, SubDArray)
    assert v.shape == (20, 16)
    assert np.array_equal(np.asarray(v), A[10:30, 4:20])
    # mixed int/slice squeezes like numpy
    row = d[5, :]
    assert row.shape == (24,)
    assert np.array_equal(np.asarray(row), A[5, :])


def test_setindex_region(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    d = dat.distribute(A.copy())
    d[4:8, 4:8] = np.zeros((4, 4), np.float32)
    A[4:8, 4:8] = 0
    assert np.array_equal(np.asarray(d), A)
    # setindex! from another DArray
    src = dat.dones((4, 4))
    d[0:4, 0:4] = src
    A[0:4, 0:4] = 1
    assert np.array_equal(np.asarray(d), A)


def test_subdarray_into_numpy(rng):
    # reference setindex!(::Array, ::SubDArray, ...) machinery
    # (darray.jl:699-820) — semantics, not implementation
    A = rng.standard_normal((20, 20)).astype(np.float32)
    d = dat.distribute(A)
    out = np.zeros((10, 10), np.float32)
    out[:, :] = np.asarray(d[5:15, 5:15])
    assert np.array_equal(out, A[5:15, 5:15])


def test_makelocal(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    d = dat.distribute(A)
    m = dat.makelocal(d, slice(3, 17), slice(0, 24))
    assert np.array_equal(np.asarray(m), A[3:17, :])


def test_set_localpart(rng):
    A = rng.standard_normal((32, 8)).astype(np.float32)
    d = dat.distribute(A.copy(), procs=range(4), dist=(4, 1))
    new = np.zeros((8, 8), np.float32)
    d.set_localpart(new, pid=2)
    A[16:24] = 0
    assert np.array_equal(np.asarray(d), A)
    with pytest.raises(ValueError):
        d.set_localpart(np.zeros((3, 3), np.float32), pid=0)


def test_fill_and_rand_inplace():
    d = dat.dzeros((16, 16))
    d.fill_(7.0)
    assert np.allclose(np.asarray(d), 7.0)
    d.rand_()
    a = np.asarray(d)
    assert a.min() >= 0 and a.max() < 1 and len(np.unique(a)) > 10


def test_copy_and_deepcopy_independent(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    d = dat.distribute(A)
    c = d.copy()
    d.fill_(0.0)
    assert np.array_equal(np.asarray(c), A)
    assert c.id != d.id


def test_reshape(rng):
    A = rng.standard_normal((64,)).astype(np.float32)
    d = dat.distribute(A)
    r = d.reshape(8, 8)
    assert r.dims == (8, 8)
    assert np.array_equal(np.asarray(r), A.reshape(8, 8))
    with pytest.raises(ValueError):
        d.reshape(9, 9)


def test_from_chunks_uneven():
    chunks = np.empty((3,), dtype=object)
    chunks[0] = np.arange(5, dtype=np.float32)
    chunks[1] = np.arange(5, 9, dtype=np.float32)
    chunks[2] = np.arange(9, 12, dtype=np.float32)
    d = dat.from_chunks(chunks)
    assert d.dims == (12,)
    assert d.cuts[0] == [0, 5, 9, 12]
    assert np.array_equal(np.asarray(d), np.arange(12, dtype=np.float32))


def test_from_chunks_plain_list():
    # regression: a plain list of equal-shaped chunks must form a 1-D grid,
    # not be stacked into a 2-D object array
    d = dat.from_chunks([np.arange(5, dtype=np.float32),
                         np.arange(5, 10, dtype=np.float32)])
    assert d.dims == (10,)
    assert np.array_equal(np.asarray(d), np.arange(10, dtype=np.float32))


def test_from_chunks_grid_rank_mismatch():
    chunks = np.empty((2,), dtype=object)
    chunks[0] = np.zeros((2, 2), np.float32)
    chunks[1] = np.zeros((2, 2), np.float32)
    with pytest.raises(ValueError, match="grid rank"):
        dat.from_chunks(chunks)


def test_close_and_registry():
    d = dat.dzeros((8, 8))
    assert d.id in dat.registry()
    d.close()
    assert d.id not in dat.registry()
    with pytest.raises(RuntimeError):
        d.localpart()


def test_d_closeall():
    ds = [dat.dzeros((4, 4)) for _ in range(5)]
    assert len(dat.live_ids()) == 5
    dat.d_closeall()
    assert dat.live_ids() == []
    with pytest.raises(RuntimeError):
        ds[0].garray  # noqa: B018


def test_procs(rng):
    d = dat.dzeros((8, 8), procs=range(8), dist=(4, 2))
    assert dat.procs(d).shape == (4, 2)
    assert sorted(dat.procs(d).flat) == list(range(8))


def test_ddata_gather():
    dd = dat.ddata(init=lambda i: f"value-{i}")
    assert dd.localpart(3) == "value-3"
    assert dat.gather(dd) == [f"value-{i}" for i in range(8)]
    dd2 = dat.ddata(data=list(range(8)))
    assert dat.gather(dd2) == list(range(8))
    with pytest.raises(ValueError):
        dat.ddata(data=list(range(9)))


def test_darray_like(rng):
    A = rng.standard_normal((50, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    e = dat.darray_like(lambda idx: np.ones((len(idx[0]), len(idx[1])),
                                            np.float32), d)
    assert e.cuts == d.cuts
    assert np.allclose(np.asarray(e), 1.0)


def test_copyto(rng):
    A = rng.standard_normal((16, 16)).astype(np.float32)
    B = rng.standard_normal((16, 16)).astype(np.float32)
    da = dat.distribute(A.copy())
    dat.copyto_(da, dat.distribute(B))
    assert np.array_equal(np.asarray(da), B)
    # into a view region (reference copyto!(::SubDArray, src))
    dat.copyto_(da[0:4, 0:4], np.zeros((4, 4), np.float32))
    B2 = B.copy(); B2[0:4, 0:4] = 0
    assert np.array_equal(np.asarray(da), B2)
    with pytest.raises(ValueError):
        dat.copyto_(da, np.zeros((3, 3), np.float32))


def test_dcat(rng):
    A = rng.standard_normal((8, 4)).astype(np.float32)
    B = rng.standard_normal((8, 4)).astype(np.float32)
    da, db = dat.distribute(A), dat.distribute(B)
    v = dat.dcat(0, da, db)       # vcat
    assert v.dims == (16, 4)
    assert np.array_equal(np.asarray(v), np.concatenate([A, B], 0))
    h = dat.dcat(1, da, B)        # hcat with a plain array
    assert h.dims == (8, 8)
    assert np.array_equal(np.asarray(h), np.concatenate([A, B], 1))


def test_dfetch():
    d = dat.dfill(3.5, (4, 4))
    # explicit fetch bypasses the scalar guard (reference Base.fetch)
    assert float(dat.dfetch(d, 2, 2)) == 3.5


def test_iteration_guarded():
    d = dat.dzeros((4,))
    with pytest.raises(RuntimeError):
        list(d)
    with dat.allowscalar(True):
        assert list(np.asarray(d)) == [0, 0, 0, 0]


# ---------------------------------------------------------------------------
# round-3: @DArray comprehension ctor analog (reference darray.jl:214-231)
# ---------------------------------------------------------------------------


def test_dfromfunction_compiled(rng):
    d = dat.dfromfunction(lambda i, j: i * 10 + j, (12, 8),
                          procs=range(8), dist=(4, 2))
    want = np.fromfunction(lambda i, j: i * 10 + j, (12, 8), dtype=int)
    np.testing.assert_array_equal(np.asarray(d), want)
    # built sharded in place: 8 addressable shards, no host round-trip
    assert len(d.garray.addressable_shards) == 8
    dat.d_closeall()


def test_dfromfunction_untraceable_falls_back():
    def f(i, j):
        # np.asarray on a tracer raises -> forces the eager per-chunk path;
        # must be pointwise in GLOBAL indices (each chunk sees its own)
        return np.asarray(i) * 2.0 + np.asarray(j)

    d = dat.dfromfunction(f, (6, 4), procs=range(4), dist=(2, 2))
    want = np.fromfunction(lambda i, j: i * 2.0 + j, (6, 4))
    np.testing.assert_array_equal(np.asarray(d), want)
    dat.d_closeall()


def test_dfromfunction_1d_and_layout():
    d = dat.dfromfunction(lambda i: i * i, (50,))
    want = np.arange(50) ** 2
    np.testing.assert_array_equal(np.asarray(d), want)
    assert d.cuts[0][-1] == 50
    dat.d_closeall()
