"""Distributed convolution tests: halo exchange + local MXU conv against
the dense lax.conv oracle (no reference analog — beyond-reference; the
halo pattern is the reference's stencil substrate,
docs/src/index.md:160-181)."""

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.ops.conv import _dense_conv


@pytest.mark.parametrize("kshape", [(3, 3), (5, 3), (1, 5), (4, 3), (2, 2)])
def test_dconv2d_matches_dense(kshape, rng):
    A = rng.standard_normal((64, 32)).astype(np.float32)
    K = rng.standard_normal(kshape).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    got = np.asarray(dat.dconv2d(d, K))
    want = np.asarray(_dense_conv(jnp.asarray(A), jnp.asarray(K)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    dat.d_closeall()


def test_dconv2d_nhwc_cout_change(rng):
    X = rng.standard_normal((2, 32, 16, 3)).astype(np.float32)
    K = rng.standard_normal((3, 3, 3, 5)).astype(np.float32)
    dx = dat.distribute(X, procs=range(4), dist=(1, 4, 1, 1))
    got = np.asarray(dat.dconv2d(dx, K))
    assert got.shape == (2, 32, 16, 5)
    want = np.asarray(_dense_conv(jnp.asarray(X), jnp.asarray(K)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    dat.d_closeall()


def test_dconv2d_2d_grid_compiled(rng):
    # round-4: a height x width image grid runs the two-phase halo
    # exchange (corners via the row-extended block) — compiled, silent
    A = rng.standard_normal((64, 32)).astype(np.float32)
    for kshape in [(3, 3), (5, 3), (3, 5), (1, 3)]:
        K = rng.standard_normal(kshape).astype(np.float32)
        d = dat.distribute(A, procs=range(8), dist=(4, 2))
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            got = np.asarray(dat.dconv2d(d, K))
        want = np.asarray(_dense_conv(jnp.asarray(A), jnp.asarray(K)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                   err_msg=str(kshape))
    dat.d_closeall()


def test_dconv2d_ineligible_warns_and_matches(rng):
    # uneven layout: still the documented host degradation, loud
    A = rng.standard_normal((50, 32)).astype(np.float32)
    K = rng.standard_normal((3, 3)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))  # uneven cuts
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        got = np.asarray(dat.dconv2d(d, K))
        assert any("gathering" in str(x.message) for x in w)
    want = np.asarray(_dense_conv(jnp.asarray(A), jnp.asarray(K)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    dat.d_closeall()


def test_dconv2d_batch_sharded_and_complex(rng):
    # batch-sharded NHWC is the canonical dp layout: zero-communication
    # eligible (no host gather); complex inputs keep their imaginary part
    X = rng.standard_normal((8, 16, 8, 2)).astype(np.float32)
    K = rng.standard_normal((3, 3, 2, 2)).astype(np.float32)
    dx = dat.distribute(X, procs=range(8), dist=(8, 1, 1, 1))
    with warnings.catch_warnings():
        warnings.simplefilter("error")          # no fallback warning
        got = np.asarray(dat.dconv2d(dx, K))
    np.testing.assert_allclose(
        got, np.asarray(_dense_conv(jnp.asarray(X), jnp.asarray(K))),
        rtol=1e-4, atol=1e-5)
    C = (rng.standard_normal((32, 8)) + 1j * rng.standard_normal((32, 8))
         ).astype(np.complex64)
    dc = dat.distribute(C, procs=range(4), dist=(4, 1))
    Kc = rng.standard_normal((3, 3)).astype(np.float32)
    gotc = np.asarray(dat.dconv2d(dc, Kc))
    assert gotc.dtype == np.complex64
    np.testing.assert_allclose(
        gotc, np.asarray(_dense_conv(jnp.asarray(C), jnp.asarray(Kc))),
        rtol=1e-4, atol=1e-5)
    dat.d_closeall()


def test_dconv2d_validation():
    with pytest.raises(TypeError, match="DArray"):
        dat.dconv2d(np.zeros((4, 4)), np.zeros((3, 3)))
    d3 = dat.dzeros((8, 8, 8), procs=range(4), dist=(4, 1, 1))
    with pytest.raises(ValueError, match="2-D or 4-D"):
        dat.dconv2d(d3, np.zeros((3, 3)))
    d2 = dat.dzeros((8, 8), procs=range(4), dist=(4, 1))
    with pytest.raises(ValueError, match="kh, kw"):
        dat.dconv2d(d2, np.zeros((3, 3, 1, 1)))
    d4 = dat.dzeros((2, 8, 8, 3), procs=range(4), dist=(1, 4, 1, 1))
    with pytest.raises(ValueError, match="Cin"):
        dat.dconv2d(d4, np.zeros((3, 3, 2, 4)))
    dat.d_closeall()
