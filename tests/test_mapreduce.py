"""Map/reduce tests (reference src/mapreduce.jl semantics; oracle = numpy,
mirroring e.g. test/darray.jl:398-441 reduction checks)."""

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu import DArray


@pytest.fixture
def dA(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    return A, dat.distribute(A, procs=range(8), dist=(4, 2))


def test_whole_array_reductions(dA):
    A, d = dA
    assert np.allclose(float(dat.dsum(d)), A.sum(), rtol=1e-4)
    assert np.allclose(float(dat.dmaximum(d)), A.max())
    assert np.allclose(float(dat.dminimum(d)), A.min())
    assert np.allclose(float(dat.dmean(d)), A.mean(), rtol=1e-5)
    assert np.allclose(float(dat.dstd(d)), A.std(ddof=1), rtol=1e-4)
    assert np.allclose(float(dat.dvar(d, ddof=1)), A.var(ddof=1), rtol=1e-4)


def test_mapreduce(dA):
    A, d = dA
    # mapreduce(abs2, +, D) — BASELINE config 2 semantics
    got = float(dat.dmapreduce(jnp.square, "sum", d))
    assert np.allclose(got, (A ** 2).sum(), rtol=1e-4)
    got = float(dat.dmapreduce(jnp.abs, "max", d))
    assert np.allclose(got, np.abs(A).max())


def test_dim_reductions_keepdims(dA):
    A, d = dA
    for dims, axis in [(0, 0), (1, 1), ((0, 1), (0, 1))]:
        r = dat.dsum(d, dims=dims)
        want = A.sum(axis=axis, keepdims=True)
        assert isinstance(r, DArray)
        assert r.dims == want.shape
        assert np.allclose(np.asarray(r), want, rtol=1e-4)


def test_dim_reduction_layout_follows_grid(dA):
    A, d = dA
    r = dat.dsum(d, dims=1)   # reduce over the 2-chunk dim
    # result keeps the 4-way chunking of dim 0 (mapreduce.jl:54-66)
    assert r.pids.shape[0] == 4
    assert np.allclose(np.asarray(r), A.sum(axis=1, keepdims=True), rtol=1e-4)


def test_all_any_count(rng):
    A = rng.standard_normal((30, 10)).astype(np.float32)
    d = dat.distribute(A)
    assert bool(dat.dall(d < 100)) is True
    assert bool(dat.dany(d > 100)) is False
    got = int(dat.dcount(lambda a: a > 0, d))
    assert got == int((A > 0).sum())


def test_extrema(dA):
    A, d = dA
    lo, hi = dat.dextrema(d)
    assert np.allclose(float(lo), A.min())
    assert np.allclose(float(hi), A.max())
    lo_d, hi_d = dat.dextrema(d, dims=1)
    assert np.allclose(np.asarray(lo_d), A.min(axis=1, keepdims=True))
    assert np.allclose(np.asarray(hi_d), A.max(axis=1, keepdims=True))


def test_map_localparts_even_shardmap(rng):
    A = rng.standard_normal((40, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    r = dat.map_localparts(lambda lp: lp * 2.0, d)
    assert np.allclose(np.asarray(r), A * 2, rtol=1e-6)


def test_map_localparts_two_args(rng):
    A = rng.standard_normal((16, 8)).astype(np.float32)
    B = rng.standard_normal((16, 8)).astype(np.float32)
    da = dat.distribute(A, procs=range(4), dist=(4, 1))
    db = dat.distribute(B, procs=range(4), dist=(4, 1))
    r = dat.map_localparts(jnp.add, da, db)
    assert np.allclose(np.asarray(r), A + B, rtol=1e-6)


def test_map_localparts_uneven_host_path(rng):
    A = rng.standard_normal((50, 8)).astype(np.float32)   # uneven dim-0 cuts
    d = dat.distribute(A, procs=range(4), dist=(4, 1))
    r = dat.map_localparts(lambda lp: np.asarray(lp) + 1.0, d)
    assert np.allclose(np.asarray(r), A + 1, rtol=1e-6)
    assert r.cuts[0] == d.cuts[0]


def test_map_localparts_into(rng):
    A = rng.standard_normal((16, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))
    dest = dat.dzeros((16, 8), procs=range(4), dist=(4, 1))
    dat.map_localparts_into(lambda lp: lp * 3.0, dest, d)
    assert np.allclose(np.asarray(dest), A * 3, rtol=1e-6)


def test_samedist(rng):
    A = rng.standard_normal((40, 24)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(8, 1))
    like = dat.dzeros((40, 24), procs=range(8), dist=(2, 4))
    r = dat.samedist(d, like)
    assert r.pids.shape == (2, 4)
    assert np.array_equal(np.asarray(r), A)
    with pytest.raises(ValueError):
        dat.samedist(d, dat.dzeros((3, 3)))


def test_mapslices(rng):
    # reference mapslices (mapreduce.jl:191-208)
    A = rng.standard_normal((24, 16)).astype(np.float32)
    d = dat.distribute(A)
    r = dat.mapslices(lambda col: col / jnp.linalg.norm(col), d, dims=0)
    want = A / np.linalg.norm(A, axis=0, keepdims=True)
    assert np.allclose(np.asarray(r), want, rtol=1e-5)


def test_mapslices_untraceable_host_fallback(rng):
    # f using concrete numpy cannot trace; the host path must cover it
    A = rng.standard_normal((24, 16)).astype(np.float32)
    d = dat.distribute(A)

    def untraceable(col):
        c = np.asarray(col)
        return c / np.linalg.norm(c)

    r = dat.mapslices(untraceable, d, dims=0)
    want = A / np.linalg.norm(A, axis=0, keepdims=True)
    assert np.allclose(np.asarray(r), want, rtol=1e-5)


def test_mapslices_shape_change(rng):
    A = rng.standard_normal((24, 16)).astype(np.float32)
    d = dat.distribute(A)
    r = dat.mapslices(lambda col: jnp.sum(col, keepdims=True), d, dims=0)
    want = A.sum(axis=0, keepdims=True)
    assert r.dims == want.shape
    assert np.allclose(np.asarray(r), want, rtol=1e-4)


def test_mapslices_3d_middle_dim(rng):
    # regression: nested-vmap axis bookkeeping — slice along the MIDDLE dim
    # of a non-square 3-D array must act on that dim, not a neighbor
    A = rng.standard_normal((3, 5, 7)).astype(np.float32)
    d = dat.distribute(A)
    r = dat.mapslices(jnp.cumsum, d, dims=1)
    want = np.cumsum(A, axis=1)
    assert r.dims == want.shape
    assert np.allclose(np.asarray(r), want, rtol=1e-5)
    r2 = dat.mapslices(jnp.cumsum, d, dims=2)
    assert np.allclose(np.asarray(r2), np.cumsum(A, axis=2), rtol=1e-5)


def test_ppeval(rng):
    # reference ppeval (mapreduce.jl:210-323): slicewise along the last dim
    A = rng.standard_normal((8, 8, 4)).astype(np.float32)
    B = rng.standard_normal((8, 8, 4)).astype(np.float32)
    da, db = dat.distribute(A), dat.distribute(B)
    r = dat.ppeval(jnp.matmul, da, db)
    want = np.stack([A[:, :, k] @ B[:, :, k] for k in range(4)], axis=-1)
    assert np.allclose(np.asarray(r), want, rtol=1e-4, atol=1e-5)


def test_ppeval_extent_mismatch(rng):
    da = dat.distribute(rng.standard_normal((4, 3)).astype(np.float32))
    db = dat.distribute(rng.standard_normal((4, 5)).astype(np.float32))
    with pytest.raises(ValueError):
        dat.ppeval(jnp.add, da, db)


def test_reduce_on_subdarray(rng):
    A = rng.standard_normal((30, 30)).astype(np.float32)
    d = dat.distribute(A)
    v = d[5:25, 10:20]
    assert np.allclose(float(dat.dsum(v)), A[5:25, 10:20].sum(), rtol=1e-4)


# ---------------------------------------------------------------------------
# arbitrary binary-op reduce (reference mapreduce.jl:17-35 accepts any
# associative op; VERDICT round-1 gap #26)
# ---------------------------------------------------------------------------


def test_dreduce_binary_traced_min(rng):
    import functools
    A = rng.standard_normal((50, 7)).astype(np.float32)
    d = dat.distribute(A)
    op = lambda a, b: jnp.minimum(a, b) * 1
    got = float(dat.dreduce(op, d))
    want = functools.reduce(lambda a, b: min(a, b), A.reshape(-1).tolist())
    assert got == np.float32(want)


def test_dreduce_binary_operator_add_ints():
    import operator
    A = np.arange(1, 101, dtype=np.int32).reshape(10, 10)
    d = dat.distribute(A)
    got = int(dat.dreduce(operator.add, d))
    assert got == A.sum()


def test_dreduce_binary_with_dims(rng):
    A = rng.standard_normal((12, 5)).astype(np.float32)
    d = dat.distribute(A)
    r = dat.dreduce(lambda a, b: jnp.maximum(a, b), d, dims=0)
    want = A.max(axis=0, keepdims=True)
    assert r.dims == want.shape
    np.testing.assert_array_equal(np.asarray(r), want)


def test_dmapreduce_binary_abs2_max(rng):
    A = rng.standard_normal((40,)).astype(np.float32)
    d = dat.distribute(A)
    got = float(dat.dmapreduce(lambda x: x * x, lambda a, b: jnp.maximum(a, b), d))
    assert got == np.float32((A * A).max())


def test_dreduce_binary_untraceable_host_fallback():
    # an op XLA cannot trace (Python float branching) takes the host fold
    import functools
    A = np.arange(1, 21, dtype=np.float32)
    d = dat.distribute(A)
    def op(a, b):
        fa, fb = float(a), float(b)  # forces concretization -> untraceable
        return fa if fa > fb else fb
    got = dat.dreduce(op, d)
    assert float(got) == functools.reduce(op, A.tolist())


def test_dreduce_binary_empty_raises():
    d = dat.dzeros((0,), dtype=np.float32)
    with pytest.raises(ValueError):
        dat.dreduce(lambda a, b: a + b, d)


def test_dreduce_named_ops_still_work(rng):
    # the binary-op detection must not capture jnp-style reducers
    A = rng.standard_normal((20, 4)).astype(np.float32)
    d = dat.distribute(A)
    assert np.allclose(float(dat.dreduce("sum", d)), A.sum(), rtol=1e-4)
    assert np.allclose(float(dat.dreduce(jnp.sum, d)), A.sum(), rtol=1e-4)


def test_dreduce_binary_noncommutative_matches_left_fold():
    # associative but NOT commutative: "first non-nan" — the tree fold must
    # pair adjacent operands (order-preserving), matching a left fold
    import functools
    A = np.array([np.nan, 2.0, 3.0, np.nan, 5.0], dtype=np.float32)
    d = dat.distribute(A)
    op = lambda a, b: jnp.where(jnp.isnan(a), b, a)
    got = float(dat.dreduce(op, d))
    want = functools.reduce(lambda a, b: b if np.isnan(a) else a, A.tolist())
    assert got == np.float32(want) == np.float32(2.0)


def test_dreduce_binary_untraceable_with_dims():
    # scalar-only Python op + dims: host fold applies per kept position
    import functools
    A = np.arange(24, dtype=np.float32).reshape(4, 6)
    d = dat.distribute(A)
    def op(a, b):
        return float(a) if float(a) > float(b) else float(b)
    r = dat.dreduce(op, d, dims=0)
    want = A.max(axis=0, keepdims=True)
    assert r.dims == want.shape
    np.testing.assert_array_equal(np.asarray(r), want)


def test_dreduce_numpy_ufunc_binary():
    # np.ufunc has no inspectable signature; nin==2 must route it binary
    A = np.arange(1, 13, dtype=np.float32).reshape(3, 4)
    d = dat.distribute(A)
    assert float(dat.dreduce(np.maximum, d)) == A.max()
    assert np.isclose(float(dat.dreduce(np.add, d)), A.sum())


# ---------------------------------------------------------------------------
# round-3 (VERDICT item 7): fallbacks warn once, genuine errors propagate
# ---------------------------------------------------------------------------


def test_map_localparts_fallback_warns_once(rng):
    import warnings as W
    from distributedarrays_tpu.ops.mapreduce import map_localparts

    def untraceable_chunk_fn(a):
        return np.asarray(a) * 2        # numpy on a tracer -> trace fails

    d = dat.distribute(rng.standard_normal((32, 8)).astype(np.float32))
    with W.catch_warnings(record=True) as rec:
        W.simplefilter("always")
        r = map_localparts(untraceable_chunk_fn, d)
        r2 = map_localparts(untraceable_chunk_fn, d)
    np.testing.assert_allclose(np.asarray(r), np.asarray(d) * 2)
    np.testing.assert_allclose(np.asarray(r2), np.asarray(d) * 2)
    msgs = [w for w in rec if "shard_map fast path" in str(w.message)]
    assert len(msgs) == 1, [str(w.message) for w in rec]  # once per site
    dat.d_closeall()


def test_map_localparts_genuine_error_propagates(rng):
    from distributedarrays_tpu.ops.mapreduce import map_localparts

    def broken_fn(a):
        raise RuntimeError("kernel bug 0xdead")

    d = dat.distribute(rng.standard_normal((16, 4)).astype(np.float32))
    with pytest.raises(RuntimeError, match="kernel bug 0xdead"):
        map_localparts(broken_fn, d)
    dat.d_closeall()


# ---------------------------------------------------------------------------
# round-3: distributed scans (parallel prefix) — dcumsum / dcumprod
# ---------------------------------------------------------------------------


def test_dcumsum_sharded_axis(rng):
    A = rng.standard_normal((32, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    got = dat.dcumsum(d, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.cumsum(A, axis=0),
                               rtol=1e-5, atol=1e-5)
    assert got.cuts == d.cuts
    got1 = dat.dcumsum(d, axis=1)
    np.testing.assert_allclose(np.asarray(got1), np.cumsum(A, axis=1),
                               rtol=1e-5, atol=1e-5)
    dat.d_closeall()


def test_dcumsum_unsharded_axis_and_negative(rng):
    A = rng.standard_normal((16, 6)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))   # dim 1 unsharded
    got = dat.dcumsum(d, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.cumsum(A, axis=1),
                               rtol=1e-5, atol=1e-5)
    dat.d_closeall()


def test_dcumprod_and_int_dtype(rng):
    A = rng.integers(1, 3, (24,)).astype(np.int32)
    d = dat.distribute(A, procs=range(8))
    got = dat.dcumprod(d)
    np.testing.assert_array_equal(np.asarray(got), np.cumprod(A))
    assert got.dtype == jnp.int32
    dat.d_closeall()


def test_dcumsum_uneven_layout_keeps_cuts(rng):
    A = rng.standard_normal((50,)).astype(np.float32)
    d = dat.distribute(A, procs=range(4))     # cuts [0,13,26,38,50]
    got = dat.dcumsum(d)
    np.testing.assert_allclose(np.asarray(got), np.cumsum(A),
                               rtol=1e-4, atol=1e-4)
    assert got.cuts == d.cuts
    dat.d_closeall()


def test_dcumsum_validation(rng):
    d = dat.dzeros((8,), procs=range(4))
    with pytest.raises(ValueError, match="axis"):
        dat.dcumsum(d, axis=2)
    with pytest.raises(TypeError, match="DArray"):
        dat.dcumsum(np.zeros(4))
    dat.d_closeall()


def test_dcummax_dcummin(rng):
    A = rng.standard_normal((32, 8)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))
    np.testing.assert_array_equal(np.asarray(dat.dcummax(d, axis=0)),
                                  np.maximum.accumulate(A, axis=0))
    np.testing.assert_array_equal(np.asarray(dat.dcummin(d, axis=1)),
                                  np.minimum.accumulate(A, axis=1))
    # int dtype neutral (iinfo, not -inf)
    B = rng.integers(-50, 50, (24,)).astype(np.int32)
    db = dat.distribute(B, procs=range(8))
    np.testing.assert_array_equal(np.asarray(dat.dcummax(db)),
                                  np.maximum.accumulate(B))
    # uneven host path
    V = dat.distribute(rng.standard_normal(50).astype(np.float32),
                       procs=range(4))
    np.testing.assert_array_equal(np.asarray(dat.dcummin(V)),
                                  np.minimum.accumulate(np.asarray(V)))
    dat.d_closeall()


def test_dcummax_bool_and_inf_edge_cases(rng):
    # bool dtype on the sharded axis (iinfo would reject bool), and a
    # leading all -inf chunk (finfo.min neutral would corrupt -inf data)
    B = rng.random(24) > 0.5
    db = dat.distribute(B, procs=range(8))
    np.testing.assert_array_equal(np.asarray(dat.dcummax(db)),
                                  np.maximum.accumulate(B))
    A = rng.standard_normal(32).astype(np.float32)
    A[:4] = -np.inf                          # rank 0's whole chunk
    da = dat.distribute(A, procs=range(8))
    np.testing.assert_array_equal(np.asarray(dat.dcummax(da)),
                                  np.maximum.accumulate(A))
    dat.d_closeall()


# ---------------------------------------------------------------------------
# round-4: uneven scans run the padded compiled path (no host gather)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind,np_scan", [
    ("dcumsum", np.cumsum), ("dcumprod", np.cumprod),
    ("dcummax", np.maximum.accumulate), ("dcummin", np.minimum.accumulate)])
def test_uneven_scan_all_kinds(kind, np_scan, rng):
    import warnings
    x = (rng.standard_normal(50) * 0.5 + 1.0).astype(np.float32)
    d = dat.distribute(x, procs=range(4))     # cuts [13,13,12,12]
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got = getattr(dat, kind)(d)
    np.testing.assert_allclose(np.asarray(got), np_scan(x),
                               rtol=1e-4, atol=1e-5)
    assert got.cuts == d.cuts


def test_uneven_2d_scan_both_axes(rng):
    A = rng.standard_normal((50, 6)).astype(np.float32)
    d = dat.distribute(A, procs=range(8), dist=(4, 2))  # dim0 uneven
    got0 = dat.dcumsum(d, axis=0)             # scan along the uneven dim
    np.testing.assert_allclose(np.asarray(got0), np.cumsum(A, axis=0),
                               rtol=1e-4, atol=1e-4)
    got1 = dat.dcumsum(d, axis=1)             # uneven elsewhere, even here
    np.testing.assert_allclose(np.asarray(got1), np.cumsum(A, axis=1),
                               rtol=1e-4, atol=1e-4)
    assert got0.cuts == d.cuts and got1.cuts == d.cuts


def test_uneven_scan_zero_sized_chunk(rng):
    # 3 elements over 4 ranks: one chunk is empty -> neutral contribution
    x = rng.standard_normal(3).astype(np.float32)
    d = dat.distribute(x, procs=range(4))
    got = dat.dcumsum(d)
    np.testing.assert_allclose(np.asarray(got), np.cumsum(x), rtol=1e-5)


def test_uneven_scan_bool_cummax(rng):
    x = np.array([0, 0, 1, 0, 0, 0, 1, 0, 0, 0], dtype=bool)
    d = dat.distribute(x, procs=range(4))
    got = dat.dcummax(d)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.maximum.accumulate(x))


def test_scan_jit_wrappers_are_cached(rng):
    # regression: repeated scans must reuse one jit wrapper per
    # (layout, kind, axis) — a lost lru_cache means a recompile per call
    from distributedarrays_tpu.ops import mapreduce as MR
    d = dat.distribute(rng.standard_normal(64).astype(np.float32),
                       procs=range(4))
    h0 = MR._scan_shm_jit.cache_info().hits
    dat.dcumsum(d); dat.dcumsum(d)
    assert MR._scan_shm_jit.cache_info().hits > h0
    du = dat.distribute(rng.standard_normal(50).astype(np.float32),
                        procs=range(4))
    h1 = MR._scan_uneven_shm_jit.cache_info().hits
    dat.dcumsum(du); dat.dcumsum(du)
    assert MR._scan_uneven_shm_jit.cache_info().hits > h1
    dat.d_closeall()
