"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh),
oracle = the dense attention from models/ring_attention."""

import numpy as np
import pytest

import distributedarrays_tpu  # noqa: F401  (package init)
from distributedarrays_tpu.models.ring_attention import reference_attention
from distributedarrays_tpu.ops.pallas_attention import flash_attention


@pytest.fixture
def qkv(rng):
    S, H, D = 128, 2, 16
    mk = lambda: rng.standard_normal((S, H, D)).astype(np.float32)
    return mk(), mk(), mk()


def test_flash_full(qkv):
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, block_q=32, block_k=32))
    want = reference_attention(q, k, v)
    assert np.abs(got - want).max() < 1e-5


def test_flash_causal(qkv):
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, causal=True,
                                     block_q=32, block_k=32))
    want = reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5


def test_flash_uneven_blocks(qkv):
    # bq != bk exercises the grid bookkeeping
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, causal=True,
                                     block_q=64, block_k=32))
    want = reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5


def test_flash_validation(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="share"):
        flash_attention(q, k[:64], v)


def test_flash_block_fitting(qkv):
    # a non-dividing block request is fitted (halved until it divides),
    # not rejected — every sequence length works with the defaults
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, causal=True, block_q=48))
    want = reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5
