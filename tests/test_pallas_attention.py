"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh),
oracle = the dense attention from models/ring_attention."""

import numpy as np
import pytest

import distributedarrays_tpu  # noqa: F401  (package init)
from distributedarrays_tpu.models.ring_attention import reference_attention
from distributedarrays_tpu.ops.pallas_attention import flash_attention


@pytest.fixture
def qkv(rng):
    S, H, D = 128, 2, 16
    mk = lambda: rng.standard_normal((S, H, D)).astype(np.float32)
    return mk(), mk(), mk()


def test_flash_full(qkv):
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, block_q=32, block_k=32))
    want = reference_attention(q, k, v)
    assert np.abs(got - want).max() < 1e-5


def test_flash_causal(qkv):
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, causal=True,
                                     block_q=32, block_k=32))
    want = reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5


def test_flash_uneven_blocks(qkv):
    # bq != bk exercises the grid bookkeeping
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, causal=True,
                                     block_q=64, block_k=32))
    want = reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5


def test_flash_validation(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="share"):
        flash_attention(q, k[:64], v)


def test_flash_block_fitting(qkv):
    # a non-dividing block request is fitted (halved until it divides),
    # not rejected — every sequence length works with the defaults
    q, k, v = qkv
    got = np.asarray(flash_attention(q, k, v, causal=True, block_q=48))
    want = reference_attention(q, k, v, causal=True)
    assert np.abs(got - want).max() < 1e-5


def test_flash_head_fold(qkv):
    # hfold > 1: heads ride the grid step as a batched dot (the lane-
    # occupancy lever for small head_dim); numerics identical
    q, k, v = qkv
    want = reference_attention(q, k, v)
    for hf in (2, 3):   # 3 is clipped to a divisor of H=2 -> 2
        got = np.asarray(flash_attention(q, k, v, block_q=32, block_k=32,
                                         head_fold=hf))
        assert np.abs(got - want).max() < 1e-5, hf
    got_c = np.asarray(flash_attention(q, k, v, causal=True, block_q=32,
                                       block_k=32, head_fold=2))
    want_c = reference_attention(q, k, v, causal=True)
    assert np.abs(got_c - want_c).max() < 1e-5


def test_flash_head_fold_grads(qkv):
    import jax
    import jax.numpy as jnp
    q, k, v = qkv

    def loss(fold):
        def f(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, causal=True,
                                           block_q=32, block_k=32,
                                           head_fold=fold) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g1 = loss(1)
    g2 = loss(2)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_autotune_three_tuple_entry(qkv):
    # a (bq, bk, hfold) registry entry drives dispatch; malformed entries
    # degrade to the defaults
    from distributedarrays_tpu.utils import autotune
    q, k, v = qkv
    want = reference_attention(q, k, v)
    key = autotune.device_key_for(128, 2, 16, q.dtype, False)
    autotune.clear()
    autotune.record("flash_attention", key, (32, 32, 2))
    got = np.asarray(flash_attention(q, k, v))
    assert np.abs(got - want).max() < 1e-5
    autotune.record("flash_attention", key, ("bogus",))
    got = np.asarray(flash_attention(q, k, v))   # degrades, still correct
    assert np.abs(got - want).max() < 1e-5
    autotune.clear()
