"""Traced shard_map collectives tests: the compiled ring/halo patterns that
replace the reference's eager send/recv programs on TPU (reference ring:
test/spmd.jl:90-101; stencil: docs/src/index.md:160-181)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import distributedarrays_tpu as dat
from distributedarrays_tpu.parallel import collectives as C


NP = 8


@pytest.fixture
def mesh():
    return C.spmd_mesh(NP)


def test_pshift_ring(mesh, rng):
    x = rng.standard_normal((NP, 4)).astype(np.float32)
    f = C.run_spmd(lambda b: C.pshift(b, "p", 1), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    got = np.asarray(f(x))
    assert np.allclose(got, np.roll(x, 1, axis=0))
    b = C.run_spmd(lambda b: C.pshift(b, "p", -1), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    assert np.allclose(np.asarray(b(x)), np.roll(x, -1, axis=0))


def test_pshift_no_wrap(mesh, rng):
    x = rng.standard_normal((NP, 2)).astype(np.float32)
    f = C.run_spmd(lambda b: C.pshift(b, "p", 1, wrap=False), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    got = np.asarray(f(x))
    assert np.allclose(got[1:], x[:-1])
    assert np.allclose(got[0], 0.0)


def test_pbarrier_psum(mesh):
    f = C.run_spmd(lambda b: b * C.pbarrier("p"), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    out = np.asarray(f(np.ones((NP,), np.float32)))
    assert np.allclose(out, NP)   # psum of 1 over 8 ranks


def test_pbcast(mesh):
    x = np.arange(NP, dtype=np.float32).reshape(NP, 1)
    f = C.run_spmd(lambda b: C.pbcast(b, "p", root=3), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    assert np.allclose(np.asarray(f(x)), 3.0)


def test_pgather(mesh):
    x = np.arange(NP, dtype=np.float32).reshape(NP, 1)
    f = C.run_spmd(lambda b: C.pgather(b, "p", tiled=True), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    got = np.asarray(f(x))   # every rank holds the full gathered vector
    assert got.shape == (NP * NP, 1)
    assert np.allclose(got[:NP, 0], np.arange(NP))


def test_preduce_ops(mesh):
    x = np.arange(NP, dtype=np.float32).reshape(NP, 1)
    for op, want in [("sum", x.sum()), ("max", x.max()), ("min", x.min()),
                     ("mean", x.mean())]:
        f = C.run_spmd(lambda b: C.preduce(b, "p", op), mesh,
                       in_specs=P("p"), out_specs=P("p"))
        assert np.allclose(np.asarray(f(x)), want), op


def test_pall_to_all(mesh, rng):
    # repartition: row-sharded → column-sharded (the sample-sort scatter
    # phase, sort.jl:24-55)
    x = rng.standard_normal((NP, NP)).astype(np.float32)
    f = C.run_spmd(lambda b: C.pall_to_all(b, "p", split_dim=1, concat_dim=0),
                   mesh, in_specs=P("p", None), out_specs=P(None, "p"))
    got = np.asarray(f(x))
    assert np.allclose(got, x)   # global view unchanged, layout transposed


def test_halo_exchange_5point_stencil(rng):
    # end-to-end: the BASELINE config-5 pattern — row-sharded 2-D grid,
    # halo exchange + 5-point laplacian, compared against a numpy oracle
    n = 64
    mesh = C.spmd_mesh(NP)
    A = rng.standard_normal((n, n)).astype(np.float32)

    def step(block):
        lo, hi = C.halo_exchange(block, "p", halo=1, dim=0, wrap=False)
        x = jnp.concatenate([lo, block, hi], axis=0)
        up = x[:-2, :]
        down = x[2:, :]
        left = jnp.roll(block, 1, axis=1)
        right = jnp.roll(block, -1, axis=1)
        return (up + down + left + right - 4.0 * block)

    f = C.run_spmd(step, mesh, in_specs=P("p", None), out_specs=P("p", None))
    got = np.asarray(f(A))

    pad = np.zeros((1, n), np.float32)
    xp = np.concatenate([pad, A, pad], axis=0)
    want = (xp[:-2] + xp[2:] + np.roll(A, 1, 1) + np.roll(A, -1, 1) - 4 * A)
    assert np.allclose(got, want, rtol=1e-5, atol=1e-5)


def test_axis_rank(mesh):
    f = C.run_spmd(lambda b: b + C.axis_rank("p"), mesh,
                   in_specs=P("p"), out_specs=P("p"))
    got = np.asarray(f(np.zeros((NP,), np.float32)))
    assert np.allclose(got, np.arange(NP))


# ---------------------------------------------------------------------------
# round-3: overlapped collective matmuls (ops/collective_matmul.py) — the
# ring-pipelined TP primitives (all-gather GEMM, GEMM + reduce-scatter)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("p", [1, 2, 4, 8])
def test_allgather_matmul_oracle(p, rng):
    from distributedarrays_tpu.ops.collective_matmul import allgather_matmul
    mesh = C.spmd_mesh(p)
    M, K, N = 16 * p, 32, 24 * p
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32)
    f = C.run_spmd(lambda xs, ws: allgather_matmul(xs, ws, "p"), mesh,
                   in_specs=(P("p", None), P(None, "p")),
                   out_specs=P(None, "p"))
    np.testing.assert_allclose(np.asarray(f(x, w)), x @ w,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("p", [1, 2, 4, 8])
def test_allgather_matmul_rhs_oracle(p, rng):
    # the right-operand twin: a resident row block, b circulating
    # contraction chunk (the DMatrix @ DMatrix TP dispatch shape)
    from distributedarrays_tpu.ops.collective_matmul import (
        allgather_matmul_rhs)
    mesh = C.spmd_mesh(p)
    M, K, N = 8 * p, 16 * p, 24
    a = rng.standard_normal((M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    f = C.run_spmd(lambda al, bl: allgather_matmul_rhs(al, bl, "p"), mesh,
                   in_specs=(P("p", None), P("p", None)),
                   out_specs=P("p", None))
    np.testing.assert_allclose(np.asarray(f(a, b)), a @ b,
                               rtol=1e-4, atol=1e-4)


def test_allgather_matmul_rhs_grad(rng):
    from distributedarrays_tpu.ops.collective_matmul import (
        allgather_matmul_rhs)
    p = 4
    mesh = C.spmd_mesh(p)
    a = rng.standard_normal((8, 16)).astype(np.float32)
    b = rng.standard_normal((16, 12)).astype(np.float32)
    import jax
    import jax.numpy as jnp

    def loss_ring(a_, b_):
        f = C.run_spmd(
            lambda al, bl: allgather_matmul_rhs(al, bl, "p"), mesh,
            in_specs=(P("p", None), P("p", None)), out_specs=P("p", None))
        return jnp.sum(f(a_, b_) ** 2)

    ga, gb = jax.grad(loss_ring, argnums=(0, 1))(a, b)
    ga0, gb0 = jax.grad(
        lambda a_, b_: jnp.sum((a_ @ b_) ** 2), argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ga0),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb0),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("p", [1, 2, 4, 8])
def test_matmul_reducescatter_oracle(p, rng):
    from distributedarrays_tpu.ops.collective_matmul import (
        matmul_reducescatter)
    mesh = C.spmd_mesh(p)
    M, K, N = 8 * p, 16 * p, 24
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32)
    f = C.run_spmd(lambda xs, ws: matmul_reducescatter(xs, ws, "p"), mesh,
                   in_specs=(P(None, "p"), P("p", None)),
                   out_specs=P("p", None))
    np.testing.assert_allclose(np.asarray(f(x, w)), x @ w,
                               rtol=1e-4, atol=1e-4)


def test_cannon_matmul_oracle(rng):
    # the square-grid (g,g) GEMM: Cannon pre-skew + overlapped double
    # panel ring must equal the dense product (BASELINE config 3's
    # 2x2 tile-grid shape; reference linalg.jl:189-253)
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.collective_matmul import cannon_matmul
    g = 2
    mesh = L.mesh_for(range(g * g), (g, g))
    M, K, N = 8 * g, 6 * g, 4 * g
    a = rng.standard_normal((M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    f = C.run_spmd(lambda al, bl: cannon_matmul(al, bl, "d0", "d1"), mesh,
                   in_specs=(P("d0", "d1"), P("d0", "d1")),
                   out_specs=P("d0", "d1"))
    np.testing.assert_allclose(np.asarray(f(a, b)), a @ b,
                               rtol=1e-4, atol=1e-4)


def test_cannon_matmul_rejects_rectangular_grid(rng):
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.collective_matmul import cannon_matmul
    mesh = L.mesh_for(range(8), (2, 4))
    a = rng.standard_normal((8, 8)).astype(np.float32)
    b = rng.standard_normal((8, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="square"):
        C.run_spmd(lambda al, bl: cannon_matmul(al, bl, "d0", "d1"), mesh,
                   in_specs=(P("d0", "d1"), P("d0", "d1")),
                   out_specs=P("d0", "d1"))(a, b)


@pytest.mark.parametrize("grid", [(2, 4), (4, 2), (2, 2)])
def test_summa_matmul_oracle(grid, rng):
    # the general (r,c)-grid panel schedule: masked-psum broadcasts of
    # lcm(r,c) contraction panels — must equal the dense product on
    # rectangular grids in BOTH orientations (and square, where it
    # coexists with the Cannon ring)
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.collective_matmul import summa_matmul
    r, c = grid
    mesh = L.mesh_for(range(r * c), (r, c))
    lcm = np.lcm(r, c)
    M, K, N = 4 * r, 3 * lcm, 4 * c
    a = rng.standard_normal((M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    f = C.run_spmd(lambda al, bl: summa_matmul(al, bl, "d0", "d1"), mesh,
                   in_specs=(P("d0", "d1"), P("d0", "d1")),
                   out_specs=P("d0", "d1"))
    np.testing.assert_allclose(np.asarray(f(a, b)), a @ b,
                               rtol=1e-4, atol=1e-4)


def test_summa_matmul_grad_matches_dense(rng):
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.collective_matmul import summa_matmul
    mesh = L.mesh_for(range(8), (2, 4))
    a = jnp.asarray(rng.standard_normal((8, 12)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((12, 8)), jnp.float32)
    f = C.run_spmd(lambda al, bl: summa_matmul(al, bl, "d0", "d1"), mesh,
                   in_specs=(P("d0", "d1"), P("d0", "d1")),
                   out_specs=P("d0", "d1"))
    ga, gb = jax.grad(lambda x, y: jnp.sum(f(x, y) ** 2), (0, 1))(a, b)
    da, db = jax.grad(lambda x, y: jnp.sum((x @ y) ** 2), (0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(da),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(db),
                               rtol=1e-4, atol=1e-3)


def test_cannon_matmul_int8_oracle(rng):
    # int8 panels + per-panel scales around the double ring: must match
    # the float product within the quantization error bound of the
    # single-device quantized_matmul family
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.collective_matmul import (
        cannon_matmul_int8)
    g = 2
    mesh = L.mesh_for(range(g * g), (g, g))
    a = rng.standard_normal((16, 16)).astype(np.float32)
    b = rng.standard_normal((16, 8)).astype(np.float32)
    f = C.run_spmd(
        lambda al, bl: cannon_matmul_int8(al, bl, "d0", "d1"), mesh,
        in_specs=(P("d0", "d1"), P("d0", "d1")),
        out_specs=P("d0", "d1"), check_vma=False)
    ref = a @ b
    got = np.asarray(f(a, b))
    assert np.abs(got - ref).max() / np.abs(ref).max() < 3e-2


def test_cannon_matmul_g3_runs_loop_body():
    # at g=2 the fori_loop(1, g-1) body never executes (seed + final
    # step cover both panels), so a 2x2-only suite would pass with a
    # flipped hop direction in the body; 3x3 is the smallest grid that
    # drives the in-loop hop + accumulate — needs 9 devices, hence a
    # fresh subprocess with its own device count
    import subprocess
    import sys
    prog = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=9"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import PartitionSpec as P
from distributedarrays_tpu import layout as L
from distributedarrays_tpu.parallel import collectives as C
from distributedarrays_tpu.ops.collective_matmul import (
    cannon_matmul, cannon_matmul_int8)
rng = np.random.default_rng(3)
mesh = L.mesh_for(range(9), (3, 3))
a = rng.standard_normal((12, 12)).astype(np.float32)
b = rng.standard_normal((12, 6)).astype(np.float32)
f = C.run_spmd(lambda al, bl: cannon_matmul(al, bl, "d0", "d1"), mesh,
               in_specs=(P("d0", "d1"), P("d0", "d1")),
               out_specs=P("d0", "d1"))
np.testing.assert_allclose(np.asarray(f(a, b)), a @ b,
                           rtol=1e-4, atol=1e-4)
q = C.run_spmd(lambda al, bl: cannon_matmul_int8(al, bl, "d0", "d1"),
               mesh, in_specs=(P("d0", "d1"), P("d0", "d1")),
               out_specs=P("d0", "d1"), check_vma=False)
ref = a @ b
got = np.asarray(q(a, b))
assert np.abs(got - ref).max() / np.abs(ref).max() < 3e-2
print("G3_OK")
"""
    import os
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "G3_OK" in r.stdout


def test_cannon_matmul_grad_matches_dense(rng):
    # pure lax (static-trip fori_loop + ppermute) -> differentiable, so
    # the 2-D TP training path can run through it
    from distributedarrays_tpu import layout as L
    from distributedarrays_tpu.ops.collective_matmul import cannon_matmul
    g = 2
    mesh = L.mesh_for(range(g * g), (g, g))
    a = jnp.asarray(rng.standard_normal((8, 12)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((12, 8)), jnp.float32)
    f = C.run_spmd(lambda al, bl: cannon_matmul(al, bl, "d0", "d1"), mesh,
                   in_specs=(P("d0", "d1"), P("d0", "d1")),
                   out_specs=P("d0", "d1"))
    ga, gb = jax.grad(lambda x, y: jnp.sum(f(x, y) ** 2), (0, 1))(a, b)
    da, db = jax.grad(lambda x, y: jnp.sum((x @ y) ** 2), (0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(da),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(db),
                               rtol=1e-4, atol=1e-3)


def test_collective_matmul_grads_match_dense(rng):
    # both primitives are pure lax -> differentiable; grads must match the
    # dense oracle so the TP training path can run through them
    from distributedarrays_tpu.ops.collective_matmul import (
        allgather_matmul, matmul_reducescatter)
    p = 4
    mesh = C.spmd_mesh(p)
    x = jnp.asarray(rng.standard_normal((16 * p, 32)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((32, 24 * p)), jnp.float32)
    f = C.run_spmd(lambda xs, ws: allgather_matmul(xs, ws, "p"), mesh,
                   in_specs=(P("p", None), P(None, "p")),
                   out_specs=P(None, "p"))
    gx, gw = jax.grad(lambda a, b: jnp.sum(f(a, b) ** 2), (0, 1))(x, w)
    dx, dw = jax.grad(lambda a, b: jnp.sum((a @ b) ** 2), (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(dx),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(dw),
                               rtol=1e-4, atol=1e-3)

    x2 = jnp.asarray(rng.standard_normal((8 * p, 16 * p)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((16 * p, 24)), jnp.float32)
    g = C.run_spmd(lambda xs, ws: matmul_reducescatter(xs, ws, "p"), mesh,
                   in_specs=(P(None, "p"), P("p", None)),
                   out_specs=P("p", None))
    ga, gb = jax.grad(lambda a, b: jnp.sum(g(a, b) ** 2), (0, 1))(x2, w2)
    da, db = jax.grad(lambda a, b: jnp.sum((a @ b) ** 2), (0, 1))(x2, w2)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(da),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(db),
                               rtol=1e-4, atol=1e-3)


def test_tp_ffn_sequence_parallel_oracle(rng):
    # the AG->gelu->RS sandwich: sequence-sharded in and out, Megatron
    # column/row weight shards, must equal the dense FFN
    from distributedarrays_tpu.ops.collective_matmul import tp_ffn
    p = 4
    mesh = C.spmd_mesh(p)
    S, E, F = 8 * p, 16, 32 * p
    x = rng.standard_normal((S, E)).astype(np.float32)
    w1 = rng.standard_normal((E, F)).astype(np.float32)
    w2 = rng.standard_normal((F, E)).astype(np.float32)
    f = C.run_spmd(lambda xs, a, b: tp_ffn(xs, a, b, "p"), mesh,
                   in_specs=(P("p", None), P(None, "p"), P("p", None)),
                   out_specs=P("p", None))
    want = np.asarray(jax.nn.gelu(jnp.asarray(x @ w1))) @ w2
    np.testing.assert_allclose(np.asarray(f(x, w1, w2)), want,
                               rtol=1e-4, atol=1e-4)


def test_matmul_reducescatter_rejects_indivisible_rows():
    from distributedarrays_tpu.ops.collective_matmul import (
        matmul_reducescatter)
    mesh = C.spmd_mesh(4)
    x = np.zeros((10, 16), np.float32)   # 10 rows, p=4: no even scatter
    w = np.zeros((16, 8), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        C.run_spmd(lambda xs, ws: matmul_reducescatter(xs, ws, "p"), mesh,
                   in_specs=(P(None, "p"), P("p", None)),
                   out_specs=P("p", None))(x, w)
