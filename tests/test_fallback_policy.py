"""One fallback-visibility policy (VERDICT round-3 item 6): every
documented degradation to a host gather emits a RuntimeWarning (once per
site), and no compiled fast path warns.  The reference has no silent
degradations to hide — its workers ARE the host; here a host gather
abandons the device mesh, so it must always be visible."""

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

import distributedarrays_tpu as dat
from distributedarrays_tpu.utils import debug as dbg


@pytest.fixture(autouse=True)
def fresh_warn_registry():
    # warn_once keys are process-global; reset so each test sees its warning
    with dbg._warned_lock:
        saved = set(dbg._warned)
        dbg._warned.clear()
    yield
    with dbg._warned_lock:
        dbg._warned.clear()
        dbg._warned.update(saved)
    dat.d_closeall()


def test_uneven_scan_compiled_and_silent(rng):
    # round-4: uneven scans run the padded compiled path — there is no
    # scan host fallback left to warn about
    d = dat.distribute(rng.standard_normal(50).astype(np.float32),
                       procs=range(4))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got = dat.dcumsum(d)
    np.testing.assert_allclose(np.asarray(got),
                               np.cumsum(np.asarray(d)), rtol=1e-4)
    assert got.cuts == d.cuts


def test_even_scan_does_not_warn(rng):
    d = dat.distribute(rng.standard_normal(64).astype(np.float32),
                       procs=range(4))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        dat.dcumsum(d)


def test_untraceable_mapslices_warns(rng):
    A = rng.standard_normal((8, 6)).astype(np.float32)
    d = dat.distribute(A, procs=range(4), dist=(4, 1))

    def untraceable(row):
        return np.sort(np.asarray(row))      # numpy concretizes the tracer

    with pytest.warns(RuntimeWarning, match="cannot be jax-traced"):
        got = dat.mapslices(untraceable, d, (1,))
    np.testing.assert_allclose(np.asarray(got), np.sort(A, axis=1),
                               rtol=1e-5)


def test_untraceable_reduce_warns(rng):
    d = dat.distribute(np.arange(16, dtype=np.float32))

    def pyop(a, b):
        return max(float(a), float(b))       # branches on concrete values

    with pytest.warns(RuntimeWarning, match="cannot be jax-traced"):
        got = dat.dreduce(pyop, d)
    assert float(got) == 15.0


def test_untraceable_sort_by_warns(rng):
    x = rng.standard_normal(32).astype(np.float32)
    d = dat.distribute(x)

    def pyby(v):
        return -float(v)                     # concretizes

    with pytest.warns(RuntimeWarning, match="cannot be jax-traced"):
        got = dat.dsort(d, by=pyby)
    np.testing.assert_array_equal(np.asarray(got), np.sort(x)[::-1])


def test_fft_conv_host_paths_warn(rng):
    # pinned here as part of the one-policy audit (also covered in their
    # own suites): dfft uneven and dconv2d multi-dim-grid host gathers
    V = dat.distribute(rng.standard_normal(50).astype(np.float32),
                       procs=range(4))
    with pytest.warns(RuntimeWarning, match="gathering"):
        dat.dfft(V)
    A = dat.distribute(rng.standard_normal((50, 16)).astype(np.float32),
                       procs=range(4), dist=(4, 1))   # uneven cuts
    k = np.ones((3, 3), np.float32)
    with pytest.warns(RuntimeWarning):
        dat.dconv2d(A, k)
