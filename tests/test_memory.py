"""HBM memory ledger + flight recorder tests.

The ledger must mirror lifecycle truth exactly: physical (shard-sum)
bytes from constructor to close/finalizer, buffers co-owned through
``_BufShare`` counted once, rebinds swapping entries in place, and the
whole thing draining to zero with the registry.  The reconciliation test
is the acceptance check: ledger live-bytes track ``jax.live_arrays()``
deltas within 1% at every phase boundary of a scripted workload.  The
flight recorder must leave exactly one postmortem bundle per crash
(spmd failure, CollectiveDivergenceError, djit trace error, SIGUSR1,
on-demand), containing the event ring, open spans, per-device ledger,
and registry census."""

import gc
import json
import os
import signal
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import distributedarrays_tpu as dat
from distributedarrays_tpu import telemetry
from distributedarrays_tpu.darray import DArray
from distributedarrays_tpu.parallel import reshard as R
from distributedarrays_tpu.telemetry import flight, memory as tmem
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)
from distributedarrays_tpu.telemetry.summarize import (read_journal,
                                                       summarize)
from distributedarrays_tpu.utils import checkpoint


def _sharding_for(shape, grid):
    from distributedarrays_tpu import layout as L
    return L.sharding_for(list(range(int(np.prod(grid)))), grid, shape)


# ---------------------------------------------------------------------------
# ledger: lifecycle accounting
# ---------------------------------------------------------------------------


def test_ctor_and_close_account_physical_bytes(telemetry_capture):
    tm = telemetry_capture
    base = tmem.live_bytes()
    d = dat.dzeros((64, 64))                      # 16 KiB f32, even layout
    assert tmem.live_bytes() - base == 64 * 64 * 4
    # per-device: one shard per device on the 8-device mesh
    snap = tm.mem()
    assert len(snap["by_device"]) == 8
    assert sum(v["live_bytes"] for v in snap["by_device"].values()) == \
        snap["live_bytes"]
    d.close()
    assert tmem.live_bytes() == base
    # journal carries the alloc/free pair with running live bytes
    names = [e["name"] for e in tm.events("hbm")]
    assert "alloc" in names and "free" in names


def test_uneven_layout_counts_padded_physical_bytes(telemetry_capture):
    base = tmem.live_bytes()
    d = dat.distribute(np.arange(70, dtype=np.float32).reshape(10, 7))
    # the at-rest buffer is the blocked-padded physical form — the ledger
    # reports what HBM actually holds, not the logical 280 bytes
    assert tmem.live_bytes() - base == d.garray_padded.nbytes
    d.close()
    assert tmem.live_bytes() == base


def test_rebind_swaps_entry_not_duplicates(telemetry_capture):
    base = tmem.live_bytes()
    d = dat.dzeros((32, 32))
    one = tmem.live_bytes() - base
    d.fill_(3.0)                                   # rebind, same size
    assert tmem.live_bytes() - base == one
    d[2:5, :] = 7.0                                # mutate → rebind
    assert tmem.live_bytes() - base == one
    d.close()
    assert tmem.live_bytes() == base


def test_bufshare_counted_once_released_by_last_owner(telemetry_capture):
    base = tmem.live_bytes()
    a = dat.distribute(np.ones((32, 16), np.float32))
    nb = a._data.nbytes
    tmem.reset_peak()
    b = dat.samedist(a, a)                         # aligned: co-owns a's buf
    assert b.garray is a.garray
    assert tmem.live_bytes() - base == nb, \
        "co-owned buffer must be counted exactly once"
    # not even TRANSIENTLY double-counted: the dst ctor joins the
    # existing entry by buffer identity, so the peak watermark for the
    # zero-copy fast path never sees 2x the buffer
    assert tmem.peak_bytes() - base <= nb
    a.close()                                      # first owner leaves
    assert tmem.live_bytes() - base == nb
    assert not b.garray.is_deleted()
    b.close()                                      # last owner frees
    assert tmem.live_bytes() == base


def test_share_then_rebind_departs_group(telemetry_capture):
    base = tmem.live_bytes()
    a = dat.distribute(np.ones((32, 16), np.float32))
    nb = a._data.nbytes
    b = dat.samedist(a, a)
    b.fill_(2.0)          # b rebinds to a fresh buffer → two buffers live
    assert tmem.live_bytes() - base == 2 * nb
    a.close()
    b.close()
    assert tmem.live_bytes() == base


def test_finalizer_drains_ledger(telemetry_capture):
    base = tmem.live_bytes()

    def scope():
        dat.drand((16, 16))
    scope()
    gc.collect()
    assert tmem.live_bytes() == base


def test_allocation_site_attribution(telemetry_capture):
    tm = telemetry_capture
    with tm.span("workload.phase1"):
        d = dat.dzeros((16, 16))
    ents = tmem.entries()
    mine = [e for e in ents if list(d.id) in e["owners"]]
    assert mine, ents
    assert mine[0]["span"] == "workload.phase1"
    assert mine[0]["stack"], "truncated stack expected by default"
    assert any("test_memory.py" in fr for fr in mine[0]["stack"])
    d.close()


def test_peak_watermark_and_reset(telemetry_capture):
    base = tmem.live_bytes()
    tmem.reset_peak()
    d = dat.dzeros((64, 64))
    d.close()
    assert tmem.peak_bytes() >= base + 64 * 64 * 4
    tmem.reset_peak()
    assert tmem.peak_bytes() == tmem.live_bytes()


# ---------------------------------------------------------------------------
# acceptance: reconciliation against jax.live_arrays()
# ---------------------------------------------------------------------------


def _jax_live_bytes():
    # physical bytes, deduped by device buffer: jax.live_arrays() lists
    # a sharded global array AND its per-shard component arrays, which
    # alias the same device buffers
    seen = set()
    total = 0
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            for s in a.addressable_shards:
                key = (getattr(s.device, "id", None),
                       s.data.unsafe_buffer_pointer())
                if key in seen:
                    continue
                seen.add(key)
                total += s.data.nbytes
        except Exception:
            total += getattr(a, "nbytes", 0) or 0
    return total


def test_reconciliation_scripted_workload(telemetry_capture, tmp_path, rng):
    # warm the compile caches with the shapes the workload uses, so jit
    # constants materialized during the phases don't drift the baseline
    w = dat.dzeros((256, 256))
    w.fill_(1.0)
    dat.copyto_(dat.dzeros((256, 256), dist=(1, 8)), w)
    dat.d_closeall()
    gc.collect()
    base_jax = _jax_live_bytes()
    base_ledger = tmem.live_bytes()
    eps = 16 * 1024                                # stray keys/consts slack

    def check_phase(phase):
        gc.collect()
        ledger = tmem.live_bytes() - base_ledger
        delta = _jax_live_bytes() - base_jax
        tol = max(0.01 * max(ledger, delta), eps)
        assert abs(ledger - delta) <= tol, \
            (phase, ledger, delta, telemetry.leak_census())

    # phase 1: constructors
    a = dat.dzeros((256, 256))                     # 256 KiB
    b = dat.distribute(rng.standard_normal((256, 256)).astype(np.float32))
    check_phase("ctors")
    # phase 2: reshard (divisible single-axis repartition)
    dest = dat.dzeros((256, 256), dist=(1, 8))
    dat.copyto_(dest, b)
    check_phase("reshard")
    # phase 3: mutate
    a[10:200, 5:50] = 3.0
    check_phase("mutate")
    # phase 4: checkpoint round-trip
    checkpoint.save(tmp_path / "ckpt", {"a": a})
    restored = checkpoint.load(tmp_path / "ckpt")["a"]
    assert isinstance(restored, DArray)
    check_phase("checkpoint")
    # phase 5: close everything — the ledger must drain to zero
    dat.d_closeall()
    gc.collect()
    assert tmem.live_bytes() == 0
    check_phase("closed")


# ---------------------------------------------------------------------------
# acceptance: reshard staging bound observed
# ---------------------------------------------------------------------------


def test_reshard_staging_highwater_within_chunk_bound(telemetry_capture,
                                                      rng, monkeypatch):
    # NB: the staging figure is plan-derived (local shard / nchunks), so
    # this audits the chunking the planner actually CHOSE against the
    # budget — a regression where _pick_chunking stops chunking (nchunks
    # collapses to 1) blows the 2x bound and fails here
    monkeypatch.setenv("DA_TPU_RESHARD_CHUNK_MB", "0.0005")  # 524 bytes
    target = int(0.0005 * 1024 * 1024)
    shape = (64, 48)
    A = rng.standard_normal(shape).astype(np.float32)
    src, dst = _sharding_for(shape, (8, 1)), _sharding_for(shape, (1, 8))
    x = jax.device_put(A, src)
    plan = R.plan_reshard(x, dst)
    assert plan.strategy == "all_to_all" and plan.nchunks > 1
    y = R.reshard(x, dst, plan=plan)
    np.testing.assert_array_equal(np.asarray(y), A)
    peak = tmem.staging_peak("reshard.all_to_all")
    assert 0 < peak <= 2 * target, \
        f"staging high-water {peak} exceeds 2x chunk target {target}"
    # the staging transient is journaled (Perfetto counter source)
    evs = [e for e in telemetry.events("hbm") if e.get("name") == "staging"]
    assert any(e.get("tag") == "reshard.all_to_all" for e in evs)
    # ...and released: live staging back to zero
    assert telemetry.report()["memory"]["staging"]["live_bytes"] == 0


# ---------------------------------------------------------------------------
# leak census
# ---------------------------------------------------------------------------


def test_leak_census_classifies_three_ways(telemetry_capture):
    d = dat.dzeros((32, 32))                       # ledger-tracked
    foreign = jnp.ones((16, 16))                   # untracked-foreign
    foreign.block_until_ready()
    census = telemetry.leak_census()
    assert census["ledger_tracked"]["count"] >= 1
    assert census["ledger_tracked"]["bytes"] >= 32 * 32 * 4
    assert census["untracked_foreign"]["count"] >= 1
    assert census["deleted_but_registered"] == {"bytes": 0, "count": 0}
    # now delete the device buffer behind the ledger's back: the census
    # must flag the entry as deleted-but-registered
    d._data.delete()
    census = telemetry.leak_census()
    assert census["deleted_but_registered"]["count"] == 1
    assert census["deleted_but_registered"]["bytes"] == 32 * 32 * 4
    d.close()
    del foreign


# ---------------------------------------------------------------------------
# satellite: hardened d_closeall
# ---------------------------------------------------------------------------


def test_d_closeall_closes_rest_and_reraises_first(telemetry_capture,
                                                   monkeypatch):
    tm = telemetry_capture
    a = dat.dzeros((8, 8))
    b = dat.dzeros((8, 8))
    c = dat.dzeros((8, 8))
    orig = DArray._close

    def bad_close(self, _unregister=True):
        if self.id == b.id:
            raise RuntimeError("boom: close failed")
        return orig(self, _unregister=_unregister)

    with monkeypatch.context() as mp:
        mp.setattr(DArray, "_close", bad_close)
        with pytest.raises(RuntimeError, match="boom"):
            dat.d_closeall()
    # the failing array must NOT strand the others: all closed, registry
    # empty, ledger holds only b's bytes
    assert a._closed and c._closed and not b._closed
    assert dat.live_ids() == []
    assert tmem.live_bytes() == 8 * 8 * 4
    evs = [e for e in tm.events("lifecycle") if e["name"] == "closeall"]
    assert evs and evs[-1]["closed"] == 2 and evs[-1]["errors"] == 1
    assert evs[-1]["freed_bytes"] == 2 * 8 * 8 * 4
    b.close()                                      # real close drains it
    assert tmem.live_bytes() == 0


# ---------------------------------------------------------------------------
# satellite: host/pid fields + per-host summarize grouping
# ---------------------------------------------------------------------------


def test_events_carry_host_and_pid(telemetry_capture):
    tm = telemetry_capture
    tm.event("x", "y")
    ev = tm.events("x")[0]
    assert ev["pid"] == os.getpid()
    assert isinstance(ev["host"], str) and ev["host"]


def test_summarize_groups_by_host_when_multihost(telemetry_capture):
    tm = telemetry_capture
    tm.event("comm", "reshard", bytes=100)
    tm.event("comm", "reshard", bytes=50)
    evs = [dict(e) for e in tm.events()]
    # simulate a merged multihost journal: second host's events appended
    merged = evs + [{**e, "host": "other-host", "pid": 999} for e in evs]
    s = summarize(merged)
    assert len(s["hosts"]) == 2
    this = [h for h in s["hosts"] if h != "other-host"][0]
    assert s["by_host"]["other-host"]["comm_bytes"] == 150
    assert s["by_host"][this]["comm_bytes"] == 150
    import io
    buf = io.StringIO()
    from distributedarrays_tpu.telemetry.summarize import format_summary
    format_summary(s, buf)
    text = buf.getvalue()
    assert "hosts (2):" in text and "other-host" in text
    # single-host journals keep the old flat rendering
    s1 = summarize(evs)
    assert len(s1["hosts"]) == 1
    buf = io.StringIO()
    format_summary(s1, buf)
    assert "hosts (" not in buf.getvalue()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_postmortem_on_demand_bundle(telemetry_capture, tmp_path):
    tm = telemetry_capture
    d = dat.dzeros((16, 16))
    tm.event("workload", "marker", step=7)
    with tm.span("outer"):
        path = telemetry.postmortem()
    assert path is not None and os.path.exists(path)
    b = json.load(open(path))
    assert b["kind"] == "da_tpu_postmortem"
    assert b["reason"] == "on_demand"
    assert b["host"] and b["pid"] == os.getpid()
    # ring contains the marker event
    assert any(e.get("cat") == "workload" for e in b["ring"])
    # the open-span stack captured the span we were inside
    assert any(s["name"] == "outer" and s["dur"] is None
               for s in b["open_spans"])
    # ledger + census sections present and live
    assert b["ledger"]["live_bytes"] >= 16 * 16 * 4
    assert b["registry_census"]["live"] >= 1
    assert "leak_census" in b
    d.close()


def test_divergence_produces_one_bundle(telemetry_capture, monkeypatch):
    tm = telemetry_capture
    monkeypatch.setenv("DA_TPU_CHECK_DIVERGENCE", "1")
    from distributedarrays_tpu.parallel import spmd_mode as sm
    from distributedarrays_tpu.analysis.divergence import \
        CollectiveDivergenceError
    d = dat.dzeros((8, 8))                         # ledger content at crash

    def f():
        if sm.myid() == 0:  # dalint: disable=DAL010 — seeded divergence: flight-recorder bundle fixture; statically cross-validated via verify-spmd
            sm.barrier()

    with pytest.raises(CollectiveDivergenceError):
        sm.spmd(f, pids=[0, 1], timeout=30)
    bundle = flight.last_bundle()
    assert bundle is not None
    assert bundle["reason"] == "exception:divergence"
    assert bundle["exception"]["type"] == "CollectiveDivergenceError"
    assert bundle["ledger"]["live_bytes"] >= 8 * 8 * 4
    assert bundle["registry_census"]["live"] >= 1
    assert bundle["divergence"], "divergence events missing from bundle"
    # exactly ONE bundle for this crash: the divergence checker bundled
    # it and the spmd driver's hook deduped on the exception object
    jdir = os.path.dirname(tm.journal_path())
    bundles = [f for f in os.listdir(jdir) if f.startswith("postmortem-")]
    assert len(bundles) == 1, bundles
    d.close()


def test_djit_crash_records_bundle(telemetry_capture):
    bad = dat.djit(lambda x: jnp.dot(x, jnp.ones((3, 3), np.float32)))
    d = dat.dzeros((4, 4))
    with pytest.raises(Exception):
        bad(d)
    b = flight.last_bundle()
    assert b is not None and b["reason"] == "exception:djit"
    d.close()


def test_spmd_failure_records_bundle(telemetry_capture):
    from distributedarrays_tpu.parallel import spmd_mode as sm

    def f():
        if sm.myid() == 1:
            raise ValueError("rank 1 exploded")

    with pytest.raises(RuntimeError, match="rank 1"):
        sm.spmd(f, pids=[0, 1], timeout=30)
    b = flight.last_bundle()
    assert b is not None and b["reason"] == "exception:spmd"
    assert b["exception"]["type"] == "ValueError"


def test_sigusr1_dumps_bundle(telemetry_capture):
    assert flight.install_sigusr1()
    os.kill(os.getpid(), signal.SIGUSR1)
    deadline = time.monotonic() + 5
    while flight.last_bundle() is None and time.monotonic() < deadline:
        time.sleep(0.01)
    b = flight.last_bundle()
    assert b is not None and b["reason"] == "sigusr1"


def test_flight_disabled_is_noop(telemetry_capture, tmp_path):
    tm = telemetry_capture
    tm.disable()
    try:
        assert telemetry.postmortem() is None
        assert flight.record_crash(ValueError("x"), where="test") is None
        assert flight.last_bundle() is None
    finally:
        tm.enable()


def test_bundle_cap_limits_writes(telemetry_capture, monkeypatch):
    monkeypatch.setenv("DA_TPU_FLIGHT_MAX", "2")
    p1 = flight.record_crash(ValueError("a"), where="t")
    p2 = flight.record_crash(ValueError("b"), where="t")
    p3 = flight.record_crash(ValueError("c"), where="t")
    assert p1 is not None and p2 is not None and p3 is None
    # same exception object never bundled twice
    e = ValueError("dup")
    monkeypatch.setenv("DA_TPU_FLIGHT_MAX", "10")
    assert flight.record_crash(e, where="t") is not None
    assert flight.record_crash(e, where="t") is None


def test_bundle_cap_holds_in_memory_only_mode(telemetry_capture,
                                              monkeypatch):
    tm = telemetry_capture
    tm.configure(None)                 # no journal, no flight dir:
    monkeypatch.delenv("DA_TPU_FLIGHT_DIR", raising=False)
    monkeypatch.setenv("DA_TPU_FLIGHT_MAX", "2")
    flight.record_crash(ValueError("first"), where="t")
    flight.record_crash(ValueError("second"), where="t")
    flight.record_crash(ValueError("third"), where="t")
    b = flight.last_bundle()
    # the cap bounds bundle ASSEMBLY, not just file writes: the third
    # crash must not have built a bundle at all
    assert b is not None and b["exception"]["message"] == "second"


# ---------------------------------------------------------------------------
# exports: Prometheus gauges + Perfetto counter track
# ---------------------------------------------------------------------------


def test_prometheus_exports_hbm_gauges(telemetry_capture):
    d = dat.dzeros((64, 64))
    text = telemetry.to_prometheus()
    assert 'da_tpu_hbm_live_bytes{device="all"} 16384' in text
    assert 'da_tpu_hbm_live_bytes{device="0"} 2048' in text
    assert "da_tpu_hbm_peak_bytes" in text
    assert "da_tpu_hbm_tracked_arrays 1" in text
    d.close()


def test_perfetto_hbm_counter_track(telemetry_capture):
    tm = telemetry_capture
    d = dat.dzeros((32, 32))
    d.close()
    trace = telemetry.to_perfetto(read_journal(tm.journal_path()))
    counters = [e for e in trace["traceEvents"]
                if e["ph"] == "C" and e["name"] == "hbm_bytes"]
    assert counters, "no HBM counter track in the Perfetto export"
    assert any(c["args"].get("live", 0) >= 32 * 32 * 4 for c in counters)
    for c in counters:                             # strict-viewer keys
        for key in ("ph", "ts", "dur", "pid", "tid"):
            assert key in c


# ---------------------------------------------------------------------------
# CLI: mem / postmortem subcommands, rc-2 journal guards
# ---------------------------------------------------------------------------


def _cli(argv):
    from distributedarrays_tpu.telemetry.__main__ import main
    return main(argv)


def test_cli_mem_from_journal_and_report(telemetry_capture, tmp_path,
                                         capsys):
    tm = telemetry_capture
    d = dat.dzeros((64, 64))
    rc = _cli(["mem", tm.journal_path()])
    out = capsys.readouterr().out
    assert rc == 0
    assert "hbm peak:" in out and "16.0 KiB" in out
    assert "top allocation sites:" in out
    # report input carries the per-device table
    report_path = str(tmp_path / "report.json")
    tm.dump(report_path)
    rc = _cli(["mem", report_path])
    out = capsys.readouterr().out
    assert rc == 0 and "per device:" in out
    rc = _cli(["mem", tm.journal_path(), "--json"])
    mem = json.loads(capsys.readouterr().out)
    assert rc == 0 and mem["peak_bytes"] >= 16384
    d.close()


def test_cli_postmortem_renders_bundle(telemetry_capture, capsys):
    d = dat.dzeros((16, 16))
    path = telemetry.postmortem()
    d.close()
    rc = _cli(["postmortem", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "postmortem: on_demand" in out
    assert "registry census:" in out and "event ring tail" in out
    rc = _cli(["postmortem", path, "--json"])
    b = json.loads(capsys.readouterr().out)
    assert rc == 0 and b["kind"] == "da_tpu_postmortem"


def test_cli_rc2_on_missing_empty_capped(telemetry_capture, tmp_path,
                                         capsys, monkeypatch):
    tm = telemetry_capture
    # missing
    rc = _cli(["summarize", str(tmp_path / "nope.jsonl")])
    assert rc == 2
    assert "cannot read input" in capsys.readouterr().err
    # empty
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    for sub in ("summarize", "trace"):
        rc = _cli([sub, str(empty)])
        assert rc == 2, sub
        assert "journal is empty" in capsys.readouterr().err
    rc = _cli([str(empty)])                        # legacy bare form
    assert rc == 2
    capsys.readouterr()
    # cap-truncated LEGACY latch (current writers rotate instead; an
    # older writer — or one whose rotation os.replace failed — leaves a
    # journal.capped marker): the latch is printed, rc 2
    capped = tmp_path / "capped.jsonl"
    capped.write_text(
        json.dumps({"seq": 0, "t": 0.1, "cat": "filler", "name": "e"})
        + "\n"
        + json.dumps({"seq": 1, "t": 0.2, "cat": "journal",
                      "name": "capped", "bytes_written": 1024,
                      "max_bytes": 1024}) + "\n")
    rc = _cli(["summarize", str(capped)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "cap-truncated" in err and "journal.capped" in err
    assert "rotate" in err                    # points at the new behavior
    # prom and mem must refuse the truncated journal too — a dashboard
    # (or ledger view) fed under-counted totals is worse than none
    for sub in ("prom", "mem"):
        rc = _cli([sub, str(capped)])
        err = capsys.readouterr().err
        assert rc == 2 and "cap-truncated" in err, sub


# ---------------------------------------------------------------------------
# satellite: fixture helpers
# ---------------------------------------------------------------------------


def test_fixture_assert_counter_and_mem(telemetry_capture):
    tm = telemetry_capture
    tm.count("my.counter", 3, kind="x")
    assert tm.assert_counter("my.counter", 3, kind="x") == 3
    with pytest.raises(AssertionError, match="recorded counters"):
        tm.assert_counter("my.counter", 4, kind="x")
    with pytest.raises(AssertionError):
        tm.assert_counter("never.recorded")
    d = dat.dzeros((16, 16))
    m = tm.mem()
    assert m["live_bytes"] >= 16 * 16 * 4 and m["tracked_arrays"] >= 1
    d.close()


def test_disabled_mode_ledger_is_single_check(telemetry_capture):
    tm = telemetry_capture
    tm.disable()
    try:
        d = dat.dzeros((32, 32))                   # not tracked
        assert tmem.live_bytes() == 0
        assert tmem.tracked_count() == 0
        with tmem.staging("x", 1 << 20):
            assert tmem.staging_peak() == 0
        d.close()                                  # untrack no-ops cleanly
        assert tmem.live_bytes() == 0
    finally:
        tm.enable()


def test_disable_midway_still_drains(telemetry_capture):
    tm = telemetry_capture
    d = dat.dzeros((32, 32))
    assert tmem.live_bytes() > 0
    tm.disable()
    try:
        d.close()                                  # tracked while enabled:
        assert tmem.live_bytes() == 0              # close must still drain
    finally:
        tm.enable()
