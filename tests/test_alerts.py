"""SLO burn-rate alerting + health sampler suite: the fast/slow window
state machine (fire on both burns, clear on half the fast burn —
hysteresis), the five stock rules, the incremental shed-fraction signal,
sampler lifecycle (env-gated, idempotent, disabled-mode no-op, tick
contents), and the Prometheus exposition edge cases the observatory
leans on: per-endpoint SLO bucket histograms, label escaping, and the
``da_tpu_alert_active`` gauge family.
"""

import json

import pytest

from distributedarrays_tpu.telemetry import alerts, core, export
from distributedarrays_tpu.telemetry.fixtures import telemetry_capture  # noqa: F401 (fixture)


@pytest.fixture(autouse=True)
def _no_leaked_sampler():
    yield
    alerts.stop_sampler()
    alerts.default_manager().reset()


def _p99_rule(**kw):
    kw.setdefault("fast_window_s", 1.0)
    kw.setdefault("slow_window_s", 4.0)
    return alerts.AlertRule(
        "serve_p99", lambda: core.gauge_value("serve.request_p99_s"),
        threshold=0.5, **kw)


# ---------------------------------------------------------------------------
# the burn-rate state machine
# ---------------------------------------------------------------------------


def test_alert_fires_on_both_burns_and_clears_with_hysteresis(
        telemetry_capture):
    tm = telemetry_capture
    mgr = alerts.AlertManager([_p99_rule()])
    tm.set_gauge("serve.request_p99_s", 2.0)          # breaching
    assert mgr.evaluate(now=10.0)["serve_p99"] is True
    assert mgr.firing() == ["serve_p99"]
    fired = [e for e in tm.events("alert") if e["state"] == "firing"]
    assert len(fired) == 1
    assert fired[0]["name"] == "serve_p99"
    assert fired[0]["burn_fast"] == 1.0
    # healthy samples arrive; while the breach is still inside the fast
    # window the burn sits at 0.5 > fast_burn/2 -> STAYS firing
    tm.set_gauge("serve.request_p99_s", 0.01)
    assert mgr.evaluate(now=10.5)["serve_p99"] is True
    # once the breach ages out of the fast window the burn drops to 0
    assert mgr.evaluate(now=11.5)["serve_p99"] is False
    cleared = [e for e in tm.events("alert") if e["state"] == "cleared"]
    assert len(cleared) == 1
    assert mgr.firing() == []
    # exactly one transition each way, no flapping
    assert tm.counter_value("alerts.transitions", alert="serve_p99",
                            state="firing") == 1
    assert tm.counter_value("alerts.transitions", alert="serve_p99",
                            state="cleared") == 1


def test_alert_needs_the_slow_burn_too(telemetry_capture):
    tm = telemetry_capture
    # slow_burn 0.5 over a 10s window: one breaching blip among many
    # healthy samples must NOT page
    mgr = alerts.AlertManager([_p99_rule(
        fast_window_s=1.0, slow_window_s=10.0, slow_burn=0.5)])
    tm.set_gauge("serve.request_p99_s", 0.01)
    for i in range(8):
        assert mgr.evaluate(now=float(i))["serve_p99"] is False
    tm.set_gauge("serve.request_p99_s", 2.0)
    # fast burn 1.0 but slow burn 1/9 < 0.5 -> still quiet
    assert mgr.evaluate(now=8.0)["serve_p99"] is False


def test_alert_no_sample_does_not_advance_windows(telemetry_capture):
    mgr = alerts.AlertManager([_p99_rule()])
    # gauge never set: signal returns None -> no sample, never fires
    assert mgr.evaluate(now=1.0)["serve_p99"] is False
    assert mgr.evaluate(now=2.0)["serve_p99"] is False


def test_alert_gauge_mirrors_firing_state(telemetry_capture):
    tm = telemetry_capture
    mgr = alerts.AlertManager([_p99_rule()])
    tm.set_gauge("serve.request_p99_s", 2.0)
    mgr.evaluate(now=10.0)
    assert tm.gauge_value("alert.active", alert="serve_p99") == 1.0
    tm.set_gauge("serve.request_p99_s", 0.01)
    mgr.evaluate(now=11.5)
    assert tm.gauge_value("alert.active", alert="serve_p99") == 0.0


def test_alert_less_than_op_for_live_devices(telemetry_capture):
    tm = telemetry_capture
    rule = alerts.AlertRule(
        "live_devices", lambda: tm.gauge_value("elastic.live_devices"),
        threshold=6.0, op="<", fast_window_s=1.0, slow_window_s=4.0)
    mgr = alerts.AlertManager([rule])
    tm.set_gauge("elastic.live_devices", 8.0)
    assert mgr.evaluate(now=1.0)["live_devices"] is False
    tm.set_gauge("elastic.live_devices", 5.0)
    assert mgr.evaluate(now=1.5)["live_devices"] is True


def test_broken_signal_is_no_sample_not_a_crash(telemetry_capture):
    def boom():
        raise RuntimeError("scraper exploded")
    mgr = alerts.AlertManager([alerts.AlertRule("broken", boom)])
    assert mgr.evaluate(now=1.0)["broken"] is False


def test_default_rules_construction():
    base = alerts.default_rules()
    assert [r.name for r in base] == ["serve_p99", "serve_shed"]
    full = alerts.default_rules(step_time_slo_s=1.0,
                                hbm_budget_bytes=1 << 30,
                                min_live_devices=6)
    assert [r.name for r in full] == [
        "serve_p99", "serve_shed", "train_step_time", "hbm_live",
        "live_devices"]
    by_name = {r.name: r for r in full}
    assert by_name["live_devices"].op == "<"
    assert by_name["hbm_live"].threshold == pytest.approx(0.9 * (1 << 30))


def test_shed_fraction_signal_is_incremental(telemetry_capture):
    tm = telemetry_capture
    sig = alerts._shed_fraction_signal()
    assert sig() is None                       # no traffic yet
    tm.count("serve.submitted", n=10, endpoint="a")
    tm.count("serve.shed", n=5, endpoint="a")
    assert sig() == pytest.approx(0.5)
    # next interval: clean traffic -> the fraction RESETS (not the
    # process-lifetime average, which would never clear)
    tm.count("serve.submitted", n=10, endpoint="a")
    assert sig() == pytest.approx(0.0)
    assert sig() is None                       # and quiet again


# ---------------------------------------------------------------------------
# the health sampler
# ---------------------------------------------------------------------------


def test_sampler_env_gated_and_idempotent(telemetry_capture, monkeypatch):
    monkeypatch.delenv(alerts.SAMPLE_ENV, raising=False)
    assert alerts.start_sampler() is False     # no env, no interval
    monkeypatch.setenv(alerts.SAMPLE_ENV, "not-a-number")
    assert alerts.start_sampler() is False
    monkeypatch.setenv(alerts.SAMPLE_ENV, "0.05")
    assert alerts.start_sampler() is True
    assert alerts.sampler_running()
    assert alerts.start_sampler() is True      # idempotent join
    alerts.stop_sampler()
    assert not alerts.sampler_running()


def test_sampler_tick_snapshots_health(telemetry_capture):
    tm = telemetry_capture
    tm.set_gauge("serve.queue_depth", 3.0)
    s = alerts._HealthSampler(0.1, alerts.AlertManager())
    s._tick()
    samples = list(tm.events("sample"))
    health = [e for e in samples if e["name"] == "health"]
    assert len(health) == 1
    assert health[0]["queue_depth"] == 3.0
    assert tm.gauge_value("health.hbm_live_bytes") is not None


def test_sampler_disabled_telemetry_is_noop(monkeypatch):
    monkeypatch.setenv(alerts.SAMPLE_ENV, "0.05")
    core.disable()
    try:
        assert alerts.start_sampler() is False
        assert not alerts.sampler_running()
        # the evaluation entry point is one boolean check when disabled
        assert alerts.AlertManager([_p99_rule()]).evaluate() == {}
    finally:
        core.enable()


# ---------------------------------------------------------------------------
# Prometheus exposition edge cases
# ---------------------------------------------------------------------------


def test_prom_multi_endpoint_slo_histograms(telemetry_capture):
    tm = telemetry_capture
    buckets = (0.01, 0.1, 1.0)
    for dt in (0.005, 0.05, 0.5):
        tm.observe("serve.slo.request_s", dt, buckets=buckets,
                   endpoint="chat")
    tm.observe("serve.slo.request_s", 5.0, buckets=buckets,
               endpoint="embed")
    text = export.to_prometheus(tm.report())
    # per-endpoint cumulative le series under ONE histogram family
    assert text.count("# TYPE da_tpu_serve_slo_request_s histogram") == 1
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="chat",le="0.01"} 1' \
        in text
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="chat",le="0.1"} 2' \
        in text
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="chat",le="1"} 3' \
        in text
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="chat",le="+Inf"} 3' \
        in text
    # the other endpoint's overflow lands only in +Inf
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="embed",le="1"} 0' \
        in text
    assert 'da_tpu_serve_slo_request_s_bucket{endpoint="embed",le="+Inf"} 1' \
        in text
    assert 'da_tpu_serve_slo_request_s_count{endpoint="chat"} 3' in text


def test_prom_label_escaping(telemetry_capture):
    tm = telemetry_capture
    tm.count("fallback.keys", key='say "hi"\\now', site="a\nb")
    text = export.to_prometheus(tm.report())
    line = next(l for l in text.splitlines()
                if l.startswith("da_tpu_fallback_keys_total{"))
    assert r'key="say \"hi\"\\now"' in line
    assert r'site="a\nb"' in line
    # still one sample, value intact
    assert line.endswith(" 1")


def test_prom_alert_active_gauge_family(telemetry_capture):
    tm = telemetry_capture
    mgr = alerts.AlertManager([_p99_rule()])
    tm.set_gauge("serve.request_p99_s", 2.0)
    mgr.evaluate(now=10.0)
    text = export.to_prometheus(tm.report())
    assert "# TYPE da_tpu_alert_active gauge" in text
    assert 'da_tpu_alert_active{alert="serve_p99"} 1' in text
    assert 'da_tpu_alerts_transitions_total{alert="serve_p99",' \
           'state="firing"} 1' in text
    tm.set_gauge("serve.request_p99_s", 0.01)
    mgr.evaluate(now=11.5)
    text = export.to_prometheus(tm.report())
    assert 'da_tpu_alert_active{alert="serve_p99"} 0' in text


def test_prom_exposition_parses_as_families(telemetry_capture):
    """Every emitted line is either a comment or `name{labels} value` —
    a scrape-shaped smoke over the whole registry with alerts, SLO
    buckets and escaped labels all present at once."""
    tm = telemetry_capture
    tm.observe("serve.slo.request_s", 0.02, buckets=(0.01, 0.1),
               endpoint='we"ird')
    mgr = alerts.AlertManager([_p99_rule()])
    tm.set_gauge("serve.request_p99_s", 2.0)
    mgr.evaluate(now=1.0)
    for line in export.to_prometheus(tm.report()).splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert name.startswith("da_tpu_"), line
        float(line.rsplit(" ", 1)[1])          # value parses
