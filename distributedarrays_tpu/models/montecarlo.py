"""Monte-Carlo estimation — the classic distributed-arrays demo workload.

Julia's Distributed/DistributedArrays tutorials estimate π by scattering
random draws over workers and reducing hit counts; here the draws are
generated *on device* under jit with the target sharding (no host RNG, no
scatter) and the hit-count reduction is the usual local-reduce +
all-reduce.  Also includes a distributed payoff-style estimator to show
``ddata``-free reduction pipelines over huge sample counts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["pi_estimate", "expectation"]


@functools.lru_cache(maxsize=32)
def _pi_jit(n_per_call: int):
    def fn(key):
        xy = jax.random.uniform(key, (n_per_call, 2), jnp.float32)
        return jnp.sum((xy[:, 0] ** 2 + xy[:, 1] ** 2) <= 1.0)
    return jax.jit(fn)


def pi_estimate(n: int, seed: int = 0, batches: int = 1) -> float:
    """Estimate π from ``n`` uniform draws, generated on device."""
    if batches <= 0 or n < batches:
        raise ValueError(f"need 1 <= batches <= n, got n={n}, "
                         f"batches={batches}")
    per = n // batches
    key = jax.random.key(seed)
    hits = 0
    fn = _pi_jit(per)
    for _ in range(batches):
        key, sub = jax.random.split(key)
        hits += int(fn(sub))
    return 4.0 * hits / (per * batches)


@functools.lru_cache(maxsize=32)
def _expect_jit(f, n: int):
    def fn(key):
        x = jax.random.normal(key, (n,), jnp.float32)
        v = f(x)
        return jnp.mean(v), jnp.std(v) / jnp.sqrt(n)
    return jax.jit(fn)


def expectation(f, n: int, seed: int = 0):
    """E[f(X)], X ~ N(0,1): returns (estimate, standard error).  ``f`` must
    be a stable traceable callable (module-level, not a fresh lambda)."""
    est, se = _expect_jit(f, int(n))(jax.random.key(seed))
    return float(est), float(se)
