"""Distributed k-means: the owner-computes iterative workload demo.

The reference's docs motivate DArrays with exactly this shape of program —
iterate: each worker computes on its block, combine small results globally
(docs/src/index.md:43-48 work-to-communication guidance).  TPU-native, the
whole Lloyd iteration is one jitted program over the point-sharded DArray:
per-device assignment (distance matmul on the MXU) + psum-style global
centroid accumulation inserted by GSPMD, scanned for a fixed iteration
count so the loop compiles once.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..darray import DArray, _wrap_global

__all__ = ["kmeans", "assign"]


def _nearest(X, C):
    """Index of each point's nearest centroid via the matmul expansion
    |x - c|^2 = |x|^2 + |c|^2 - 2<x, c>  (MXU-friendly)."""
    x2 = jnp.sum(X * X, axis=1, keepdims=True)
    c2 = jnp.sum(C * C, axis=1)[None, :]
    return jnp.argmin(x2 + c2 - 2.0 * (X @ C.T), axis=1)


@functools.lru_cache(maxsize=32)
def _kmeans_jit(iters: int):
    def step(X, C):
        a = _nearest(X, C)                               # (n,)
        onehot = jax.nn.one_hot(a, C.shape[0], dtype=X.dtype)   # (n, k)
        counts = jnp.sum(onehot, axis=0)                 # (k,)
        sums = onehot.T @ X                              # (k, d)
        C_new = jnp.where(counts[:, None] > 0,
                          sums / jnp.maximum(counts[:, None], 1.0), C)
        shift = jnp.sum((C_new - C) ** 2)
        return C_new, shift

    def run(X, C0):
        def body(C, _):
            C, shift = step(X, C)
            return C, shift
        C, shifts = lax.scan(body, C0, None, length=iters)
        return C, shifts

    return jax.jit(run)


def kmeans(d: DArray, k: int, iters: int = 20, seed: int = 0):
    """Lloyd's algorithm on an (n, dim) point-sharded DArray.

    Returns ``(centroids (k, dim) jax.Array, shifts per iter)``.  Initial
    centroids are ``k`` rows sampled without replacement by ``seed``.  The
    argmin/one-hot/accumulate step runs sharded over the mesh; centroid
    reduction is the compiler-inserted all-reduce.
    """
    if d.ndim != 2:
        raise ValueError("kmeans expects an (n, dim) DArray")
    n = d.dims[0]
    if not (0 < k <= n):
        raise ValueError(f"need 0 < k <= n, got k={k}, n={n}")
    C0 = jnp.asarray(_kmeanspp_init(d, k, seed), dtype=d.dtype)
    C, shifts = _kmeans_jit(int(iters))(d.garray, C0)
    return C, np.asarray(shifts)


def _kmeanspp_init(d: DArray, k: int, seed: int) -> np.ndarray:
    """k-means++ seeding on a host-side sample (≤4096 points): spread the
    initial centroids proportionally to squared distance, avoiding the
    duplicate-cluster local optima of uniform random picks."""
    n = d.dims[0]
    rng = np.random.default_rng(seed)
    m = min(n, 4096)
    sel = np.sort(rng.choice(n, size=m, replace=False)) if m < n \
        else np.arange(n)
    S = np.asarray(jax.device_get(d.garray[jnp.asarray(sel)]), np.float32)
    C = np.empty((k, S.shape[1]), np.float32)
    C[0] = S[rng.integers(m)]
    d2 = np.sum((S - C[0]) ** 2, axis=1)
    for j in range(1, k):
        s = float(d2.sum())
        if s > 0:
            C[j] = S[rng.choice(m, p=d2 / s)]
        else:
            # all remaining sample points coincide with a centroid
            # (duplicate-heavy data): fall back to a uniform pick
            C[j] = S[rng.integers(m)]
        d2 = np.minimum(d2, np.sum((S - C[j]) ** 2, axis=1))
    return C


@functools.lru_cache(maxsize=None)
def _assign_jit():
    return jax.jit(_nearest)


def assign(d: DArray, centroids) -> DArray:
    """Nearest-centroid labels, sharded to follow ``d``'s row blocks: label
    block i lives with the first owner of row block i."""
    labels = _assign_jit()(d.garray, jnp.asarray(centroids))
    row_owners = [int(p) for p in
                  d.pids.reshape(d.pids.shape[0], -1)[:, 0]]
    return _wrap_global(labels, procs=row_owners,
                        dist=[d.pids.shape[0]])
