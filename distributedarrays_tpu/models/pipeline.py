"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference's parallelism inventory stops at data/tensor/ring patterns
(SURVEY.md §2 "DP/PP/EP: absent in reference — ring/halo + all-to-all
cover the communication substrate they'd need").  This module builds PP on
that substrate: each mesh rank along the ``pp`` axis owns one pipeline
stage's weights; activations flow stage-to-stage with ``lax.ppermute``
(the same neighbor shift as the halo exchange), and the whole
fill-steady-drain schedule is one ``lax.fori_loop`` inside ONE compiled
shard_map program — no per-tick dispatch, no host in the loop.

Schedule: with P stages and M microbatches, T = M + P - 1 ticks; at tick
``t`` stage ``s`` processes microbatch ``t - s`` (bubble ticks compute on
zeros and are masked out of the output).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import run_spmd, spmd_mesh

__all__ = ["pipeline_forward", "pipeline_train_step", "init_pipeline_params",
           "make_pp_mesh", "reference_forward"]


def make_pp_mesh(n_stages: int, axis: str = "pp") -> Mesh:
    return spmd_mesh(n_stages, axis)


def init_pipeline_params(key, n_stages: int, hidden: int,
                         dtype=jnp.float32):
    """One (hidden, hidden) layer + bias per stage, stacked on a leading
    stage axis so the stack shards P('pp', ...)."""
    keys = jax.random.split(key, n_stages)
    W = jnp.stack([
        jax.random.normal(k, (hidden, hidden), dtype) *
        jnp.asarray(np.sqrt(1.0 / hidden), dtype) for k in keys])
    b = jnp.zeros((n_stages, hidden), dtype)
    return {"W": W, "b": b}


def _stage_fn(x, W, b):
    return jax.nn.gelu(x @ W + b)


@functools.lru_cache(maxsize=32)
def _pipeline_jit(mesh):
    # one wrapper per mesh; jax retraces per microbatch shape internally
    axis = mesh.axis_names[0]
    nstg = mesh.shape[axis]

    def kernel(mb, W, b):
        # mb: (M, B, H) full microbatch stack (replicated);
        # W: (1, H, H), b: (1, H): this stage's weights
        me = lax.axis_index(axis)
        Ws, bs = W[0], b[0]
        M, B, H = mb.shape
        T = M + nstg - 1
        perm = [(i, i + 1) for i in range(nstg - 1)]     # no wraparound

        def tick(t, carry):
            recv, outs = carry
            # stage 0 injects microbatch t (zeros during drain ticks)
            mb_t = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            x = jnp.where(me == 0, jnp.where(t < M, 1.0, 0.0) * mb_t, recv)
            y = _stage_fn(x, Ws, bs)
            # last stage banks microbatch (t - nstg + 1) when valid
            oidx = jnp.clip(t - nstg + 1, 0, M - 1)
            valid = (me == nstg - 1) & (t - nstg + 1 >= 0)
            cur = lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, cur), oidx, 0)
            # activation advances one stage (non-wrapping shift)
            recv = lax.ppermute(y, axis, perm)
            return recv, outs

        recv0 = jnp.zeros((B, H), mb.dtype)
        outs0 = jnp.zeros((M, B, H), mb.dtype)
        _, outs = lax.fori_loop(0, T, tick, (recv0, outs0))
        # broadcast the last stage's banked outputs to every rank
        src = jnp.where(me == nstg - 1, 1.0, 0.0)
        return lax.psum(outs * src, axis)

    return run_spmd(
        kernel, mesh,
        in_specs=(P(), P(axis, None, None), P(axis, None)),
        out_specs=P())


def pipeline_forward(params, mb, mesh: Mesh):
    """Run the (M, B, H) microbatch stack through the pipeline; returns the
    (M, B, H) outputs (replicated)."""
    mb = jnp.asarray(mb)
    if mb.ndim != 3:
        raise ValueError(f"microbatches must be (M, B, H), got {mb.shape}")
    nstg = mesh.shape[mesh.axis_names[0]]
    if params["W"].shape[0] != nstg:
        raise ValueError(
            f"params have {params['W'].shape[0]} stages, mesh has {nstg}")
    return _pipeline_jit(mesh)(mb, params["W"], params["b"])


@functools.lru_cache(maxsize=32)
def _train_jit(mesh):
    fwd = _pipeline_jit(mesh)

    def loss_fn(params, mb, tgt):
        out = fwd(mb, params["W"], params["b"])
        return jnp.mean(jnp.square(out - tgt))

    def step(params, mb, tgt, lr):
        # lr rides as a traced scalar so schedules don't recompile
        loss, g = jax.value_and_grad(loss_fn)(params, mb, tgt)
        new = jax.tree_util.tree_map(lambda p, gg: p - lr * gg, params, g)
        return new, loss

    return jax.jit(step)


def pipeline_train_step(params, mb, tgt, mesh: Mesh, lr: float = 1e-2):
    """One SGD step through the pipeline: the backward pass re-traverses the
    schedule in reverse (ppermute transposes to the opposite shift), all
    inside the same compiled program.  Gradients match the sequential model
    exactly (see tests)."""
    return _train_jit(mesh)(params, jnp.asarray(mb), jnp.asarray(tgt),
                            jnp.float32(lr))


def reference_forward(params, mb):
    """Sequential oracle: apply every stage in order."""
    x = jnp.asarray(mb)
    for s in range(params["W"].shape[0]):
        x = _stage_fn(x, params["W"][s], params["b"][s])
    return x
