"""Pipeline parallelism: microbatch pipelining over a mesh axis.

The reference's parallelism inventory stops at data/tensor/ring patterns
(SURVEY.md §2 "DP/PP/EP: absent in reference — ring/halo + all-to-all
cover the communication substrate they'd need").  This module builds PP on
that substrate: each mesh rank along the ``pp`` axis owns one pipeline
stage's weights (a stack of ``n_layers`` dense layers); activations flow
stage-to-stage with ``lax.ppermute`` (the same neighbor shift as the halo
exchange), and the whole schedule is one ``lax.fori_loop`` inside ONE
compiled shard_map program — no per-tick dispatch, no host in the loop.

Two training schedules:

- ``pipeline_train_step`` — GPipe: autodiff through the fill-steady-drain
  forward (XLA saves per-tick residuals; activation memory grows with the
  microbatch count M).
- ``pipeline_train_step_1f1b`` — 1F1B: a hand-scheduled
  one-forward-one-backward interleave with per-stage ``jax.vjp``
  recomputation.  Activation memory is bounded by ``min(M, 2P-1)`` saved
  stage INPUTS per stage regardless of M (the 1F1B property); gradients
  are exactly the GPipe/sequential gradients (tests pin this).

Forward schedule: with P stages and M microbatches, stage ``s`` runs
microbatch ``t - s`` at tick ``t`` (bubble ticks compute on zeros and are
masked).  1F1B adds the backward wave: stage ``s`` runs the backward of
microbatch ``t - (2P - 2 - s)``, so gradients counterflow on the same
ring, and the loop closes after ``M + 2P - 2`` ticks.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import run_spmd, spmd_mesh

__all__ = ["pipeline_forward", "pipeline_train_step",
           "pipeline_train_step_1f1b", "init_pipeline_params",
           "make_pp_mesh", "reference_forward"]


def make_pp_mesh(n_stages: int, axis: str = "pp") -> Mesh:
    return spmd_mesh(n_stages, axis)


def init_pipeline_params(key, n_stages: int, hidden: int,
                         n_layers: int = 1, dtype=jnp.float32):
    """``n_layers`` (hidden, hidden) dense layers + biases per stage,
    stacked on a leading stage axis so the stacks shard P('pp', ...)."""
    keys = jax.random.split(key, n_stages * n_layers)
    sc = jnp.asarray(np.sqrt(1.0 / hidden), dtype)
    W = jnp.stack([
        jnp.stack([jax.random.normal(keys[s * n_layers + l],
                                     (hidden, hidden), dtype) * sc
                   for l in range(n_layers)])
        for s in range(n_stages)])                     # (S, L, H, H)
    b = jnp.zeros((n_stages, n_layers, hidden), dtype)
    return {"W": W, "b": b}


def _norm_params(params):
    """Accept the pre-multi-layer (S, H, H) weight shape as L=1."""
    W, b = params["W"], params["b"]
    if W.ndim == 3:
        W, b = W[:, None], b[:, None]
    return W, b


def _stage_fn(x, Ws, bs):
    """One stage: ``n_layers`` gelu-dense layers, scanned (Ws: (L, H, H))."""
    def layer(h, wb):
        W, b = wb
        return jax.nn.gelu(h @ W + b), None
    h, _ = lax.scan(layer, x, (Ws, bs))
    return h


@functools.lru_cache(maxsize=32)
def _pipeline_jit(mesh):
    # one wrapper per mesh; jax retraces per microbatch shape internally
    axis = mesh.axis_names[0]
    nstg = mesh.shape[axis]

    def kernel(mb, W, b):
        # mb: (M, B, H) full microbatch stack (replicated);
        # W: (1, L, H, H), b: (1, L, H): this stage's weights
        me = lax.axis_index(axis)
        Ws, bs = W[0], b[0]
        M, B, H = mb.shape
        T = M + nstg - 1
        perm = [(i, i + 1) for i in range(nstg - 1)]     # no wraparound

        def tick(t, carry, send=True):
            recv, outs = carry
            # stage 0 injects microbatch t (zeros during drain ticks)
            mb_t = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            x = jnp.where(me == 0, jnp.where(t < M, 1.0, 0.0) * mb_t, recv)
            y = _stage_fn(x, Ws, bs)
            # last stage banks microbatch (t - nstg + 1) when valid
            oidx = jnp.clip(t - nstg + 1, 0, M - 1)
            valid = (me == nstg - 1) & (t - nstg + 1 >= 0)
            cur = lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, cur), oidx, 0)
            # activation advances one stage (non-wrapping shift); the
            # final tick's send would be discarded with the loop carry —
            # skip it instead of paying the wire hop
            recv = lax.ppermute(y, axis, perm) if send else y
            return recv, outs

        recv0 = jnp.zeros((B, H), mb.dtype)
        outs0 = jnp.zeros((M, B, H), mb.dtype)
        carry = lax.fori_loop(0, T - 1, tick, (recv0, outs0))
        _, outs = tick(T - 1, carry, send=False)
        # broadcast the last stage's banked outputs to every rank
        src = jnp.where(me == nstg - 1, 1.0, 0.0)
        return lax.psum(outs * src, axis)

    return run_spmd(
        kernel, mesh,
        in_specs=(P(), P(axis, None, None, None), P(axis, None, None)),
        out_specs=P())


def pipeline_forward(params, mb, mesh: Mesh):
    """Run the (M, B, H) microbatch stack through the pipeline; returns the
    (M, B, H) outputs (replicated)."""
    mb = jnp.asarray(mb)
    if mb.ndim != 3:
        raise ValueError(f"microbatches must be (M, B, H), got {mb.shape}")
    W, b = _norm_params(params)
    nstg = mesh.shape[mesh.axis_names[0]]
    if W.shape[0] != nstg:
        raise ValueError(
            f"params have {W.shape[0]} stages, mesh has {nstg}")
    return _pipeline_jit(mesh)(mb, W, b)


@functools.lru_cache(maxsize=32)
def _train_jit(mesh):
    fwd = _pipeline_jit(mesh)

    def loss_fn(wb, mb, tgt):
        out = fwd(mb, wb[0], wb[1])
        return jnp.mean(jnp.square(out - tgt))

    def step(wb, mb, tgt, lr):
        # lr rides as a traced scalar so schedules don't recompile
        loss, g = jax.value_and_grad(loss_fn)(wb, mb, tgt)
        new = jax.tree_util.tree_map(lambda p, gg: p - lr * gg, wb, g)
        return new, loss

    return jax.jit(step)


def pipeline_train_step(params, mb, tgt, mesh: Mesh, lr: float = 1e-2):
    """One SGD step, GPipe schedule: the backward pass re-traverses the
    schedule in reverse (ppermute transposes to the opposite shift), all
    inside the same compiled program.  Gradients match the sequential model
    exactly (see tests)."""
    W, b = _norm_params(params)
    (W2, b2), loss = _train_jit(mesh)(
        (W, b), jnp.asarray(mb), jnp.asarray(tgt), jnp.float32(lr))
    if params["W"].ndim == 3:
        W2, b2 = W2[:, 0], b2[:, 0]
    return {"W": W2, "b": b2}, loss


@functools.lru_cache(maxsize=32)
def _train_1f1b_jit(mesh):
    axis = mesh.axis_names[0]
    nstg = mesh.shape[axis]

    def kernel(mb, tgt, W, b):
        # mb/tgt: (M, B, H) replicated; W: (1, L, H, H); b: (1, L, H)
        me = lax.axis_index(axis)
        Ws, bs = W[0], b[0]
        M, B, H = mb.shape
        S = min(M, 2 * nstg - 1)        # ring slots: the 1F1B memory bound
        T = M + 2 * nstg - 2
        fwd_perm = [(i, i + 1) for i in range(nstg - 1)]
        bwd_perm = [(i + 1, i) for i in range(nstg - 1)]
        denom = jnp.asarray(1.0 / (M * B * H), jnp.float32)

        def tick(t, carry, send=True):
            recv_x, recv_g, saved, dW, db, loss_acc = carry

            # ---- forward half: stage `me` runs microbatch t - me -------
            mf = t - me
            f_valid = (mf >= 0) & (mf < M)
            mb_t = lax.dynamic_index_in_dim(
                mb, jnp.clip(mf, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(me == 0, mb_t, recv_x)
            x_in = jnp.where(f_valid, x_in, jnp.zeros_like(x_in))
            y = _stage_fn(x_in, Ws, bs)
            # bank this microbatch's stage INPUT for its backward; ring
            # slot mf % S (collision-free: <= 2P-1 in flight per stage).
            # Invalid ticks must not clobber a live slot.
            slot = jnp.clip(mf, 0, M - 1) % S
            cur = lax.dynamic_index_in_dim(saved, slot, 0, keepdims=False)
            saved = lax.dynamic_update_index_in_dim(
                saved, jnp.where(f_valid, x_in, cur), slot, 0)

            # ---- backward half: microbatch t - (2P - 2 - me) -----------
            mk = t - (2 * nstg - 2 - me)
            b_valid = (mk >= 0) & (mk < M)
            bslot = jnp.clip(mk, 0, M - 1) % S
            x_save = lax.dynamic_index_in_dim(saved, bslot, 0,
                                              keepdims=False)
            # recompute the stage forward for residuals (rematerialize)
            y2, vjp = jax.vjp(_stage_fn, x_save, Ws, bs)
            tgt_b = lax.dynamic_index_in_dim(
                tgt, jnp.clip(mk, 0, M - 1), 0, keepdims=False)
            # loss = (1/M) sum_m mean_{B,H} (y_m - tgt_m)^2  — identical
            # to the GPipe step's jnp.mean over (M, B, H)
            dy_last = (2.0 * (y2 - tgt_b) * denom).astype(y2.dtype)
            dy = jnp.where(me == nstg - 1, dy_last, recv_g)
            dy = jnp.where(b_valid, dy, jnp.zeros_like(dy))
            dx, dWs, dbs = vjp(dy)
            dW = dW + dWs
            db = db + dbs
            loss_acc = loss_acc + jnp.where(
                b_valid & (me == nstg - 1),
                jnp.sum(jnp.square(y2 - tgt_b)) * denom, 0.0)

            # ---- ring sends: activation down, cotangent up -------------
            # (skipped on the final tick — both results would be
            # discarded with the loop carry, two wasted wire hops)
            if send:
                recv_x = lax.ppermute(
                    jnp.where(f_valid, y, jnp.zeros_like(y)), axis,
                    fwd_perm)
                recv_g = lax.ppermute(dx, axis, bwd_perm)
            else:
                recv_x, recv_g = y, dx
            return recv_x, recv_g, saved, dW, db, loss_acc

        z = jnp.zeros((B, H), mb.dtype)
        init = (z, z, jnp.zeros((S, B, H), mb.dtype),
                jnp.zeros_like(Ws), jnp.zeros_like(bs),
                jnp.float32(0.0))
        carry = lax.fori_loop(0, T - 1, tick, init)
        _, _, _, dW, db, loss = tick(T - 1, carry, send=False)
        # loss lives on the last stage only; grads are per-stage shards
        return dW[None], db[None], lax.psum(loss, axis)

    grad_fn = run_spmd(
        kernel, mesh,
        in_specs=(P(), P(), P(axis, None, None, None), P(axis, None, None)),
        out_specs=(P(axis, None, None, None), P(axis, None, None), P()))

    def step(W, b, mb, tgt, lr):
        dW, db, loss = grad_fn(mb, tgt, W, b)
        return W - lr * dW, b - lr * db, loss

    return jax.jit(step)


def pipeline_train_step_1f1b(params, mb, tgt, mesh: Mesh, lr: float = 1e-2):
    """One SGD step under the hand-scheduled 1F1B interleave.

    Same gradients and loss as ``pipeline_train_step`` (pinned by tests),
    but each stage saves at most ``min(M, 2P-1)`` microbatch inputs and
    rematerializes its forward in the backward half — activation memory is
    bounded by the pipeline depth, not the microbatch count, which is the
    reason 1F1B exists."""
    W, b = _norm_params(params)
    W2, b2, loss = _train_1f1b_jit(mesh)(
        W, b, jnp.asarray(mb), jnp.asarray(tgt), jnp.float32(lr))
    if params["W"].ndim == 3:
        W2, b2 = W2[:, 0], b2[:, 0]
    return {"W": W2, "b": b2}, loss


def reference_forward(params, mb):
    """Sequential oracle: apply every stage (and its layers) in order."""
    W, b = _norm_params(params)
    x = jnp.asarray(mb)
    for s in range(W.shape[0]):
        x = _stage_fn(x, W[s], b[s])
    return x
