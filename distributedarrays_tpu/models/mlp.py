"""Flagship end-to-end model: a mesh-sharded MLP trained with the framework.

The reference has no model layer (SURVEY.md §1: "no scheduler, no CLI, no
model layer") — its flagship end-to-end program is "distribute → broadcast
chain → reduction → gather".  This module provides the framework's
equivalent *demonstrator at training scale*: an MLP whose parameters are
tensor-parallel sharded over one mesh axis and whose batch is data-parallel
sharded over the other, trained with a jitted step whose collectives
(psum of partials from the tp contraction, gradient all-reduce over dp)
are inserted by GSPMD — the pattern every DArray op in this framework
builds on.

Used by ``__graft_entry__.py`` for the single-chip compile check and the
multi-chip dry-run.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_params", "forward", "loss_fn", "train_step", "make_mesh",
           "shard_params", "shard_batch"]


def make_mesh(n_devices: int | None = None) -> Mesh:
    """A ('dp','tp') mesh over the first n devices (tp=2 when possible)."""
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    n = len(devs)
    tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // tp
    grid = np.asarray(devs, dtype=object).reshape(dp, tp)
    return Mesh(grid, ("dp", "tp"))


def init_params(key, sizes: Sequence[int], dtype=jnp.bfloat16):
    """Layer weights [in,out] + biases; bfloat16 by default to feed the MXU."""
    params = []
    for i in range(len(sizes) - 1):
        key, wk = jax.random.split(key)
        w = jax.random.normal(wk, (sizes[i], sizes[i + 1]), dtype) \
            * jnp.asarray(np.sqrt(2.0 / sizes[i]), dtype)
        b = jnp.zeros((sizes[i + 1],), dtype)
        params.append({"w": w, "b": b})
    return params


def shard_params(params, mesh: Mesh):
    """Tensor-parallel layout: alternate sharding the output/input feature
    dim over the 'tp' axis (Megatron-style column→row pairs), replicated
    over 'dp'."""
    out = []
    for i, layer in enumerate(params):
        col = i % 2 == 0  # even layers: split output features; odd: input
        wspec = P(None, "tp") if col else P("tp", None)
        bspec = P("tp") if col else P(None)
        out.append({
            "w": jax.device_put(layer["w"], NamedSharding(mesh, wspec)),  # dalint: disable=DAL007 — initial host→mesh parameter placement, no source layout
            "b": jax.device_put(layer["b"], NamedSharding(mesh, bspec)),  # dalint: disable=DAL007 — initial host→mesh parameter placement, no source layout
        })
    return out


def shard_batch(x, y, mesh: Mesh):
    sh = NamedSharding(mesh, P("dp", None))
    return jax.device_put(x, sh), jax.device_put(y, sh)  # dalint: disable=DAL007 — per-step host batch scatter, no source layout


def forward(params, x):
    h = x
    for i, layer in enumerate(params):
        h = h @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            h = jax.nn.gelu(h)
    return h


def loss_fn(params, x, y):
    pred = forward(params, x)
    return jnp.mean(jnp.square(pred.astype(jnp.float32) -
                               y.astype(jnp.float32)))


@functools.partial(jax.jit, static_argnames=("lr",), donate_argnums=(0,))
def train_step(params, x, y, lr: float = 1e-3):
    """One SGD step.  Params are donated so the update is in-place in HBM;
    GSPMD inserts the tp-contraction psums and dp gradient all-reduce."""
    loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
    new_params = jax.tree_util.tree_map(
        lambda p, g: (p.astype(jnp.float32) - lr * g.astype(jnp.float32))
        .astype(p.dtype), params, grads)
    return new_params, loss
